"""Backpressure gate (ISSUE 13, docs/SERVING.md backpressure section;
degradation tiers: docs/RESILIENCE.md): one deliberately wedged
consumer must not harm anyone else.

Two arms against REAL gateway server subprocesses on unix sockets,
with a small egress bound and a short wedge deadline so the tiers
engage on the smoke shape:

  1. **baseline** -- 32 healthy subscriber connections + a writer
     streaming ROUNDS large change frames; every subscriber must
     receive every change, and the healthy change->fanout p99 is
     recorded.
  2. **wedged** -- identical traffic plus one consumer that subscribes
     and then never reads its socket again.  Gates:
       * every healthy subscriber still receives every change (the
         dispatcher/flush path never blocks on the wedged socket);
       * healthy p99 within 2x the baseline arm's p99 (floored at
         ``AMTPU_SMOKE_BP_P99_FLOOR_MS``, default 300 ms -- this check
         runs ~35 processes' worth of traffic on a 1-2 core CI
         stand-in, so sub-floor baselines are scheduler noise);
       * the wedged peer was degraded through the tiers: egress sheds
         observed, and it was resynced (typed ``{"event": "resync"}``
         envelope) or wedge-evicted;
       * after reconnecting, the dropped peer's backfill is
         byte-identical to a serial per-Connection replay of the full
         history (no dup, no gap);
       * ``fallback.oracle == 0``.

Run: JAX_PLATFORMS=cpu python tools/backpressure_check.py
     (make backpressure-check)
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CONNS = 32
ROUNDS = 24
BLOB = 'x' * 8192
ROOT_ID = '00000000-0000-0000-0000-000000000000'
DOC = 'bp-doc'

SERVER_ENV = {
    'AMTPU_FLUSH_DEADLINE_MS': '5',
    'AMTPU_EGRESS_MAX_BYTES': '32768',
    'AMTPU_EGRESS_WEDGE_S': '1.5',
    'AMTPU_EGRESS_RESYNC_SHEDS': '2',
}


def change(seq):
    return {'actor': 'writer', 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': 'k%d' % (seq % 3), 'value': BLOB}]}


def spawn_server(path):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(SERVER_ENV)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path], env=env, cwd=REPO)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('gateway server did not come up')
        time.sleep(0.05)
    return proc


def stop_server(proc):
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


def canon(changes):
    return json.dumps(changes, sort_keys=True)


def serial_oracle():
    """Full-history backfill through the reference's per-Connection
    shape: what a fresh empty-clock peer must receive."""
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.doc_set import DocSet
    ds = DocSet()
    for r in range(1, ROUNDS + 1):
        ds.apply_changes(DOC, [change(r)])
    msgs = []
    conn = Connection(ds, msgs.append)
    conn.open()
    conn.receive_msg({'docId': DOC, 'clock': {}})
    return [c for m in msgs if m.get('changes') for c in m['changes']]


def drain_all(client, want, timeout=120):
    got = []
    deadline = time.time() + timeout
    while len(got) < want and time.time() < deadline:
        e = client.next_event(timeout=max(0.1, deadline - time.time()))
        if e is None:
            break
        if e.get('event') == 'change':
            got.extend(e['changes'])
    return got


def run_arm(wedged):
    from automerge_tpu.sidecar.client import SidecarClient
    path = os.path.join(tempfile.mkdtemp(), 'gw-bp.sock')
    proc = spawn_server(path)
    out = {'arm': 'wedged' if wedged else 'baseline'}
    try:
        wedge_sock = None
        if wedged:
            wedge_sock = socket.socket(socket.AF_UNIX,
                                       socket.SOCK_STREAM)
            wedge_sock.connect(path)
            wedge_sock.sendall((json.dumps(
                {'id': 1, 'cmd': 'subscribe', 'doc': DOC,
                 'peer': 'wedge'}) + '\n').encode())
            wedge_sock.settimeout(30)
            assert wedge_sock.recv(65536), 'wedge subscribe unanswered'
            # ...and from here on it never reads again

        subs = [SidecarClient(sock_path=path) for _ in range(N_CONNS)]
        for i, c in enumerate(subs):
            r = c.subscribe(DOC, peer='h%02d' % i)
            assert r['clock'] == {} and r['changes'] == [], r
        writer = SidecarClient(sock_path=path)
        t0 = time.time()
        for s in range(1, ROUNDS + 1):
            writer.apply_changes(DOC, [change(s)])
        for i, c in enumerate(subs):
            got = drain_all(c, ROUNDS)
            assert len(got) == ROUNDS, \
                '%s arm: healthy peer %d got %d/%d changes' \
                % (out['arm'], i, len(got), ROUNDS)
        out['wall_s'] = round(time.time() - t0, 3)

        h = writer.healthz()
        lat = h['fanout']['latency_ms']
        out['p50_ms'] = lat.get('p50', 0.0)
        out['p99_ms'] = lat.get('p99', 0.0)
        out['egress'] = {k: h['egress'].get(k, 0) for k in
                        ('sheds', 'shed_frames', 'resyncs',
                         'wedge_evictions', 'writes', 'write_errors')}
        out['fallback_oracle'] = h['scheduler']['fallback_oracle']

        if wedged:
            # the wedged peer must have been degraded: sheds observed,
            # then resynced with the typed envelope or evicted
            deadline = time.time() + 30
            while time.time() < deadline:
                h = writer.healthz()
                eg = h['egress']
                if eg.get('resyncs', 0) or eg.get('wedge_evictions', 0):
                    break
                time.sleep(0.2)
            eg = writer.healthz()['egress']
            out['egress'] = {k: eg.get(k, 0) for k in out['egress']}
            assert eg.get('sheds', 0) >= 1, \
                'wedged arm never tier-1 shed: %r' % (eg,)
            assert eg.get('resyncs', 0) >= 1 \
                or eg.get('wedge_evictions', 0) >= 1, \
                'wedged peer neither resynced nor evicted: %r' % (eg,)
            # drain whatever reached the wedged socket: either a typed
            # resync envelope is in there, or the server evicted it
            # (EOF after the kernel buffer drains)
            buf, resynced, eof = b'', False, False
            wedge_sock.settimeout(0.5)
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    chunk = wedge_sock.recv(65536)
                except socket.timeout:
                    if resynced or eof:
                        break
                    continue
                except OSError:
                    eof = True
                    break
                if not chunk:
                    eof = True
                    break
                buf += chunk
                resynced = b'"event": "resync"' in buf \
                    or b'"resync"' in buf
            evicted = eg.get('wedge_evictions', 0) >= 1
            assert resynced or evicted, \
                'no typed resync envelope and no eviction for the ' \
                'wedged peer'
            out['wedged_outcome'] = 'resync' if resynced else 'evicted'
            wedge_sock.close()

            # reconnect: the dropped peer comes back at an empty clock
            # and its backfill must be byte-identical to the serial
            # per-Connection replay of the whole history
            back = SidecarClient(sock_path=path)
            r = back.subscribe(DOC, peer='wedge-back')
            assert canon(r['changes']) == canon(serial_oracle()), \
                'post-reconnect backfill diverged from serial replay'
            out['reconnect_parity'] = True
            back.close()

        assert out['fallback_oracle'] == 0, out
        for c in subs:
            c.close()
        writer.close()
    finally:
        stop_server(proc)
    return out


def main():
    from automerge_tpu.utils.common import env_float
    floor_ms = env_float('AMTPU_SMOKE_BP_P99_FLOOR_MS', 300.0)
    base = run_arm(wedged=False)
    print('backpressure-check: baseline OK (%d conns x %d rounds, '
          'p50 %.1fms / p99 %.1fms, wall %.1fs)'
          % (N_CONNS, ROUNDS, base['p50_ms'], base['p99_ms'],
             base['wall_s']))
    wedge = run_arm(wedged=True)
    print('backpressure-check: wedged arm OK (healthy peers all '
          'served; p50 %.1fms / p99 %.1fms; outcome=%s; egress %r)'
          % (wedge['p50_ms'], wedge['p99_ms'],
             wedge.get('wedged_outcome'), wedge['egress']))
    gate = max(2.0 * base['p99_ms'], floor_ms)
    assert wedge['p99_ms'] <= gate, \
        'healthy p99 %.1fms with a wedged consumer exceeds the gate ' \
        '%.1fms (2x baseline %.1fms, floor %.0fms)' \
        % (wedge['p99_ms'], gate, base['p99_ms'], floor_ms)
    print('backpressure-check: isolation OK (wedged-arm healthy p99 '
          '%.1fms <= max(2 x %.1fms, %.0fms))'
          % (wedge['p99_ms'], base['p99_ms'], floor_ms))
    print('backpressure-check: reconnect parity OK (dropped peer '
          'byte-identical to serial replay); oracle=0')
    with open(os.path.join(REPO, '.backpressure_check.json'), 'w') as f:
        json.dump({'baseline': base, 'wedged': wedge,
                   'p99_gate_ms': gate}, f, indent=2)
    print('BACKPRESSURE-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
