"""amtpu-top: a live terminal view of one serving sidecar -- stage
waterfall, queue depth, shed/quarantine state, SLO burn -- by polling
the HTTP listener's /metrics + /healthz (docs/OBSERVABILITY.md).

No dependencies beyond the stdlib: Prometheus exposition is parsed
with a regex, the healthz payload is JSON.  Between polls the tool
differences the cumulative stage histograms, so the waterfall shows
the LAST interval's mean milliseconds per stage (and each stage's
share of the total as a bar), not the process-lifetime average.

A restarted sidecar resets every cumulative counter to zero; the tool
detects the backwards step, drops the stale baseline (the frame falls
back to lifetime means instead of printing garbage negative shares),
clamps the rate at 0, and flags the frame RESTARTED.

Run:  python tools/amtpu_top.py --url http://127.0.0.1:9464
      python tools/amtpu_top.py --url ... --once        # one frame (CI)
      python tools/amtpu_top.py --url ... --interval 2
      python tools/amtpu_top.py --fleet --url http://h1:9464 \
          --url http://h2:9464     # merged multi-replica view
"""

import argparse
import json
import re
import sys
import time
import urllib.request

STAGES = ('admit', 'queue', 'claim', 'dispatch', 'collect', 'emit',
          'fanout')
BAR_W = 28

_SAMPLE_RE = re.compile(
    r'^amtpu_request_stage_ms_(sum|count)\{stage="([a-z]+)"\}\s+(\S+)$')
_RUNTIME_RE = re.compile(
    r'^amtpu_runtime_counter\{name="([^"]+)"\}\s+(\S+)$')


def fetch(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def parse_metrics(text):
    """({stage: {'sum': ms, 'count': n}}, {runtime counter: value})."""
    stages = {}
    runtime = {}
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line)
        if m:
            kind, stage, val = m.groups()
            stages.setdefault(stage, {})[kind] = float(val)
            continue
        m = _RUNTIME_RE.match(line)
        if m:
            runtime[m.group(1)] = float(m.group(2))
    return stages, runtime


def _bar(frac, width=BAR_W):
    n = max(0, min(width, int(round(frac * width))))
    return '#' * n + '.' * (width - n)


def _mb(n):
    try:
        return '%.1fMB' % (float(n) / 1048576.0)
    except (TypeError, ValueError):
        return '?'


def render_capacity(health, out):
    """The capacity panel (ISSUE 15): headroom bar, eviction pressure
    state, and the top-K hot docs by arena / disk / fanned bytes from
    the healthz `capacity` + `storage` sections."""
    cap = health.get('capacity') or {}
    if not cap or 'error' in cap:
        return
    sto = health.get('storage') or {}
    head = cap.get('headroom') or {}
    tot = cap.get('totals') or {}
    budget = head.get('budget_bytes') or 0
    used = head.get('used_bytes') or 0
    pressure = head.get('pressure') or 0.0
    out.append('')
    if budget:
        eta = head.get('exhaustion_s')
        # CURRENT pressure state, not the cumulative eviction counter
        # (which would stay lit forever after one eviction)
        evict_frac = head.get('pressure_evict') or 0
        hot_now = evict_frac > 0 and pressure >= evict_frac
        out.append('capacity: used %s / %s |%s| %5.1f%%  burn %s/s  '
                   'eta %s%s'
                   % (_mb(used), _mb(budget), _bar(min(1.0, pressure)),
                      100 * pressure, _mb(head.get('burn_bytes_s') or 0),
                      '%.0fs' % eta if eta is not None else '-',
                      '  PRESSURE' if hot_now else ''))
    else:
        out.append('capacity: used %s (no AMTPU_MEM_BUDGET_MB set)'
                   % _mb(used))
    out.append('  arena %s  clock %s  disk %s (%s cold docs)  fanned %s  '
               'egress %s  | evictions %s (%s freed, %s pressure)'
               % (_mb(tot.get('arena_bytes', 0)),
                  _mb(tot.get('clock_bytes', 0)),
                  _mb(tot.get('disk_bytes', 0)),
                  tot.get('cold_docs', 0),
                  _mb(tot.get('fanned_bytes', 0)),
                  _mb(tot.get('egress_bytes', 0)),
                  sto.get('evictions', 0),
                  _mb(sto.get('evicted_bytes', 0)),
                  sto.get('pressure_evictions', 0)))
    top = cap.get('top') or {}
    for tier, field in (('arena', 'arena_bytes'), ('clock', 'clock_bytes'),
                        ('disk', 'disk_bytes'),
                        ('fanned', 'fanned_bytes')):
        rows = top.get(tier) or []
        if not rows:
            continue
        cells = []
        for r in rows[:5]:
            cell = '%s=%s' % (r.get('doc'), _mb(r.get(field, 0)))
            if r.get('subscribers'):
                cell += '(%d subs)' % r['subscribers']
            cells.append(cell)
        out.append('  hot(%s): %s' % (tier, '  '.join(cells)))


def counters_reset(stages, prev_stages, runtime, prev_runtime):
    """True when any cumulative counter moved BACKWARDS since the last
    poll -- the server restarted (counters are monotone within one
    process lifetime).  The caller drops its stale baseline: keeping
    it would difference a fresh process against the dead one and
    render negative rates / garbage share bars (ISSUE 16
    satellite)."""
    for cur, prev in ((runtime, prev_runtime),):
        for k, v in (prev or {}).items():
            if cur.get(k, v) < v:
                return True
    for s, prev_kinds in (prev_stages or {}).items():
        cur_kinds = stages.get(s, {})
        for kind, v in prev_kinds.items():
            if cur_kinds.get(kind, v) < v:
                return True
    return False


def render(health, stages, prev_stages, runtime, prev_runtime,
           interval_s, restarted=False):
    out = []
    sched = health.get('scheduler') or {}
    slo = health.get('slo') or {}
    rec = health.get('recorder') or {}
    res = health.get('resilience') or {}
    reqs = runtime.get('slo.requests', 0.0)
    rate = max(0.0, (reqs - prev_runtime.get('slo.requests', reqs))
               / interval_s) if prev_runtime else 0.0
    out.append('amtpu-top  up %ss  conns %s  req/s %.1f  %s%s%s'
               % (health.get('uptime_s', '?'),
                  sched.get('connections', '?'), rate,
                  'RESTARTED  ' if restarted else '',
                  'SHEDDING  ' if sched.get('shedding') else '',
                  'DEGRADED' if health.get('degraded') else ''))
    out.append('queue: depth %s/%s ops  queued %s  pending docs %s  '
               'shed total %s'
               % (sched.get('depth_ops', '?'), sched.get('max_ops', '?'),
                  sched.get('queued', '?'),
                  sched.get('pending_docs', '?'),
                  int(runtime.get('scheduler.shed', 0))))
    out.append('')
    out.append('stage waterfall (last interval mean ms per request):')
    # interval deltas of the cumulative histograms.  The lifetime
    # fallback applies to the WHOLE frame (no attributed requests this
    # interval), never per stage -- mixing an interval total with a
    # lifetime stage mean would print shares past 100%
    deltas = {}
    tot = stages.get('total', {})
    tot_prev = (prev_stages or {}).get('total', {})
    frame_idle = prev_stages is None or \
        tot.get('count', 0.0) - tot_prev.get('count', 0.0) <= 0
    for s in STAGES + ('total',):
        cur = stages.get(s, {})
        prev = (prev_stages or {}).get(s, {})
        if frame_idle:
            dc, ds = cur.get('count', 0.0), cur.get('sum', 0.0)
        else:
            dc = max(0.0, cur.get('count', 0.0) - prev.get('count', 0.0))
            ds = max(0.0, cur.get('sum', 0.0) - prev.get('sum', 0.0))
        deltas[s] = (ds / dc if dc else 0.0, int(dc))
    total_ms = deltas.get('total', (0.0, 0))[0] or \
        sum(deltas[s][0] for s in STAGES if s != 'fanout')
    for s in STAGES:
        mean, n = deltas[s]
        share = mean / total_ms if total_ms else 0.0
        out.append('  %-9s %8.3f ms  |%s| %5.1f%%  n=%d'
                   % (s, mean, _bar(share), 100 * share, n))
    out.append('  %-9s %8.3f ms' % ('total', total_ms))
    out.append('')
    burn = (slo.get('burn') or {})
    out.append('slo: p99 target %s ms  slow %s ms  burn %s  '
               'breaches %d  exemplars %d'
               % (slo.get('target_p99_ms', '?'),
                  slo.get('slow_ms', '?'),
                  ' '.join('%s=%.2f' % kv
                           for kv in sorted(burn.items())),
                  int(runtime.get('slo.breaches', 0)),
                  int(runtime.get('slo.exemplars', 0))))
    for cls, wins in sorted((slo.get('classes') or {}).items()):
        parts = []
        for w in ('60s', '300s', '3600s'):
            d = wins.get(w) or {}
            parts.append('%s: n=%d p50=%.1f p99=%.1f'
                         % (w, d.get('count', 0), d.get('p50_ms', 0.0),
                            d.get('p99_ms', 0.0)))
        out.append('  %-8s %s' % (cls, '   '.join(parts)))
    out.append('')
    out.append('resilience: quarantined %d  retries %d  rollbacks %d  '
               '| recorder: %s/%s events  dumps %d'
               % (int(res.get('quarantined', 0)),
                  int(res.get('retry.attempts', 0)),
                  int(res.get('rollback', 0)),
                  rec.get('events', '?'), rec.get('size', '?'),
                  int(runtime.get('recorder.dumps', 0))))
    render_capacity(health, out)
    return '\n'.join(out)


def _fleet_loop(args):
    """--fleet mode: scrape EVERY --url replica and render the merged
    fleet view (summed SLO slots recomputed through the per-replica
    code path, headroom skew table) via telemetry/fleet.py."""
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))
    sys.path.insert(0, here)
    from automerge_tpu.telemetry import fleet
    from amtpu_fleet import render as fleet_render
    while True:
        scrapes, section = fleet.scrape_fleet(
            [u.rstrip('/') for u in args.url], timeout=args.timeout)
        if args.once:
            fleet_render(scrapes, section)
            return 1 if section['errors'] else 0
        sys.stdout.write('\x1b[2J\x1b[H')
        fleet_render(scrapes, section)
        sys.stdout.flush()
        time.sleep(args.interval)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--url', action='append', required=True,
                    help='base URL of the sidecar metrics listener, '
                         'e.g. http://127.0.0.1:9464 (repeat with '
                         '--fleet for a multi-replica view)')
    ap.add_argument('--interval', type=float, default=2.0)
    ap.add_argument('--once', action='store_true',
                    help='print one frame and exit (no screen clears; '
                         'the obs-check CI mode)')
    ap.add_argument('--timeout', type=float, default=10.0)
    ap.add_argument('--fleet', action='store_true',
                    help='aggregate ALL --url replicas into one '
                         'merged view (telemetry/fleet.py)')
    args = ap.parse_args(argv)
    if args.fleet:
        return _fleet_loop(args)
    if len(args.url) > 1:
        ap.error('multiple --url endpoints require --fleet')
    base = args.url[0].rstrip('/')
    prev_stages = prev_runtime = None
    while True:
        try:
            health = json.loads(fetch(base + '/healthz', args.timeout))
            stages, runtime = parse_metrics(
                fetch(base + '/metrics', args.timeout))
        except (OSError, ValueError) as e:
            print('amtpu-top: poll failed: %s' % e, file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        restarted = counters_reset(stages, prev_stages, runtime,
                                   prev_runtime)
        if restarted:
            # the dead process's counters are not a baseline for the
            # fresh one: fall back to lifetime means for this frame
            prev_stages = prev_runtime = None
        frame = render(health, stages, prev_stages, runtime,
                       prev_runtime, args.interval,
                       restarted=restarted)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write('\x1b[2J\x1b[H' + frame + '\n')
        sys.stdout.flush()
        prev_stages, prev_runtime = stages, runtime
        time.sleep(args.interval)


if __name__ == '__main__':
    sys.exit(main())
