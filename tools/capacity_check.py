"""CI gate: per-doc resource accounting + capacity observability
(ISSUE 15, docs/OBSERVABILITY.md capacity section).

Four acceptance checks, one process:

  1. **reconciliation** -- under a churn + GC + fold + evict + reload
     workload, the per-doc ``amtpu_doc_stats`` rows must sum
     BIT-EXACTLY to the pool-wide ``amtpu_history_bytes`` /
     ``amtpu_op_count`` at every checkpoint, in BOTH exec modes
     (kernel + full-host) and on a dp=4 ``MeshDocPool``;
  2. **hot-doc ranking** -- on a zipfian fan-out stream the space-saver
     sketch's top docs must match the exact per-doc totals, and the
     arena hot-doc table must rank by the real per-doc history bytes;
  3. **pressure eviction** -- with ``AMTPU_MEM_BUDGET_MB`` modeled by
     the headroom estimator, proactive eviction must fire BEFORE the
     budget is breached (the used-bytes curve never crosses it) and
     record the bytes it freed;
  4. **oracle-free** -- ``fallback.oracle == 0`` throughout.

The always-on accounting COST is priced by `make telemetry-check`
(its raw arm no-ops the `capacity.note_*` seams; same 6% bar as the
flight recorder).

Usage: [JAX_PLATFORMS=cpu] python tools/capacity_check.py [--out F]
"""
import argparse
import json
import os
import random
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
# the mesh lane needs 4 virtual devices (same conftest pattern)
flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
               os.environ.get('XLA_FLAGS', ''))
os.environ['XLA_FLAGS'] = (
    flags + ' --xla_force_host_platform_device_count=4').strip()

ROOT_ID = '00000000-0000-0000-0000-000000000000'
N_DOCS = int(os.environ.get('AMTPU_BENCH_CAPACITY_DOCS', '32'))


def _changes(doc_i, seq0, n, rng):
    actor = 'w%d' % (doc_i % 4)
    out = []
    for i in range(n):
        out.append({'actor': actor, 'seq': seq0 + i + 1,
                    'deps': {actor: seq0 + i} if seq0 + i else {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID,
                             'key': 'k%d' % rng.randrange(12),
                             'value': 'v%d' % rng.randrange(1 << 20)}]})
    return out


def _assert_reconciled(pool, problems, label, where):
    ids, stats = pool.doc_stats()
    hist = pool.history_bytes()
    ops = pool.op_count()
    s_hist = int(stats[:, 0].sum()) if len(ids) else 0
    s_ops = int(stats[:, 1].sum()) if len(ids) else 0
    if s_hist != hist or s_ops != ops:
        problems.append(
            '%s/%s: per-doc stats do not reconcile (hist %d vs %d, '
            'ops %d vs %d)' % (label, where, s_hist, hist, s_ops, ops))
        return False
    return True


def _churn_evict(pool, problems, label):
    """Churn + GC + fold + evict + reload on `pool`, reconciling at
    every phase boundary."""
    from automerge_tpu.storage.coldstore import ColdStore, DocEvictor
    rng = random.Random(23)
    seqs = {}
    evictor = DocEvictor(pool, max_resident=max(4, N_DOCS // 2),
                         store=ColdStore(), gc_every=8)
    for rnd in range(4):
        for d in range(N_DOCS):
            doc = 'cap%d' % d
            chs = _changes(d, seqs.get(doc, 0), 4, rng)
            seqs[doc] = seqs.get(doc, 0) + 4
            pool.apply_changes(doc, chs)
            evictor.note_mutations(doc, 4)     # GC + op-state folding
            evictor.note_touch([doc])
        _assert_reconciled(pool, problems, label, 'round%d' % rnd)
        evictor.maybe_evict()                  # LRU past the cap
        _assert_reconciled(pool, problems, label,
                           'round%d-evicted' % rnd)
    # reload-on-touch: every cold doc replays back in one batch
    failed = evictor.ensure_resident(['cap%d' % d
                                      for d in range(N_DOCS)])
    if failed:
        problems.append('%s: %d cold docs failed to reload'
                        % (label, len(failed)))
    ok = _assert_reconciled(pool, problems, label, 'reloaded')
    return ok


def check_reconcile(problems, report):
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.native.mesh_pool import MeshDocPool
    modes = {}
    for mode, env in (('kernel', '0'), ('host_full', '1')):
        os.environ['AMTPU_HOST_FULL'] = env
        pool = NativeDocPool()
        modes[mode] = _churn_evict(pool, problems, mode)
    os.environ['AMTPU_HOST_FULL'] = '0'
    mesh = MeshDocPool(dp=4)
    modes['mesh_dp4'] = _churn_evict(mesh, problems, 'mesh_dp4')
    report['reconcile'] = {'docs': N_DOCS, 'modes': modes}


def check_hot_docs(problems, report):
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.telemetry.capacity import SpaceSaver, TRACKER
    rng = random.Random(41)
    # zipfian fan-out stream over many more docs than the sketch holds
    sketch = SpaceSaver(64)
    exact = {}
    n_keys = 800
    for _ in range(40000):
        d = 'z%d' % min(int(rng.paretovariate(1.1)) - 1, n_keys - 1)
        b = rng.randrange(64, 4096)
        sketch.offer(d, b)
        exact[d] = exact.get(d, 0) + b
    exact_top = [d for d, _ in sorted(exact.items(),
                                      key=lambda kv: -kv[1])[:5]]
    sketch_top = [d for d, _v, _e in sketch.top(5)]
    if sketch_top[:3] != exact_top[:3]:
        problems.append('sketch top-3 %r != exact top-3 %r'
                        % (sketch_top[:3], exact_top[:3]))
    over = [(d, v, e) for d, v, e in sketch.top()
            if not (v - e <= exact.get(d, 0) <= v)]
    if over:
        problems.append('sketch bounds violated for %r' % over[:3])
    # arena ranking: one deliberately heavy doc must lead the table
    os.environ['AMTPU_HOST_FULL'] = '1'
    pool = NativeDocPool()
    for d in range(8):
        n = 40 if d == 3 else 4
        pool.apply_changes('h%d' % d,
                           _changes(d, 0, n, random.Random(d)))
    TRACKER.reset()
    TRACKER.attach(pool=pool)
    snap = TRACKER.refresh(force=True)
    top = snap['top']['arena']
    if not top or top[0]['doc'] != 'h3':
        problems.append('arena hot-doc table does not lead with the '
                        'heavy doc: %r' % top[:3])
    if top and top[0]['arena_bytes'] != pool.history_bytes('h3'):
        problems.append('arena table bytes %r != per-doc history bytes '
                        '%r' % (top[0]['arena_bytes'],
                                pool.history_bytes('h3')))
    TRACKER.detach()
    report['hot_docs'] = {'sketch_top': sketch_top[:5],
                          'exact_top': exact_top[:5],
                          'arena_top': [r['doc'] for r in top[:3]]}


def check_pressure(problems, report):
    """Budget-modeled pressure eviction: grow resident docs; the
    estimator (used = base + live arena bytes) must trip proactive
    eviction before `used` ever crosses the budget."""
    from automerge_tpu import telemetry
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.storage.coldstore import ColdStore, DocEvictor
    from automerge_tpu.telemetry.capacity import (HeadroomEstimator,
                                                  TRACKER)
    os.environ['AMTPU_HOST_FULL'] = '1'
    os.environ['AMTPU_CAPACITY_REFRESH_S'] = '0'
    pool = NativeDocPool()
    evictor = DocEvictor(pool, max_resident=0, store=ColdStore(),
                         gc_every=0)
    base = 4096
    budget = 64 * 1024
    TRACKER.reset()
    TRACKER.attach(pool=pool, storage_tier=evictor)
    TRACKER.estimator = HeadroomEstimator(
        budget_bytes=budget, used_fn=lambda: base + pool.history_bytes())
    os.environ['AMTPU_MEM_PRESSURE_EVICT'] = '0.75'
    # no cooldown: the lane models many flush cycles in a tight loop
    os.environ['AMTPU_PRESSURE_EVICT_COOLDOWN_S'] = '0'
    rng = random.Random(5)
    breached = False
    evictions = 0
    seqs = {}
    lru = []
    for step in range(400):
        doc = 'p%d' % step
        pool.apply_changes(doc, _changes(step, 0, 3, rng))
        seqs[doc] = 3
        evictor.note_touch([doc])
        lru.append(doc)
        used = base + pool.history_bytes()
        if used > budget:
            breached = True
        if TRACKER.evict_due():
            evictions += evictor.maybe_evict(protect=[doc],
                                             pressure=True)
    flat = telemetry.metrics_snapshot()
    report['pressure'] = {
        'budget_bytes': budget, 'evictions': evictions,
        'pressure_evictions': int(flat.get('storage.pressure_evictions',
                                           0)),
        'evicted_bytes': int(flat.get('storage.evicted_bytes', 0)),
        'final_used': base + pool.history_bytes(),
        'cold_docs': len(evictor.store)}
    if breached:
        problems.append('memory budget was breached before pressure '
                        'eviction relieved it')
    if evictions <= 0 or flat.get('storage.pressure_evictions', 0) <= 0:
        problems.append('pressure eviction never fired '
                        '(storage.pressure_evictions == 0)')
    if flat.get('storage.evicted_bytes', 0) <= 0:
        problems.append('evictions recorded no freed bytes '
                        '(storage.evicted_bytes == 0)')
    # the evicted docs are whole: reload one and reconcile
    cold = evictor.store.doc_ids()
    if cold:
        failed = evictor.ensure_resident(cold[:4])
        if failed:
            problems.append('post-pressure reload failed: %r'
                            % list(failed))
        _assert_reconciled(pool, problems, 'pressure', 'reloaded')
    TRACKER.detach()
    os.environ.pop('AMTPU_MEM_PRESSURE_EVICT', None)
    os.environ.pop('AMTPU_PRESSURE_EVICT_COOLDOWN_S', None)
    os.environ.pop('AMTPU_CAPACITY_REFRESH_S', None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=os.path.join(ROOT,
                                                  '.capacity_check.json'))
    args = ap.parse_args()
    from automerge_tpu import telemetry
    problems, report = [], {}
    check_reconcile(problems, report)
    check_hot_docs(problems, report)
    check_pressure(problems, report)
    flat = telemetry.metrics_snapshot()
    oracle = flat.get('fallback.oracle', 0)
    report['fallback_oracle'] = oracle
    if oracle:
        problems.append('fallback.oracle == %s (must be 0)' % oracle)
    report['ok'] = not problems
    report['problems'] = problems
    with open(args.out, 'w') as f:
        json.dump(report, f, indent=1, sort_keys=True)
    for p in problems:
        print('capacity-check: FAIL -- %s' % p)
    if problems:
        return 1
    print('capacity-check: PASS (%d docs x 3 pool modes reconciled '
          'bit-exact; hot docs ranked; pressure eviction fired inside '
          'the budget; %s)' % (N_DOCS, args.out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
