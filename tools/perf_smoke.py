"""CI gate: the packed member epilogue must be ACTIVE on the table
workload's kernel path (ISSUE 3), the collect wall must stay down and
the pool-resident batch state must actually serve (ISSUE 6).

Part A runs the config-4 shape through tools/quickbench.py with the
kernel path forced (AMTPU_HOST_FULL=0) and fails if

  * `fallback.oracle` is nonzero -- a register group fell past every
    escalation tier back to the host oracle, or
  * `collect.packed_member_batches` is zero -- the member-mode batches
    stopped taking the packed epilogue (ONE i32 per register row +
    sparse CSR conflicts), or
  * `collect.full_matrix_readback` is nonzero -- some batch read back
    the full winner/conflicts/alive/overflow matrices, the pre-packed
    transfer wall this gate exists to keep dead, or
  * `device.collect` share of summed native batch time >= 40% -- the
    per-batch upload/collect round-trip ISSUE 6 removed is creeping
    back (shares come from the phases block quickbench embeds).

Part B drives a steady-state pool IN-PROCESS: the config-4 changes
split into two causally-ordered halves applied to ONE pool, so the
second batch runs against mirrors and pool-resident clock rows the
first batch persisted.  It fails unless `resident.batch_hits` (the
device clock table survived across batches: delta-upload or no-op) and
`resident.batch_hit_rows` (C++ rows served from persisted entries) are
both nonzero -- a silently dead resident cache must not pass.

Wired into `make check` as `make perf-smoke` (next to fallback-check,
which gates the escalation ladder itself on the same shape).

Usage: [JAX_PLATFORMS=cpu] python tools/perf_smoke.py
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

COLLECT_SHARE_MAX = float(os.environ.get('AMTPU_SMOKE_COLLECT_SHARE',
                                         0.40))


def quickbench_gates():
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env['AMTPU_HOST_FULL'] = '0'            # the kernel path IS the subject
    env.pop('AMTPU_PACKED_EPILOGUE', None)  # gate the DEFAULT epilogue
    # same deterministic shape as fallback-check: member mode engages and
    # the dup-assign groups escalate, so the packed epilogue (not the
    # fused path) is what actually serves the batches
    env.setdefault('AMTPU_BENCH_C4_DOCS', '256')
    env.setdefault('AMTPU_BENCH_SHARDS', '8')
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, 'quickbench.py'),
         '--config', '4', '--runs', '1'],
        env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        print('perf-smoke: quickbench failed (rc=%d)' % proc.returncode,
              file=sys.stderr)
        return 1
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    tel = result.get('telemetry', {})
    fallbacks = tel.get('fallbacks', {})
    collect = tel.get('collect', {})
    phases = tel.get('phases', {})
    from automerge_tpu import telemetry
    share, collect_s, basis = telemetry.collect_share(tel)

    problems = []
    if fallbacks.get('oracle', -1) != 0:
        problems.append('fallback.oracle = %s (want 0)'
                        % fallbacks.get('oracle'))
    if collect.get('packed_member_batches', 0) <= 0:
        problems.append('collect.packed_member_batches missing/zero -- '
                        'the packed member epilogue did not engage')
    if collect.get('full_matrix_readback', 0) != 0:
        problems.append('collect.full_matrix_readback = %s (want 0) -- '
                        'a batch read back the full register matrices'
                        % collect.get('full_matrix_readback'))
    if not basis or not phases:
        problems.append('no phases/batch-latency block in the BENCH '
                        'line -- collect share is unattributable')
    elif share >= COLLECT_SHARE_MAX:
        problems.append('device.collect share %.1f%% >= %.0f%% of summed '
                        'batch time (%.3fs of %.3fs) -- the per-batch '
                        'collect round-trip is creeping back'
                        % (100 * share, 100 * COLLECT_SHARE_MAX,
                           collect_s, basis))
    if problems:
        print('perf-smoke FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        print('  telemetry.collect = %s' % json.dumps(collect),
              file=sys.stderr)
        print('  telemetry.fallbacks = %s' % json.dumps(fallbacks),
              file=sys.stderr)
        return 1
    print('perf-smoke: packed epilogue on %d member batches, '
          'full-matrix readbacks 0, oracle 0, collect share %.1f%%, '
          '%.0f ops/s'
          % (collect['packed_member_batches'], 100 * share,
             result.get('value', 0.0)))
    return 0


def resident_hit_gate():
    """Steady-state resident gate, in-process (the env must bind BEFORE
    jax/the pool library initialize, which is why this runs after main()
    set it).  Two causally-ordered halves of the config-4 changes hit
    ONE pool: batch 2 must be served by state batch 1 persisted.

    Wave pipelining is pinned OFF: intra-call waves hit each other's
    just-appended rows, which would satisfy the counters even if the
    cache were wiped between apply calls -- the exact regression this
    gate exists to catch."""
    os.environ['AMTPU_PIPELINE_DEPTH'] = '1'
    from automerge_tpu.utils.jaxenv import pin_cpu
    pin_cpu()
    import random

    import msgpack

    import bench
    from automerge_tpu import telemetry, trace
    from automerge_tpu.native import NativeDocPool

    rng = random.Random(int(os.environ.get('AMTPU_BENCH_SEED', 7)))
    batch, _metric = bench.BUILDERS[4](rng)
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    # split "all but each doc's causally-last change" -> "the last
    # change": batch 2 then reuses batch 1's actor population (a NEW
    # actor would bump the resident generation and legitimately force a
    # full re-upload -- steady-state serving is the stable-actor case
    # this gate pins)
    halves = [
        msgpack.packb({d: chs[:-1] for d, chs in keyed.items()
                       if len(chs) > 1}, use_bin_type=True),
        msgpack.packb({d: chs[-1:] for d, chs in keyed.items()},
                      use_bin_type=True),
    ]
    pool = NativeDocPool()
    telemetry.enable()
    try:
        for payload in halves:
            pool.apply_batch_bytes(payload)
        m = trace.metrics_snapshot()
    finally:
        telemetry.disable()
    hits = int(m.get('resident.batch_hits', 0))
    hit_rows = int(m.get('resident.batch_hit_rows', 0))
    if hits <= 0 or hit_rows <= 0:
        print('perf-smoke FAILED:', file=sys.stderr)
        print('  * resident.batch_hits=%d batch_hit_rows=%d (want both '
              '> 0) -- the pool-resident clock table did not survive '
              'across batches' % (hits, hit_rows), file=sys.stderr)
        print('  resident.* = %s' % json.dumps(
            {k: v for k, v in sorted(m.items())
             if k.startswith('resident.')}), file=sys.stderr)
        return 1
    print('perf-smoke: resident batch state served across batches '
          '(batch_hits=%d, hit_rows=%d)' % (hits, hit_rows))
    return 0


def main():
    rc = quickbench_gates()
    if rc:
        return rc
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ['AMTPU_HOST_FULL'] = '0'
    os.environ.setdefault('AMTPU_BENCH_C4_DOCS', '64')
    return resident_hit_gate()


if __name__ == '__main__':
    sys.exit(main())
