"""CI gate: the packed member epilogue must be ACTIVE on the table
workload's kernel path (ISSUE 3).

Runs the config-4 shape through tools/quickbench.py with the kernel path
forced (AMTPU_HOST_FULL=0) and fails if

  * `fallback.oracle` is nonzero -- a register group fell past every
    escalation tier back to the host oracle, or
  * `collect.packed_member_batches` is zero -- the member-mode batches
    stopped taking the packed epilogue (ONE i32 per register row +
    sparse CSR conflicts), or
  * `collect.full_matrix_readback` is nonzero -- some batch read back
    the full winner/conflicts/alive/overflow matrices, the pre-packed
    transfer wall this gate exists to keep dead.

Wired into `make check` as `make perf-smoke` (next to fallback-check,
which gates the escalation ladder itself on the same shape).

Usage: [JAX_PLATFORMS=cpu] python tools/perf_smoke.py
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env['AMTPU_HOST_FULL'] = '0'            # the kernel path IS the subject
    env.pop('AMTPU_PACKED_EPILOGUE', None)  # gate the DEFAULT epilogue
    # same deterministic shape as fallback-check: member mode engages and
    # the dup-assign groups escalate, so the packed epilogue (not the
    # fused path) is what actually serves the batches
    env.setdefault('AMTPU_BENCH_C4_DOCS', '256')
    env.setdefault('AMTPU_BENCH_SHARDS', '8')
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, 'quickbench.py'),
         '--config', '4', '--runs', '1'],
        env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        print('perf-smoke: quickbench failed (rc=%d)' % proc.returncode,
              file=sys.stderr)
        return 1
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    tel = result.get('telemetry', {})
    fallbacks = tel.get('fallbacks', {})
    collect = tel.get('collect', {})

    problems = []
    if fallbacks.get('oracle', -1) != 0:
        problems.append('fallback.oracle = %s (want 0)'
                        % fallbacks.get('oracle'))
    if collect.get('packed_member_batches', 0) <= 0:
        problems.append('collect.packed_member_batches missing/zero -- '
                        'the packed member epilogue did not engage')
    if collect.get('full_matrix_readback', 0) != 0:
        problems.append('collect.full_matrix_readback = %s (want 0) -- '
                        'a batch read back the full register matrices'
                        % collect.get('full_matrix_readback'))
    if problems:
        print('perf-smoke FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        print('  telemetry.collect = %s' % json.dumps(collect),
              file=sys.stderr)
        print('  telemetry.fallbacks = %s' % json.dumps(fallbacks),
              file=sys.stderr)
        return 1
    print('perf-smoke: packed epilogue on %d member batches, '
          'full-matrix readbacks 0, oracle 0, %.0f ops/s'
          % (collect['packed_member_batches'], result.get('value', 0.0)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
