"""Project-specific static analysis gate (`make static-check`).

Runs the four automerge_tpu.analysis checkers -- env-latch,
telemetry-key, dispatch-alias, lock-discipline (docs/ANALYSIS.md) --
over the package, then the generic Python lint baseline (ruff or
pyflakes, whichever is installed; skipped with a note otherwise --
the container must not need a pip install to gate).

Exit code 1 on any finding.  Usage:

    python tools/static_check.py                 # the full gate
    python tools/static_check.py --only env-latch
    python tools/static_check.py --extra tests/fixtures/analysis/x.py
"""

import argparse
import os
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from automerge_tpu.analysis import run_checks  # noqa: E402
from automerge_tpu.analysis.engine import CHECKERS  # noqa: E402


def run_generic_lint():
    """ruff/pyflakes baseline (pyproject.toml [tool.ruff]); returns
    (finding_count, label) -- the label records what actually ran so
    the PASS line never claims coverage that was skipped."""
    targets = [os.path.join(ROOT, 'automerge_tpu')]
    if shutil.which('ruff'):
        cmd, label = ['ruff', 'check'] + targets, 'ruff'
    else:
        try:
            import pyflakes  # noqa: F401
            cmd = [sys.executable, '-m', 'pyflakes'] + targets
            label = 'pyflakes'
        except ImportError:
            print('static-check: generic lint skipped (neither ruff nor '
                  'pyflakes is installed; the project checkers still '
                  'gate)', file=sys.stderr)
            return 0, 'lint skipped'
    proc = subprocess.run(cmd, cwd=ROOT, capture_output=True, text=True)
    out = (proc.stdout + proc.stderr).strip()
    if proc.returncode != 0:
        # a failing linter with empty output is still a failure --
        # never report silence as cleanliness
        print(out or ('static-check: %s exited %d with no output'
                      % (label, proc.returncode)))
        return max(1, out.count('\n') + 1), label
    return 0, label


def main(argv=None):
    # the checker registry needs the modules imported
    from automerge_tpu.analysis import (  # noqa: F401
        check_alias, check_env, check_locks, check_telemetry)
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--only', action='append', default=None,
                    metavar='CHECKER',
                    help='run only this checker (repeatable); known: %s'
                    % ', '.join(sorted(CHECKERS)))
    ap.add_argument('--extra', action='append', default=[],
                    metavar='FILE',
                    help='additionally scan this file (fixture lanes)')
    ap.add_argument('--no-lint', action='store_true',
                    help='skip the generic ruff/pyflakes baseline')
    args = ap.parse_args(argv)

    try:
        findings = run_checks(ROOT, checkers=args.only,
                              extra_files=args.extra)
    except ValueError as e:
        print('static-check: %s' % e, file=sys.stderr)
        return 2
    for f in findings:
        print(f.format(ROOT))
    n_lint, lint_label = (0, None) if (args.no_lint or args.only) \
        else run_generic_lint()
    total = len(findings) + n_lint
    if total:
        print('static-check: FAIL (%d finding%s)'
              % (total, '' if total == 1 else 's'))
        return 1
    print('static-check: PASS (%d checkers%s)'
          % (len(args.only or CHECKERS),
             '' if lint_label is None else ' + %s' % lint_label))
    return 0


if __name__ == '__main__':
    sys.exit(main())
