"""CI gate: cold-state economics (ISSUE 10, docs/STORAGE.md).

Four acceptance checks, one process, kernel path forced:

  1. **compression** -- columnar-encoding the config-4 bench change
     corpus (per doc, the real save/WAL unit) must be >= 5x smaller
     than the same corpus' JSON change bytes;
  2. **bounded arena under churn** -- a rolling create/mutate/idle
     workload with the settled-history GC cadence must end with a
     strictly smaller retained raw-change arena than an identical
     no-GC arm, with byte-identical final patches;
  3. **evict/reload byte parity** -- save -> drop_doc -> load ->
     mutate must equal a never-evicted twin, patch-for-patch;
  4. **oracle-free** -- `fallback.oracle == 0` across all of it (the
     storage tier may never push work off the kernel path).

Writes the BENCH_STORAGE artifact (JSON; `--out` overrides) with the
measured ratios and the telemetry block.

Usage: [JAX_PLATFORMS=cpu] python tools/storage_check.py [--out F]
"""
import argparse
import json
import os
import random
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['AMTPU_HOST_FULL'] = '0'       # the kernel path is the subject
os.environ.pop('AMTPU_STORAGE_FORMAT', None)   # columnar is the subject

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def _corpus():
    """The config-4 bench corpus at a CI-sized doc count (env
    overridable, same knob bench.py reads)."""
    sys.path.insert(0, ROOT)
    os.environ.setdefault('AMTPU_BENCH_C4_DOCS', '256')
    import bench
    batch, _metric = bench.build_config_4(random.Random(7))
    return batch


def check_compression(problems, report):
    import msgpack

    from automerge_tpu.storage import encode_columnar
    batch = _corpus()
    t0 = time.perf_counter()
    json_bytes = col_bytes = mp_bytes = n_changes = 0
    for changes in batch.values():
        raws = [msgpack.packb(c, use_bin_type=True) for c in changes]
        blob = encode_columnar(raws)
        json_bytes += len(json.dumps(
            {'version': 1, 'changes': changes}, separators=(',', ':'),
            sort_keys=True))
        mp_bytes += sum(len(r) for r in raws)
        col_bytes += len(blob)
        n_changes += len(changes)
    dt = time.perf_counter() - t0
    ratio = json_bytes / max(1, col_bytes)
    report['compression'] = {
        'docs': len(batch), 'changes': n_changes,
        'json_bytes': json_bytes, 'msgpack_bytes': mp_bytes,
        'columnar_bytes': col_bytes,
        'ratio_vs_json': round(ratio, 2),
        'ratio_vs_msgpack': round(mp_bytes / max(1, col_bytes), 2),
        'encode_s': round(dt, 3),
    }
    if ratio < 5.0:
        problems.append('columnar compression %.2fx vs JSON is below '
                        'the 5x gate' % ratio)


def _churn(pool, gc, docs=48, rounds=10, muts=6):
    """Rolling churn: every round mutates a rotating doc window; the
    GC arm folds settled history on the gateway cadence."""
    rng = random.Random(13)
    patches = {}
    seqs = {}
    for r in range(rounds):
        for d in range(docs):
            if (d + r) % 3:          # rotating idle window
                continue
            doc = 'churn%d' % d
            actor = 'w%d' % (d % 4)
            seq0 = seqs.get(doc, 0)
            changes = []
            for i in range(muts):
                changes.append({
                    'actor': actor, 'seq': seq0 + i + 1,
                    'deps': {actor: seq0 + i} if seq0 + i else {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID,
                             'key': 'k%d' % rng.randrange(16),
                             'value': r * 1000 + i}]})
            seqs[doc] = seq0 + muts
            pool.apply_changes(doc, changes)
            if gc:
                pool.compact(doc)
    for d in range(docs):
        patches['churn%d' % d] = pool.get_patch('churn%d' % d)
    return patches


def check_churn(problems, report):
    from automerge_tpu.native import NativeDocPool
    gc_pool, raw_pool = NativeDocPool(), NativeDocPool()
    t0 = time.perf_counter()
    gc_patches = _churn(gc_pool, gc=True)
    raw_patches = _churn(raw_pool, gc=False)
    dt = time.perf_counter() - t0
    gc_arena = gc_pool.history_bytes()
    raw_arena = raw_pool.history_bytes()
    report['churn'] = {
        'gc_arena_bytes': gc_arena, 'nogc_arena_bytes': raw_arena,
        'arena_ratio': round(raw_arena / max(1, gc_arena), 2),
        'wall_s': round(dt, 3),
    }
    if gc_patches != raw_patches:
        problems.append('churn workload: GC arm patches diverge from '
                        'the no-GC arm')
    if not gc_arena < raw_arena:
        problems.append('post-GC arena (%d B) is not smaller than the '
                        'no-GC arm (%d B)' % (gc_arena, raw_arena))


def check_evict_reload(problems, report):
    from automerge_tpu.native import NativeDocPool
    pool, twin = NativeDocPool(), NativeDocPool()
    batch = _corpus()
    sample = dict(list(batch.items())[:8])
    for p in (pool, twin):
        for d, changes in sample.items():
            p.apply_changes('t%d' % d, changes)
    cycled = 0
    for d in sample:
        doc = 't%d' % d
        pool.compact(doc)
        blob = pool.save(doc)
        if not pool.drop_doc(doc):
            problems.append('drop_doc(%r) found nothing' % doc)
            continue
        pool.load(doc, blob)
        cycled += 1
    mut = [{'actor': 'z', 'seq': 1, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': 'post-evict', 'value': 1}]}]
    mismatches = 0
    for d in sample:
        doc = 't%d' % d
        if pool.apply_changes(doc, mut) != twin.apply_changes(doc, mut):
            mismatches += 1
        elif pool.get_patch(doc) != twin.get_patch(doc):
            mismatches += 1
    report['evict_reload'] = {'docs_cycled': cycled,
                              'mismatches': mismatches}
    if mismatches:
        problems.append('%d docs diverged through the save -> evict '
                        '-> reload -> mutate cycle' % mismatches)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=os.path.join(ROOT,
                                                  'BENCH_STORAGE.json'))
    args = ap.parse_args()
    from automerge_tpu import telemetry
    telemetry.metrics_reset()
    problems = []
    report = {'metric': 'storage_check', 'ts': time.time()}
    check_compression(problems, report)
    check_churn(problems, report)
    check_evict_reload(problems, report)
    snap = telemetry.metrics_snapshot()
    oracle = snap.get('fallback.oracle', 0)
    if oracle:
        problems.append('fallback.oracle == %s on the storage gate '
                        'workloads (must be 0)' % oracle)
    report['fallback_oracle'] = oracle
    report['telemetry'] = telemetry.bench_block()
    with open(args.out, 'w') as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write('\n')
    if problems:
        print('storage-check FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        return 1
    c = report['compression']
    print('storage-check: %.1fx vs JSON (%.1fx vs msgpack) on %d '
          'changes; churn arena %d -> %d B (%.1fx); %d evict/reload '
          'cycles byte-identical; oracle=0'
          % (c['ratio_vs_json'], c['ratio_vs_msgpack'], c['changes'],
             report['churn']['nogc_arena_bytes'],
             report['churn']['gc_arena_bytes'],
             report['churn']['arena_ratio'],
             report['evict_reload']['docs_cycled']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
