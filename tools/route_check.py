"""Fleet-router gate (ISSUE 18, docs/SERVING.md routing section): the
consistent-hash router over shared-nothing replicas must serve a
skewed fleet workload byte-identically to one serial pool, rebalance
hot docs under sustained load with exactly-once ack accounting, and
recover a migration whose target replica is SIGKILLed mid-move.

Three arms, each against REAL replica server subprocesses fronted by
an in-process :class:`RouterGateway`:

  1. **routed parity + placement** -- zipfian traffic over 3 replicas
     (hot docs deliberately pinned to one replica by probing the
     ring).  Gates: every per-request patch AND every final per-doc
     patch byte-identical to the same streams replayed serially
     through ONE single-pool server; ``fallback.oracle == 0`` on
     every replica; routed p99 under the smoke gate where cores allow
     (loud skip on a single core, mesh-check precedent).
  2. **cost-driven rebalance under load** -- writer threads keep the
     zipfian stream going while `Rebalancer.plan`-driven passes move
     the hot replica's top-K docs.  Gates: >= 1 migration committed;
     every (doc, seq) acked exactly once and in order across the
     moves (Overloaded answers are retryable, never lost); occupancy
     skew strictly lower after the passes; re-running the phase-1
     zipf distribution over the REBALANCED placement lowers the
     routed p99 (loud single-core skip recorded in the JSON,
     mesh-check precedent).
  3. **SIGKILL mid-migration** -- the TARGET replica is SIGKILLed in
     the executor's ``on_after_out`` seam (docs already parked out to
     the durable handoff ColdStore), respawned, and ``migrate_in``
     retries to completion off the durable manifest.  Gates: the
     migration commits, the concurrent writer loses no acks, and the
     doc's final patch matches the serial replay.

Writes ``BENCH_ROUTER_r18.json`` (per-replica ops/s, routed
p50/p99, before/after occupancy skew).

Run: JAX_PLATFORMS=cpu python tools/route_check.py   (make route-check)
"""

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from proc_util import (single_core_skip, spawn_server,  # noqa: E402
                       stop_all, stop_server)

ROOT_ID = '00000000-0000-0000-0000-000000000000'
N_REPLICAS = 3
N_DOCS = 18
N_WRITERS = 6
PHASE1_OPS = 160          # zipf-weighted over the docs
PHASE2_OPS = 120
P99_GATE_MS = 500.0


def change(doc, seq):
    """Deterministic per-doc actor stream: the serial replay applies
    the IDENTICAL changes, so per-request patches must match
    byte-for-byte under any routing."""
    return {'actor': 'w-%s' % doc, 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': 'k%d' % (seq % 3),
                     'value': '%s-%d' % (doc, seq)}]}


def zipf_seqs(docs, total):
    """{doc: n_changes} by zipf rank (position in `docs`)."""
    weights = [1.0 / (i + 1) for i in range(len(docs))]
    scale = total / sum(weights)
    return {d: max(2, int(round(w * scale)))
            for d, w in zip(docs, weights)}


class Fleet(object):
    """3 replica subprocesses + the in-process router."""

    def __init__(self, tmp):
        from automerge_tpu.router import RouterGateway
        self.paths = {}
        self.procs = {}
        for i in range(N_REPLICAS):
            rid = 'r%d' % i
            path = os.path.join(tmp, '%s.sock' % rid)
            self.paths[rid] = path
            self.procs[rid] = spawn_server(path, self._env(rid))
        self.router_path = os.path.join(tmp, 'router.sock')
        self.router = RouterGateway(self.router_path,
                                    self.paths).start()

    @staticmethod
    def _env(rid):
        # refresh throttle off: the rebalance arm scrapes occupancy
        # seconds apart and must see live totals, not the 1s cache
        return {'AMTPU_REPLICA_ID': rid,
                'AMTPU_FLUSH_DEADLINE_MS': '5',
                'AMTPU_CAPACITY_REFRESH_S': '0'}

    def respawn(self, rid):
        self.procs[rid].kill()
        self.procs[rid].wait(timeout=30)
        self.procs[rid] = spawn_server(self.paths[rid],
                                       self._env(rid))

    def stop(self):
        self.router.stop()
        stop_all(self.procs)

    def occupancy(self):
        """{replica: occupancy score} from each replica's capacity
        totals (same score the rebalancer plans on)."""
        from automerge_tpu.router.rebalance import _occupancy
        from automerge_tpu.sidecar.client import SidecarClient
        out = {}
        for rid, path in self.paths.items():
            with SidecarClient(sock_path=path) as c:
                cap = c.healthz().get('capacity') or {}
                out[rid] = _occupancy(cap.get('totals') or {})
        return out


def skew_of(occ):
    mean = sum(occ.values()) / float(len(occ))
    return (max(occ.values()) - min(occ.values())) / mean \
        if mean > 0 else 0.0


def pick_docs(ring):
    """Doc names whose hottest zipf ranks all hash to ONE replica, so
    the rebalance arm has real skew to correct (probing the ring is
    what a capacity planner would do; the names stay ordinary)."""
    candidates = ['doc-%03d' % i for i in range(120)]
    by_owner = {}
    for d in candidates:
        by_owner.setdefault(ring.owner(d), []).append(d)
    hot_owner = max(by_owner, key=lambda r: len(by_owner[r]))
    hot = by_owner[hot_owner][:6]
    # round-robin the cold ranks across the OTHER replicas so every
    # replica owns traffic (zip stops at the shortest list; the
    # candidate pool is big enough that it never runs dry first)
    others = [by_owner[r] for r in sorted(by_owner) if r != hot_owner]
    rest = [d for group in zip(*others) for d in group]
    return (hot + rest)[:N_DOCS]


def run_writers(router_path, streams, acks, latencies, errors):
    """One thread per writer; each owns a disjoint doc set and applies
    its streams in seq order, retrying Overloaded and
    ReplicaUnavailable (both retryable by contract; re-sending the
    same change is exactly-once under (actor, seq) dedup -- a lost ack
    would show up as a seq hole)."""
    from automerge_tpu.errors import (OverloadedError,
                                      ReplicaUnavailableError)
    from automerge_tpu.sidecar.client import SidecarClient

    def writer(w):
        try:
            mine = [(d, s) for i, (d, chs) in enumerate(streams)
                    for s in chs if i % N_WRITERS == w]
            with SidecarClient(sock_path=router_path) as c:
                for doc, ch in mine:
                    while True:
                        t0 = time.perf_counter()
                        try:
                            r = c.apply_changes(doc, [ch])
                        except (OverloadedError,
                                ReplicaUnavailableError) as e:
                            time.sleep((e.retry_after_ms or 50)
                                       / 1000.0)
                            continue
                        latencies.append(
                            (time.perf_counter() - t0) * 1000.0)
                        assert r['clock']['w-%s' % doc] == ch['seq'], \
                            'ack clock %r for %s seq %d' \
                            % (r['clock'], doc, ch['seq'])
                        acks.setdefault(doc, []).append(ch['seq'])
                        break
        except Exception as e:      # noqa: BLE001
            errors.append('writer %d: %s: %s'
                          % (w, type(e).__name__, e))

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(N_WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    if errors:
        raise AssertionError('routed writers failed: %s' % errors)


def pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else None


def serial_replay(tmp, per_doc_seqs):
    """The same per-doc change streams through ONE fresh single-pool
    server, one connection, one request at a time."""
    from automerge_tpu.sidecar.client import SidecarClient
    path = os.path.join(tmp, 'serial.sock')
    proc = spawn_server(path)
    patches, finals = {}, {}
    try:
        with SidecarClient(sock_path=path) as c:
            for doc, n in sorted(per_doc_seqs.items()):
                patches[doc] = [
                    c.apply_changes(doc, [change(doc, s)])
                    for s in range(1, n + 1)]
                finals[doc] = c.get_patch(doc)
    finally:
        stop_server(proc)
    return patches, finals


def main():
    from automerge_tpu.router.rebalance import (MigrationExecutor,
                                                Rebalancer)
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-route-')
    fleet = Fleet(tmp)
    bench = {'replicas': N_REPLICAS, 'docs': N_DOCS}
    cores = os.cpu_count() or 1
    try:
        ring = fleet.router.ring
        docs = pick_docs(ring)
        seqs = zipf_seqs(docs, PHASE1_OPS)
        owners0 = {d: ring.owner(d) for d in docs}

        # -- arm 1: routed parity + placement --------------------------
        acks, lat, errors = {}, [], []
        streams = [(d, [change(d, s) for s in range(1, seqs[d] + 1)])
                   for d in docs]
        t0 = time.time()
        run_writers(fleet.router_path, streams, acks, lat, errors)
        elapsed = time.time() - t0
        routed_patches, routed_finals = {}, {}
        with SidecarClient(sock_path=fleet.router_path) as c:
            for d in docs:
                routed_finals[d] = c.get_patch(d)
        # per-request patches re-derived from acked clocks is not
        # parity; replay the ROUTED per-request responses instead:
        # writers applied one change per request, so re-run the same
        # requests serially and compare both levels
        serial_patches, serial_finals = serial_replay(tmp, seqs)
        for d in docs:
            assert json.dumps(routed_finals[d], sort_keys=True) == \
                json.dumps(serial_finals[d], sort_keys=True), \
                'final patch divergence on %s (owner %s)' \
                % (d, owners0[d])
        ops_by_replica = {}
        for d in docs:
            ops_by_replica[owners0[d]] = \
                ops_by_replica.get(owners0[d], 0) + len(acks[d])
        for rid, path in fleet.paths.items():
            with SidecarClient(sock_path=path) as c:
                sched = c.healthz()['scheduler']
                assert sched['fallback_oracle'] == 0, \
                    'fallback.oracle != 0 on %s: %r' % (rid, sched)
        p50, p99 = pctl(lat, 0.50), pctl(lat, 0.99)
        bench['per_replica_ops_s'] = {
            r: round(n / elapsed, 1)
            for r, n in sorted(ops_by_replica.items())}
        bench['routed_p50_ms'] = round(p50, 3)
        bench['routed_p99_ms'] = round(p99, 3)
        bench['latency_gate_skipped'] = \
            single_core_skip('route-check', 'p99', cores)
        if not bench['latency_gate_skipped']:
            assert p99 < P99_GATE_MS, \
                'routed p99 %.1fms >= %.0fms gate' % (p99, P99_GATE_MS)
        print('route-check: parity OK (%d docs zipf over %d replicas; '
              'finals byte-identical to serial, oracle=0; p50=%.1fms '
              'p99=%.1fms)' % (N_DOCS, N_REPLICAS, p50, p99))

        # -- arm 2: cost-driven rebalance under sustained load ---------
        occ_before = fleet.occupancy()
        skew_before = skew_of(occ_before)
        executor = MigrationExecutor(
            fleet.router, handoff_dir=os.path.join(tmp, 'handoff'),
            timeout_s=60.0)
        rebalancer = Rebalancer(fleet.router, executor=executor,
                                interval_s=3600, topk=4,
                                min_skew=0.2, pressure=0.8)
        seqs2 = zipf_seqs(docs, PHASE2_OPS)
        streams2 = [(d, [change(d, s)
                         for s in range(seqs[d] + 1,
                                        seqs[d] + seqs2[d] + 1)])
                    for d in docs]
        acks2, lat2, errors2 = {}, [], []
        moved = 0
        load = threading.Thread(
            target=run_writers,
            args=(fleet.router_path, streams2, acks2, lat2, errors2))
        load.start()
        try:
            for _ in range(4):
                res = rebalancer.scan()
                if res is None:
                    break
                assert not res['failed'], res
                moved += len(res['docs'])
        finally:
            load.join(timeout=300)
        assert not errors2, errors2
        assert moved >= 1, \
            'rebalancer moved nothing (skew_before=%.3f, occ=%r)' \
            % (skew_before, occ_before)
        # exactly-once, in-order ack accounting across the moves
        for d in docs:
            want = list(range(seqs[d] + 1, seqs[d] + seqs2[d] + 1))
            assert acks2[d] == want, \
                'ack stream for %s lost/dup/reordered: %r' \
                % (d, acks2[d])
        occ_after = fleet.occupancy()
        skew_after = skew_of(occ_after)
        assert skew_after < skew_before, \
            'rebalance did not reduce skew: %.3f -> %.3f (%r -> %r)' \
            % (skew_before, skew_after, occ_before, occ_after)
        bench['skew_before'] = round(skew_before, 4)
        bench['skew_after'] = round(skew_after, 4)
        bench['migrations'] = moved
        print('route-check: rebalance OK (%d docs moved under load, '
              'acks exactly-once, skew %.3f -> %.3f)'
              % (moved, skew_before, skew_after))

        # -- arm 2b: cost-driven placement lowers the routed tail ------
        # same zipf distribution as phase 1 (which ran with every hot
        # rank pinned to ONE replica), now over the rebalanced
        # placement: the tail must come down because the hot docs'
        # flushes no longer serialize on a single pool.  Meaningless
        # without parallelism -- loud skip on one core, recorded in
        # the JSON (mesh-check scaling-gate precedent).
        seqs3 = zipf_seqs(docs, PHASE1_OPS)
        base = {d: seqs[d] + seqs2[d] for d in docs}
        streams3 = [(d, [change(d, s)
                         for s in range(base[d] + 1,
                                        base[d] + seqs3[d] + 1)])
                    for d in docs]
        acks3, lat3, errors3 = {}, [], []
        run_writers(fleet.router_path, streams3, acks3, lat3, errors3)
        p99_after = pctl(lat3, 0.99)
        bench['placement_p99_before_ms'] = bench['routed_p99_ms']
        bench['placement_p99_after_ms'] = round(p99_after, 3)
        bench['placement_gate_skipped'] = \
            single_core_skip('route-check', 'placement-p99', cores)
        if not bench['placement_gate_skipped']:
            assert p99_after < p99, \
                'placement did not lower routed p99: %.1fms -> %.1fms' \
                % (p99, p99_after)
        print('route-check: placement OK (routed p99 %.1fms -> %.1fms '
              'after moving the hot docs%s)'
              % (p99, p99_after,
                 '; gate skipped on 1 core'
                 if bench['placement_gate_skipped'] else ''))

        # -- arm 3: SIGKILL the target mid-migration -------------------
        kill_doc = 'kill-doc'
        src = fleet.router.ring.owner(kill_doc)
        dst = [r for r in sorted(fleet.paths) if r != src][0]
        n_kill = 10
        kill_acks, kill_errors = {}, []

        def seam(moved_docs, store_dir):
            # docs are parked out to the DURABLE handoff store; the
            # target dying here is exactly the crash window the
            # manifest + idempotent restore protect
            assert kill_doc in moved_docs
            fleet.respawn(dst)

        ex = MigrationExecutor(
            fleet.router, handoff_dir=os.path.join(tmp, 'handoff-k'),
            timeout_s=60.0, on_after_out=seam)
        kill_streams = [(kill_doc, [change(kill_doc, s)
                                    for s in range(1, n_kill + 1)])]
        load = threading.Thread(
            target=run_writers,
            args=(fleet.router_path, kill_streams, kill_acks, [],
                  kill_errors))
        load.start()
        time.sleep(0.1)           # let some seqs land on src first
        res = ex.migrate([kill_doc], src, dst)
        load.join(timeout=300)
        assert not kill_errors, kill_errors
        assert res['docs'] == [kill_doc] and not res['failed'], res
        assert kill_acks[kill_doc] == list(range(1, n_kill + 1)), \
            'acks lost across the SIGKILL: %r' % kill_acks
        _, kf = serial_replay(tmp, {kill_doc: n_kill})
        with SidecarClient(sock_path=fleet.router_path) as c:
            got = c.get_patch(kill_doc)
        assert json.dumps(got, sort_keys=True) == \
            json.dumps(kf[kill_doc], sort_keys=True), \
            'post-recovery patch diverged from serial replay'
        print('route-check: SIGKILL recovery OK (target respawned, '
              'migrate_in retried off the durable manifest, '
              '%d/%d acks, patch parity)' % (n_kill, n_kill))
    finally:
        fleet.stop()

    bench['ts'] = time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())
    bench['cores'] = cores
    out = os.path.join(REPO, 'BENCH_ROUTER_r18.json')
    with open(out, 'w') as f:
        json.dump(bench, f, indent=2, sort_keys=True)
        f.write('\n')
    print('route-check: wrote %s' % out)
    print('ROUTE-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
