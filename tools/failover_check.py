"""Fleet-failover gate (ISSUE 19, docs/RESILIENCE.md fleet degradation
tiers): a 3-replica supervised fleet under zipfian load must survive a
SIGKILL of one replica mid-flush with NO lost or duplicated acks, come
back byte-identical to a serial replay, resync its subscribers
gapless, and drain docs back onto the respawned generation.

One continuous scenario against REAL replica server subprocesses
spawned by the in-process :class:`ReplicaSupervisor` (write-through
stores, ``AMTPU_STORAGE_SYNC=1``), fronted by an in-process
:class:`RouterGateway` + :class:`HealthMonitor` + :class:`FailoverExecutor`:

  1. **warmup** -- zipfian writers land phase-1 streams; a subscriber
     attaches to the hottest victim-owned doc.
  2. **SIGKILL mid-flush** -- phase-2 writers are mid-stream when the
     victim replica is SIGKILLed.  The supervisor reports the exit,
     the health machine declares it dead, the failover executor
     restores its docs onto the survivors from its write-through
     store, parked frames replay, and the supervisor respawns a new
     generation that rejoins pinned (nothing implicitly remapped).
     Gates: every in-flight and subsequent request is answered within
     the park window (writers finish; retryable envelopes only --
     ``requests_failed_hard == 0``); exactly-once in-order acks per
     doc; ``fallback.oracle == 0`` on every live replica.
  3. **parity + resync + drain-back** -- every doc's final patch is
     byte-identical to the same streams replayed serially through ONE
     single-pool server (zero duplicate applies under (actor, seq)
     dedup); the subscriber observed the failover resync and reads
     through to the final clock gapless; a rebalance pass migrates
     >= 1 doc onto the rejoined generation and writes keep landing.

Writes ``BENCH_FAILOVER_r19.json`` (time-to-detect / time-to-restore /
time-to-rejoin, retry counts, recovered/lost/replayed).

Run: JAX_PLATFORMS=cpu python tools/failover_check.py  (make failover-check)
"""

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from route_check import (change, pctl, serial_replay,  # noqa: E402
                         zipf_seqs)

N_REPLICAS = 3
N_DOCS = 15
N_WRITERS = 5
PHASE1_OPS = 120
PHASE2_OPS = 150
DETECT_GATE_S = 15.0      # generous: CI boxes stall; the distribution
RESTORE_GATE_S = 30.0     # is what the artifact is for


def run_writers(router_path, streams, acks, retries, errors):
    """route_check's writer loop, plus a per-writer count of retryable
    answers (Overloaded / ReplicaUnavailable) -- the gate's
    ``requests_failed`` distribution.  Anything non-retryable is a
    hard failure."""
    from automerge_tpu.errors import (OverloadedError,
                                      ReplicaUnavailableError)
    from automerge_tpu.sidecar.client import SidecarClient

    def writer(w):
        try:
            mine = [(d, s) for i, (d, chs) in enumerate(streams)
                    for s in chs if i % N_WRITERS == w]
            with SidecarClient(sock_path=router_path) as c:
                for doc, ch in mine:
                    while True:
                        try:
                            r = c.apply_changes(doc, [ch])
                        except (OverloadedError,
                                ReplicaUnavailableError) as e:
                            retries.append((doc, ch['seq']))
                            time.sleep((e.retry_after_ms or 50)
                                       / 1000.0)
                            continue
                        assert r['clock']['w-%s' % doc] == ch['seq'], \
                            'ack clock %r for %s seq %d' \
                            % (r['clock'], doc, ch['seq'])
                        acks.setdefault(doc, []).append(ch['seq'])
                        break
        except Exception as e:      # noqa: BLE001
            errors.append('writer %d: %s: %s'
                          % (w, type(e).__name__, e))

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(N_WRITERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    if errors:
        raise AssertionError('writers failed hard: %s' % errors)


def poll(cond, deadline_s, what):
    t0 = time.time()
    while not cond():
        if time.time() - t0 > deadline_s:
            raise AssertionError('timed out (%.0fs) on %s'
                                 % (deadline_s, what))
        time.sleep(0.02)
    return time.time() - t0


def main():
    from automerge_tpu import telemetry
    from automerge_tpu.router import (FailoverExecutor, HealthMonitor,
                                      ReplicaSupervisor, RouterGateway)
    from automerge_tpu.router.rebalance import (MigrationExecutor,
                                                Rebalancer)
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-failover-')
    bench = {'replicas': N_REPLICAS, 'docs': N_DOCS}
    router_path = os.path.join(tmp, 'router.sock')
    router = RouterGateway(
        router_path, {},
        journal_path=os.path.join(tmp, 'placement.json')).start()
    ex = FailoverExecutor(router)
    hm = HealthMonitor(router, heartbeat_s=0.1, deadline_s=0.5,
                       miss_max=3, on_dead=ex.fail_over).start()
    sup = ReplicaSupervisor(
        router, tmp, health=hm, failover=ex,
        spawn_env={'AMTPU_FLUSH_DEADLINE_MS': '5',
                   'AMTPU_CAPACITY_REFRESH_S': '0',
                   'JAX_PLATFORMS': 'cpu'}).start()
    try:
        sup.spawn_fleet(N_REPLICAS)
        ring = router.ring
        docs = ['doc-%03d' % i for i in range(N_DOCS)]
        seqs1 = zipf_seqs(docs, PHASE1_OPS)
        seqs2 = zipf_seqs(docs, PHASE2_OPS)

        # -- phase 1: warmup under zipfian load ------------------------
        acks1, retries1, errs1 = {}, [], []
        streams1 = [(d, [change(d, s) for s in range(1, seqs1[d] + 1)])
                    for d in docs]
        run_writers(router_path, streams1, acks1, retries1, errs1)
        victim = ring.owner(docs[0])    # owner of the hottest doc
        victim_docs = [d for d in docs if ring.owner(d) == victim]
        sub_doc = victim_docs[0]
        total = {d: seqs1[d] + seqs2[d] for d in docs}

        # subscriber on a victim-owned doc: the client auto-resubscribes
        # through the failover resync envelope; reading to the final
        # clock proves the stream re-homed gapless
        sub = SidecarClient(sock_path=router_path)
        sub.subscribe(sub_doc, peer='failover-watch')

        # -- phase 2: SIGKILL the victim mid-flush ---------------------
        acks2, retries2, errs2 = {}, [], []
        streams2 = [(d, [change(d, s)
                         for s in range(seqs1[d] + 1, total[d] + 1)])
                    for d in docs]
        load = threading.Thread(
            target=run_writers,
            args=(router_path, streams2, acks2, retries2, errs2))
        load.start()
        time.sleep(0.3)                 # writers are mid-stream
        t_kill = time.time()
        sup.proc(victim).kill()
        detect_s = poll(lambda: hm.state(victim) == 'dead',
                        DETECT_GATE_S, 'death detection')
        restore_s = poll(lambda: victim not in router.replicas,
                         RESTORE_GATE_S, 'failover completion')
        rejoin_s = poll(
            lambda: any(m.endswith('-g1') for m in router.replicas),
            60, 'supervised respawn rejoin')
        load.join(timeout=300)
        assert not errs2, 'hard failures under failover: %s' % errs2
        joiner = [m for m in router.replicas if m.endswith('-g1')][0]

        # exactly-once, in-order acks across the kill (retries that
        # re-sent an already-applied change deduped on (actor, seq))
        for d in docs:
            want = list(range(seqs1[d] + 1, total[d] + 1))
            assert acks2[d] == want, \
                'ack stream for %s lost/dup/reordered: %r' \
                % (d, acks2[d])
        print('failover-check: SIGKILL survived (detect %.2fs, '
              'restore %.2fs, rejoin %.2fs as %s; %d retried '
              'requests, 0 hard failures)'
              % (detect_s, restore_s, rejoin_s, joiner, len(retries2)))

        # -- every doc answerable + byte parity vs serial replay -------
        finals = {}
        with SidecarClient(sock_path=router_path) as c:
            for d in docs:
                finals[d] = c.get_patch(d)
                assert finals[d]['clock'] == {'w-%s' % d: total[d]}, \
                    'clock for %s: %r (duplicate or lost applies)' \
                    % (d, finals[d]['clock'])
        _, serial_finals = serial_replay(tmp, total)
        for d in docs:
            assert json.dumps(finals[d], sort_keys=True) == \
                json.dumps(serial_finals[d], sort_keys=True), \
                'final patch divergence on %s after failover' % d
        print('failover-check: parity OK (%d docs byte-identical to '
              'serial replay; every doc answerable)' % N_DOCS)

        # -- subscriber resynced gapless -------------------------------
        deadline = time.time() + 60
        seen = {}
        while seen.get('w-%s' % sub_doc, 0) < total[sub_doc]:
            assert time.time() < deadline, \
                'subscriber never reached the final clock: %r' % seen
            e = sub.next_event(timeout=30)
            if e and e.get('event') == 'change':
                for a, s in (e.get('clock') or {}).items():
                    seen[a] = max(seen.get(a, 0), s)
        sub.close()
        flat = telemetry.metrics_snapshot()
        assert flat.get('router.resyncs', 0) >= 1, \
            'failover staged no subscriber resync'
        print('failover-check: subscriber resynced gapless to clock '
              '%d on %s' % (total[sub_doc], sub_doc))

        # -- rebalance drains docs back onto the rejoiner --------------
        executor = MigrationExecutor(
            router, handoff_dir=os.path.join(tmp, 'handoff'),
            timeout_s=60.0)
        rebalancer = Rebalancer(router, executor=executor,
                                interval_s=3600, topk=4,
                                min_skew=0.2, pressure=0.8)
        drained = 0
        for _ in range(4):
            res = rebalancer.scan()
            if res is None:
                break
            assert not res['failed'], res
            drained += sum(1 for d in res['docs']
                           if router.ring.owner(d) == joiner)
        assert drained >= 1, \
            'rebalancer drained nothing onto the rejoiner %s' % joiner
        moved_doc = next(d for d in docs
                         if router.ring.owner(d) == joiner)
        with SidecarClient(sock_path=router_path) as c:
            r = c.apply_changes(
                moved_doc, [change(moved_doc, total[moved_doc] + 1)])
            assert r['clock']['w-%s' % moved_doc] == \
                total[moved_doc] + 1
        print('failover-check: rebalance drained %d docs onto %s, '
              'writes landing' % (drained, joiner))

        # -- oracle stays cold on every live replica -------------------
        for member, path in sorted(router.replicas.items()):
            with SidecarClient(sock_path=path) as c:
                sched = c.healthz()['scheduler']
                assert sched['fallback_oracle'] == 0, \
                    'fallback.oracle != 0 on %s: %r' % (member, sched)

        bench['detect_s'] = round(detect_s, 3)
        bench['restore_s'] = round(restore_s, 3)
        bench['rejoin_s'] = round(rejoin_s, 3)
        bench['requests_retried'] = len(retries2)
        bench['requests_retried_p99_per_doc'] = pctl(
            sorted(sum(1 for rd, _ in retries2 if rd == d)
                   for d in docs), 0.99)
        bench['requests_failed_hard'] = 0
        bench['victim_docs'] = len(victim_docs)
        bench['drained_to_rejoiner'] = drained
        for k in ('failovers', 'docs_recovered', 'docs_lost',
                  'replayed', 'rejoins', 'respawns'):
            bench[k] = int(flat.get('failover.%s' % k, 0))
        assert bench['docs_lost'] == 0, bench
    finally:
        sup.stop()
        hm.stop()
        router.stop()

    bench['ts'] = time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())
    bench['cores'] = os.cpu_count() or 1
    out = os.path.join(REPO, 'BENCH_FAILOVER_r19.json')
    with open(out, 'w') as f:
        json.dump(bench, f, indent=2, sort_keys=True)
        f.write('\n')
    print('failover-check: wrote %s' % out)
    print('FAILOVER-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
