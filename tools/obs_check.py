"""Observability gate (ISSUE 12, docs/OBSERVABILITY.md): the flight
recorder, the per-request critical-path attribution, and the SLO
surface must actually work against a LIVE gateway, not just in unit
tests.

Four phases, each against real server subprocess(es) on unix sockets:

  1. **attribution + SLO + exemplars** -- 8 concurrent connections of
     mixed traffic (mutations + bypass reads) with ``AMTPU_SLOW_MS``
     pinned low so the tail sampler must fire.  Gates:
       * the per-stage ``amtpu_request_stage_ms`` sums partition the
         ``total`` series (sum of admit/queue/claim/dispatch/collect/
         emit ~= sum of total, within 2% -- the stages are deltas of
         one timestamp vector, so real drift means broken marks);
       * at least one ``request.exemplar`` span tree landed in the
         ``AMTPU_TRACE_FILE`` JSONL with its stage children and
         attached recorder events;
       * healthz carries the ``slo`` section (per-class windows +
         burn) and the ``recorder`` ring state;
       * ``tools/amtpu_top.py --once`` renders a frame from the live
         /metrics + /healthz listener;
       * SIGTERM leaves a recorder dump file behind.
  2. **fault -> quarantine -> dump** -- one armed permanent
     ``native.begin`` fault: the poisoned request answers the per-doc
     error envelope AND the quarantine triggers a recorder dump whose
     JSONL contains the injected ``fault.injected`` event (the
     post-mortem exists without anyone asking for it), while an
     on-demand ``dump`` request round-trips a fresh file.
  3. **two-process distributed tracing** (ISSUE 16) -- THIS process
     traces as the client (own ``AMTPU_TRACE_FILE``) against a traced
     server writing ITS own file; ``tools/amtpu_trace.py`` must
     assemble cross-process trees spanning both files.  Gates: joined
     trees exist; the server-side stage partition (the exemplar's
     stage children) accounts for the client wall within 5% (the
     residual is wire + client overhead); the SAME trace id shows up
     in the gateway's recorder ``request.slow`` events, in the request
     exemplars, and on the fan-out ``change`` frames a subscriber
     receives.
  4. **fleet aggregation** (ISSUE 16) -- two live replicas with
     distinct ``AMTPU_REPLICA_ID``s; ``amtpu_fleet --once --json``
     must merge them, and the merged SLO windows must equal the
     recompute from the summed slots (mergeable-slot additivity: the
     merged per-class window counts are exactly the per-replica sums
     through the same pure ``section_from_slots``).

Run: JAX_PLATFORMS=cpu python tools/obs_check.py      (make obs-check)
"""

import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CONNS = 8
ROUNDS = 6
ROOT_ID = '00000000-0000-0000-0000-000000000000'


def spawn_server(path, extra_env=None, stderr_path=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    stderr = open(stderr_path, 'wb') if stderr_path else None
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path]
        + (['--metrics-port', '0'] if stderr_path else []),
        env=env, cwd=REPO, stderr=stderr)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('gateway server did not come up')
        time.sleep(0.05)
    return proc


def stop_server(proc):
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


def metrics_port(stderr_path):
    """The ephemeral port the server printed to stderr."""
    deadline = time.time() + 30
    pat = re.compile(r'metrics on http://[^:]+:(\d+)/metrics')
    while time.time() < deadline:
        with open(stderr_path, 'rb') as f:
            m = pat.search(f.read().decode(errors='replace'))
        if m:
            return int(m.group(1))
        time.sleep(0.1)
    raise RuntimeError('metrics port never appeared on stderr')


def drive_traffic(path):
    from automerge_tpu.sidecar.client import SidecarClient
    errors = []

    def client(i):
        try:
            doc = 'obs-%02d' % i
            with SidecarClient(sock_path=path) as c:
                for s in range(1, ROUNDS + 1):
                    c.apply_changes(doc, [{
                        'actor': 'w%02d' % i, 'seq': s, 'deps': {},
                        'ops': [{'action': 'set', 'obj': ROOT_ID,
                                 'key': 'k%d' % (s % 3),
                                 'value': '%d-%d' % (i, s)}]}])
                    if s % 2 == 0:
                        c.get_patch(doc)
        except Exception as e:
            errors.append((i, '%s: %s' % (type(e).__name__, e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CONNS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError('traffic failed: %s' % errors)


def stage_sums(metrics_text):
    pat = re.compile(
        r'^amtpu_request_stage_ms_(sum|count)\{stage="([a-z]+)"\}'
        r'\s+(\S+)$', re.M)
    out = {}
    for kind, stage, val in pat.findall(metrics_text):
        out.setdefault(stage, {})[kind] = float(val)
    return out


def check_phase1(problems):
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-obs-')
    sock = os.path.join(tmp, 'gw.sock')
    trace_file = os.path.join(tmp, 'spans.jsonl')
    rec_dir = os.path.join(tmp, 'recorder')
    stderr_path = os.path.join(tmp, 'server.stderr')
    proc = spawn_server(sock, {
        'AMTPU_FLUSH_DEADLINE_MS': '5',
        'AMTPU_SLOW_MS': '0.01',         # everything is "slow": the
        'AMTPU_TRACE_FILE': trace_file,  # tail sampler must fire
        'AMTPU_RECORDER_DIR': rec_dir,
    }, stderr_path=stderr_path)
    try:
        drive_traffic(sock)
        with SidecarClient(sock_path=sock) as c:
            health = c.healthz()
            metrics = c.metrics()['body']
        port = metrics_port(stderr_path)
        import urllib.request
        with urllib.request.urlopen(
                'http://127.0.0.1:%d/debug/docs' % port,
                timeout=30) as r:
            debug_docs = json.loads(r.read())
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'amtpu_top.py'),
             '--url', 'http://127.0.0.1:%d' % port, '--once'],
            capture_output=True, text=True, timeout=60)
    finally:
        stop_server(proc)

    # 1a. stage sums partition the total
    stages = stage_sums(metrics)
    total = stages.get('total', {}).get('sum', 0.0)
    parts = sum(stages.get(s, {}).get('sum', 0.0)
                for s in ('admit', 'queue', 'claim', 'dispatch',
                          'collect', 'emit'))
    if total <= 0:
        problems.append('phase1: no attributed requests '
                        '(total sum = %r)' % total)
    elif abs(parts - total) > 0.02 * total:
        problems.append('phase1: stage sums %.3f ms != total %.3f ms '
                        '(>2%% drift)' % (parts, total))
    n_mut = stages.get('total', {}).get('count', 0)
    if n_mut < N_CONNS * ROUNDS:
        problems.append('phase1: only %s attributed requests '
                        '(want >= %d)' % (n_mut, N_CONNS * ROUNDS))

    # 1b. exemplars in the trace file, with children + recorder events
    roots, children = [], []
    if os.path.exists(trace_file):
        for ln in open(trace_file):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get('name') == 'request.exemplar':
                roots.append(rec)
            elif str(rec.get('name', '')).startswith('request.stage.'):
                children.append(rec)
    if not roots:
        problems.append('phase1: no request.exemplar records in %s'
                        % trace_file)
    else:
        root = roots[-1]
        kids = [c for c in children if c['parent'] == root['span']]
        if not kids:
            problems.append('phase1: exemplar has no stage children')
        if not isinstance(root.get('events'), list):
            problems.append('phase1: exemplar carries no recorder '
                            'events')

    # 1c. the SLO surface on healthz
    slo = health.get('slo') or {}
    if 'burn' not in slo or 'classes' not in slo:
        problems.append('phase1: healthz slo section missing/short: %r'
                        % sorted(slo))
    else:
        mut = slo['classes'].get('mutate', {}).get('300s', {})
        if not mut.get('count'):
            problems.append('phase1: slo mutate window empty: %r' % mut)
    if not (health.get('recorder') or {}).get('events'):
        problems.append('phase1: healthz recorder section empty')

    # 1d. amtpu_top renders from the live listener
    if top.returncode != 0 or 'stage waterfall' not in top.stdout:
        problems.append('phase1: amtpu_top --once failed (rc %s): %s %s'
                        % (top.returncode, top.stdout[-200:],
                           top.stderr[-200:]))

    # 1e. SIGTERM left a recorder dump behind
    if not glob.glob(os.path.join(rec_dir, '*sigterm*.jsonl')):
        problems.append('phase1: no sigterm recorder dump in %s'
                        % rec_dir)

    # 1f. the capacity surface (ISSUE 15): healthz `capacity` section,
    # /debug/docs, and the amtpu_top capacity panel all render the
    # live hot-doc table
    cap = health.get('capacity') or {}
    if not (cap.get('totals') or {}).get('arena_bytes'):
        problems.append('phase1: healthz capacity section has no arena '
                        'total: %r' % sorted(cap))
    elif not (cap.get('top') or {}).get('arena'):
        problems.append('phase1: healthz capacity hot-doc table empty')
    if not debug_docs.get('hot_docs'):
        problems.append('phase1: /debug/docs served no hot docs: %r'
                        % sorted(debug_docs))
    if 'capacity:' not in top.stdout or 'hot(arena):' not in top.stdout:
        problems.append('phase1: amtpu_top frame has no capacity '
                        'panel: %s' % top.stdout[-300:])
    if not problems:
        print('obs-check: phase 1 OK (%d reqs attributed; stage sums '
              '%.1f ms ~= total %.1f ms; %d exemplars; amtpu_top + '
              'capacity panel ok; sigterm dump present)'
              % (n_mut, parts, total, len(roots)))


def check_phase2(problems):
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-obs2-')
    sock = os.path.join(tmp, 'gw.sock')
    rec_dir = os.path.join(tmp, 'recorder')
    proc = spawn_server(sock, {
        'AMTPU_FLUSH_DEADLINE_MS': '5',
        'AMTPU_RECORDER_DIR': rec_dir,
        # one permanent begin fault: the first apply quarantines
        'AMTPU_FAULT': 'native.begin:permanent:1.0:1',
    })
    try:
        with SidecarClient(sock_path=sock) as c:
            from automerge_tpu.errors import AutomergeError
            try:
                resp = c.apply_changes('poison', [{
                    'actor': 'px', 'seq': 1, 'deps': {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID,
                             'key': 'k', 'value': 1}]}])
                problems.append('phase2: poisoned apply answered a '
                                'normal patch: %r' % (resp,))
            except AutomergeError:
                pass                     # the quarantine envelope
            # a healthy doc still serves afterwards
            ok = c.apply_changes('healthy', [{
                'actor': 'h', 'seq': 1, 'deps': {},
                'ops': [{'action': 'set', 'obj': ROOT_ID,
                         'key': 'k', 'value': 2}]}])
            if 'clock' not in ok:
                problems.append('phase2: healthy doc result odd: %r'
                                % (ok,))
            on_demand = c.dump()
            health = c.healthz()
    finally:
        stop_server(proc)

    dumps = glob.glob(os.path.join(rec_dir, '*quarantine*.jsonl'))
    if not dumps:
        problems.append('phase2: quarantine produced no recorder dump '
                        'in %s' % rec_dir)
    else:
        events = [json.loads(ln) for ln in open(dumps[0])][1:]
        fault = [e for e in events if e.get('event') == 'fault.injected']
        if not fault:
            problems.append('phase2: quarantine dump lacks the '
                            'injected fault event: %r'
                            % [e.get('event') for e in events][-10:])
        elif 'native.begin' not in str(fault[-1].get('detail')):
            problems.append('phase2: fault event detail odd: %r'
                            % fault[-1])
        quar = [e for e in events
                if e.get('event') == 'resilience.quarantine']
        if not quar or quar[-1].get('doc') != 'poison':
            problems.append('phase2: dump lacks the quarantine event '
                            'for the poisoned doc: %r' % quar)
    if not on_demand.get('path') or not os.path.exists(on_demand['path']):
        problems.append('phase2: on-demand dump did not round-trip a '
                        'file: %r' % on_demand)
    if health.get('resilience', {}).get('quarantined', 0) < 1:
        problems.append('phase2: healthz quarantined counter is zero')
    if not problems:
        print('obs-check: phase 2 OK (quarantine dumped %d events incl.'
              ' the injected fault; on-demand dump %s; healthz sees the'
              ' quarantine)' % (len(events), on_demand['path']))


def check_phase3(problems):
    """Two-process tracing: this process is the traced client, the
    server subprocess the traced hop; the assembled tree must join
    them."""
    import urllib.request

    from automerge_tpu import telemetry
    from automerge_tpu.sidecar.client import SidecarClient
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    import amtpu_trace
    tmp = tempfile.mkdtemp(prefix='amtpu-obs3-')
    sock = os.path.join(tmp, 'gw.sock')
    server_trace = os.path.join(tmp, 'server_spans.jsonl')
    client_trace = os.path.join(tmp, 'client_spans.jsonl')
    stderr_path = os.path.join(tmp, 'server.stderr')
    proc = spawn_server(sock, {
        'AMTPU_TRACE': '1',
        'AMTPU_TRACE_FILE': server_trace,
        'AMTPU_SLOW_MS': '0.01',         # every request leaves an
        'AMTPU_RECORDER_DIR': tmp,       # exemplar (rate limit aside)
        # server-resident wall >> wire: the flush deadline dominates
        # each request, so the 5% partition budget prices the real
        # wire + client overhead, not scheduling noise
        'AMTPU_FLUSH_DEADLINE_MS': '25',
    }, stderr_path=stderr_path)
    telemetry.enable()
    telemetry.set_trace_file(client_trace)
    fan_events = []
    try:
        with SidecarClient(sock_path=sock) as sub:
            sub.subscribe(doc='obs-00')
            drive_traffic(sock)
            deadline = time.time() + 30
            while time.time() < deadline and len(fan_events) < 4:
                ev = sub.next_event(timeout=2)
                if ev is None:
                    break
                fan_events.append(ev)
        port = metrics_port(stderr_path)
        with urllib.request.urlopen(
                'http://127.0.0.1:%d/debug/recorder' % port,
                timeout=30) as r:
            dbg = json.loads(r.read())
    finally:
        telemetry.set_trace_file(None)
        telemetry.disable()
        stop_server(proc)

    # 3a. cross-process assembly: trees spanning BOTH trace files
    traces = amtpu_trace.group_traces(
        amtpu_trace.load_files([client_trace, server_trace]))
    joined = {tid: nodes for tid, nodes in traces.items()
              if len({n['_proc'] for n in nodes}) >= 2}
    if not joined:
        problems.append('phase3: no trace joined both files '
                        '(%d client-only/server-only traces)'
                        % len(traces))
        return

    # 3b. the per-hop stage partition accounts for the client wall:
    # exemplar stage children ~= exemplar total (2%, the attribution
    # invariant), and total ~= client wall within 5% (the residual is
    # wire + client-side overhead)
    best = None
    partitioned = 0
    for tid, nodes in joined.items():
        spans = {n['span']: n for n in nodes}
        client = next((n for n in nodes
                       if n['name'] == 'sidecar.client.request'
                       and n.get('parent') not in spans), None)
        ex = next((n for n in nodes
                   if n['name'] == 'request.exemplar'), None)
        if client is None or ex is None:
            continue
        kids = [n for n in nodes
                if str(n['name']).startswith('request.stage.')
                and n.get('parent') == ex['span']]
        stage_sum = sum(n['dur_s'] for n in kids
                        if n['name'] != 'request.stage.fanout')
        wall = client.get('dur_s', 0.0)
        if not kids or wall <= 0 or ex['dur_s'] <= 0:
            continue
        if abs(stage_sum - ex['dur_s']) > 0.02 * ex['dur_s']:
            continue
        partitioned += 1
        residual = (wall - ex['dur_s']) / wall
        if best is None or abs(residual) < abs(best):
            best = residual
    if not partitioned:
        problems.append('phase3: no joined trace carried a stage-'
                        'partitioned exemplar (of %d joined)'
                        % len(joined))
    elif best is None or not -0.05 <= best <= 0.05:
        problems.append('phase3: per-hop stages leave %.1f%% of the '
                        'client wall unaccounted (budget 5%%)'
                        % (100 * (best or 1.0)))

    # 3c. the SAME trace ids in the gateway recorder + exemplars
    rec_traced = {e.get('trace') for e in dbg.get('events', ())
                  if e.get('event') == 'request.slow' and e.get('trace')}
    if not rec_traced & set(joined):
        problems.append('phase3: no recorder request.slow event '
                        'carries a joined trace id (%d traced events)'
                        % len(rec_traced))
    ex_traced = [x for x in dbg.get('exemplars', ())
                 if x.get('trace') in joined and x.get('parent')]
    if not ex_traced:
        problems.append('phase3: no served exemplar adopted a joined '
                        'wire trace (parent span + trace id)')

    # 3d. fan-out event frames carry the originating trace id
    fan_traced = [ev for ev in fan_events
                  if ev.get('event') == 'change' and ev.get('trace')]
    if not fan_traced:
        problems.append('phase3: no fan-out change frame carried a '
                        'trace id (%d frames)' % len(fan_events))
    elif not {ev['trace'] for ev in fan_traced} & set(traces):
        problems.append('phase3: fan-out frame trace ids match no '
                        'client trace')
    if not problems:
        print('obs-check: phase 3 OK (%d/%d traces joined 2 files; '
              'best wall residual %.2f%%; recorder/exemplar/fan-out '
              'frames all trace-correlated)'
              % (len(joined), len(traces), 100 * (best or 0.0)))


def check_phase4(problems):
    """Fleet arm: two live replicas, one merged view, merged SLO
    windows == per-replica recompute sums."""
    from automerge_tpu.telemetry import fleet
    from automerge_tpu.telemetry.attribution import section_from_slots
    tmp = tempfile.mkdtemp(prefix='amtpu-obs4-')
    procs = []
    try:
        socks = []
        for i in (1, 2):
            sock = os.path.join(tmp, 'gw%d.sock' % i)
            procs.append(spawn_server(sock, {
                'AMTPU_FLUSH_DEADLINE_MS': '5',
                'AMTPU_REPLICA_ID': 'obs-replica-%d' % i,
            }, stderr_path=os.path.join(tmp, 'server%d.stderr' % i)))
            socks.append(sock)
        for sock in socks:
            drive_traffic(sock)
        urls = ['http://127.0.0.1:%d'
                % metrics_port(os.path.join(tmp, 'server%d.stderr' % i))
                for i in (1, 2)]
        scrapes = [fleet.scrape(u, timeout=30) for u in urls]
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
        cli = subprocess.run(
            [sys.executable,
             os.path.join(REPO, 'tools', 'amtpu_fleet.py'),
             '--url', urls[0], '--url', urls[1], '--once', '--json',
             '--timeout', '30'],
            capture_output=True, text=True, timeout=120, env=env)
    finally:
        for p in procs:
            stop_server(p)

    errs = [s for s in scrapes if 'error' in s]
    if errs:
        problems.append('phase4: scrape failed: %r' % errs)
        return
    ids = {s['replica_id'] for s in scrapes}
    if ids != {'obs-replica-1', 'obs-replica-2'}:
        problems.append('phase4: replica ids wrong: %r' % sorted(ids))

    # merged windows equal the per-replica recompute sums through the
    # SAME pure function, at one aligned now_slot (bit-consistency of
    # the mergeable-slot design)
    all_slots = [s['slots'] for s in scrapes]
    slot_keys = [int(k) for slots in all_slots
                 for per_cls in slots.values() for k in per_cls]
    if not slot_keys:
        problems.append('phase4: no SLO slots scraped')
        return
    now_slot = max(slot_keys) + 1
    merged_sec = section_from_slots(fleet.merge_slots(all_slots),
                                    now_slot=now_slot)
    per_secs = [section_from_slots(s, now_slot=now_slot)
                for s in all_slots]
    for cls, wins in merged_sec['classes'].items():
        for win, row in wins.items():
            want = sum(p['classes'].get(cls, {}).get(win, {})
                       .get('count', 0) for p in per_secs)
            if row['count'] != want:
                problems.append(
                    'phase4: merged %s/%s count %d != per-replica sum '
                    '%d' % (cls, win, row['count'], want))
    mut = merged_sec['classes'].get('mutate', {}).get('3600s', {})
    if mut.get('count', 0) < 2 * N_CONNS * ROUNDS:
        problems.append('phase4: merged mutate window count %s < both '
                        'replicas\' traffic (%d)'
                        % (mut.get('count'), 2 * N_CONNS * ROUNDS))

    # the CLI recomputes the same merge from its own scrape (slots are
    # frozen once traffic stops, so the hour window must agree exactly)
    if cli.returncode != 0:
        problems.append('phase4: amtpu_fleet --once failed (rc %s): %s'
                        % (cli.returncode, cli.stderr[-300:]))
        return
    try:
        section = json.loads(cli.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        problems.append('phase4: amtpu_fleet --json unparseable: %r'
                        % cli.stdout[-300:])
        return
    if len(section.get('replicas', ())) != 2 or section.get('errors'):
        problems.append('phase4: fleet section roll-call wrong: %r/%r'
                        % (section.get('replicas'),
                           section.get('errors')))
    cli_mut = (section.get('slo', {}).get('classes', {})
               .get('mutate', {}).get('3600s', {}))
    if cli_mut.get('count') != mut.get('count'):
        problems.append('phase4: amtpu_fleet merged count %s != local '
                        'recompute %s'
                        % (cli_mut.get('count'), mut.get('count')))
    if not problems:
        print('obs-check: phase 4 OK (2 replicas merged; %d requests '
              'in the merged mutate window == per-replica sums; '
              'amtpu_fleet --once agrees)' % mut.get('count', 0))


def main():
    problems = []
    check_phase1(problems)
    if not problems:
        check_phase2(problems)
    if not problems:
        check_phase3(problems)
    if not problems:
        check_phase4(problems)
    if problems:
        for p in problems:
            print('obs-check: FAIL %s' % p)
        return 1
    print('obs-check: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
