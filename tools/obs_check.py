"""Observability gate (ISSUE 12, docs/OBSERVABILITY.md): the flight
recorder, the per-request critical-path attribution, and the SLO
surface must actually work against a LIVE gateway, not just in unit
tests.

Two phases, each against a real server subprocess on a unix socket:

  1. **attribution + SLO + exemplars** -- 8 concurrent connections of
     mixed traffic (mutations + bypass reads) with ``AMTPU_SLOW_MS``
     pinned low so the tail sampler must fire.  Gates:
       * the per-stage ``amtpu_request_stage_ms`` sums partition the
         ``total`` series (sum of admit/queue/claim/dispatch/collect/
         emit ~= sum of total, within 2% -- the stages are deltas of
         one timestamp vector, so real drift means broken marks);
       * at least one ``request.exemplar`` span tree landed in the
         ``AMTPU_TRACE_FILE`` JSONL with its stage children and
         attached recorder events;
       * healthz carries the ``slo`` section (per-class windows +
         burn) and the ``recorder`` ring state;
       * ``tools/amtpu_top.py --once`` renders a frame from the live
         /metrics + /healthz listener;
       * SIGTERM leaves a recorder dump file behind.
  2. **fault -> quarantine -> dump** -- one armed permanent
     ``native.begin`` fault: the poisoned request answers the per-doc
     error envelope AND the quarantine triggers a recorder dump whose
     JSONL contains the injected ``fault.injected`` event (the
     post-mortem exists without anyone asking for it), while an
     on-demand ``dump`` request round-trips a fresh file.

Run: JAX_PLATFORMS=cpu python tools/obs_check.py      (make obs-check)
"""

import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CONNS = 8
ROUNDS = 6
ROOT_ID = '00000000-0000-0000-0000-000000000000'


def spawn_server(path, extra_env=None, stderr_path=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    stderr = open(stderr_path, 'wb') if stderr_path else None
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path]
        + (['--metrics-port', '0'] if stderr_path else []),
        env=env, cwd=REPO, stderr=stderr)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('gateway server did not come up')
        time.sleep(0.05)
    return proc


def stop_server(proc):
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


def metrics_port(stderr_path):
    """The ephemeral port the server printed to stderr."""
    deadline = time.time() + 30
    pat = re.compile(r'metrics on http://[^:]+:(\d+)/metrics')
    while time.time() < deadline:
        with open(stderr_path, 'rb') as f:
            m = pat.search(f.read().decode(errors='replace'))
        if m:
            return int(m.group(1))
        time.sleep(0.1)
    raise RuntimeError('metrics port never appeared on stderr')


def drive_traffic(path):
    from automerge_tpu.sidecar.client import SidecarClient
    errors = []

    def client(i):
        try:
            doc = 'obs-%02d' % i
            with SidecarClient(sock_path=path) as c:
                for s in range(1, ROUNDS + 1):
                    c.apply_changes(doc, [{
                        'actor': 'w%02d' % i, 'seq': s, 'deps': {},
                        'ops': [{'action': 'set', 'obj': ROOT_ID,
                                 'key': 'k%d' % (s % 3),
                                 'value': '%d-%d' % (i, s)}]}])
                    if s % 2 == 0:
                        c.get_patch(doc)
        except Exception as e:
            errors.append((i, '%s: %s' % (type(e).__name__, e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CONNS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError('traffic failed: %s' % errors)


def stage_sums(metrics_text):
    pat = re.compile(
        r'^amtpu_request_stage_ms_(sum|count)\{stage="([a-z]+)"\}'
        r'\s+(\S+)$', re.M)
    out = {}
    for kind, stage, val in pat.findall(metrics_text):
        out.setdefault(stage, {})[kind] = float(val)
    return out


def check_phase1(problems):
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-obs-')
    sock = os.path.join(tmp, 'gw.sock')
    trace_file = os.path.join(tmp, 'spans.jsonl')
    rec_dir = os.path.join(tmp, 'recorder')
    stderr_path = os.path.join(tmp, 'server.stderr')
    proc = spawn_server(sock, {
        'AMTPU_FLUSH_DEADLINE_MS': '5',
        'AMTPU_SLOW_MS': '0.01',         # everything is "slow": the
        'AMTPU_TRACE_FILE': trace_file,  # tail sampler must fire
        'AMTPU_RECORDER_DIR': rec_dir,
    }, stderr_path=stderr_path)
    try:
        drive_traffic(sock)
        with SidecarClient(sock_path=sock) as c:
            health = c.healthz()
            metrics = c.metrics()['body']
        port = metrics_port(stderr_path)
        import urllib.request
        with urllib.request.urlopen(
                'http://127.0.0.1:%d/debug/docs' % port,
                timeout=30) as r:
            debug_docs = json.loads(r.read())
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'amtpu_top.py'),
             '--url', 'http://127.0.0.1:%d' % port, '--once'],
            capture_output=True, text=True, timeout=60)
    finally:
        stop_server(proc)

    # 1a. stage sums partition the total
    stages = stage_sums(metrics)
    total = stages.get('total', {}).get('sum', 0.0)
    parts = sum(stages.get(s, {}).get('sum', 0.0)
                for s in ('admit', 'queue', 'claim', 'dispatch',
                          'collect', 'emit'))
    if total <= 0:
        problems.append('phase1: no attributed requests '
                        '(total sum = %r)' % total)
    elif abs(parts - total) > 0.02 * total:
        problems.append('phase1: stage sums %.3f ms != total %.3f ms '
                        '(>2%% drift)' % (parts, total))
    n_mut = stages.get('total', {}).get('count', 0)
    if n_mut < N_CONNS * ROUNDS:
        problems.append('phase1: only %s attributed requests '
                        '(want >= %d)' % (n_mut, N_CONNS * ROUNDS))

    # 1b. exemplars in the trace file, with children + recorder events
    roots, children = [], []
    if os.path.exists(trace_file):
        for ln in open(trace_file):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get('name') == 'request.exemplar':
                roots.append(rec)
            elif str(rec.get('name', '')).startswith('request.stage.'):
                children.append(rec)
    if not roots:
        problems.append('phase1: no request.exemplar records in %s'
                        % trace_file)
    else:
        root = roots[-1]
        kids = [c for c in children if c['parent'] == root['span']]
        if not kids:
            problems.append('phase1: exemplar has no stage children')
        if not isinstance(root.get('events'), list):
            problems.append('phase1: exemplar carries no recorder '
                            'events')

    # 1c. the SLO surface on healthz
    slo = health.get('slo') or {}
    if 'burn' not in slo or 'classes' not in slo:
        problems.append('phase1: healthz slo section missing/short: %r'
                        % sorted(slo))
    else:
        mut = slo['classes'].get('mutate', {}).get('300s', {})
        if not mut.get('count'):
            problems.append('phase1: slo mutate window empty: %r' % mut)
    if not (health.get('recorder') or {}).get('events'):
        problems.append('phase1: healthz recorder section empty')

    # 1d. amtpu_top renders from the live listener
    if top.returncode != 0 or 'stage waterfall' not in top.stdout:
        problems.append('phase1: amtpu_top --once failed (rc %s): %s %s'
                        % (top.returncode, top.stdout[-200:],
                           top.stderr[-200:]))

    # 1e. SIGTERM left a recorder dump behind
    if not glob.glob(os.path.join(rec_dir, '*sigterm*.jsonl')):
        problems.append('phase1: no sigterm recorder dump in %s'
                        % rec_dir)

    # 1f. the capacity surface (ISSUE 15): healthz `capacity` section,
    # /debug/docs, and the amtpu_top capacity panel all render the
    # live hot-doc table
    cap = health.get('capacity') or {}
    if not (cap.get('totals') or {}).get('arena_bytes'):
        problems.append('phase1: healthz capacity section has no arena '
                        'total: %r' % sorted(cap))
    elif not (cap.get('top') or {}).get('arena'):
        problems.append('phase1: healthz capacity hot-doc table empty')
    if not debug_docs.get('hot_docs'):
        problems.append('phase1: /debug/docs served no hot docs: %r'
                        % sorted(debug_docs))
    if 'capacity:' not in top.stdout or 'hot(arena):' not in top.stdout:
        problems.append('phase1: amtpu_top frame has no capacity '
                        'panel: %s' % top.stdout[-300:])
    if not problems:
        print('obs-check: phase 1 OK (%d reqs attributed; stage sums '
              '%.1f ms ~= total %.1f ms; %d exemplars; amtpu_top + '
              'capacity panel ok; sigterm dump present)'
              % (n_mut, parts, total, len(roots)))


def check_phase2(problems):
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp(prefix='amtpu-obs2-')
    sock = os.path.join(tmp, 'gw.sock')
    rec_dir = os.path.join(tmp, 'recorder')
    proc = spawn_server(sock, {
        'AMTPU_FLUSH_DEADLINE_MS': '5',
        'AMTPU_RECORDER_DIR': rec_dir,
        # one permanent begin fault: the first apply quarantines
        'AMTPU_FAULT': 'native.begin:permanent:1.0:1',
    })
    try:
        with SidecarClient(sock_path=sock) as c:
            from automerge_tpu.errors import AutomergeError
            try:
                resp = c.apply_changes('poison', [{
                    'actor': 'px', 'seq': 1, 'deps': {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID,
                             'key': 'k', 'value': 1}]}])
                problems.append('phase2: poisoned apply answered a '
                                'normal patch: %r' % (resp,))
            except AutomergeError:
                pass                     # the quarantine envelope
            # a healthy doc still serves afterwards
            ok = c.apply_changes('healthy', [{
                'actor': 'h', 'seq': 1, 'deps': {},
                'ops': [{'action': 'set', 'obj': ROOT_ID,
                         'key': 'k', 'value': 2}]}])
            if 'clock' not in ok:
                problems.append('phase2: healthy doc result odd: %r'
                                % (ok,))
            on_demand = c.dump()
            health = c.healthz()
    finally:
        stop_server(proc)

    dumps = glob.glob(os.path.join(rec_dir, '*quarantine*.jsonl'))
    if not dumps:
        problems.append('phase2: quarantine produced no recorder dump '
                        'in %s' % rec_dir)
    else:
        events = [json.loads(ln) for ln in open(dumps[0])][1:]
        fault = [e for e in events if e.get('event') == 'fault.injected']
        if not fault:
            problems.append('phase2: quarantine dump lacks the '
                            'injected fault event: %r'
                            % [e.get('event') for e in events][-10:])
        elif 'native.begin' not in str(fault[-1].get('detail')):
            problems.append('phase2: fault event detail odd: %r'
                            % fault[-1])
        quar = [e for e in events
                if e.get('event') == 'resilience.quarantine']
        if not quar or quar[-1].get('doc') != 'poison':
            problems.append('phase2: dump lacks the quarantine event '
                            'for the poisoned doc: %r' % quar)
    if not on_demand.get('path') or not os.path.exists(on_demand['path']):
        problems.append('phase2: on-demand dump did not round-trip a '
                        'file: %r' % on_demand)
    if health.get('resilience', {}).get('quarantined', 0) < 1:
        problems.append('phase2: healthz quarantined counter is zero')
    if not problems:
        print('obs-check: phase 2 OK (quarantine dumped %d events incl.'
              ' the injected fault; on-demand dump %s; healthz sees the'
              ' quarantine)' % (len(events), on_demand['path']))


def main():
    problems = []
    check_phase1(problems)
    if not problems:
        check_phase2(problems)
    if problems:
        for p in problems:
            print('obs-check: FAIL %s' % p)
        return 1
    print('obs-check: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
