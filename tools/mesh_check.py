"""Mesh-execution gate (ISSUE 7, docs/ARCHITECTURE.md mesh section):
the first-class `MeshDocPool` must actually be a drop-in AND actually
scale, or `AMTPU_MESH` is a lie.

Two lanes, each in fresh subprocesses (the device count and the
AMTPU_MESH topology latch at first backend init):

  1. **PARITY** -- a mixed real workload (scaling text docs + map- and
     table-shaped docs) through ``make_pool()`` under ``AMTPU_MESH=4``
     on 4 virtual CPU devices: every per-doc patch byte-identical to a
     serial `NativeDocPool` replay, ``fallback.oracle == 0`` on the
     mesh path, chips actually engaged (``mesh.batches/shards``).
  2. **SCALING** -- dp=1 vs dp=4 on the MULTICHIP scaling workload,
     interleaved A/B across ``AMTPU_MESHCHECK_ROUNDS`` (3) rounds to
     cancel host drift, fresh pool per step, median-of-medians AND
     min-of-mins ratios.  Gate: dp=4 >= 1.5x dp=1 on EITHER statistic
     (min is the robust one on a shared box -- noise only ever adds
     time), retried up to ``AMTPU_MESHCHECK_TRIALS`` (3) times before
     failing.  The printed JSON
     records the physical-core ceiling: on this CPU-core-bound
     stand-in the dp axis parallelizes the HOST work (C++ decode/
     begin/emit in one GIL-released thread per chip), so the ideal
     ratio is min(dp, cores), not dp.  On a SINGLE-core host that
     ceiling is 1x -- there is nothing for dp to scale onto and the
     threading overhead makes the ratio < 1 by construction -- so the
     scaling assertion is skipped (loudly; the measured ratio still
     lands in the JSON) and parity/oracle/engagement remain the gate.

Run: JAX_PLATFORMS=cpu python tools/mesh_check.py     (make mesh-check)
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATE = 1.5
N_DOCS = int(os.environ.get('AMTPU_MESHCHECK_DOCS', '2048'))
STEPS = int(os.environ.get('AMTPU_MESHCHECK_STEPS', '5'))
ROUNDS = int(os.environ.get('AMTPU_MESHCHECK_ROUNDS', '3'))


def _scaling_workload(n_docs):
    from automerge_tpu.parallel import mesh_encode
    return mesh_encode.scaling_workload(n_docs)


def child_parity():
    import msgpack

    from automerge_tpu import telemetry
    from automerge_tpu.native import NativeDocPool, make_pool
    from automerge_tpu.native.mesh_pool import MeshDocPool
    from automerge_tpu.parallel import mesh_encode

    problems = []
    docs = _scaling_workload(64)
    for d, chs in mesh_encode.demo_map_workload(8).items():
        docs[NativeDocPool._doc_key('m-%d' % d)] = chs
    for d, chs in mesh_encode.demo_table_workload(8).items():
        docs[NativeDocPool._doc_key('tb-%d' % d)] = chs
    payload = msgpack.packb(docs, use_bin_type=True)

    pool = make_pool()
    if not isinstance(pool, MeshDocPool) or pool.dp != 4:
        problems.append('make_pool() under AMTPU_MESH=4 built %r'
                        % type(pool).__name__)
    telemetry.metrics_reset()
    got = msgpack.unpackb(pool.apply_batch_bytes(payload), raw=False,
                          strict_map_key=False)
    snap = telemetry.metrics_snapshot()
    want = msgpack.unpackb(NativeDocPool().apply_batch_bytes(payload),
                           raw=False, strict_map_key=False)
    if set(got) != set(want):
        problems.append('doc set mismatch')
    bad = [d for d in want
           if msgpack.packb(got.get(d), use_bin_type=True)
           != msgpack.packb(want[d], use_bin_type=True)]
    if bad:
        problems.append('%d docs lost byte parity vs the serial replay '
                        '(e.g. %r)' % (len(bad), bad[0]))
    if snap.get('fallback.oracle', 0) != 0:
        problems.append('fallback.oracle = %s on the mesh path'
                        % snap.get('fallback.oracle'))
    if snap.get('mesh.batches', 0) < 1 or snap.get('mesh.shards', 0) < 4:
        problems.append('mesh drive did not engage: batches=%s shards=%s'
                        % (snap.get('mesh.batches'),
                           snap.get('mesh.shards')))
    from automerge_tpu.native import live_batch_handles
    if live_batch_handles() != 0:
        problems.append('%d batch handles leaked' % live_batch_handles())
    print(json.dumps({'ok': not problems, 'problems': problems}))
    return 0 if not problems else 1


def child_measure(dp):
    import time

    import msgpack

    from automerge_tpu import telemetry
    from automerge_tpu.native import make_pool

    docs = _scaling_workload(N_DOCS)
    payload = msgpack.packb(docs, use_bin_type=True)
    total_ops = sum(len(c['ops']) for chs in docs.values() for c in chs)
    make_pool().apply_batch_bytes(payload)     # per-chip jit warmup
    telemetry.metrics_reset()
    walls = []
    for _ in range(STEPS):
        pool = make_pool()                     # fresh pool: real work
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        walls.append(time.perf_counter() - t0)
    snap = telemetry.metrics_snapshot()
    med = sorted(walls)[len(walls) // 2]
    print(json.dumps({
        'dp': dp, 'docs': N_DOCS, 'ops': total_ops,
        'med_s': round(med, 4), 'min_s': round(min(walls), 4),
        'ops_s': round(total_ops / med, 1),
        'steps': [round(w, 4) for w in walls],
        'fallback_oracle': snap.get('fallback.oracle', 0),
        'mesh': telemetry.bench_block()['mesh'],
    }))
    return 0


def _spawn(args, dp):
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO,
               AMTPU_MESH=str(dp))
    flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
                   env.get('XLA_FLAGS', ''))
    env['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_'
                        'count=%d' % dp).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0 and not proc.stdout.strip():
        raise RuntimeError('child %r failed rc=%d:\n%s'
                           % (args, proc.returncode, proc.stderr[-2000:]))
    sys.stderr.write(proc.stderr[-2000:] if proc.returncode else '')
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _scaling_trial():
    """One interleaved A/B trial; returns (ratio_med, ratio_min, rows)."""
    rows = {1: [], 4: []}
    for _ in range(ROUNDS):
        for dp in (1, 4):
            rows[dp].append(_spawn(['--child-measure', str(dp)], dp))
    mom = {dp: sorted(r['med_s'] for r in rows[dp])[ROUNDS // 2]
           for dp in rows}
    mins = {dp: min(r['min_s'] for r in rows[dp]) for dp in rows}
    return mom[1] / mom[4], mins[1] / mins[4], rows


def main():
    if '--child-parity' in sys.argv:
        return child_parity()
    if '--child-measure' in sys.argv:
        return child_measure(int(sys.argv[-1]))

    problems = []
    parity = _spawn(['--child-parity'], 4)
    if not parity.get('ok'):
        problems.extend(parity.get('problems', ['parity child failed']))

    cores = os.cpu_count() or 1
    trials = []
    # bounded retries: the box is shared and the A/B still sees
    # minute-scale drift (same deflake posture as telemetry-check's
    # median-of-trials).  One trial suffices when the assertion below
    # is vacuous anyway (single core) -- the ratio is still recorded.
    n_trials = int(os.environ.get('AMTPU_MESHCHECK_TRIALS', '3')) \
        if cores >= 2 else 1
    for _ in range(n_trials):
        ratio_med, ratio_min, rows = _scaling_trial()
        trials.append((ratio_med, ratio_min))
        if max(ratio_med, ratio_min) >= GATE:
            break
    speedup = max(ratio_med, ratio_min)
    if cores < 2:
        # nothing for the dp axis to scale onto: min(dp, cores) = 1,
        # and per-chip threading overhead makes the ratio < 1 by
        # construction.  Asserting 1.5x here would gate host
        # provisioning, not the code -- parity/oracle/engagement above
        # still gate.
        print('mesh-check: scaling gate SKIPPED (1 physical core; '
              'ceiling 1x; measured %.2fx recorded in the JSON)'
              % speedup, file=sys.stderr)
    elif speedup < GATE:
        problems.append('dp=4 speedup %.2fx (med %.2fx / min %.2fx) '
                        '< %.1fx gate' % (speedup, ratio_med, ratio_min,
                                          GATE))
    for dp in rows:
        bad = [r for r in rows[dp] if r['fallback_oracle'] != 0]
        if bad:
            problems.append('fallback.oracle != 0 in dp=%d measure' % dp)

    out = {
        'ok': not problems,
        'gate_speedup': GATE,
        'scaling_gate_skipped': cores < 2,
        'speedup_med': round(ratio_med, 3),
        'speedup_min': round(ratio_min, 3),
        'trials': [[round(a, 3), round(b, 3)] for a, b in trials],
        # the dp axis parallelizes host work: on a CPU-core-bound host
        # the ceiling is the physical core count, not dp
        'physical_cores': cores,
        'speedup_ceiling': min(4, cores),
        'dp1': rows[1][-1], 'dp4': rows[4][-1],
        'parity': parity,
        'problems': problems,
    }
    print(json.dumps(out))
    if problems:
        print('mesh-check FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        return 1
    print('mesh-check: parity ok, dp=4 %.2fx over dp=1 (gate %s, '
          'ceiling %dx on %d cores), oracle==0'
          % (speedup, 'skipped' if cores < 2 else '%.1fx' % GATE,
             min(4, cores), cores), file=sys.stderr)
    return 0


if __name__ == '__main__':
    sys.exit(main())
