"""Diff two BENCH_*.json artifacts: ops/s, collect share, phase
seconds -- the bench trajectory as a regression table instead of
hand-diffed JSON.

Accepts every artifact shape the repo emits:

  * JSON lines (`bench.py --all`, `--multichip`, `--fanout`): one
    result object per line;
  * a single result object (`bench.py --config N` > file, the
    `.bench_smoke.json` the pre-commit gate writes);
  * the round-capture wrapper (``{"cmd", "rc", "tail", "parsed"}``):
    the embedded ``parsed`` object is the line.

Lines pair by ``(config, mode)`` (falling back to ``metric``); for each
pair the table reports ops/s delta, collect-share delta (from the
embedded telemetry block when present), and the biggest per-phase
second movers.  Coldstart artifacts (``metric ==
'coldstart_restore'``, BENCH_COLDSTART_*.json) additionally pair the
ISSUE-17 economics metrics -- ``docs_per_gb`` (higher is better),
``restore_s_per_doc`` and ``peak_rss_mb`` (lower is better) -- and
report their regressions like ops/s.  Exit code: 1 when any pair
regresses past the thresholds (``--tol-ops`` fractional ops/s drop,
default 0.10, which also bounds the coldstart economics metrics;
``--tol-share`` absolute collect-share increase, default 0.10) --
unless ``--soft``, the report-only mode `make check` wires in (this
host's windows jitter far past any honest hard gate; the table is for
eyes and artifacts, the hard perf gates stay in perf-smoke/mesh-check).

Run: python tools/bench_compare.py [--soft] OLD.json NEW.json
"""

import argparse
import json
import sys


def load_lines(path):
    """[(key, line_dict)] for one artifact, any supported shape."""
    with open(path) as f:
        text = f.read()
    objs = []
    try:
        one = json.loads(text)
        if isinstance(one, dict) and 'parsed' in one \
                and isinstance(one['parsed'], dict):
            one = one['parsed']
        objs = one if isinstance(one, list) else [one]
    except ValueError:
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                objs.append(json.loads(ln))
            except ValueError:
                pass
    out = []
    for o in objs:
        if not isinstance(o, dict) or 'value' not in o:
            continue
        key = (str(o.get('config', o.get('metric', '?'))),
               str(o.get('mode', '?')))
        out.append((key, o))
    return out


def collect_share_of(line):
    tele = line.get('telemetry') or {}
    share = line.get('collect_share', tele.get('collect_share'))
    return float(share) if share is not None else None


def phases_of(line):
    tele = line.get('telemetry') or {}
    return {k: v.get('s', 0.0)
            for k, v in (tele.get('phases') or {}).items()}


def _fmt_ops(v):
    return '%.0f' % v if v is not None else '-'


def _fmt_pct(frac):
    return '%+.1f%%' % (100 * frac) if frac is not None else '-'


def _fmt_share(s):
    return '%.3f' % s if s is not None else '-'


def compare(old_path, new_path, tol_ops, tol_share, top_phases=4):
    old = dict(load_lines(old_path))
    new = dict(load_lines(new_path))
    keys = [k for k in new if k in old]
    if not keys:
        print('bench-compare: no comparable (config, mode) lines '
              'between %s and %s' % (old_path, new_path))
        return []
    print('bench-compare: %s -> %s' % (old_path, new_path))
    header = ('config/mode', 'old ops/s', 'new ops/s', 'delta',
              'share old', 'share new')
    rows = []
    regressions = []
    econ_lines = []
    for key in sorted(keys):
        ol, nl = old[key], new[key]
        ov, nv = float(ol['value']), float(nl['value'])
        delta = (nv - ov) / ov if ov else None
        oshare, nshare = collect_share_of(ol), collect_share_of(nl)
        rows.append(('%s/%s' % key, _fmt_ops(ov), _fmt_ops(nv),
                     _fmt_pct(delta), _fmt_share(oshare),
                     _fmt_share(nshare)))
        if delta is not None and delta < -tol_ops:
            regressions.append('%s/%s: ops/s %s' % (key[0], key[1],
                                                    _fmt_pct(delta)))
        if oshare is not None and nshare is not None \
                and nshare - oshare > tol_share:
            regressions.append('%s/%s: collect share %.3f -> %.3f'
                               % (key[0], key[1], oshare, nshare))
        # coldstart economics (ISSUE 17): docs_per_gb up is good,
        # restore_s_per_doc / peak_rss_mb down is good
        for field, better in (('docs_per_gb', 'higher'),
                              ('restore_s_per_doc', 'lower'),
                              ('peak_rss_mb', 'lower')):
            o, n = ol.get(field), nl.get(field)
            if o is None or n is None or not float(o):
                continue
            o, n = float(o), float(n)
            frac = (n - o) / o
            econ_lines.append('  %s/%s: %s %.6g -> %.6g (%s)'
                              % (key[0], key[1], field, o, n,
                                 _fmt_pct(frac)))
            worse = frac < -tol_ops if better == 'higher' \
                else frac > tol_ops
            if worse:
                regressions.append('%s/%s: %s %.6g -> %.6g (%s)'
                                   % (key[0], key[1], field, o, n,
                                      _fmt_pct(frac)))
    widths = [max(len(r[i]) for r in [header] + rows)
              for i in range(len(header))]
    for r in [header] + rows:
        print('  ' + '  '.join(c.rjust(w) for c, w in zip(r, widths)))
    for ln in econ_lines:
        print(ln)
    # phase movers: the per-phase seconds that moved most, per pair
    for key in sorted(keys):
        op, np_ = phases_of(old[key]), phases_of(new[key])
        moves = sorted(((np_.get(p, 0.0) - op.get(p, 0.0), p)
                        for p in set(op) | set(np_)),
                       key=lambda m: -abs(m[0]))[:top_phases]
        moves = [(d, p) for d, p in moves if abs(d) >= 1e-4]
        if moves:
            print('  phases %s/%s: %s' % (key[0], key[1], ', '.join(
                '%s %+.3fs' % (p, d) for d, p in moves)))
    return regressions


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('old')
    ap.add_argument('new')
    ap.add_argument('--soft', action='store_true',
                    help='report only; always exit 0 (the make-check '
                         'wiring)')
    ap.add_argument('--tol-ops', type=float, default=0.10,
                    help='fractional ops/s drop that counts as a '
                         'regression (default 0.10)')
    ap.add_argument('--tol-share', type=float, default=0.10,
                    help='absolute collect-share increase that counts '
                         'as a regression (default 0.10)')
    args = ap.parse_args(argv)
    regressions = compare(args.old, args.new, args.tol_ops,
                          args.tol_share)
    if regressions:
        for r in regressions:
            print('bench-compare: REGRESSION %s' % r)
        if not args.soft:
            return 1
        print('bench-compare: soft mode, reporting only')
    else:
        print('bench-compare: no regressions past tolerance')
    return 0


if __name__ == '__main__':
    sys.exit(main())
