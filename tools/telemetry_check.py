"""Disabled-path overhead gate for the telemetry layer.

The observability contract (ISSUE 1 / docs/OBSERVABILITY.md) is that an
idle telemetry layer is FREE: with tracing disabled, the instrumented
pipeline must run within noise of an un-instrumented one.  The
un-instrumented binary no longer exists, so this harness reconstructs
it in-process: every telemetry entry point the hot path touches
(trace.span/count/add/metric, telemetry.observe_batch, the always-on
counters) is monkeypatched to a bare no-op, which is the
closest executable stand-in for deleting the call sites.

Protocol: one warmup, then PAIRS interleaved (raw, disabled) runs of
the quickbench workload on fresh pools -- interleaving is the only
honest A/B on this single-core host (runs drift +-15% between windows;
see tools/quickbench.py).  MINIMA compare (the minimum of N identical
runs is the least-contended sample, the robust statistic for a shared
host); the target is ~2% overhead, the assert threshold defaults to 6%
to absorb residual jitter (AMTPU_TCHECK_TOL overrides).  The gate takes
the MEDIAN of AMTPU_TCHECK_TRIALS (default 5) independent overhead
estimates, so one unlucky scheduling window cannot fail it alone, and
accepts a clean best-trial (<= TOL/2) even when the median is over --
a real regression inflates every window, host contention does not
deflate one (ISSUE 8 deflake).  A final enabled-path pass sanity-checks
that tracing actually records (an accidentally dead telemetry layer
must not pass the overhead gate by being dead).

Run via `make telemetry-check`, or directly:
    JAX_PLATFORMS=cpu AMTPU_BENCH_DOCS=256 python tools/telemetry_check.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# small-but-real default workload (env overrides win)
os.environ.setdefault('AMTPU_BENCH_DOCS', '256')
os.environ.setdefault('AMTPU_BENCH_ORACLE_DOCS', '1')

from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu()

import msgpack  # noqa: E402

from automerge_tpu import telemetry, trace  # noqa: E402
from automerge_tpu.native import NativeDocPool, ShardedNativePool  # noqa: E402
from automerge_tpu.telemetry import attribution, capacity, recorder  # noqa: E402
from automerge_tpu.telemetry.spans import NULL_SPAN  # noqa: E402

PAIRS = int(os.environ.get('AMTPU_TCHECK_PAIRS', 5))
TOL = float(os.environ.get('AMTPU_TCHECK_TOL', 0.06))
TRIALS = int(os.environ.get('AMTPU_TCHECK_TRIALS', 5))


def _noop(*args, **kwargs):
    return None


def _null_span(*args, **kwargs):
    return NULL_SPAN


_PATCHES = [
    (trace, 'span', _null_span), (trace, 'count', _noop),
    (trace, 'add', _noop), (trace, 'metric', _noop),
    (telemetry, 'span', _null_span),
    (telemetry, 'observe_batch', _noop),
    (telemetry, 'observe_device_dispatch', _noop),
    (telemetry, 'metric', _noop),
    # the always-on recorder/attribution seams (ISSUE 12): the raw arm
    # must approximate deleting them too, so the gate prices their
    # disabled-path cost honestly
    (recorder, 'record', _noop),
    (attribution, 'note_flush_phase', _noop),
    # the always-on capacity seams (ISSUE 15): per-doc fan-out/egress
    # attribution is priced against the same bar as the recorder
    (capacity, 'note_fanout', _noop),
    (capacity, 'note_egress', _noop),
    # the wire-trace stamping seam (ISSUE 16): SidecarClient consults
    # the ambient span context on EVERY outbound request, so the raw
    # arm prices that lookup alongside the other always-on hooks
    (telemetry, 'current_trace_context', _noop),
]


class raw_mode(object):
    """Context manager approximating the un-instrumented pipeline."""

    def __enter__(self):
        self._saved = [(m, n, getattr(m, n)) for m, n, _ in _PATCHES]
        for m, n, f in _PATCHES:
            setattr(m, n, f)

    def __exit__(self, *exc):
        for m, n, f in self._saved:
            setattr(m, n, f)
        return False


def main():
    import random

    import bench
    rng = random.Random(int(os.environ.get('AMTPU_BENCH_SEED', 7)))
    config = int(os.environ.get('AMTPU_TCHECK_CONFIG', 3))
    batch, metric = bench.BUILDERS[config](rng)
    total_ops = sum(len(c['ops']) for chs in batch.values() for c in chs)
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    payload = msgpack.packb(keyed, use_bin_type=True)
    print('telemetry-check: config %d, %d docs, %d ops'
          % (config, len(batch), total_ops), file=sys.stderr)

    def make_pool():
        n = int(os.environ.get('AMTPU_BENCH_SHARDS', 0)) or \
            ShardedNativePool.default_shards()
        n = min(n, len(batch))
        return ShardedNativePool(n) if n > 1 else NativeDocPool()

    def run_once():
        pool = make_pool()
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        return time.perf_counter() - t0

    telemetry.disable()
    run_once()                      # warmup: jit compiles, allocator heat
    # median-of-TRIALS overhead estimates (each from its own interleaved
    # minima): one unlucky scheduling window can no longer fail the gate
    # on its own -- the jitter this deflakes is documented at +-15%
    # between windows on this host
    overheads = []
    for t in range(TRIALS):
        raw_times, dis_times = [], []
        for _ in range(PAIRS):
            with raw_mode():
                raw_times.append(run_once())
            dis_times.append(run_once())
        raw_best = min(raw_times)
        dis_best = min(dis_times)
        overheads.append((dis_best - raw_best) / raw_best)
        print('trial %d: raw %s | disabled %s -> %.2f%%'
              % (t, ['%.3f' % x for x in raw_times],
                 ['%.3f' % x for x in dis_times], 100 * overheads[-1]),
              file=sys.stderr)
    overhead = sorted(overheads)[len(overheads) // 2]
    print('telemetry-check: disabled-path overhead %.2f%% '
          '(median of %d trials %s; tolerance %.0f%%)'
          % (100 * overhead, TRIALS,
             ['%.1f%%' % (100 * o) for o in sorted(overheads)],
             100 * TOL))

    # enabled-path sanity: tracing must actually record when on
    telemetry.reset_all()
    telemetry.enable()
    try:
        run_once()
        snap = telemetry.phase_snapshot()
        assert snap, 'enabled tracing recorded no phases'
        assert telemetry.metrics_snapshot() is not None
        block = telemetry.bench_block()
        assert block['batch_latency'], 'no batch latency recorded'
    finally:
        telemetry.disable()
    print('telemetry-check: enabled-path sanity ok (%d phases)'
          % len(snap), file=sys.stderr)

    # Acceptance (deflaked, ISSUE 8): the gate measures the DISABLED
    # telemetry layer, whose true overhead is ~0-2% -- a failure mode is
    # "every interleaved window this run was contended", not "the layer
    # got slow".  So fail only when the median exceeds tolerance AND no
    # single trial came in clean (<= TOL/2): a real regression inflates
    # every trial including the least-contended one, while host jitter
    # cannot suppress a genuine +6% in all five windows at once.
    clean_min = min(overheads)
    if overhead > TOL and clean_min > TOL / 2:
        print('telemetry-check: FAIL -- disabled path is %.1f%% slower '
              'than the no-op pipeline (tolerance %.0f%%; best trial '
              '%.1f%%)' % (100 * overhead, 100 * TOL, 100 * clean_min))
        return 1
    if overhead > TOL:
        print('telemetry-check: PASS (median %.1f%% is over tolerance '
              'but the best trial measured %.1f%% -- host contention, '
              'not instrument cost)' % (100 * overhead, 100 * clean_min))
        return 0
    print('telemetry-check: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
