"""Fast perf-iteration harness for the host pipeline.

Runs the BASELINE config-3 workload shape through ShardedNativePool once
(after one warmup) and prints the wall time plus the AMTPU_TRACE phase
split.  Intended for tight optimize-measure loops on the HOST phases
(cxx.decode/schedule/encode/emit + python layer); run with
JAX_PLATFORMS=cpu when the TPU link is down -- host-phase timings are
device-independent.

Usage:  AMTPU_TRACE=1 [JAX_PLATFORMS=cpu] python tools/quickbench.py [n_runs]
Env:    AMTPU_BENCH_DOCS / _ACTORS / _ROUNDS / _OPS_PER_CHANGE / _SHARDS
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault('AMTPU_TRACE', '1')

from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu()

import msgpack  # noqa: E402

from automerge_tpu import trace  # noqa: E402
from automerge_tpu.native import ShardedNativePool  # noqa: E402


def env_int(name, default):
    return int(os.environ.get(name, default))


def main():
    n_runs = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    n_docs = env_int('AMTPU_BENCH_DOCS', 4096)
    n_actors = env_int('AMTPU_BENCH_ACTORS', 8)
    n_rounds = env_int('AMTPU_BENCH_ROUNDS', 2)
    opc = env_int('AMTPU_BENCH_OPS_PER_CHANGE', 16)
    n_shards = env_int('AMTPU_BENCH_SHARDS', 20)

    import random
    rng = random.Random(7)
    from automerge_tpu.parallel.mesh_encode import text_doc_changes
    t0 = time.perf_counter()
    batch = {}
    for d in range(n_docs):
        batch['text-%d' % d] = text_doc_changes(
            'text-%d' % d, n_actors, n_rounds, opc,
            lambda i, a, has: rng.random() < 0.15 and has)
    total_ops = sum(len(c['ops']) for chs in batch.values() for c in chs)
    payload = msgpack.packb(batch, use_bin_type=True)
    print('workload: %d docs, %d ops, payload %.1f MB (built in %.1fs)'
          % (n_docs, total_ops, len(payload) / 1e6,
             time.perf_counter() - t0), file=sys.stderr)

    # warmup (jit compile)
    t0 = time.perf_counter()
    ShardedNativePool(n_shards).apply_batch_bytes(payload)
    print('warmup: %.2fs' % (time.perf_counter() - t0), file=sys.stderr)

    times = []
    for run in range(n_runs):
        trace.reset()
        pool = ShardedNativePool(n_shards)
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        dt = time.perf_counter() - t0
        times.append(dt)
        print('run %d: %.3fs  (%.0f ops/s)' % (run, dt, total_ops / dt),
              file=sys.stderr)
        if run == n_runs - 1:
            # last run: steady state (run 0 carries warmup artifacts)
            print(trace.report(), file=sys.stderr)
    med = sorted(times)[len(times) // 2]
    print('median: %.3fs  %.0f ops/s' % (med, total_ops / med))


if __name__ == '__main__':
    main()
