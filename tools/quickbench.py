"""Fast perf-iteration harness for the host pipeline.

Builds ONE BASELINE-config workload (default: config 3, the headline
shape), then loops fresh-pool `apply_batch_bytes` runs and prints wall
times + the phase split.  Intended for tight optimize-measure loops on
the HOST phases; run with JAX_PLATFORMS=cpu when the TPU link is down --
host-phase timings are device-independent.

The single-core host jitters +-15% between windows: for honest A/B
comparisons interleave runs of both binaries (swap the built .so), or
compare the thread-CPU cxx.* spans (tracing on), which are immune
to wall-clock contention.

Tracing is toggled at RUNTIME (telemetry.enable(); no more AMTPU_TRACE
env mutation before import); --no-trace measures the production
disabled path.  The final stdout line is BENCH JSON embedding
`telemetry.bench_block()` (fallback rates, device seconds, batch
histograms).  `make telemetry-check` gates the disabled-path overhead
of the same workload (tools/telemetry_check.py).

Usage:  [JAX_PLATFORMS=cpu] python tools/quickbench.py \
            [--config N] [--runs K] [--no-trace]
Env:    the same AMTPU_BENCH_* knobs bench.py reads.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu()

import msgpack  # noqa: E402

from automerge_tpu import telemetry  # noqa: E402
from automerge_tpu.native import NativeDocPool, ShardedNativePool  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--config', type=int, default=3, choices=[1, 2, 3, 4])
    ap.add_argument('--runs', type=int, default=5)
    ap.add_argument('--no-trace', action='store_true',
                    help='leave span tracing disabled (measures the '
                         'production path; always-on counters still '
                         'accumulate)')
    ap.add_argument('--phases', type=int, nargs='?', const=10, default=0,
                    metavar='N',
                    help='print a top-N phase table (seconds + share of '
                         'native batch time) from the embedded telemetry '
                         'block -- collect regressions readable without '
                         'jq (default N=10; implies tracing)')
    args = ap.parse_args()
    if args.runs < 1:
        ap.error('--runs must be >= 1')
    if args.phases and args.no_trace:
        ap.error('--phases needs tracing; drop --no-trace')
    if not args.no_trace:
        telemetry.enable()

    import random

    import bench
    rng = random.Random(int(os.environ.get('AMTPU_BENCH_SEED', 7)))
    t0 = time.perf_counter()
    batch, metric = bench.BUILDERS[args.config](rng)
    total_ops = sum(len(c['ops']) for chs in batch.values() for c in chs)
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    payload = msgpack.packb(keyed, use_bin_type=True)
    print('config %d (%s): %d docs, %d ops, payload %.1f MB (built %.1fs)'
          % (args.config, metric, len(batch), total_ops,
             len(payload) / 1e6, time.perf_counter() - t0),
          file=sys.stderr)

    def make_pool():
        n = int(os.environ.get('AMTPU_BENCH_SHARDS', 0)) or \
            ShardedNativePool.default_shards()
        n = min(n, len(batch))
        return ShardedNativePool(n) if n > 1 else NativeDocPool()

    t0 = time.perf_counter()
    make_pool().apply_batch_bytes(payload)
    print('warmup: %.2fs' % (time.perf_counter() - t0), file=sys.stderr)

    # ONE measurement window for the whole embed: warmup's compiles are
    # excluded, then histograms, counters, AND phases all cover exactly
    # the timed runs (mixed windows would skew any phase-per-batch math)
    telemetry.reset_all()
    times = []
    for _ in range(args.runs):
        pool = make_pool()
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        times.append(time.perf_counter() - t0)
    med = sorted(times)[len(times) // 2]
    print('runs: %s -> best %.0f ops/s, median %.0f ops/s'
          % (['%.3f' % t for t in times], total_ops / min(times),
             total_ops / med), file=sys.stderr)
    if telemetry.enabled():
        print(telemetry.phase_report(), file=sys.stderr)
    block = telemetry.bench_block()
    if args.phases:
        print(phase_table(block, args.phases), file=sys.stderr)
    print(json.dumps({'metric': 'quickbench_%s' % metric,
                      'value': round(total_ops / med, 1),
                      'unit': 'ops/sec', 'config': args.config,
                      'telemetry': block}))


def phase_table(block, top_n):
    """Top-N phase table from a bench_block: seconds + share of the
    summed per-shard native batch time (shares can exceed 100% only if
    a span double-counts; collect share is THE regression gauge --
    ISSUE 3 tracks it below 50%).  Note: with async dispatch,
    device.collect includes the kernel compute it blocks on."""
    phases = block.get('phases') or {}
    lat = block.get('batch_latency', {})
    # pipeline mode drives _phase_a/b directly, so only the whole-batch
    # 'sharded' series exists -- fall back to it for the share basis
    native_s = (lat.get('native', {}).get('sum', 0.0)
                or lat.get('sharded', {}).get('sum', 0.0))
    rows = sorted(((v['s'], v['n'], k) for k, v in phases.items()
                   if v['s'] > 0), reverse=True)[:top_n]
    if not rows:
        return 'phase table: no phase occupancy recorded'
    width = max(len(k) for _s, _n, k in rows)
    out = ['top %d phases (of %.2fs native batch time):'
           % (len(rows), native_s)]
    for s, n, k in rows:
        share = (' %5.1f%%' % (100.0 * s / native_s)) if native_s else ''
        out.append('  %-*s %8.3fs%s  x%d' % (width, k, s, share, n))
    return '\n'.join(out)


if __name__ == '__main__':
    main()
