"""Shared subprocess plumbing for the fleet CI gates (route-check,
failover-check): replica spawn-and-wait, one process-tree teardown
ladder, and the loud single-core skip convention for timing gates.

Every gate that SIGKILLs or respawns replica servers must tear the
whole tree down through `stop_server`/`stop_all` -- an orphaned
replica holding its unix socket makes the NEXT arm flaky in a way
that only reproduces on loaded CI machines.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_server(path, extra_env=None, deadline_s=60):
    """Spawns one replica server subprocess on `path` and waits for
    its socket to appear (or raises, reaping the child)."""
    if os.path.exists(path):
        os.unlink(path)           # a stale socket from a killed proc
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path], env=env, cwd=REPO)
    deadline = time.time() + deadline_s
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            stop_server(proc)
            raise RuntimeError('replica server did not come up '
                               '(rc=%s)' % proc.returncode)
        time.sleep(0.05)
    return proc


def stop_server(proc):
    """terminate -> wait -> kill -> wait: the one teardown ladder.
    Safe on already-dead processes."""
    if proc is None or proc.poll() is not None:
        return
    try:
        proc.terminate()
    except OSError:
        pass
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        try:
            proc.kill()
        except OSError:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


def stop_all(procs):
    """Tears down every process in a dict/list, best-effort, never
    raising -- gates call this from `finally`."""
    vals = procs.values() if hasattr(procs, 'values') else procs
    for proc in list(vals):
        try:
            stop_server(proc)
        except Exception:
            pass


def single_core_skip(check, gate_desc, cores=None):
    """True (and prints the loud skip line, mesh-check precedent) when
    the machine has one core: timing gates assert nothing there, but
    the measured numbers still land in the JSON artifact."""
    cores = cores if cores is not None else (os.cpu_count() or 1)
    if cores >= 2:
        return False
    print('%s: %s gate SKIPPED (1 physical core; measured values '
          'recorded in the JSON)' % (check, gate_desc),
          file=sys.stderr)
    return True
