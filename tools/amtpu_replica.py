#!/usr/bin/env python
"""Run a materialized read replica (ISSUE 20; docs/SERVING.md).

    python tools/amtpu_replica.py \
        --upstream /run/amtpu/gw.sock --listen /run/amtpu/read0.sock \
        --store /var/lib/amtpu/cold --prefix doc/

Consumes the upstream gateway's fan-out stream into a local pool and
serves reads (`get_patch`, `snapshot`, `healthz`, ...) on `--listen`
as a read-only gateway; mutations answer a typed ``ReadOnly`` error.
With `--store` the pool bootstraps arena-direct from the ColdStore
manifest before subscribing, so upstream only backfills the tail.

Staleness SLO: every `AMTPU_READ_RESYNC_S` the replica probes the
upstream frontier per doc; a doc behind for longer than
`AMTPU_READ_STALENESS_SLO_S` is force-caught-up via one
``get_missing_changes`` walk.  `--status-interval N` prints the
healthz ``readview`` section as a JSON line every N seconds.
"""

import argparse
import json
import os
import signal
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None):
    from automerge_tpu.readview.replica import ReadReplica
    ap = argparse.ArgumentParser(
        description='materialized read replica over one gateway')
    ap.add_argument('--upstream', required=True,
                    help='authoritative gateway unix socket path')
    ap.add_argument('--listen', required=True,
                    help='unix socket path this replica serves reads on')
    ap.add_argument('--doc', action='append', default=[],
                    help='doc id to follow (repeatable)')
    ap.add_argument('--prefix',
                    help='follow every doc under this id prefix')
    ap.add_argument('--store',
                    help='ColdStore root to bootstrap the pool from')
    ap.add_argument('--peer', default='replica',
                    help='peer name for the upstream subscription')
    ap.add_argument('--msgpack', action='store_true',
                    help='msgpack framing on both sockets')
    ap.add_argument('--status-interval', type=float, default=0.0,
                    help='print the readview healthz section as JSON '
                         'every N seconds (0: quiet)')
    args = ap.parse_args(argv)
    if not args.doc and args.prefix is None and not args.store:
        ap.error('nothing to follow: pass --doc/--prefix/--store')
    replica = ReadReplica(args.upstream, args.listen, docs=args.doc,
                          prefix=args.prefix, store_dir=args.store,
                          peer=args.peer, use_msgpack=args.msgpack)
    replica.start()
    print('replica: serving reads on %s (upstream %s)'
          % (args.listen, args.upstream), file=sys.stderr)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        last = time.monotonic()
        while not stop:
            time.sleep(0.2)
            if args.status_interval and \
                    time.monotonic() - last >= args.status_interval:
                last = time.monotonic()
                print(json.dumps({'readview':
                                  replica.healthz_section()}))
                sys.stdout.flush()
    finally:
        replica.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
