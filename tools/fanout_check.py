"""Batched-sync-fan-out gate (ISSUE 9, docs/SERVING.md fan-out
section): the encode-once coalesced path must actually reuse its
encoding, deliver byte-identical change streams to a serial
per-`Connection` replay, meet the change->fanout p99 SLO on the smoke
shape, and never push the pool off the kernel path.

One REAL gateway server subprocess on a unix socket:

  1. **encode-once + parity** -- 1 popular doc x 200 subscribers (8
     connections x 25 multiplexed peers, all empty clocks) + a
     subscribed writer.  Each of ``ROUNDS`` writer mutations must fan
     out to every subscriber; gates:
       * ``sync.fanout.encode_reuse >= 199`` (N subscribers -> >= N-1
         reuses of one encoding);
       * every subscriber's concatenated received-change stream
         byte-identical (canonical JSON) to the serial per-Connection
         replay of the same traffic, including a STRAGGLER that joins
         mid-run at a stale clock with no backfill;
       * the writer's own connection receives no echo frame.
  2. **SLO** -- ``amtpu_fanout_latency_ms`` p50 under 150 ms and p99
     under the gate (``AMTPU_SMOKE_FANOUT_P99_MS``, default 750 ms --
     deliberately padded: this check runs 10 processes on a 2-core CI
     stand-in, so the tail is scheduler jitter, not fan-out cost; the
     BENCH_FANOUT artifact records the real distribution).
  3. **kernel-path hygiene** -- ``fallback.oracle == 0`` after the run.

Run: JAX_PLATFORMS=cpu python tools/fanout_check.py   (make fanout-check)
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CONNS = 8
PEERS_PER_CONN = 25
N_SUBS = N_CONNS * PEERS_PER_CONN
ROUNDS = 6
STRAGGLER_JOIN_ROUND = 3      # joins after this round, at round-1 clock
ROOT_ID = '00000000-0000-0000-0000-000000000000'
DOC = 'hot-doc'


def change(seq):
    return {'actor': 'writer', 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': 'k%d' % (seq % 3), 'value': seq}]}


def spawn_server(path, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path], env=env, cwd=REPO)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('gateway server did not come up')
        time.sleep(0.05)
    return proc


def stop_server(proc):
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


def canon(changes):
    return json.dumps(changes, sort_keys=True)


def serial_replay():
    """The same traffic through per-peer Connections over a DocSet --
    the reference's scalar shape, computed in-process."""
    from automerge_tpu.sync.connection import Connection
    from automerge_tpu.sync.doc_set import DocSet
    ds = DocSet()
    msgs = []
    conn = Connection(ds, msgs.append)
    conn.open()
    conn.receive_msg({'docId': DOC, 'clock': {}})
    straggler_msgs = []
    for r in range(1, ROUNDS + 1):
        ds.apply_changes(DOC, [change(r)])
        if r == STRAGGLER_JOIN_ROUND:
            sconn = Connection(ds, straggler_msgs.append)
            sconn.open()
            sconn.receive_msg({'docId': DOC, 'clock': {'writer': 1}})
    sub_stream = [c for m in msgs if m.get('changes')
                  for c in m['changes']]
    straggler_stream = [c for m in straggler_msgs if m.get('changes')
                        for c in m['changes']]
    return sub_stream, straggler_stream


def drain_changes(client, want, timeout=120):
    got = []
    deadline = time.time() + timeout
    while len(got) < want:
        e = client.next_event(timeout=max(0.1, deadline - time.time()))
        if e is None:
            break
        if e.get('event') == 'change':
            got.append(e)
    return got


def main():
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.utils.common import env_float
    p99_gate = env_float('AMTPU_SMOKE_FANOUT_P99_MS', 750.0)
    p50_gate = env_float('AMTPU_SMOKE_FANOUT_P50_MS', 150.0)
    path = os.path.join(tempfile.mkdtemp(), 'gw-fanout.sock')
    proc = spawn_server(path, {'AMTPU_FLUSH_DEADLINE_MS': '5'})
    subs, errors = [], []
    try:
        # 200 subscribers across 8 connections, in parallel
        def connect(i):
            try:
                c = SidecarClient(sock_path=path)
                for p in range(PEERS_PER_CONN):
                    r = c.subscribe(DOC, peer='c%d-p%02d' % (i, p))
                    assert r['clock'] == {} and r['changes'] == [], r
                subs.append(c)
            except Exception as e:
                errors.append('conn %d: %s: %s'
                              % (i, type(e).__name__, e))
        threads = [threading.Thread(target=connect, args=(i,))
                   for i in range(N_CONNS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(subs) == N_CONNS

        writer = SidecarClient(sock_path=path)
        writer.subscribe(DOC, peer='writer')
        straggler = None
        for r in range(1, ROUNDS + 1):
            writer.apply_changes(DOC, [change(r)])
            if r == STRAGGLER_JOIN_ROUND:
                # a peer joins mid-run at a stale clock WITHOUT a
                # backfill: the next flush must serve its gap through
                # the per-peer straggler filter
                straggler = SidecarClient(sock_path=path)
                sr = straggler.subscribe(DOC, clock={'writer': 1},
                                         peer='straggler',
                                         backfill=False)
                assert sr['changes'] == [], sr

        exp_stream, exp_straggler = serial_replay()

        # every subscriber connection: 25 identical frames per flush;
        # collapse consecutive duplicates into flush units and compare
        # the concatenated per-peer change stream
        for i, c in enumerate(subs):
            frames = drain_changes(c, PEERS_PER_CONN * ROUNDS)
            assert len(frames) == PEERS_PER_CONN * ROUNDS, \
                'conn %d got %d/%d change frames' \
                % (i, len(frames), PEERS_PER_CONN * ROUNDS)
            per_peer = {}
            for f in frames:
                per_peer.setdefault(canon(f['clock']), f)
            stream = [ch for key in sorted(
                per_peer, key=lambda k: json.loads(k).get('writer', 0))
                for ch in per_peer[key]['changes']]
            assert canon(stream) == canon(exp_stream), \
                'conn %d change stream diverged from serial replay' % i
        print('fanout-check: parity OK (%d subscribers x %d rounds '
              'byte-identical to serial per-Connection replay)'
              % (N_SUBS, ROUNDS))

        s_frames = drain_changes(straggler,
                                 ROUNDS - STRAGGLER_JOIN_ROUND)
        s_stream = [ch for f in s_frames for ch in f['changes']]
        assert canon(s_stream) == canon(exp_straggler), \
            'straggler stream diverged from serial replay'
        print('fanout-check: straggler OK (filtered delta == serial '
              'replay backfill+deltas, %d changes)' % len(s_stream))

        # the writer connection must never see its own change echoed
        echo = writer.next_event(timeout=1.0)
        while echo is not None and echo.get('event') != 'change':
            echo = writer.next_event(timeout=1.0)
        assert echo is None, 'writer received echo frame: %r' % echo

        h = writer.healthz()
        fan = h['fanout']
        reuse = fan.get('encode_reuse', 0)
        assert reuse >= (N_SUBS - 1), \
            'encode_reuse %.0f < %d: the coalesced path is not ' \
            'reusing its encoding' % (reuse, N_SUBS - 1)
        lat = fan['latency_ms']
        assert lat.get('count', 0) >= N_SUBS, lat
        assert lat['p50'] < p50_gate, \
            'change->fanout p50 %.1fms over the %.0fms gate (%r)' \
            % (lat['p50'], p50_gate, lat)
        assert lat['p99'] < p99_gate, \
            'change->fanout p99 %.1fms over the %.0fms gate (%r)' \
            % (lat['p99'], p99_gate, lat)
        assert h['scheduler']['fallback_oracle'] == 0, h['scheduler']
        amp = fan.get('bytes_on_wire', 0) / max(
            1.0, fan.get('bytes_encoded', 0))
        print('fanout-check: encode-once OK (reuse=%d >= %d; '
              'amplification %.1fx)' % (reuse, N_SUBS - 1, amp))
        print('fanout-check: SLO OK (change->fanout p50 %.1fms / p99 '
              '%.1fms < %.0fms; oracle=0)'
              % (lat['p50'], lat['p99'], p99_gate))
        for c in subs + [writer, straggler]:
            c.close()
    finally:
        stop_server(proc)
    print('FANOUT-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
