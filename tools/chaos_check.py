"""CI gate: the resilience layer must actually isolate injected faults
(ISSUE 4).

Three lanes, each asserting the acceptance contract end to end:

  1. TRANSIENT -- ``AMTPU_FAULT=device.dispatch:transient:1.0:2`` (two
     forced transient faults) on a config-3 batch: the result bytes must
     be IDENTICAL to the fault-free run and ``resilience.retry.success``
     >= 1.
  2. PERMANENT -- a permanent fault pinned to one doc: exactly that doc
     quarantined (per-doc error envelope), every healthy doc's patch
     byte-identical to the fault-free run.
  3. SIDECAR -- SIGKILL the server mid-session: the client respawns,
     replays its checkpoint WAL, a subsequent get_patch matches the
     uninterrupted session, healthz reports the restart count, and the
     process tree is clean after close().

Wired into ``make check`` as ``make chaos-check``.

Usage: [JAX_PLATFORMS=cpu] python tools/chaos_check.py
"""
import os
import random
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
# the kernel path is the subject (device sites are unreachable on the
# full host path), and the smoke stays small
os.environ['AMTPU_HOST_FULL'] = '0'
os.environ['AMTPU_HOST_REG'] = '0'
os.environ.setdefault('AMTPU_BENCH_DOCS', '48')
os.environ.setdefault('AMTPU_BENCH_ACTORS', '4')

from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu()

import msgpack  # noqa: E402

from automerge_tpu import faults, resilience, telemetry  # noqa: E402
from automerge_tpu.native import NativeDocPool, make_pool  # noqa: E402

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def _per_doc(raw):
    """{doc: packed patch bytes} -- the chaos lanes compare per doc so
    they hold for ANY configured pool (the mesh pool's shard merge is
    doc-order-free; byte identity is per-doc, exactly what clients
    see)."""
    out = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    return {d: msgpack.packb(p, use_bin_type=True) for d, p in out.items()}


def _config3_payload():
    import bench
    rng = random.Random(int(os.environ.get('AMTPU_BENCH_SEED', 7)))
    batch, _metric = bench.BUILDERS[3](rng)
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    return msgpack.packb(keyed, use_bin_type=True), list(keyed)


def lane_transient(payload, want, problems):
    telemetry.metrics_reset()
    faults.reset('device.dispatch:transient:1.0:2')   # the env syntax
    got = _per_doc(make_pool().apply_batch_bytes_resilient(payload))
    faults.disarm()
    snap = telemetry.metrics_snapshot()
    if got != want:
        problems.append('transient lane: result bytes differ from the '
                        'fault-free run')
    if snap.get('resilience.retry.success', 0) < 1:
        problems.append('transient lane: resilience.retry.success = %s '
                        '(want >= 1)'
                        % snap.get('resilience.retry.success'))
    if snap.get('resilience.fault_injected', 0) != 2:
        problems.append('transient lane: %s faults fired (want 2)'
                        % snap.get('resilience.fault_injected'))
    return snap


def lane_permanent(payload, want, doc_keys, problems):
    poison = doc_keys[len(doc_keys) // 2]
    telemetry.metrics_reset()
    faults.arm('device.dispatch', 'permanent', 1.0, match=poison)
    got_raw = msgpack.unpackb(
        make_pool().apply_batch_bytes_resilient(payload),
        raw=False, strict_map_key=False)
    faults.disarm()
    snap = telemetry.metrics_snapshot()
    quarantined = [d for d in got_raw
                   if resilience.is_quarantined(got_raw[d])]
    if quarantined != [poison]:
        problems.append('permanent lane: quarantined %r (want exactly '
                        '[%r])' % (quarantined, poison))
    if snap.get('resilience.quarantined', 0) != 1:
        problems.append('permanent lane: resilience.quarantined = %s '
                        '(want 1)' % snap.get('resilience.quarantined'))
    bad = [d for d in want if d != poison and
           msgpack.packb(got_raw[d], use_bin_type=True) != want[d]]
    if bad:
        problems.append('permanent lane: %d healthy docs lost parity '
                        '(e.g. %r)' % (len(bad), bad[0]))
    return snap


def lane_sidecar(problems):
    from automerge_tpu.sidecar.client import SidecarClient
    chs = [
        {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
             'value': 'magpie'}]},
        {'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'fish',
             'value': 'trout'}]},
    ]
    with SidecarClient() as ref:
        for ch in chs:
            ref.apply_changes('doc', [ch])
        want = ref.get_patch('doc')
    c = SidecarClient()
    try:
        for ch in chs:
            c.apply_changes('doc', [ch])
        os.kill(c._proc.pid, signal.SIGKILL)
        time.sleep(0.2)
        got = c.get_patch('doc')
        if got != want:
            problems.append('sidecar lane: post-respawn get_patch '
                            'differs from the uninterrupted session')
        hz = c.healthz()
        if hz.get('restarts') != 1:
            problems.append('sidecar lane: healthz restarts = %s '
                            '(want 1)' % hz.get('restarts'))
    finally:
        c.close()
    if c._proc is not None and c._proc.returncode is None:
        problems.append('sidecar lane: server process leaked past '
                        'close() (pid %d)' % c._proc.pid)
    return c.restarts


def main():
    problems = []
    payload, doc_keys = _config3_payload()
    faults.disarm()
    # fault-free reference from the plain serial pool: the configured
    # pool (AMTPU_MESH included) must reproduce it per doc under faults
    want = _per_doc(NativeDocPool().apply_batch_bytes(payload))

    t_snap = lane_transient(payload, want, problems)
    p_snap = lane_permanent(payload, want, doc_keys, problems)
    restarts = lane_sidecar(problems)

    if problems:
        print('chaos-check FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        return 1
    print('chaos-check: transient retried to parity '
          '(retry.success=%d), poison doc quarantined alone '
          '(bisect.rounds=%d), sidecar respawn+replay OK (restarts=%d), '
          'process tree clean'
          % (t_snap.get('resilience.retry.success', 0),
             p_snap.get('resilience.bisect.rounds', 0), restarts))
    return 0


if __name__ == '__main__':
    sys.exit(main())
