"""Read-path gate (ISSUE 20, docs/SERVING.md read path): patch-mode
fan-out must actually be cheaper for thin clients than shipping change
bytes, a materialized read replica must stay inside its staleness SLO
under sustained writer churn and close a forced gap via resync, and a
snapshot cold-open must be byte-identical to a full history replay.

One REAL gateway server subprocess on a unix socket:

  1. **patch-vs-change A/B** -- one popular doc, ``ROUNDS`` writer
     flushes, one change-mode and one patch-mode subscriber draining
     the same traffic.  Per frame, the change-mode thin client pays
     the FULL backend (`Backend.apply_changes` + `apply_patch`) while
     the patch-mode client only applies the server-computed patch;
     gates:
       * both clients' materialized end states byte-identical to the
         server's serial ``get_patch`` oracle;
       * patch-mode cumulative apply CPU strictly below change-mode
         (the whole point of server-side patch shipping);
       * wire bytes for both lanes recorded in the artifact (patch
         frames carry materialized state, so bytes can go either way
         -- the CPU win is the gate, the bytes are the honest cost).
  2. **replica staleness SLO** -- a `ReadReplica` follows the popular
     doc through churn (two phases, ``CHURN_ROUNDS`` flushes each);
     mid-run, a FORCED GAP: the writer grows a doc the replica never
     subscribed to, and ``resync_doc`` must fetch exactly that many
     changes and land byte-identical to the upstream ``get_patch``.
     After churn the replica must drain to zero lag inside
     ``AMTPU_SMOKE_READPATH_DRAIN_S`` (default 30 s) and every sampled
     staleness reading is recorded; reads served during churn come
     from the replica's own listener (read-only: a write must be
     refused).
  3. **snapshot cold-open** -- the gateway serves the churned doc's v2
     container; loading it into a fresh pool must be byte-identical
     (``save`` round-trip) to replaying the full change history, and
     a second fetch at the same frontier must hit the cache.
  4. **kernel-path hygiene** -- ``fallback.oracle == 0`` at the end.

Writes BENCH_READPATH_r20.json.

Run: JAX_PLATFORMS=cpu python tools/readpath_check.py  (make readpath-check)
"""

import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from proc_util import spawn_server, stop_server  # noqa: E402

ROUNDS = 20                   # arm 1 flushes
OPS_PER_CHANGE = 6
CHURN_ROUNDS = 15             # arm 2 flushes per phase
GAP_CHANGES = 5               # forced-gap size
ROOT_ID = '00000000-0000-0000-0000-000000000000'
DOC = 'popular-doc'
GAP_DOC = 'gap-doc'
ARTIFACT = os.path.join(REPO, 'BENCH_READPATH_r20.json')


def change(doc, seq, actor='writer'):
    return {'actor': actor, 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': '%s-k%d' % (doc, (seq * 7 + i) % 9),
                     'value': 'v%d.%d' % (seq, i)}
                    for i in range(OPS_PER_CHANGE)]}


def canon(obj):
    return json.dumps(obj, sort_keys=True, default=str)


def wire_len(frame):
    """The frame's JSON-lines wire size (what the gateway encoder
    ships) -- measured client-side off the decoded dict."""
    return len((json.dumps(dict(frame)) + '\n').encode())


def drain(client, kind, want, apply_fn, timeout=120):
    """Drains `want` frames of `kind`, timing ONLY the apply_fn calls
    (the thin client's CPU) and summing wire bytes."""
    got, cpu_s, wire_b = 0, 0.0, 0
    deadline = time.time() + timeout
    while got < want:
        ev = client.next_event(timeout=max(0.1, deadline - time.time()))
        if ev is None:
            break
        if ev.get('event') != kind:
            continue
        wire_b += wire_len(ev)
        t0 = time.perf_counter()
        apply_fn(ev)
        cpu_s += time.perf_counter() - t0
        got += 1
    assert got == want, '%s-mode client got %d/%d frames' \
        % (kind, got, want)
    return cpu_s, wire_b


def arm_patch_vs_change(path, bench):
    import automerge_tpu.backend as Backend
    import automerge_tpu.frontend as Frontend
    from automerge_tpu.frontend import apply_patch
    from automerge_tpu.sidecar.client import SidecarClient

    writer = SidecarClient(sock_path=path)
    fat = SidecarClient(sock_path=path)
    thin = SidecarClient(sock_path=path)
    fat.subscribe(DOC, peer='fat')
    thin_sub = thin.subscribe(DOC, peer='thin', mode='patch')
    assert thin_sub['patch'] is None and thin_sub['clock'] == {}

    for seq in range(1, ROUNDS + 1):
        writer.apply_changes(DOC, [change(DOC, seq)])

    # the change-mode thin client: a FULL backend per peer
    fat_state = {'backend': Backend.init(),
                 'doc': Frontend.init({'actorId': 'fat'})}

    def fat_apply(ev):
        fat_state['backend'], patch = Backend.apply_changes(
            fat_state['backend'], ev['changes'])
        fat_state['doc'] = apply_patch(fat_state['doc'], patch)

    thin_state = {'doc': Frontend.init({'actorId': 'thin'})}

    def thin_apply(ev):
        base = Frontend.init({'actorId': 'thin'}) if ev.get('full') \
            else thin_state['doc']
        thin_state['doc'] = apply_patch(base, ev['patch'])

    fat_cpu, fat_wire = drain(fat, 'change', ROUNDS, fat_apply)
    thin_cpu, thin_wire = drain(thin, 'patch', ROUNDS, thin_apply)

    oracle = writer.get_patch(DOC)
    oracle_doc = apply_patch(Frontend.init({'actorId': 'o'}), oracle)
    assert canon(dict(fat_state['doc'])) == canon(dict(oracle_doc)), \
        'change-mode end state diverged from the get_patch oracle'
    assert canon(dict(thin_state['doc'])) == canon(dict(oracle_doc)), \
        'patch-mode end state diverged from the get_patch oracle'

    bench['ab_rounds'] = ROUNDS
    bench['ab_change_apply_cpu_ms'] = round(fat_cpu * 1000, 3)
    bench['ab_patch_apply_cpu_ms'] = round(thin_cpu * 1000, 3)
    bench['ab_change_wire_bytes'] = fat_wire
    bench['ab_patch_wire_bytes'] = thin_wire
    bench['ab_cpu_ratio'] = round(fat_cpu / max(thin_cpu, 1e-9), 2)
    assert thin_cpu < fat_cpu, \
        'patch mode did not win on thin-client CPU: patch %.2fms vs ' \
        'change %.2fms' % (thin_cpu * 1000, fat_cpu * 1000)
    for c in (writer, fat, thin):
        c.close()
    print('readpath-check: A/B OK (thin-client apply CPU %.2fms patch '
          'vs %.2fms change = %.1fx win; wire %dB patch vs %dB change; '
          'both end states == get_patch oracle)'
          % (thin_cpu * 1000, fat_cpu * 1000, bench['ab_cpu_ratio'],
             thin_wire, fat_wire))


def arm_replica_slo(path, bench):
    from automerge_tpu.errors import AutomergeError
    from automerge_tpu.readview.replica import ReadReplica
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.utils.common import env_float
    drain_s = env_float('AMTPU_SMOKE_READPATH_DRAIN_S', 30.0)

    writer = SidecarClient(sock_path=path)
    rd_path = os.path.join(tempfile.mkdtemp(), 'replica.sock')
    rep = ReadReplica(path, rd_path, docs=[DOC],
                      probe_s=0.2, slo_s=30.0).start()
    reader = SidecarClient(sock_path=rd_path)
    samples, reads = [], 0
    stop = threading.Event()

    def sample_loop():
        while not stop.is_set():
            st = rep.staleness().get(DOC)
            if st is not None:
                samples.append(st)
            time.sleep(0.05)

    sampler = threading.Thread(target=sample_loop, daemon=True)
    sampler.start()
    base = ROUNDS
    try:
        # churn phase 1: the replica tails the live stream
        for seq in range(base + 1, base + CHURN_ROUNDS + 1):
            writer.apply_changes(DOC, [change(DOC, seq)])
            reader.get_patch(DOC)        # replica serves DURING churn
            reads += 1
        # forced gap: a doc the replica never subscribed to grows
        for seq in range(1, GAP_CHANGES + 1):
            writer.apply_changes(GAP_DOC, [change(GAP_DOC, seq)])
        n = rep.resync_doc(GAP_DOC)
        assert n == GAP_CHANGES, \
            'resync fetched %d changes, wanted %d' % (n, GAP_CHANGES)
        assert canon(reader.get_patch(GAP_DOC)) == \
            canon(writer.get_patch(GAP_DOC)), \
            'post-resync replica state diverged from upstream'
        # churn phase 2: the stream keeps flowing after the resync
        for seq in range(base + CHURN_ROUNDS + 1,
                         base + 2 * CHURN_ROUNDS + 1):
            writer.apply_changes(DOC, [change(DOC, seq)])
            reader.get_patch(DOC)
            reads += 1
        # a replica is read-only: the write lane must refuse
        refused = False
        try:
            reader.apply_changes(DOC, [change(DOC, 999, actor='evil')])
        except AutomergeError:
            refused = True
        assert refused, 'replica accepted a write'
        # drain: believed must reach auth inside the budget
        target = writer.get_clock(DOC)['clock']
        deadline = time.time() + drain_s
        t0 = time.time()
        while time.time() < deadline:
            if reader.get_patch(DOC)['clock'] == target:
                break
            time.sleep(0.05)
        drained_ms = (time.time() - t0) * 1000
        assert reader.get_patch(DOC)['clock'] == target, \
            'replica did not drain to the upstream frontier in %.0fs' \
            % drain_s
        assert canon(reader.get_patch(DOC)) == \
            canon(writer.get_patch(DOC))
    finally:
        stop.set()
        sampler.join(timeout=5)
        reader.close()
        writer.close()
        rep.stop()
    max_lag = max([s['lag'] for s in samples] or [0])
    max_stale = max([s['stale_s'] for s in samples] or [0.0])
    bench['replica_churn_flushes'] = 2 * CHURN_ROUNDS
    bench['replica_reads_during_churn'] = reads
    bench['replica_staleness_samples'] = len(samples)
    bench['replica_max_lag_changes'] = max_lag
    bench['replica_max_stale_s'] = round(max_stale, 3)
    bench['replica_drain_ms'] = round(drained_ms, 1)
    bench['replica_resync_changes'] = GAP_CHANGES
    assert max_stale < drain_s, \
        'measured staleness %.1fs blew the %.0fs budget' \
        % (max_stale, drain_s)
    print('readpath-check: replica OK (%d reads served during %d '
          'churn flushes; max lag %d changes / %.2fs stale; forced '
          'gap of %d closed via resync; drained to the upstream '
          'frontier in %.0fms; write refused)'
          % (reads, 2 * CHURN_ROUNDS, max_lag, max_stale,
             GAP_CHANGES, drained_ms))


def arm_snapshot_cold_open(path, bench):
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.sidecar.client import SidecarClient

    client = SidecarClient(sock_path=path)
    snap = client.snapshot(DOC)
    t0 = time.perf_counter()
    cold = NativeDocPool()
    cold.load(DOC, snap.data)
    cold_ms = (time.perf_counter() - t0) * 1000

    # the oracle: replay the FULL change history into a fresh pool
    history = client.get_missing_changes(DOC, {})
    t0 = time.perf_counter()
    replayed = NativeDocPool()
    replayed.apply_changes(DOC, history)
    replay_ms = (time.perf_counter() - t0) * 1000

    assert cold.save(DOC) == replayed.save(DOC), \
        'snapshot cold-open diverged from full history replay'
    assert canon(cold.get_patch(DOC)) == canon(replayed.get_patch(DOC))

    # same frontier -> the second fetch must be served from the cache
    def snapshot_hits():
        body = client.metrics()['body']
        for line in body.splitlines():
            if line.startswith('amtpu_runtime_counter') \
                    and 'readview.snapshot_hits' in line:
                return float(line.rsplit(None, 1)[1])
        return 0.0

    hits0 = snapshot_hits()
    snap2 = client.snapshot(DOC)
    assert snap2.data == snap.data and snap2.clock == snap.clock
    hits1 = snapshot_hits()
    assert hits1 > hits0, 'repeat snapshot at the same frontier ' \
        'missed the cache (%s -> %s)' % (hits0, hits1)
    client.close()
    bench['snapshot_bytes'] = len(snap.data)
    bench['snapshot_cold_open_ms'] = round(cold_ms, 3)
    bench['snapshot_replay_ms'] = round(replay_ms, 3)
    bench['snapshot_history_changes'] = len(history)
    print('readpath-check: snapshot OK (%dB container, cold-open '
          '%.1fms vs %.1fms full replay of %d changes, byte-identical '
          'state; repeat fetch cache-hit)'
          % (len(snap.data), cold_ms, replay_ms, len(history)))


def main():
    from automerge_tpu.sidecar.client import SidecarClient
    bench = {'check': 'readpath', 'issue': 20,
             'denominator': 'change-mode thin client running the '
                            'full scalar backend per frame'}
    path = os.path.join(tempfile.mkdtemp(), 'gw-readpath.sock')
    proc = spawn_server(path, {'AMTPU_FLUSH_DEADLINE_MS': '5'})
    try:
        arm_patch_vs_change(path, bench)
        arm_replica_slo(path, bench)
        arm_snapshot_cold_open(path, bench)
        probe = SidecarClient(sock_path=path)
        h = probe.healthz()
        assert h['scheduler']['fallback_oracle'] == 0, h['scheduler']
        bench['fallback_oracle'] = 0
        probe.close()
    finally:
        stop_server(proc)
    with open(ARTIFACT, 'w') as f:
        f.write(json.dumps(bench, sort_keys=True) + '\n')
    print('readpath-check: artifact %s' % os.path.relpath(ARTIFACT,
                                                          REPO))
    print('READPATH-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
