"""CI gate: native columnar cold-start economics (ISSUE 14,
docs/STORAGE.md).

Eight acceptance checks, one process, on a scaled (~2k-doc) corpus:

  1. **native decode speed** -- columnar decode through the native
     codec must sustain >= 10x the Python codec's changes/s on BOTH
     the corpus' own chunk+tail blobs and the config-4 table corpus
     (the acceptance corpus; interleaved A/B, median-of-medians);
  2. **cold-restart speed** -- the END-TO-END restore through
     `load_batch` (decode + the shared C++ apply, which bounds the
     ratio) with `AMTPU_STORAGE_NATIVE=1` must be >= 4x the Python-
     codec dict-replay arm, same A/B protocol, fresh pool per trial;
  3. **post-restart byte parity** -- every restored doc's `save()`
     bytes must equal the never-evicted builder twin's, and a sample of
     whole-doc patches must match, in BOTH arms;
  4. **durable kill-mid-save recovery** -- a `storage.save` fault mid-
     write (partial tempfile, no rename) must leave the prior blob AND
     the manifest naming it intact; a FRESH ColdStore on the same dir
     must recover and serve the committed bytes;
  5. **arena-direct path engaged** -- `storage.native_loads` > 0 in the
     native arm (the gate must fail if the fast path silently falls
     back to dict replay);
  6. **oracle-free** -- `fallback.oracle == 0` across all of it;
  7. **parallel store restore** (ISSUE 17) -- `restore_from_store`
     auto fan-out must be >= 2x the serial (threads=1) arm's changes/s
     on multi-core hosts (1-core hosts skip loudly like mesh-check),
     with the `storage.restore.*` counters engaged and byte parity;
  8. **clock folding** (ISSUE 17) -- `amtpu_fold_clocks` must hold
     clock memory strictly below the unfolded
     (`AMTPU_STORAGE_FOLD_CLOCKS=0`) arm on a churned corpus, with
     byte-identical saves/patches/missing-clock frames and the
     `clk_pairs` accounting column reconciling against the fresh-walk
     oracle.

Usage: [JAX_PLATFORMS=cpu] python tools/coldstart_check.py
Corpus size: AMTPU_SMOKE_COLDSTART_DOCS (default 2048).
"""
import os
import random
import statistics
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ.pop('AMTPU_STORAGE_FORMAT', None)   # columnar is the subject
os.environ.pop('AMTPU_STORAGE_NATIVE', None)   # the A/B flips it per arm

ROOT_ID = '00000000-0000-0000-0000-000000000000'
#: codec-stage decode throughput floor (the ISSUE acceptance metric:
#: native decode changes/s vs the Python codec)
MIN_DECODE_SPEEDUP = 10.0
#: end-to-end cold-restart floor: decode + the SHARED C++ apply, which
#: bounds the achievable ratio (the apply runs in both arms)
MIN_RESTORE_SPEEDUP = 4.0


def _doc_changes(d, rng, rounds=16, ops_per_round=8):
    """One doc's history: a text-editing session (the realistic cold-
    start shape -- elemId keys, interleaved actors, catch-up deps) plus
    some map churn."""
    doc_t = 'T%d' % d
    chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': doc_t},
        {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
         'value': doc_t}]}]
    clock = {'a0': 1}
    prev, elem = '_head', 0
    for r in range(rounds):
        actor = 'a%d' % (r % 3)
        clock[actor] = clock.get(actor, 0) + 1
        ops = []
        for _o in range(ops_per_round // 2):
            elem += 1
            ops.append({'action': 'ins', 'obj': doc_t, 'key': prev,
                        'elem': elem})
            key = '%s:%d' % (actor, elem)
            ops.append({'action': 'set', 'obj': doc_t, 'key': key,
                        'value': chr(97 + (elem * 7) % 26)})
            prev = key
        if r % 4 == 0:
            ops.append({'action': 'set', 'obj': ROOT_ID,
                        'key': 'k%d' % (r % 3),
                        'value': rng.randrange(10000)})
        chs.append({'actor': actor, 'seq': clock[actor],
                    'deps': {a: s for a, s in clock.items()
                             if a != actor},
                    'ops': ops})
    return chs


def _build_blobs(n_docs, rng):
    """One builder pool: n_docs text-session docs, half of them
    compacted so their checkpoints carry snapshot chunks; returns
    ({doc: save bytes}, builder pool)."""
    from automerge_tpu.native import NativeDocPool
    pool = NativeDocPool()
    batch_docs = 512
    for base in range(0, n_docs, batch_docs):
        payload = {('doc-%05d' % d): _doc_changes(d, rng)
                   for d in range(base, min(base + batch_docs, n_docs))}
        pool.apply_batch(payload)
    for d in range(0, n_docs, 2):
        pool.compact('doc-%05d' % d)
    return {('doc-%05d' % d): pool.save('doc-%05d' % d)
            for d in range(n_docs)}, pool


def _timed_restore(blobs, native):
    from automerge_tpu.native import NativeDocPool
    os.environ['AMTPU_STORAGE_NATIVE'] = '1' if native else '0'
    pool = NativeDocPool()
    t0 = time.perf_counter()
    pool.load_batch(blobs)
    return time.perf_counter() - t0, pool


def check_decode_speed(problems, report, blobs):
    """Codec-stage A/B: decode_columnar over the corpus' own chunk +
    tail blobs, native vs Python, interleaved, median-of-medians."""
    from automerge_tpu import storage
    parts = []
    for data in blobs.values():
        _f, chunks, tail = storage.unpack_checkpoint_parts(bytes(data))
        parts.extend(chunks)
        parts.append(tail)
    times = {True: [], False: []}
    n_changes = 0
    for t in range(3):
        for native in (True, False) if t % 2 == 0 else (False, True):
            os.environ['AMTPU_STORAGE_NATIVE'] = '1' if native else '0'
            t0 = time.perf_counter()
            n_changes = sum(len(storage.decode_columnar(p))
                            for p in parts)
            times[native].append(time.perf_counter() - t0)
    os.environ.pop('AMTPU_STORAGE_NATIVE', None)
    med_nat = statistics.median(times[True])
    med_py = statistics.median(times[False])
    speedup = med_py / max(med_nat, 1e-9)
    report['decode_changes'] = n_changes
    report['native_decode_changes_per_s'] = round(
        n_changes / max(med_nat, 1e-9))
    report['python_decode_changes_per_s'] = round(
        n_changes / max(med_py, 1e-9))
    report['decode_speedup'] = round(speedup, 2)
    print('coldstart-check: decode %d changes native %.0fk/s python '
          '%.0fk/s (%.1fx)'
          % (n_changes, n_changes / med_nat / 1e3,
             n_changes / med_py / 1e3, speedup), file=sys.stderr)
    if speedup < MIN_DECODE_SPEEDUP:
        problems.append('native codec decode %.1fx < %.0fx the Python '
                        'codec' % (speedup, MIN_DECODE_SPEEDUP))


def check_decode_speed_config4(problems, report, rng):
    """The acceptance corpus: config-4 table changes (nested map row
    values -- where the Python codec pays a msgpack round trip per
    value and the native codec splices spans)."""
    import msgpack

    from automerge_tpu import storage
    os.environ.setdefault('AMTPU_BENCH_C4_DOCS', '128')
    import bench
    batch, _metric = bench.build_config_4(rng)
    os.environ['AMTPU_STORAGE_NATIVE'] = '1'
    blobs, n_changes = [], 0
    for changes in batch.values():
        raws = [msgpack.packb(c, use_bin_type=True) for c in changes]
        n_changes += len(raws)
        blobs.append(storage.encode_columnar(raws))
    times = {True: [], False: []}
    for t in range(3):
        for native in (True, False) if t % 2 == 0 else (False, True):
            os.environ['AMTPU_STORAGE_NATIVE'] = '1' if native else '0'
            t0 = time.perf_counter()
            for b in blobs:
                storage.decode_columnar(b)
            times[native].append(time.perf_counter() - t0)
    os.environ.pop('AMTPU_STORAGE_NATIVE', None)
    med_nat = statistics.median(times[True])
    med_py = statistics.median(times[False])
    speedup = med_py / max(med_nat, 1e-9)
    report['config4_decode_speedup'] = round(speedup, 2)
    print('coldstart-check: config-4 decode %d changes native %.0fk/s '
          'python %.0fk/s (%.1fx)'
          % (n_changes, n_changes / med_nat / 1e3,
             n_changes / med_py / 1e3, speedup), file=sys.stderr)
    if speedup < MIN_DECODE_SPEEDUP:
        problems.append('config-4 native codec decode %.1fx < %.0fx '
                        'the Python codec'
                        % (speedup, MIN_DECODE_SPEEDUP))


def check_speed_and_parity(problems, report, blobs, builder):
    from automerge_tpu import telemetry
    trials = {True: [], False: []}
    pools = {}
    for t in range(3):
        for native in (True, False) if t % 2 == 0 else (False, True):
            dt, pool = _timed_restore(blobs, native)
            trials[native].append(dt)
            pools[native] = pool
    os.environ.pop('AMTPU_STORAGE_NATIVE', None)
    med_nat = statistics.median(trials[True])
    med_py = statistics.median(trials[False])
    speedup = med_py / max(med_nat, 1e-9)
    report['native_restore_s'] = round(med_nat, 4)
    report['python_restore_s'] = round(med_py, 4)
    report['restore_speedup'] = round(speedup, 2)
    print('coldstart-check: restore %d docs native %.3fs python %.3fs '
          '(%.1fx)' % (len(blobs), med_nat, med_py, speedup),
          file=sys.stderr)
    if speedup < MIN_RESTORE_SPEEDUP:
        problems.append('end-to-end restore %.1fx < %.0fx the Python '
                        'arm' % (speedup, MIN_RESTORE_SPEEDUP))
    snap = telemetry.metrics_snapshot()
    report['native_loads'] = int(snap.get('storage.native_loads', 0))
    if report['native_loads'] < 1:
        problems.append('storage.native_loads == 0: the arena-direct '
                        'path never engaged')
    # post-restart byte parity vs the never-evicted twin, both arms
    sample = sorted(blobs)[::max(1, len(blobs) // 100)]
    bad = 0
    for arm, pool in pools.items():
        for doc in blobs:
            if pool.save(doc) != builder.save(doc):
                bad += 1
                problems.append('save bytes diverged for %s (arm %s)'
                                % (doc, 'native' if arm else 'python'))
                break
        for doc in sample:
            if pool.get_patch(doc) != builder.get_patch(doc):
                bad += 1
                problems.append('patch diverged for %s (arm %s)'
                                % (doc, 'native' if arm else 'python'))
                break
    report['parity'] = bad == 0


def check_parallel_restore(problems, report, blobs, builder):
    """ISSUE 17: `restore_from_store` serial (threads=1) vs auto
    fan-out over shard pools must hit >= 2x changes/s on multi-core
    hosts; on 1-core hosts the gate is vacuous by construction
    (ceiling 1x) and SKIPS LOUDLY like mesh-check's scaling gate.
    Parity + restore-counter engagement gate on every host shape."""
    import tempfile

    from automerge_tpu import telemetry
    from automerge_tpu.native import ShardedNativePool, _restore_threads
    from automerge_tpu.storage.coldstore import ColdStore
    os.environ.pop('AMTPU_STORAGE_NATIVE', None)
    store = ColdStore(root=tempfile.mkdtemp(prefix='amtpu-cs-par-'))
    for d, b in blobs.items():
        store.put(d, bytes(b))
    n_changes = 17 * len(blobs)
    cores = os.cpu_count() or 1
    trials = {1: [], 0: []}
    pool = None
    for t in range(3 if cores >= 2 else 1):
        for threads in (1, 0) if t % 2 == 0 else (0, 1):
            p = ShardedNativePool(4)
            t0 = time.perf_counter()
            summary = p.restore_from_store(store, threads=threads or None)
            trials[threads].append(time.perf_counter() - t0)
            if summary['docs'] != len(blobs) or summary['corrupt'] \
                    or summary['failed']:
                problems.append('restore_from_store summary off: %r'
                                % {k: summary[k] for k in
                                   ('docs', 'corrupt', 'failed')})
            pool = p
    serial_s = statistics.median(trials[1])
    par_s = statistics.median(trials[0])
    speedup = serial_s / max(par_s, 1e-9)
    report['restore_parallel'] = {
        'cores': cores, 'threads': _restore_threads(),
        'serial_changes_per_s': round(n_changes / serial_s),
        'parallel_changes_per_s': round(n_changes / par_s),
        'speedup': round(speedup, 2),
    }
    print('coldstart-check: store restore serial %.3fs parallel %.3fs '
          '(%.2fx on %d cores)' % (serial_s, par_s, speedup, cores),
          file=sys.stderr)
    if cores < 2:
        print('coldstart-check: parallel-restore gate SKIPPED '
              '(1 physical core; ceiling 1x; measured %.2fx recorded '
              'in the JSON)' % speedup, file=sys.stderr)
    elif speedup < 2.0:
        problems.append('parallel restore %.2fx < 2x the serial arm '
                        'on %d cores' % (speedup, cores))
    snap = telemetry.metrics_snapshot()
    if not snap.get('storage.restore.docs'):
        problems.append('storage.restore.docs == 0: restore_from_store '
                        'never counted')
    sample = sorted(blobs)[::max(1, len(blobs) // 64)]
    for doc in sample:
        if pool.save(doc) != builder.save(doc):
            problems.append('restore_from_store save bytes diverged '
                            'for %s' % doc)
            break


def check_clock_fold(problems, report):
    """ISSUE 17: clock folding (`amtpu_fold_clocks`) must hold clock
    memory STRICTLY below the unfolded (AMTPU_STORAGE_FOLD_CLOCKS=0)
    arm on the same churned corpus, with byte-identical saves, patches
    and missing-clock frames across the arms."""
    from automerge_tpu.native import NativeDocPool
    n_docs = 64

    def _run(folded, arm_rng):
        os.environ['AMTPU_STORAGE_FOLD_CLOCKS'] = '1' if folded else '0'
        pool = NativeDocPool()
        for base in range(0, n_docs, 32):
            pool.apply_batch({('doc-%05d' % d): _doc_changes(d, arm_rng)
                              for d in range(base,
                                             min(base + 32, n_docs))})
        seqs = {}
        for r in range(6):
            payload = {}
            for d in range(n_docs):
                doc = 'doc-%05d' % d
                s0 = seqs.get(doc, 0)
                payload[doc] = [
                    {'actor': 'churn', 'seq': s0 + i + 1,
                     'deps': {'churn': s0 + i} if s0 + i else {},
                     'ops': [{'action': 'set', 'obj': ROOT_ID,
                              'key': 'k%d' % (i % 4), 'value': r + i}]}
                    for i in range(4)]
                seqs[doc] = s0 + 4
            pool.apply_batch(payload)
            for doc in payload:
                pool.compact(doc)
        return pool

    folded = _run(True, random.Random(23))
    unfolded = _run(False, random.Random(23))
    os.environ.pop('AMTPU_STORAGE_FOLD_CLOCKS', None)
    # clock memory: sparse pairs (8 B each) + the densified fold table
    ids, stats = folded.doc_stats()
    fold_mem = int((stats[:, 6] * 8 + stats[:, 7]).sum())
    _ids, ustats = unfolded.doc_stats()
    unfold_mem = int((ustats[:, 6] * 8 + ustats[:, 7]).sum())
    report['clock_fold'] = {
        'folded_clock_bytes': fold_mem,
        'unfolded_clock_bytes': unfold_mem,
        'sparse_pairs_left': int(folded.clock_pairs()),
    }
    print('coldstart-check: clock fold %d B vs unfolded %d B '
          '(%d sparse pairs left)' % (fold_mem, unfold_mem,
                                      int(folded.clock_pairs())),
          file=sys.stderr)
    if not fold_mem < unfold_mem:
        problems.append('folded clock memory %d B not strictly below '
                        'the unfolded arm %d B' % (fold_mem, unfold_mem))
    # acct column must reconcile with the fresh-walk oracle
    for pool, arm in ((folded, 'folded'), (unfolded, 'unfolded')):
        pids, pstats = pool.doc_stats()
        oracle = pool.clock_pairs()
        acct = int(pstats[:, 6].sum())
        if acct != oracle:
            problems.append('clk_pairs acct %d != oracle %d (%s arm)'
                            % (acct, oracle, arm))
    for d in range(0, n_docs, 7):
        doc = 'doc-%05d' % d
        if folded.save(doc) != unfolded.save(doc):
            problems.append('clock fold: save bytes diverged for %s'
                            % doc)
            break
        if folded.get_patch(doc) != unfolded.get_patch(doc):
            problems.append('clock fold: patch diverged for %s' % doc)
            break
        if folded._missing_clock(doc, {}) \
                != unfolded._missing_clock(doc, {}):
            problems.append('clock fold: missing-clock frame diverged '
                            'for %s' % doc)
            break
        if folded.get_missing_changes(doc, {'churn': 2, 'a1': 2}) \
                != unfolded.get_missing_changes(doc, {'churn': 2,
                                                      'a1': 2}):
            problems.append('clock fold: straggler backfill diverged '
                            'for %s' % doc)
            break


def check_durable_recovery(problems, report):
    import tempfile

    from automerge_tpu import faults
    from automerge_tpu.storage.coldstore import ColdStore
    root = tempfile.mkdtemp(prefix='amtpu-coldstart-check-')
    committed = b'committed-checkpoint-bytes' * 64
    cs = ColdStore(root=root, durable=True)
    cs.put('doc-h', committed)
    spec = faults.arm('storage.save', 'permanent')
    killed = False
    try:
        cs.put('doc-h', b'new-bytes-the-kill-interrupts' * 64)
    except faults.InjectedFault:
        killed = True
    faults.disarm(spec)
    ok = killed and cs.get('doc-h') == committed
    fresh = ColdStore(root=root, durable=True)
    ok = ok and fresh.doc_ids() == ['doc-h'] \
        and fresh.get('doc-h') == committed
    report['durable_recovery'] = ok
    if not ok:
        problems.append('durable kill-mid-save recovery failed '
                        '(killed=%s)' % killed)
    else:
        print('coldstart-check: kill-mid-save left the committed copy '
              '+ manifest intact; fresh store recovered it',
              file=sys.stderr)


def main():
    from automerge_tpu import telemetry
    from automerge_tpu.utils.common import env_int
    n_docs = env_int('AMTPU_SMOKE_COLDSTART_DOCS', 2048)
    problems, report = [], {'docs': n_docs}
    rng = random.Random(7)
    t0 = time.perf_counter()
    blobs, builder = _build_blobs(n_docs, rng)
    print('coldstart-check: built %d docs in %.1fs'
          % (n_docs, time.perf_counter() - t0), file=sys.stderr)
    check_decode_speed(problems, report, blobs)
    check_decode_speed_config4(problems, report, rng)
    check_speed_and_parity(problems, report, blobs, builder)
    check_parallel_restore(problems, report, blobs, builder)
    check_clock_fold(problems, report)
    check_durable_recovery(problems, report)
    snap = telemetry.metrics_snapshot()
    report['fallback_oracle'] = int(snap.get('fallback.oracle', 0))
    if report['fallback_oracle']:
        problems.append('fallback.oracle == %d (must be 0)'
                        % report['fallback_oracle'])
    if problems:
        print('coldstart-check: FAIL')
        for p in problems:
            print('  - %s' % p)
        return 1
    print('coldstart-check: PASS (%d docs, codec %.1fx / restore '
          '%.1fx vs the Python arm, parallel store restore %.2fx, '
          'clock fold %d B < %d B unfolded, parity + durable recovery '
          '+ oracle-free)'
          % (n_docs, report['decode_speedup'],
             report['restore_speedup'],
             report['restore_parallel']['speedup'],
             report['clock_fold']['folded_clock_bytes'],
             report['clock_fold']['unfolded_clock_bytes']))
    return 0


if __name__ == '__main__':
    sys.exit(main())
