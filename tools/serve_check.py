"""Serve-gateway gate (ISSUE 5, docs/SERVING.md): the continuous
-batching gateway must actually coalesce concurrent traffic, stay
byte-identical to serial application, and shed load with the typed
Overloaded envelope instead of hanging or growing without bound.

Three phases, each against a REAL server subprocess on a unix socket:

  1. **coalescing + parity** -- 32 concurrent connections of mixed-doc
     traffic (each connection owns one doc's actor stream and
     interleaves reads).  Gates: median ``amtpu_batch_occupancy`` > 4
     docs/flush; every per-request patch AND every final per-doc patch
     byte-identical to the same traffic replayed serially through ONE
     connection on a fresh server; ``fallback.oracle == 0``; no leaked
     batch handles at drain (``native.live_batch_handles == 0``).
  2. **overload** -- a fresh server with the queue capped low
     (``AMTPU_QUEUE_MAX_OPS=8``): a burst of concurrent mutations must
     produce typed ``Overloaded`` envelopes (no hang), and the server
     must answer healthz and fresh mutations after the burst drains.
  3. **drain hygiene** -- after both phases the phase-1 server's
     healthz reports an empty queue, no shed state, zero live batch
     handles, and a zero oracle-fallback count.

Run: JAX_PLATFORMS=cpu python tools/serve_check.py    (make serve-check)
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CONNS = 32
ROUNDS = 6
ROOT_ID = '00000000-0000-0000-0000-000000000000'


def spawn_server(path, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path], env=env, cwd=REPO)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline or proc.poll() is not None:
            raise RuntimeError('gateway server did not come up')
        time.sleep(0.05)
    return proc


def stop_server(proc):
    proc.terminate()
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=30)


def doc_stream(i):
    """Connection i's traffic: one actor's changes on its own doc (so
    per-request patches are deterministic under any cross-connection
    interleaving), docs deliberately reused across rounds."""
    doc = 'doc-%02d' % i
    chs = [{'actor': 'w%02d' % i, 'seq': s, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': 'k%d' % (s % 3),
                     'value': '%d-%d' % (i, s)}]}
           for s in range(1, ROUNDS + 1)]
    return doc, chs


def run_concurrent(path):
    """32 threads, one connection each; returns per-conn response
    patches + final per-doc patches."""
    from automerge_tpu.sidecar.client import SidecarClient
    patches = {}
    finals = {}
    errors = []
    barrier = threading.Barrier(N_CONNS, timeout=120)

    def client(i):
        try:
            doc, chs = doc_stream(i)
            with SidecarClient(sock_path=path) as c:
                barrier.wait()          # max concurrency from round 1
                got = []
                for s, ch in enumerate(chs, 1):
                    got.append(c.apply_changes(doc, [ch]))
                    if s % 3 == 0:      # mixed traffic: bypass reads
                        c.get_patch(doc)
                patches[i] = got
                finals[i] = c.get_patch(doc)
        except Exception as e:
            errors.append((i, '%s: %s' % (type(e).__name__, e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CONNS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    if errors:
        raise AssertionError('concurrent clients failed: %s' % errors)
    assert len(patches) == N_CONNS
    return patches, finals


def run_serial(path):
    """The SAME traffic through one connection, one request at a time."""
    from automerge_tpu.sidecar.client import SidecarClient
    patches = {}
    finals = {}
    with SidecarClient(sock_path=path) as c:
        for i in range(N_CONNS):
            doc, chs = doc_stream(i)
            patches[i] = [c.apply_changes(doc, [ch]) for ch in chs]
            finals[i] = c.get_patch(doc)
    return patches, finals


def check_phase1():
    from automerge_tpu.sidecar.client import SidecarClient
    tmp = tempfile.mkdtemp()
    conc_path = os.path.join(tmp, 'gw-conc.sock')
    serial_path = os.path.join(tmp, 'gw-serial.sock')

    proc = spawn_server(conc_path,
                        {'AMTPU_FLUSH_DEADLINE_MS': '5'})
    try:
        conc_patches, conc_finals = run_concurrent(conc_path)
        with SidecarClient(sock_path=conc_path) as c:
            health = c.healthz()
            metrics = c.metrics()['body']
        sched = health['scheduler']
    finally:
        stop_server(proc)

    proc = spawn_server(serial_path)
    try:
        serial_patches, serial_finals = run_serial(serial_path)
    finally:
        stop_server(proc)

    for i in range(N_CONNS):
        assert json.dumps(conc_patches[i], sort_keys=True) == \
            json.dumps(serial_patches[i], sort_keys=True), \
            'per-request patch divergence on conn %d' % i
        assert json.dumps(conc_finals[i], sort_keys=True) == \
            json.dumps(serial_finals[i], sort_keys=True), \
            'final patch divergence on doc of conn %d' % i
    print('serve-check: parity OK (%d conns x %d rounds, per-request '
          '+ final patches byte-identical to serial)'
          % (N_CONNS, ROUNDS))

    occ = sched['occupancy']
    assert occ['count'] >= 1, 'no gateway flushes recorded'
    assert occ['p50'] > 4, \
        'median batch occupancy %.2f docs/flush (need > 4); summary %r' \
        % (occ['p50'], occ)
    assert sched['depth_ops'] == 0 and not sched['shedding'], sched
    assert sched['live_batch_handles'] == 0, \
        'leaked batch handles: %r' % sched
    assert sched['fallback_oracle'] == 0, \
        'oracle fallback fired: %r' % sched
    assert 'amtpu_batch_occupancy_bucket' in metrics
    assert 'amtpu_queue_wait_ms_bucket' in metrics
    print('serve-check: occupancy OK (median %.1f docs/flush, %d '
          'flushes; queue drained, 0 leaked handles, oracle=0)'
          % (occ['p50'], occ['count']))


def check_phase2():
    from automerge_tpu.errors import OverloadedError
    from automerge_tpu.sidecar.client import SidecarClient
    path = os.path.join(tempfile.mkdtemp(), 'gw-ovl.sock')
    # tiny queue + slow flush so the burst reliably crosses the
    # watermark; each request carries several queued ops
    proc = spawn_server(path, {'AMTPU_QUEUE_MAX_OPS': '8',
                               'AMTPU_FLUSH_DEADLINE_MS': '25'})
    try:
        outcomes = []

        def push(i):
            try:
                with SidecarClient(sock_path=path) as c:
                    chs = [{'actor': 'b%02d' % i, 'seq': s, 'deps': {},
                            'ops': [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': s}]}
                           for s in range(1, 5)]
                    c.apply_changes('burst-%d' % i, chs)
                    outcomes.append('ok')
            except OverloadedError as e:
                assert e.retry_after_ms and e.retry_after_ms >= 1, \
                    'Overloaded without retryAfterMs'
                outcomes.append('overloaded')

        threads = [threading.Thread(target=push, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(outcomes) == 16, \
            'burst client hung (%d/16 returned)' % len(outcomes)
        n_over = outcomes.count('overloaded')
        assert n_over >= 1, 'queue capped at 8 ops never shed %r' \
            % outcomes
        # the server survives the burst: drains, clears shed state, and
        # accepts fresh work
        with SidecarClient(sock_path=path) as c:
            deadline = time.time() + 60
            while True:
                try:
                    p = c.apply_changes('after-burst', [{
                        'actor': 'z', 'seq': 1, 'deps': {},
                        'ops': [{'action': 'set', 'obj': ROOT_ID,
                                 'key': 'k', 'value': 1}]}])
                    break
                except OverloadedError:
                    assert time.time() < deadline, \
                        'gateway never recovered from the shed state'
                    time.sleep(0.05)
            assert p['clock'] == {'z': 1}
            health = c.healthz()
            assert health['ok'] and not health['scheduler']['shedding']
            assert health['scheduler']['depth_ops'] == 0
        print('serve-check: overload OK (%d/16 burst requests shed '
              'with typed envelopes, server healthy after drain)'
              % n_over)
    finally:
        stop_server(proc)


def main():
    check_phase1()
    check_phase2()
    print('SERVE-CHECK GREEN')
    return 0


if __name__ == '__main__':
    sys.exit(main())
