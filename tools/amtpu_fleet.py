#!/usr/bin/env python
"""Fleet observability CLI: scrape N replicas' /healthz +
/debug/slo_slots endpoints and print ONE merged view -- per-class SLO
windows recomputed from summed slots (never averaged percentiles),
error-budget burn, and a per-replica headroom/skew table
(automerge_tpu/telemetry/fleet.py; ISSUE 16).

Usage:
  amtpu_fleet.py --url http://h1:9100 --url http://h2:9100 --once
  amtpu_fleet.py --url ... --interval 5        # refresh loop
  amtpu_fleet.py --url ... --once --json       # machine-readable
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _fmt_mb(n):
    if n is None:
        return '-'
    return '%.1fMB' % (n / (1024.0 * 1024.0))


def render(scrapes, section, out=sys.stdout):
    w = out.write
    w('amtpu fleet: %d replicas up, %d unreachable\n'
      % (len(section['replicas']), len(section['errors'])))
    for r in section['replicas']:
        w('  up   %-24s %s  uptime %ss\n'
          % (r.get('replica_id'), r['url'], r.get('uptime_s')))
    for e in section['errors']:
        w('  DOWN %-24s %s\n' % (e['url'], e['error']))
    slo = section['slo']
    w('slo (merged windows; target p99 %dms, slow %dms)\n'
      % (slo['target_p99_ms'], slo['slow_ms']))
    for cls, windows in sorted(slo['classes'].items()):
        for win, row in sorted(windows.items(),
                               key=lambda kv: int(kv[0][:-1])):
            w('  %-10s %-5s n=%-7d p50=%-8s p99=%-8s breach=%s\n'
              % (cls, win, row['count'],
                 row['p50_ms'] if row['p50_ms'] is not None else '-',
                 row['p99_ms'] if row['p99_ms'] is not None else '-',
                 row['breach_frac']))
    w('burn (merged): %s\n' % slo['burn'])
    hr = section['headroom']
    w('headroom: used %s / budget %s  pressure %.3f  skew %.3f\n'
      % (_fmt_mb(hr['used_bytes']),
         _fmt_mb(hr['budget_bytes']) if hr['budget_bytes'] else '(none)',
         hr['pressure'], hr['pressure_skew']))
    for r in hr['replicas']:
        w('  %-24s used %-9s pressure %-6s exhaustion %s\n'
          % (r.get('replica_id'), _fmt_mb(r.get('used_bytes')),
             r.get('pressure') if r.get('pressure') is not None else '-',
             '%ss' % r['exhaustion_s']
             if r.get('exhaustion_s') is not None else '-'))
    rt = section.get('routing') or {}
    if rt.get('members'):
        w('routing: ring v%s..v%s  %s\n'
          % (rt.get('ring_version_min'), rt.get('ring_version_max'),
             'consistent' if rt.get('consistent')
             else 'CONVERGING (rebalance in flight)'))
        for m in rt['members']:
            if m.get('role') == 'router':
                w('  %-24s router  ring v%-4s members=%s overrides=%s'
                  ' migrating=%s\n'
                  % (m.get('replica_id'), m.get('ring_version'),
                     len(m.get('members') or ()), m.get('overrides'),
                     m.get('migrating_docs')))
            else:
                w('  %-24s replica ring v%-4s owned=%-6s disowned=%-4s'
                  ' mig in/out=%s/%s\n'
                  % (m.get('replica_id'), m.get('ring_version'),
                     m.get('owned_docs'), m.get('disowned_docs'),
                     m.get('migrations_in'), m.get('migrations_out')))
    fh = section.get('health')
    if fh:
        w('health: %d up / %d suspect / %d dead / %d quarantined'
          '  parked %d docs (%s)\n'
          % (fh['up'], fh['suspect'], fh['dead'], fh['quarantined'],
             fh['parked_docs'], _fmt_mb(fh['parked_bytes'])))
        for m, st in sorted(fh['members'].items()):
            if st.get('state') != 'up':
                w('  %-24s %-11s misses=%-3s for %ss\n'
                  % (m, st.get('state'), st.get('misses'),
                     st.get('for_s')))


def main(argv=None):
    from automerge_tpu.telemetry import fleet
    ap = argparse.ArgumentParser(
        description='merged multi-replica amtpu observability view')
    ap.add_argument('--url', action='append', required=True,
                    help='replica metrics base url (repeatable)')
    ap.add_argument('--once', action='store_true',
                    help='scrape once, print, exit non-zero if any '
                         'replica was unreachable')
    ap.add_argument('--interval', type=float, default=5.0)
    ap.add_argument('--json', action='store_true',
                    help='print the fleet section as JSON')
    ap.add_argument('--timeout', type=float, default=2.0)
    args = ap.parse_args(argv)
    while True:
        scrapes, section = fleet.scrape_fleet(args.url,
                                              timeout=args.timeout)
        if args.json:
            print(json.dumps(section, default=str))
        else:
            if not args.once:
                sys.stdout.write('\x1b[2J\x1b[H')
            render(scrapes, section)
        if args.once:
            return 1 if section['errors'] else 0
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == '__main__':
    sys.exit(main())
