#!/usr/bin/env python
"""Cross-process trace assembly: merge the per-process JSONL trace
files (``AMTPU_TRACE_FILE``) of a client + N servers into per-request
trace trees, normalize per-process clock skew, and render a waterfall
with the critical-path hop flagged (ISSUE 16; docs/OBSERVABILITY.md
distributed-tracing section).

Each process exports only its OWN spans; what joins them is the wire
trace context (``{"trace": {"traceId", "spanId"}}``) the client stamps
on every request: the server's ``sidecar.request`` span names the
client's span as its parent, so the cross-process edge is an ordinary
parent link that happens to resolve in another file.  Rotated
siblings (``<path>.1``) load automatically.

Clock skew: span ``start`` stamps come from each process's own
``time.time()``.  For every cross-process parent->child edge we know
the child started AFTER the parent (the request had to cross the
wire), so ``min(child.start - parent.start)`` over a process pair's
edges bounds that process's clock offset (tightest when the fastest
request's wire time ~ 0).  Offsets propagate from the root process
(offset 0) across the edge graph; every rendered start is
offset-corrected.  With one edge the estimate absorbs that request's
wire time -- good enough to order hops, not to measure sub-wire
intervals.

Usage:
  amtpu_trace.py FILE [FILE...]           # list assembled traces
  amtpu_trace.py --trace ID FILE...       # waterfall one trace
  amtpu_trace.py --json FILE...           # machine-readable summaries
"""

import argparse
import json
import os
import sys


def load_files(paths):
    """All span records from `paths` (plus their ``.1`` rotation
    siblings), each tagged with ``_proc`` = the file it came from --
    the clock-skew domain.  Lines that are not span-shaped JSON (e.g.
    a torn tail line) are skipped, not fatal."""
    records = []
    for path in paths:
        for p in (path + '.1', path):
            if not os.path.exists(p):
                continue
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(rec, dict) \
                            or 'trace' not in rec or 'span' not in rec \
                            or 'start' not in rec:
                        continue
                    rec['_proc'] = path
                    records.append(rec)
    return records


def group_traces(records):
    """{trace_id: [records]} preserving file order within a trace."""
    traces = {}
    for rec in records:
        traces.setdefault(rec['trace'], []).append(rec)
    return traces


def estimate_offsets(nodes):
    """{proc: clock offset seconds} for one trace's nodes, relative to
    the root span's process (offset 0).  Edge estimate per ordered
    process pair (P -> Q): ``min(child.start - parent.start)`` over the
    cross-process parent/child pairs; offsets propagate breadth-first
    over the pair graph.  Processes unreachable from the root's (no
    cross edge at all) keep offset 0."""
    by_span = {n['span']: n for n in nodes}
    edges = {}      # (parent_proc, child_proc) -> min delta
    for n in nodes:
        parent = by_span.get(n.get('parent'))
        if parent is None or parent['_proc'] == n['_proc']:
            continue
        key = (parent['_proc'], n['_proc'])
        delta = n['start'] - parent['start']
        if key not in edges or delta < edges[key]:
            edges[key] = delta
    roots = [n for n in nodes if n.get('parent') not in by_span]
    root_proc = roots[0]['_proc'] if roots else nodes[0]['_proc']
    offsets = {root_proc: 0.0}
    frontier = [root_proc]
    while frontier:
        cur = frontier.pop()
        for (pp, cp), delta in edges.items():
            if pp == cur and cp not in offsets:
                offsets[cp] = offsets[cur] + delta
                frontier.append(cp)
            elif cp == cur and pp not in offsets:
                offsets[pp] = offsets[cur] - delta
                frontier.append(pp)
    for n in nodes:
        offsets.setdefault(n['_proc'], 0.0)
    return offsets


def build_tree(nodes):
    """Skew-normalize and link one trace's nodes: each gains
    ``start_n`` (offset-corrected start) and ``children`` (sorted by
    normalized start); returns the roots (parent unknown), earliest
    first."""
    offsets = estimate_offsets(nodes)
    by_span = {}
    for n in nodes:
        n = dict(n)
        n['start_n'] = n['start'] - offsets[n['_proc']]
        n['children'] = []
        by_span[n['span']] = n
    roots = []
    for n in by_span.values():
        parent = by_span.get(n.get('parent'))
        if parent is not None:
            parent['children'].append(n)
        else:
            roots.append(n)
    for n in by_span.values():
        n['children'].sort(key=lambda c: c['start_n'])
    roots.sort(key=lambda r: r['start_n'])
    return roots


def critical_path(root):
    """Span ids of the longest-duration child chain from `root` -- the
    hop to look at first when the request was slow."""
    path = set()
    node = root
    while node is not None:
        path.add(node['span'])
        node = max(node['children'], key=lambda c: c.get('dur_s', 0.0),
                   default=None)
    return path


def summarize(trace_id, nodes):
    """One trace's gate-facing numbers: the client wall (root
    ``sidecar.client.request`` span), the summed server request time
    under it, and the residual wire+overhead share -- what the
    obs-check two-process arm asserts a budget on."""
    roots = build_tree(nodes)
    procs = sorted({n['_proc'] for n in nodes})
    out = {'trace': trace_id, 'spans': len(nodes), 'procs': len(procs),
           'proc_files': procs,
           'roots': [r['name'] for r in roots]}
    client = next((r for r in roots
                   if r['name'] == 'sidecar.client.request'), None)
    if client is not None:
        server_s = sum(n.get('dur_s', 0.0) for n in nodes
                       if n['name'] == 'sidecar.request')
        wall = client.get('dur_s', 0.0)
        out['client_wall_s'] = round(wall, 9)
        out['server_s'] = round(server_s, 9)
        out['wire_s'] = round(max(0.0, wall - server_s), 9)
        out['cmd'] = (client.get('attrs') or {}).get('cmd')
    return out


def render_waterfall(trace_id, nodes, out=sys.stdout):
    roots = build_tree(nodes)
    if not roots:
        return
    t0 = roots[0]['start_n']
    crit = set()
    for r in roots:
        crit |= critical_path(r)
    procs = sorted({n['_proc'] for n in nodes})
    out.write('trace %s  (%d spans, %d process files)\n'
              % (trace_id, len(nodes), len(procs)))
    for i, p in enumerate(procs):
        out.write('  proc[%d] %s\n' % (i, p))
    pidx = {p: i for i, p in enumerate(procs)}

    def walk(node, depth):
        mark = '*' if node['span'] in crit else ' '
        out.write('%s %8.3fms %9.3fms  p%d %s%s\n'
                  % (mark, (node['start_n'] - t0) * 1e3,
                     node.get('dur_s', 0.0) * 1e3,
                     pidx[node['_proc']],
                     '  ' * depth, node['name']))
        for c in node['children']:
            walk(c, depth + 1)

    out.write('    start      duration  proc  span '
              '(* = critical path)\n')
    for r in roots:
        walk(r, 0)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='assemble cross-process amtpu trace trees')
    ap.add_argument('files', nargs='+',
                    help='per-process AMTPU_TRACE_FILE paths '
                         '(.1 rotations load automatically)')
    ap.add_argument('--trace', help='render one trace id as a '
                                    'waterfall')
    ap.add_argument('--json', action='store_true',
                    help='print per-trace summaries as JSON lines')
    args = ap.parse_args(argv)
    traces = group_traces(load_files(args.files))
    if args.trace:
        nodes = traces.get(args.trace)
        if not nodes:
            print('trace %r not found' % args.trace, file=sys.stderr)
            return 1
        render_waterfall(args.trace, nodes)
        return 0
    summaries = [summarize(tid, nodes)
                 for tid, nodes in traces.items()]
    summaries.sort(key=lambda s: -s.get('client_wall_s', 0.0))
    if args.json:
        for s in summaries:
            print(json.dumps(s))
        return 0
    print('%d traces from %d files' % (len(summaries),
                                       len(args.files)))
    for s in summaries:
        wall = s.get('client_wall_s')
        print('  %s  spans=%-3d procs=%d  %s%s'
              % (s['trace'], s['spans'], s['procs'],
                 ('wall=%.3fms wire=%.3fms '
                  % (wall * 1e3, s['wire_s'] * 1e3))
                 if wall is not None else '',
                 s.get('cmd') or '/'.join(s['roots'])))
    return 0


if __name__ == '__main__':
    sys.exit(main())
