"""CI gate: the kernel path must be oracle-free on the table workload.

Runs a config-4-shaped smoke (the shape that produced 8,532 host-oracle
rows before the escalation ladder, VERDICT r5) through tools/quickbench.py
with the kernel path forced (AMTPU_HOST_FULL=0), then fails if

  * the telemetry block reports ANY `fallback.oracle` count -- a register
    group fell past every escalation tier back to the host oracle, or
  * the per-tier escalation counters (`fallback.escalated.wN`) are absent
    from the block -- the bench line stopped proving where resolution
    work landed, or
  * nothing escalated at all -- the smoke no longer exercises the ladder
    and the gate would be vacuously green.

Wired into `make check` as `make fallback-check`.

Usage: [JAX_PLATFORMS=cpu] python tools/fallback_check.py
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env['AMTPU_HOST_FULL'] = '0'            # the kernel path IS the subject
    # deterministic shape: enough docs that the seeded workload grows a
    # register group past the base window (member mode engages and every
    # same-change dup-assign group escalates), and a PINNED shard count
    # so the doc->shard split doesn't vary with the host's core count
    env.setdefault('AMTPU_BENCH_C4_DOCS', '256')
    env.setdefault('AMTPU_BENCH_SHARDS', '8')
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, 'quickbench.py'),
         '--config', '4', '--runs', '1'],
        env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        print('fallback-check: quickbench smoke failed (rc=%d)'
              % proc.returncode, file=sys.stderr)
        return 1
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    fallbacks = result.get('telemetry', {}).get('fallbacks', {})

    tiers = {k: v for k, v in fallbacks.items()
             if k.startswith('escalated.w')}
    problems = []
    if 'oracle' not in fallbacks:
        problems.append("no 'oracle' counter in the telemetry block")
    elif fallbacks['oracle'] != 0:
        problems.append('kernel path reported %s fallback.oracle rows'
                        % fallbacks['oracle'])
    if not tiers:
        problems.append('per-tier escalation counters absent from the '
                        'telemetry block')
    elif sum(tiers.values()) <= 0:
        problems.append('smoke did not exercise the escalation ladder '
                        '(all tier counters zero)')
    if problems:
        print('fallback-check FAILED:', file=sys.stderr)
        for p in problems:
            print('  * ' + p, file=sys.stderr)
        print('  telemetry.fallbacks = %s' % json.dumps(fallbacks),
              file=sys.stderr)
        return 1
    active = {k: v for k, v in tiers.items() if v}
    print('fallback-check: oracle=0, escalated tiers %s, %.0f ops/s'
          % (json.dumps(active), result.get('value', 0.0)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
