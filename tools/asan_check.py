"""Native-sanitizer gate (`make asan-check`; docs/ANALYSIS.md).

Builds native/core.cpp with ``-fsanitize=address,undefined``
(`make -C native asan` -> libamtpu_core_asan.so), then runs the
native-heavy test subset against it: ``AMTPU_NATIVE_LIB`` points the
loader at the instrumented build and ``LD_PRELOAD`` injects libasan
into the (uninstrumented) Python interpreter so the runtime's
interceptors are live before dlopen.

This is the gate that catches the recurring C++ bug classes at CI time
instead of review round 5: the batch-column use-after-free family (an
error path freeing C++ memory before draining in-flight kernels -- hit
twice in PR 6), the `recs[0]` empty-mirror OOB, and any UB the
undefined sanitizer can prove (which aborts: -fno-sanitize-recover).

The subset is the native driver + rollback/atomicity lanes -- the
paths that exercise begin/rollback/mid/emit and the escalation tiers
hardest per second.  Leak checking is off (CPython and jax hold
intentional globals); the win is heap/stack/global corruption + UB.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASAN_LIB = os.path.join(ROOT, 'automerge_tpu', 'native',
                        'libamtpu_core_asan.so')

#: the native-heavy subset: driver + overflow/escalation paths
#: (test_native), rollback byte-atomicity (test_atomicity), the
#: C++-vs-oracle differential (test_backend), and the native columnar
#: codec / arena-direct load / op-state folding ABI (test_storage_
#: native, ISSUE 14) -- broad begin/emit coverage without the slow
#: subprocess lanes
SUBSET = ('tests/test_native.py', 'tests/test_atomicity.py',
          'tests/test_backend.py', 'tests/test_storage_native.py',
          'tests/test_clock_fold.py')


def _gxx_lib(name):
    out = subprocess.run(['g++', '-print-file-name=%s' % name],
                         capture_output=True, text=True, check=True)
    path = out.stdout.strip()
    if not os.path.isabs(path):
        raise SystemExit('asan-check: %s not found (g++ says %r)'
                         % (name, path))
    return path


def main():
    subprocess.run(['make', '-C', os.path.join(ROOT, 'native'), 'asan'],
                   check=True)
    # libstdc++ rides along in LD_PRELOAD: CPython does not link it, so
    # without an early load ASan's __cxa_throw interceptor cannot
    # resolve the real symbol and aborts the process the first time the
    # C++ runtime throws ("real___cxa_throw != 0" CHECK)
    preload = '%s %s' % (_gxx_lib('libasan.so'),
                         _gxx_lib('libstdc++.so.6'))
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        AMTPU_NATIVE_LIB=ASAN_LIB,
        LD_PRELOAD=preload,
        # no leak pass (CPython/jax hold intentional globals); abort on
        # the first real report so pytest can't swallow it
        ASAN_OPTIONS='detect_leaks=0:abort_on_error=1',
        UBSAN_OPTIONS='halt_on_error=1:print_stacktrace=1',
    )

    # sanity: the instrumented library must actually load through the
    # override and the asan runtime must be live in-process
    probe = subprocess.run(
        [sys.executable, '-c',
         'import ctypes\n'
         'assert ctypes.CDLL(None).__asan_region_is_poisoned\n'
         'from automerge_tpu import native\n'
         'assert native._LIB_PATH.endswith("_asan.so"), native._LIB_PATH\n'
         'native.lib()\n'
         'print("asan-check: instrumented library loaded")\n'],
        cwd=ROOT, env=env)
    if probe.returncode != 0:
        print('asan-check: FAIL -- could not load the instrumented '
              'library under the asan runtime')
        return 1

    cmd = [sys.executable, '-m', 'pytest', '-q', '-p', 'no:cacheprovider',
           *SUBSET]
    print('asan-check: running %s under ASan+UBSan' % ' '.join(SUBSET),
          file=sys.stderr)
    rc = subprocess.run(cmd, cwd=ROOT, env=env).returncode
    if rc != 0:
        print('asan-check: FAIL (rc=%d) -- a sanitizer report or test '
              'failure above' % rc)
        return 1
    print('asan-check: PASS')
    return 0


if __name__ == '__main__':
    sys.exit(main())
