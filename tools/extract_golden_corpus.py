"""Mechanically derives a golden (changes-in -> patch-out) corpus from the
reference's own backend test fixtures
(`/root/reference/test/backend_test.js`).

The reference suite can't run here (no Node), but its fixtures are plain
object literals driven through a tiny statement vocabulary
(`Backend.applyChanges` / `applyLocalChange` / `getPatch` +
`assert.deepEqual` / `assert.throws`).  This script translates each
`it(...)` block into a JSON test case whose EXPECTED patches come from the
reference's own assertions -- independent evidence, not our oracle's
output.  Cases using the high-level `Automerge.*` API are skipped and
listed in the corpus metadata.

Run:  python tools/extract_golden_corpus.py  (rewrites
tests/golden/backend_corpus.json; the replayer is
tests/test_golden_corpus.py)
"""

import json
import os
import re
import sys

REF = '/root/reference/test/backend_test.js'
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'tests', 'golden', 'backend_corpus.json')
ROOT_ID = '00000000-0000-0000-0000-000000000000'


class Date:
    """Stand-in for the fixtures' `new Date()`: a fixed timestamp keeps
    the corpus deterministic (the tests only ever use .getTime())."""

    def __init__(self, ms=1234567890123):
        self.ms = ms

    def getTime(self):
        return self.ms


def balanced_span(src, start, open_ch, close_ch):
    """End index (exclusive) of the bracketed span opening at `start`."""
    depth = 0
    in_str = None
    i = start
    while i < len(src):
        c = src[i]
        if in_str:
            if c == '\\':
                i += 2
                continue
            if c == in_str:
                in_str = None
        elif c in '\'"`':
            in_str = c
        elif c == open_ch:
            depth += 1
        elif c == close_ch:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    raise ValueError('unbalanced from %d' % start)


def js_expr_to_python(expr):
    """Translates the fixtures' JS expression subset to eval-able Python."""
    s = expr
    # stash template literals first: the object-literal regex passes below
    # must not touch the braces inside them
    stash = []

    def template(m):
        inner = m.group(1).replace('${', '{')
        stash.append("f'%s'" % inner)
        return '__TPL_%d__' % (len(stash) - 1)
    s = re.sub(r'`([^`]*)`', template, s)
    # new Date(...) -> Date(...)
    s = re.sub(r'\bnew\s+Date\b', 'Date', s)
    # shorthand properties: {actor, ...} / , actor} -> "actor": actor
    for _ in range(3):   # a few passes: adjacent shorthands share delims
        s = re.sub(r'([{,]\s*)([A-Za-z_]\w*)\s*(?=[,}])',
                   r'\1"\2": \2', s)
    # computed keys [expr]: -> sentinel (must survive key quoting)
    s = re.sub(r'([{,]\s*)\[([A-Za-z_]\w*)\]\s*:', r'\1__CK_\2__:', s)
    # quote remaining bare keys
    s = re.sub(r'([{,]\s*)([A-Za-z_]\w*)\s*:', r'\1"\2":', s)
    # un-sentinel computed keys back to variable references
    s = re.sub(r'"?__CK_([A-Za-z_]\w*)__"?\s*:', r'\1:', s)
    s = re.sub(r'\btrue\b', 'True', s)
    s = re.sub(r'\bfalse\b', 'False', s)
    s = re.sub(r'\bnull\b', 'None', s)
    for n, tpl in enumerate(stash):
        # the key-quoting pass may have wrapped a stashed token used in
        # key position; unwrap before substituting the f-string back
        s = s.replace('"__TPL_%d__"' % n, tpl).replace('__TPL_%d__' % n,
                                                       tpl)
    return s


def eval_js(expr, env):
    return eval(js_expr_to_python(expr), {'__builtins__': {}}, env)


def extract_case(name, body):
    """Translates one it-block body into a corpus case (or a skip
    reason)."""
    if 'Automerge.' in body:
        return None, 'uses the high-level Automerge API'
    uuid_n = [0]

    def uuid():
        uuid_n[0] += 1
        return 'uuid-%d' % uuid_n[0]

    env = {'ROOT_ID': ROOT_ID, 'uuid': uuid, 'Date': Date}
    patches = {}   # patch var -> step index
    steps = []

    i = 0
    while i < len(body):
        m = re.compile(r'\bconst\s+').search(body, i)
        stmt_m = re.compile(
            r'\b(?:const\s+\[\s*(\w+)\s*,\s*(\w+)\s*\]\s*=\s*)?'
            r'Backend\.(applyChanges|applyLocalChange)\s*\(').search(body, i)
        assert_m = re.compile(
            r'assert\.(deepEqual|throws)\s*\(').search(body, i)
        # next statement in source order
        # order matters on ties: a destructuring Backend call also matches
        # the bare-const pattern at the same offset
        candidates = [x for x in (stmt_m, assert_m, m) if x]
        if not candidates:
            break
        nxt = min(candidates, key=lambda x: x.start())

        if nxt is stmt_m:
            _state, patch_var, fn = stmt_m.group(1, 2, 3)
            astart = stmt_m.end() - 1
            aend = balanced_span(body, astart, '(', ')')
            args = body[astart + 1:aend - 1]
            # first arg is the state var; the rest is the payload expr
            payload = args.split(',', 1)[1].strip()
            value = eval_js(payload, env)
            if fn == 'applyChanges':
                steps.append({'op': 'apply_changes', 'changes': value})
            else:
                steps.append({'op': 'apply_local_change', 'request': value})
            if patch_var:
                patches[patch_var] = len(steps) - 1
            i = aend
        elif nxt is assert_m:
            kind = assert_m.group(1)
            astart = assert_m.end() - 1
            aend = balanced_span(body, astart, '(', ')')
            args = body[astart + 1:aend - 1].strip()
            if kind == 'throws':
                call = re.search(
                    r'Backend\.applyLocalChange\(\s*\w+\s*,\s*(\w+)\s*\)',
                    args)
                err = re.search(r'/(.+)/\s*$', args)
                if not call or not err:
                    return None, 'unsupported assert.throws form'
                steps.append({'op': 'apply_local_change_error',
                              'request': env[call.group(1)],
                              'error_match': err.group(1)})
            else:
                target, expected = args.split(',', 1)
                target = target.strip()
                value = eval_js(expected.strip(), env)
                gp = re.match(r'Backend\.getPatch\(\s*\w+\s*\)$', target)
                if gp:
                    steps.append({'op': 'get_patch', 'expected': value})
                elif target in patches:
                    steps[patches[target]]['expected'] = value
                else:
                    return None, 'assertion on unsupported target %r' % target
            i = aend
        else:   # const bindings (possibly several decls, incl. objects)
            line_end = m.end()
            # find statement end: scan until a newline at bracket depth 0
            depth = 0
            j = m.end()
            while j < len(body):
                c = body[j]
                if c in '([{':
                    j = balanced_span(body, j, c, {'(': ')', '[': ']',
                                                   '{': '}'}[c])
                    continue
                if c == '\n' and depth == 0:
                    # statement continues if the line ends with , or =
                    stripped = body[line_end:j].rstrip()
                    if stripped.endswith((',', '=', '[', '{', '(')):
                        j += 1
                        continue
                    break
                j += 1
            decls = body[m.end():j]
            # split top-level "name = expr" pairs on commas at depth 0
            parts = []
            depth = 0
            last = 0
            k = 0
            while k < len(decls):
                c = decls[k]
                if c in '([{':
                    k = balanced_span(decls, k, c, {'(': ')', '[': ']',
                                                    '{': '}'}[c])
                    continue
                if c == ',' and depth == 0 and \
                        re.match(r'\s*[A-Za-z_]\w*\s*=', decls[k + 1:]):
                    parts.append(decls[last:k])
                    last = k + 1
                k += 1
            parts.append(decls[last:])
            for part in parts:
                dm = re.match(r'\s*([A-Za-z_]\w*)\s*=\s*(.+)$', part,
                              re.DOTALL)
                if dm and 'Backend.' not in dm.group(2):
                    env[dm.group(1)] = eval_js(dm.group(2).strip(), env)
            i = j
    if not steps:
        return None, 'no recognized statements'
    return {'name': name, 'steps': steps}, None


def main():
    src = open(REF).read()
    cases = []
    skipped = []
    for m in re.finditer(r"it\('([^']+)',\s*\(\)\s*=>\s*", src):
        name = m.group(1)
        bstart = src.index('{', m.end() - 1)
        bend = balanced_span(src, bstart, '{', '}')
        body = src[bstart + 1:bend - 1]
        case, why = extract_case(name, body)
        if case:
            cases.append(case)
        else:
            skipped.append({'name': name, 'reason': why})
    corpus = {
        'source': 'test/backend_test.js (reference repo)',
        'note': 'expected patches are the reference suite\'s own '
                'assertions, mechanically translated; regenerate with '
                'tools/extract_golden_corpus.py',
        'skipped': skipped,
        'cases': cases,
    }
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, 'w') as f:
        json.dump(corpus, f, indent=1, sort_keys=False)
        f.write('\n')
    print('extracted %d cases (%d skipped) -> %s'
          % (len(cases), len(skipped), OUT))
    for s in skipped:
        print('  skipped: %(name)s (%(reason)s)' % s)
    return 0


if __name__ == '__main__':
    sys.exit(main())
