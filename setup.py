"""Builds the C++ host runtime into the wheel.

`pip install .` / `python -m build` compile native/core.cpp via the
project Makefile so the wheel ships libamtpu_core.so; the runtime loader
(automerge_tpu/native/__init__.py) also rebuilds on demand from a source
checkout, so development installs work without this hook.
"""

import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution


class BuildWithNative(build_py):
    def run(self):
        subprocess.run(['make', '-C', 'native'], check=True)
        super().run()


class BinaryDistribution(Distribution):
    # the wheel ships libamtpu_core.so: it is platform-specific, not
    # py3-none-any, even though no setuptools ext_modules are declared
    def has_ext_modules(self):
        return True


setup(cmdclass={'build_py': BuildWithNative},
      distclass=BinaryDistribution)
