"""Benchmark driver -- batched TPU backend vs single-thread scalar backend.

Headline config (BASELINE.json config 3, scaled by env): N Text docs, K
actors each, interleaved insert/delete ops, delivered as ONE causal
catch-up batch -- the "1M queued ops across 10k docs" north-star shape.

Methodology:
  * workload: per doc, actor a0 creates a Text object, then every actor
    appends/deletes characters over R rounds; all changes are queued and
    delivered as ONE msgpack payload to `NativeDocPool.apply_batch_bytes`
    -- the C++ host runtime + JAX device kernels, bytes in / patch bytes
    out, i.e. the split-deployment wire path the reference's
    frontend/backend protocol boundary ships.
  * baseline: the same changes through `automerge_tpu.backend` -- the
    single-threaded host backend whose semantics mirror the reference's
    Node.js backend (`/root/reference/backend/op_set.js`).  Node itself is
    not installed in this image, so this scalar path is the measured
    denominator; it is byte-compatible with the reference (see
    tests/test_backend.py golden cases).  Measured on a sampled doc subset,
    reported as per-op rate.
  * parity: native patches must equal oracle patches on the sampled docs.
  * warmup: the workload runs twice on throwaway pools -- the first pass
    pays jit compiles, the second settles dispatch/transfer paths -- so the
    timed run measures steady state; warmup seconds go to stderr.

Prints ONE json line to stdout:
  {"metric": ..., "value": ..., "unit": "ops/sec", "vs_baseline": ...}
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def env_int(name, default):
    return int(os.environ.get(name, default))


N_DOCS = env_int('AMTPU_BENCH_DOCS', 4096)
N_ACTORS = env_int('AMTPU_BENCH_ACTORS', 8)
N_ROUNDS = env_int('AMTPU_BENCH_ROUNDS', 2)
OPS_PER_CHANGE = env_int('AMTPU_BENCH_OPS_PER_CHANGE', 16)
ORACLE_DOCS = env_int('AMTPU_BENCH_ORACLE_DOCS', 48)
SEED = env_int('AMTPU_BENCH_SEED', 7)
N_SHARDS = env_int('AMTPU_BENCH_SHARDS', 10)


def make_doc_changes(doc, rng):
    """One doc's queued change history: create a Text object, then
    interleaved insert/delete rounds from N_ACTORS concurrent actors."""
    tid = 'text-%d' % doc
    changes = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': tid},
        {'action': 'ins', 'obj': tid, 'key': '_head', 'elem': 1},
        {'action': 'set', 'obj': tid, 'key': 'a0:1', 'value': 'x'},
        {'action': 'link', 'obj': ROOT_ID, 'key': 'text', 'value': tid}]}]
    max_elem = 1
    last = {}
    for r in range(1, N_ROUNDS + 1):
        for a in range(N_ACTORS):
            actor = 'a%d' % a
            seq = r + 1 if a == 0 else r
            ops = []
            for _ in range(OPS_PER_CHANGE // 2):
                max_elem += 1
                elem = max_elem
                prev = last.get(a) or 'a0:1'
                ops.append({'action': 'ins', 'obj': tid, 'key': prev,
                            'elem': elem})
                if rng.random() < 0.15 and a in last:
                    ops.append({'action': 'del', 'obj': tid, 'key': last[a]})
                else:
                    ops.append({'action': 'set', 'obj': tid,
                                'key': '%s:%d' % (actor, elem),
                                'value': chr(97 + elem % 26)})
                last[a] = '%s:%d' % (actor, elem)
            changes.append({'actor': actor, 'seq': seq, 'deps': {'a0': 1},
                            'ops': ops})
    return changes


def main():
    import msgpack

    from automerge_tpu import backend as Backend
    from automerge_tpu.native import NativeDocPool, ShardedNativePool

    rng = random.Random(SEED)
    batch = {d: make_doc_changes(d, rng) for d in range(N_DOCS)}
    total_ops = sum(len(c['ops']) for chs in batch.values() for c in chs)
    per_doc_ops = total_ops // N_DOCS
    print('workload: %d docs x %d ops = %d total ops'
          % (N_DOCS, per_doc_ops, total_ops), file=sys.stderr)

    # ---- baseline: single-thread scalar backend on a doc subset ----------
    oracle_docs = list(range(min(ORACLE_DOCS, N_DOCS)))
    oracle_states = {}
    t0 = time.perf_counter()
    for d in oracle_docs:
        state = Backend.init()
        state, _patch = Backend.apply_changes(state, batch[d])
        oracle_states[d] = state
    oracle_s = time.perf_counter() - t0
    oracle_ops = per_doc_ops * len(oracle_docs)
    oracle_rate = oracle_ops / oracle_s
    print('baseline (scalar backend, %d docs): %.2fs -> %.0f ops/sec'
          % (len(oracle_docs), oracle_s, oracle_rate), file=sys.stderr)

    # ---- wire payload (the split-deployment protocol form) ---------------
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    payload = msgpack.packb(keyed, use_bin_type=True)

    # ---- warmup: compile cache + transport steady state ------------------
    # two passes: the first pays jit compiles, the second settles dispatch
    # and transfer paths; the timed run then measures steady state
    t0 = time.perf_counter()
    ShardedNativePool(N_SHARDS).apply_batch_bytes(payload)
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    ShardedNativePool(N_SHARDS).apply_batch_bytes(payload)
    warm2_s = time.perf_counter() - t0
    print('warmup (incl. jit compile): %.2fs + %.2fs'
          % (warm_s, warm2_s), file=sys.stderr)

    # ---- timed runs: C++ host runtime + device kernels, bytes in/out -----
    # median of 3 fresh-pool runs (the device link is shared; single runs
    # jitter +-30%)
    import gc

    from automerge_tpu import trace
    times = []
    pool = None
    for run in range(3):
        trace.reset()
        pool = ShardedNativePool(N_SHARDS)
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        times.append(time.perf_counter() - t0)
        if trace.ENABLED and run == 0:
            print(trace.report(), file=sys.stderr)
        gc.collect()
    tpu_s = sorted(times)[1]
    tpu_rate = total_ops / tpu_s
    print('native pool runs: %s -> median %.0f ops/sec'
          % (['%.2fs' % t for t in times], tpu_rate), file=sys.stderr)

    # ---- parity ----------------------------------------------------------
    for d in oracle_docs:
        got = pool.get_patch(d)
        want = Backend.get_patch(oracle_states[d])
        if got != want:
            print('PARITY FAILURE on doc %d' % d, file=sys.stderr)
            print(json.dumps({'metric': 'text_catchup_ops_per_sec',
                              'value': 0.0, 'unit': 'ops/sec',
                              'vs_baseline': 0.0, 'parity': False}))
            return 1
    print('parity: ok (%d docs byte-identical)' % len(oracle_docs),
          file=sys.stderr)

    print(json.dumps({
        'metric': 'text_catchup_ops_per_sec',
        'value': round(tpu_rate, 1),
        'unit': 'ops/sec',
        'vs_baseline': round(tpu_rate / oracle_rate, 3),
    }))
    return 0


if __name__ == '__main__':
    sys.exit(main())
