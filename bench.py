"""Benchmark driver -- batched TPU backend vs single-thread scalar backend.

Covers all five BASELINE.json configs (select with --config N or
AMTPU_BENCH_CONFIG; default 3, the headline shape):

  1  single Text doc, 2 actors, sequential char inserts
  2  many Map docs, 8 concurrent actors, random key set ops
  3  many Text docs, concurrent actors, interleaved insert/delete (RGA
     stress) delivered as ONE causal catch-up batch -- the "1M queued ops
     across 10k docs" north-star shape
  4  Table docs: concurrent row add/update with nested Map row values
  5  Connection/DocSet sync: 64 replicas, 100k-op backlog, full causal
     catch-up (BatchedReplicaSet: device-planned gossip, bytes shipping)

Methodology (all configs):
  * baseline: the same workload through `automerge_tpu.backend` -- the
    single-threaded host backend whose semantics mirror the reference's
    Node.js backend (`/root/reference/backend/op_set.js`).  Node itself is
    not installed in this image, so this scalar path is the measured
    denominator; it is byte-compatible with the reference (see
    tests/test_backend.py golden cases).  Measured on a sampled doc
    subset, reported as per-op rate.
  * parity: native patches must equal oracle patches on >= 10% of docs
    (workloads apply changes in identical order, so patches are
    byte-identical, not just tree-equal).
  * warmup: the workload runs twice on throwaway pools (first pass pays
    jit compiles, second settles dispatch/transfer paths); timed result
    is the median of 3 fresh-pool runs (the tunneled device link jitters
    +-40% between windows).

Prints ONE json line to stdout:
  {"metric": ..., "value": ..., "unit": "ops/sec", "vs_baseline": ...}
"""

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# a CPU-only run (make check) must never touch a wedged device tunnel
from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu()


def probe_device(timeout_s=90):
    """The tunneled accelerator link can wedge indefinitely inside
    backend init (observed: make_c_api_client blocking >8 min).  Probe
    device enumeration in a THROWAWAY subprocess first; if it hangs or
    dies, pin this process to CPU so the bench always produces a result
    (a CPU number beats an rc=124 timeout artifact)."""
    import subprocess
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        return 'cpu (pinned by env)'
    try:
        out = subprocess.run(
            [sys.executable, '-c',
             'import jax; d = jax.devices(); print(d[0].platform, len(d))'],
            timeout=timeout_s, capture_output=True, text=True)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except subprocess.TimeoutExpired:
        pass
    print('device probe failed/hung -> falling back to CPU',
          file=sys.stderr)
    pin_cpu(force=True)
    return 'cpu (device link down)'

from automerge_tpu.utils.common import ROOT_ID  # noqa: E402


def env_int(name, default):
    return int(os.environ.get(name, default))


N_DOCS = env_int('AMTPU_BENCH_DOCS', 4096)
N_ACTORS = env_int('AMTPU_BENCH_ACTORS', 8)
N_ROUNDS = env_int('AMTPU_BENCH_ROUNDS', 2)
OPS_PER_CHANGE = env_int('AMTPU_BENCH_OPS_PER_CHANGE', 16)
ORACLE_DOCS = env_int('AMTPU_BENCH_ORACLE_DOCS', 0)   # 0 = 10% of docs
SEED = env_int('AMTPU_BENCH_SEED', 7)
# 0 = let ShardedNativePool pick its mode-aware default (20 for the
# 1-core pipeline, one per core for threads -- the 20-shard rationale is
# specific to pipeline overlap and would oversubscribe threads mode)
N_SHARDS = env_int('AMTPU_BENCH_SHARDS', 0)

# Every multiplier this harness reports divides by the repo's own
# single-thread Python scalar oracle (`automerge_tpu.backend`), byte-
# compatible with the reference backend.  The north-star target
# (BASELINE.json) names the Node.js backend as the denominator; Node is
# not installed in this image, so the oracle is the stand-in -- named
# in every JSON line so no multiplier is quoted without its
# denominator (VERDICT r4 #4).
BASELINE_NAME = 'python-scalar-oracle'


# ---------------------------------------------------------------------------
# workload builders: {doc: [change...]} per config
# ---------------------------------------------------------------------------

def _text_doc_changes(doc, rng, n_actors, n_rounds, ops_per_change):
    """Interleaved concurrent Text insert/delete (config 3 shape); the
    shared generator with bench's rng delete policy (the rng draw happens
    for every slot, keeping the stream identical to earlier rounds)."""
    from automerge_tpu.parallel.mesh_encode import text_doc_changes
    return text_doc_changes(
        'text-%d' % doc, n_actors, n_rounds, ops_per_change,
        lambda i, a, has: rng.random() < 0.15 and has)


def build_config_1(rng):
    """Single Text doc, 2 actors, sequential char inserts."""
    chars = env_int('AMTPU_BENCH_C1_CHARS', 10000)
    per_change = 50
    tid = 'text-0'
    changes = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': tid},
        {'action': 'link', 'obj': ROOT_ID, 'key': 'text', 'value': tid}]}]
    seqs = {'a0': 1, 'a1': 0}
    prev = '_head'
    elem = 0
    for start in range(0, chars, per_change):
        actor = 'a%d' % ((start // per_change) % 2)
        ops = []
        for _ in range(min(per_change, chars - start)):
            elem += 1
            ops.append({'action': 'ins', 'obj': tid, 'key': prev,
                        'elem': elem})
            ops.append({'action': 'set', 'obj': tid,
                        'key': '%s:%d' % (actor, elem),
                        'value': chr(97 + elem % 26)})
            prev = '%s:%d' % (actor, elem)
        seqs[actor] += 1
        deps = {a: s for a, s in seqs.items() if a != actor and s}
        changes.append({'actor': actor, 'seq': seqs[actor], 'deps': deps,
                        'ops': ops})
    return {0: changes}, 'text_single_doc_ops_per_sec'


def build_config_2(rng):
    """Map docs, 8 concurrent actors, random key set ops (this Automerge
    version has no Counter CRDT; "inc" models as read-modify-write set,
    see BASELINE.md)."""
    docs = env_int('AMTPU_BENCH_C2_DOCS', 1024)
    rounds = env_int('AMTPU_BENCH_C2_ROUNDS', 8)
    batch = {}
    for d in range(docs):
        changes = []
        for r in range(1, rounds + 1):
            for a in range(N_ACTORS):
                actor = 'a%d' % a
                ops = []
                # distinct keys per change: the reference frontend dedupes
                # assignments per (obj, key) within one change
                # (ensureSingleAssignment, frontend/index.js:53), so real
                # change streams never assign a key twice
                for key_n in rng.sample(range(max(32, OPS_PER_CHANGE)),
                                         OPS_PER_CHANGE):
                    key = 'k%d' % key_n
                    if rng.random() < 0.1:
                        ops.append({'action': 'del', 'obj': ROOT_ID,
                                    'key': key})
                    elif rng.random() < 0.1:
                        ops.append({'action': 'set', 'obj': ROOT_ID,
                                    'key': key, 'value': r * 1000 + a,
                                    'datatype': 'timestamp'})
                    else:
                        ops.append({'action': 'set', 'obj': ROOT_ID,
                                    'key': key, 'value': r * 1000 + a})
                changes.append({'actor': actor, 'seq': r, 'deps': {},
                                'ops': ops})
        batch[d] = changes
    return batch, 'map_concurrent_ops_per_sec'


def build_config_3(rng):
    batch = {d: _text_doc_changes(d, rng, N_ACTORS, N_ROUNDS,
                                  OPS_PER_CHANGE)
             for d in range(N_DOCS)}
    return batch, 'text_catchup_ops_per_sec'


def build_config_4(rng):
    """Table docs: concurrent row add/update, nested Map row values
    (reference Table semantics: frontend/table.js:26-196; a row add is
    makeMap + field sets + link into the table keyed by row id)."""
    docs = env_int('AMTPU_BENCH_C4_DOCS', 1024)
    rows_per_actor = env_int('AMTPU_BENCH_C4_ROWS', 16)
    batch = {}
    for d in range(docs):
        table = 'table-%d' % d
        changes = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeTable', 'obj': table},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'rows',
             'value': table}]}]
        row_ids = []
        for a in range(N_ACTORS):
            actor = 'a%d' % a
            seq = 2 if a == 0 else 1
            ops = []
            for i in range(rows_per_actor):
                row = 'row-%d-%d-%d' % (d, a, i)
                ops.extend([
                    {'action': 'makeMap', 'obj': row},
                    {'action': 'set', 'obj': row, 'key': 'name',
                     'value': 'r%d' % i},
                    {'action': 'set', 'obj': row, 'key': 'n',
                     'value': i * a},
                    {'action': 'link', 'obj': table, 'key': row,
                     'value': row}])
                row_ids.append(row)
            changes.append({'actor': actor, 'seq': seq,
                            'deps': {'a0': 1}, 'ops': ops})
        # concurrent updates of random existing rows
        for a in range(N_ACTORS):
            actor = 'a%d' % a
            seq = 3 if a == 0 else 2
            ops = []
            for _ in range(rows_per_actor):
                row = row_ids[rng.randrange(len(row_ids))]
                ops.append({'action': 'set', 'obj': row, 'key': 'n',
                            'value': rng.randrange(1000)})
            changes.append({'actor': actor, 'seq': seq,
                            'deps': {'a%d' % b: (2 if b == 0 else 1)
                                     for b in range(N_ACTORS)},
                            'ops': ops})
        batch[d] = changes
    return batch, 'table_rows_ops_per_sec'


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def _alt_mode_env(alt):
    """Context manager flipping AMTPU_HOST_FULL for a sibling-mode
    measurement, restoring the caller's env on exit."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        prior = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_FULL'] = '0' if alt == 'kernel' else '1'
        try:
            yield
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_FULL', None)
            else:
                os.environ['AMTPU_HOST_FULL'] = prior
    return cm()


def _alt_block(rate, oracle_rate, stats, ok):
    """Sibling-mode result block; parity failure zeroes the numbers so
    the regression is loud in the artifact (and main() fails the rc)."""
    block = {'value': round(rate, 1),
             'vs_baseline': round(rate / oracle_rate, 3)}
    block.update(stats)
    if not ok:
        block.update(parity=False, value=0.0, vs_baseline=0.0)
    return block


def _current_mode():
    """Name of the execution mode the pools will resolve right now
    (per-batch knobs + platform default)."""
    from automerge_tpu.native import _host_full_on
    res = os.environ.get('AMTPU_RESIDENT')
    if res not in (None, '', '0'):
        return 'resident'
    return 'host_full' if _host_full_on() else 'kernel'


def _measure_mode(make_pool, payload, total_ops, label):
    """Warmup + 3 timed runs + fallback counters + one synchronous
    device-time pass for whatever execution mode the current env
    resolves to.  Returns (median_rate, pool_from_last_run, stats)."""
    import gc

    from automerge_tpu import telemetry, trace

    # ---- warmup ----------------------------------------------------------
    t0 = time.perf_counter()
    make_pool().apply_batch_bytes(payload)
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    make_pool().apply_batch_bytes(payload)
    warm2_s = time.perf_counter() - t0
    print('[%s] warmup (incl. jit compile): %.2fs + %.2fs'
          % (label, warm_s, warm2_s), file=sys.stderr)

    # ---- timed runs ------------------------------------------------------
    times = []
    pool = None
    # devtime's per-dispatch block_until_ready serializes the pipeline;
    # an externally-exported AMTPU_DEVTIME=1 must not poison the timed
    # runs (restored for the dedicated pass below)
    devtime_prior = os.environ.pop('AMTPU_DEVTIME', None)
    # one measurement window per mode: flat metrics AND the registry
    # reset together, so the telemetry block captured below describes
    # exactly these 3 timed runs (not warmups, parity checks, or a
    # sibling mode's passes)
    trace.metrics_reset()
    telemetry.registry.reset()
    for run in range(3):
        trace.reset()
        pool = make_pool()
        t0 = time.perf_counter()
        pool.apply_batch_bytes(payload)
        times.append(time.perf_counter() - t0)
        if trace.ENABLED and run == 0:
            print(trace.report(), file=sys.stderr)
        gc.collect()
    med_s = sorted(times)[1]
    rate = total_ops / med_s
    print('[%s] pool runs: %s -> median %.0f ops/sec'
          % (label, ['%.2fs' % t for t in times], rate), file=sys.stderr)
    # oracle-fallback visibility: counts accumulated over the 3 timed
    # runs (a degraded run must be visible without AMTPU_TRACE)
    fallbacks = {k.split('.', 1)[1]: int(v) for k, v in
                 trace.metrics_snapshot().items()
                 if k.startswith('fallback.')}
    print('[%s] fallbacks (3 runs): %s' % (label, fallbacks or 'none'),
          file=sys.stderr)
    # captured HERE, before the devtime pass resets the flat metrics:
    # the embedded block describes the timed runs, so a degraded run's
    # fallback counts survive into the artifact
    telemetry_block = telemetry.bench_block()

    # ---- device-time pass ------------------------------------------------
    # One EXTRA pass with synchronous per-dispatch timing: every device
    # dispatch blocks until ready, so kernel time is measured, not
    # inferred.  Serializing the pipeline perturbs throughput, which is
    # why this runs outside the timed runs.
    trace.metrics_reset()
    os.environ['AMTPU_DEVTIME'] = '1'
    try:
        dev_pool = make_pool()       # pool build outside the wall clock,
        t0 = time.perf_counter()     # same as the timed runs
        dev_pool.apply_batch_bytes(payload)
        dev_wall = time.perf_counter() - t0
    finally:
        if devtime_prior is None:
            os.environ.pop('AMTPU_DEVTIME', None)
        else:
            os.environ['AMTPU_DEVTIME'] = devtime_prior
    m = trace.metrics_snapshot()
    device = {
        'sync_dispatch_s': round(m.get('device.dispatch_sync_s', 0.0), 4),
        'dispatches': int(m.get('device.dispatches', 0)),
        'sync_wall_s': round(dev_wall, 4),
        'busy_frac': round(m.get('device.dispatch_sync_s', 0.0) /
                           dev_wall, 4) if dev_wall else 0.0,
    }
    if m.get('resident.dispatches'):
        device['resident_dispatches'] = int(m['resident.dispatches'])
    print('[%s] device (sync pass): %.3fs kernels / %.3fs wall = %.1f%% '
          'busy, %d dispatches' % (label, device['sync_dispatch_s'],
                                   dev_wall, 100 * device['busy_frac'],
                                   device['dispatches']), file=sys.stderr)
    telemetry_block['device_s'] = device['sync_dispatch_s']
    telemetry_block['device_dispatches'] = device['dispatches']

    # ---- phase pass ------------------------------------------------------
    # One extra TRACED run: per-phase seconds land in the BENCH line
    # machine-readable (the quickbench --phases table), so phase-share
    # claims -- device.collect above all -- are attributable from the
    # artifact alone (ISSUE 6).  Runs outside the timed window because
    # tracing costs a few percent; `collect_share` is pre-divided
    # against the summed native batch time, the share basis the
    # quickbench table prints.
    was_enabled = telemetry.enabled()
    telemetry.reset_all()
    telemetry.enable()
    try:
        ph_pool = make_pool()
        t0 = time.perf_counter()
        ph_pool.apply_batch_bytes(payload)
        ph_wall = time.perf_counter() - t0
        ph_block = telemetry.bench_block()
    finally:
        if not was_enabled:
            telemetry.disable()
        telemetry.reset_all()
    telemetry_block['phases'] = ph_block.get('phases') or {}
    telemetry_block['phase_wall_s'] = round(ph_wall, 4)
    share, _coll, _basis = telemetry.collect_share(ph_block)
    telemetry_block['collect_share'] = round(share, 4)
    print('[%s] phase pass: %.2fs wall, device.collect share %.1f%%'
          % (label, ph_wall, 100 * telemetry_block['collect_share']),
          file=sys.stderr)
    return rate, pool, {'fallbacks': fallbacks, 'device': device,
                        'telemetry': telemetry_block}


def run_batch_config(build, rng, both_modes=True):
    """Shared driver for configs 1-4: one causal catch-up batch.

    Measures the platform-default execution mode as the headline AND
    (both_modes) the opposite mode as a sibling block in the same JSON
    line -- the kernel path (AMTPU_HOST_FULL=0) when the default is the
    full host path, the host path when the default is the kernels -- so
    a regression in either mode fails loudly in every artifact
    (VERDICT r4 #1)."""
    import msgpack

    from automerge_tpu import backend as Backend
    from automerge_tpu.native import NativeDocPool, ShardedNativePool

    batch, metric = build(rng)
    doc_ids = list(batch)
    total_ops = sum(len(c['ops']) for chs in batch.values() for c in chs)
    per_doc_ops = {d: sum(len(c['ops']) for c in batch[d])
                   for d in doc_ids}
    print('workload: %d docs, %d total ops'
          % (len(doc_ids), total_ops), file=sys.stderr)

    def make_pool():
        # shard count resolves per mode: host_full wants 1, the kernel
        # pipeline wants overlap granularity (default 20)
        if N_SHARDS:
            n = min(N_SHARDS, len(doc_ids))
        else:
            n = min(ShardedNativePool.default_shards(), len(doc_ids))
        return ShardedNativePool(n) if n > 1 else NativeDocPool()

    # ---- baseline: single-thread scalar backend on a >=10% subset -------
    # median of 3 passes: the shared host core's speed wobbles between
    # windows, and a slow scalar window inflates vs_baseline dishonestly
    n_oracle = ORACLE_DOCS or max(1, len(doc_ids) // 10)
    oracle_docs = doc_ids[:min(n_oracle, len(doc_ids))]
    oracle_times = []
    for _ in range(3):
        oracle_states = {}
        t0 = time.perf_counter()
        for d in oracle_docs:
            state = Backend.init()
            state, _patch = Backend.apply_changes(state, batch[d])
            oracle_states[d] = state
        oracle_times.append(time.perf_counter() - t0)
    oracle_s = sorted(oracle_times)[1]
    oracle_ops = sum(per_doc_ops[d] for d in oracle_docs)
    oracle_rate = oracle_ops / oracle_s
    print('baseline (scalar backend, %d docs): %s -> median %.0f ops/sec'
          % (len(oracle_docs), ['%.2fs' % t for t in oracle_times],
             oracle_rate), file=sys.stderr)

    # ---- wire payload (the split-deployment protocol form) ---------------
    keyed = {NativeDocPool._doc_key(d): chs for d, chs in batch.items()}
    payload = msgpack.packb(keyed, use_bin_type=True)

    def parity_ok(pool, label):
        for d in oracle_docs:
            if pool.get_patch(d) != Backend.get_patch(oracle_states[d]):
                print('[%s] PARITY FAILURE on doc %r' % (label, d),
                      file=sys.stderr)
                return False
        print('[%s] parity: ok (%d docs byte-identical)'
              % (label, len(oracle_docs)), file=sys.stderr)
        return True

    # ---- headline: the platform-default mode -----------------------------
    mode = _current_mode()
    rate, pool, stats = _measure_mode(make_pool, payload, total_ops, mode)
    if not parity_ok(pool, mode):
        return {'metric': metric, 'value': 0.0, 'unit': 'ops/sec',
                'vs_baseline': 0.0, 'baseline': BASELINE_NAME,
                'mode': mode, 'parity': False}
    result = {'metric': metric, 'value': round(rate, 1),
              'unit': 'ops/sec',
              'vs_baseline': round(rate / oracle_rate, 3),
              'baseline': BASELINE_NAME, 'mode': mode}
    result.update(stats)

    # ---- sibling: the opposite execution mode ----------------------------
    # resident mode can't be entered here (AMTPU_RESIDENT latches in the
    # native lib's static init at the first batch above) -- `--mode
    # resident` / `--all` run it in a fresh process instead
    if both_modes and mode in ('host_full', 'kernel'):
        alt = 'kernel' if mode == 'host_full' else 'host_full'
        with _alt_mode_env(alt):
            arate, apool, astats = _measure_mode(
                make_pool, payload, total_ops, alt)
            result['%s_path' % alt] = _alt_block(
                arate, oracle_rate, astats, parity_ok(apool, alt))
    return result


def run_config_5(rng, both_modes=True):
    """64 replicas, ~100k-op backlog, full causal catch-up.  The measured
    rate counts op-APPLICATIONS (every replica ingests every foreign op --
    the work a full catch-up performs, identical to what the reference's
    64 pairwise Connections would do)."""
    from automerge_tpu import backend as Backend
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.sync.replica_set import BatchedReplicaSet, \
        patch_to_tree

    n_replicas = env_int('AMTPU_BENCH_C5_REPLICAS', 64)
    n_docs = env_int('AMTPU_BENCH_C5_DOCS', 8)
    n_changes = env_int('AMTPU_BENCH_C5_CHANGES', 13)
    ops_per_change = env_int('AMTPU_BENCH_C5_OPS', 15)

    # backlog: each replica authors one actor's stream per doc.  Keys are
    # distinct per change (the reference frontend dedupes assignments per
    # change, ensureSingleAssignment): same-change duplicate assigns have
    # history-dependent conflict-tie order in the reference itself, so no
    # realistic change stream contains them.
    by_replica = [dict() for _ in range(n_replicas)]
    union = {d: [] for d in range(n_docs)}
    key_space = range(max(64, ops_per_change))
    for d in range(n_docs):
        for r in range(n_replicas):
            actor = 'a%03d' % r
            for seq in range(1, n_changes + 1):
                ops = [{'action': 'set', 'obj': ROOT_ID,
                        'key': 'k%d' % k,
                        'value': '%s-%d-%d' % (actor, seq, i)}
                       for i, k in enumerate(
                           rng.sample(key_space, ops_per_change))]
                ch = {'actor': actor, 'seq': seq, 'deps': {}, 'ops': ops}
                by_replica[r].setdefault(d, []).append(ch)
                union[d].append(ch)
    backlog_ops = sum(len(c['ops']) for chs in union.values()
                      for c in chs)
    # full catch-up applies every foreign op at every replica
    total_applications = backlog_ops * (n_replicas - 1)
    print('workload: %d replicas x %d docs, backlog %d ops -> %d '
          'op-applications' % (n_replicas, n_docs, backlog_ops,
                               total_applications), file=sys.stderr)

    # ---- baseline: scalar backend ingesting one doc's union --------------
    oracle_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        state = Backend.init()
        state, _ = Backend.apply_changes(state, union[0])
        oracle_times.append(time.perf_counter() - t0)
    oracle_s = sorted(oracle_times)[1]
    oracle_rate = len(union[0]) * ops_per_change / oracle_s
    print('baseline (scalar, 1-doc union): %s -> median %.0f ops/sec'
          % (['%.2fs' % t for t in oracle_times], oracle_rate),
          file=sys.stderr)

    def load_set():
        rs = BatchedReplicaSet(n_replicas, pool_factory=NativeDocPool)
        for r, by_doc in enumerate(by_replica):
            rs.apply_batch(r, by_doc)
        return rs

    # warmup (jit compiles for plan + apply kernels)
    t0 = time.perf_counter()
    load_set().catch_up()
    print('warmup: %.2fs' % (time.perf_counter() - t0), file=sys.stderr)

    from automerge_tpu import trace

    def measure_catchup(label):
        times = []
        rs = None
        fallbacks = {}
        rounds = None
        for _ in range(3):
            rs = load_set()
            # metric window covers ONLY the measured catch-up --
            # fallbacks during the untimed backlog load must not flag
            # the run
            trace.metrics_reset()
            t0 = time.perf_counter()
            rounds = rs.catch_up()
            times.append(time.perf_counter() - t0)
            for k, v in trace.metrics_snapshot().items():
                if k.startswith('fallback.'):
                    key = k.split('.', 1)[1]
                    fallbacks[key] = fallbacks.get(key, 0) + int(v)
        sync_s = sorted(times)[1]
        rate = total_applications / sync_s
        print('[%s] fallbacks (3 runs): %s' % (label, fallbacks or 'none'),
              file=sys.stderr)
        print('[%s] catch-up runs: %s (rounds: %s) -> median %.0f ops/sec'
              % (label, ['%.2fs' % t for t in times], rounds, rate),
              file=sys.stderr)
        return rate, rs, fallbacks

    def parity_ok(rs, label):
        # every replica's tree equals the oracle union
        if not rs.converged():
            return False
        for d in range(n_docs):
            patch = rs.assert_identical(d)
            st = Backend.init()
            st, _ = Backend.apply_changes(st, union[d])
            want = Backend.get_patch(st)
            if patch['clock'] != want['clock'] or \
                    patch_to_tree(patch) != patch_to_tree(want):
                print('[%s] PARITY FAILURE on doc %d' % (label, d),
                      file=sys.stderr)
                return False
        print('[%s] parity: ok (%d docs, %d replicas convergent + '
              'oracle-equal)' % (label, n_docs, n_replicas),
              file=sys.stderr)
        return True

    mode = _current_mode()
    rate, rs, fallbacks = measure_catchup(mode)
    if not parity_ok(rs, mode):
        return {'metric': 'replica_catchup_ops_per_sec', 'value': 0.0,
                'unit': 'ops/sec', 'vs_baseline': 0.0,
                'baseline': BASELINE_NAME, 'mode': mode, 'parity': False}
    result = {'metric': 'replica_catchup_ops_per_sec',
              'value': round(rate, 1), 'unit': 'ops/sec',
              'vs_baseline': round(rate / oracle_rate, 3),
              'baseline': BASELINE_NAME, 'mode': mode,
              'fallbacks': fallbacks}

    if both_modes and mode in ('host_full', 'kernel'):
        alt = 'kernel' if mode == 'host_full' else 'host_full'
        with _alt_mode_env(alt):
            arate, ars, afb = measure_catchup(alt)
            result['%s_path' % alt] = _alt_block(
                arate, oracle_rate, {'fallbacks': afb},
                parity_ok(ars, alt))
    return result


def run_config_1_mesh(rng):
    """Config 1 through the MESH path (the sequence-parallel showcase):
    the single long Text doc is mesh-encoded (arena columns laid out for
    sp sharding) and resolved by the shard_map step on a 1-chip mesh --
    the same compiled path dryrun_multichip validates on N virtual
    devices.  Parity pins the kernel outputs against the pool's public
    patches."""
    from functools import partial

    import jax
    import numpy as np

    from automerge_tpu import backend as Backend
    from automerge_tpu.parallel import mesh as M
    from automerge_tpu.parallel import mesh_encode

    workload, _ = build_config_1(rng)
    total_ops = sum(len(c['ops']) for chs in workload.values()
                    for c in chs)
    print('workload: 1 doc, %d ops (mesh/sp path)' % total_ops,
          file=sys.stderr)

    oracle_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        state = Backend.init()
        state, _p = Backend.apply_changes(state, workload[0])
        oracle_times.append(time.perf_counter() - t0)
    oracle_s = sorted(oracle_times)[1]
    oracle_rate = total_ops / oracle_s
    print('baseline (scalar backend): %s -> median %.0f ops/sec'
          % (['%.2fs' % t for t in oracle_times], oracle_rate),
          file=sys.stderr)

    batch, meta = mesh_encode.encode_batch(workload, sp=1)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    mesh = M.make_mesh(1, sp=1)
    step = M.build_sharded_step(mesh, n_linearize_iters=n_iters)
    sharded = M.shard_batch(mesh, batch)

    t0 = time.perf_counter()
    out = step(sharded)
    jax.block_until_ready(out)
    print('warmup (incl. jit compile): %.2fs'
          % (time.perf_counter() - t0), file=sys.stderr)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = step(sharded)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    mesh_s = sorted(times)[1]
    rate = total_ops / mesh_s
    print('mesh-step runs: %s -> median %.0f ops/sec'
          % (['%.2fs' % t for t in times], rate), file=sys.stderr)

    out = {k: np.asarray(v) for k, v in out.items()}
    try:
        mesh_encode.verify_against_pool(workload, meta, out)
    except AssertionError as e:
        print('PARITY FAILURE: %s' % e, file=sys.stderr)
        return {'metric': 'text_single_doc_mesh_ops_per_sec', 'value': 0.0,
                'unit': 'ops/sec', 'vs_baseline': 0.0,
                'baseline': BASELINE_NAME, 'mode': 'mesh', 'parity': False}
    print('parity: ok (kernel outputs match pool patches)',
          file=sys.stderr)
    return {'metric': 'text_single_doc_mesh_ops_per_sec',
            'value': round(rate, 1), 'unit': 'ops/sec',
            'vs_baseline': round(rate / oracle_rate, 3),
            'baseline': BASELINE_NAME, 'mode': 'mesh'}


def _scaling_workload_payload(n_docs):
    """MULTICHIP scaling workload as a wire payload (the one builder
    lives in mesh_encode.scaling_workload, shared with the mesh-check
    gate and the dryrun)."""
    import msgpack

    from automerge_tpu.parallel import mesh_encode
    docs = mesh_encode.scaling_workload(n_docs)
    total_ops = sum(len(c['ops']) for chs in docs.values() for c in chs)
    return msgpack.packb(docs, use_bin_type=True), total_ops


def run_multichip_child(dp):
    """One MULTICHIP line: the scaling workload through the first-class
    mesh pool mode (`make_pool` under AMTPU_MESH=dp, exported by the
    parent together with the matching device count) on the full
    `_measure_mode` protocol -- warmup, 3 fresh-pool timed steps,
    device-time pass, TRACED phase pass."""
    import jax

    from automerge_tpu.native import make_pool
    n_docs = env_int('AMTPU_MC_DOCS', 2048)
    payload, total_ops = _scaling_workload_payload(n_docs)
    if os.environ.get('AMTPU_MC_LIGHT'):
        # light re-measurement round (parent interleaves these across
        # the dp ladder to cancel host drift): warm + 3 timed steps,
        # no device/phase passes
        make_pool().apply_batch_bytes(payload)
        walls = []
        for _ in range(3):
            pool = make_pool()
            t0 = time.perf_counter()
            pool.apply_batch_bytes(payload)
            walls.append(time.perf_counter() - t0)
        med = sorted(walls)[1]
        print(json.dumps({'metric': 'multichip_pool_ops_per_sec',
                          'light': True, 'dp': dp,
                          'value': round(total_ops / med, 1),
                          'step_wall_s': round(med, 4)}))
        return 0
    rate, _pool, stats = _measure_mode(make_pool, payload, total_ops,
                                       'mesh dp=%d' % dp)
    result = {
        'metric': 'multichip_pool_ops_per_sec',
        'value': round(rate, 1), 'unit': 'ops/sec', 'mode': 'mesh',
        'baseline': 'mesh_dp1',       # parent fills vs_baseline from dp=1
        'dp': dp, 'sp': 1,
        'devices': len(jax.devices()), 'cores': os.cpu_count(),
        'docs': n_docs, 'ops': total_ops,
        'step_wall_s': round(total_ops / rate, 4) if rate else 0.0,
        'fallbacks': stats['fallbacks'],
        'device': stats['device'],
        'telemetry': stats['telemetry'],
    }
    print(json.dumps(result))
    return 0


def run_multichip_sp_child(sp_min):
    """sp-crossover probe arm: steady-state resident edit batches on one
    long Text doc per arena size, with the sp fence pinned by the parent
    (AMTPU_MESH_SP_MIN=16 -> sharded arm, huge -> dp-only arm).  Prints
    {'rows': {elems: median_edit_s}, 'sp_engaged': ...}."""
    from automerge_tpu import telemetry
    from automerge_tpu.native import NativeDocPool
    sizes = [int(s) for s in os.environ.get(
        'AMTPU_MC_SP_SIZES', '8192,32768,131072,262144').split(',')]
    pool = NativeDocPool()
    telemetry.metrics_reset()
    rows = {}
    for n_elems in sizes:
        doc = 'sp-%d' % n_elems
        chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeText', 'obj': 't'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
             'value': 't'}]}]
        prev, e, ops = '_head', 0, []
        for _ in range(n_elems):
            e += 1
            ops.append({'action': 'ins', 'obj': 't', 'key': prev,
                        'elem': e})
            ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                        'value': 'x'})
            prev = 'a0:%d' % e
        chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
        pool.apply_changes(doc, chs)
        seq = 2
        times = []
        for k in range(6):
            seq += 1
            e += 1
            edit = [{'actor': 'a0', 'seq': seq, 'deps': {}, 'ops': [
                {'action': 'ins', 'obj': 't', 'key': prev, 'elem': e},
                {'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                 'value': 'y'}]}]
            prev = 'a0:%d' % e
            t0 = time.perf_counter()
            pool.apply_changes(doc, edit)
            if k:                          # first edit pays jit compile
                times.append(time.perf_counter() - t0)
        rows[n_elems] = round(sorted(times)[len(times) // 2], 4)
    snap = telemetry.metrics_snapshot()
    print(json.dumps({'rows': rows, 'sp_min': sp_min,
                      'sp_engaged': int(snap.get('mesh.sp_engaged', 0)),
                      'sp_fenced': int(snap.get('mesh.sp_fenced', 0))}))
    return 0


def run_multichip(args):
    """--multichip: the MULTICHIP artifact through the first-class pool
    mode (ISSUE 7 satellite 2) -- retires the dryrun tail-scrape.  One
    fresh subprocess per dp (the device count, AMTPU_MESH topology, and
    resident knobs all latch at first backend init), plus the two-arm
    sp-crossover probe that justifies the sp fence
    (resident.SP_CROSSOVER_ELEMS)."""
    import re as _re
    import subprocess

    def spawn(extra_args, n_devices, extra_env):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        flags = _re.sub(r'--xla_force_host_platform_device_count=\d+',
                        '', env.get('XLA_FLAGS', ''))
        env['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_'
                            'device_count=%d' % n_devices).strip()
        env.update(extra_env)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + extra_args,
            env=env, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        line = (proc.stdout.strip().splitlines() or ['{}'])[-1]
        try:
            rec = json.loads(line)
        except ValueError:
            rec = {'error': 'rc=%d no-json' % proc.returncode}
        if proc.returncode != 0:
            rec.setdefault('error', 'rc=%d' % proc.returncode)
        return rec

    lines = []
    env_dp = os.environ.get('AMTPU_MULTICHIP_DP')
    dps = [int(d) for d in (env_dp or '1,2,4,8').split(',')]
    if env_dp is None:
        # the dp axis parallelizes HOST work on this CPU stand-in, so
        # chips past the physical-core ceiling only add thread
        # contention and per-chip fixed cost (measured: dp=8 on 2 cores
        # regresses below dp=4); the default ladder stops where the
        # host can still show real scaling.  Real multi-chip hardware
        # runs the full ladder (AMTPU_MULTICHIP_DP=1,2,4,8).
        cap = max(4, 2 * (os.cpu_count() or 1))
        dropped = [d for d in dps if d > cap]
        if dropped:
            print('multichip: dp %s dropped (past the %d-core host\'s '
                  'x%d parallelism ceiling; set AMTPU_MULTICHIP_DP to '
                  'force)' % (dropped, os.cpu_count() or 1, cap),
                  file=sys.stderr)
        dps = [d for d in dps if d <= cap]
    # round 0: one FULL child per dp (device/phase passes, telemetry-
    # rich line); rounds 1..R-1: LIGHT children interleaved across the
    # ladder so minute-scale host drift hits every dp equally.  The
    # line's headline value is the best round (noise on a shared box
    # only ever adds time; every round is kept in `round_values`).
    rounds = env_int('AMTPU_MULTICHIP_ROUNDS', 3)
    by_dp = {}
    for dp in dps:
        print('== multichip dp=%d ==' % dp, file=sys.stderr)
        rec = spawn(['--multichip-child', str(dp)], dp,
                    {'AMTPU_MESH': str(dp)})
        rec['round_values'] = [rec.get('value', 0.0)]
        by_dp[dp] = rec
        lines.append(rec)
    for r in range(1, rounds):
        for dp in dps:
            print('== multichip dp=%d (light round %d) ==' % (dp, r),
                  file=sys.stderr)
            light = spawn(['--multichip-child', str(dp)], dp,
                          {'AMTPU_MESH': str(dp), 'AMTPU_MC_LIGHT': '1'})
            if light.get('value'):
                by_dp[dp]['round_values'].append(light['value'])
    for dp, rec in by_dp.items():
        # a failed full child has no 'ops' (and no meaning to update);
        # its light rounds still print, but the error line stands
        if rec.get('round_values') and rec.get('ops'):
            best = max(rec['round_values'])
            if best > rec.get('value', 0.0):
                rec['value'] = best
                rec['step_wall_s'] = round(rec['ops'] / best, 4)
    base = next((r for r in lines if r.get('dp') == 1 and r.get('value')),
                None)
    for rec in lines:
        if base and rec.get('value'):
            rec['vs_baseline'] = round(rec['value'] / base['value'], 3)
        print(json.dumps({k: rec[k] for k in
                          ('metric', 'value', 'dp', 'vs_baseline',
                           'round_values') if k in rec}))

    # sp-crossover probe: sharded arm vs dp-only arm, 2 devices each
    print('== multichip sp probe ==', file=sys.stderr)
    sharded = spawn(['--multichip-sp-child', '16'], 2,
                    {'AMTPU_RESIDENT': '1', 'AMTPU_RESIDENT_MIN': '16',
                     'AMTPU_MESH_SP_MIN': '16'})
    fenced = spawn(['--multichip-sp-child', '1073741824'], 2,
                   {'AMTPU_RESIDENT': '1', 'AMTPU_RESIDENT_MIN': '16',
                    'AMTPU_MESH_SP_MIN': '1073741824'})
    from automerge_tpu.native.resident import SP_CROSSOVER_ELEMS
    rows = []
    crossover = None
    for elems in sorted(int(k) for k in (sharded.get('rows') or {})):
        a = (fenced.get('rows') or {}).get(str(elems)) or \
            (fenced.get('rows') or {}).get(elems)
        b = sharded['rows'].get(str(elems)) or sharded['rows'].get(elems)
        if not a or not b:
            continue
        rows.append({'elems': elems, 'dp_only_s': a, 'sp_s': b,
                     'sp_speedup': round(a / b, 3)})
        if crossover is None and a >= b:
            crossover = elems
    sp_line = {
        'metric': 'multichip_sp_crossover',
        'rows': rows,
        'crossover_elems': crossover,
        'fence_default_elems': SP_CROSSOVER_ELEMS,
        'policy': 'sp>1 engages only past AMTPU_MESH_SP_MIN (default '
                  'fence_default_elems) or AMTPU_MESH=1,sp opt-in; '
                  'below it the dp-only kernel serves (mesh.sp_fenced)',
        'sp_probe_engaged': sharded.get('sp_engaged', 0),
    }
    if 'error' in sharded or 'error' in fenced:
        sp_line['error'] = sharded.get('error') or fenced.get('error')
    lines.append(sp_line)
    print(json.dumps(sp_line))

    if args.out:
        with open(args.out, 'w') as f:
            for rec in lines:
                f.write(json.dumps(rec) + '\n')
        print('wrote %d lines -> %s' % (len(lines), args.out),
              file=sys.stderr)
    bad = [r for r in lines if 'error' in r]
    return 1 if bad else 0


BUILDERS = {1: build_config_1, 2: build_config_2, 3: build_config_3,
            4: build_config_4}


def _rss_mb():
    """Current (not peak) resident set in MB via /proc -- the churn
    arm's flatness signal; ru_maxrss only ratchets."""
    try:
        with open('/proc/self/statm') as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf('SC_PAGE_SIZE') / 1e6)
    except Exception:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run_coldstart(args):
    """--coldstart (ISSUE 14 + 17): the scale bench behind the CI
    miniature -- a timed cold restart of ``AMTPU_BENCH_COLDSTART_DOCS``
    (default 100k; 1M is the headline shape) saved docs through the
    native arena-direct decode (`amtpu_begin_columnar`), recording wall
    time, changes/s, and the process peak RSS (the "working-set >> RAM"
    soak), plus the Python-codec dict-replay arm on a subset for the
    A/B ratio and a sampled per-doc byte-parity check between the arms.
    ISSUE 17 adds (a) the parallel arena-direct `restore_from_store`
    arm from a real ColdStore -- serial (AMTPU_RESTORE_THREADS=1) vs
    auto fan-out across shard pools -- emitting `docs_per_gb` and
    `restore_s_per_doc` as first-class metrics, and (b) a steady-state
    churn arm where GC + op-state folding + clock folding must hold
    RSS FLAT, with byte-identical patches vs an unfolded
    (AMTPU_STORAGE_FOLD_CLOCKS=0) oracle twin.  Emits one
    BENCH_COLDSTART JSON line (--out writes it)."""
    import resource
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'tools'))
    import coldstart_check as cc
    from automerge_tpu import telemetry
    from automerge_tpu.native import NativeDocPool
    n_docs = env_int('AMTPU_BENCH_COLDSTART_DOCS', 100000)
    py_docs = min(n_docs, env_int('AMTPU_BENCH_COLDSTART_PYDOCS', 4096))
    step = env_int('AMTPU_BENCH_COLDSTART_BATCH', 8192)
    rng = random.Random(SEED)
    t0 = time.perf_counter()
    blobs, builder = cc._build_blobs(n_docs, rng)
    build_s = time.perf_counter() - t0
    n_changes = 17 * n_docs          # 1 init + 16 rounds per doc
    blob_bytes = sum(len(b) for b in blobs.values())
    # parity sample captured BEFORE the builder pool frees: the restore
    # must reproduce these bytes exactly
    sample_docs = sorted(blobs)[::max(1, n_docs // 64)]
    sample_saves = {d: builder.save(d) for d in sample_docs}
    del builder
    print('coldstart: built %d docs (%d changes, %.1f MB cold bytes) '
          'in %.1fs' % (n_docs, n_changes, blob_bytes / 1e6, build_s),
          file=sys.stderr)

    # Python-codec arm on a subset (the full corpus would take minutes
    # at the Python codec's changes/s -- which is the point)
    os.environ['AMTPU_STORAGE_NATIVE'] = '0'
    sub = {d: blobs[d] for d in list(blobs)[:py_docs]}
    p = NativeDocPool()
    t0 = time.perf_counter()
    p.load_batch(sub)
    py_s = time.perf_counter() - t0
    py_rate = (17 * py_docs) / py_s
    del p, sub
    print('coldstart: python arm %d docs in %.1fs (%.0f changes/s)'
          % (py_docs, py_s, py_rate), file=sys.stderr)

    # the timed native cold restart (chunked payloads bound memory)
    os.environ['AMTPU_STORAGE_NATIVE'] = '1'
    pool = NativeDocPool()
    docs = list(blobs)
    t0 = time.perf_counter()
    for i in range(0, len(docs), step):
        pool.load_batch({d: blobs[d] for d in docs[i:i + step]})
    native_s = time.perf_counter() - t0
    native_rate = n_changes / native_s
    peak_rss_mb = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss / 1024.0
    parity = all(pool.save(d) == sample_saves[d] for d in sample_docs)
    os.environ.pop('AMTPU_STORAGE_NATIVE', None)
    speedup = native_rate / py_rate
    print('coldstart: native restart %d docs in %.1fs (%.0f changes/s, '
          '%.1fx the python arm), peak RSS %.0f MB, parity %s'
          % (n_docs, native_s, native_rate, speedup, peak_rss_mb,
             parity), file=sys.stderr)
    del pool

    # -- ISSUE 17 (a): parallel arena-direct restore from a real cold
    # store: serial (threads=1) vs auto fan-out over shard pools
    import tempfile

    from automerge_tpu.native import ShardedNativePool, _restore_threads
    from automerge_tpu.storage.coldstore import ColdStore
    store = ColdStore(root=tempfile.mkdtemp(prefix='amtpu-coldstart-'))
    for d in docs:
        store.put(d, bytes(blobs[d]))
    shards = env_int('AMTPU_BENCH_COLDSTART_SHARDS', 4)
    serial_pool = ShardedNativePool(shards)
    t0 = time.perf_counter()
    serial_pool.restore_from_store(store, threads=1)
    serial_s = time.perf_counter() - t0
    serial_rate = n_changes / serial_s
    del serial_pool
    pool = ShardedNativePool(shards)
    t0 = time.perf_counter()
    rsum = pool.restore_from_store(store)
    par_s = time.perf_counter() - t0
    par_rate = n_changes / par_s
    par_speedup = par_rate / serial_rate
    resident_mb = _rss_mb()
    par_parity = all(pool.save(d) == sample_saves[d]
                     for d in sample_docs)
    cores = os.cpu_count() or 1
    restore_s_per_doc = par_s / n_docs
    docs_per_gb = n_docs / max(resident_mb / 1024.0, 1e-9)
    print('coldstart: store restore %d docs serial %.1fs parallel '
          '%.1fs (%.2fx, %d threads on %d cores), %.2fus/doc, '
          '%.0f docs/GB resident, parity %s'
          % (n_docs, serial_s, par_s, par_speedup,
             _restore_threads(), cores, restore_s_per_doc * 1e6,
             docs_per_gb, par_parity), file=sys.stderr)

    # -- ISSUE 17 (b): steady-state churn -- GC + op folding + clock
    # folding must hold RSS flat; patches must match an unfolded twin
    churn_rounds = env_int('AMTPU_BENCH_COLDSTART_CHURN_ROUNDS', 12)
    churn_docs = min(n_docs, env_int('AMTPU_BENCH_COLDSTART_CHURN_DOCS',
                                     2048))
    churn = None
    if churn_rounds > 0:
        cd = docs[:churn_docs]
        twin_docs = cd[::max(1, churn_docs // 128)]
        os.environ['AMTPU_STORAGE_FOLD_CLOCKS'] = '0'
        twin = NativeDocPool()
        twin.load_batch({d: blobs[d] for d in twin_docs})
        os.environ.pop('AMTPU_STORAGE_FOLD_CLOCKS', None)
        seqs, rss_series = {}, []
        muts = 6
        for r in range(churn_rounds):
            payload = {}
            for d in cd:
                seq0 = seqs.get(d, 0)
                payload[d] = [
                    {'actor': 'churn', 'seq': seq0 + i + 1,
                     'deps': {'churn': seq0 + i} if seq0 + i else {},
                     'ops': [{'action': 'set', 'obj': cc.ROOT_ID,
                              'key': 'k%d' % (i % 8),
                              'value': r * 100 + i}]}
                    for i in range(muts)]
                seqs[d] = seq0 + muts
            pool.apply_batch(payload)
            for d in cd:
                pool.compact(d)
            os.environ['AMTPU_STORAGE_FOLD_CLOCKS'] = '0'
            twin.apply_batch({d: payload[d] for d in twin_docs})
            for d in twin_docs:
                twin.compact(d)
            os.environ.pop('AMTPU_STORAGE_FOLD_CLOCKS', None)
            rss_series.append(round(_rss_mb(), 1))
        warm = max(1, churn_rounds // 3)
        early = max(rss_series[warm:2 * warm] or rss_series[:1])
        late = max(rss_series[-warm:])
        rss_flat = late <= early * 1.05 + 16
        fold_parity = all(
            pool.get_patch(d) == twin.get_patch(d)
            and pool.save(d) == twin.save(d) for d in twin_docs)
        clock_pairs = pool.clock_pairs()
        churn = {
            'docs': churn_docs, 'rounds': churn_rounds,
            'changes': churn_rounds * churn_docs * muts,
            'rss_mb_series': rss_series, 'rss_flat': rss_flat,
            'fold_parity_vs_unfolded': fold_parity,
            'clock_pairs_after': clock_pairs,
        }
        del twin
        print('coldstart: churn %d docs x %d rounds, RSS %s -> %s MB '
              '(flat %s), fold parity %s, %d sparse clock pairs left'
              % (churn_docs, churn_rounds, rss_series[0],
                 rss_series[-1], rss_flat, fold_parity, clock_pairs),
              file=sys.stderr)
    peak_rss_mb = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss / 1024.0
    result = {
        'metric': 'coldstart_restore',
        'value': round(native_rate, 1),
        'unit': 'changes/sec',
        'docs': n_docs,
        'changes': n_changes,
        'cold_bytes': blob_bytes,
        'build_s': round(build_s, 2),
        'native_restore_s': round(native_s, 3),
        'python_arm': {'docs': py_docs, 'restore_s': round(py_s, 3),
                       'changes_per_s': round(py_rate, 1)},
        'vs_baseline': round(speedup, 2),
        'baseline': 'python-codec-dict-replay',
        'peak_rss_mb': round(peak_rss_mb, 1),
        'parity': parity,
        # ISSUE 17 first-class economics metrics (bench_compare pairs
        # these across BENCH_COLDSTART_*.json like ops/s)
        'docs_per_gb': round(docs_per_gb, 1),
        'restore_s_per_doc': round(restore_s_per_doc, 8),
        'resident_rss_mb': round(resident_mb, 1),
        'restore_parallel': {
            'shards': shards, 'threads': _restore_threads(),
            'cores': cores,
            'serial_s': round(serial_s, 3),
            'parallel_s': round(par_s, 3),
            'serial_changes_per_s': round(serial_rate, 1),
            'parallel_changes_per_s': round(par_rate, 1),
            'speedup': round(par_speedup, 2),
            'parity': par_parity,
            'summary': {k: (len(v) if isinstance(v, dict) else v)
                        for k, v in rsum.items()},
        },
        'churn': churn,
        'telemetry': telemetry.bench_block(),
    }
    print(json.dumps(result))
    if args.out:
        with open(args.out, 'w') as f:
            f.write(json.dumps(result) + '\n')
        print('wrote %s' % args.out, file=sys.stderr)
    ok = parity and par_parity and speedup >= 4.0
    if churn is not None:
        ok = ok and churn['rss_flat'] and churn['fold_parity_vs_unfolded']
    # the >=2x parallel gate only binds on multi-core hosts (1-core
    # ceiling is 1x by construction; coldstart-check skips loudly too)
    if cores >= 2:
        ok = ok and par_speedup >= 2.0
    return 0 if ok else 1


def run_fanout(args):
    """--fanout (ISSUE 9): the real collaboration workload -- RGA-heavy
    text edits under zipfian doc popularity fanned out to 1k+
    subscribed peers through a live in-process gateway -- plus the
    vectorized-vs-scalar missing-changes classification A/B in the
    same session.  Emits one BENCH_FANOUT JSON line with p50/p99
    change->fanout latency, fan-out amplification (bytes-on-wire /
    bytes-encoded), both A/B throughputs, and the embedded telemetry
    block."""
    import tempfile
    import threading

    import numpy as np

    from automerge_tpu import telemetry
    from automerge_tpu.parallel.mesh_encode import text_doc_changes
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.sidecar.server import SidecarBackend
    from automerge_tpu.sync.fanout import classify_scalar, classify_vector

    n_peers = env_int('AMTPU_BENCH_FANOUT_PEERS', 1024)
    n_docs = env_int('AMTPU_BENCH_FANOUT_DOCS', 24)
    n_conns = env_int('AMTPU_BENCH_FANOUT_CONNS', 16)
    n_rounds = env_int('AMTPU_BENCH_FANOUT_ROUNDS', 96)
    zipf_s = float(os.environ.get('AMTPU_BENCH_FANOUT_ZIPF', '1.2'))
    rng = random.Random(SEED)

    # zipfian doc popularity: weight 1/k^s for doc rank k
    weights = [1.0 / (k + 1) ** zipf_s for k in range(n_docs)]
    doc_of_peer = rng.choices(range(n_docs), weights=weights, k=n_peers)
    write_docs = rng.choices(range(n_docs), weights=weights, k=n_rounds)
    subs_per_doc = [doc_of_peer.count(d) for d in range(n_docs)]

    # RGA-heavy edit streams: one change per write round per doc
    per_doc_changes = {}
    for d in range(n_docs):
        need = write_docs.count(d)
        rounds = max(1, (need + 1) // 2)
        per_doc_changes[d] = text_doc_changes(
            'text-%d' % d, 2, rounds, 40,
            lambda i, a, has: rng.random() < 0.15 and has)

    path = os.path.join(tempfile.mkdtemp(), 'bench-fanout.sock')
    telemetry.reset_all()
    gw = GatewayServer(path, backend=SidecarBackend()).start()
    drainers, counts, stop = [], [0] * n_conns, threading.Event()
    try:
        conns = [SidecarClient(sock_path=path) for _ in range(n_conns)]
        for i, doc in enumerate(doc_of_peer):
            conns[i % n_conns].subscribe('doc-%d' % doc,
                                         peer='p%04d' % i)

        def drain(ci):
            while not stop.is_set():
                try:
                    e = conns[ci].next_event(timeout=0.2)
                except ConnectionError:
                    return
                if e is not None and e.get('event') == 'change':
                    counts[ci] += 1

        drainers = [threading.Thread(target=drain, args=(ci,),
                                     daemon=True)
                    for ci in range(n_conns)]
        for t in drainers:
            t.start()

        writer = SidecarClient(sock_path=path)
        cursor = {d: 0 for d in range(n_docs)}
        expected = 0
        t0 = time.perf_counter()
        for d in write_docs:
            chs = per_doc_changes[d]
            if cursor[d] < len(chs):
                writer.apply_changes('doc-%d' % d, [chs[cursor[d]]])
                cursor[d] += 1
                expected += subs_per_doc[d]
        # frames lag the final response by at most one flush window;
        # wait for the server-side frame counter to reach/settle
        deadline = time.time() + 60
        while time.time() < deadline:
            got = telemetry.metrics_snapshot() \
                .get('sync.fanout.frames', 0)
            if got >= expected:
                break
            time.sleep(0.1)
        wall = time.perf_counter() - t0
        stop.set()
        for t in drainers:
            t.join(timeout=10)
        for c in conns + [writer]:
            c.close()
    finally:
        stop.set()
        gw.stop()

    snap = telemetry.metrics_snapshot()
    lat = telemetry.FANOUT_LATENCY.summary() or {}
    enc = snap.get('sync.fanout.bytes_encoded', 0.0)
    wire = snap.get('sync.fanout.bytes_on_wire', 0.0)

    # -- the vectorized-vs-scalar classification A/B (same session) ------
    npr = np.random.RandomState(SEED)
    A = 64
    post = npr.randint(1, 50, size=(n_peers, A)).astype(np.int64)
    pre = np.maximum(post - npr.randint(0, 3, size=(n_peers, A)), 0)
    bel = np.where(npr.random_sample((n_peers, A)) < 0.9, pre,
                   np.maximum(pre - 1, 0))

    def rate(fn, min_s=0.8):
        fn(bel, pre, post)                       # warm
        n, t = 0, time.perf_counter()
        while time.perf_counter() - t < min_s:
            fn(bel, pre, post)
            n += 1
        return n_peers * n / (time.perf_counter() - t)

    vec_rate = rate(classify_vector)
    scal_rate = rate(classify_scalar)
    speedup = vec_rate / scal_rate if scal_rate else float('inf')

    line = {
        'bench': 'fanout',
        'peers': n_peers, 'docs': n_docs, 'conns': n_conns,
        'write_rounds': n_rounds, 'zipf_s': zipf_s,
        'hot_doc_subscribers': max(subs_per_doc),
        'frames': int(snap.get('sync.fanout.frames', 0)),
        'frames_drained': sum(counts),
        'encode_reuse': int(snap.get('sync.fanout.encode_reuse', 0)),
        'coalesced_peers': int(snap.get('sync.fanout.coalesced_peers',
                                        0)),
        'straggler_peers': int(snap.get('sync.fanout.straggler_peers',
                                        0)),
        'p50_ms': lat.get('p50'), 'p95_ms': lat.get('p95'),
        'p99_ms': lat.get('p99'),
        'amplification': round(wire / enc, 2) if enc else None,
        'write_wall_s': round(wall, 3),
        'classify_ab': {
            'matrix_peers': n_peers, 'actors': A,
            'vector_peers_per_s': round(vec_rate),
            'scalar_peers_per_s': round(scal_rate),
            'speedup': round(speedup, 1),
        },
        'fallback_oracle': snap.get('fallback.oracle', 0),
        'telemetry': telemetry.bench_block(),
    }
    out = json.dumps(line)
    print(out)
    if args.out:
        with open(args.out, 'w') as f:
            f.write(out + '\n')
        print('wrote BENCH_FANOUT line -> %s' % args.out,
              file=sys.stderr)
    print('fanout bench: %d peers, hot doc %d subs, p50 %.1fms p99 '
          '%.1fms, amplification %.1fx, classify A/B %.0fk vs %.0fk '
          'peers/s (%.1fx)'
          % (n_peers, max(subs_per_doc), lat.get('p50', -1),
             lat.get('p99', -1), line['amplification'] or 0,
             vec_rate / 1e3, scal_rate / 1e3, speedup),
          file=sys.stderr)
    # the acceptance floor: the vectorized pass must beat the per-peer
    # scalar loop by >= 5x on the 1k-peer shape
    return 0 if speedup >= 5.0 and line['frames'] > 0 else 1


def run_all(args):
    """--all: every config in every execution mode, one JSON-lines
    artifact (VERDICT r4 #5: a committed all-config file per round).

    Each line runs in a FRESH subprocess: the latched native knobs
    (AMTPU_RESIDENT*) only bind at a process's first batch, jit caches
    don't leak across configs, and one config's memory high-water can't
    pollute the next config's timings on this single-core host.

    Per config: one `--mode auto` line (which itself embeds the
    opposite-mode sibling block), plus a `--mode resident` line for the
    long-list shapes (configs 1 and 3) -- the device-resident arena
    path the multichip dryrun shards."""
    import subprocess
    lines = []
    runs = [(c, 'auto') for c in (1, 2, 3, 4, 5)]
    runs += [(1, 'resident'), (3, 'resident')]
    for config, bmode in runs:
        cmd = [sys.executable, os.path.abspath(__file__),
               '--config', str(config), '--mode', bmode]
        print('== bench --config %d --mode %s ==' % (config, bmode),
              file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        line = (proc.stdout.strip().splitlines() or [''])[-1]
        try:
            rec = json.loads(line)
        except ValueError:
            rec = {'metric': 'config_%d' % config, 'value': 0.0,
                   'unit': 'ops/sec', 'vs_baseline': 0.0,
                   'baseline': BASELINE_NAME, 'mode': bmode,
                   'error': 'rc=%d no-json' % proc.returncode}
        # the subprocess rc carries failures the top-level fields don't:
        # a sibling-mode parity regression zeroes only the *_path block
        # (main()'s sibling_bad check fails the rc) -- bench-all must be
        # exactly as loud
        if proc.returncode != 0:
            rec.setdefault('error', 'rc=%d' % proc.returncode)
        rec['config'] = config
        lines.append(rec)
        print(json.dumps(rec))
    if args.out:
        with open(args.out, 'w') as f:
            for rec in lines:
                f.write(json.dumps(rec) + '\n')
        print('wrote %d lines -> %s' % (len(lines), args.out),
              file=sys.stderr)
    bad = [r for r in lines if not r.get('vs_baseline') or 'error' in r]
    return 1 if bad else 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    # internal child entries (spawned by run_multichip with the device
    # count / AMTPU_MESH / resident knobs already in the env)
    if argv[:1] == ['--multichip-child']:
        return run_multichip_child(int(argv[1]))
    if argv[:1] == ['--multichip-sp-child']:
        return run_multichip_sp_child(int(argv[1]))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--config', type=int,
                    default=env_int('AMTPU_BENCH_CONFIG', 3),
                    choices=[1, 2, 3, 4, 5])
    ap.add_argument('--mode', default='auto',
                    choices=['auto', 'host', 'kernel', 'resident'],
                    help='execution mode: auto = platform default '
                         'headline + opposite-mode sibling block; '
                         'host/kernel/resident pin one mode (resident '
                         'requires a fresh process -- the knob latches '
                         'at the first native batch)')
    ap.add_argument('--all', action='store_true',
                    help='run every config in every mode (fresh '
                         'subprocess each) and write a JSON-lines '
                         'artifact (--out)')
    ap.add_argument('--multichip', action='store_true',
                    help='MULTICHIP artifact through the first-class '
                         'mesh pool mode: one subprocess per dp '
                         '(AMTPU_MULTICHIP_DP, default 1,2,4,8) + the '
                         'sp-crossover probe; write with --out')
    ap.add_argument('--coldstart', action='store_true',
                    help='BENCH_COLDSTART artifact (ISSUE 14): timed '
                         '100k-doc cold restart + peak-RSS soak '
                         'through the native arena-direct decode, '
                         'with the Python-codec arm on a subset; '
                         'write with --out')
    ap.add_argument('--fanout', action='store_true',
                    help='BENCH_FANOUT artifact (ISSUE 9): RGA-heavy '
                         'text edits under zipfian doc popularity '
                         'fanned to 1k+ subscribed peers through a '
                         'live gateway + the vectorized-vs-scalar '
                         'missing-changes A/B; write with --out')
    ap.add_argument('--out', default='',
                    help='with --all/--multichip: artifact path '
                         '(JSON lines)')
    args = ap.parse_args(argv)
    # argparse skips the choices check for non-string DEFAULTS, so an
    # env-supplied AMTPU_BENCH_CONFIG needs explicit validation
    if args.config not in (1, 2, 3, 4, 5):
        ap.error('invalid config %r (AMTPU_BENCH_CONFIG must be 1..5)'
                 % (args.config,))
    if args.all:
        return run_all(args)
    if args.multichip:
        return run_multichip(args)
    if args.coldstart:
        return run_coldstart(args)
    if args.fanout:
        return run_fanout(args)
    if args.mode == 'host':
        os.environ['AMTPU_HOST_FULL'] = '1'
    elif args.mode == 'kernel':
        os.environ['AMTPU_HOST_FULL'] = '0'
    elif args.mode == 'resident':
        # only meaningful in a fresh process: the native lib latches
        # AMTPU_RESIDENT in its static init at the first batch
        os.environ['AMTPU_RESIDENT'] = '1'
        # bind residency for the config-1 arena (10k elements) too, not
        # just arenas past the default 16384 threshold
        os.environ.setdefault('AMTPU_RESIDENT_MIN', '4096')
    print('device: %s' % probe_device(), file=sys.stderr)
    rng = random.Random(SEED)
    both = args.mode == 'auto'
    if args.config == 5:
        result = run_config_5(rng, both_modes=both)
    elif args.config == 1 and env_int('AMTPU_BENCH_C1_MESH', 0):
        result = run_config_1_mesh(rng)
    else:
        result = run_batch_config(BUILDERS[args.config], rng, both_modes=both)
    # every BENCH line embeds a telemetry block (fallback rates, device
    # seconds, batch-latency histograms) so an artifact is
    # self-describing about HOW its number was produced.  Configs 1-4
    # already carry a per-mode block scoped to their timed runs
    # (_measure_mode); this setdefault covers the remaining paths
    # (config 5, mesh) with the process-wide view
    from automerge_tpu import telemetry
    result.setdefault('telemetry', telemetry.bench_block())
    print(json.dumps(result))
    # a parity failure in EITHER mode fails the run: the sibling-mode
    # block exists precisely so a kernel-path regression is loud even
    # where the host path is the platform default
    sibling_bad = any(
        isinstance(v, dict) and v.get('parity') is False
        for k, v in result.items() if k.endswith('_path'))
    return 0 if result.get('vs_baseline') and not sibling_bad else 1


if __name__ == '__main__':
    sys.exit(main())
