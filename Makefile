# Top-level developer entry points.

.PHONY: all native test bench bench-all bench-tpu bench-multichip check \
	clean wheel telemetry-check fallback-check perf-smoke chaos-check \
	serve-check mesh-check static-check asan-check fanout-check \
	bench-fanout storage-check obs-check backpressure-check \
	coldstart-check bench-coldstart capacity-check route-check \
	failover-check readpath-check

all: native

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

# One committed all-config artifact per round (VERDICT r4 #5): every
# config, every execution mode, fresh subprocess each, JSON lines.
bench-all: native
	python bench.py --all --out BENCH_ALL.json

# The hardware day (VERDICT r4 #6): the moment the tunneled TPU link
# recovers, this one command captures the full device story -- all five
# configs, platform-default (= kernel on TPU) + host sibling embedded
# per line, plus the resident-arena lines for the long-list shapes,
# with AMTPU_DEVTIME device busy fractions in every block.  No
# JAX_PLATFORMS pin: bench.py's subprocess probe decides, so a wedged
# link still degrades to CPU instead of hanging.
bench-tpu: native
	AMTPU_DEVTIME=1 python bench.py --all --out BENCH_TPU.json

# The pre-commit gate: native build + full test suite + a bench smoke
# covering BOTH execution modes (the default line embeds the
# opposite-mode sibling block; rc fails on either mode's parity or a
# missing kernel measurement) + the driver's multi-chip dryrun, all
# CPU-pinned so a wedged device tunnel can't hang it.  Run before EVERY
# snapshot commit; nothing ships unless this is green (the reference's
# analogue: `npm test`, /root/reference/package.json:7).
check: native
	python -m pytest tests/ -q
	JAX_PLATFORMS=cpu AMTPU_BENCH_DOCS=192 AMTPU_BENCH_ORACLE_DOCS=24 \
	  python bench.py --config 3 > .bench_smoke.json
	python -c "import json; \
	  r = json.load(open('.bench_smoke.json')); \
	  k = r.get('kernel_path') or r.get('host_full_path'); \
	  assert k and k.get('value'), 'no sibling-mode measurement'; \
	  assert r['baseline'] == 'python-scalar-oracle', r.get('baseline'); \
	  print('bench smoke: %s %.0f ops/s + sibling %.0f ops/s' \
	        % (r['mode'], r['value'], k['value']))"
	JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; \
	  g.dryrun_multichip(8); print('dryrun ok')"
	@# soft bench trajectory: diff this smoke against the previous
	@# GREEN check's (report-only -- the hard perf gates stay below);
	@# the baseline rolls forward only after every gate passes
	-@[ -f .bench_smoke.prev.json ] && \
	  python tools/bench_compare.py --soft .bench_smoke.prev.json \
	    .bench_smoke.json || true
	$(MAKE) static-check
	$(MAKE) fallback-check
	$(MAKE) perf-smoke
	$(MAKE) chaos-check
	$(MAKE) serve-check
	$(MAKE) fanout-check
	$(MAKE) readpath-check
	$(MAKE) backpressure-check
	$(MAKE) storage-check
	$(MAKE) coldstart-check
	$(MAKE) capacity-check
	$(MAKE) obs-check
	$(MAKE) route-check
	$(MAKE) failover-check
	$(MAKE) mesh-check
	$(MAKE) asan-check
	@cp .bench_smoke.json .bench_smoke.prev.json
	@echo "CHECK GREEN"

# Escalation-ladder gate (ISSUE 2): a config-4-shaped smoke on the
# FORCED kernel path must report fallback.oracle == 0 with the per-tier
# escalation counters present in the BENCH telemetry block -- the table
# workload may never fall back to host-oracle register resolution again.
fallback-check: native
	JAX_PLATFORMS=cpu python tools/fallback_check.py

# Packed-epilogue gate (ISSUE 3): the same config-4 smoke must be served
# by the packed member epilogue (collect.packed_member_batches > 0) with
# ZERO full-matrix readbacks and fallback.oracle == 0 -- the collect
# transfer wall may not silently return.
perf-smoke: native
	JAX_PLATFORMS=cpu python tools/perf_smoke.py

# Resilience gate (ISSUE 4, docs/RESILIENCE.md): injected faults must
# actually be isolated -- two forced transient device faults retry to a
# byte-identical config-3 result, a doc-pinned permanent fault
# quarantines exactly that doc with healthy-doc parity intact, and a
# SIGKILLed sidecar server respawns + replays its checkpoint WAL with a
# clean process tree afterwards.
chaos-check: native
	JAX_PLATFORMS=cpu python tools/chaos_check.py

# Serve-gateway gate (ISSUE 5, docs/SERVING.md): 32 concurrent
# connections of mixed-doc traffic must coalesce (median batch
# occupancy > 4 docs/flush) with every patch byte-identical to serial
# application; with the queue capped low, overloaded requests must get
# the typed Overloaded envelope and the server must stay healthy after
# the burst; no oracle fallback, no leaked batch handles at drain.
serve-check: native
	JAX_PLATFORMS=cpu python tools/serve_check.py

# Batched-sync-fan-out gate (ISSUE 9, docs/SERVING.md fan-out section):
# 1 popular doc x 200 subscribers must show encode_reuse >= 199 (the
# coalesced delta encodes once), every subscriber's received-change
# stream byte-identical to a serial per-Connection replay (incl. a
# mid-run straggler at a stale clock), change->fanout p99 under the
# smoke gate, and fallback.oracle == 0.
fanout-check: native
	JAX_PLATFORMS=cpu python tools/fanout_check.py

# Read-path gate (ISSUE 20, docs/SERVING.md read path): patch-mode
# fan-out must beat change shipping on thin-client apply CPU with both
# end states byte-identical to the get_patch oracle, a ReadReplica
# must stay inside its staleness SLO under writer churn and close a
# forced gap via resync, a snapshot cold-open must be byte-identical
# to a full history replay (repeat fetch cache-hit), and
# fallback.oracle == 0.  Writes BENCH_READPATH_r20.json.
readpath-check: native
	JAX_PLATFORMS=cpu python tools/readpath_check.py

# Backpressure gate (ISSUE 13, docs/SERVING.md backpressure section):
# one deliberately wedged consumer while 32 healthy connections stream
# -- every healthy peer still receives every change, healthy p99 stays
# within 2x the no-wedge baseline (floored for CI jitter), the wedged
# peer is resynced with a typed envelope or evicted, its
# post-reconnect backfill is byte-identical to a serial replay, and
# fallback.oracle == 0.
backpressure-check: native
	JAX_PLATFORMS=cpu python tools/backpressure_check.py

# The BENCH_FANOUT artifact (ISSUE 9): RGA-heavy text edits under
# zipfian doc popularity fanned to 1k+ subscribed peers, with the
# vectorized-vs-scalar missing-changes A/B in the same session.
bench-fanout: native
	JAX_PLATFORMS=cpu python bench.py --fanout --out BENCH_FANOUT.json

# Cold-state gate (ISSUE 10, docs/STORAGE.md): the config-4 change
# corpus must columnar-encode >= 5x smaller than its JSON bytes, a
# rolling churn workload with settled-history GC must end with a
# strictly smaller retained arena than the no-GC arm (byte-identical
# patches), save -> evict -> reload -> mutate must equal a never-
# evicted twin, and fallback.oracle must stay 0 throughout.  Writes
# the BENCH_STORAGE artifact.
storage-check: native
	JAX_PLATFORMS=cpu python tools/storage_check.py

# Cold-start gate (ISSUE 14, docs/STORAGE.md): the native columnar
# codec must decode >= 10x the Python codec's changes/s (scaled text
# corpus AND the config-4 acceptance corpus), the end-to-end 2k-doc
# restore through the arena-direct load must beat the dict-replay arm
# >= 4x with per-doc byte parity vs the never-evicted twin, a durable-
# mode kill-mid-save must recover via the manifest, and
# fallback.oracle == 0 throughout.
coldstart-check: native
	JAX_PLATFORMS=cpu python tools/coldstart_check.py

# The BENCH_COLDSTART artifact (ISSUE 14): timed 100k-doc cold restart
# + peak-RSS soak through the native arena-direct decode, with the
# Python-codec arm measured on a subset for the A/B ratio.
bench-coldstart: native
	JAX_PLATFORMS=cpu python bench.py --coldstart --out BENCH_COLDSTART.json

# Capacity gate (ISSUE 15, docs/OBSERVABILITY.md capacity section):
# per-doc accounting must reconcile BIT-EXACTLY with the pool-wide
# counters under churn + GC + fold + evict + reload in both exec modes
# and on a dp=4 mesh pool, the hot-doc sketch must rank a zipfian
# stream correctly, and memory-pressure eviction must fire BEFORE the
# modeled AMTPU_MEM_BUDGET_MB is breached.  The always-on accounting
# cost is priced by telemetry-check (raw arm no-ops capacity.note_*).
capacity-check: native
	JAX_PLATFORMS=cpu python tools/capacity_check.py

# Observability gate (ISSUE 12, docs/OBSERVABILITY.md): flight
# recorder + critical-path attribution + SLO surface against a LIVE
# gateway -- per-stage attribution must sum to the request wall, a
# slow request must land an exemplar span tree in the trace file, a
# fault-triggered quarantine must dump a recorder file containing the
# injected event, the on-demand `dump` request must round-trip a file,
# and amtpu_top must render from the live /metrics + /healthz.
obs-check: native
	JAX_PLATFORMS=cpu python tools/obs_check.py

# Telemetry idle-cost gate (docs/OBSERVABILITY.md): idle telemetry must
# be free.  Interleaved A/B of the disabled path vs a no-op-patched "raw"
# pipeline on the quickbench workload (target ~2% overhead; the assert
# tolerance is padded for this single-core host's +-15% jitter), plus
# an enabled-path sanity pass.  CPU-pinned: host-phase cost is
# device-independent and a wedged tunnel must not hang the gate.
telemetry-check: native
	JAX_PLATFORMS=cpu python tools/telemetry_check.py

# Static-analysis gate (ISSUE 8, docs/ANALYSIS.md): the four
# project-specific checkers -- env-latch spec/ABI/docs lockstep,
# telemetry-key pre-seed + glossary lockstep, dispatch-alias (post-
# dispatch mutation of jax-staged host buffers), lock-discipline
# (`# guarded-by:` annotations) -- plus the generic ruff/pyflakes
# baseline when installed.  Needs the native build: the env checker
# cross-checks spec defaults against the amtpu_latch_defaults ABI.
static-check: native
	python tools/static_check.py

# Native-sanitizer gate (ISSUE 8, docs/ANALYSIS.md): core.cpp rebuilt
# with -fsanitize=address,undefined and driven by the native-heavy test
# subset (driver + atomicity + differential) through AMTPU_NATIVE_LIB
# with libasan LD_PRELOADed -- the batch-column use-after-free and OOB
# classes every hardening round re-found by hand now fail CI.
asan-check: native
	JAX_PLATFORMS=cpu python tools/asan_check.py

# Fleet-router gate (ISSUE 18, docs/SERVING.md routing section): 3
# replica server subprocesses behind the consistent-hash RouterGateway
# must serve a zipfian workload with per-doc byte parity vs ONE
# single-pool serial replay and fallback.oracle == 0 on every replica;
# a cost-driven rebalance under sustained load must commit >= 1
# migration with every (doc, seq) acked exactly once and strictly
# lower occupancy skew after; and a migration whose TARGET replica is
# SIGKILLed between migrate_out and migrate_in must recover off the
# durable handoff manifest with no lost acks.  Writes the
# BENCH_ROUTER artifact (per-replica ops/s, routed p50/p99, skew).
route-check: native
	JAX_PLATFORMS=cpu python tools/route_check.py

# Fleet-failover gate (ISSUE 19, docs/RESILIENCE.md fleet degradation
# tiers): a supervised 3-replica fleet under zipfian load must survive
# a SIGKILL of one replica mid-flush -- death detected, docs restored
# onto survivors from the write-through store, parked frames replayed,
# a new generation respawned and rejoined pinned -- with exactly-once
# acks, per-doc byte parity vs a serial replay, subscribers resynced
# gapless, rebalance draining docs back onto the rejoiner, and
# fallback.oracle == 0 throughout.  Writes the BENCH_FAILOVER artifact
# (time-to-detect / time-to-restore / time-to-rejoin, retry counts).
failover-check: native
	JAX_PLATFORMS=cpu python tools/failover_check.py

# Mesh-execution gate (ISSUE 7, docs/ARCHITECTURE.md mesh section):
# MeshDocPool under AMTPU_MESH=4 must serve a mixed real workload with
# per-doc byte parity vs a serial replay and fallback.oracle == 0, and
# dp=4 must beat dp=1 by >= 1.5x on the MULTICHIP scaling workload
# (interleaved A/B, bounded retries; the JSON records the physical-core
# ceiling this CPU stand-in can offer).
mesh-check: native
	JAX_PLATFORMS=cpu python tools/mesh_check.py

# The MULTICHIP artifact through the first-class pool mode (ISSUE 7):
# one fresh subprocess per dp in {1,2,4,8} + the sp-crossover probe,
# JSON lines with per-phase seconds and the mesh.* telemetry block.
# Replaces the dryrun tail-scrape as the source of MULTICHIP_r0N.json.
bench-multichip: native
	python bench.py --multichip --out MULTICHIP.json

wheel: native
	python -m pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C native clean
