# Top-level developer entry points.

.PHONY: all native test bench check clean wheel

all: native

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

# The pre-commit gate: native build + full test suite + a 30s bench smoke
# + the driver's multi-chip dryrun, all CPU-pinned so a wedged device
# tunnel can't hang it.  Run before EVERY snapshot commit; nothing ships
# unless this is green (the reference's analogue: `npm test`,
# /root/reference/package.json:7).
check: native
	python -m pytest tests/ -q
	JAX_PLATFORMS=cpu AMTPU_BENCH_DOCS=192 AMTPU_BENCH_ORACLE_DOCS=24 \
	  python bench.py --config 3
	JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; \
	  g.dryrun_multichip(8); print('dryrun ok')"
	@echo "CHECK GREEN"

wheel: native
	python -m pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C native clean
