# Top-level developer entry points.

.PHONY: all native test bench clean wheel

all: native

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

wheel: native
	python -m pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C native clean
