"""Connection/DocSet sync tests with a scripted message-schedule mini-DSL
(deliver/drop/match), incl. message drops and duplicate deliveries -- a
multi-node execution without any real network.

Ported from `/root/reference/test/connection_test.js` (309 LoC).
"""

import pytest

import automerge_tpu as am
from automerge_tpu.sync.connection import Connection
from automerge_tpu.sync.doc_set import DocSet


class Spy:
    """Records sent messages (the stand-in for sinon.spy())."""

    def __init__(self):
        self.calls = []

    def __call__(self, msg):
        self.calls.append(msg)

    @property
    def call_count(self):
        return len(self.calls)


class Execution:
    """Mini-DSL runner: scripts message schedules between linked nodes
    (reference: connection_test.js:17-66)."""

    def __init__(self, nodes, links):
        self.nodes = nodes
        self.links = links
        self.count = {}
        self.spies = {}
        self.conns = {}
        for n1, n2 in links:
            for a, b in ((n1, n2), (n2, n1)):
                self.count[(a, b)] = 0
                self.spies[(a, b)] = Spy()
                self.conns[(a, b)] = Connection(nodes[a], self.spies[(a, b)])
        for conn in self.conns.values():
            conn.open()

    def step(self, frm, to, deliver=False, drop=False, match=None):
        spy = self.spies[(frm, to)]
        if spy.call_count <= self.count[(frm, to)]:
            raise AssertionError('Expected message was not sent: %s->%s'
                                 % (frm, to))
        msg = spy.calls[self.count[(frm, to)]]
        if match:
            match(msg)
        if deliver:
            self.count[(frm, to)] += 1
            self.conns[(to, frm)].receive_msg(msg)
        elif drop:
            self.count[(frm, to)] += 1

    def finish(self):
        for n1, n2 in self.links:
            for a, b in ((n1, n2), (n2, n1)):
                assert self.spies[(a, b)].call_count == self.count[(a, b)], \
                    'Expected %d messages from %s to %s, saw %d' % (
                        self.count[(a, b)], a, b, self.spies[(a, b)].call_count)


@pytest.fixture
def doc1():
    return am.change(am.init(), lambda doc: doc.update({'doc1': 'doc1'}))


@pytest.fixture
def nodes():
    return [DocSet() for _ in range(5)]


class TestConnection:
    def test_no_messages_without_documents(self, nodes):
        ex = Execution(nodes, [(1, 2)])
        ex.finish()

    def test_advertises_local_documents(self, nodes, doc1):
        nodes[1].set_doc('doc1', doc1)
        ex = Execution(nodes, [(1, 2)])
        actor = am.get_actor_id(doc1)
        ex.step(1, 2, drop=True,
                match=lambda msg: _expect(msg, {'docId': 'doc1',
                                                'clock': {actor: 1}}))
        ex.finish()

    def test_sends_document_missing_remotely(self, nodes, doc1):
        nodes[1].set_doc('doc1', doc1)
        actor = am.get_actor_id(doc1)
        ex = Execution(nodes, [(1, 2)])
        # node 1 advertises; node 2 requests; node 1 responds; node 2 acks
        ex.step(1, 2, deliver=True,
                match=lambda msg: _expect(msg, {'docId': 'doc1',
                                                'clock': {actor: 1}}))
        ex.step(2, 1, deliver=True,
                match=lambda msg: _expect(msg, {'docId': 'doc1', 'clock': {}}))

        def check_changes(msg):
            assert msg['docId'] == 'doc1'
            assert len(msg['changes']) == 1
        ex.step(1, 2, deliver=True, match=check_changes)
        assert nodes[2].get_doc('doc1')['doc1'] == 'doc1'
        ex.step(2, 1, deliver=True,
                match=lambda msg: _expect(msg, {'docId': 'doc1',
                                                'clock': {actor: 1}}))
        ex.finish()

    def test_concurrent_exchange_of_missing_documents(self, nodes, doc1):
        doc2 = am.change(am.init(), lambda doc: doc.update({'doc2': 'doc2'}))
        nodes[1].set_doc('doc1', doc1)
        nodes[2].set_doc('doc2', doc2)
        ex = Execution(nodes, [(1, 2)])
        ex.step(1, 2, deliver=True)
        ex.step(2, 1, deliver=True)
        ex.step(1, 2, deliver=True)   # request for doc2
        ex.step(2, 1, deliver=True)   # request for doc1
        ex.step(1, 2, deliver=True)   # doc1 data
        ex.step(2, 1, deliver=True)   # doc2 data
        ex.step(1, 2, deliver=True)   # ack
        ex.step(2, 1, deliver=True)   # ack
        ex.finish()
        assert nodes[1].get_doc('doc2')['doc2'] == 'doc2'
        assert nodes[2].get_doc('doc1')['doc1'] == 'doc1'

    def test_brings_older_copy_up_to_date(self, nodes, doc1):
        doc2 = am.merge(am.init(), doc1)
        doc2 = am.change(doc2, lambda doc: doc.update({'doc1': 'doc1++'}))
        nodes[1].set_doc('doc1', doc1)
        nodes[2].set_doc('doc1', doc2)
        ex = Execution(nodes, [(1, 2)])
        ex.step(1, 2, deliver=True)
        ex.step(2, 1, deliver=True)

        def check(msg):
            assert msg['docId'] == 'doc1'
            assert len(msg['changes']) == 1
        ex.step(2, 1, deliver=True, match=check)
        ex.step(1, 2, deliver=True)
        ex.finish()
        assert nodes[1].get_doc('doc1')['doc1'] == 'doc1++'

    def test_bidirectional_merge_of_divergent_copies(self, nodes, doc1):
        doc2 = am.merge(am.init(), doc1)
        doc2 = am.change(doc2, lambda doc: doc.update({'two': 'two'}))
        doc1b = am.change(doc1, lambda doc: doc.update({'one': 'one'}))
        nodes[1].set_doc('doc1', doc1b)
        nodes[2].set_doc('doc1', doc2)
        ex = Execution(nodes, [(1, 2)])
        ex.step(1, 2, deliver=True)
        ex.step(2, 1, drop=True)   # node 2's advertisement is lost

        def check_one_change(msg):
            assert len(msg['changes']) == 1
        ex.step(2, 1, deliver=True, match=check_one_change)
        ex.step(1, 2, deliver=True, match=check_one_change)
        ex.step(2, 1, deliver=True)
        ex.finish()
        merged = nodes[1].get_doc('doc1')
        assert am.equals(merged, {'doc1': 'doc1', 'one': 'one', 'two': 'two'})
        assert am.equals(nodes[2].get_doc('doc1'), merged)

    def test_forwards_incoming_changes(self, nodes, doc1):
        nodes[2].set_doc('doc1', doc1)
        ex = Execution(nodes, [(1, 2), (1, 3)])
        ex.step(2, 1, deliver=True)
        ex.step(1, 2, deliver=True)
        ex.step(2, 1, deliver=True)
        assert nodes[1].get_doc('doc1')['doc1'] == 'doc1'
        ex.step(1, 2, deliver=True)
        ex.step(1, 3, deliver=True)
        ex.step(3, 1, deliver=True)
        ex.step(1, 3, deliver=True)
        assert nodes[3].get_doc('doc1')['doc1'] == 'doc1'
        ex.step(3, 1, deliver=True)
        ex.finish()

    def test_tolerates_duplicate_deliveries(self, nodes):
        doc = am.change(am.init(), lambda d: d.update({'list': []}))
        nodes[1].set_doc('doc1', doc)
        nodes[2].set_doc('doc1', doc)
        nodes[3].set_doc('doc1', doc)
        ex = Execution(nodes, [(1, 2), (1, 3), (2, 3)])
        for frm, to in [(1, 2), (1, 3), (2, 1), (2, 3), (3, 1), (3, 2)]:
            ex.step(frm, to, deliver=True)

        doc = am.change(doc, lambda d: d['list'].push('hello'))
        nodes[1].set_doc('doc1', doc)
        actor = am.get_actor_id(doc)

        def check(msg):
            assert msg['clock'] == {actor: 2}
            assert len(msg['changes']) == 1
        ex.step(1, 2, deliver=True, match=check)
        ex.step(1, 3, match=check)
        ex.step(2, 1, deliver=True)
        ex.step(2, 3, match=lambda msg: check(msg))
        # node 3 receives the same change twice (from node 1 AND node 2)
        ex.step(1, 3, deliver=True)
        ex.step(2, 3, deliver=True)
        ex.step(3, 1, deliver=True)
        ex.step(3, 2, deliver=True)
        ex.finish()
        for n in (1, 2, 3):
            assert am.equals(nodes[n].get_doc('doc1'), {'list': ['hello']})


class TestWatchableDoc:
    def test_watchable_doc_notifies_handlers(self):
        from automerge_tpu.sync.watchable_doc import WatchableDoc
        doc = am.init()
        watched = WatchableDoc(doc)
        seen = []
        watched.register_handler(lambda d: seen.append(d))
        doc2 = am.change(doc, lambda d: d.update({'x': 1}))
        changes = am.get_changes(doc, doc2)
        new_doc = watched.apply_changes(changes)
        assert new_doc['x'] == 1
        assert len(seen) == 1 and seen[0]['x'] == 1
        assert watched.get()['x'] == 1


def _expect(msg, expected):
    assert msg == expected, '%r != %r' % (msg, expected)
