"""Adversarial differential fuzz (VERDICT r3 #8): schedules built to hit
the engine's cliffs rather than its fast paths.

Each scenario drives the scalar oracle, the batched Python pool, and the
C++ native pool with IDENTICAL inputs and requires byte-identical
patches at every delivery -- the same contract as
tests/test_engine_differential.py, pointed at:

  * wide antichains: >8 concurrent writer streams per key (member-window
    overflow -> tiered kernel escalation, hostreg on the CPU backend; the
    host oracle only as parity referee), on maps AND list elements;
  * deep cross-doc causal chains delivered fully reversed (the causal
    queue fixpoint, not the in-order fast path);
  * undo/redo interleaved with remote merges (undo-stack capture against
    registers that remote batches keep rewriting);
  * save/load mid-stream (checkpoint/restore of every mirror the engine
    maintains, then continued ingestion on the restored state).

Seeds are fixed for CI reproducibility; AMTPU_FUZZ_SEED overrides to
widen the search (same convention as TestRotatingFuzz).
"""

import os
import random

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
from automerge_tpu.parallel.engine import TPUDocPool

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def seed_base(default):
    env = os.environ.get('AMTPU_FUZZ_SEED')
    return int(env) if env else default


def deliver_all(change_batches, n_docs=1):
    """Oracle + both pools, patch-equal at every step and at the end.

    Runs the native pool in whatever mode the environment selects (the
    full host path on the CPU test mesh); the `exec_mode` fixture
    below re-runs every scenario with AMTPU_HOST_FULL=0 so the kernel
    path faces the same adversarial schedules."""
    oracle = {d: Backend.init() for d in range(n_docs)}
    pools = [TPUDocPool(), NativeDocPool()]
    for batch in change_batches:
        want = {}
        for doc, chs in batch.items():
            oracle[doc], p = Backend.apply_changes(
                oracle[doc], [dict(c) for c in chs])
            want[doc] = p
        for pool in pools:
            got = pool.apply_batch(batch)
            for doc in batch:
                assert got[doc] == want[doc], (
                    '%s patch mismatch doc %r' % (type(pool).__name__, doc))
    for d in range(n_docs):
        final = Backend.get_patch(oracle[d])
        for pool in pools:
            assert pool.get_patch(d) == final, type(pool).__name__
    return oracle, pools


@pytest.fixture(params=['default', 'kernel'])
def exec_mode(request):
    """Both execution modes face the adversarial schedules: the CPU
    default (full host path) and the forced kernel path."""
    if request.param == 'kernel':
        prior = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_FULL'] = '0'
        yield 'kernel'
        if prior is None:
            os.environ.pop('AMTPU_HOST_FULL', None)
        else:
            os.environ['AMTPU_HOST_FULL'] = prior
    else:
        yield 'default'


class TestWideAntichains:
    """Register groups wider than the base kernel window: every width
    must resolve through the escalation ladder (n writers -> n-1
    candidates: 9/15/17 land in the w16 tier, 33 in w32),
    byte-identical to the oracle in both execution modes."""

    @pytest.mark.parametrize('n_writers', [9, 12, 15, 17, 20, 33])
    def test_map_hot_keys(self, n_writers, exec_mode):
        rng = random.Random(seed_base(501) + n_writers)
        changes = []
        for seq in range(1, 4):
            for a in range(n_writers):
                ops = []
                for k in rng.sample(range(5), 3):
                    if rng.random() < 0.15:
                        ops.append({'action': 'del', 'obj': ROOT_ID,
                                    'key': 'k%d' % k})
                    else:
                        ops.append({'action': 'set', 'obj': ROOT_ID,
                                    'key': 'k%d' % k,
                                    'value': 'w%02d.%d' % (a, seq)})
                changes.append({'actor': 'w%02d' % a, 'seq': seq,
                                'deps': {}, 'ops': ops})
        rng.shuffle(changes)
        # causally safe shuffle: per-actor order restored
        changes.sort(key=lambda c: c['seq'])
        batches = []
        i = 0
        while i < len(changes):
            n = rng.randint(2, 9)
            batches.append({0: changes[i:i + n]})
            i += n
        deliver_all(batches)

    def test_list_element_antichain(self, exec_mode):
        """14 writers concurrently assign the SAME list element (and one
        deletes it): a wide antichain on an element register, which must
        route through the overflow fallback WITH dominance work."""
        base = {'actor': 'base', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': 'l'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'list',
             'value': 'l'},
            {'action': 'ins', 'obj': 'l', 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': 'l', 'key': 'base:1', 'value': 'v0'},
            {'action': 'ins', 'obj': 'l', 'key': 'base:1', 'elem': 2},
            {'action': 'set', 'obj': 'l', 'key': 'base:2', 'value': 'v1'},
        ]}
        writers = []
        for a in range(14):
            op = ({'action': 'del', 'obj': 'l', 'key': 'base:1'}
                  if a == 7 else
                  {'action': 'set', 'obj': 'l', 'key': 'base:1',
                   'value': 'w%02d' % a})
            writers.append({'actor': 'w%02d' % a, 'seq': 1,
                            'deps': {'base': 1}, 'ops': [op]})
        deliver_all([{0: [base]}, {0: writers}])


class TestEscalationFallbackFree:
    """The ISSUE-2 acceptance lanes: the kernel path must be oracle-free
    on every width the ladder serves -- including the table-adversarial
    shape (same-change dup assigns) that produced the recorded 8,532
    oracle-fallback rows, and a 100+ concurrent-live-writer antichain."""

    def _assert_kernel_fallback_free(self, run, exec_mode,
                                     expect_escalated=True):
        from automerge_tpu import telemetry
        telemetry.metrics_reset()
        run()
        snap = telemetry.metrics_snapshot()
        assert snap.get('fallback.oracle', 0) == 0, snap
        if expect_escalated:
            assert any(k.startswith('fallback.escalated.w') and v > 0
                       for k, v in snap.items()), (exec_mode, snap)

    @pytest.mark.parametrize('n_writers', [9, 15, 17, 33, 100, 120])
    def test_concurrent_live_writers_one_key(self, n_writers, exec_mode):
        """n fully concurrent live writers on one key in ONE batch: the
        widest antichain shape, resolved without a single oracle row."""
        writers = [{'actor': 'w%03d' % a, 'seq': 1, 'deps': {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                             'value': 'w%03d' % a}]}
                   for a in range(n_writers)]
        self._assert_kernel_fallback_free(
            lambda: deliver_all([{0: writers}]), exec_mode)

    def test_table_shape_dup_assigns(self, exec_mode):
        """Config-4-shaped rows: concurrent field updates where changes
        assign the SAME row key twice (the member-window-unholdable
        shape) -- previously all-oracle, now all-ladder."""
        rng = random.Random(seed_base(60603))
        n_actors = 9
        rows = ['row-%d' % i for i in range(6)]
        setup = {'actor': 'setup', 'seq': 1, 'deps': {}, 'ops':
                 [{'action': 'makeTable', 'obj': 'tb'},
                  {'action': 'link', 'obj': ROOT_ID, 'key': 'rows',
                   'value': 'tb'}] +
                 [op for r in rows for op in (
                     {'action': 'makeMap', 'obj': r},
                     {'action': 'set', 'obj': r, 'key': 'n', 'value': -1},
                     {'action': 'link', 'obj': 'tb', 'key': r,
                      'value': r})]}
        updates = []
        for a in range(n_actors):
            ops = []
            for _ in range(8):   # 8 picks of 6 rows: dup assigns certain
                r = rows[rng.randrange(len(rows))]
                ops.append({'action': 'set', 'obj': r, 'key': 'n',
                            'value': rng.randrange(1000)})
            updates.append({'actor': 'a%d' % a, 'seq': 1,
                            'deps': {'setup': 1}, 'ops': ops})
        self._assert_kernel_fallback_free(
            lambda: deliver_all([{0: [setup]}, {0: updates}]), exec_mode)

    def test_oracle_referee_parity(self, exec_mode):
        """AMTPU_ESCALATE=0 pins the referee: the host oracle must
        produce byte-identical patches to the ladder (and the run must
        actually take the oracle path -- fallback.oracle > 0)."""
        from automerge_tpu import telemetry
        prior = os.environ.get('AMTPU_ESCALATE')
        os.environ['AMTPU_ESCALATE'] = '0'
        try:
            telemetry.metrics_reset()
            writers = [{'actor': 'w%02d' % a, 'seq': 1, 'deps': {},
                        'ops': [{'action': 'set', 'obj': ROOT_ID,
                                 'key': 'k', 'value': a}]}
                       for a in range(20)]
            deliver_all([{0: writers}])
            snap = telemetry.metrics_snapshot()
            assert snap.get('fallback.oracle', 0) > 0, snap
        finally:
            if prior is None:
                os.environ.pop('AMTPU_ESCALATE', None)
            else:
                os.environ['AMTPU_ESCALATE'] = prior

    def test_wide_antichain_with_list_dominance(self, exec_mode):
        """30 concurrent writers on ONE list element register: escalation
        must compose with the dominance stage, not just map emits."""
        base = {'actor': 'base', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': 'l'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'list',
             'value': 'l'},
            {'action': 'ins', 'obj': 'l', 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': 'l', 'key': 'base:1', 'value': 'v0'}]}
        writers = []
        for a in range(30):
            op = ({'action': 'del', 'obj': 'l', 'key': 'base:1'}
                  if a == 13 else
                  {'action': 'set', 'obj': 'l', 'key': 'base:1',
                   'value': 'w%02d' % a})
            writers.append({'actor': 'w%02d' % a, 'seq': 1,
                            'deps': {'base': 1}, 'ops': [op]})
        self._assert_kernel_fallback_free(
            lambda: deliver_all([{0: [base]}, {0: writers}]), exec_mode)


@pytest.fixture(params=['packed', 'unpacked'])
def packed_epilogue(request):
    """Both member epilogues face the same schedules: the packed
    transfer (default) and the full-matrix readback
    (AMTPU_PACKED_EPILOGUE=0) -- byte parity between them is the
    ISSUE-3 acceptance bar."""
    prior = os.environ.get('AMTPU_PACKED_EPILOGUE')
    os.environ['AMTPU_PACKED_EPILOGUE'] = \
        '1' if request.param == 'packed' else '0'
    yield request.param
    if prior is None:
        os.environ.pop('AMTPU_PACKED_EPILOGUE', None)
    else:
        os.environ['AMTPU_PACKED_EPILOGUE'] = prior


class TestPackedEpilogueParity:
    """ISSUE-3 fuzz lane: the packed member epilogue (ONE i32 per
    register row + sparse CSR conflicts + in-packed escalation merge)
    must be byte-identical to the full-matrix readback it replaced,
    against the scalar-oracle referee, in both execution modes.

    The workload is built to hit every packed-path branch at once:
    member mode (hot keys deeper than the sliding window), host-flagged
    overflow escalating through wider tiers (>8 concurrent streams AND
    same-change dup assigns), base-kernel conflict rows OUTSIDE the
    flagged groups (the sparse CSR gather), deletes, and registers that
    resolve to a single survivor."""

    def _workload(self, rng, n_actors=11, n_keys=6, n_rounds=3):
        batches = []
        setup = {'actor': 'setup', 'seq': 1, 'deps': {}, 'ops':
                 [{'action': 'set', 'obj': ROOT_ID, 'key': 'k%d' % k,
                   'value': 'base'} for k in range(n_keys)]}
        batches.append({0: [setup]})
        for rnd in range(n_rounds):
            changes = []
            for a in range(n_actors):
                ops = []
                # hot key k0: every actor, every round (member mode +
                # >8 concurrent streams -> escalation)
                ops.append({'action': 'set', 'obj': ROOT_ID, 'key': 'k0',
                            'value': 'a%d-r%d' % (a, rnd)})
                if a == 3:
                    # same-change dup assign: the member-window
                    # unholdable shape
                    ops.append({'action': 'set', 'obj': ROOT_ID,
                                'key': 'k0', 'value': 'dup-%d' % rnd})
                # narrow keys: 2-3 writers each (conflicts survive on
                # the BASE kernel path, outside any flagged group)
                k = 1 + (a + rnd) % (n_keys - 1)
                if a < 3:
                    op = {'action': 'set', 'obj': ROOT_ID,
                          'key': 'k%d' % k, 'value': a * 100 + rnd}
                    if a == 2 and rnd == 1:
                        op = {'action': 'del', 'obj': ROOT_ID,
                              'key': 'k%d' % k}
                    ops.append(op)
                # deep sequential history on one key: member mode with a
                # single surviving stream
                if a == 5:
                    for i in range(4):
                        ops.append({'action': 'set', 'obj': ROOT_ID,
                                    'key': 'k5',
                                    'value': 'seq-%d-%d' % (rnd, i)})
                # private keys: unflagged member rows keep the batch off
                # the hostreg route (2 * pre_ovf < T), so the KERNEL
                # member path -- the epilogue under test -- serves it
                for i in range(3):
                    ops.append({'action': 'set', 'obj': ROOT_ID,
                                'key': 'p%d' % a,
                                'value': 'p-%d-%d-%d' % (a, rnd, i)})
                changes.append({'actor': 'f%02d' % a, 'seq': rnd + 1,
                                'deps': {'setup': 1},
                                'ops': ops})
            rng.shuffle(changes)
            batches.append({0: changes})
        return batches

    def test_member_epilogue_byte_parity(self, packed_epilogue,
                                         exec_mode):
        from automerge_tpu import telemetry
        telemetry.metrics_reset()
        rng = random.Random(seed_base(70707))
        # pin routing: hostreg would bypass the epilogue under test on
        # the CPU backend (the counters below assert which path served)
        prior = os.environ.get('AMTPU_HOST_REG')
        os.environ['AMTPU_HOST_REG'] = '0'
        try:
            deliver_all(self._workload(rng))
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_REG', None)
            else:
                os.environ['AMTPU_HOST_REG'] = prior
        snap = telemetry.metrics_snapshot()
        assert snap.get('fallback.oracle', 0) == 0, snap
        if exec_mode == 'kernel':
            # the toggle must actually select the epilogue under test
            if packed_epilogue == 'packed':
                assert snap.get('collect.packed_member_batches', 0) > 0, \
                    snap
                assert snap.get('collect.full_matrix_readback', 0) == 0, \
                    snap
            else:
                assert snap.get('collect.full_matrix_readback', 0) > 0, \
                    snap
                assert snap.get('collect.packed_member_batches', 0) == 0, \
                    snap

    @pytest.mark.parametrize('seed', [1, 2, 3])
    def test_rotating_hot_key_fuzz(self, seed, packed_epilogue,
                                   exec_mode):
        """Randomized widths/depths: writer counts rotate through the
        base window, the first tier, and multi-tier territory."""
        rng = random.Random(seed_base(81000 + seed))
        n_actors = rng.choice([9, 12, 17])
        batches = self._workload(rng, n_actors=n_actors,
                                 n_keys=rng.randrange(3, 7),
                                 n_rounds=2)
        deliver_all(batches)


class TestReversedCausalChains:
    def test_deep_chain_reversed(self, exec_mode):
        """120-deep cross-actor dependency chain delivered fully
        reversed: every change but the first buffers, then one fixpoint
        admits the whole chain."""
        rng = random.Random(seed_base(601))
        actors = ['a%d' % i for i in range(4)]
        seqs = {a: 0 for a in actors}
        chain = []
        frontier = {}
        for i in range(120):
            a = actors[i % 4]
            seqs[a] += 1
            ops = [{'action': 'set', 'obj': ROOT_ID,
                    'key': 'k%d' % rng.randrange(6), 'value': i}]
            deps = {x: s for x, s in frontier.items() if x != a}
            chain.append({'actor': a, 'seq': seqs[a], 'deps': deps,
                          'ops': ops})
            frontier[a] = seqs[a]
        reversed_chain = list(reversed(chain))
        # reversed in small batches: deps stay missing until the last
        # batch arrives, then everything cascades
        batches = []
        i = 0
        while i < len(reversed_chain):
            n = rng.randint(1, 7)
            batches.append({0: reversed_chain[i:i + n]})
            i += n
        deliver_all(batches)

    def test_cross_doc_reversed_streams(self, exec_mode):
        """Several docs' chains interleaved, each doc's stream reversed
        independently within one multi-doc batch sequence."""
        rng = random.Random(seed_base(602))
        streams = {}
        for d in range(3):
            chain = []
            for i in range(40):
                a = 'd%d-a%d' % (d, i % 3)
                chain.append({'actor': a, 'seq': i // 3 + 1,
                              'deps': ({'d%d-a%d' % (d, (i - 1) % 3):
                                        (i - 1) // 3 + 1} if i else {}),
                              'ops': [{'action': 'set', 'obj': ROOT_ID,
                                       'key': 'x', 'value': i}]})
            streams[d] = list(reversed(chain))
        batches = []
        pos = {d: 0 for d in streams}
        while any(pos[d] < len(streams[d]) for d in streams):
            batch = {}
            for d in streams:
                if pos[d] < len(streams[d]):
                    n = rng.randint(1, 5)
                    batch[d] = streams[d][pos[d]:pos[d] + n]
                    pos[d] += n
            batches.append(batch)
        deliver_all(batches, n_docs=3)


class TestUndoRedoUnderMerge:
    def test_undo_redo_interleaved_with_remote_batches(self):
        """Local change/undo/redo interleaved with remote deliveries:
        the undo stack captures registers that remote merges keep
        rewriting, and redo must replay against the merged state --
        all three backends byte-identical at every step."""
        rng = random.Random(seed_base(701))
        oracle = Backend.init()
        pools = [TPUDocPool(), NativeDocPool()]
        local_seq = 0
        remote_seqs = {}
        can_undo = 0

        for step in range(40):
            roll = rng.random()
            if roll < 0.4:
                local_seq += 1
                req = {'requestType': 'change', 'actor': 'local',
                       'seq': local_seq, 'deps': {}, 'ops': [
                           {'action': 'set', 'obj': ROOT_ID,
                            'key': 'k%d' % rng.randrange(3),
                            'value': 'L%d' % step}]}
                can_undo += 1
            elif roll < 0.6 and can_undo:
                local_seq += 1
                req = {'requestType': 'undo', 'actor': 'local',
                       'seq': local_seq, 'deps': {}}
                can_undo -= 1
            elif roll < 0.7 and oracle['opSet']['redoStack']:
                local_seq += 1
                req = {'requestType': 'redo', 'actor': 'local',
                       'seq': local_seq, 'deps': {}}
            else:
                # remote delivery touching the same keys
                a = 'r%d' % rng.randrange(3)
                remote_seqs[a] = remote_seqs.get(a, 0) + 1
                ch = {'actor': a, 'seq': remote_seqs[a], 'deps': {},
                      'ops': [{'action': 'set', 'obj': ROOT_ID,
                               'key': 'k%d' % rng.randrange(3),
                               'value': '%s.%d' % (a, step)}]}
                oracle, want = Backend.apply_changes(oracle, [dict(ch)])
                for pool in pools:
                    got = pool.apply_batch({0: [dict(ch)]})[0]
                    assert got == want, (step, type(pool).__name__)
                continue
            oracle, want = Backend.apply_local_change(oracle, dict(req))
            for pool in pools:
                got = pool.apply_local_change(0, dict(req))
                assert got == want, (step, req['requestType'],
                                     type(pool).__name__)

        final = Backend.get_patch(oracle)
        for pool in pools:
            assert pool.get_patch(0) == final, type(pool).__name__


class TestSaveLoadMidStream:
    """Checkpoint semantics match the reference: save() serializes the
    APPLIED document (opSet.history, src/automerge.js:45-52) -- changes
    still buffered in the causal queue at checkpoint time are NOT part
    of the doc and are recovered by the sync layer re-shipping anything
    the restored clock doesn't cover.  (This very suite found that an
    arbitrary mid-stream cut can leave a change buffered at save time,
    so the restored-side oracle below is built from the actual save
    blob, and continuation is driven the way the protocol does it:
    redeliver everything, duplicates no-op.)"""

    @pytest.mark.parametrize('seed', [801, 802])
    def test_checkpoint_restore_continue(self, seed):
        import msgpack

        from tests.test_engine_differential import WorkloadGen
        rng = random.Random(seed)
        changes = WorkloadGen(seed, n_actors=4,
                              structure='mixed').generate(40)
        half = len(changes) // 2
        pools = [TPUDocPool(), NativeDocPool()]
        for pool in pools:
            pool.apply_batch({0: [dict(c) for c in changes[:half]]})

        restored = []
        blobs = []
        for pool in pools:
            blob = pool.save(0)
            blobs.append(blob)
            fresh = type(pool)()
            fresh.load(0, blob)
            restored.append(fresh)
        # both backends checkpoint the same applied history
        assert blobs[0] == blobs[1]

        # restored-side oracle: replay the saved history itself
        # (container-format agnostic: the v2 columnar container decodes
        # through the storage helpers, docs/STORAGE.md)
        from automerge_tpu import storage
        oracle = Backend.init()
        oracle, _ = Backend.apply_changes(
            oracle, [msgpack.unpackb(r, raw=False)
                     for r in storage.checkpoint_raw_changes(blobs[0])])
        for pool in restored:
            assert pool.get_patch(0) == Backend.get_patch(oracle), \
                type(pool).__name__

        # continuation via the redelivery protocol: EVERYTHING shuffled
        # (first half again + second half); applied changes dedup as
        # no-ops, changes dropped from the queue at checkpoint re-apply
        redelivery = [dict(c) for c in changes]
        rng.shuffle(redelivery)
        for ch in redelivery:
            oracle, want = Backend.apply_changes(oracle, [dict(ch)])
            for pool in restored:
                got = pool.apply_batch({0: [dict(ch)]})[0]
                assert got == want, type(pool).__name__
        final = Backend.get_patch(oracle)
        for pool in restored:
            assert pool.get_patch(0) == final, type(pool).__name__
        # nothing left buffered anywhere
        for pool in restored:
            assert pool.get_missing_deps(0) == {}


class TestTableAdversarial:
    """Table-shaped cliffs (round 5): the emit hot paths this round
    rewrote -- path-cache invalidation keyed on inbound[0] erasure,
    two-way obj/type caches, link inbound maintenance, cross-probe
    decode -- all face concurrent row lifecycles here.

    Shapes (reference Table semantics, frontend/table.js:26-196):
      * concurrent add/update/unlink/relink of the SAME rows by many
        actors, shuffled causal delivery;
      * rows linked under TWO parents, then the first parent's link
        removed (inbound[0] erase -> cached paths must re-render);
      * nested maps inside rows written before AND after the row is
        linked (null -> real path transitions that are never cached).
    """

    def test_concurrent_row_lifecycle(self, exec_mode):
        # every per-actor row object is created up front; afterwards six
        # actors concurrently update/unlink/relink the same rows with NO
        # causal ordering (deps={}), delivered fully shuffled -- maximal
        # concurrency on the table's link registers and inbound lists
        rng = random.Random(seed_base(60601))
        n_actors = 6
        objs = ['row-%d-a%d' % (i, a)
                for i in range(10) for a in range(n_actors)]
        setup = {'actor': 'setup', 'seq': 1, 'deps': {}, 'ops':
                 [{'action': 'makeTable', 'obj': 'tb'},
                  {'action': 'link', 'obj': ROOT_ID, 'key': 'rows',
                   'value': 'tb'}] +
                 [op for o in objs for op in (
                     {'action': 'makeMap', 'obj': o},
                     {'action': 'set', 'obj': o, 'key': 'n', 'value': -1},
                     {'action': 'link', 'obj': 'tb', 'key': o,
                      'value': o})]}
        changes = []
        for a in range(n_actors):
            actor = 'a%d' % a
            for seq in range(1, 7):
                ops = []
                for o in rng.sample(objs, 5):
                    kind = rng.random()
                    if kind < 0.3:
                        ops.append({'action': 'del', 'obj': 'tb',
                                    'key': o})
                    elif kind < 0.6:
                        ops.append({'action': 'link', 'obj': 'tb',
                                    'key': o, 'value': o})
                    else:
                        ops.append({'action': 'set', 'obj': o, 'key': 'n',
                                    'value': seq * 100 + a})
                changes.append({'actor': actor, 'seq': seq, 'deps': {},
                                'ops': ops})
        rng.shuffle(changes)
        deliver_all([{0: [setup]}] + [{0: [ch]} for ch in changes])

    def test_two_parent_row_first_link_removed(self, exec_mode):
        # row under two tables; removing the FIRST link (inbound[0])
        # must flip emitted paths to the second parent
        batches = [
            {0: [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeTable', 'obj': 't1'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'p1',
                 'value': 't1'},
                {'action': 'makeTable', 'obj': 't2'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'p2',
                 'value': 't2'},
                {'action': 'makeMap', 'obj': 'shared'},
                {'action': 'link', 'obj': 't1', 'key': 'shared',
                 'value': 'shared'},
                {'action': 'link', 'obj': 't2', 'key': 'shared',
                 'value': 'shared'},
                {'action': 'set', 'obj': 'shared', 'key': 'v',
                 'value': 1}]}]},
            {0: [{'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 't1', 'key': 'shared'},
                {'action': 'set', 'obj': 'shared', 'key': 'v',
                 'value': 2}]}]},
            {0: [{'actor': 'a', 'seq': 3, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 't2', 'key': 'shared'},
                {'action': 'set', 'obj': 'shared', 'key': 'v',
                 'value': 3}]}]},
        ]
        deliver_all(batches)

    def test_nested_map_written_around_link(self, exec_mode):
        rng = random.Random(seed_base(60602))
        ops = [{'action': 'makeTable', 'obj': 'tb'},
               {'action': 'link', 'obj': ROOT_ID, 'key': 'rows',
                'value': 'tb'}]
        for i in range(12):
            row, child = 'r%d' % i, 'c%d' % i
            ops += [{'action': 'makeMap', 'obj': row},
                    {'action': 'makeMap', 'obj': child},
                    # child written while BOTH are unreachable
                    {'action': 'set', 'obj': child, 'key': 'x',
                     'value': i},
                    {'action': 'link', 'obj': row, 'key': 'kid',
                     'value': child},
                    # child written while row is still unreachable
                    {'action': 'set', 'obj': child, 'key': 'x',
                     'value': i * 10},
                    {'action': 'link', 'obj': 'tb', 'key': row,
                     'value': row},
                    # and now fully reachable
                    {'action': 'set', 'obj': child, 'key': 'x',
                     'value': i * 100}]
        # split into changes of 5 ops, delivered in order then the
        # whole stream redelivered shuffled (dedup no-ops)
        chs = [{'actor': 'a', 'seq': s + 1, 'deps': {},
                'ops': ops[s * 5:(s + 1) * 5]}
               for s in range((len(ops) + 4) // 5)]
        chs = [c for c in chs if c['ops']]
        deliver_all([{0: chs}])
        redeliver = [dict(c) for c in chs]
        rng.shuffle(redeliver)
        deliver_all([{0: chs}, {0: redeliver}])
