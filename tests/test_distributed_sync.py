"""Multi-process replica sync (VERDICT r2 #7): change shipping crosses
process boundaries (TCP mesh, the DCN stand-in) while clock gossip rides
jax.distributed collectives; each worker verifies convergence of every
replica in every process against the scalar oracle before reporting OK.
"""

import re

import pytest

from automerge_tpu.sync.distributed import launch


@pytest.mark.parametrize('n_processes', [2, 3])
def test_cross_process_convergence(n_processes):
    outs = launch(n_processes, timeout=300)
    assert len(outs) == n_processes
    for pid, out in enumerate(outs):
        m = re.search(r'DISTRIBUTED-OK pid=%d rounds=\[([0-9, ]+)\]' % pid,
                      out)
        assert m, 'worker %d did not report OK:\n%s' % (pid, out)
        rounds = [int(x) for x in m.group(1).split(',')]
        # converges (last round plans nothing) and actually shipped work
        assert rounds[-1] == 0 and sum(rounds) > 0
