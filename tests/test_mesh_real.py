"""The mesh path on REAL workloads: encode an actual change payload, run
the (sharded and unsharded) mesh step, and pin its outputs against the
pool's public patches -- clocks, per-op list indexes, and diff actions all
derived from the same wire-format changes the pools consume.
"""

import numpy as np
import pytest

from automerge_tpu.parallel import mesh as M
from automerge_tpu.parallel import mesh_encode
from automerge_tpu.parallel.mesh_encode import demo_text_workload as \
    text_workload

ROOT = '00000000-0000-0000-0000-000000000000'


def check_against_pool(workload, batch, meta, out):
    mesh_encode.verify_against_pool(workload, meta, out)


def test_single_step_matches_pool_on_real_workload():
    workload = text_workload(n_docs=4)
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    check_against_pool(workload, batch, meta, out)


@pytest.mark.parametrize('sp', [1, 2, 4])
def test_sharded_step_matches_pool_on_real_workload(sp):
    import jax
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 devices')
    mesh = M.make_mesh(8, sp=sp)
    workload = text_workload(n_docs=8 // sp * 2)
    batch, meta = mesh_encode.encode_batch(workload, sp=sp)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    step = M.build_sharded_step(mesh, n_linearize_iters=n_iters, chunk=16)
    out = step(M.shard_batch(mesh, batch))
    jax.block_until_ready(out)
    check_against_pool(workload, batch, meta, out)
    # sharded == unsharded, bit for bit
    ref = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    for key in ('doc_clock', 'frontier', 'rank', 'indexes', 'winner'):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]), err_msg=key)


def test_encoder_rejects_non_causal_payloads():
    bad = {0: [{'actor': 'A', 'seq': 2, 'deps': {},
                'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                         'value': 1}]}]}
    with pytest.raises(ValueError, match='causally ordered'):
        mesh_encode.encode_batch(bad)


def test_same_change_duplicate_assigns_are_exact_on_mesh_path():
    """One change setting a key twice keeps BOTH records in the reference
    (same-clock rows are mutually concurrent); the sliding-window kernel
    reproduces that exactly, so the mesh path needs no oracle fallback."""
    workload = {0: [{'actor': 'A', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': 'T'},
        {'action': 'ins', 'obj': 'T', 'key': '_head', 'elem': 1},
        {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'x'},
        {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'y'},
        {'action': 'del', 'obj': 'T', 'key': 'A:1'},
        {'action': 'link', 'obj': ROOT, 'key': 't', 'value': 'T'}]}]}
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(workload, meta, out)
    # both set records survive (same-clock rows are concurrent) and the
    # same-change del kills neither
    alive = np.asarray(out['alive_after'])
    assert alive[0, meta['ops'][0][-1][0]] == 2
