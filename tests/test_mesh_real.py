"""The mesh path on REAL workloads: encode an actual change payload, run
the (sharded and unsharded) mesh step, and pin its outputs against the
pool's public patches -- clocks, per-op list indexes, and diff actions all
derived from the same wire-format changes the pools consume.
"""

import numpy as np
import pytest

from automerge_tpu.parallel import mesh as M
from automerge_tpu.parallel import mesh_encode
from automerge_tpu.parallel.mesh_encode import demo_text_workload as \
    text_workload

ROOT = '00000000-0000-0000-0000-000000000000'


def check_against_pool(workload, batch, meta, out):
    mesh_encode.verify_against_pool(workload, meta, out)


def test_single_step_matches_pool_on_real_workload():
    workload = text_workload(n_docs=4)
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    check_against_pool(workload, batch, meta, out)


@pytest.mark.parametrize('sp', [1, 2, 4])
def test_sharded_step_matches_pool_on_real_workload(sp):
    import jax
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 devices')
    mesh = M.make_mesh(8, sp=sp)
    workload = text_workload(n_docs=8 // sp * 2)
    batch, meta = mesh_encode.encode_batch(workload, sp=sp)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    step = M.build_sharded_step(mesh, n_linearize_iters=n_iters, chunk=16)
    out = step(M.shard_batch(mesh, batch))
    jax.block_until_ready(out)
    check_against_pool(workload, batch, meta, out)
    # sharded == unsharded, bit for bit
    ref = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    for key in ('doc_clock', 'frontier', 'rank', 'indexes', 'winner'):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]), err_msg=key)


def test_encoder_rejects_true_causal_gaps():
    bad = {0: [{'actor': 'A', 'seq': 2, 'deps': {},
                'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                         'value': 1}]}]}
    with pytest.raises(ValueError, match='missing dependencies'):
        mesh_encode.encode_batch(bad)


def test_same_change_duplicate_assigns_are_exact_on_mesh_path():
    """One change setting a key twice keeps BOTH records in the reference
    (same-clock rows are mutually concurrent); the sliding-window kernel
    reproduces that exactly, so the mesh path needs no oracle fallback."""
    workload = {0: [{'actor': 'A', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': 'T'},
        {'action': 'ins', 'obj': 'T', 'key': '_head', 'elem': 1},
        {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'x'},
        {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'y'},
        {'action': 'del', 'obj': 'T', 'key': 'A:1'},
        {'action': 'link', 'obj': ROOT, 'key': 't', 'value': 'T'}]}]}
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(meta['max_arena']) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(workload, meta, out)
    # both set records survive (same-clock rows are concurrent) and the
    # same-change del kills neither
    alive = np.asarray(out['alive_after'])
    assert alive[0, meta['ops'][0][-1][0]] == 2


_map_workload = mesh_encode.demo_map_workload
_table_workload = mesh_encode.demo_table_workload


def test_map_workload_single_step_matches_pool():
    workload = _map_workload()
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(workload, meta, out)


def test_table_workload_single_step_matches_pool():
    workload = _table_workload()
    batch, meta = mesh_encode.encode_batch(workload)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(workload, meta, out)


@pytest.mark.parametrize('build', [_map_workload, _table_workload])
def test_config_shaped_workloads_through_sharded_step(build):
    import jax
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 devices')
    mesh = M.make_mesh(8, sp=2)
    workload = build()
    batch, meta = mesh_encode.encode_batch(workload, sp=2)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    step = M.build_sharded_step(mesh, n_linearize_iters=n_iters, chunk=16)
    out = step(M.shard_batch(mesh, batch))
    jax.block_until_ready(out)
    mesh_encode.verify_against_pool(workload, meta, out)


def test_out_of_order_and_duplicate_delivery_buffer_on_mesh_path():
    """Queued causal gaps: shuffled + duplicated delivery encodes via
    causal buffering and matches the pool (which buffers identically)."""
    import random
    workload = _map_workload(n_docs=2)
    rng = random.Random(11)
    shuffled = {}
    for d, chs in workload.items():
        chs = list(chs) + [dict(chs[0])]       # duplicate delivery
        rng.shuffle(chs)
        shuffled[d] = chs
    batch, meta = mesh_encode.encode_batch(shuffled)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(shuffled, meta, out)


def test_pre_existing_state_via_history():
    """Continuation batches: the doc's prior history replays ahead of
    the new changes; final clocks and map outcomes match a pool that saw
    both batches."""
    full = _map_workload(n_docs=2, n_rounds=2)
    history = {d: [c for c in chs if c['seq'] == 1]
               for d, chs in full.items()}
    new = {d: [c for c in chs if c['seq'] == 2]
           for d, chs in full.items()}
    batch, meta = mesh_encode.encode_batch(new, history_by_doc=history)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    # verification against a pool that ingested history + new
    mesh_encode.verify_against_pool(
        {d: history[d] + new[d] for d in full}, meta, out)
    assert all(r > 0 for r in meta['first_new_row'])


def test_route_workload_splits_overflow_docs_to_pool():
    """> WINDOW concurrent writers on one key cannot run on the mesh
    path (no host-oracle fallback there); route_workload diverts those
    docs to the pool at per-doc granularity."""
    ok = _map_workload(n_docs=2)
    hot = {  # 10 concurrent writers on ONE key -> window overflow
        'hot': [{'actor': 'w%02d' % a, 'seq': 1, 'deps': {},
                 'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                          'value': a}]} for a in range(10)]}
    workload = dict(ok, **hot)
    mesh_docs, pool_docs = mesh_encode.route_workload(workload)
    assert set(pool_docs) == {'hot'}
    assert set(mesh_docs) == set(ok)
    # the mesh half runs + verifies; the pool half resolves via the
    # pool's own overflow fallback with oracle parity
    batch, meta = mesh_encode.encode_batch(mesh_docs)
    n_iters = M.list_rank.ceil_log2(max(meta['max_arena'], 1)) + 1
    out = M.single_step(batch, n_linearize_iters=n_iters, chunk=16)
    mesh_encode.verify_against_pool(mesh_docs, meta, out)
    from automerge_tpu import backend as Backend
    from automerge_tpu.native import NativeDocPool
    pool = NativeDocPool()
    pool.apply_batch(pool_docs)
    st = Backend.init()
    st, _ = Backend.apply_changes(st, hot['hot'])
    assert pool.get_patch('hot') == Backend.get_patch(st)
