"""Unit coverage for the ISSUE-12 observability layer: the flight
recorder ring (telemetry/recorder.py), per-request critical-path
attribution + SLO windows (telemetry/attribution.py), and the
size-capped trace-file rotation (telemetry/spans.py)."""

import json
import os
import time

import pytest

from automerge_tpu import telemetry
from automerge_tpu.telemetry import attribution, recorder, spans


@pytest.fixture(autouse=True)
def _reset():
    telemetry.reset_all()
    attribution._exemplar_last = 0.0   # re-open the tail sampler
    yield
    telemetry.reset_all()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_ring_keeps_newest_on_wrap():
    r = recorder.Recorder(16)
    for i in range(50):
        r.record('batch.begin', n=i)
    snap = r.snapshot()
    assert len(snap) == 16
    seqs = [s[0] for s in snap]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 49
    assert seqs[0] == 50 - 16


def test_record_fields_and_tail():
    r = recorder.Recorder(32)
    t0 = time.time()
    r.record('resilience.quarantine', doc='doc-7', n=2, detail='Boom')
    ev = r.events_json()[-1]
    assert ev['event'] == 'resilience.quarantine'
    assert ev['doc'] == 'doc-7' and ev['n'] == 2
    assert ev['detail'] == 'Boom'
    assert r.tail(t0 - 1)[-1]['event'] == 'resilience.quarantine'
    assert r.tail(time.time() + 60) == []


def test_dump_writes_jsonl_and_rate_limits(tmp_path, monkeypatch):
    monkeypatch.setenv('AMTPU_RECORDER_DIR', str(tmp_path))
    r = recorder.Recorder(16)
    r.record('fault.injected', doc='p', detail='native.begin:permanent')
    out = r.dump('quarantine')
    assert out is not None and os.path.exists(out['path'])
    lines = [json.loads(ln) for ln in open(out['path'])]
    assert lines[0]['recorder_dump'] == 'quarantine'
    assert any(e.get('event') == 'fault.injected' for e in lines[1:])
    # second dump for the same reason inside the rate window is refused
    assert r.dump('quarantine') is None
    # ...but force (the on-demand `dump` request) always writes
    assert r.dump('quarantine', force=True) is not None
    assert telemetry.metrics_snapshot().get('recorder.dumps') == 2
    # healthz reports dumps WRITTEN, not trigger reasons attempted
    assert r.healthz_section()['dumps'] == 2


def test_dump_degrades_on_unwritable_dir(tmp_path, monkeypatch):
    # an uncreatable dump dir must degrade the DUMP (None +
    # recorder.dump_failed), never raise into the quarantine path
    blocker = tmp_path / 'blocker'
    blocker.write_text('x')
    monkeypatch.setenv('AMTPU_RECORDER_DIR',
                       str(blocker / 'sub'))   # parent is a file
    r = recorder.Recorder(16)
    r.record('batch.begin')
    assert r.dump('quarantine') is None
    assert telemetry.metrics_snapshot().get('recorder.dump_failed') == 1
    assert r.healthz_section()['dumps'] == 0


def test_module_ring_is_always_on():
    before = len(recorder.snapshot())
    recorder.record('shed.on', n=123)
    assert len(recorder.snapshot()) >= min(before + 1,
                                           recorder.RECORDER.size)
    assert recorder.events_json()[-1]['event'] == 'shed.on'


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def _stage_sums():
    fam = attribution._family()
    return {k: v['sum'] for k, v in (fam.snapshot() or {}).items()}


def test_stage_partition_sums_to_total():
    c = attribution.Clock('mutate')
    time.sleep(0.002)
    c.mark('admit')
    c.mark('queue')
    c.mark('claim')
    time.sleep(0.002)
    c.mark_split('dispatch', 'collect', 0.0005)
    c.mark('emit')
    c.add('fanout', 0.003)
    attribution.finish(c, ok=True, cmd='apply_changes', rid=1, doc='d')
    sums = _stage_sums()
    partition = sum(sums.get(s, 0.0) for s in
                    ('admit', 'queue', 'claim', 'dispatch', 'collect',
                     'emit'))
    assert sums['total'] == pytest.approx(partition, rel=1e-6)
    # the fan-out tail is attributed on top, never inside the total
    assert sums['fanout'] == pytest.approx(3.0, rel=0.05)
    assert telemetry.metrics_snapshot().get('slo.requests') == 1


def test_mark_split_clamps_to_segment():
    c = attribution.Clock('read')
    c.mark('admit')
    c.mark_split('dispatch', 'collect', 10.0)   # larger than the wall
    d = dict(c.stages)
    assert d['dispatch'] == 0.0
    assert d['collect'] < 1.0


def test_slow_request_emits_exemplar(tmp_path, monkeypatch):
    monkeypatch.setenv('AMTPU_SLOW_MS', '1')
    trace_file = tmp_path / 'spans.jsonl'
    spans.set_trace_file(str(trace_file))
    try:
        c = attribution.Clock('mutate')
        time.sleep(0.005)
        c.mark('admit')
        c.mark('emit')
        attribution.finish(c, ok=True, cmd='apply_changes', rid=9,
                           doc='slow-doc')
        recs = [json.loads(ln) for ln in open(trace_file)]
    finally:
        spans.set_trace_file(None)
    roots = [r for r in recs if r['name'] == 'request.exemplar']
    assert roots and roots[-1]['attrs']['doc'] == 'slow-doc'
    assert roots[-1]['events'] is not None
    kids = [r for r in recs if r.get('parent') == roots[-1]['span']]
    assert {k['name'] for k in kids} >= {'request.stage.admit',
                                         'request.stage.emit'}
    assert attribution.recent_exemplars()[-1]['attrs']['rid'] == 9
    assert telemetry.metrics_snapshot().get('slo.exemplars', 0) >= 1


def test_failed_request_always_sampled(monkeypatch):
    monkeypatch.setenv('AMTPU_SLOW_MS', '60000')
    before = telemetry.metrics_snapshot().get('slo.exemplars', 0)
    c = attribution.Clock('mutate')
    c.mark('admit')
    c.mark('emit')
    attribution.finish(c, ok=False, cmd='apply_changes', rid=2, doc='q')
    assert telemetry.metrics_snapshot().get('slo.exemplars') == \
        before + 1


def test_exemplar_rate_limit(monkeypatch):
    # an error storm must not emit one exemplar per failing request
    monkeypatch.setenv('AMTPU_SLOW_MS', '60000')
    monkeypatch.setenv('AMTPU_EXEMPLAR_MIN_S', '30')
    before = telemetry.metrics_snapshot().get('slo.exemplars', 0)
    for i in range(10):
        c = attribution.Clock('mutate')
        c.mark('admit')
        c.mark('emit')
        attribution.finish(c, ok=False, cmd='apply_changes', rid=i)
    assert telemetry.metrics_snapshot().get('slo.exemplars') == \
        before + 1


def test_flush_phase_bracket_is_thread_scoped():
    assert attribution.flush_phases_end() == {}
    attribution.note_flush_phase('collect', 1.0)   # outside a bracket
    attribution.flush_phases_begin()
    attribution.note_flush_phase('collect', 0.25)
    attribution.note_flush_phase('collect', 0.25)
    attribution.note_flush_phase('dispatch', 0.1)
    got = attribution.flush_phases_end()
    assert got == {'collect': 0.5, 'dispatch': 0.1}
    assert attribution.flush_phases_end() == {}


def test_slo_windows_and_burn(monkeypatch):
    monkeypatch.setenv('AMTPU_SLO_P99_MS', '10')
    slo = attribution._SloWindows()
    for _ in range(99):
        slo.observe('mutate', 1.0, False)
    slo.observe('mutate', 500.0, True)
    monkeypatch.setattr(attribution, '_SLO', slo)
    sec = attribution.slo_section()
    w = sec['classes']['mutate']['60s']
    assert w['count'] == 100
    assert w['p50_ms'] <= 10
    assert w['p99_ms'] >= 1.0
    assert w['breach_frac'] == pytest.approx(0.01)
    # 1% breaches == exactly the 1% budget -> burn 1.0
    assert sec['burn']['300s'] == pytest.approx(1.0)
    assert sec['target_p99_ms'] == 10


def test_class_of_covers_protocol():
    assert attribution.class_of('apply_changes') == 'mutate'
    assert attribution.class_of('load') == 'mutate'
    assert attribution.class_of('subscribe') == 'control'
    assert attribution.class_of('get_patch') == 'read'


# ---------------------------------------------------------------------------
# trace-file rotation (satellite: bounded span export)
# ---------------------------------------------------------------------------

def test_trace_file_rotates_at_cap(tmp_path, monkeypatch):
    # the env helper reads MB; 1 MB cap keeps the test fast
    monkeypatch.setenv('AMTPU_TRACE_FILE_MAX_MB', '1')
    path = str(tmp_path / 'trace.jsonl')
    spans.set_trace_file(path)
    telemetry.enable()
    try:
        big = 'x' * 8192
        for i in range(200):            # ~1.6 MB of spans
            with telemetry.span('rotate.test', blob=big):
                pass
    finally:
        telemetry.disable()
        spans.set_trace_file(None)
    assert os.path.exists(path + '.1'), 'rotation never triggered'
    assert os.path.getsize(path + '.1') <= 1.2 * 1024 * 1024
    assert os.path.getsize(path) <= 1.2 * 1024 * 1024
    # both generations stay valid JSONL (rotation never tears a line)
    for p in (path, path + '.1'):
        with open(p) as f:
            for ln in f:
                json.loads(ln)


def test_trace_file_cap_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv('AMTPU_TRACE_FILE_MAX_MB', '0')
    path = str(tmp_path / 'trace.jsonl')
    spans.set_trace_file(path)
    telemetry.enable()
    try:
        for _i in range(5):
            with telemetry.span('norotate.test', blob='y' * 64):
                pass
    finally:
        telemetry.disable()
        spans.set_trace_file(None)
    assert not os.path.exists(path + '.1')


def test_export_record_without_tracing(tmp_path):
    path = str(tmp_path / 'trace.jsonl')
    spans.set_trace_file(path)
    try:
        assert not telemetry.enabled()
        spans.export_record({'name': 'exemplar.probe', 'x': 1})
        rec = json.loads(open(path).readline())
    finally:
        spans.set_trace_file(None)
    assert rec == {'name': 'exemplar.probe', 'x': 1}
