"""Seeded dispatch-alias violations (tests/test_analysis.py): the
post-dispatch staging mutation the PR-4/PR-6 hardening rounds kept
re-finding by hand."""

import numpy as np
import jax.numpy as jnp


def _jit_scatter(donate):
    raise NotImplementedError('fixture only')


def post_dispatch_mutation(tab):
    idx = np.arange(16, dtype=np.int32)
    rows = np.zeros((16, 4), np.int32)
    out = _jit_scatter(False)(tab, idx, rows)
    # violations: both staging arrays are refilled while the dispatch
    # may still be reading them
    rows.fill(0)
    idx[0] = 7
    return out


def jnp_array_alias(host):
    dev = jnp.array(host)
    host[0] = -1          # violation: jnp.array's copy can defer
    return dev


def tls_staging(self_like, vals):
    # violation: thread-local staging buffer without a private copy
    return jnp.asarray(self_like._tls.buf)


def loop_staging_reuse(tab, chunks):
    buf = np.empty(64, np.int32)
    out = []
    for chunk in chunks:
        buf[:16] = chunk          # violation: refills the buffer the
        out.append(jnp.array(buf))  # previous iteration still stages
    return out


def loop_fresh_buffer(tab, chunks):
    out = []
    for chunk in chunks:
        buf = np.array(chunk, np.int32)   # NOT flagged: fresh per
        out.append(jnp.array(buf))        # iteration (rebound in loop)
    return out


def clean_private_copy(tab, idx, rows):
    # NOT flagged: the dispatch gets private synchronous copies, and
    # rebinding releases the capture
    out = _jit_scatter(True)(tab, np.array(idx), np.array(rows))
    idx = np.arange(4)
    idx[0] = 1
    return out
