"""Seeded env-latch violations (tests/test_analysis.py): the checker
must flag every block below; nothing here is ever imported."""

import os

from automerge_tpu.utils.common import env_float, env_int


def direct_read():
    # violation: raw os.environ read outside utils/common
    return os.environ.get('AMTPU_RESIDENT')


def unknown_flag():
    # violation: flag not registered in env_spec.ENV_FLAGS
    return env_int('AMTPU_FIXTURE_BOGUS_FLAG', 1)


def default_drift():
    # violation: spec default for AMTPU_PIPELINE_DEPTH is 2
    return env_int('AMTPU_PIPELINE_DEPTH', 3)


def type_drift():
    # violation: AMTPU_MAX_TIER is an int flag
    return env_float('AMTPU_MAX_TIER', 1024)
