"""Seeded telemetry-key violations (tests/test_analysis.py)."""

from automerge_tpu import trace


def unseeded_counter():
    # violation: not in KNOWN_RESIDENT_BATCH_KEYS (and undocumented)
    trace.metric('resident.batch_fixture_bogus')


def undeclared_dynamic():
    # violation: formatted key in a pre-seeded namespace that matches
    # no DYNAMIC_KEY_PATTERNS family
    trace.metric('scheduler.fixture_%d' % 3)
