"""Seeded lock-discipline violations (tests/test_analysis.py)."""

import threading


class Guarded(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}          # guarded-by: self._lock
        self._depth = 0           # guarded-by(w): self._lock

    def ok(self):
        with self._lock:
            self._state['x'] = self._depth
            return len(self._state)

    def ok_writes_only_read(self):
        return self._depth        # NOT flagged: guarded-by(w)

    def ok_holder(self):          # holds-lock: self._lock
        return self._state.get('x')

    def bad_load(self):
        return self._state.get('x')     # violation

    def bad_store(self):
        with self._lock:
            pass
        self._depth += 1                # violation (outside the with)
