"""Backend unit tests: hand-written change JSON in, exact patch JSON out.

Ported from `/root/reference/test/backend_test.js` -- these fixtures are the
differential-testing seam: any backend implementation (oracle or TPU batch
engine) must produce identical patches for these exact inputs.
"""

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.errors import RangeError
from automerge_tpu.utils.uuid import uuid

ROOT_ID = '00000000-0000-0000-0000-000000000000'


class TestIncrementalDiffs:
    def test_assign_to_a_key_in_a_map(self):
        actor = uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}
        ]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [change1])
        assert patch1 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'path': [],
                       'type': 'map', 'key': 'bird', 'value': 'magpie'}]
        }

    def test_conflict_on_assignment_to_same_key(self):
        change1 = {'actor': 'actor1', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}
        ]}
        change2 = {'actor': 'actor2', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'blackbird'}
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch2 = Backend.apply_changes(s1, [change2])
        assert patch2 == {
            'canUndo': False, 'canRedo': False,
            'clock': {'actor1': 1, 'actor2': 1},
            'deps': {'actor1': 1, 'actor2': 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'path': [], 'type': 'map',
                       'key': 'bird', 'value': 'blackbird',
                       'conflicts': [{'actor': 'actor1', 'value': 'magpie'}]}]
        }

    def test_delete_key_from_map(self):
        actor = uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}
        ]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'del', 'obj': ROOT_ID, 'key': 'bird'}
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch2 = Backend.apply_changes(s1, [change2])
        assert patch2 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [{'action': 'remove', 'obj': ROOT_ID, 'path': [],
                       'type': 'map', 'key': 'bird'}]
        }

    def test_create_nested_maps(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeMap', 'obj': birds},
            {'action': 'set', 'obj': birds, 'key': 'wrens', 'value': 3},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}
        ]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [change1])
        assert patch1 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': birds, 'type': 'map'},
                {'action': 'set', 'obj': birds, 'type': 'map', 'path': None,
                 'key': 'wrens', 'value': 3},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map', 'path': [],
                 'key': 'birds', 'value': birds, 'link': True}
            ]
        }

    def test_assign_to_keys_in_nested_maps(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeMap', 'obj': birds},
            {'action': 'set', 'obj': birds, 'key': 'wrens', 'value': 3},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}
        ]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': birds, 'key': 'sparrows', 'value': 15}
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch2 = Backend.apply_changes(s1, [change2])
        assert patch2 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [{'action': 'set', 'obj': birds, 'type': 'map',
                       'path': ['birds'], 'key': 'sparrows', 'value': 15}]
        }

    def test_create_lists(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': birds},
            {'action': 'ins', 'obj': birds, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'chaffinch'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}
        ]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [change1])
        assert patch1 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': birds, 'type': 'list'},
                {'action': 'insert', 'obj': birds, 'type': 'list', 'path': None,
                 'index': 0, 'value': 'chaffinch', 'elemId': '%s:1' % actor},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map', 'path': [],
                 'key': 'birds', 'value': birds, 'link': True}
            ]
        }

    def test_apply_updates_inside_lists(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': birds},
            {'action': 'ins', 'obj': birds, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'chaffinch'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}
        ]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'greenfinch'}
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch2 = Backend.apply_changes(s1, [change2])
        assert patch2 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [{'action': 'set', 'obj': birds, 'type': 'list',
                       'path': ['birds'], 'index': 0, 'value': 'greenfinch'}]
        }

    def test_delete_list_elements(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': birds},
            {'action': 'ins', 'obj': birds, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'chaffinch'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}
        ]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'del', 'obj': birds, 'key': '%s:1' % actor}
        ]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, patch2 = Backend.apply_changes(s1, [change2])
        assert patch2 == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [{'action': 'remove', 'obj': birds, 'type': 'list',
                       'path': ['birds'], 'index': 0}]
        }

    def test_timestamp_at_root(self):
        now = 1234567890123
        actor = uuid()
        change = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'now', 'value': now,
             'datatype': 'timestamp'}
        ]}
        s0 = Backend.init()
        s1, patch = Backend.apply_changes(s0, [change])
        assert patch == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                       'path': [], 'key': 'now', 'value': now,
                       'datatype': 'timestamp'}]
        }

    def test_timestamp_in_list(self):
        now, lst, actor = 1234567890123, uuid(), uuid()
        change = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': lst},
            {'action': 'ins', 'obj': lst, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': lst, 'key': '%s:1' % actor, 'value': now,
             'datatype': 'timestamp'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'list', 'value': lst}
        ]}
        s0 = Backend.init()
        s1, patch = Backend.apply_changes(s0, [change])
        assert patch == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': lst, 'type': 'list'},
                {'action': 'insert', 'obj': lst, 'type': 'list', 'path': None,
                 'index': 0, 'value': now, 'elemId': '%s:1' % actor,
                 'datatype': 'timestamp'},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map', 'path': [],
                 'key': 'list', 'value': lst, 'link': True}
            ]
        }


class TestApplyLocalChange:
    def test_apply_change_requests(self):
        actor = uuid()
        change1 = {'requestType': 'change', 'actor': actor, 'seq': 1, 'deps': {},
                   'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                            'value': 'magpie'}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_local_change(s0, change1)
        assert patch1 == {
            'actor': actor, 'seq': 1, 'canUndo': True, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'path': [],
                       'type': 'map', 'key': 'bird', 'value': 'magpie'}]
        }

    def test_throws_on_duplicate_requests(self):
        actor = uuid()
        change1 = {'requestType': 'change', 'actor': actor, 'seq': 1, 'deps': {},
                   'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                            'value': 'magpie'}]}
        change2 = {'requestType': 'change', 'actor': actor, 'seq': 2, 'deps': {},
                   'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                            'value': 'jay'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_local_change(s0, change1)
        s2, _ = Backend.apply_local_change(s1, change2)
        with pytest.raises(RangeError, match='Change request has already been applied'):
            Backend.apply_local_change(s2, change1)
        with pytest.raises(RangeError, match='Change request has already been applied'):
            Backend.apply_local_change(s2, change2)


class TestGetPatch:
    def test_most_recent_value_for_key(self):
        actor = uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'blackbird'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                       'key': 'bird', 'value': 'blackbird'}]
        }

    def test_conflicting_values_for_key(self):
        change1 = {'actor': 'actor1', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}]}
        change2 = {'actor': 'actor2', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'blackbird'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {'actor1': 1, 'actor2': 1},
            'deps': {'actor1': 1, 'actor2': 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                       'key': 'bird', 'value': 'blackbird',
                       'conflicts': [{'actor': 'actor1', 'value': 'magpie'}]}]
        }

    def test_nested_maps(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeMap', 'obj': birds},
            {'action': 'set', 'obj': birds, 'key': 'wrens', 'value': 3},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'del', 'obj': birds, 'key': 'wrens'},
            {'action': 'set', 'obj': birds, 'key': 'sparrows', 'value': 15}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [
                {'action': 'create', 'obj': birds, 'type': 'map'},
                {'action': 'set', 'obj': birds, 'type': 'map',
                 'key': 'sparrows', 'value': 15},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                 'key': 'birds', 'value': birds, 'link': True}
            ]
        }

    def test_create_lists(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': birds},
            {'action': 'ins', 'obj': birds, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'chaffinch'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': birds, 'type': 'list'},
                {'action': 'insert', 'obj': birds, 'type': 'list', 'index': 0,
                 'value': 'chaffinch', 'elemId': '%s:1' % actor},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                 'key': 'birds', 'value': birds, 'link': True}
            ]
        }

    def test_latest_state_of_list(self):
        birds, actor = uuid(), uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': birds},
            {'action': 'ins', 'obj': birds, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': birds, 'key': '%s:1' % actor, 'value': 'chaffinch'},
            {'action': 'ins', 'obj': birds, 'key': '%s:1' % actor, 'elem': 2},
            {'action': 'set', 'obj': birds, 'key': '%s:2' % actor, 'value': 'goldfinch'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'birds', 'value': birds}]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'del', 'obj': birds, 'key': '%s:1' % actor},
            {'action': 'ins', 'obj': birds, 'key': '%s:1' % actor, 'elem': 3},
            {'action': 'set', 'obj': birds, 'key': '%s:3' % actor, 'value': 'greenfinch'},
            {'action': 'set', 'obj': birds, 'key': '%s:2' % actor, 'value': 'goldfinches!!'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1, change2])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 2}, 'deps': {actor: 2},
            'diffs': [
                {'action': 'create', 'obj': birds, 'type': 'list'},
                {'action': 'insert', 'obj': birds, 'type': 'list', 'index': 0,
                 'value': 'greenfinch', 'elemId': '%s:3' % actor},
                {'action': 'insert', 'obj': birds, 'type': 'list', 'index': 1,
                 'value': 'goldfinches!!', 'elemId': '%s:2' % actor},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                 'key': 'birds', 'value': birds, 'link': True}
            ]
        }

    def test_nested_maps_in_lists(self):
        todos, item, actor = uuid(), uuid(), uuid()
        change = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': todos},
            {'action': 'ins', 'obj': todos, 'key': '_head', 'elem': 1},
            {'action': 'makeMap', 'obj': item},
            {'action': 'set', 'obj': item, 'key': 'title', 'value': 'water plants'},
            {'action': 'set', 'obj': item, 'key': 'done', 'value': False},
            {'action': 'link', 'obj': todos, 'key': '%s:1' % actor, 'value': item},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'todos', 'value': todos}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': item, 'type': 'map'},
                {'action': 'set', 'obj': item, 'type': 'map',
                 'key': 'title', 'value': 'water plants'},
                {'action': 'set', 'obj': item, 'type': 'map',
                 'key': 'done', 'value': False},
                {'action': 'create', 'obj': todos, 'type': 'list'},
                {'action': 'insert', 'obj': todos, 'type': 'list', 'index': 0,
                 'value': item, 'link': True, 'elemId': '%s:1' % actor},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                 'key': 'todos', 'value': todos, 'link': True}
            ]
        }

    def test_timestamps_at_root(self):
        now = 1234567890123
        actor = uuid()
        change = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'now', 'value': now,
             'datatype': 'timestamp'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                       'key': 'now', 'value': now, 'datatype': 'timestamp'}]
        }

    def test_timestamps_in_list(self):
        now, lst, actor = 1234567890123, uuid(), uuid()
        change = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': lst},
            {'action': 'ins', 'obj': lst, 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': lst, 'key': '%s:1' % actor, 'value': now,
             'datatype': 'timestamp'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'list', 'value': lst}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change])
        assert Backend.get_patch(s1) == {
            'canUndo': False, 'canRedo': False,
            'clock': {actor: 1}, 'deps': {actor: 1},
            'diffs': [
                {'action': 'create', 'obj': lst, 'type': 'list'},
                {'action': 'insert', 'obj': lst, 'type': 'list', 'index': 0,
                 'value': now, 'elemId': '%s:1' % actor, 'datatype': 'timestamp'},
                {'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                 'key': 'list', 'value': lst, 'link': True}
            ]
        }


class TestStatePersistence:
    """The COW fork must preserve old states (Immutable.js parity)."""

    def test_old_state_remains_valid(self):
        actor = uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'jay'}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [change1])
        s2, _ = Backend.apply_changes(s1, [change2])
        # s1 must still materialize the old value even after s2 advanced
        patch1 = Backend.get_patch(s1)
        assert patch1['diffs'][-1]['value'] == 'magpie'
        assert patch1['clock'] == {actor: 1}
        patch2 = Backend.get_patch(s2)
        assert patch2['diffs'][-1]['value'] == 'jay'
        # and s0 is still empty
        assert Backend.get_patch(s0) == {
            'canUndo': False, 'canRedo': False, 'clock': {}, 'deps': {},
            'diffs': []
        }

    def test_causally_buffered_changes(self):
        """Changes with unmet deps sit in the queue until prerequisites
        arrive (reference: op_set.js:279-295, test/test.js:1319-1344)."""
        actor = uuid()
        change1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'a', 'value': 1}]}
        change2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'b', 'value': 2}]}
        s0 = Backend.init()
        # deliver out of order: change2 first
        s1, patch1 = Backend.apply_changes(s0, [change2])
        assert patch1['diffs'] == []
        assert Backend.get_missing_deps(s1) == {actor: 1}
        s2, patch2 = Backend.apply_changes(s1, [change1])
        # both changes apply once the dependency arrives
        assert [d['key'] for d in patch2['diffs']] == ['a', 'b']
        assert Backend.get_missing_deps(s2) == {}
