"""Batched replica catch-up: convergence, fault tolerance, oracle parity.

Semantics under test mirror the reference's Connection behavior
(`/root/reference/src/connection.js:58-73`) and its multi-node test DSL's
fault model (`/root/reference/test/connection_test.js:17-66`): dropped
messages heal on later rounds, duplicate deliveries are no-ops.
"""

import random

import pytest

from automerge_tpu.backend import apply_changes as oracle_apply
from automerge_tpu.backend import get_patch as oracle_get_patch
from automerge_tpu.backend import init as oracle_init
from automerge_tpu.native import NativeDocPool
from automerge_tpu.parallel.engine import TPUDocPool
from automerge_tpu.sync.replica_set import BatchedReplicaSet, patch_to_tree

ROOT = '00000000-0000-0000-0000-000000000000'


def partitioned_history(n_replicas, n_docs, rounds=3, seed=5):
    """Each replica authors one actor's changes per doc: the classic
    fully-partitioned backlog (nobody has anyone else's stream)."""
    rng = random.Random(seed)
    by_replica = [dict() for _ in range(n_replicas)]
    all_changes = {}
    for d in range(n_docs):
        doc = 'doc%d' % d
        all_changes[doc] = []
        for r in range(n_replicas):
            actor = 'a%d' % r
            for seq in range(1, rounds + 1):
                change = {'actor': actor, 'seq': seq, 'deps': {},
                          'ops': [{'action': 'set', 'obj': ROOT,
                                   'key': 'k%d' % rng.randrange(4),
                                   'value': '%s-%d' % (actor, seq)}]}
                by_replica[r].setdefault(doc, []).append(change)
                all_changes[doc].append(change)
    return by_replica, all_changes


@pytest.mark.parametrize('pool_factory', [NativeDocPool, TPUDocPool])
def test_partitioned_backlog_converges(pool_factory):
    rs = BatchedReplicaSet(4, pool_factory=pool_factory)
    by_replica, all_changes = partitioned_history(4, 3)
    for r, by_doc in enumerate(by_replica):
        rs.apply_batch(r, by_doc)
    assert not rs.converged()
    rounds = rs.catch_up()
    assert rs.converged()
    assert rounds[-1] == 0
    # byte parity across replicas AND against the oracle fed the union
    for doc, changes in all_changes.items():
        patch = rs.assert_identical(doc)
        state = oracle_init()
        state, _ = oracle_apply(state, changes)
        want = oracle_get_patch(state)
        assert patch['clock'] == want['clock']
        assert patch_to_tree(patch) == patch_to_tree(want)


def test_dropped_shipments_heal_on_later_rounds():
    dropped = []

    def drop(sender, receiver, doc_id):
        # drop the first 5 shipments outright
        if len(dropped) < 5:
            dropped.append((sender, receiver, doc_id))
            return True
        return False

    rs = BatchedReplicaSet(3, drop=drop)
    by_replica, all_changes = partitioned_history(3, 2)
    for r, by_doc in enumerate(by_replica):
        rs.apply_batch(r, by_doc)
    rs.catch_up()
    assert rs.converged()
    assert len(dropped) == 5
    for doc in all_changes:
        rs.assert_identical(doc)


def test_duplicate_deliveries_are_noops():
    rs = BatchedReplicaSet(3)
    by_replica, all_changes = partitioned_history(3, 2)
    for r, by_doc in enumerate(by_replica):
        rs.apply_batch(r, by_doc)
        # deliver the same batch again: seq dedup must no-op
        patches = rs.apply_batch(r, by_doc)
        assert all(p['diffs'] == [] for p in patches.values())
    rs.catch_up()
    assert rs.converged()
    for doc in all_changes:
        rs.assert_identical(doc)


def test_causal_gap_buffers_until_stream_arrives():
    """A change referencing another actor's unseen change queues, then
    applies once catch-up ships the dependency."""
    rs = BatchedReplicaSet(2)
    rs.apply_changes(0, 'd', [
        {'actor': 'a0', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'x', 'value': 1}]}])
    # replica 1 authors a change DEPENDING on a0's change it has...
    rs.apply_changes(1, 'd', [
        {'actor': 'a0', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'x', 'value': 1}]}])
    rs.apply_changes(1, 'd', [
        {'actor': 'a1', 'seq': 1, 'deps': {'a0': 1},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'y', 'value': 2}]}])
    # replica 0 receives a1's change OUT OF ORDER relative to... it already
    # has a0:1, so ship a1's stream via catch-up and confirm convergence
    rs.catch_up()
    assert rs.converged()
    patch = rs.assert_identical('d')
    keys = {d['key'] for d in patch['diffs']}
    assert keys == {'x', 'y'}


def test_sixteen_replica_text_backlog():
    """Mid-size RGA stress: 16 replicas, concurrent text edits, full
    catch-up converges byte-identically."""
    n = 16
    rs = BatchedReplicaSet(n)
    # seed change shared by all replicas (the doc's creation)
    seed = {'actor': 'a0', 'seq': 1, 'deps': {},
            'ops': [{'action': 'makeText', 'obj': 'T'},
                    {'action': 'ins', 'obj': 'T', 'key': '_head',
                     'elem': 1},
                    {'action': 'set', 'obj': 'T', 'key': 'a0:1',
                     'value': 'x'},
                    {'action': 'link', 'obj': ROOT, 'key': 'text',
                     'value': 'T'}]}
    all_changes = [seed]
    for r in range(n):
        rs.apply_changes(r, 'd', [dict(seed)])
    for r in range(n):
        actor = 'a%d' % r
        seq0 = 2 if r == 0 else 1
        ops = []
        for i in range(4):
            elem = 100 + r * 10 + i
            prev = 'a0:1' if i == 0 else '%s:%d' % (actor, elem - 1)
            ops.append({'action': 'ins', 'obj': 'T', 'key': prev,
                        'elem': elem})
            ops.append({'action': 'set', 'obj': 'T',
                        'key': '%s:%d' % (actor, elem),
                        'value': chr(97 + (r + i) % 26)})
        change = {'actor': actor, 'seq': seq0, 'deps': {'a0': 1},
                  'ops': ops}
        rs.apply_changes(r, 'd', [change])
        all_changes.append(change)
    rs.catch_up()
    assert rs.converged()
    patch = rs.assert_identical('d')
    state = oracle_init()
    state, _ = oracle_apply(state, all_changes)
    want = oracle_get_patch(state)
    assert patch['clock'] == want['clock']
    assert patch_to_tree(patch) == patch_to_tree(want)
