"""Clock-vector folding + parallel restore lanes (ISSUE 17,
docs/STORAGE.md): `amtpu_fold_clocks` folds settled per-change
`all_deps` clock vectors into the densified per-doc table, and every
causal query -- straggler backfill, `get_missing_changes` /
`get_changes_for_actor`, missing-clock frames, undo/redo -- must answer
byte-identically to an unfolded (`AMTPU_STORAGE_FOLD_CLOCKS=0`) twin,
across both exec modes, `ShardedNativePool`, and the dp=4 mesh.  Plus
the `restore_from_store` parallel cold start: summary accounting,
`storage.restore.*` counters, and the corrupt-blob quarantine."""

import os
import random

import pytest

from automerge_tpu import telemetry
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.storage.coldstore import ColdStore, ColdStoreCorrupt

ROOT = '00000000-0000-0000-0000-000000000000'


@pytest.fixture(autouse=True)
def _reset():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


@pytest.fixture(params=['default', 'kernel'])
def exec_mode(request):
    """Both execution modes face the parity lanes (same pattern as
    tests/test_storage_native.py): folded clock reads resolve host-side
    in C++, so their output must match under the CPU default AND the
    forced kernel path."""
    if request.param == 'kernel':
        prior = {k: os.environ.get(k)
                 for k in ('AMTPU_HOST_FULL', 'AMTPU_HOST_REG')}
        os.environ['AMTPU_HOST_FULL'] = '0'
        os.environ['AMTPU_HOST_REG'] = '0'
        yield 'kernel'
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    else:
        yield 'default'


@pytest.fixture
def fold_env():
    """Set/unset AMTPU_STORAGE_FOLD_CLOCKS per arm (checked per call,
    so flipping the env interleaves cleanly)."""
    prior = os.environ.get('AMTPU_STORAGE_FOLD_CLOCKS')

    def arm(folded):
        os.environ['AMTPU_STORAGE_FOLD_CLOCKS'] = '1' if folded else '0'
    yield arm
    if prior is None:
        os.environ.pop('AMTPU_STORAGE_FOLD_CLOCKS', None)
    else:
        os.environ['AMTPU_STORAGE_FOLD_CLOCKS'] = prior


def _history(doc_idx, rounds=6, actors=3):
    """Interleaved multi-actor history with catch-up deps -- the shape
    whose all_deps vectors grow O(history) without folding."""
    chs = []
    clock = {}
    for r in range(rounds):
        actor = 'a%d' % ((doc_idx + r) % actors)
        clock[actor] = clock.get(actor, 0) + 1
        chs.append({'actor': actor, 'seq': clock[actor],
                    'deps': {a: s for a, s in clock.items()
                             if a != actor},
                    'ops': [{'action': 'set', 'obj': ROOT,
                             'key': 'k%d' % (r % 4),
                             'value': doc_idx * 100 + r}]})
    return chs


def _build_twins(fold_env, make_folded, make_unfolded, n_docs=12,
                 rounds=6, compact=True):
    """Identical corpora into a folded and an unfolded pool; compaction
    drives `_fold_settled` + `_fold_clocks` on the folded arm only."""
    pools = []
    for folded, make in ((True, make_folded), (False, make_unfolded)):
        fold_env(folded)
        pool = make()
        pool.apply_batch({('doc%02d' % d): _history(d, rounds)
                          for d in range(n_docs)})
        if compact:
            for d in range(n_docs):
                pool.compact('doc%02d' % d)
        pools.append(pool)
    return pools


def test_fold_frees_pairs_and_acct_reconciles(fold_env, exec_mode):
    folded, unfolded = _build_twins(fold_env, NativeDocPool,
                                    NativeDocPool)
    _ids, fstats = folded.doc_stats()
    _ids, ustats = unfolded.doc_stats()
    # the folded arm's clock memory (sparse pairs + fold table) is
    # strictly below the unfolded arm's sparse pairs
    fold_mem = int((fstats[:, 6] * 8 + fstats[:, 7]).sum())
    unfold_mem = int((ustats[:, 6] * 8 + ustats[:, 7]).sum())
    assert fold_mem < unfold_mem
    assert int(fstats[:, 7].sum()) > 0          # fold table engaged
    # acct column == fresh-walk oracle, both arms
    assert int(fstats[:, 6].sum()) == folded.clock_pairs()
    assert int(ustats[:, 6].sum()) == unfolded.clock_pairs()
    assert telemetry.metrics_snapshot().get(
        'storage.gc.clocks_folded', 0) > 0
    # the unfolded arm must not have folded anything
    assert int(ustats[:, 7].sum()) == 0


def test_causal_queries_parity(fold_env, exec_mode):
    folded, unfolded = _build_twins(fold_env, NativeDocPool,
                                    NativeDocPool)
    for d in range(12):
        doc = 'doc%02d' % d
        assert folded.save(doc) == unfolded.save(doc)
        assert folded.get_patch(doc) == unfolded.get_patch(doc)
        # missing-clock frames byte-identical at multiple clocks
        for have in ({}, {'a0': 1}, {'a0': 2, 'a1': 1},
                     {'a0': 99, 'a1': 99, 'a2': 99}):
            assert folded._missing_clock(doc, have) \
                == unfolded._missing_clock(doc, have)
            assert folded.get_missing_changes(doc, have) \
                == unfolded.get_missing_changes(doc, have)
        # straggler backfill per actor
        for actor in ('a0', 'a1', 'a2'):
            for after in (0, 1):
                assert folded.get_changes_for_actor(doc, actor, after) \
                    == unfolded.get_changes_for_actor(doc, actor, after)


def test_fold_then_more_history_parity(fold_env, exec_mode):
    """Changes applied AFTER a fold must seed their deps through the
    folded rows (update_states reads all_deps via the fold table) --
    the drift the ISSUE forbids."""
    folded, unfolded = _build_twins(fold_env, NativeDocPool,
                                    NativeDocPool)
    for arm, pool in ((True, folded), (False, unfolded)):
        for r in range(4):
            pool.apply_batch({('doc%02d' % d): [
                {'actor': 'a0', 'seq': 7 + r, 'deps': {'a1': 2, 'a2': 2}
                 if r == 0 else {}, 'ops': [
                     {'action': 'set', 'obj': ROOT, 'key': 'late',
                      'value': r}]}] for d in range(12)})
    for d in range(12):
        doc = 'doc%02d' % d
        assert folded.save(doc) == unfolded.save(doc)
        assert folded.get_patch(doc) == unfolded.get_patch(doc)
        assert folded.get_missing_changes(doc, {'a0': 6}) \
            == unfolded.get_missing_changes(doc, {'a0': 6})


def test_undo_redo_parity_at_multiple_clocks(fold_env):
    """Undo/redo through apply_local_change against the unfolded twin,
    folding between rounds on the folded arm only."""
    fold_env(True)
    folded = NativeDocPool()
    fold_env(False)
    unfolded = NativeDocPool()
    for r in range(5):
        req = {'requestType': 'change', 'actor': 'u1', 'seq': r + 1,
               'deps': {}, 'ops': [{'action': 'set', 'obj': ROOT,
                                    'key': 'k%d' % (r % 2),
                                    'value': r}]}
        fold_env(True)
        pf = folded.apply_local_change('u', dict(req))
        folded.compact('u')
        fold_env(False)
        pu = unfolded.apply_local_change('u', dict(req))
        unfolded.compact('u')
        assert pf == pu
    seq = 6
    for kind in ('undo', 'undo', 'redo', 'undo', 'redo', 'redo'):
        req = {'requestType': kind, 'actor': 'u1', 'seq': seq,
               'deps': {}}
        seq += 1
        fold_env(True)
        pf = folded.apply_local_change('u', dict(req))
        folded.compact('u')
        fold_env(False)
        pu = unfolded.apply_local_change('u', dict(req))
        unfolded.compact('u')
        assert pf == pu
    assert folded.get_patch('u') == unfolded.get_patch('u')
    assert folded.save('u') == unfolded.save('u')


def test_sharded_and_mesh_parity(fold_env):
    """ShardedNativePool + the dp=4 mesh with folding on answer
    byte-identically to a flat unfolded NativeDocPool."""
    from automerge_tpu.native.mesh_pool import MeshDocPool
    for make in (lambda: ShardedNativePool(4),
                 lambda: MeshDocPool(dp=4)):
        telemetry.reset_all()
        folded, unfolded = _build_twins(fold_env, make, NativeDocPool)
        for d in range(12):
            doc = 'doc%02d' % d
            assert folded.save(doc) == unfolded.save(doc)
            assert folded.get_patch(doc) == unfolded.get_patch(doc)
            assert folded.get_missing_changes(doc, {'a1': 1}) \
                == unfolded.get_missing_changes(doc, {'a1': 1})
        assert folded.clock_pairs() < unfolded.clock_pairs()


def test_fold_actor_population_cap(fold_env, monkeypatch):
    """Docs whose history spans more actors than
    AMTPU_FOLDCLK_MAX_ACTORS keep those entries sparse -- and still
    answer identically."""
    monkeypatch.setenv('AMTPU_FOLDCLK_MAX_ACTORS', '2')
    folded, unfolded = _build_twins(fold_env, NativeDocPool,
                                    NativeDocPool, n_docs=4, rounds=8)
    # 3 actors > cap 2: the wide entries stay sparse (pairs remain)
    assert folded.clock_pairs() > 0
    for d in range(4):
        doc = 'doc%02d' % d
        assert folded.save(doc) == unfolded.save(doc)
        assert folded.get_patch(doc) == unfolded.get_patch(doc)


def _store_with(blobs, tmp_path, durable=False):
    store = ColdStore(root=str(tmp_path / 'cold'), durable=durable)
    for d, b in blobs.items():
        store.put(d, bytes(b))
    return store


def _corpus(n_docs=24):
    pool = NativeDocPool()
    pool.apply_batch({('doc%02d' % d): _history(d)
                      for d in range(n_docs)})
    return pool, {('doc%02d' % d): pool.save('doc%02d' % d)
                  for d in range(n_docs)}


def test_restore_from_store_roundtrip(tmp_path):
    builder, blobs = _corpus()
    store = _store_with(blobs, tmp_path)
    for make in (NativeDocPool, lambda: ShardedNativePool(4)):
        telemetry.reset_all()
        pool = make()
        summary = pool.restore_from_store(store)
        assert summary['docs'] == len(blobs)
        assert summary['corrupt'] == {} and summary['failed'] == {}
        assert summary['bytes'] == sum(len(b) for b in blobs.values())
        for d in blobs:
            assert pool.save(d) == blobs[d]
        snap = telemetry.metrics_snapshot()
        assert snap.get('storage.restore.docs') == len(blobs)
        assert snap.get('storage.restore.batches', 0) >= 1
        assert snap.get('storage.restore.corrupt', 0) == 0


def test_restore_serial_and_batched(tmp_path, monkeypatch):
    builder, blobs = _corpus()
    store = _store_with(blobs, tmp_path)
    monkeypatch.setenv('AMTPU_RESTORE_THREADS', '1')
    monkeypatch.setenv('AMTPU_RESTORE_BATCH', '5')
    pool = ShardedNativePool(4)
    summary = pool.restore_from_store(store)
    assert summary['docs'] == len(blobs)
    # 24 docs over 4 shards at batch=5 -> every shard chunks
    assert summary['batches'] >= 4
    for d in blobs:
        assert pool.save(d) == blobs[d]


def test_restore_doc_ids_subset(tmp_path):
    builder, blobs = _corpus()
    store = _store_with(blobs, tmp_path)
    want = sorted(blobs)[:7]
    pool = NativeDocPool()
    summary = pool.restore_from_store(store, doc_ids=want)
    assert summary['docs'] == 7
    assert sorted(pool.doc_stats()[0]) == want


def test_restore_quarantines_corrupt_blob(tmp_path):
    """A checksum-failed blob (ISSUE 17 small fix) must skip that doc
    with a typed per-doc error + storage.restore.corrupt, not fail the
    pool restore."""
    builder, blobs = _corpus()
    store = _store_with(blobs, tmp_path, durable=True)
    victim = sorted(blobs)[3]
    path = store._index[victim][0]
    with open(path, 'r+b') as f:
        f.seek(0)
        f.write(b'\xde\xad\xbe\xef')
    # direct get raises the typed error (still a ValueError subclass)
    with pytest.raises(ColdStoreCorrupt):
        store.get(victim)
    assert isinstance(ColdStoreCorrupt('x', 'detail'), ValueError)
    pool = ShardedNativePool(4)
    summary = pool.restore_from_store(store)
    assert summary['docs'] == len(blobs) - 1
    assert list(summary['corrupt']) == [victim]
    assert summary['corrupt'][victim]['errorType'] == 'ColdStoreCorrupt'
    assert victim not in list(pool.doc_stats()[0])
    for d in blobs:
        if d != victim:
            assert pool.save(d) == blobs[d]
    snap = telemetry.metrics_snapshot()
    assert snap.get('storage.restore.corrupt') == 1
    assert snap.get('storage.restore.docs') == len(blobs) - 1


def test_restore_after_fold_roundtrip(fold_env, tmp_path):
    """Save -> fold -> save -> restore: blobs written after clock
    folding restore byte-identically (folding never leaks into the
    wire format)."""
    fold_env(True)
    pool = NativeDocPool()
    pool.apply_batch({('doc%02d' % d): _history(d) for d in range(8)})
    for d in range(8):
        pool.compact('doc%02d' % d)
    blobs = {('doc%02d' % d): pool.save('doc%02d' % d)
             for d in range(8)}
    store = _store_with(blobs, tmp_path)
    fresh = NativeDocPool()
    fresh.restore_from_store(store)
    for d in blobs:
        assert fresh.save(d) == blobs[d]
        assert fresh.get_patch(d) == pool.get_patch(d)
