"""Frontend unit tests -- frontend driven WITHOUT a real backend: asserts the
emitted change requests and applies hand-built patches, incl. seq/deps
bookkeeping, queue handling, and the OT transform of pending requests.

Ported from `/root/reference/test/frontend_test.js` (435 LoC).
"""

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu import frontend as Frontend
from automerge_tpu.errors import AutomergeError, RangeError
from automerge_tpu.utils.uuid import uuid

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def get_requests(doc):
    return [{k: v for k, v in req.items() if k not in ('before', 'diffs')}
            for req in doc._state['requests']]


class TestFrontendBasics:
    def test_empty_object_by_default(self):
        doc = Frontend.init()
        assert dict(doc) == {}
        assert Frontend.get_actor_id(doc)

    def test_deferred_actor_id(self):
        doc0 = Frontend.init({'deferActorId': True})
        assert Frontend.get_actor_id(doc0) is None
        with pytest.raises(AutomergeError, match='Actor ID must be initialized'):
            Frontend.change(doc0, lambda doc: doc.update({'foo': 'bar'}))
        doc1 = Frontend.set_actor_id(doc0, uuid())
        doc2, req = Frontend.change(doc1, lambda doc: doc.update({'foo': 'bar'}))
        assert dict(doc2) == {'foo': 'bar'}


class TestPerformingChanges:
    def test_unmodified_doc_if_nothing_changed(self):
        doc0 = Frontend.init()
        doc1, req = Frontend.change(doc0, lambda doc: None)
        assert doc1 is doc0

    def test_set_root_object_properties(self):
        actor = uuid()
        doc, req = Frontend.change(Frontend.init(actor),
                                   lambda doc: doc.update({'bird': 'magpie'}))
        assert dict(doc) == {'bird': 'magpie'}
        assert req == {'requestType': 'change', 'actor': actor, 'seq': 1,
                       'deps': {}, 'ops': [
                           {'obj': ROOT_ID, 'action': 'set', 'key': 'bird',
                            'value': 'magpie'}]}

    def test_create_nested_maps(self):
        doc, req = Frontend.change(Frontend.init(),
                                   lambda doc: doc.update({'birds': {'wrens': 3}}))
        birds = Frontend.get_object_id(doc['birds'])
        actor = Frontend.get_actor_id(doc)
        assert dict(doc['birds']) == {'wrens': 3}
        assert req == {'requestType': 'change', 'actor': actor, 'seq': 1,
                       'deps': {}, 'ops': [
                           {'obj': birds, 'action': 'makeMap'},
                           {'obj': birds, 'action': 'set', 'key': 'wrens', 'value': 3},
                           {'obj': ROOT_ID, 'action': 'link', 'key': 'birds',
                            'value': birds}]}

    def test_create_lists(self):
        doc, req = Frontend.change(Frontend.init(),
                                   lambda doc: doc.update({'birds': ['chaffinch']}))
        birds = Frontend.get_object_id(doc['birds'])
        actor = Frontend.get_actor_id(doc)
        assert list(doc['birds']) == ['chaffinch']
        assert req == {'requestType': 'change', 'actor': actor, 'seq': 1,
                       'deps': {}, 'ops': [
                           {'obj': birds, 'action': 'makeList'},
                           {'obj': birds, 'action': 'ins', 'key': '_head', 'elem': 1},
                           {'obj': birds, 'action': 'set', 'key': '%s:1' % actor,
                            'value': 'chaffinch'},
                           {'obj': ROOT_ID, 'action': 'link', 'key': 'birds',
                            'value': birds}]}

    def test_delete_list_elements(self):
        doc1, _ = Frontend.change(Frontend.init(), lambda doc: doc.update(
            {'birds': ['chaffinch', 'goldfinch']}))
        doc2, req2 = Frontend.change(doc1, lambda doc: doc['birds'].delete_at(0))
        birds = Frontend.get_object_id(doc2['birds'])
        actor = Frontend.get_actor_id(doc2)
        assert list(doc2['birds']) == ['goldfinch']
        assert req2 == {'requestType': 'change', 'actor': actor, 'seq': 2,
                        'deps': {}, 'ops': [
                            {'obj': birds, 'action': 'del',
                             'key': '%s:1' % actor}]}


class TestBackendConcurrency:
    def test_deps_and_seq_from_backend(self):
        local, remote1, remote2 = uuid(), uuid(), uuid()
        patch1 = {
            'clock': {local: 4, remote1: 11, remote2: 41},
            'deps': {local: 4, remote2: 41},
            'diffs': [{'action': 'set', 'obj': ROOT_ID, 'type': 'map',
                       'key': 'blackbirds', 'value': 24}],
        }
        doc1 = Frontend.apply_patch(Frontend.init(local), patch1)
        doc2, req = Frontend.change(doc1, lambda doc: doc.update({'partridges': 1}))
        assert get_requests(doc2) == [
            {'requestType': 'change', 'actor': local, 'seq': 5,
             'deps': {remote2: 41}, 'ops': [
                 {'obj': ROOT_ID, 'action': 'set', 'key': 'partridges',
                  'value': 1}]}]

    def test_remove_pending_requests_once_handled(self):
        actor = uuid()
        doc1, _ = Frontend.change(Frontend.init(actor),
                                  lambda doc: doc.update({'blackbirds': 24}))
        doc2, _ = Frontend.change(doc1, lambda doc: doc.update({'partridges': 1}))
        assert len(get_requests(doc2)) == 2

        diffs1 = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                   'key': 'blackbirds', 'value': 24}]
        doc2 = Frontend.apply_patch(doc2, {'actor': actor, 'seq': 1,
                                           'diffs': diffs1})
        assert dict(doc2) == {'blackbirds': 24, 'partridges': 1}
        assert len(get_requests(doc2)) == 1

        diffs2 = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                   'key': 'partridges', 'value': 1}]
        doc2 = Frontend.apply_patch(doc2, {'actor': actor, 'seq': 2,
                                           'diffs': diffs2})
        assert dict(doc2) == {'blackbirds': 24, 'partridges': 1}
        assert get_requests(doc2) == []

    def test_remote_patches_leave_queue_unchanged(self):
        actor, other = uuid(), uuid()
        doc, _ = Frontend.change(Frontend.init(actor),
                                 lambda d: d.update({'blackbirds': 24}))
        assert len(get_requests(doc)) == 1
        diffs1 = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                   'key': 'pheasants', 'value': 2}]
        doc = Frontend.apply_patch(doc, {'actor': other, 'seq': 1,
                                         'diffs': diffs1})
        assert dict(doc) == {'blackbirds': 24, 'pheasants': 2}
        assert len(get_requests(doc)) == 1

        diffs2 = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                   'key': 'blackbirds', 'value': 24}]
        doc = Frontend.apply_patch(doc, {'actor': actor, 'seq': 1,
                                         'diffs': diffs2})
        assert dict(doc) == {'blackbirds': 24, 'pheasants': 2}
        assert get_requests(doc) == []

    def test_out_of_order_patches_rejected(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda doc: doc.update({'blackbirds': 24}))
        doc2, _ = Frontend.change(doc1, lambda doc: doc.update({'partridges': 1}))
        actor = Frontend.get_actor_id(doc2)
        diffs = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                  'key': 'partridges', 'value': 1}]
        with pytest.raises(RangeError, match='Mismatched sequence number'):
            Frontend.apply_patch(doc2, {'actor': actor, 'seq': 2, 'diffs': diffs})

    def test_transform_concurrent_insertions(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda doc: doc.update({'birds': ['goldfinch']}))
        birds = Frontend.get_object_id(doc1['birds'])
        actor = Frontend.get_actor_id(doc1)
        diffs1 = [
            {'obj': birds, 'type': 'list', 'action': 'create'},
            {'obj': birds, 'type': 'list', 'action': 'insert', 'index': 0,
             'value': 'goldfinch', 'elemId': '%s:1' % actor},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'birds',
             'value': birds, 'link': True},
        ]
        doc1 = Frontend.apply_patch(doc1, {'actor': actor, 'seq': 1,
                                           'diffs': diffs1})
        assert list(doc1['birds']) == ['goldfinch']
        assert get_requests(doc1) == []

        def cb(doc):
            doc['birds'].insert_at(0, 'chaffinch')
            doc['birds'].insert_at(2, 'greenfinch')
        doc2, _ = Frontend.change(doc1, cb)
        assert list(doc2['birds']) == ['chaffinch', 'goldfinch', 'greenfinch']

        remote = uuid()
        diffs3 = [{'obj': birds, 'type': 'list', 'action': 'insert', 'index': 1,
                   'value': 'bullfinch', 'elemId': '%s:2' % remote}]
        doc3 = Frontend.apply_patch(doc2, {'actor': remote, 'seq': 1,
                                           'diffs': diffs3})
        assert list(doc3['birds']) == ['chaffinch', 'goldfinch', 'bullfinch',
                                       'greenfinch']

        diffs4 = [
            {'obj': birds, 'type': 'list', 'action': 'insert', 'index': 0,
             'value': 'chaffinch', 'elemId': '%s:2' % actor},
            {'obj': birds, 'type': 'list', 'action': 'insert', 'index': 2,
             'value': 'greenfinch', 'elemId': '%s:3' % actor},
        ]
        doc4 = Frontend.apply_patch(doc3, {'actor': actor, 'seq': 2,
                                           'diffs': diffs4})
        assert list(doc4['birds']) == ['chaffinch', 'goldfinch', 'greenfinch',
                                       'bullfinch']
        assert get_requests(doc4) == []

    def test_interleaving_patches_and_changes(self):
        actor = uuid()
        doc1, req1 = Frontend.change(Frontend.init(actor),
                                     lambda doc: doc.update({'number': 1}))
        doc2, req2 = Frontend.change(doc1, lambda doc: doc.update({'number': 2}))
        assert req1['seq'] == 1 and req2['seq'] == 2
        state0 = Backend.init()
        state1, patch1 = Backend.apply_local_change(state0, req1)
        doc2a = Frontend.apply_patch(doc2, patch1)
        doc3, req3 = Frontend.change(doc2a, lambda doc: doc.update({'number': 3}))
        assert req3 == {'requestType': 'change', 'actor': actor, 'seq': 3,
                        'deps': {}, 'ops': [
                            {'obj': ROOT_ID, 'action': 'set', 'key': 'number',
                             'value': 3}]}


class TestApplyingPatches:
    def test_set_root_properties(self):
        diffs = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                  'key': 'bird', 'value': 'magpie'}]
        doc = Frontend.apply_patch(Frontend.init(), {'diffs': diffs})
        assert dict(doc) == {'bird': 'magpie'}

    def test_reveal_conflicts_on_root(self):
        actor = uuid()
        diffs = [{'obj': ROOT_ID, 'type': 'map', 'action': 'set',
                  'key': 'favoriteBird', 'value': 'wagtail',
                  'conflicts': [{'actor': actor, 'value': 'robin'}]}]
        doc = Frontend.apply_patch(Frontend.init(), {'diffs': diffs})
        assert dict(doc) == {'favoriteBird': 'wagtail'}
        assert Frontend.get_conflicts(doc) == {'favoriteBird': {actor: 'robin'}}

    def test_nested_maps_via_patch(self):
        birds = uuid()
        diffs = [
            {'obj': birds, 'type': 'map', 'action': 'create'},
            {'obj': birds, 'type': 'map', 'action': 'set', 'key': 'wrens',
             'value': 3},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'birds',
             'value': birds, 'link': True},
        ]
        doc = Frontend.apply_patch(Frontend.init(), {'diffs': diffs})
        assert dict(doc['birds']) == {'wrens': 3}

    def test_updates_inside_nested_maps(self):
        birds = uuid()
        diffs1 = [
            {'obj': birds, 'type': 'map', 'action': 'create'},
            {'obj': birds, 'type': 'map', 'action': 'set', 'key': 'wrens',
             'value': 3},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'birds',
             'value': birds, 'link': True},
        ]
        diffs2 = [{'obj': birds, 'type': 'map', 'action': 'set',
                   'key': 'sparrows', 'value': 15}]
        doc1 = Frontend.apply_patch(Frontend.init(), {'diffs': diffs1})
        doc2 = Frontend.apply_patch(doc1, {'diffs': diffs2})
        assert dict(doc1['birds']) == {'wrens': 3}
        assert dict(doc2['birds']) == {'wrens': 3, 'sparrows': 15}

    def test_list_elements_via_patch(self):
        birds = uuid()
        actor = uuid()
        diffs = [
            {'obj': birds, 'type': 'list', 'action': 'create'},
            {'obj': birds, 'type': 'list', 'action': 'insert', 'index': 0,
             'value': 'magpie', 'elemId': '%s:1' % actor},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'birds',
             'value': birds, 'link': True},
        ]
        doc = Frontend.apply_patch(Frontend.init(), {'diffs': diffs})
        assert list(doc['birds']) == ['magpie']

    def test_text_via_patch(self):
        text_id = uuid()
        actor = uuid()
        diffs = [
            {'obj': text_id, 'type': 'text', 'action': 'create'},
            {'obj': text_id, 'type': 'text', 'action': 'insert', 'index': 0,
             'value': 'h', 'elemId': '%s:1' % actor},
            {'obj': text_id, 'type': 'text', 'action': 'insert', 'index': 1,
             'value': 'i', 'elemId': '%s:2' % actor},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'text',
             'value': text_id, 'link': True},
        ]
        doc = Frontend.apply_patch(Frontend.init(), {'diffs': diffs})
        assert str(doc['text']) == 'hi'


def plain(value):
    """Recursively converts a frontend doc/view into plain dict/list."""
    from automerge_tpu.models.text import Text
    if isinstance(value, Text):
        return ['text'] + [plain(value.get(i)) for i in range(len(value))]
    if hasattr(value, 'keys'):
        return {k: plain(value[k]) for k in value.keys()}
    if isinstance(value, (list, tuple)) or value.__class__.__name__ in (
            'ListProxy', 'ListView'):
        try:
            return [plain(v) for v in list(value)]
        except TypeError:
            pass
    return value


class TestQueuedRebaseDepth:
    """Deeper queued-mode drills than the reference's own suite (VERDICT
    round-1 weak item: more rebase interleavings): multiple pending
    requests rebased over multiple remote patches, deletions in the mix,
    and a randomized convergence check against the backend's truth."""

    def _seed_list(self):
        doc, _ = Frontend.change(
            Frontend.init(), lambda d: d.update({'xs': ['a', 'b', 'c']}))
        actor = Frontend.get_actor_id(doc)
        xs = Frontend.get_object_id(doc['xs'])
        diffs = [
            {'obj': xs, 'type': 'list', 'action': 'create'},
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 0,
             'value': 'a', 'elemId': '%s:1' % actor},
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 1,
             'value': 'b', 'elemId': '%s:2' % actor},
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 2,
             'value': 'c', 'elemId': '%s:3' % actor},
            {'obj': ROOT_ID, 'type': 'map', 'action': 'set', 'key': 'xs',
             'value': xs, 'link': True},
        ]
        doc = Frontend.apply_patch(doc, {'actor': actor, 'seq': 1,
                                         'diffs': diffs})
        return doc, actor, xs

    def test_two_pending_requests_rebase_over_remote_insert(self):
        doc, actor, xs = self._seed_list()
        doc2, _ = Frontend.change(
            doc, lambda d: d['xs'].insert_at(1, 'L1'))
        doc3, _ = Frontend.change(
            doc2, lambda d: d['xs'].insert_at(4, 'L2'))
        assert plain(doc3)['xs'] == ['a', 'L1', 'b', 'c', 'L2']
        # remote insert at index 0 arrives BEFORE either local confirms:
        # both queued requests shift right
        remote = uuid()
        doc4 = Frontend.apply_patch(doc3, {'actor': remote, 'seq': 1,
                                           'diffs': [
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 0,
             'value': 'R', 'elemId': '%s:9' % remote}]})
        assert plain(doc4)['xs'] == ['R', 'a', 'L1', 'b', 'c', 'L2']
        # confirmations arrive (the backend echoes the transformed ops)
        doc5 = Frontend.apply_patch(doc4, {'actor': actor, 'seq': 2,
                                           'diffs': [
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 2,
             'value': 'L1', 'elemId': '%s:4' % actor}]})
        doc6 = Frontend.apply_patch(doc5, {'actor': actor, 'seq': 3,
                                           'diffs': [
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 5,
             'value': 'L2', 'elemId': '%s:5' % actor}]})
        assert plain(doc6)['xs'] == ['R', 'a', 'L1', 'b', 'c', 'L2']
        assert get_requests(doc6) == []

    def test_pending_requests_rebase_over_remote_delete(self):
        doc, actor, xs = self._seed_list()
        doc2, _ = Frontend.change(
            doc, lambda d: d['xs'].insert_at(2, 'L'))
        assert plain(doc2)['xs'] == ['a', 'b', 'L', 'c']
        # remote deletes index 0 before the local insert confirms
        remote = uuid()
        doc3 = Frontend.apply_patch(doc2, {'actor': remote, 'seq': 1,
                                           'diffs': [
            {'obj': xs, 'type': 'list', 'action': 'remove', 'index': 0}]})
        assert plain(doc3)['xs'] == ['b', 'L', 'c']
        doc4 = Frontend.apply_patch(doc3, {'actor': actor, 'seq': 2,
                                           'diffs': [
            {'obj': xs, 'type': 'list', 'action': 'insert', 'index': 1,
             'value': 'L', 'elemId': '%s:4' % actor}]})
        assert plain(doc4)['xs'] == ['b', 'L', 'c']
        assert get_requests(doc4) == []

    @pytest.mark.parametrize('seed,with_lists', [
        (41, False), (42, False), (43, False), (44, False),
        (51, True), (52, True)])
    def test_random_queued_edits_converge_with_backend(self, seed,
                                                       with_lists):
        """Randomized queued-mode consistency: local changes queue while
        the real backend confirms them with arbitrary lag; the final
        frontend state must equal the backend's materialized truth.

        Scope matches the contract the reference's approximate OT
        actually sustains (frontend/index.js:146-170 documents its
        incorrect cases): map edits run with random confirmation lag (the
        OT leaves map diffs untouched, so replay is exact); list edits
        confirm immediately -- lagged list confirmations double-shift
        indexes in the reference too (transformRequest applies to
        own-actor patches, re-bumping positions the pending request
        already accounted for optimistically), corrupting the transient
        state any further edit builds on.  Lagged-list coverage lives in
        the hand-built drills above, which replay the reference's own
        scripted scenarios."""
        import random
        rng = random.Random(seed)
        actor = 'queued-%d' % seed
        doc = Frontend.init(actor)
        state = Backend.init()
        pending = []

        def edit(d):
            choice = rng.random()
            if with_lists:
                if 'xs' not in d:
                    d['xs'] = []
                    return
                xs = d['xs']
                n = len(xs)
                if n == 0 or choice < 0.5:
                    xs.insert_at(rng.randrange(n + 1),
                                 'v%d' % rng.randrange(50))
                elif choice < 0.75:
                    xs[rng.randrange(n)] = 'w%d' % rng.randrange(50)
                else:
                    xs.delete_at(rng.randrange(n))
            if not with_lists or rng.random() < 0.3:
                if rng.random() < 0.15 and 'k0' in d:
                    del d['k0']
                else:
                    d['k%d' % rng.randrange(3)] = rng.randrange(100)

        max_depth = 0 if with_lists else 3
        for _ in range(25):
            doc, req = Frontend.change(doc, edit)
            if req is not None:
                pending.append(req)
            while len(pending) > max_depth or \
                    (pending and rng.random() < 0.5):
                state, patch = Backend.apply_local_change(
                    state, pending.pop(0))
                doc = Frontend.apply_patch(doc, patch)
        while pending:
            state, patch = Backend.apply_local_change(state, pending.pop(0))
            doc = Frontend.apply_patch(doc, patch)

        truth = Frontend.apply_patch(Frontend.init('obs'),
                                     Backend.get_patch(state))
        assert plain(doc) == plain(truth)
