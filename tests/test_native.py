"""Differential tests for the C++ native host runtime (NativeDocPool):
its patches must equal the Python pool's and the scalar oracle's for the
same change streams, including msgpack round-trips of every value type.
"""

import random

import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu.parallel.engine import TPUDocPool

from test_engine_differential import WorkloadGen

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def native_pool():
    from automerge_tpu.native import NativeDocPool
    return NativeDocPool()


def deliver_and_compare(change_batches, n_docs=1):
    """Feeds identical batches to oracle, Python pool and native pool;
    asserts patch equality at every step and getPatch equality at the end."""
    oracle_states = {d: Backend.init() for d in range(n_docs)}
    py = TPUDocPool()
    nat = native_pool()

    for batch in change_batches:
        expected = {}
        for doc, changes in batch.items():
            oracle_states[doc], patch = Backend.apply_changes(
                oracle_states[doc], changes)
            expected[doc] = patch
        got_py = py.apply_batch(batch)
        got_nat = nat.apply_batch(batch)
        for doc in batch:
            assert got_py[doc] == expected[doc]
            assert got_nat[doc] == expected[doc], (
                'native patch mismatch for doc %r:\nexpected %r\ngot      %r'
                % (doc, expected[doc], got_nat[doc]))

    for doc in range(n_docs):
        want = Backend.get_patch(oracle_states[doc])
        assert nat.get_patch(doc) == want


class TestNativeBasics:
    def test_map_sets_and_dels(self):
        deliver_and_compare([
            {0: [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                 'value': 'magpie'}]}]},
            {0: [{'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                 'value': 'jay'},
                {'action': 'del', 'obj': ROOT_ID, 'key': 'bird'}]}]},
        ])

    def test_value_types_round_trip(self):
        # int, float, bool, None, str, timestamp datatype
        deliver_and_compare([{0: [{'actor': 'a', 'seq': 1, 'deps': {},
                                   'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'i', 'value': 42},
            {'action': 'set', 'obj': ROOT_ID, 'key': 'neg', 'value': -7},
            {'action': 'set', 'obj': ROOT_ID, 'key': 'big',
             'value': 2 ** 40},
            {'action': 'set', 'obj': ROOT_ID, 'key': 'f', 'value': 3.25},
            {'action': 'set', 'obj': ROOT_ID, 'key': 'b', 'value': True},
            {'action': 'set', 'obj': ROOT_ID, 'key': 'n', 'value': None},
            {'action': 'set', 'obj': ROOT_ID, 'key': 's', 'value': 'hi'},
            {'action': 'set', 'obj': ROOT_ID, 'key': 't', 'value': 1234567,
             'datatype': 'timestamp'}]}]}])

    def test_concurrent_conflict(self):
        deliver_and_compare([
            {0: [{'actor': 'a1', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                 'value': 'from-a'}]},
                {'actor': 'z9', 'seq': 1, 'deps': {}, 'ops': [
                    {'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                     'value': 'from-z'}]}]},
        ])

    def test_nested_maps_and_links(self):
        deliver_and_compare([
            {0: [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeMap', 'obj': 'm1'},
                {'action': 'set', 'obj': 'm1', 'key': 'x', 'value': 1},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'child',
                 'value': 'm1'}]}]},
            {0: [{'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': 'm1', 'key': 'y', 'value': 2},
                {'action': 'del', 'obj': ROOT_ID, 'key': 'child'}]}]},
        ])

    def test_out_of_order_buffering(self):
        nat = native_pool()
        ch1 = {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]}
        ch2 = {'actor': 'b', 'seq': 1, 'deps': {'a': 1}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 2}]}
        st = Backend.init()
        st, _ = Backend.apply_changes(st, [ch2])
        nat.apply_changes(0, [ch2])
        assert nat.get_missing_deps(0) == Backend.get_missing_deps(st)
        st, _ = Backend.apply_changes(st, [ch1, ch1])  # dup tolerated
        nat.apply_changes(0, [ch1, ch1])
        assert nat.get_patch(0) == Backend.get_patch(st)

    def test_inconsistent_seq_reuse_raises(self):
        from automerge_tpu.errors import AutomergeError
        nat = native_pool()
        nat.apply_changes(0, [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]}])
        with pytest.raises(AutomergeError):
            nat.apply_changes(0, [{'actor': 'a', 'seq': 1, 'deps': {},
                                   'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                 'value': 999}]}])

    def test_get_changes_for_actor(self):
        nat = native_pool()
        st = Backend.init()
        chs = [
            {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]},
            {'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 2}]},
            {'actor': 'b', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'j', 'value': 3}]},
        ]
        st, _ = Backend.apply_changes(st, chs)
        nat.apply_changes(0, chs)
        for actor, after in (('a', 0), ('a', 1), ('b', 0), ('zz', 0)):
            got = nat.get_changes_for_actor(0, actor, after)
            want = [dict(c) for c in chs
                    if c['actor'] == actor and c['seq'] > after]
            assert got == want, (actor, after, got)

    def test_get_missing_changes(self):
        nat = native_pool()
        st = Backend.init()
        chs = [
            {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]},
            {'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 2}]},
            {'actor': 'b', 'seq': 1, 'deps': {'a': 2}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'j', 'value': 3}]},
        ]
        st, _ = Backend.apply_changes(st, chs)
        nat.apply_changes(0, chs)
        for have in ({}, {'a': 1}, {'a': 2}, {'a': 2, 'b': 1}):
            want = Backend.get_missing_changes(st, have)
            got = nat.get_missing_changes(0, have)
            assert got == want, (have, got, want)


class TestNativeLists:
    def test_text_interleaved(self):
        actor = 'actor-a'
        deliver_and_compare([
            {0: [{'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeText', 'obj': 'text-1'},
                {'action': 'ins', 'obj': 'text-1', 'key': '_head',
                 'elem': 1},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'value': 'h'},
                {'action': 'ins', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'elem': 2},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:2' % actor,
                 'value': 'i'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
                 'value': 'text-1'}]}]},
            {0: [{'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 'text-1', 'key': '%s:1' % actor},
                {'action': 'ins', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'elem': 3},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:3' % actor,
                 'value': 'H'}]}]},
        ])

    def test_concurrent_same_position_inserts(self):
        deliver_and_compare([
            {0: [{'actor': 'aa', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeList', 'obj': 'list-1'},
                {'action': 'ins', 'obj': 'list-1', 'key': '_head',
                 'elem': 1},
                {'action': 'set', 'obj': 'list-1', 'key': 'aa:1',
                 'value': 'base'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'l',
                 'value': 'list-1'}]}]},
            {0: [{'actor': 'aa', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'ins', 'obj': 'list-1', 'key': 'aa:1', 'elem': 2},
                {'action': 'set', 'obj': 'list-1', 'key': 'aa:2',
                 'value': 'from-aa'}]}]},
            {0: [{'actor': 'zz', 'seq': 1, 'deps': {'aa': 1}, 'ops': [
                {'action': 'ins', 'obj': 'list-1', 'key': 'aa:1', 'elem': 2},
                {'action': 'set', 'obj': 'list-1', 'key': 'zz:2',
                 'value': 'from-zz'}]}]},
        ])

    def test_multi_doc_batch(self):
        batches = []
        for d in range(4):
            tid = 'text-%d' % d
            batches.append({'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeText', 'obj': tid},
                {'action': 'ins', 'obj': tid, 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': tid, 'key': 'a:1',
                 'value': chr(97 + d)},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
                 'value': tid}]})
        deliver_and_compare([{d: [batches[d]] for d in range(4)}], n_docs=4)


class TestShardedPool:
    def _mk(self, n_shards=3):
        from automerge_tpu.native import ShardedNativePool
        return ShardedNativePool(n_shards)

    def test_parity_with_single_pool_many_docs(self):
        # >15 docs forces the byte-level merge across the fixmap/map16
        # header boundary; doc set spans all shards
        from automerge_tpu.native import NativeDocPool
        batch = {}
        for d in range(20):
            tid = 'text-%d' % d
            batch['doc-%d' % d] = [{'actor': 'a', 'seq': 1, 'deps': {},
                                    'ops': [
                {'action': 'makeText', 'obj': tid},
                {'action': 'ins', 'obj': tid, 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': tid, 'key': 'a:1',
                 'value': chr(97 + d % 26)},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
                 'value': tid}]}]
        single = NativeDocPool()
        sharded = self._mk(3)
        want = single.apply_batch(batch)
        got = sharded.apply_batch(batch)
        assert got == want
        for d in batch:
            assert sharded.get_patch(d) == single.get_patch(d)
            assert sharded.get_missing_deps(d) == {}

    def test_int_doc_ids_route_consistently(self):
        sharded = self._mk(4)
        sharded.apply_changes(7, [{'actor': 'a', 'seq': 1, 'deps': {},
                                   'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]}])
        assert sharded.get_patch(7)['clock'] == {'a': 1}

    def test_empty_payload(self):
        import msgpack
        sharded = self._mk(2)
        out = sharded.apply_batch_bytes(msgpack.packb({}))
        assert msgpack.unpackb(out, raw=False) == {}

    def test_invalid_shard_count(self):
        from automerge_tpu.native import ShardedNativePool
        with pytest.raises(ValueError):
            ShardedNativePool(0)

    def test_python_cpp_routing_parity(self):
        from automerge_tpu.native import lib
        sharded = self._mk(5)
        for d in ('a', 'doc-42', 'i:7', 'long-document-name-xyz'):
            key = d.encode()
            assert sharded._shard_of(d) == \
                int(lib().amtpu_doc_shard(key, len(key), 5))


class TestNativeRandomWorkloads:
    @pytest.mark.parametrize('seed,structure', [
        (1, 'map'), (3, 'list'), (5, 'mixed'), (6, 'mixed'),
    ])
    def test_in_order_delivery(self, seed, structure):
        changes = WorkloadGen(seed, structure=structure).generate(20)
        deliver_and_compare([{0: [c]} for c in changes])

    @pytest.mark.parametrize('seed', [11, 13])
    def test_shuffled_delivery(self, seed):
        rng = random.Random(seed)
        changes = WorkloadGen(seed, structure='mixed').generate(16)
        shuffled = list(changes)
        rng.shuffle(shuffled)
        deliver_and_compare([{0: shuffled}])

    @pytest.mark.parametrize('seed', [21, 22])
    def test_batched_delivery(self, seed):
        rng = random.Random(seed)
        changes = WorkloadGen(seed, structure='mixed').generate(24)
        batches = []
        i = 0
        while i < len(changes):
            n = rng.randint(1, 6)
            batches.append({0: changes[i:i + n]})
            i += n
        deliver_and_compare(batches)


class TestSingleLargeDoc:
    def test_long_sequential_text(self):
        """One Text doc with thousands of sequential inserts from two
        alternating actors (BASELINE config-1 shape, scaled down): the
        big-arena size classes and cross-change dependency chains."""
        nat = native_pool()
        st = Backend.init()
        chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeText', 'obj': 't'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
             'value': 't'}]}]
        last = '_head'
        seqs = {'a0': 1, 'a1': 0}
        e = 0
        while e < 1200:
            for a in ('a0', 'a1'):
                ops = []
                for _ in range(50):
                    e += 1
                    ops.append({'action': 'ins', 'obj': 't', 'key': last,
                                'elem': e})
                    ops.append({'action': 'set', 'obj': 't',
                                'key': '%s:%d' % (a, e),
                                'value': chr(97 + e % 26)})
                    last = '%s:%d' % (a, e)
                seqs[a] += 1
                chs.append({'actor': a, 'seq': seqs[a],
                            'deps': {k: v for k, v in seqs.items()
                                     if k != a and v > 0},
                            'ops': ops})
        st, _ = Backend.apply_changes(st, chs)
        nat.apply_changes('big', chs)
        assert nat.get_patch('big') == Backend.get_patch(st)


class TestQueryConstLookup:
    """Queries on unknown doc ids must not materialize pool state
    (round-2 advisor finding: phantom DocState on typo'd ids)."""

    QUERIES = [
        lambda p: p.get_patch('no-such-doc'),
        lambda p: p.get_clock('no-such-doc'),
        lambda p: p.get_missing_deps('no-such-doc'),
        lambda p: p.get_missing_changes('no-such-doc', {'a0': 1}),
        lambda p: p.get_changes_for_actor('no-such-doc', 'a0'),
        lambda p: p.save('no-such-doc'),
    ]

    def _exercise(self, pool, doc_count):
        pool.apply_changes('real', [{'actor': 'a0', 'seq': 1, 'deps': {},
                                     'ops': [{'action': 'set',
                                              'obj': ROOT_ID, 'key': 'x',
                                              'value': 1}]}])
        for q in self.QUERIES:
            q(pool)
        assert doc_count(pool) == 1
        # and the real doc still answers correctly
        patch = pool.get_patch('real')
        assert patch['clock'] == {'a0': 1}

    def test_python_pool(self):
        pool = TPUDocPool()
        self._exercise(pool, lambda p: len(p.docs))

    def test_native_pool(self):
        from automerge_tpu.native import NativeDocPool
        pool = NativeDocPool()
        self._exercise(pool, lambda p: p.doc_count())

    def test_sharded_pool(self):
        from automerge_tpu.native import ShardedNativePool
        pool = ShardedNativePool(4)
        self._exercise(pool, lambda p: sum(s.doc_count()
                                           for s in p.pools))

    def test_unknown_doc_patch_is_empty(self):
        pool = TPUDocPool()
        patch = pool.get_patch('ghost')
        assert patch['clock'] == {} and patch['deps'] == {}
        assert 'ghost' not in pool.docs


class TestBatchHandleLeaks:
    """Phase-a failures after amtpu_begin must free the C++ batch handle
    (each handle owns the whole decoded batch; leaking under sustained
    error traffic is unbounded growth).  live_batch_handles() is the
    audit hook: every begin increments, every free decrements."""

    def _simple_batch(self):
        return {0: [{'actor': 'a0', 'seq': 1, 'deps': {},
                     'ops': [{'action': 'set', 'obj': ROOT_ID,
                              'key': 'k', 'value': 1}]}]}

    def test_success_path_balances(self):
        from automerge_tpu import native
        base = native.live_batch_handles()
        pool = native.NativeDocPool()
        pool.apply_batch(self._simple_batch())
        assert native.live_batch_handles() == base

    def test_phase_a_failure_frees_handle(self):
        """AMTPU_WEFF with a non-numeric value raises inside
        _phase_a_rest AFTER begin succeeded -- exactly the window where
        a leak would hide."""
        import os
        from automerge_tpu import native
        base = native.live_batch_handles()
        pool = native.NativeDocPool()
        prior = os.environ.get('AMTPU_WEFF')
        os.environ['AMTPU_WEFF'] = 'bogus'
        try:
            with pytest.raises(ValueError):
                pool.apply_batch(self._simple_batch())
        finally:
            if prior is None:
                os.environ.pop('AMTPU_WEFF', None)
            else:
                os.environ['AMTPU_WEFF'] = prior
        assert native.live_batch_handles() == base
        # the pool is still serviceable after the failed batch
        pool.apply_batch(self._simple_batch())
        assert native.live_batch_handles() == base

    def test_pipelined_phase_a_failure_frees_all(self):
        """The pipelined driver collects phase-a errors across pools;
        every handle -- failed and healthy alike -- must be freed."""
        import os
        from automerge_tpu import native
        base = native.live_batch_handles()
        import msgpack
        payload = msgpack.packb(
            {native.NativeDocPool._doc_key(0):
             self._simple_batch()[0]}, use_bin_type=True)
        pools = [native.NativeDocPool() for _ in range(3)]
        prior = os.environ.get('AMTPU_WEFF')
        os.environ['AMTPU_WEFF'] = 'bogus'
        try:
            with pytest.raises(ValueError):
                native.apply_payloads_pipelined(
                    [(p, payload) for p in pools])
        finally:
            if prior is None:
                os.environ.pop('AMTPU_WEFF', None)
            else:
                os.environ['AMTPU_WEFF'] = prior
        assert native.live_batch_handles() == base


class TestShardErrorReporting:
    def test_error_names_failing_shard(self):
        from automerge_tpu.native import ShardedNativePool
        pool = ShardedNativePool(4)
        bad = {'d%d' % i: [{'actor': 'a0', 'seq': 1, 'deps': {},
                            'ops': [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': i}]}]
               for i in range(8)}
        # one doc carries an inconsistent seq reuse -> its shard errors
        victim = 'd3'
        bad[victim] = [
            {'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': []},
            {'actor': 'a0', 'seq': 1, 'deps': {},
             'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                      'value': 9}]},
        ]
        shard = pool._shard_of(victim)
        with pytest.raises(Exception) as ei:
            pool.apply_batch(bad)
        assert '[shard %d]' % shard in str(ei.value)


def test_wide_overflow_register_conflicts_emit_correctly():
    """20 concurrent writers on one key exceed both the register window
    (host-oracle fallback) and the fixarray conflicts bound (>15
    entries) -- the diff stream must stay valid msgpack and match the
    oracle byte for byte (round-3 regression: the stack fast path must
    reject such registers)."""
    nat = native_pool()
    st = Backend.init()
    chs = [{'actor': 'w%02d' % a, 'seq': 1, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'hot',
                     'value': 'v%d' % a}]}
           for a in range(20)]
    nat.apply_changes('doc', chs)
    st, _ = Backend.apply_changes(st, chs)
    patch = nat.get_patch('doc')
    assert patch == Backend.get_patch(st)
    final = [d for d in patch['diffs'] if d.get('key') == 'hot'][-1]
    assert len(final['conflicts']) == 19


def test_duplicate_actor_seq_after_ops_last_wins():
    """Malformed envelope repeating 'actor'/'seq' with DIFFERENT values
    AFTER the 'ops' key: the inline-decoded ops must be re-stamped with
    the final (last-wins) values, matching the span-reparse path and JS
    object semantics -- previously they kept the stale attribution."""
    import msgpack

    from automerge_tpu.native import NativeDocPool
    ops = [{'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 7}]
    # canonical reference: the change as a JS object would decode it
    ref = native_pool()
    ref_patch = ref.apply_changes('doc', [
        {'actor': 'zzz', 'seq': 1, 'deps': {}, 'ops': ops}])

    # malformed wire form: actor 'aaa' triggers inline op decode, then
    # 'actor'/'seq' repeat after 'ops' with the values that must win
    body = (msgpack.packb('actor') + msgpack.packb('aaa') +
            msgpack.packb('seq') + msgpack.packb(9) +
            msgpack.packb('deps') + msgpack.packb({}) +
            msgpack.packb('ops') + msgpack.packb(ops) +
            msgpack.packb('actor') + msgpack.packb('zzz') +
            msgpack.packb('seq') + msgpack.packb(1))
    change = b'\x86' + body                        # fixmap, 6 pairs
    key = NativeDocPool._doc_key('doc')
    payload = (b'\x81' + msgpack.packb(key) +      # {doc: [change]}
               b'\x91' + change)
    nat = native_pool()
    got = msgpack.unpackb(nat.apply_batch_bytes(payload), raw=False)[key]
    assert got == ref_patch
    assert got['clock'] == {'zzz': 1}
    # the register record itself carries the re-stamped attribution
    reg = nat.get_register('doc', ROOT_ID, 'k')
    assert [(r['actor'], r['seq']) for r in reg] == [('zzz', 1)]


class TestHostDominanceParity:
    """A/B parity between the two dominance implementations: the device
    kernel (`ops/pallas_dominance.py` / the fused dispatch) and the C++
    Fenwick sweep (`amtpu_host_dominance`), which the driver selects
    per-platform (AMTPU_HOST_DOM; default host on the CPU backend).  The
    env knob is read per BATCH, so one process can drive both paths on
    identical inputs and require byte-identical patch streams."""

    def _run(self, batches, hostdom):
        import os
        prior = os.environ.get('AMTPU_HOST_DOM')
        prior_full = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_DOM'] = hostdom
        # the A/B here is device-dominance vs Fenwick-mid: both need the
        # KERNEL dispatch, which host-full (the CPU default) skips
        os.environ['AMTPU_HOST_FULL'] = '0'
        try:
            pool = native_pool()
            out = [pool.apply_batch(b) for b in batches]
            out.append(pool.get_patch(0))
            return out
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_DOM', None)
            else:
                os.environ['AMTPU_HOST_DOM'] = prior
            if prior_full is None:
                os.environ.pop('AMTPU_HOST_FULL', None)
            else:
                os.environ['AMTPU_HOST_FULL'] = prior_full

    @pytest.mark.parametrize('seed,structure', [
        (31, 'list'), (32, 'mixed'), (33, 'mixed'),
    ])
    def test_ab_identical_random(self, seed, structure):
        changes = WorkloadGen(seed, structure=structure).generate(24)
        rng = random.Random(seed)
        batches = []
        i = 0
        while i < len(changes):
            n = rng.randint(1, 6)
            batches.append({0: changes[i:i + n]})
            i += n
        assert self._run(batches, '1') == self._run(batches, '0')

    def test_ab_identical_interleaved_delete(self, ):
        """Concurrent insert/delete on one text: visibility deltas hit
        the Fenwick sweep's -1 path and the remove-index bookkeeping."""
        chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeText', 'obj': 't'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
             'value': 't'}]}]
        last = '_head'
        e = 0
        live = []
        rng = random.Random(99)
        for seq in range(2, 12):
            ops = []
            for _ in range(20):
                if live and rng.random() < 0.3:
                    victim = live.pop(rng.randrange(len(live)))
                    ops.append({'action': 'del', 'obj': 't',
                                'key': victim})
                else:
                    e += 1
                    ops.append({'action': 'ins', 'obj': 't', 'key': last,
                                'elem': e})
                    ops.append({'action': 'set', 'obj': 't',
                                'key': 'a0:%d' % e, 'value': 'x'})
                    last = 'a0:%d' % e
                    live.append(last)
            chs.append({'actor': 'a0', 'seq': seq, 'deps': {},
                        'ops': ops})
        batches = [{0: [c]} for c in chs]
        a = self._run(batches, '1')
        b = self._run(batches, '0')
        assert a == b
        # and both equal the scalar oracle
        st = Backend.init()
        st, _ = Backend.apply_changes(st, chs)
        assert a[-1] == Backend.get_patch(st)

    @pytest.mark.parametrize('hostdom', ['1', '0'])
    def test_overflow_fallback_under_both_dominance_modes(self, hostdom):
        """The fused overflow -> oracle fallback with LIST dominance
        work, under both dominance modes.  The dynamic window makes
        saturation unreachable in normal operation, so AMTPU_WEFF=2
        forces a 2-wide window against 5 concurrent writers per element:
        the kernel flags overflow, amtpu_mid re-resolves the groups with
        the host oracle, and indexes come from the device kernel
        (hostdom=0) or the Fenwick sweep consuming host_registers
        (hostdom=1).  Both must match the scalar oracle byte-for-byte."""
        import os
        chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': 'l'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'list',
             'value': 'l'},
            {'action': 'ins', 'obj': 'l', 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': 'l', 'key': 'a0:1', 'value': 'base'},
            {'action': 'ins', 'obj': 'l', 'key': 'a0:1', 'elem': 2},
            {'action': 'set', 'obj': 'l', 'key': 'a0:2', 'value': 'two'},
        ]}]
        # 5 concurrent writers on BOTH elements (wide register groups on
        # list element keys -> window overflow at weff=2)
        for a in range(1, 6):
            chs.append({'actor': 'w%d' % a, 'seq': 1, 'deps': {'a0': 1},
                        'ops': [
                {'action': 'set', 'obj': 'l', 'key': 'a0:1',
                 'value': 'w%d-1' % a},
                {'action': 'set', 'obj': 'l', 'key': 'a0:2',
                 'value': 'w%d-2' % a} if a != 3 else
                {'action': 'del', 'obj': 'l', 'key': 'a0:2'},
            ]})
        st = Backend.init()
        st, _ = Backend.apply_changes(st, chs)

        prior = {k: os.environ.get(k)
                 for k in ('AMTPU_WEFF', 'AMTPU_HOST_DOM',
                           'AMTPU_HOST_FULL')}
        os.environ['AMTPU_WEFF'] = '2'
        os.environ['AMTPU_HOST_DOM'] = hostdom
        os.environ['AMTPU_HOST_FULL'] = '0'   # overflow needs the kernel
        try:
            from automerge_tpu import trace
            trace.metrics_reset()
            pool = native_pool()
            # deliver concurrent writers as ONE batch so the register
            # rows coexist in one dispatch
            pool.apply_batch({0: [chs[0]]})
            pool.apply_batch({0: chs[1:]})
            assert pool.get_patch(0) == Backend.get_patch(st)
            m = trace.metrics_snapshot()
            assert m.get('fallback.overflow_batches', 0) >= 1, m
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


class TestHostRegisterMode:
    """Host-register mode (amtpu_mid_hostreg): map-only batches whose
    register groups are mostly wider than the member window skip the
    kernel dispatch entirely and resolve at emit against the live
    mirror.  A/B vs the member-kernel + scratch-oracle path
    (AMTPU_HOST_REG=0) and vs the scalar oracle."""

    def _drive(self, batches, hostreg):
        import os
        prior = os.environ.get('AMTPU_HOST_REG')
        prior_full = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_REG'] = hostreg
        # hostreg-vs-kernel A/B: both sides run the member build, which
        # host-full (the CPU default) skips entirely
        os.environ['AMTPU_HOST_FULL'] = '0'
        try:
            from automerge_tpu import trace
            trace.metrics_reset()
            pool = native_pool()
            out = [pool.apply_batch(b) for b in batches]
            out.append(pool.get_patch(0))
            engaged = trace.metrics_snapshot().get('hostreg.batches', 0)
            if hostreg == '1':
                # the gate must actually fire, else the A/B is vacuous
                assert engaged > 0, 'hostreg gate never engaged'
            else:
                assert engaged == 0, 'hostreg ran despite AMTPU_HOST_REG=0'
            return out
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_REG', None)
            else:
                os.environ['AMTPU_HOST_REG'] = prior
            if prior_full is None:
                os.environ.pop('AMTPU_HOST_FULL', None)
            else:
                os.environ['AMTPU_HOST_FULL'] = prior_full

    def test_wide_groups_incremental_with_deletes(self):
        rng = random.Random(41)
        changes = []
        # 14 concurrent writers x 4 sequential changes each over a
        # shared 6-key space, with deletes -- every group wider than
        # the W=8 member window
        for seq in range(1, 5):
            for a in range(14):
                ops = []
                for k in rng.sample(range(6), 4):
                    if rng.random() < 0.2:
                        ops.append({'action': 'del', 'obj': ROOT_ID,
                                    'key': 'k%d' % k})
                    else:
                        ops.append({'action': 'set', 'obj': ROOT_ID,
                                    'key': 'k%d' % k,
                                    'value': 'w%02d-%d' % (a, seq)})
                changes.append({'actor': 'w%02d' % a, 'seq': seq,
                                'deps': {}, 'ops': ops})
        # incremental delivery in writer-interleaved order
        batches = []
        i = 0
        while i < len(changes):
            n = rng.randint(3, 9)
            batches.append({0: changes[i:i + n]})
            i += n
        on = self._drive(batches, '1')
        off = self._drive(batches, '0')
        assert on == off
        st = Backend.init()
        for b in batches:
            st, _ = Backend.apply_changes(st, b[0])
        assert on[-1] == Backend.get_patch(st)


class TestHostFullParity:
    """Full host path (the CPU-backend default) vs the kernel path:
    byte-identical patch streams on identical inputs, including list
    dominance (the in-emit Fenwick) and interleaved deletes."""

    def _drive(self, batches, hostfull):
        import os
        prior = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_FULL'] = hostfull
        try:
            from automerge_tpu import trace
            trace.metrics_reset()
            pool = native_pool()
            out = [pool.apply_batch(b) for b in batches]
            out.append(pool.get_patch(0))
            engaged = trace.metrics_snapshot().get('hostfull.batches', 0)
            if hostfull == '1':
                assert engaged > 0, 'hostfull gate never engaged'
            else:
                assert engaged == 0, 'hostfull ran despite =0'
            return out
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_FULL', None)
            else:
                os.environ['AMTPU_HOST_FULL'] = prior

    @pytest.mark.parametrize('seed,structure', [
        (51, 'list'), (52, 'mixed'), (53, 'map'),
    ])
    def test_ab_identical(self, seed, structure):
        changes = WorkloadGen(seed, structure=structure).generate(28)
        rng = random.Random(seed)
        batches = []
        i = 0
        while i < len(changes):
            n = rng.randint(1, 6)
            chunk = list(changes[i:i + n])
            if rng.random() < 0.3:
                rng.shuffle(chunk)
            batches.append({0: chunk})
            i += n
        a = self._drive(batches, '1')
        b = self._drive(batches, '0')
        assert a == b
        # and the scalar oracle agrees
        st = Backend.init()
        for batch in batches:
            st, _ = Backend.apply_changes(st, [dict(c) for c in batch[0]])
        assert a[-1] == Backend.get_patch(st)

    def test_undo_redo_under_hostfull(self):
        import os
        prior = os.environ.get('AMTPU_HOST_FULL')
        os.environ['AMTPU_HOST_FULL'] = '1'
        try:
            pool = native_pool()
            st = Backend.init()
            reqs = [
                {'requestType': 'change', 'actor': 'me', 'seq': 1,
                 'deps': {}, 'ops': [
                     {'action': 'makeList', 'obj': 'l'},
                     {'action': 'link', 'obj': ROOT_ID, 'key': 'xs',
                      'value': 'l'},
                     {'action': 'ins', 'obj': 'l', 'key': '_head',
                      'elem': 1},
                     {'action': 'set', 'obj': 'l', 'key': 'me:1',
                      'value': 'a'}]},
                {'requestType': 'change', 'actor': 'me', 'seq': 2,
                 'deps': {}, 'ops': [
                     {'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                      'value': 1}]},
                {'requestType': 'undo', 'actor': 'me', 'seq': 3,
                 'deps': {}},
                {'requestType': 'redo', 'actor': 'me', 'seq': 4,
                 'deps': {}},
            ]
            for r in reqs:
                st, want = Backend.apply_local_change(st, dict(r))
                got = pool.apply_local_change(0, dict(r))
                assert got == want, r['requestType']
            assert pool.get_patch(0) == Backend.get_patch(st)
        finally:
            if prior is None:
                os.environ.pop('AMTPU_HOST_FULL', None)
            else:
                os.environ['AMTPU_HOST_FULL'] = prior
