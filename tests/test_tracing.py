"""Distributed tracing tests (ISSUE 16): 128-bit trace ids, the
always-stamped wire context (stable across respawn retries and WAL
replay), the single-winner trace-file rotation, and cross-process
trace assembly with clock-skew normalization (tools/amtpu_trace.py).
The heavyweight lane -- one client-visible request whose trace spans
two server incarnations across a SIGKILL -- rides a real sidecar
subprocess, mirroring tests/test_chaos.py."""

import io
import json
import os
import signal
import sys
import threading
import time

import pytest

from automerge_tpu import telemetry
from automerge_tpu.telemetry import spans

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, 'tools'))

import amtpu_trace  # noqa: E402

ROOT_ID = '00000000-0000-0000-0000-000000000000'

CHS = [
    {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
         'value': 'magpie'}]},
    {'actor': 'a', 'seq': 2, 'deps': {'a': 1}, 'ops': [
        {'action': 'set', 'obj': ROOT_ID, 'key': 'fish',
         'value': 'pike'}]},
]


@pytest.fixture(autouse=True)
def _isolate():
    """Telemetry state is process-global: zero it around every test and
    restore the enable flag + exporter."""
    telemetry.reset_all()
    was = telemetry.enabled()
    was_file = telemetry.trace_file()
    yield
    telemetry.set_trace_file(was_file)
    if was:
        telemetry.enable()
    else:
        telemetry.disable()
    telemetry.reset_all()


# ---------------------------------------------------------------------------
# ids + wire context shape
# ---------------------------------------------------------------------------

def test_id_widths():
    tid = telemetry.new_trace_id()
    sid = telemetry.new_id()
    assert len(tid) == 32 and int(tid, 16) >= 0      # 128-bit
    assert len(sid) == 16 and int(sid, 16) >= 0      # 64-bit
    assert telemetry.new_trace_id() != tid


def test_new_root_context_shape():
    ctx = telemetry.new_root_context()
    assert set(ctx) == {'traceId', 'spanId'}
    assert len(ctx['traceId']) == 32 and len(ctx['spanId']) == 16


def test_root_span_mints_128_bit_trace():
    telemetry.enable()
    with telemetry.span('t.root') as sp:
        assert len(sp.trace_id) == 32
        assert len(sp.span_id) == 16
        with telemetry.span('t.child') as child:
            assert child.trace_id == sp.trace_id


# ---------------------------------------------------------------------------
# client stamping: always-stamp + once-per-logical-request
# ---------------------------------------------------------------------------

def _hand_client(responses):
    """A SidecarClient around BytesIO pipes (no process), the
    test_telemetry.py idiom."""
    from automerge_tpu.sidecar.client import SidecarClient
    c = SidecarClient.__new__(SidecarClient)
    c._msgpack = False
    c._next_id = 0
    c._proc = c._sock = None
    c._r = io.BytesIO(''.join(
        json.dumps(r) + '\n' for r in responses).encode())
    c._w = io.BytesIO()
    return c


def test_always_stamp_counts_roots_and_propagated():
    c = _hand_client([{'id': 1, 'result': {'ok': True}}])
    telemetry.disable()           # no ambient span possible
    c.call('ping')
    snap = telemetry.metrics_snapshot()
    assert snap.get('trace.roots') == 1.0
    assert 'trace.propagated' not in snap

    telemetry.enable()            # call() opens the client-hop span
    c._w = io.BytesIO()
    c.__dict__['_r'] = io.BytesIO(
        (json.dumps({'id': 2, 'result': {'ok': True}}) + '\n').encode())
    c.call('ping')
    assert telemetry.metrics_snapshot().get('trace.propagated') == 1.0


def test_trace_stable_across_respawn_retries():
    """The respawn retry re-sends the SAME wire context: one
    client-visible request is one trace even when the first attempt
    died with the server."""
    from automerge_tpu.sidecar.client import SidecarClient
    c = SidecarClient.__new__(SidecarClient)
    c._init_locks()
    c._heal = True
    c._proc = object()            # "owns a process"
    stamped = []

    def fake_call_raw(cmd, kwargs, trace=None):
        stamped.append((cmd, trace))
        if len(stamped) == 1:
            raise ConnectionError('server died mid-request')
        return {'ok': True}

    c._call_raw = fake_call_raw
    c._respawn_and_replay = lambda: None
    assert c.call('apply_changes', doc='d', changes=[]) == {'ok': True}
    assert [cmd for cmd, _ in stamped] == ['apply_changes',
                                           'apply_changes']
    first, retry = stamped[0][1], stamped[1][1]
    assert first is not None and first is retry


def test_wal_records_and_replays_original_trace():
    from automerge_tpu.sidecar.client import CheckpointWAL
    wal = CheckpointWAL(compact_every=1000, max_bytes=0)
    tctx = {'traceId': 'f' * 32, 'spanId': '1' * 16}
    wal.record('apply_changes', {'doc': 'd', 'changes': []}, trace=tctx)
    assert wal.log[0][2] is tctx            # 4-tuple carries the trace
    replayed = []

    def call_raw(cmd, kwargs, trace=None):
        replayed.append((cmd, trace))
        return {}

    wal.replay(call_raw)
    assert replayed == [('apply_changes', tctx)]


# ---------------------------------------------------------------------------
# rotation: single-winner (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def test_rotation_loser_does_not_re_rotate(tmp_path, monkeypatch):
    """A thread that observed the over-cap size but lost the race must
    NOT rotate again: the re-check under the lock sees the fresh file
    and returns, so the just-written ``<path>.1`` survives."""
    monkeypatch.setattr(spans, '_max_export_bytes', lambda: 256)
    path = str(tmp_path / 't.jsonl')
    telemetry.set_trace_file(path)
    telemetry.enable()
    for i in range(8):
        with telemetry.span('rot.winner', i=i, pad='x' * 64):
            pass
    assert os.path.exists(path + '.1')      # the cap tripped at least once
    rotations = telemetry.metrics_snapshot().get('trace.rotations')
    assert rotations and rotations >= 1
    kept = open(path + '.1').read()
    assert kept
    # the "loser" re-enters with the stale over-cap observation: no-op
    with spans._export_lock:
        spans._maybe_rotate_locked(256)
    assert open(path + '.1').read() == kept
    # ...and after one small write the fresh file is still under cap
    with telemetry.span('rot.small'):
        pass
    with spans._export_lock:
        spans._maybe_rotate_locked(256)
    assert open(path + '.1').read() == kept
    telemetry.set_trace_file(None)


def test_rotation_race_no_torn_lines(tmp_path, monkeypatch):
    """Concurrent writers crossing the cap: every surviving line in the
    live file AND the rotation must parse (no torn/interleaved lines,
    no lost fresh rotation)."""
    monkeypatch.setattr(spans, '_max_export_bytes', lambda: 1024)
    path = str(tmp_path / 'race.jsonl')
    telemetry.set_trace_file(path)
    telemetry.enable()

    def writer(tid):
        for i in range(100):
            with telemetry.span('rot.race', t=tid, i=i, pad='y' * 32):
                pass

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    telemetry.set_trace_file(None)
    assert telemetry.metrics_snapshot().get('trace.rotations', 0) >= 1
    parsed = 0
    for p in (path, path + '.1'):
        if not os.path.exists(p):
            continue
        for line in open(p):
            rec = json.loads(line)            # raises on a torn line
            assert rec['name'].startswith('rot.')
            parsed += 1
    assert parsed > 0


# ---------------------------------------------------------------------------
# cross-process assembly (tools/amtpu_trace.py)
# ---------------------------------------------------------------------------

def _write_jsonl(path, records):
    with open(path, 'w') as f:
        for r in records:
            f.write(json.dumps(r) + '\n')


def test_assembly_and_clock_skew(tmp_path):
    """Two synthetic process files with a deliberate +1000 s server
    clock: assembly joins them by trace id and the skew estimate
    (min child-parent delta over cross-process edges) normalizes the
    server spans back onto the client timeline."""
    tid = 'a' * 32
    client = str(tmp_path / 'client.jsonl')
    server = str(tmp_path / 'server.jsonl')
    _write_jsonl(client, [
        {'name': 'sidecar.client.request', 'trace': tid, 'span': 'c' * 16,
         'parent': None, 'start': 100.0, 'dur_s': 0.05,
         'attrs': {'cmd': 'apply_changes'}},
    ])
    _write_jsonl(server, [
        'not json at all',                    # torn line: skipped
        {'name': 'sidecar.request', 'trace': tid, 'span': 's' * 16,
         'parent': 'c' * 16, 'start': 1100.01, 'dur_s': 0.04,
         'attrs': {'cmd': 'apply_changes'}},
        {'name': 'pool.apply', 'trace': tid, 'span': 'd' * 16,
         'parent': 's' * 16, 'start': 1100.02, 'dur_s': 0.01},
    ])
    records = amtpu_trace.load_files([client, server])
    assert len(records) == 3                  # the torn line is skipped
    traces = amtpu_trace.group_traces(records)
    nodes = traces[tid]

    offsets = amtpu_trace.estimate_offsets(nodes)
    assert offsets[client] == 0.0
    assert abs(offsets[server] - 1000.01) < 1e-9

    roots = amtpu_trace.build_tree(nodes)
    assert len(roots) == 1
    root = roots[0]
    assert root['name'] == 'sidecar.client.request'
    hop = root['children'][0]
    assert hop['name'] == 'sidecar.request'
    assert hop['start_n'] >= root['start_n']  # normalized onto client time
    assert abs(hop['start_n'] - 100.0) < 1e-6

    summary = amtpu_trace.summarize(tid, nodes)
    assert summary['procs'] == 2
    assert summary['cmd'] == 'apply_changes'
    assert abs(summary['client_wall_s'] - 0.05) < 1e-9
    assert abs(summary['server_s'] - 0.04) < 1e-9
    assert abs(summary['wire_s'] - 0.01) < 1e-9

    crit = amtpu_trace.critical_path(root)
    assert {'c' * 16, 's' * 16, 'd' * 16} == crit

    out = io.StringIO()
    amtpu_trace.render_waterfall(tid, nodes, out=out)
    text = out.getvalue()
    assert 'sidecar.request' in text and '*' in text


def test_load_files_reads_rotation_sibling(tmp_path):
    path = str(tmp_path / 't.jsonl')
    _write_jsonl(path + '.1', [
        {'name': 'old', 'trace': 't' * 32, 'span': '1' * 16,
         'start': 1.0, 'dur_s': 0.1}])
    _write_jsonl(path, [
        {'name': 'new', 'trace': 't' * 32, 'span': '2' * 16,
         'start': 2.0, 'dur_s': 0.1}])
    recs = amtpu_trace.load_files([path])
    assert [r['name'] for r in recs] == ['old', 'new']
    assert all(r['_proc'] == path for r in recs)   # one skew domain


# ---------------------------------------------------------------------------
# recorder trace field
# ---------------------------------------------------------------------------

def test_recorder_event_carries_trace():
    from automerge_tpu.telemetry import recorder
    r = recorder.Recorder(8)
    r.record('request.slow', doc='d', n=3, detail='apply_changes',
             trace='b' * 32)
    r.record('batch.begin')
    evs = r.events_json()
    assert evs[-2]['trace'] == 'b' * 32
    assert evs[-1]['trace'] is None
    assert r.tail(0)[-2]['trace'] == 'b' * 32


# ---------------------------------------------------------------------------
# satellite 3: one request's trace spans two server incarnations
# ---------------------------------------------------------------------------

def test_trace_survives_kill_respawn_and_wal_replay(tmp_path,
                                                    monkeypatch):
    """SIGKILL the sidecar mid-session: the retried request keeps its
    trace id across the respawn, the WAL replay re-executes the first
    request under its ORIGINAL trace id in the new incarnation, and
    `amtpu_trace` assembles both traces across the client + server
    trace files."""
    from automerge_tpu.sidecar.client import SidecarClient
    server_trace = str(tmp_path / 'server.jsonl')
    client_trace = str(tmp_path / 'client.jsonl')
    monkeypatch.setenv('AMTPU_TRACE', '1')
    monkeypatch.setenv('AMTPU_TRACE_FILE', server_trace)
    telemetry.enable()
    telemetry.set_trace_file(client_trace)
    c = SidecarClient()
    try:
        c.apply_changes('doc1', [CHS[0]])
        os.kill(c._proc.pid, signal.SIGKILL)
        time.sleep(0.2)
        c.apply_changes('doc1', [CHS[1]])
        assert c.restarts == 1
    finally:
        c.close()
        telemetry.set_trace_file(None)

    crecs = [json.loads(ln) for ln in open(client_trace)]
    hops = [r for r in crecs if r['name'] == 'sidecar.client.request']
    assert len(hops) == 2
    trace_a, trace_b = hops[0]['trace'], hops[1]['trace']
    assert trace_a != trace_b and len(trace_a) == 32

    srecs = [json.loads(ln) for ln in open(server_trace)]

    def server_applies(tid):
        return [r for r in srecs
                if r['trace'] == tid and r['name'] == 'sidecar.request'
                and (r.get('attrs') or {}).get('cmd') == 'apply_changes']

    # request 1 executed in incarnation 1 AND replayed (same trace id)
    # into incarnation 2 -- the state both requests built on is fully
    # attributed to the request that created it
    assert len(server_applies(trace_a)) >= 2
    # the retried request 2 landed server-side under its original id
    assert server_applies(trace_b)

    traces = amtpu_trace.group_traces(
        amtpu_trace.load_files([client_trace, server_trace]))
    for tid in (trace_a, trace_b):
        s = amtpu_trace.summarize(tid, traces[tid])
        assert s['procs'] == 2                # joined across both files
        assert 'sidecar.client.request' in s['roots']
