"""Differential tests of the TPU kernels against the scalar oracle --
the kernel analogue of the reference's shadow-oracle property tests
(`/root/reference/test/skip_list_test.js:171-224`).
"""

import random

import numpy as np
import pytest

import automerge_tpu.backend.op_set as OpSet
from automerge_tpu.backend import init as backend_init
from automerge_tpu.ops.clock import (NOT_APPLIED, schedule_queue,
                                     schedule_queue_batch)
from automerge_tpu.ops.list_rank import (ceil_log2, dominance_indexes,
                                         linearize)

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def oracle_schedule(clock, changes):
    """Reference fixpoint loop (op_set.js:279-295) over (actor, seq, deps)."""
    clock = dict(clock)
    order = {}
    counter = 0
    queue = list(range(len(changes)))
    while True:
        next_queue = []
        progress = False
        for i in queue:
            actor, seq, deps = changes[i]
            deps = dict(deps)
            deps[actor] = seq - 1
            if all(clock.get(a, 0) >= s for a, s in deps.items()):
                if seq <= clock.get(actor, 0):
                    order[i] = -2  # duplicate
                else:
                    clock[actor] = seq
                    order[i] = counter
                    counter += 1
                progress = True
            else:
                next_queue.append(i)
        queue = next_queue
        if not progress:
            return order, clock


class TestScheduler:
    def run_case(self, n_actors, clock0, changes):
        A = n_actors
        C = len(changes)
        actor = np.full((C,), -1, np.int32)
        seq = np.zeros((C,), np.int32)
        deps = np.zeros((C, A), np.int32)
        for i, (a, s, d) in enumerate(changes):
            actor[i] = a
            seq[i] = s
            for da, ds in d.items():
                deps[i, da] = ds
        clock = np.zeros((A,), np.int32)
        for a, s in clock0.items():
            clock[a] = s
        order, new_clock = schedule_queue(
            clock, actor, seq, deps, np.ones((C,), bool))
        order = np.asarray(order)
        new_clock = np.asarray(new_clock)

        expect_order, expect_clock = oracle_schedule(clock0, changes)
        for i in range(C):
            exp = expect_order.get(i)
            if exp is None:
                assert order[i] == int(NOT_APPLIED), (i, order[i])
            else:
                assert order[i] == exp, (i, order[i], exp)
        for a in range(A):
            assert new_clock[a] == expect_clock.get(a, 0)

    def test_in_order_single_actor(self):
        self.run_case(2, {}, [(0, 1, {}), (0, 2, {}), (0, 3, {})])

    def test_out_of_order_buffering(self):
        # seq 3 and 2 arrive before seq 1: two fixpoint passes needed
        self.run_case(2, {}, [(0, 3, {}), (0, 2, {}), (0, 1, {})])

    def test_cross_actor_deps(self):
        self.run_case(3, {}, [
            (1, 1, {0: 1}),   # blocked until actor0 seq1
            (0, 1, {}),
            (2, 1, {0: 1, 1: 1}),
        ])

    def test_duplicates_and_unresolvable(self):
        self.run_case(2, {0: 2}, [
            (0, 1, {}),          # duplicate (already applied)
            (0, 3, {}),          # fresh
            (1, 5, {}),          # gap: never ready (seq 1..4 missing)
        ])

    def test_random_schedules(self):
        rng = random.Random(7)
        for trial in range(25):
            A = rng.randint(1, 4)
            # build a valid causal history, then deliver in random order
            clocks = {a: 0 for a in range(A)}
            changes = []
            frontier = {}
            for _ in range(rng.randint(1, 24)):
                a = rng.randrange(A)
                clocks[a] += 1
                deps = {da: ds for da, ds in frontier.items() if da != a}
                changes.append((a, clocks[a], deps))
                frontier = {da: max(frontier.get(da, 0), ds)
                            for da, ds in list(frontier.items())
                            + [(a, clocks[a])]}
            rng.shuffle(changes)
            self.run_case(A, {}, changes)

    def test_vmapped_batch(self):
        A, C, D = 3, 4, 5
        actor = np.zeros((D, C), np.int32)
        seq = np.tile(np.arange(1, C + 1, dtype=np.int32), (D, 1))
        deps = np.zeros((D, C, A), np.int32)
        clock = np.zeros((D, A), np.int32)
        valid = np.ones((D, C), bool)
        order, new_clock = schedule_queue_batch(clock, actor, seq, deps, valid)
        assert np.all(np.asarray(order) == np.arange(C))
        assert np.all(np.asarray(new_clock)[:, 0] == C)


def build_forest_via_oracle(rng, n_ops, n_actors=3):
    """Random interleaved inserts through the oracle; returns the oracle's
    linear order and the columnar forest encoding."""
    state = backend_init()
    opset = state['opSet']
    opset = opset.copy_with_gen(1)

    actors = ['actor%d' % i for i in range(n_actors)]
    list_id = 'list-1'
    OpSet.apply_make(opset, {'action': 'makeList', 'obj': list_id})

    elems = []          # (elem_id, ctr, actor_rank, parent_elem_id)
    max_elem = 0
    for i in range(n_ops):
        a = rng.randrange(n_actors)
        max_elem += 1
        parent = '_head' if not elems or rng.random() < 0.2 else \
            rng.choice(elems)[0]
        op = {'action': 'ins', 'obj': list_id, 'key': parent,
              'elem': max_elem, 'actor': actors[a], 'seq': 1}
        OpSet.apply_insert(opset, op)
        elems.append(('%s:%d' % (actors[a], max_elem), max_elem, a, parent))

    # oracle linear order: walk get_next from _head
    oracle_order = []
    key = '_head'
    while True:
        key = OpSet.get_next(opset, list_id, key)
        if key is None:
            break
        oracle_order.append(key)
    return elems, oracle_order


class TestLinearize:
    @pytest.mark.parametrize('n_ops,seed', [(1, 0), (5, 1), (30, 2), (100, 3),
                                            (100, 4), (250, 5)])
    def test_matches_oracle_walk(self, n_ops, seed):
        rng = random.Random(seed)
        elems, oracle_order = build_forest_via_oracle(rng, n_ops)
        L = len(elems)
        index_of = {e[0]: i for i, e in enumerate(elems)}
        obj = np.zeros((L,), np.int32)
        parent = np.array([index_of.get(e[3], -1) for e in elems], np.int32)
        ctr = np.array([e[1] for e in elems], np.int32)
        actor = np.array([e[2] for e in elems], np.int32)
        valid = np.ones((L,), bool)
        rank = np.asarray(linearize(obj, parent, ctr, actor, valid,
                                    n_iters=ceil_log2(L) + 1))
        got_order = [None] * L
        for i in range(L):
            got_order[rank[i]] = elems[i][0]
        assert got_order == oracle_order

    def test_multiple_objects(self):
        # two independent lists in one arena: obj 0 has a->b, obj 1 has c
        obj = np.array([0, 0, 1], np.int32)
        parent = np.array([-1, 0, -1], np.int32)
        ctr = np.array([1, 2, 1], np.int32)
        actor = np.array([0, 0, 0], np.int32)
        valid = np.ones((3,), bool)
        rank = np.asarray(linearize(obj, parent, ctr, actor, valid, n_iters=3))
        assert rank.tolist() == [0, 1, 0]

    def test_padding_rows(self):
        obj = np.array([0, 0, 0, 0], np.int32)
        parent = np.array([-1, 0, -1, -1], np.int32)
        ctr = np.array([1, 2, 7, 9], np.int32)
        actor = np.array([0, 0, 0, 0], np.int32)
        valid = np.array([True, True, False, False])
        rank = np.asarray(linearize(obj, parent, ctr, actor, valid, n_iters=3))
        assert rank[0] == 0 and rank[1] == 1
        assert rank[2] == -1 and rank[3] == -1


class TestDominanceIndexes:
    def test_against_bruteforce(self):
        rng = random.Random(11)
        for trial in range(10):
            L = rng.randint(1, 40)
            T = rng.randint(1, 60)
            n_objs = rng.randint(1, 3)
            elem_obj = np.array([rng.randrange(n_objs) for _ in range(L)],
                                np.int32)
            # unique ranks per object
            elem_rank = np.zeros((L,), np.int32)
            for o in range(n_objs):
                idxs = [i for i in range(L) if elem_obj[i] == o]
                for r, i in enumerate(rng.sample(idxs, len(idxs))):
                    elem_rank[i] = r
            vis = np.array([rng.random() < 0.5 for _ in range(L)], np.float32)
            vis0 = vis.copy()

            op_elem = np.zeros((T,), np.int32)
            op_delta = np.zeros((T,), np.int32)
            expect = np.zeros((T,), np.int32)
            vis_state = vis.copy()
            for t in range(T):
                e = rng.randrange(L)
                op_elem[t] = e
                expect[t] = int(sum(
                    vis_state[i] for i in range(L)
                    if elem_obj[i] == elem_obj[e]
                    and elem_rank[i] < elem_rank[e]))
                if vis_state[e] > 0 and rng.random() < 0.5:
                    op_delta[t] = -1
                elif vis_state[e] == 0 and rng.random() < 0.7:
                    op_delta[t] = 1
                vis_state[e] += op_delta[t]

            got = np.asarray(dominance_indexes(
                elem_obj, elem_rank, vis0,
                op_elem, elem_obj[op_elem], elem_rank[op_elem],
                op_delta, np.ones((T,), bool), chunk=8))
            assert got.tolist() == expect.tolist(), trial

    def test_grouped_matches_flat(self):
        """dominance_grouped == dominance_indexes on random single-object
        batches (the grouped kernel's batch axis IS the object axis)."""
        from automerge_tpu.ops.list_rank import dominance_grouped
        rng = random.Random(23)
        K = 8
        n_objs = 4
        Lp, Tp = 32, 24
        v0 = np.zeros((n_objs, Lp), np.float32)
        er = np.full((n_objs, Lp), -1, np.int32)
        oe = np.full((n_objs, Tp), -1, np.int32)
        orank = np.full((n_objs, Tp), -1, np.int32)
        od = np.zeros((n_objs, Tp), np.int32)
        ov = np.zeros((n_objs, Tp), bool)
        expect = np.zeros((n_objs, Tp), np.int32)
        for o in range(n_objs):
            L = rng.randint(1, Lp)
            T = rng.randint(1, Tp)
            ranks = list(range(L))
            rng.shuffle(ranks)
            er[o, :L] = ranks
            vis = np.array([rng.random() < 0.5 for _ in range(L)],
                           np.float32)
            v0[o, :L] = vis
            vis_state = vis.copy()
            for t in range(T):
                e = rng.randrange(L)
                oe[o, t] = e
                orank[o, t] = er[o, e]
                ov[o, t] = True
                expect[o, t] = int(sum(
                    vis_state[i] for i in range(L)
                    if er[o, i] < er[o, e]))
                if vis_state[e] > 0 and rng.random() < 0.5:
                    od[o, t] = -1
                elif vis_state[e] == 0 and rng.random() < 0.7:
                    od[o, t] = 1
                vis_state[e] += od[o, t]
        got = np.asarray(dominance_grouped(v0, er, oe, orank, od, ov,
                                           chunk=K))
        assert (got[ov] == expect[ov]).all()


class TestRegisters:
    def test_lww_partition_and_conflicts(self):
        from automerge_tpu.ops.registers import resolve_registers
        # actors A(0), B(1), C(2).  A1 and B1 set key k concurrently;
        # C1 (deps A:1, B:1) overwrites both; A2 (deps C:1) deletes.
        A = 3
        T = 4
        group = np.zeros((T,), np.int32)
        time = np.arange(T, dtype=np.int32)
        actor = np.array([0, 1, 2, 0], np.int32)
        seq = np.array([1, 1, 1, 2], np.int32)
        clock = np.zeros((T, A), np.int32)
        clock[2] = [1, 1, 0]            # C1 allDeps
        clock[3] = [1, 1, 1]            # A2 allDeps
        is_del = np.array([False, False, False, True])
        out = resolve_registers(group, time, actor, seq, clock, is_del,
                                np.ones((T,), bool))
        alive = np.asarray(out['alive_after'])
        winner = np.asarray(out['winner'])
        conflicts = np.asarray(out['conflicts'])
        visible_before = np.asarray(out['visible_before'])
        assert alive.tolist() == [1, 2, 1, 0]
        assert winner.tolist() == [0, 1, 2, -1]
        # after B1: both alive, winner B (higher actor), conflict = A's op
        assert conflicts[1, 0] == 0 and conflicts[1, 1] == -1
        assert visible_before.tolist() == [False, True, True, True]
        assert not np.asarray(out['overflow']).any()

    def test_state_ops_superseded(self):
        from automerge_tpu.ops.registers import resolve_registers
        # state op (B, 1) persisted from a previous batch at time -1;
        # batch op (A, 2) with allDeps covering B:1 supersedes it.
        A = 2
        group = np.zeros((2,), np.int32)
        time = np.array([-1, 0], np.int32)
        actor = np.array([1, 0], np.int32)
        seq = np.array([1, 2], np.int32)
        clock = np.array([[0, 0], [1, 1]], np.int32)
        is_del = np.zeros((2,), bool)
        out = resolve_registers(group, time, actor, seq, clock, is_del,
                                np.ones((2,), bool))
        assert np.asarray(out['alive_after']).tolist() == [1, 1]
        assert np.asarray(out['winner']).tolist() == [0, 1]
        assert np.asarray(out['visible_before']).tolist() == [False, True]

    def test_concurrent_state_and_batch(self):
        from automerge_tpu.ops.registers import resolve_registers
        # state op (B, 1); batch op (A, 1) concurrent -> conflict set of 2,
        # winner is B (higher actor rank)
        A = 2
        group = np.zeros((2,), np.int32)
        time = np.array([-1, 0], np.int32)
        actor = np.array([1, 0], np.int32)
        seq = np.array([1, 1], np.int32)
        clock = np.zeros((2, A), np.int32)
        is_del = np.zeros((2,), bool)
        out = resolve_registers(group, time, actor, seq, clock, is_del,
                                np.ones((2,), bool))
        assert np.asarray(out['alive_after']).tolist() == [1, 2]
        assert np.asarray(out['winner']).tolist() == [0, 0]  # B's op index 0
        assert np.asarray(out['conflicts'])[1, 0] == 1       # A's op loses


class TestEscalationLadder:
    """escalate_overflow must equal an exact wide sliding-window dispatch
    on every overflowed group -- for antichain widths spanning several
    tiers (9, 15, 17, 33, 100+ concurrent live writers) and for the one
    shape member windows alone cannot hold (same-change dup assigns)."""

    def _concurrent_group(self, n_writers, base_time=0, gid=0, A=None):
        """Rows of one group: n_writers fully concurrent single-seq
        writers (empty clocks)."""
        rows = []
        for i in range(n_writers):
            rows.append((gid, base_time + i, i, 1, False))
        return rows

    def _dispatch(self, rows, A, dels=()):
        T = len(rows)
        group = np.array([r[0] for r in rows], np.int32)
        time = np.array([r[1] for r in rows], np.int32)
        actor = np.array([r[2] for r in rows], np.int32)
        seq = np.array([r[3] for r in rows], np.int32)
        is_del = np.array([r[4] for r in rows], bool)
        ctab = np.zeros((T, A), np.int32)
        cidx = np.arange(T, dtype=np.int32)
        return group, time, actor, seq, is_del, ctab, cidx

    @pytest.mark.parametrize('n_writers', [9, 15, 17, 33, 100, 130])
    def test_matches_wide_sliding_window(self, n_writers):
        from automerge_tpu.ops import registers as R
        cols = self._dispatch(self._concurrent_group(n_writers),
                              A=n_writers)
        group, time, actor, seq, is_del, ctab, cidx = cols
        T = len(group)
        ref = R.resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones(T, bool), window=T,
            sort_idx=np.lexsort((time, group)).astype(np.int32),
            clock_table=ctab, clock_idx=cidx)
        ref = {k: np.asarray(v) for k, v in ref.items()}
        ovf = np.zeros(T, bool)
        ovf[-1] = True   # flag one saturated row; the WHOLE group escalates
        resolved, oracle_rows, tiers = R.escalate_overflow(
            group, time, actor, seq, is_del, ctab, cidx, ovf)
        assert oracle_rows.size == 0
        assert len(resolved) == T
        expect_tier = R._tier_of(n_writers - 1, R.ESCALATION_FLOOR)
        assert list(tiers) == [expect_tier], tiers
        for row, (w, confs, alive, vb) in resolved.items():
            assert w == ref['winner'][row]
            assert confs == [c for c in ref['conflicts'][row] if c >= 0]
            assert alive == ref['alive_after'][row]
            assert vb == bool(ref['visible_before'][row])

    def test_dup_assign_same_change(self):
        """A change assigning one key twice (same actor+seq rows): the
        fixed member build can't hold it; the ladder's dup-extended
        streams must."""
        from automerge_tpu.ops import registers as R
        rows = self._concurrent_group(10)
        rows.append((0, 10, 4, 1, False))   # actor 4 assigns again, seq 1
        rows.append((0, 11, 4, 1, False))   # ...and a third time
        cols = self._dispatch(rows, A=10)
        group, time, actor, seq, is_del, ctab, cidx = cols
        T = len(group)
        ref = R.resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones(T, bool), window=T,
            sort_idx=np.lexsort((time, group)).astype(np.int32),
            clock_table=ctab, clock_idx=cidx)
        ref = {k: np.asarray(v) for k, v in ref.items()}
        resolved, oracle_rows, _ = R.escalate_overflow(
            group, time, actor, seq, is_del, ctab, cidx,
            np.ones(T, bool))
        assert oracle_rows.size == 0
        for row, (w, confs, alive, _vb) in resolved.items():
            assert w == ref['winner'][row]
            assert confs == [c for c in ref['conflicts'][row] if c >= 0]
            assert alive == ref['alive_after'][row]

    def test_multi_group_multi_tier_and_oracle_residue(self):
        """Groups of different widths bucket into different tiers in one
        call; a group wider than max_tier comes back as oracle rows."""
        from automerge_tpu.ops import registers as R
        rows = []
        rows += self._concurrent_group(9, base_time=0, gid=0)
        rows += self._concurrent_group(33, base_time=100, gid=1)
        rows += self._concurrent_group(40, base_time=200, gid=2)
        cols = self._dispatch(rows, A=40)
        group, time, actor, seq, is_del, ctab, cidx = cols
        T = len(group)
        resolved, oracle_rows, tiers = R.escalate_overflow(
            group, time, actor, seq, is_del, ctab, cidx,
            np.ones(T, bool), max_tier=32)
        # gid 2 needs W=64 > max_tier -> oracle residue, whole group
        assert sorted(oracle_rows.tolist()) == list(range(42, 82))
        assert set(tiers) == {16, 32}
        assert len(resolved) == 42
        # unflagged groups are untouched
        resolved2, oracle2, tiers2 = R.escalate_overflow(
            group, time, actor, seq, is_del, ctab, cidx,
            np.zeros(T, bool))
        assert not resolved2 and not oracle2.size and not tiers2

    def test_scratch_budget_chunks_and_oracle_residue(self):
        """The [Tn, W+1, W+1] scratch budget: a tier of many groups is
        CHUNKED into several dispatches (all still resolved), while a
        single group too large for any chunking takes the oracle."""
        import os

        from automerge_tpu.ops import registers as R
        # six groups of 300 rows each (12 actors x 25 sequential rounds:
        # width stays 12 -> tier 16, but the row count is what the
        # budget must chunk); clocks make each actor's later write
        # supersede its earlier ones
        rows = []
        t = 0
        for g in range(6):
            for s in range(1, 26):
                for a in range(12):
                    rows.append((g, t, a, s, False))
                    t += 1
        group = np.array([r[0] for r in rows], np.int32)
        time = np.array([r[1] for r in rows], np.int32)
        actor = np.array([r[2] for r in rows], np.int32)
        seq = np.array([r[3] for r in rows], np.int32)
        is_del = np.zeros(len(rows), bool)
        T = len(rows)
        ctab = np.zeros((T, 12), np.int32)
        ctab[np.arange(T), actor] = seq - 1
        cidx = np.arange(T, dtype=np.int32)
        prior = os.environ.get('AMTPU_ESCALATE_BUDGET_MB')
        os.environ['AMTPU_ESCALATE_BUDGET_MB'] = '1'
        try:
            # one group fits a dispatch; two do not -> the tier chunks
            assert R._dispatch_cost(300, 16) <= 1 << 20
            assert R._dispatch_cost(600, 16) > 1 << 20
            resolved, oracle_rows, tiers = R.escalate_overflow(
                group, time, actor, seq, is_del, ctab, cidx,
                np.ones(T, bool))
            assert oracle_rows.size == 0
            assert len(resolved) == T          # every row still resolved
            assert tiers == {16: T}
            ref = R.resolve_registers(
                group, time, actor, seq, is_del=is_del,
                alive_in=np.ones(T, bool), window=16,
                sort_idx=np.lexsort((time, group)).astype(np.int32),
                clock_table=ctab, clock_idx=cidx)
            refw = np.asarray(ref['winner'])
            refa = np.asarray(ref['alive_after'])
            for row, (w, _c, a_, _vb) in resolved.items():
                assert w == refw[row]
                assert a_ == refa[row]
            # a single group whose own padded cost exceeds the budget
            # is memory-unboundable -> oracle residue, not an OOM
            wide = self._dispatch(self._concurrent_group(600), A=600)
            g2, t2, a2, s2, d2, ct2, ci2 = wide
            r2, oracle2, tiers2 = R.escalate_overflow(
                g2, t2, a2, s2, d2, ct2, ci2, np.ones(600, bool))
            assert not r2 and not tiers2
            assert oracle2.size == 600
        finally:
            if prior is None:
                os.environ.pop('AMTPU_ESCALATE_BUDGET_MB', None)
            else:
                os.environ['AMTPU_ESCALATE_BUDGET_MB'] = prior

    def test_packed_word_codec_round_trip(self):
        """pack_register_word (kernel side) and NativeDocPool's
        _unpack_packed (host side) are the two ends of the packed
        transfer: encode/decode must round-trip at the edges -- no
        winner (0xffffff), alive saturation at PACKED_ALIVE_MAX, and
        the overflow bit."""
        from automerge_tpu.native import NativeDocPool
        from automerge_tpu.ops import registers as R
        winner = np.array([-1, 0, 123456, (1 << 24) - 2], np.int32)
        alive = np.array([0, 1, 63, 1000], np.int32)
        ovf = np.array([0, 1, 0, 1], np.uint8)
        word = R.pack_register_word(winner, alive, ovf)
        w2, a2, o2 = NativeDocPool._unpack_packed(word)
        assert w2.tolist() == winner.tolist()
        assert a2.tolist() == [0, 1, 63, R.PACKED_ALIVE_MAX]
        assert o2.tolist() == ovf.tolist()

    def test_escalated_merge_writes_decodable_words(self):
        """The packed member epilogue merges tier results INTO the packed
        word (native _collect_member_packed); the merged words must
        decode to the wide-window reference -- winner exact, alive
        saturated, overflow bit CLEAR for every ladder-resolved row even
        though the row entered flagged."""
        from automerge_tpu.native import NativeDocPool
        from automerge_tpu.ops import registers as R
        n = 70    # survivors > PACKED_ALIVE_MAX: saturation engaged
        cols = self._dispatch(self._concurrent_group(n), A=n)
        group, time, actor, seq, is_del, ctab, cidx = cols
        ref = R.resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones(n, bool), window=n,
            sort_idx=np.lexsort((time, group)).astype(np.int32),
            clock_table=ctab, clock_idx=cidx)
        pending, oracle_rows, _tiers = R.escalate_overflow_dispatch(
            group, time, actor, seq, is_del, ctab, cidx,
            np.ones(n, bool))
        assert oracle_rows.size == 0
        # the merge the native driver performs, on a base word that
        # entered with the member-overflow route (flag conceptually set)
        packed = np.full(n, -1, np.int32)     # poisoned base words
        for ch in R.escalate_overflow_collect_arrays(pending):
            packed[ch.rows] = R.pack_register_word(ch.winner, ch.alive)
        w2, a2, o2 = NativeDocPool._unpack_packed(packed)
        assert w2.tolist() == np.asarray(ref['winner']).tolist()
        assert a2.tolist() == np.minimum(
            np.asarray(ref['alive_after']), R.PACKED_ALIVE_MAX).tolist()
        assert (o2 == 0).all()

    def test_packed_word_saturates_alive(self):
        """Widened packed layout: alive saturates at 63 (bits 24..29),
        overflow rides bit 30, winner keeps its 24 bits."""
        from automerge_tpu.ops import registers as R
        n = 70   # survivors > PACKED_ALIVE_MAX
        cols = self._dispatch(self._concurrent_group(n), A=n)
        group, time, actor, seq, is_del, ctab, cidx = cols
        out = R.resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones(n, bool), window=n,
            sort_idx=np.lexsort((time, group)).astype(np.int32),
            clock_table=ctab, clock_idx=cidx)
        packed = np.asarray(out['packed'])
        alive = np.asarray(out['alive_after'])
        last = int(np.argmax(alive))          # row with all 70 alive
        assert alive[last] == n
        assert (packed[last] >> 24) & 0x3f == R.PACKED_ALIVE_MAX
        assert (packed[last] & 0xffffff) == np.asarray(out['winner'])[last]
        assert (packed[last] >> 30) & 1 == 0


class TestPallasDominance:
    """The Pallas TPU kernel must equal the XLA kernel bit-for-bit; on the
    CPU test mesh it runs through the Pallas interpreter."""

    def _random_case(self, seed, W=8, L=128, T=128):
        rng = random.Random(seed)
        v0 = np.zeros((W, L), np.float32)
        er = np.full((W, L), -1, np.int32)
        oe = np.full((W, T), -1, np.int32)
        orank = np.full((W, T), -1, np.int32)
        od = np.zeros((W, T), np.int32)
        ov = np.zeros((W, T), bool)
        for o in range(W):
            n = rng.randint(1, L)
            t = rng.randint(1, T)
            ranks = list(range(n))
            rng.shuffle(ranks)
            er[o, :n] = ranks
            v0[o, :n] = [rng.random() < 0.5 for _ in range(n)]
            for k in range(t):
                e = rng.randrange(n)
                oe[o, k] = e
                orank[o, k] = er[o, e]
                od[o, k] = rng.choice([-1, 0, 1])
                ov[o, k] = True
        return v0, er, oe, orank, od, ov

    @pytest.mark.parametrize('seed,W', [(3, 8), (4, 8), (5, 24)])
    def test_interpreter_matches_xla(self, seed, W):
        # W=24 covers grid > 1: per-program VMEM scratch re-init
        from automerge_tpu.ops.list_rank import dominance_grouped
        from automerge_tpu.ops.pallas_dominance import \
            dominance_grouped_pallas
        args = self._random_case(seed, W=W)
        want = np.asarray(dominance_grouped(*args, chunk=128))
        got = np.asarray(dominance_grouped_pallas(*args, chunk=128,
                                                  interpret=True))
        ov = args[-1]
        assert (got[ov] == want[ov]).all()

    def test_auto_dispatch_fallback(self):
        # off-TPU the dispatcher must route to the XLA kernel
        from automerge_tpu.ops.list_rank import dominance_grouped
        from automerge_tpu.ops.pallas_dominance import \
            dominance_grouped_auto
        args = self._random_case(9, W=4, L=48, T=64)
        want = np.asarray(dominance_grouped(*args, chunk=64))
        got = np.asarray(dominance_grouped_auto(*args, chunk=64))
        ov = args[-1]
        assert (got[ov] == want[ov]).all()


class TestClockTablePath:
    def test_table_matches_dense(self):
        """resolve_registers(clock_table, clock_idx) must equal the dense
        clock path on identical inputs."""
        from automerge_tpu.ops.registers import resolve_registers
        rng = random.Random(17)
        T, A, C = 32, 4, 6
        group = np.array([rng.randrange(4) for _ in range(T)], np.int32)
        time = np.arange(T, dtype=np.int32)
        actor = np.array([rng.randrange(A) for _ in range(T)], np.int32)
        seq = np.array([rng.randint(1, 5) for _ in range(T)], np.int32)
        table = np.array([[rng.randint(0, 5) for _ in range(A)]
                          for _ in range(C)], np.int32)
        idx = np.array([rng.randrange(C) for _ in range(T)], np.int32)
        is_del = np.array([rng.random() < 0.2 for _ in range(T)])
        alive = np.ones((T,), bool)
        dense = resolve_registers(group, time, actor, seq, table[idx],
                                  is_del, alive)
        tabled = resolve_registers(group, time, actor, seq, is_del=is_del,
                                   alive_in=alive, clock_table=table,
                                   clock_idx=idx)
        for k in ('winner', 'alive_after', 'conflicts', 'overflow',
                  'packed'):
            assert (np.asarray(dense[k]) == np.asarray(tabled[k])).all(), k

    def test_requires_exactly_one_clock_form(self):
        from automerge_tpu.ops.registers import resolve_registers
        z = np.zeros((4,), np.int32)
        with pytest.raises(ValueError):
            resolve_registers(z, z, z, z, is_del=z.astype(bool),
                              alive_in=np.ones(4, bool))


class TestPallasRegisters:
    """The Pallas sliding-window register kernel must equal the XLA
    kernel bit-for-bit (interpret mode on the CPU test mesh)."""

    def _random_case(self, seed, T=256, A=16, n_groups=24, window=4):
        rng = random.Random(seed)
        group = np.full((T,), -1, np.int32)
        time = np.zeros((T,), np.int32)
        actor = np.zeros((T,), np.int32)
        seq = np.zeros((T,), np.int32)
        is_del = np.zeros((T,), bool)
        # deduplicated clock rows, one per (actor, seq)
        rows = {}
        table = [np.zeros((A,), np.int32)]
        idx = np.zeros((T,), np.int32)
        n_real = rng.randint(T // 2, T)
        # per-actor current seq; clocks grow monotonically per actor with
        # random cross-actor knowledge -- realistic causal structure
        seqs = [0] * A
        known = [np.zeros((A,), np.int32) for _ in range(A)]
        for i in range(n_real):
            g = rng.randrange(n_groups)
            a = rng.randrange(A)
            if rng.random() < 0.6:
                seqs[a] += 1
                # learn some other actor's frontier before authoring
                o = rng.randrange(A)
                known[a] = np.maximum(known[a], known[o])
                known[a][a] = seqs[a] - 1
            s = max(seqs[a], 1)
            seqs[a] = s
            group[i] = g
            time[i] = i
            actor[i] = a
            seq[i] = s
            is_del[i] = rng.random() < 0.1
            key = (a, s)
            if key not in rows:
                clk = known[a].copy()
                clk[a] = s - 1
                rows[key] = len(table)
                table.append(clk)
            idx[i] = rows[key]
        # a few state rows (negative times) for early groups
        for g in range(min(4, n_groups)):
            i = n_real - 1 - g
            if i > 0:
                time[i] = -(g + 1)
        clock_table = np.stack(table)
        sort_idx = np.lexsort((time, group)).astype(np.int32)
        return (group, time, actor, seq, is_del, sort_idx,
                clock_table, idx)

    @pytest.mark.parametrize('seed,window', [(1, 4), (2, 8), (7, 2)])
    def test_interpreter_matches_xla(self, seed, window):
        from automerge_tpu.ops.pallas_registers import \
            resolve_registers_pallas
        from automerge_tpu.ops.registers import resolve_registers
        (group, time, actor, seq, is_del, sort_idx,
         clock_table, idx) = self._random_case(seed, window=window)
        want = resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones_like(is_del), window=window,
            sort_idx=sort_idx, clock_table=clock_table, clock_idx=idx)
        got = resolve_registers_pallas(
            group, time, actor, seq, is_del, sort_idx,
            clock_table, idx, window=window, interpret=True)
        for k in ('winner', 'alive_after', 'conflicts', 'visible_before',
                  'overflow', 'packed'):
            assert (np.asarray(got[k]) == np.asarray(want[k])).all(), k

    def test_auto_dispatch_fallback(self):
        # off-TPU the dispatcher must route to the XLA kernel
        from automerge_tpu.ops.pallas_registers import \
            resolve_registers_auto
        from automerge_tpu.ops.registers import resolve_registers
        (group, time, actor, seq, is_del, sort_idx,
         clock_table, idx) = self._random_case(11)
        want = resolve_registers(
            group, time, actor, seq, is_del=is_del,
            alive_in=np.ones_like(is_del), window=4,
            sort_idx=sort_idx, clock_table=clock_table, clock_idx=idx)
        got = resolve_registers_auto(
            group, time, actor, seq, is_del, np.ones_like(is_del),
            sort_idx, clock_table, idx, window=4)
        for k in ('winner', 'packed'):
            assert (np.asarray(got[k]) == np.asarray(want[k])).all(), k
