"""Queued-path bound: parity AND a wall-time ceiling at scale.

The causal queue (the `applyQueuedOps` analogue,
/root/reference/backend/op_set.js:279-295) carries 0% of every benchmark
config -- real change streams arrive in order (docs/PERF.md wavefront
table) -- so without this test a quadratic regression in the fixpoint
would be invisible to every perf artifact.  Here ~10k fully shuffled
changes across ~100 docs must (a) produce byte-identical patches to the
oracle fed the SAME shuffled stream, and (b) resolve inside a wall
ceiling in BOTH execution modes, turning docs/PERF.md's "~1ms per 200
shuffled changes" claim into a tested bound.

The ceiling is generous (the fixpoint itself resolves this workload in
well under a second; the bound mostly guards against quadratic blowup)
because the host jitters +-40% between windows and CI machines vary.
"""

import os
import random
import time

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
from automerge_tpu.utils.common import ROOT_ID

N_DOCS = int(os.environ.get('AMTPU_QBOUND_DOCS', '100'))
CHANGES_PER_DOC = int(os.environ.get('AMTPU_QBOUND_CHANGES', '100'))
OPS_PER_CHANGE = 4
# wall ceiling for applying the whole shuffled batch (~10k changes /
# ~40k ops).  The measured time is ~0.5-1s on the 1-core CI host; a
# quadratic queue regression lands >60s.
CEILING_S = float(os.environ.get('AMTPU_QBOUND_CEILING_S', '15'))


def build_shuffled_batch(rng):
    """{doc: [changes]} -- per doc, two actors' causal chains (each
    change depends on the doc's full frontier) delivered fully shuffled,
    so nothing is admissible in arrival order beyond chance."""
    batch = {}
    for d in range(N_DOCS):
        tid = 'list-%d' % d
        changes = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': tid},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'l', 'value': tid}]}]
        seqs = {'a0': 1, 'a1': 0}
        elem = 0
        prev = '_head'
        for i in range(CHANGES_PER_DOC - 1):
            actor = 'a%d' % (i % 2)
            ops = []
            for _ in range(OPS_PER_CHANGE // 2):
                elem += 1
                key = '%s:%d' % (actor, elem)
                ops.append({'action': 'ins', 'obj': tid, 'key': prev,
                            'elem': elem})
                ops.append({'action': 'set', 'obj': tid, 'key': key,
                            'value': elem % 9})
                prev = key
            seqs[actor] += 1
            deps = {a: s for a, s in seqs.items() if a != actor and s}
            changes.append({'actor': actor, 'seq': seqs[actor],
                            'deps': deps, 'ops': ops})
        shuffled = changes[:]
        rng.shuffle(shuffled)
        batch[d] = shuffled
    return batch


@pytest.mark.parametrize('mode', ['host_full', 'kernel'])
def test_shuffled_bulk_parity_and_bound(mode):
    rng = random.Random(1234)
    batch = build_shuffled_batch(rng)
    n_changes = sum(len(c) for c in batch.values())
    assert n_changes == N_DOCS * CHANGES_PER_DOC

    prior = os.environ.get('AMTPU_HOST_FULL')
    os.environ['AMTPU_HOST_FULL'] = '1' if mode == 'host_full' else '0'
    try:
        pool = NativeDocPool()
        # warmup on a throwaway doc with an ORDERED, admissible change
        # stream (a shuffled prefix would just buffer without emitting,
        # compiling nothing) so kernel-mode jit compiles stay outside
        # the measured window
        warm = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeList', 'obj': 'w'},
            {'action': 'link', 'obj': ROOT_ID, 'key': 'l', 'value': 'w'},
            {'action': 'ins', 'obj': 'w', 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': 'w', 'key': 'a0:1', 'value': 1}]}]
        pool.apply_changes('warm', warm)
        t0 = time.perf_counter()
        pool.apply_batch(batch)
        wall = time.perf_counter() - t0
    finally:
        if prior is None:
            os.environ.pop('AMTPU_HOST_FULL', None)
        else:
            os.environ['AMTPU_HOST_FULL'] = prior

    assert wall < CEILING_S, (
        '%s: %d fully shuffled changes took %.2fs (ceiling %.0fs) -- '
        'the causal-queue fixpoint has regressed'
        % (mode, n_changes, wall, CEILING_S))

    # everything admitted: nothing left buffered
    for d in (0, N_DOCS // 2, N_DOCS - 1):
        assert pool.get_missing_deps(d) == {}

    # byte parity vs the oracle fed the SAME shuffled stream (sampled:
    # the scalar oracle replays ~100 docs of this in ~10s otherwise)
    for d in range(0, N_DOCS, 10):
        st = Backend.init()
        st, _ = Backend.apply_changes(st, batch[d])
        assert pool.get_patch(d) == Backend.get_patch(st), \
            '%s: doc %d diverged from oracle under shuffled delivery' \
            % (mode, d)
    print('%s: %d shuffled changes in %.3fs' % (mode, n_changes, wall))
