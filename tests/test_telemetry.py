"""Telemetry layer tests (PR 1): histogram bucketing/percentile math,
concurrent-writer safety, span-id propagation through a sidecar
serve_stream round trip, and a golden check that the Prometheus text
exposition parses (format 0.0.4)."""

import io
import json
import os
import re
import subprocess
import sys
import threading
import urllib.request

import pytest

from automerge_tpu import telemetry, trace
from automerge_tpu.telemetry.metrics import MetricRegistry

ROOT_ID = '00000000-0000-0000-0000-000000000000'
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CH = {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'set', 'obj': ROOT_ID, 'key': 'bird', 'value': 'magpie'}]}


@pytest.fixture(autouse=True)
def _isolate():
    """Telemetry state is process-global: zero it around every test and
    restore the enable flag + exporter."""
    telemetry.reset_all()
    was = telemetry.enabled()
    was_file = telemetry.trace_file()
    yield
    telemetry.set_trace_file(was_file)
    if was:
        telemetry.enable()
    else:
        telemetry.disable()
    telemetry.reset_all()


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------

def test_histogram_bucketing_and_counts():
    reg = MetricRegistry()
    h = reg.histogram('h_test_seconds', 'test')
    bounds = h.labels().bounds
    # boundary value lands in its own bucket (le = inclusive upper edge)
    h.observe(bounds[3])
    assert h.labels().counts[3] == 1
    # just above a bound spills into the next bucket
    h.observe(bounds[3] * 1.0001)
    assert h.labels().counts[4] == 1
    # below the first bound -> bucket 0; beyond the last -> +Inf bucket
    h.observe(0.0)
    assert h.labels().counts[0] == 1
    h.observe(bounds[-1] * 10)
    assert h.labels().counts[-1] == 1
    assert h.labels().count == 4
    assert abs(h.labels().sum -
               (bounds[3] + bounds[3] * 1.0001 + bounds[-1] * 10)) < 1e-9


def test_histogram_percentiles():
    reg = MetricRegistry()
    h = reg.histogram('h_pct_seconds', 'test')
    for _ in range(50):
        h.observe(0.0005)       # bucket (..., 0.000512]
    for _ in range(50):
        h.observe(0.002)        # bucket (0.001024, 0.002048]
    assert h.quantile(0.5) <= 0.000512 + 1e-12
    p95 = h.quantile(0.95)
    assert 0.001024 < p95 <= 0.002048
    assert h.quantile(0.99) <= 0.002048
    s = h.summary()
    assert s['count'] == 100 and abs(s['sum'] - 0.125) < 1e-6
    assert s['p50'] <= s['p95'] <= s['p99']


def test_histogram_edge_cases():
    reg = MetricRegistry()
    h = reg.histogram('h_edge_seconds', 'test')
    assert h.quantile(0.5) == 0.0           # empty
    h.observe(1e9)                          # +Inf bucket clamps to last bound
    assert h.quantile(0.99) == h.labels().bounds[-1]


def test_counter_rejects_negative_and_gauge_sets():
    reg = MetricRegistry()
    c = reg.counter('c_total', 'test')
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge('g_now', 'test')
    g.set(4.5)
    g.dec(0.5)
    assert g.value == 4.0
    # re-registration with a different schema is an error
    with pytest.raises(ValueError):
        reg.gauge('c_total', 'test')


# ---------------------------------------------------------------------------
# concurrency: hammer the registry like ShardedNativePool hammers trace
# ---------------------------------------------------------------------------

def test_concurrent_writers_exact_totals():
    reg = MetricRegistry()
    c = reg.counter('cc_total', 'test')
    lc = reg.counter('cl_total', 'test', ('shard',))
    h = reg.histogram('ch_seconds', 'test')
    n_threads, n_iter = 8, 2000

    def hammer(tid):
        child = lc.labels(str(tid % 2))
        for _ in range(n_iter):
            c.inc()
            child.inc(2)
            h.observe(0.001)
            telemetry.metric('fallback.hammer')
            telemetry.phase_count('hammer.phase')

    telemetry.enable()
    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * n_iter
    assert c.value == total
    assert lc.labels('0').value + lc.labels('1').value == 2 * total
    assert h.labels().count == total
    assert telemetry.metrics_snapshot()['fallback.hammer'] == total
    assert telemetry.phase_snapshot()['hammer.phase']['n'] == total


# ---------------------------------------------------------------------------
# runtime toggle + trace shim compatibility
# ---------------------------------------------------------------------------

def test_runtime_toggle_and_trace_shim():
    telemetry.disable()
    with trace.span('t.off'):
        pass
    assert 't.off' not in trace.snapshot()
    trace.ENABLED = True                    # legacy toggle spelling
    assert telemetry.enabled()
    with trace.span('t.on'):
        pass
    trace.add('t.add', 0.25, 2)
    trace.count('t.count', 3)
    snap = trace.snapshot()
    assert snap['t.on']['n'] == 1
    assert abs(snap['t.add']['s'] - 0.25) < 1e-9 and snap['t.add']['n'] == 2
    assert snap['t.count']['n'] == 3
    assert 'occupancy seconds' in trace.report()
    trace.ENABLED = False
    assert not telemetry.enabled()
    # the always-on flat metrics ignore the toggle
    trace.metric('fallback.test', 2)
    assert trace.metrics_snapshot()['fallback.test'] == 2.0


def test_span_nesting_and_context():
    telemetry.enable()
    assert telemetry.current_trace_context() is None
    with telemetry.span('outer') as outer:
        ctx = telemetry.current_trace_context()
        assert ctx == {'traceId': outer.trace_id, 'spanId': outer.span_id}
        with telemetry.span('inner') as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert telemetry.current_trace_context() is None


# ---------------------------------------------------------------------------
# span-id propagation through a sidecar serve_stream round trip
# ---------------------------------------------------------------------------

def test_span_propagation_serve_stream_round_trip(tmp_path):
    from automerge_tpu.sidecar.server import serve_stream
    telemetry.enable()
    path = str(tmp_path / 'spans.jsonl')
    telemetry.set_trace_file(path)
    trace_id, parent_id = 'feedfacecafed00d', '0123456789abcdef'
    reqs = [
        {'id': 1, 'cmd': 'apply_changes', 'doc': 'd', 'changes': [CH],
         'trace': {'traceId': trace_id, 'spanId': parent_id}},
        {'id': 2, 'cmd': 'get_patch', 'doc': 'd'},
    ]
    rfile = io.BytesIO(''.join(json.dumps(r) + '\n' for r in reqs).encode())
    wfile = io.BytesIO()
    serve_stream(rfile, wfile)
    telemetry.set_trace_file(None)     # flush/close before reading

    resps = [json.loads(l) for l in wfile.getvalue().splitlines()]
    assert [r['id'] for r in resps] == [1, 2]
    assert resps[0]['result']['clock'] == {'a': 1}
    # the trace envelope is consumed server-side: responses carry no
    # telemetry fields (byte-parity with the pre-PR-1 protocol)
    assert set(resps[0]) == {'id', 'result'}

    recs = [json.loads(l) for l in open(path)]
    req_spans = [r for r in recs if r['name'] == 'sidecar.request']
    assert len(req_spans) == 2
    # request 1 RESUMES the client's trace; request 2 mints its own
    assert req_spans[0]['trace'] == trace_id
    assert req_spans[0]['parent'] == parent_id
    assert req_spans[0]['attrs']['cmd'] == 'apply_changes'
    assert req_spans[1]['trace'] != trace_id
    # nested pool spans joined the SAME trace as their request
    nested = [r for r in recs if r['parent'] == req_spans[0]['span']]
    assert nested and all(r['trace'] == trace_id for r in nested)


def test_client_injects_trace_context():
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.sidecar.server import serve_stream
    telemetry.enable()
    c = SidecarClient.__new__(SidecarClient)
    c._msgpack = False
    c._next_id = 0
    c._proc = c._sock = None
    c._r = io.BytesIO(
        (json.dumps({'id': 1, 'result': {'ok': True}}) + '\n').encode())
    c._w = io.BytesIO()
    with telemetry.span('frontend.change') as root:
        assert c.call('ping') == {'ok': True}
    sent = json.loads(c._w.getvalue())
    # same trace as the caller's span; the parent span id is the
    # client-hop span (sidecar.client.request) nested under it, so the
    # server's spans become children of the hop, not of the frontend
    assert sent['trace']['traceId'] == root.trace_id
    assert sent['trace']['spanId'] != root.span_id
    assert len(sent['trace']['spanId']) == 16
    # ...and the server resumes exactly that trace
    out = io.BytesIO()
    serve_stream(io.BytesIO(c._w.getvalue()), out)
    assert json.loads(out.getvalue())['result'] == {'ok': True}

    # without an ambient span the client still stamps: a freshly minted
    # ROOT context (ISSUE 16 always-stamp; 128-bit trace id), distinct
    # from the earlier trace
    telemetry.disable()
    c._w = io.BytesIO()
    c.__dict__['_r'] = io.BytesIO(
        (json.dumps({'id': 2, 'result': {'ok': True}}) + '\n').encode())
    c.call('ping')
    sent2 = json.loads(c._w.getvalue())
    assert len(sent2['trace']['traceId']) == 32
    assert sent2['trace']['traceId'] != sent['trace']['traceId']

    # AMTPU_TRACE_WIRE=0 (latched per client) turns stamping off
    c._wire_trace = False
    c._w = io.BytesIO()
    c.__dict__['_r'] = io.BytesIO(
        (json.dumps({'id': 3, 'result': {'ok': True}}) + '\n').encode())
    c.call('ping')
    assert 'trace' not in json.loads(c._w.getvalue())


# ---------------------------------------------------------------------------
# Prometheus exposition: golden parse + required families
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'           # metric name
    r'(\{[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*"'    # first label
    r'(?:,[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*")*\})?'  # more labels
    r' (-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\+Inf|NaN)$')


def parse_exposition(body):
    """Strict mini-parser: returns ({family: type}, [(name, labels, value)]);
    asserts every line is HELP, TYPE, or a well-formed sample."""
    types, samples = {}, []
    for line in body.splitlines():
        if not line:
            continue
        if line.startswith('# HELP '):
            assert len(line.split(' ', 3)) == 4, line
            continue
        if line.startswith('# TYPE '):
            _, _, name, type_ = line.split(' ', 3)
            assert type_ in ('counter', 'gauge', 'histogram'), line
            types[name] = type_
            continue
        m = _SAMPLE_RE.match(line)
        assert m, 'unparseable exposition line: %r' % line
        samples.append((m.group(1), m.group(2) or '', m.group(3)))
    for name, _labels, _v in samples:
        base = re.sub(r'_(bucket|sum|count)$', '', name)
        assert name in types or base in types, \
            'sample %s has no TYPE declaration' % name
    return types, samples


def _engine_backend():
    from automerge_tpu.parallel.engine import TPUDocPool
    from automerge_tpu.sidecar.server import SidecarBackend
    return SidecarBackend(pool=TPUDocPool())


def test_metrics_request_answers_valid_exposition():
    telemetry.enable()
    backend = _engine_backend()
    resp = backend.handle({'id': 1, 'cmd': 'apply_changes', 'doc': 'd',
                           'changes': [CH]})
    assert 'result' in resp
    out = backend.handle({'id': 2, 'cmd': 'metrics'})
    assert out['id'] == 2
    body = out['result']['body']
    assert 'text/plain' in out['result']['contentType']
    types, samples = parse_exposition(body)

    # acceptance criteria: batch-latency histogram, per-phase occupancy,
    # op/doc counters, oracle-fallback counters
    assert types['amtpu_batch_latency_seconds'] == 'histogram'
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert any('pool="engine"' in l for l, _ in
               by_name['amtpu_batch_latency_seconds_bucket'])
    assert float(dict(by_name['amtpu_ops_total'])['']) >= 1
    assert float(dict(by_name['amtpu_docs_total'])['']) >= 1
    assert any('phase="engine.kernels"' in l for l, _ in
               by_name['amtpu_phase_seconds_total'])
    assert any('reason="overflow_batches"' in l for l, _ in
               by_name['amtpu_fallback_total'])
    assert any('cmd="apply_changes"' in l for l, _ in
               by_name['amtpu_sidecar_requests_total'])

    # histogram invariants: buckets cumulative-monotonic, +Inf == _count
    eng = [(l, float(v)) for l, v in
           by_name['amtpu_batch_latency_seconds_bucket']
           if 'pool="engine"' in l]
    counts = [v for _, v in eng]
    assert counts == sorted(counts)
    inf = [v for l, v in eng if 'le="+Inf"' in l]
    count = [float(v) for l, v in
             by_name['amtpu_batch_latency_seconds_count']
             if 'pool="engine"' in l]
    assert inf == count and count[0] >= 1


def test_healthz_command():
    backend = _engine_backend()
    out = backend.handle({'id': 1, 'cmd': 'healthz'})
    assert out['result']['ok'] is True
    assert 'uptime_s' in out['result']
    # unknown commands still error (the new cmds didn't loosen dispatch)
    assert 'error' in backend.handle({'id': 2, 'cmd': 'frobnicate'})
    reqs = telemetry.SIDECAR_REQS.snapshot()
    assert reqs.get('healthz,ok') == 1
    assert reqs.get('unknown,error') == 1


def test_http_listener_serves_metrics_and_healthz():
    from automerge_tpu.telemetry.httpd import start_metrics_server
    telemetry.enable()
    with telemetry.span('engine.batch'):
        pass
    server = start_metrics_server(0)
    try:
        base = 'http://127.0.0.1:%d' % server.server_port
        with urllib.request.urlopen(base + '/metrics', timeout=10) as r:
            assert r.status == 200
            assert 'text/plain' in r.headers['Content-Type']
            types, _ = parse_exposition(r.read().decode())
            assert 'amtpu_up' in types
        with urllib.request.urlopen(base + '/healthz', timeout=10) as r:
            assert r.status == 200
            assert json.load(r)['ok'] is True
        try:
            urllib.request.urlopen(base + '/nope', timeout=10)
            assert False, 'expected 404'
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# full subprocess round trip: the deployment shape a scraper sees
# ---------------------------------------------------------------------------

def test_sidecar_subprocess_metrics_round_trip():
    from automerge_tpu.sidecar.client import SidecarClient
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server', '--trace'],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, cwd=REPO)
    with SidecarClient(proc=proc) as c:
        c.apply_changes('doc1', [CH])
        assert c.healthz()['ok'] is True
        out = c.metrics()
        types, samples = parse_exposition(out['body'])
        assert types['amtpu_batch_latency_seconds'] == 'histogram'
        names = {n for n, _, _ in samples}
        assert 'amtpu_sidecar_requests_total' in names
        assert 'amtpu_fallback_total' in names
        assert 'amtpu_phase_seconds_total' in names   # --trace enabled it


# ---------------------------------------------------------------------------
# bench embedding
# ---------------------------------------------------------------------------

def test_bench_block_shape():
    telemetry.enable()
    backend = _engine_backend()
    backend.handle({'id': 1, 'cmd': 'apply_changes', 'doc': 'd',
                    'changes': [CH]})
    telemetry.metric('fallback.overflow_batches', 2)
    block = telemetry.bench_block()
    # every KNOWN reason is pre-seeded at 0 (the fallback-check gate
    # reads presence, not just values); observed counters overlay
    assert block['fallbacks']['overflow_batches'] == 2
    for reason in telemetry.KNOWN_FALLBACK_REASONS:
        assert reason in block['fallbacks'], reason
    assert block['fallbacks']['oracle'] == 0
    # the scheduler block is pre-seeded the same way (serve-check and
    # dashboards read explicit zeros before the first gateway request)
    for key in telemetry.KNOWN_SCHEDULER_KEYS:
        assert block['scheduler'][key] == 0, key
    assert block['batch_latency']['engine']['count'] == 1
    assert block['ops_total'] >= 1 and block['docs_total'] >= 1
    assert 'engine.kernels' in block['phases']
    json.dumps(block)    # must be JSON-serializable as-is
