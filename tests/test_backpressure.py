"""Subscriber-scale backpressure (ISSUE 13): bounded egress queues,
drop-to-resubscribe degradation, and stampede-proof reconnect.

Lanes:
  * EgressQueue unit behaviour: tier-1 event shedding (responses
    survive), tier-2 overflow escalation, tier-3 wedge eviction, and
    the `fanout.write` / `fanout.stall` fault sites.
  * Clock-regression shed parity: a peer whose staged frames are shed
    converges byte-identically to a never-shed twin (no dup, no gap).
  * Reconnect-mid-backfill: a peer dropped to resubscribe while its
    straggler delta was still queued converges byte-identically after
    re-subscribing at its received clock.
  * Encode batching across the straggler/backfill paths, wildcard and
    doc-set subscriptions, live-gateway resync + client
    auto-resubscribe, wedged-consumer isolation, and stampede
    admission control with jittered retryAfterMs.
"""

import json
import os
import socket
import time

import pytest

from automerge_tpu import faults, telemetry
from automerge_tpu.native import NativeDocPool
from automerge_tpu.scheduler.egress import EgressQueue
from automerge_tpu.sync.fanout import FanoutEngine

ROOT = '00000000-0000-0000-0000-000000000000'
DOC = 'bp-doc'


@pytest.fixture(autouse=True)
def _hygiene():
    yield
    faults.reset('')
    telemetry.reset_all()


def ch(actor, seq, key, value, deps=None):
    return {'actor': actor, 'seq': seq, 'deps': dict(deps or {}),
            'ops': [{'action': 'set', 'obj': ROOT, 'key': key,
                     'value': value}]}


def canon(changes):
    return json.dumps(changes, sort_keys=True, default=str)


def _pair(sndbuf=None):
    a, b = socket.socketpair()
    if sndbuf is not None:
        try:
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
        except OSError:
            pass
    return a, b


def _drain(sock, timeout=5.0):
    """Reads whatever arrives on `sock` until quiet; returns bytes."""
    sock.settimeout(0.2)
    out = b''
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            break
        if not chunk:
            break
        out += chunk
    return out


# ---------------------------------------------------------------------------
# EgressQueue unit lanes
# ---------------------------------------------------------------------------

def test_egress_shed_drops_events_keeps_responses():
    """Tier 1: overflow drops queued EVENT frames (their on_drop runs)
    while response frames survive and are eventually delivered."""
    a, b = _pair(sndbuf=4096)
    dead = []
    q = EgressQueue(a, max_bytes=4096, wedge_s=30.0, resync_sheds=99,
                    on_dead=dead.append)
    # a large response wedges the writer mid-frame (nobody reads yet),
    # so everything staged after it queues
    big = b'R' * 262144
    assert q.stage(big, kind='response')
    time.sleep(0.1)                      # writer is now blocked in send
    dropped = []
    q.stage(b'EVENT-1\n', kind='event',
            on_drop=lambda: dropped.append(1))
    q.stage(b'E' * 8192, kind='event',
            on_drop=lambda: dropped.append(2))  # crosses max_bytes
    q.stage(b'RESP-2\n', kind='response')
    deadline = time.time() + 5
    while len(dropped) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert sorted(dropped) == [1, 2], \
        'tier-1 shed did not drop the queued event frames'
    got = _drain(b)
    assert got.startswith(b'R' * 1024)
    assert b'RESP-2' in got, 'response frame was shed'
    assert b'EVENT-1' not in got, 'shed event frame still delivered'
    assert not dead
    snap = telemetry.metrics_snapshot()
    assert snap.get('egress.sheds', 0) >= 1
    assert snap.get('egress.shed_frames', 0) >= 2
    q.close()
    a.close()
    b.close()


def test_egress_tier2_overflow_escalation_fires_once():
    """Repeated sheds without a drain escalate to on_overflow exactly
    once; a full drain re-arms the escalation."""
    a, b = _pair(sndbuf=4096)
    slow = []
    q = EgressQueue(a, max_bytes=2048, wedge_s=30.0, resync_sheds=2,
                    on_overflow=lambda _q: slow.append(1))
    q.stage(b'R' * 262144, kind='response')     # wedge the writer
    time.sleep(0.1)
    for _ in range(4):                          # 4 sheds, 1 escalation
        q.stage(b'E' * 4096, kind='event')
        time.sleep(0.01)
    deadline = time.time() + 5
    while not slow and time.time() < deadline:
        time.sleep(0.01)
    assert slow == [1], 'tier-2 escalation must fire exactly once'
    assert q.stats()['sheds'] >= 2
    _drain(b)                                   # let the writer drain
    deadline = time.time() + 5
    while q.stats()['queued_frames'] and time.time() < deadline:
        _drain(b, timeout=0.5)
    assert q.stats()['sheds'] == 0, 'a full drain resets escalation'
    q.close()
    a.close()
    b.close()


def test_egress_wedge_eviction():
    """Tier 3: a consumer that accepts no bytes for the wedge deadline
    is declared dead -- without any thread ever blocking on it."""
    a, b = _pair(sndbuf=4096)
    dead = []
    q = EgressQueue(a, max_bytes=1 << 20, wedge_s=0.4, resync_sheds=99,
                    on_dead=dead.append)
    q.stage(b'X' * 524288, kind='response')     # nobody ever reads b
    deadline = time.time() + 10
    while not dead and time.time() < deadline:
        time.sleep(0.02)
    assert dead == ['wedge']
    snap = telemetry.metrics_snapshot()
    assert snap.get('egress.wedge_evictions', 0) == 1
    q.close()
    a.close()
    b.close()


def test_fault_site_fanout_write_kills_transport():
    a, b = _pair()
    dead = []
    q = EgressQueue(a, wedge_s=30.0, on_dead=dead.append)
    faults.arm('fanout.write', 'permanent', 1.0)
    try:
        q.stage(b'hello\n', kind='response')
        deadline = time.time() + 5
        while not dead and time.time() < deadline:
            time.sleep(0.01)
    finally:
        faults.disarm()
    assert dead == ['error']
    snap = telemetry.metrics_snapshot()
    assert snap.get('egress.write_errors', 0) >= 1
    assert snap.get('resilience.fault_injected.fanout.write', 0) >= 1
    q.close()
    a.close()
    b.close()


def test_fault_site_fanout_stall_drives_wedge_eviction():
    """An armed permanent stall makes the writer progress-free, so the
    tier-3 eviction fires deterministically even though the peer's
    socket is perfectly healthy."""
    a, b = _pair()
    dead = []
    q = EgressQueue(a, wedge_s=0.3, on_dead=dead.append)
    faults.arm('fanout.stall', 'permanent', 1.0)
    try:
        q.stage(b'hello\n', kind='response')
        deadline = time.time() + 10
        while not dead and time.time() < deadline:
            time.sleep(0.02)
    finally:
        faults.disarm()
    assert dead == ['wedge']
    # a transient stall (bounded count) clears and the frame delivers
    a2, b2 = _pair()
    q2 = EgressQueue(a2, wedge_s=5.0)
    faults.arm('fanout.stall', 'transient', 1.0, count=2)
    try:
        q2.stage(b'after-stall\n', kind='response')
        got, deadline = b'', time.time() + 10
        while b'after-stall' not in got and time.time() < deadline:
            got += _drain(b2, timeout=1.0)
        assert b'after-stall' in got
    finally:
        faults.disarm()
    for s in (q, q2):
        s.close()
    for s in (a, b, a2, b2):
        s.close()


def test_oversized_event_frame_is_exempt_not_self_shed():
    """A single event frame larger than the whole bound staged into an
    otherwise-empty queue must DELIVER (the bound limits backlog, not
    frame size) -- shedding it would regress, re-stage the same
    oversized straggler delta, and starve a healthy peer forever."""
    a, b = _pair()
    dropped = []
    q = EgressQueue(a, max_bytes=1024, wedge_s=10.0, resync_sheds=99)
    q.stage(b'J' * 8192, kind='event',
            on_drop=lambda: dropped.append(1))
    got, deadline = b'', time.time() + 10
    while len(got) < 8192 and time.time() < deadline:
        got += _drain(b, timeout=1.0)
    assert len(got) == 8192 and not dropped, \
        'oversized lone event frame was shed instead of delivered'
    q.close()
    a.close()
    b.close()


def test_unsheddable_backlog_hard_cap_evicts():
    """Response frames are never shed, but a consumer accumulating an
    unsheddable backlog past 4x the bound is evicted -- a trickling
    reader defeats the wedge clock, so growth must not be unbounded.
    A SINGLE oversized response (a big backfill) stays exempt."""
    a, b = _pair(sndbuf=4096)
    dead = []
    q = EgressQueue(a, max_bytes=2048, wedge_s=30.0, resync_sheds=99,
                    on_dead=dead.append)
    # one big response alone: over the hard cap but a single frame --
    # exempt, the writer starts delivering it
    assert q.stage(b'R' * 262144, kind='response')
    assert not dead
    time.sleep(0.1)                     # writer wedges mid-frame
    # more unsheddable frames pile up past 4x the bound -> eviction
    ok = True
    for _ in range(8):
        ok = q.stage(b'S' * 2048, kind='response')
        if not ok:
            break
    deadline = time.time() + 5
    while not dead and time.time() < deadline:
        time.sleep(0.01)
    assert dead == ['overflow']
    assert not ok, 'stage() must refuse after the overflow eviction'
    snap = telemetry.metrics_snapshot()
    assert snap.get('egress.overflow_evictions', 0) == 1
    q.close()
    a.close()
    b.close()


def test_disarmed_cost_is_one_attr_read():
    """The standard disarmed-cost contract: with nothing armed the
    writer's fault hook is a single `faults.ARMED` check -- fire() is
    never entered (monkeypatching it would otherwise be visible)."""
    assert not faults.ARMED
    called = []
    orig = faults.fire
    faults.fire = lambda *a, **k: called.append(a)
    try:
        a, b = _pair()
        q = EgressQueue(a, wedge_s=5.0)
        q.stage(b'ping\n', kind='response')
        assert b'ping' in _drain(b)
        q.close()
        a.close()
        b.close()
    finally:
        faults.fire = orig
    assert not called


# ---------------------------------------------------------------------------
# engine-level: clock regression, resync, encode batching, wildcards
# ---------------------------------------------------------------------------

class FakeEgress(object):
    """Egress-shaped transport the engine stages into: frames deliver
    (on_write) or shed (on_drop) under test control, synchronously."""

    def __init__(self):
        self.delivered = []
        self.drop_next = 0

    def stage(self, buf, kind='event', on_write=None, on_drop=None):
        if kind == 'event' and self.drop_next > 0:
            self.drop_next -= 1
            if on_drop is not None:
                on_drop()
            return True
        self.delivered.append(buf)
        if on_write is not None:
            on_write()
        return True

    def changes(self):
        out = []
        for buf in self.delivered:
            for line in buf.decode().splitlines():
                frame = json.loads(line)
                if frame.get('event') == 'change':
                    out.extend(frame['changes'])
        return out


class Harness(object):
    def __init__(self):
        self.pool = NativeDocPool()
        self.engine = FanoutEngine(
            self.pool, lambda obj: (json.dumps(obj) + '\n').encode())

    def flush(self, batch, doc=DOC):
        res = self.pool.apply_changes(doc, batch)
        self.engine.on_flush({doc: res['clock']},
                             enq={doc: time.perf_counter()})
        return res


def test_clock_regression_shed_parity_vs_never_shed_twin():
    """A peer whose flush frame is shed regresses to its acked clock,
    is healed as a straggler next flush, and its total received change
    stream is byte-identical to a twin that never shed."""
    shed, clean = Harness(), Harness()
    t_shed, t_clean = FakeEgress(), FakeEgress()
    shed.engine.subscribe((1, 'p'), DOC, {}, t_shed)
    clean.engine.subscribe((1, 'p'), DOC, {}, t_clean)
    batches = [[ch('a', 1, 'k', 1)], [ch('a', 2, 'k', 2)],
               [ch('b', 1, 'j', 3)]]
    for i, batch in enumerate(batches):
        if i == 1:
            t_shed.drop_next = 1          # tier-1 sheds this flush
        shed.flush(batch)
        clean.flush(batch)
    assert canon(t_shed.changes()) == canon(t_clean.changes()), \
        'shed peer diverged from never-shed twin (dup or gap)'
    assert len(t_shed.delivered) == len(t_clean.delivered) - 1, \
        'the healing flush must carry the lost delta in ONE frame'
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.regressed_peers', 0) >= 1
    assert snap.get('sync.fanout.straggler_peers', 0) >= 1


def test_reconnect_mid_backfill_resync_converges_no_dup_no_gap():
    """Drop-to-resubscribe while the peer's straggler delta is still
    queued: the shed drops the queued delta (regression), resync frees
    the rows, and a re-subscribe at the peer's RECEIVED clock closes
    the gap byte-identically."""
    h = Harness()
    t = FakeEgress()
    h.engine.subscribe((7, 'p'), DOC, {}, t)
    h.flush([ch('a', 1, 'k', 1)])             # delivered
    t.drop_next = 2
    h.flush([ch('a', 2, 'k', 2)])             # shed (coalesced frame)
    h.flush([ch('a', 3, 'k', 3)])             # shed (straggler delta)
    docs = h.engine.resync_conn(7)            # tier 2
    assert docs == [DOC]
    assert h.engine.healthz_section()['live_subscriptions'] == 0
    # the client reconnects at the clock of what it actually received
    received = t.changes()
    assert [(c['actor'], c['seq']) for c in received] == [('a', 1)]
    back = h.engine.subscribe((8, 'p'), DOC, {'a': 1}, t)
    h.flush([ch('a', 4, 'k', 4)])             # and life goes on
    total = received + back['changes'] + t.changes()[len(received):]
    seen = [(c['actor'], c['seq']) for c in total]
    assert seen == [('a', 1), ('a', 2), ('a', 3), ('a', 4)], \
        'resync + backfill left a dup or a gap: %r' % (seen,)


def test_straggler_encodes_batch_across_shared_clock():
    h = Harness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1), ch('a', 2, 'k', 2)])
    transports = [FakeEgress() for _ in range(3)]
    for i, t in enumerate(transports):
        h.engine.subscribe((i, 'p'), DOC, {'a': 1}, t, backfill=False)
    telemetry.metrics_reset()
    h.flush([ch('b', 1, 'j', 9)])
    bufs = {t.delivered[-1] for t in transports}
    assert len(bufs) == 1, \
        'stragglers at one clock must share ONE encoding'
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.straggler_reuse', 0) == 2
    assert snap.get('sync.fanout.straggler_peers', 0) == 3


def test_backfill_memo_reuses_and_invalidates():
    h = Harness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1)])
    t = FakeEgress()
    telemetry.metrics_reset()
    r1 = h.engine.subscribe((1, 'x'), DOC, {}, t)
    r2 = h.engine.subscribe((2, 'y'), DOC, {}, t)
    assert canon(r1['changes']) == canon(r2['changes'])
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.backfills', 0) == 1
    assert snap.get('sync.fanout.backfill_reuse', 0) == 1
    # a mutation invalidates the memo by value: the next subscriber at
    # the same advertised clock gets the FULL fresh backfill
    h.flush([ch('a', 2, 'k', 2)])
    r3 = h.engine.subscribe((3, 'z'), DOC, {}, t)
    assert [(c['actor'], c['seq']) for c in r3['changes']] == \
        [('a', 1), ('a', 2)]


def test_docset_and_prefix_subscriptions():
    h = Harness()
    h.pool.apply_changes('ws/a', [ch('a', 1, 'k', 1)])
    t = FakeEgress()
    res = h.engine.subscribe_many((1, 'r'), ['ws/a', 'plain'], {}, t)
    assert set(res['docs']) == {'ws/a', 'plain'}
    assert [(c['actor'], c['seq'])
            for c in res['docs']['ws/a']['changes']] == [('a', 1)]
    pre = h.engine.subscribe_prefix((2, 'w'), 'ws/', FakeEgress())
    assert pre['prefix'] == 'ws/'
    assert set(pre['docs']) == {'ws/a'}       # known doc attached now
    # a NEW doc under the prefix auto-attaches on its first flush and
    # ships its complete history through the straggler filter
    wt = h.engine._peer_send[(2, 'w')]
    res = h.pool.apply_changes('ws/new', [ch('n', 1, 'k', 7)])
    h.engine.on_flush({'ws/new': res['clock']})
    assert [(c['actor'], c['seq']) for c in wt.changes()] == [('n', 1)]
    # ...and a non-matching doc does not
    res = h.pool.apply_changes('other', [ch('o', 1, 'k', 8)])
    h.engine.on_flush({'other': res['clock']})
    assert len(wt.changes()) == 1
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.prefix_attaches', 0) == 1
    # prefix unsubscribe retires the registration and its rows
    h.engine.unsubscribe_prefix((2, 'w'), 'ws/')
    res = h.pool.apply_changes('ws/more', [ch('m', 1, 'k', 9)])
    h.engine.on_flush({'ws/more': res['clock']})
    assert len(wt.changes()) == 1


def test_row_reuse_guard_on_stale_completion():
    """A write/drop completion that lands after its subscription row
    was freed (and possibly reallocated) must not touch the new
    tenant's clocks."""
    h = Harness()

    class HoldingEgress(FakeEgress):
        def __init__(self):
            super().__init__()
            self.held = []

        def stage(self, buf, kind='event', on_write=None, on_drop=None):
            self.held.append((buf, on_write, on_drop))
            return True

    t = HoldingEgress()
    h.engine.subscribe((1, 'old'), DOC, {}, t)
    h.flush([ch('a', 1, 'k', 1)])
    assert t.held
    h.engine.unsubscribe((1, 'old'))          # frees the row...
    t2 = FakeEgress()
    h.engine.subscribe((2, 'new'), DOC, {}, t2, backfill=False)
    # ...which the new subscriber now occupies at a zero clock
    for _buf, on_write, _on_drop in t.held:
        if on_write is not None:
            on_write()                        # stale completion
    h.flush([ch('a', 2, 'k', 2)])
    # the new tenant's clock was NOT advanced by the stale completion:
    # it still receives the full history as a straggler
    assert [(c['actor'], c['seq']) for c in t2.changes()] == \
        [('a', 1), ('a', 2)]


# ---------------------------------------------------------------------------
# live gateway lanes
# ---------------------------------------------------------------------------

def _gateway(tmp_path, env=None):
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.sidecar.server import SidecarBackend
    for k, v in (env or {}).items():
        os.environ[k] = v
    path = str(tmp_path / 'gw-bp.sock')
    gw = GatewayServer(path, backend=SidecarBackend()).start()
    return gw, path


def _cleanup(gw, env):
    gw.stop()
    for k in env:
        os.environ.pop(k, None)


def test_gateway_resync_and_client_auto_resubscribe(tmp_path):
    """Tier-2 end to end: the gateway drops a connection to
    resubscribe; SidecarClient sees the typed envelope, re-subscribes
    at its last-seen clock on its own, and keeps receiving deltas."""
    from automerge_tpu.sidecar.client import SidecarClient
    env = {'AMTPU_FLUSH_DEADLINE_MS': '5'}
    gw, path = _gateway(tmp_path, env)
    try:
        sub = SidecarClient(sock_path=path)
        w = SidecarClient(sock_path=path)
        w.apply_changes('rdoc', [ch('w', 1, 'k', 1)])
        r = sub.subscribe('rdoc', peer='alice')
        assert r['clock'] == {'w': 1}
        w.apply_changes('rdoc', [ch('w', 2, 'k', 2)])
        e = sub.next_event(timeout=30)
        assert e['event'] == 'change' and e['clock'] == {'w': 2}
        # force tier 2 on the subscriber's connection
        with gw._conns_lock:
            victim = [c for c in gw._conns.values()
                      if c.cid == 1][0]
        gw._conn_slow(victim)
        e = sub.next_event(timeout=30)
        assert e['event'] == 'resync' and e['docs'] == ['rdoc']
        assert isinstance(e.get('retryAfterMs'), int)
        # the client re-subscribes by itself (at {'w': 2}, so the
        # backfill is empty -- no synthetic event) and the next flush
        # reaches it again
        deadline = time.time() + 30
        while time.time() < deadline:
            if gw.fanout.healthz_section()['live_subscriptions'] >= 2:
                break
            time.sleep(0.05)
        w.apply_changes('rdoc', [ch('w', 3, 'k', 3)])
        e = sub.next_event(timeout=30)
        assert e['event'] == 'change' and e['clock'] == {'w': 3}, e
        snap = telemetry.metrics_snapshot()
        assert snap.get('egress.resyncs', 0) >= 1
        assert snap.get('sidecar.client.resyncs', 0) >= 1
        assert snap.get('sidecar.client.resubscribes', 0) >= 1
        sub.close()
        w.close()
    finally:
        _cleanup(gw, env)


def test_wedged_consumer_does_not_stall_healthy_peers(tmp_path):
    """One subscriber stops reading entirely; 4 healthy subscribers
    must still receive every flush's delta (the dispatcher and the
    fan-out pass never block on the wedged socket), and the wedged
    consumer ends up shed + resynced or evicted."""
    from automerge_tpu.sidecar.client import SidecarClient
    env = {'AMTPU_FLUSH_DEADLINE_MS': '5',
           'AMTPU_EGRESS_MAX_BYTES': '32768',
           'AMTPU_EGRESS_WEDGE_S': '1.0',
           'AMTPU_EGRESS_RESYNC_SHEDS': '2'}
    gw, path = _gateway(tmp_path, env)
    try:
        wedge = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        wedge.connect(path)
        wedge.sendall((json.dumps(
            {'id': 1, 'cmd': 'subscribe', 'doc': 'wdoc',
             'peer': 'wedge'}) + '\n').encode())
        wedge.settimeout(10)
        assert b'"id": 1' in wedge.recv(65536)  # backfill answered
        # ...and never reads again
        healthy = []
        for i in range(4):
            c = SidecarClient(sock_path=path)
            c.subscribe('wdoc', peer='h%d' % i)
            healthy.append(c)
        w = SidecarClient(sock_path=path)
        rounds, blob = 24, 'x' * 8192
        for s in range(1, rounds + 1):
            w.apply_changes('wdoc', [ch('w', s, 'k', blob)])
        for i, c in enumerate(healthy):
            got = 0
            deadline = time.time() + 60
            while got < rounds and time.time() < deadline:
                e = c.next_event(timeout=max(
                    0.1, deadline - time.time()))
                if e is not None and e.get('event') == 'change':
                    got += len(e['changes'])
            assert got == rounds, \
                'healthy peer %d got %d/%d changes' % (i, got, rounds)
        deadline = time.time() + 30
        while time.time() < deadline:
            snap = telemetry.metrics_snapshot()
            if snap.get('egress.resyncs', 0) \
                    or snap.get('egress.wedge_evictions', 0):
                break
            time.sleep(0.1)
        snap = telemetry.metrics_snapshot()
        assert snap.get('egress.sheds', 0) >= 1, snap
        assert snap.get('egress.resyncs', 0) >= 1 \
            or snap.get('egress.wedge_evictions', 0) >= 1, snap
        for c in healthy:
            c.close()
        w.close()
        wedge.close()
    finally:
        _cleanup(gw, env)


def test_subscribe_stampede_sheds_with_jittered_retry(tmp_path):
    """Reconnect-stampede admission: past the queue watermark a
    subscribe answers the typed Overloaded envelope with a JITTERED
    retryAfterMs (>= the deterministic hint the queue computes)."""
    from automerge_tpu.errors import OverloadedError
    from automerge_tpu.sidecar.client import SidecarClient
    env = {'AMTPU_FLUSH_DEADLINE_MS': '400',
           'AMTPU_QUEUE_MAX_OPS': '1'}
    gw, path = _gateway(tmp_path, env)
    try:
        pump = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        pump.connect(path)
        # two queued mutations: the first admits, the second trips the
        # watermark so the queue is shedding when the subscribe lands
        for i in range(2):
            pump.sendall((json.dumps(
                {'id': i, 'cmd': 'apply_changes', 'doc': 'sdoc',
                 'changes': [ch('a', i + 1, 'k', i)]}) + '\n').encode())
        sub = SidecarClient(sock_path=path)
        base = gw.queue.retry_after_ms()
        hit = None
        for _ in range(50):
            try:
                sub.subscribe('sdoc', peer='late')
            except OverloadedError as e:
                hit = e
                break
            time.sleep(0.005)
        assert hit is not None, 'subscribe was never shed'
        assert hit.retry_after_ms >= base, \
            'jittered retryAfterMs below the deterministic hint'
        snap = telemetry.metrics_snapshot()
        assert snap.get('sync.fanout.subscribe_shed', 0) >= 1
        # after the backlog drains, the same subscribe is admitted
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                r = sub.subscribe('sdoc', peer='late')
                break
            except OverloadedError as e:
                time.sleep(max(1, e.retry_after_ms) / 1000.0)
        assert r['clock'], r
        sub.close()
        pump.close()
    finally:
        _cleanup(gw, env)


def test_gateway_docset_and_prefix_over_the_wire(tmp_path):
    from automerge_tpu.sidecar.client import SidecarClient
    env = {'AMTPU_FLUSH_DEADLINE_MS': '5'}
    gw, path = _gateway(tmp_path, env)
    try:
        w = SidecarClient(sock_path=path)
        w.apply_changes('ws/a', [ch('a', 1, 'k', 1)])
        sub = SidecarClient(sock_path=path)
        r = sub.subscribe(docs=['ws/a', 'ws/b'], peer='router')
        assert set(r['docs']) == {'ws/a', 'ws/b'}
        assert [(c['actor'], c['seq'])
                for c in r['docs']['ws/a']['changes']] == [('a', 1)]
        pre = sub.subscribe(prefix='ws/', peer='router')
        assert pre['prefix'] == 'ws/'
        w.apply_changes('ws/new', [ch('n', 1, 'k', 2)])
        e = sub.next_event(timeout=30)
        assert e['event'] == 'change' and e['doc'] == 'ws/new'
        assert [(c['actor'], c['seq']) for c in e['changes']] == \
            [('n', 1)]
        r = sub.unsubscribe(prefix='ws/', peer='router')
        assert r['removed'] >= 1
        sub.close()
        w.close()
    finally:
        _cleanup(gw, env)
