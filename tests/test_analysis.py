"""Static-analysis engine lanes (ISSUE 8, docs/ANALYSIS.md).

Two-sided per checker: it must FIRE on its seeded-violation fixture
(tests/fixtures/analysis/) and stay SILENT on the real tree -- a
checker that cannot fire is dead weight, and one that fires on the
tree means the tree (or the spec) regressed.  Plus the runtime
sanitizer lane: AMTPU_SANITIZE=1 must be invisible while the
private-copy contract holds and must catch a deliberately re-opened
zero-copy alias (the PR-4/PR-6 class) as loud parity divergence.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, 'tests', 'fixtures', 'analysis')
sys.path.insert(0, REPO)

from automerge_tpu.analysis import run_checks  # noqa: E402
from automerge_tpu.analysis.env_spec import (  # noqa: E402
    ABI_LATCH_DEFAULTS, ENV_FLAGS, SPEC)


def _fixture(name):
    return os.path.join(FIXTURES, name)


def _codes(findings, path=None):
    return sorted({f.code for f in findings
                   if path is None or f.path == path})


# ---------------------------------------------------------------------------
# the tree itself must be clean (every checker, in one pass)
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    findings = run_checks(REPO)
    assert findings == [], '\n'.join(f.format(REPO) for f in findings)


# ---------------------------------------------------------------------------
# per-checker fixture lanes: must fire on the seed, only on the seed
# ---------------------------------------------------------------------------

def _run_fixture(checker, name):
    path = _fixture(name)
    findings = run_checks(REPO, checkers=[checker], extra_files=[path])
    on_fixture = [f for f in findings if f.path == path]
    off_fixture = [f for f in findings if f.path != path]
    assert off_fixture == [], '\n'.join(f.format(REPO)
                                        for f in off_fixture)
    return on_fixture


def test_env_checker_fires_on_fixture():
    hits = _run_fixture('env-latch', 'env_drift.py')
    codes = _codes(hits)
    assert 'direct-read' in codes, hits
    assert 'unknown-flag' in codes, hits
    assert 'default-drift' in codes, hits
    assert 'type-drift' in codes, hits


def test_telemetry_checker_fires_on_fixture():
    hits = _run_fixture('telemetry-key', 'telemetry_unseeded.py')
    codes = _codes(hits)
    assert 'unseeded-key' in codes, hits
    assert 'undeclared-dynamic-key' in codes, hits


def test_alias_checker_fires_on_fixture():
    hits = _run_fixture('dispatch-alias', 'alias_mutation.py')
    codes = _codes(hits)
    assert 'post-dispatch-mutation' in codes, hits
    assert 'tls-staging' in codes, hits
    assert 'loop-staging-reuse' in codes, hits
    # the clean arms (private copies, fresh per-iteration buffer) must
    # NOT be flagged
    text = open(_fixture('alias_mutation.py')).read().splitlines()
    for f in hits:
        assert 'NOT flagged' not in text[f.line - 1], f.format(REPO)
    # exactly the seeded sites fire: 3 mutations + 1 tls + 1 loop
    assert len(hits) == 5, '\n'.join(f.format(REPO) for f in hits)


def test_lock_checker_fires_on_fixture():
    hits = _run_fixture('lock-discipline', 'lock_unguarded.py')
    assert _codes(hits) == ['unguarded-access'], hits
    # exactly the two bad_* methods, nothing in ok_*
    assert len(hits) == 2, '\n'.join(f.format(REPO) for f in hits)


def test_suppression_comment_silences(tmp_path):
    src = ("import os\n"
           "def f():\n"
           "    return os.environ.get('AMTPU_RESIDENT')"
           "  # static-ok: env-latch\n")
    p = tmp_path / 'suppressed.py'
    p.write_text(src)
    findings = run_checks(REPO, checkers=['env-latch'],
                          extra_files=[str(p)])
    assert [f for f in findings if f.path == str(p)] == []


# ---------------------------------------------------------------------------
# env spec sanity: the ABI defaults the flip guard reads are the spec's
# ---------------------------------------------------------------------------

def test_env_spec_matches_latch_abi():
    import ctypes
    lib_path = os.path.join(REPO, 'automerge_tpu', 'native',
                            'libamtpu_core.so')
    if not os.path.exists(lib_path):
        pytest.skip('native library not built')
    out = (ctypes.c_int64 * 3)()
    ctypes.CDLL(lib_path).amtpu_latch_defaults(out)
    for i, name in enumerate(ABI_LATCH_DEFAULTS):
        assert int(out[i]) == SPEC[name].default, name


def test_env_spec_names_are_unique_and_sorted_types():
    assert len({f.name for f in ENV_FLAGS}) == len(ENV_FLAGS)
    for f in ENV_FLAGS:
        assert f.type in ('int', 'float', 'bool', 'str', 'raw',
                          'special'), f


# ---------------------------------------------------------------------------
# runtime alias sanitizer (AMTPU_SANITIZE=1)
# ---------------------------------------------------------------------------

# clock rows are keyed (doc, actor, seq), so docs x actors fresh rows
# append per round while the ACTOR population stays at 8 (well under
# AMTPU_RESCLK_MAX_ACTORS -- the cache must stay enabled).  The delta
# scatter's staging arrays must clear jax's synchronous-completion
# window for the alias to be observable: ~4096 rows measures 10/10
# corruption on this host, below ~1k the tiny kernel finishes before
# the poison lands (the bug class is exactly as timing-dependent in
# production, which is why the sanitizer exists).
BATCH_WORKLOAD = r"""
ROOT = '00000000-0000-0000-0000-000000000000'

def build_round(r, docs=512, actors=8):
    payload = {}
    for d in range(docs):
        chs = []
        for a in range(actors):
            ops = [{'action': 'set', 'obj': ROOT,
                    'key': 'shared%d' % (r % 3),
                    'value': 'a%d r%d' % (a, r)}]
            chs.append({'actor': 'w%d' % a, 'seq': r,
                        'deps': {}, 'ops': ops})
        payload['doc%d' % d] = chs
    return payload
"""

SANITIZE_LANE = r"""
import sys
sys.path.insert(0, REPO_PATH)
import os
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
from automerge_tpu.native import NativeDocPool
import automerge_tpu.native.batch_resident as br
from automerge_tpu.analysis import sanitize
WORKLOAD

def run_rounds():
    pool = NativeDocPool()
    for r in (1, 2, 3):
        pool.apply_batch(build_round(r))
    # a corrupted clock scatter skews conflict resolution across the
    # whole batch; a 64-doc sample is ample to observe divergence
    return [pool.get_patch('doc%d' % i) for i in range(64)]

# reference: sanitizer off, clean pipeline
ref = run_rounds()

# arm the sanitizer: with the private-copy contract intact the poison
# is invisible (jax only ever aliased buffers no caller sees)
os.environ['AMTPU_SANITIZE'] = '1'
assert sanitize.refresh()
clean = run_rounds()
assert clean == ref, 'sanitizer corrupted a CLEAN pipeline'
assert sanitize.poisoned_count() > 0, \
    'sanitizer never engaged (delta staging path not hit?)'

# deliberately re-open the PR-4/PR-6 alias: hand the scatter the RAW
# staging buffers (no private np.array copies).  The sanitizer's poison
# now scribbles over memory the async dispatch may still read -- the
# corruption the alias would cause in production becomes a loud,
# deterministic parity failure here.
import jax as _jax
def _aliasing(donate):
    def scatter(tab, idx, rows):
        return tab.at[idx].set(rows, mode='drop')
    jitted = _jax.jit(scatter)
    def run(tab, idx, rows):
        out = jitted(tab, idx, rows)        # raw buffers: may zero-copy
        sanitize.poison(idx, rows)
        return out
    return run
br._jit_row_scatter = _aliasing

caught = False
# the alias is only observable while the async dispatch still holds
# the raw buffers; on a saturated single-core host XLA sometimes
# completes inside the dispatch call itself and an attempt misses.
# Six independent attempts keep the detection power while pushing the
# all-miss flake rate into the noise (p_miss^6; measured ~10-20%
# all-miss at 3 attempts on a 1-core box)
for attempt in range(6):
    if run_rounds() != ref:
        caught = True
        break
assert caught, 'sanitizer failed to catch the deliberate alias'
print('SANITIZE-OK')
""".replace('WORKLOAD', BATCH_WORKLOAD)


def test_sanitizer_catches_deliberate_alias():
    """AMTPU_SANITIZE=1: invisible on the clean pipeline, loud on a
    deliberately re-opened zero-copy alias (the exact PR-4/PR-6
    staging-buffer class)."""
    script = SANITIZE_LANE.replace('REPO_PATH', repr(REPO))
    # kernel path (the scatter only exists there), no wave pipelining
    # (512 docs would otherwise split; the lane pins the single-batch
    # delta scatter), resilience off (corruption must surface, not heal)
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_HOST_FULL='0',
               AMTPU_PIPELINE_DEPTH='1', AMTPU_RESILIENCE='0')
    env.pop('AMTPU_SANITIZE', None)
    out = subprocess.run([sys.executable, '-c', script], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'SANITIZE-OK' in out.stdout
