"""Dedicated proxy-surface tests: the proxies inside change() must behave
like plain Python dicts/lists, mirroring the reference's expectation that
its ES proxies behave like plain JS objects/arrays
(reference: /root/reference/test/proxies_test.js, 459 LoC).
"""

import json

import pytest

import automerge_tpu as am
from automerge_tpu.errors import RangeError


def change(doc, fn):
    return am.change(doc, fn)


class TestMapProxy:
    def test_instanceof_like_shape(self):
        def cb(doc):
            assert doc._type == 'map'
            assert doc._objectId == '00000000-0000-0000-0000-000000000000'
        change(am.init(), cb)

    def test_getitem_and_attribute_access(self):
        def cb(doc):
            doc['key1'] = 'value1'
            assert doc['key1'] == 'value1'
            assert doc.key1 == 'value1'
        change(am.init(), cb)

    def test_unknown_key_returns_none(self):
        def cb(doc):
            assert doc.get('missing') is None
            assert doc.get('missing', 'dflt') == 'dflt'
        change(am.init(), cb)

    def test_underscore_attributes_raise(self):
        def cb(doc):
            with pytest.raises(AttributeError):
                doc._nonexistent_private
        change(am.init(), cb)

    def test_in_operator(self):
        def cb(doc):
            doc['key1'] = 'value1'
            assert 'key1' in doc
            assert 'key2' not in doc
        change(am.init(), cb)

    def test_keys_values_items_iteration(self):
        def cb(doc):
            doc['key1'] = 'v1'
            doc['key2'] = 'v2'
            assert doc.keys() == ['key1', 'key2']
            assert doc.values() == ['v1', 'v2']
            assert doc.items() == [('key1', 'v1'), ('key2', 'v2')]
            assert list(iter(doc)) == ['key1', 'key2']
            assert len(doc) == 2
        change(am.init(), cb)

    def test_set_del_attribute_style(self):
        def cb(doc):
            doc.key1 = 'value1'
            assert doc['key1'] == 'value1'
            del doc.key1
            assert 'key1' not in doc

        def cb2(doc):
            doc['key2'] = 'value2'
            del doc['key2']
            assert doc.get('key2') is None
        change(am.init(), cb)
        change(am.init(), cb2)

    def test_update_bulk_assign(self):
        d = change(am.init(), lambda doc: doc.update(
            {'a': 1, 'b': 'two', 'c': None}))
        assert d['a'] == 1 and d['b'] == 'two' and d['c'] is None

    def test_nested_object_creation_returns_proxy(self):
        def cb(doc):
            doc['nested'] = {'deep': {'leaf': 7}}
            assert doc['nested']._type == 'map'
            assert doc['nested']['deep']['leaf'] == 7
            doc['nested']['deep']['leaf'] = 8
            assert doc['nested']['deep']['leaf'] == 8
        d = change(am.init(), cb)
        assert d['nested']['deep']['leaf'] == 8

    def test_json_round_trip_of_materialized_doc(self):
        d = change(am.init(), lambda doc: doc.update(
            {'s': 'x', 'n': 3, 'list': [1, 2, {'k': 'v'}]}))
        # the frozen materialized doc serializes like plain data
        as_json = json.loads(json.dumps(
            {'s': d['s'], 'n': d['n'],
             'list': [d['list'][0], d['list'][1], dict(d['list'][2])]}))
        assert as_json == {'s': 'x', 'n': 3, 'list': [1, 2, {'k': 'v'}]}

    def test_overwrite_and_delete_missing_is_noop_like(self):
        def cb(doc):
            doc['k'] = 1
            doc['k'] = 2
            assert doc['k'] == 2
        change(am.init(), cb)


class TestListProxy:
    def make(self, items=('a', 'b', 'c')):
        return change(am.init(), lambda doc: doc.__setitem__(
            'list', list(items)))

    def test_type_and_object_id(self):
        def cb(doc):
            doc['list'] = [1]
            assert doc['list']._type == 'list'
            assert isinstance(doc['list']._objectId, str)
        change(am.init(), cb)

    def test_getitem_len_iter_contains(self):
        def cb(doc):
            lst = doc['list']
            assert lst[0] == 'a' and lst[2] == 'c'
            assert len(lst) == 3 and lst.length == 3
            assert list(lst) == ['a', 'b', 'c']
            assert 'b' in lst and 'z' not in lst
        change(self.make(), cb)

    def test_slice_and_negative_free_indexing(self):
        def cb(doc):
            lst = doc['list']
            assert lst[0:2] == ['a', 'b']
            assert lst.slice(1) == ['b', 'c']
            assert lst.slice(0, 2) == ['a', 'b']
        change(self.make(), cb)

    def test_string_indexes_accepted(self):
        def cb(doc):
            assert doc['list']['1'] == 'b'
            doc['list']['1'] = 'B'
            assert doc['list'][1] == 'B'
        change(self.make(), cb)

    def test_bad_indexes_raise(self):
        def cb(doc):
            with pytest.raises(TypeError):
                doc['list'][1.5]
            with pytest.raises(RangeError):
                doc['list'][-1]
            with pytest.raises(TypeError):
                doc['list'][True]
        change(self.make(), cb)

    def test_read_only_helpers(self):
        def cb(doc):
            lst = doc['list']
            assert lst.index_of('b') == 1
            assert lst.index_of('zz') == -1
            assert lst.includes('c') and not lst.includes('q')
            assert lst.join('-') == 'a-b-c'
            assert lst.map(str.upper) == ['A', 'B', 'C']
            assert lst.filter(lambda v: v != 'b') == ['a', 'c']
        change(self.make(), cb)

    def test_setitem_delitem(self):
        d = change(self.make(), lambda doc: doc['list'].__setitem__(1, 'B'))
        assert list(d['list']) == ['a', 'B', 'c']
        d = change(d, lambda doc: doc['list'].__delitem__(0))
        assert list(d['list']) == ['B', 'c']

    def test_delete_at_multi(self):
        d = change(self.make('abcdef'),
                   lambda doc: doc['list'].delete_at(1, 3))
        assert list(d['list']) == ['a', 'e', 'f']

    def test_insert_at_and_insert(self):
        d = change(self.make(), lambda doc: doc['list'].insert_at(1, 'x', 'y'))
        assert list(d['list']) == ['a', 'x', 'y', 'b', 'c']
        d = change(d, lambda doc: doc['list'].insert(0, 'z'))
        assert list(d['list']) == ['z', 'a', 'x', 'y', 'b', 'c']

    def test_push_append_extend(self):
        def cb(doc):
            doc['list'].push('d', 'e')
            doc['list'].append('f')
            doc['list'].extend(['g', 'h'])
        d = change(self.make(), cb)
        assert list(d['list']) == list('abcdefgh')

    def test_pop_and_shift_return_values(self):
        def cb(doc):
            assert doc['list'].pop() == 'c'
            assert doc['list'].shift() == 'a'
            assert list(doc['list']) == ['b']
        change(self.make(), cb)

    def test_pop_empty_returns_none(self):
        def cb(doc):
            doc['empty'] = []
            assert doc['empty'].pop() is None
            assert doc['empty'].shift() is None
        change(am.init(), cb)

    def test_unshift(self):
        d = change(self.make(), lambda doc: doc['list'].unshift('x', 'y'))
        assert list(d['list']) == ['x', 'y', 'a', 'b', 'c']

    def test_splice_returns_deleted(self):
        def cb(doc):
            deleted = doc['list'].splice(1, 2, 'X')
            assert deleted == ['b', 'c']
            assert list(doc['list']) == ['a', 'X']
        change(self.make(), cb)

    def test_splice_default_deletes_to_end(self):
        def cb(doc):
            deleted = doc['list'].splice(1)
            assert deleted == ['b', 'c']
            assert list(doc['list']) == ['a']
        change(self.make(), cb)

    def test_fill(self):
        d = change(self.make('abcde'),
                   lambda doc: doc['list'].fill('z', 1, 4))
        assert list(d['list']) == ['a', 'z', 'z', 'z', 'e']
        d = change(d, lambda doc: doc['list'].fill('q'))
        assert list(d['list']) == ['q'] * 5

    def test_nested_objects_in_lists(self):
        def cb(doc):
            doc['list'] = [{'k': 1}, [2, 3]]
            assert doc['list'][0]._type == 'map'
            assert doc['list'][0]['k'] == 1
            assert doc['list'][1]._type == 'list'
            doc['list'][0]['k'] = 9
        d = change(am.init(), cb)
        assert d['list'][0]['k'] == 9
        assert list(d['list'][1]) == [2, 3]

    def test_mutations_persist_across_changes(self):
        d = self.make()
        d = change(d, lambda doc: doc['list'].push('d'))
        d = change(d, lambda doc: doc['list'].delete_at(0))
        assert list(d['list']) == ['b', 'c', 'd']

    def test_camelcase_aliases(self):
        def cb(doc):
            lst = doc['list']
            assert lst.indexOf('b') == 1
            lst.insertAt(0, 'z')
            lst.deleteAt(0)
            assert list(lst) == ['a', 'b', 'c']
        change(self.make(), cb)
