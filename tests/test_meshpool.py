"""Pool-level mesh execution lanes (ISSUE 7): `MeshDocPool` must be a
drop-in for `NativeDocPool` -- byte-identical patches across dp widths
on real workloads, resilience pass-through at per-doc granularity, the
AMTPU_MESH latch guard, and the sp-axis fence's routing policy.

The suite process runs on 8 virtual CPU devices (conftest), so dp
placement is real multi-device; the latch lane runs in a subprocess
because the latch-at-first-batch snapshot is process-global.
"""

import os
import subprocess
import sys

import msgpack
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from automerge_tpu import faults, resilience, telemetry  # noqa: E402
from automerge_tpu.native import NativeDocPool, make_pool  # noqa: E402
from automerge_tpu.native.mesh_pool import MeshDocPool  # noqa: E402
from automerge_tpu.parallel import mesh_encode  # noqa: E402

ROOT = '00000000-0000-0000-0000-000000000000'


def _real_workload(n_docs=24):
    """Mixed real wire-format changes: long-ish text histories plus
    map- and table-shaped docs -- the three demo classes the mesh
    encoder/tests already pin against the pool."""
    docs = dict(mesh_encode.demo_text_workload(n_docs // 2))
    for d, chs in mesh_encode.demo_map_workload(n_docs // 4).items():
        docs['m-%d' % d] = chs
    for d, chs in mesh_encode.demo_table_workload(n_docs // 4).items():
        docs['tb-%d' % d] = chs
    return {NativeDocPool._doc_key(str(d)): chs for d, chs in docs.items()}


def _payload(docs):
    return msgpack.packb(docs, use_bin_type=True)


def _per_doc(raw):
    out = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    return {d: msgpack.packb(p, use_bin_type=True) for d, p in out.items()}


@pytest.fixture(scope='module')
def workload_and_reference():
    docs = _real_workload()
    payload = _payload(docs)
    want = _per_doc(NativeDocPool().apply_batch_bytes(payload))
    return docs, payload, want


@pytest.mark.parametrize('dp', [1, 2, 4])
def test_mesh_pool_byte_parity_across_dp(dp, workload_and_reference):
    docs, payload, want = workload_and_reference
    pool = MeshDocPool(dp=dp)
    got = _per_doc(pool.apply_batch_bytes(payload))
    assert set(got) == set(want)
    bad = [d for d in want if got[d] != want[d]]
    assert not bad, 'dp=%d lost byte parity on %r' % (dp, bad[:3])
    # per-doc queries route to the owning chip and agree with the
    # single-device pool
    ref = NativeDocPool()
    ref.apply_batch_bytes(payload)
    for d in list(docs)[:4]:
        assert pool.get_patch(d) == ref.get_patch(d)
        assert pool.get_clock(d) == ref.get_clock(d)


def test_mesh_pool_places_chips_on_distinct_devices():
    import jax
    if len(jax.devices()) < 4:
        pytest.skip('needs 4 devices')
    pool = MeshDocPool(dp=4)
    devs = [p.device for p in pool.pools]
    assert len(set(devs)) == 4, devs


def test_mesh_pool_serves_kernel_path_with_zero_oracle(
        workload_and_reference):
    _docs, payload, _want = workload_and_reference
    telemetry.metrics_reset()
    MeshDocPool(dp=2).apply_batch_bytes(payload)
    snap = telemetry.metrics_snapshot()
    assert snap.get('mesh.batches', 0) >= 1, snap
    assert snap.get('mesh.shards', 0) >= 2, snap
    assert snap.get('fallback.oracle', 0) == 0, snap


def test_mesh_pool_poison_doc_quarantines_only_that_doc(
        workload_and_reference):
    docs, payload, want = workload_and_reference
    poison = sorted(docs)[len(docs) // 2]
    telemetry.metrics_reset()
    faults.arm('device.dispatch', 'permanent', 1.0, match=poison)
    try:
        got = _per_doc(MeshDocPool(dp=4).apply_batch_bytes_resilient(
            payload))
    finally:
        faults.disarm()
    snap = telemetry.metrics_snapshot()
    quarantined = [
        d for d in got
        if resilience.is_quarantined(
            msgpack.unpackb(got[d], raw=False, strict_map_key=False))]
    assert quarantined == [poison], quarantined
    assert snap.get('resilience.quarantined', 0) == 1, snap
    bad = [d for d in want if d != poison and got[d] != want[d]]
    assert not bad, 'healthy docs lost parity under poison: %r' % bad[:3]


def test_make_pool_factory_honors_amtpu_mesh(monkeypatch):
    monkeypatch.setenv('AMTPU_MESH', '2')
    pool = make_pool()
    assert isinstance(pool, MeshDocPool)
    assert (pool.dp, pool.sp) == (2, 1)
    monkeypatch.setenv('AMTPU_MESH', '2,4')
    pool = make_pool()
    assert (pool.dp, pool.sp) == (2, 4)
    monkeypatch.delenv('AMTPU_MESH')
    assert type(make_pool()) is NativeDocPool
    monkeypatch.setenv('AMTPU_MESH', '0')
    assert type(make_pool()) is NativeDocPool
    monkeypatch.setenv('AMTPU_MESH', 'banana')
    with pytest.raises(ValueError):
        make_pool()


def test_sp_fence_routing_policy(monkeypatch):
    """The sp-axis triage (ISSUE 7 satellite): sp sharding routes only
    past the measured long-list crossover, never onto devices the dp
    axis owns, and never on a malformed topology."""
    import jax

    from automerge_tpu.native import resident
    if len(jax.devices()) < 2:
        pytest.skip('needs 2 devices')
    crossover = resident.SP_CROSSOVER_ELEMS
    monkeypatch.delenv('AMTPU_MESH', raising=False)
    monkeypatch.delenv('AMTPU_MESH_SP_MIN', raising=False)
    # legacy auto policy: long arenas shard, short ones are fenced;
    # only the dispatch-site call (count_fenced) records the counter
    assert resident._sp_sharding(crossover) is not None
    telemetry.metrics_reset()
    assert resident._sp_sharding(8192) is None
    assert telemetry.metrics_snapshot().get('mesh.sp_fenced', 0) == 0
    assert resident._sp_sharding(8192, count_fenced=True) is None
    assert telemetry.metrics_snapshot().get('mesh.sp_fenced', 0) == 1
    # dp-only mesh: every device is a dp chip -- sp never engages
    monkeypatch.setenv('AMTPU_MESH', '4')
    assert resident._sp_sharding(crossover) is None
    # explicit dp=1,sp topology: sharding over exactly sp devices
    monkeypatch.setenv('AMTPU_MESH', '1,2')
    sh = resident._sp_sharding(crossover)
    assert sh is not None and sh.mesh.size == 2
    # still fenced below the crossover even when opted in
    assert resident._sp_sharding(8192) is None
    # crossover override opens the short arena up
    monkeypatch.setenv('AMTPU_MESH_SP_MIN', '4096')
    assert resident._sp_sharding(8192) is not None
    # malformed topology never shards
    monkeypatch.setenv('AMTPU_MESH', 'dp=oops')
    assert resident._sp_sharding(crossover) is None


MESH_LATCH = r"""
import os, sys, warnings
sys.path.insert(0, REPO_PATH)
os.environ['JAX_PLATFORMS'] = 'cpu'
from automerge_tpu.utils.jaxenv import pin_cpu
pin_cpu(force=True)
from automerge_tpu import telemetry
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
pool = NativeDocPool()
pool.apply_changes('d', [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 'v'}]}])
os.environ['AMTPU_MESH'] = '4'            # after the first batch: latched
with warnings.catch_warnings(record=True) as w:
    warnings.simplefilter('always')
    pool.apply_changes('d', [{'actor': 'a', 'seq': 2, 'deps': {}, 'ops': [
        {'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 'w'}]}])
msgs = [str(x.message) for x in w if issubclass(x.category, RuntimeWarning)]
assert any('AMTPU_MESH' in m for m in msgs), msgs
snap = telemetry.metrics_snapshot()
assert snap.get('mesh.latch_flip_ignored', 0) >= 1, snap
# warned once per (key, value): a repeat flip stays counted, not re-warned
with warnings.catch_warnings(record=True) as w2:
    warnings.simplefilter('always')
    pool.apply_changes('d', [{'actor': 'a', 'seq': 3, 'deps': {}, 'ops': [
        {'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 'x'}]}])
assert not [x for x in w2 if 'AMTPU_MESH' in str(x.message)]
print('MESH-LATCH-OK')
""".replace('REPO_PATH', repr(REPO))


def test_amtpu_mesh_latch_flip_warns_once():
    """AMTPU_MESH flips after the first batch warn + count
    mesh.latch_flip_ignored (the PR-6 latch-guard machinery, extended
    to the mesh topology knobs)."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('AMTPU_MESH', None)
    out = subprocess.run([sys.executable, '-c', MESH_LATCH], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'MESH-LATCH-OK' in out.stdout
