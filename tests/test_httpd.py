"""Direct coverage for the telemetry HTTP listener
(automerge_tpu/telemetry/httpd.py): /metrics, /healthz,
/debug/recorder, 404s, ephemeral-port binding, and clean shutdown.
The listener is a plain stdlib ThreadingHTTPServer on a daemon thread,
so every test binds port 0 (ephemeral) and shuts its server down."""

import json
import urllib.error
import urllib.request

import pytest

from automerge_tpu import telemetry
from automerge_tpu.telemetry import attribution, httpd, recorder


@pytest.fixture
def server():
    srv = httpd.start_metrics_server(0)
    try:
        yield srv
    finally:
        srv.shutdown()
        srv.server_close()


def _get(srv, path):
    url = 'http://127.0.0.1:%d%s' % (srv.server_port, path)
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get('Content-Type'), r.read()


def test_ephemeral_port_binds(server):
    # port 0 must resolve to a real bound port the OS handed out
    assert server.server_port != 0


def test_metrics_exposition(server):
    status, ctype, body = _get(server, '/metrics')
    assert status == 200
    assert ctype == httpd.CONTENT_TYPE
    text = body.decode()
    assert 'amtpu_up 1' in text
    # the request-stage family registers at first use; force it so the
    # scrape carries the attribution surface
    attribution.finish(attribution.Clock('read'), ok=True, cmd='ping')
    text = _get(server, '/metrics')[2].decode()
    assert 'amtpu_request_stage_ms_bucket' in text


def test_metrics_query_string_ignored(server):
    status, _ctype, body = _get(server, '/metrics?foo=bar')
    assert status == 200
    assert b'amtpu_up' in body


def test_healthz_payload(server):
    status, ctype, body = _get(server, '/healthz')
    assert status == 200
    assert ctype == 'application/json'
    payload = json.loads(body)
    assert payload['ok'] is True
    # the SLO surface and recorder state ride every healthz answer
    assert 'burn' in payload['slo']
    assert set(payload['slo']['classes']) == set(attribution.CLASSES)
    assert payload['recorder']['size'] >= 16


def test_debug_recorder(server):
    recorder.record('batch.begin', n=7, detail='httpd-test')
    status, ctype, body = _get(server, '/debug/recorder')
    assert status == 200
    assert ctype == 'application/json'
    payload = json.loads(body)
    events = [e for e in payload['events']
              if e['detail'] == 'httpd-test']
    assert events and events[-1]['n'] == 7
    assert 'exemplars' in payload


def test_debug_docs(server):
    from automerge_tpu.native import NativeDocPool
    from automerge_tpu.telemetry import capacity
    pool = NativeDocPool()
    pool.apply_changes('httpd-doc', [
        {'actor': 'h', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set',
                  'obj': '00000000-0000-0000-0000-000000000000',
                  'key': 'x', 'value': 1}]}])
    capacity.TRACKER.reset()
    capacity.attach(pool=pool)
    try:
        capacity.note_fanout('httpd-doc', 10, 50, 5)
        status, ctype, body = _get(server, '/debug/docs')
        assert status == 200
        assert ctype == 'application/json'
        payload = json.loads(body)
        assert payload['totals']['arena_bytes'] == pool.history_bytes()
        docs = {r['doc'] for r in payload['hot_docs']}
        assert 'httpd-doc' in docs
        # ?k=n bounds the hot-doc table
        payload = json.loads(_get(server, '/debug/docs?k=1')[2])
        assert len(payload['hot_docs']) <= 1
    finally:
        capacity.detach()
        capacity.TRACKER.reset()


def test_unknown_path_404(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, '/nope')
    assert ei.value.code == 404


def test_clean_shutdown():
    srv = httpd.start_metrics_server(0)
    port = srv.server_port
    assert _get(srv, '/healthz')[0] == 200
    srv.shutdown()
    srv.server_close()
    # the socket must actually be released: a rebind of the same port
    # succeeds (no lingering listener thread holding it)
    srv2 = httpd.start_metrics_server(port)
    try:
        assert srv2.server_port == port
        assert _get(srv2, '/healthz')[0] == 200
    finally:
        srv2.shutdown()
        srv2.server_close()


def test_metrics_reflect_runtime_counters(server):
    telemetry.metric('recorder.dumps', 0)   # pre-seed visibility
    text = _get(server, '/metrics')[2].decode()
    assert 'amtpu_runtime_counter{name="recorder.dumps"}' in text
