"""Sidecar protocol tests: a real server subprocess driven over stdio
(JSON lines and msgpack framing) and a unix socket, with patches compared
against the scalar oracle."""

import os
import subprocess
import sys
import tempfile
import time

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.errors import AutomergeError, RangeError
from automerge_tpu.sidecar.client import SidecarClient

ROOT_ID = '00000000-0000-0000-0000-000000000000'
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn(extra=()):
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server', *extra],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, cwd=REPO)
    return proc


CHS = [
    {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
         'value': 'magpie'}]},
    {'actor': 'b', 'seq': 1, 'deps': {'a': 1}, 'ops': [
        {'action': 'makeText', 'obj': 't1'},
        {'action': 'ins', 'obj': 't1', 'key': '_head', 'elem': 1},
        {'action': 'set', 'obj': 't1', 'key': 'b:1', 'value': 'x'},
        {'action': 'link', 'obj': ROOT_ID, 'key': 'text', 'value': 't1'}]},
]


def oracle_patches():
    st = Backend.init()
    patches = []
    for ch in CHS:
        st, p = Backend.apply_changes(st, [ch])
        patches.append(p)
    return st, patches


@pytest.mark.parametrize('framing', ['json', 'msgpack'])
def test_stdio_round_trip(framing):
    extra = ['--msgpack'] if framing == 'msgpack' else []
    proc = spawn(extra)
    st, want = oracle_patches()
    with SidecarClient(proc=proc, use_msgpack=(framing == 'msgpack')) as c:
        assert c.call('ping') == {'ok': True}
        for ch, wp in zip(CHS, want):
            got = c.apply_changes('doc1', [ch])
            assert got == wp
        assert c.get_patch('doc1') == Backend.get_patch(st)
        assert c.get_missing_deps('doc1') == {}
        for have in ({}, {'a': 1}, {'a': 1, 'b': 1}):
            got_changes = c.get_missing_changes('doc1', have)
            assert got_changes == Backend.get_missing_changes(st, have)


def test_apply_batch_and_errors():
    proc = spawn()
    with SidecarClient(proc=proc) as c:
        patches = c.apply_batch({'d1': [CHS[0]], 'd2': [CHS[0]]})
        assert set(patches) == {'d1', 'd2'}
        assert patches['d1']['clock'] == {'a': 1}
        # inconsistent seq reuse -> AutomergeError over the wire
        with pytest.raises(AutomergeError):
            c.apply_changes('d1', [{
                'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                    {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                     'value': 'DIFFERENT'}]}])
        # unknown command
        with pytest.raises(RangeError):
            c.call('frobnicate')


def test_apply_local_change():
    proc = spawn()
    with SidecarClient(proc=proc) as c:
        patch = c.apply_local_change('d1', dict(CHS[0], requestType='change'))
        assert patch['actor'] == 'a' and patch['seq'] == 1
        # replay of an applied seq is rejected (backend/index.js:178-180)
        with pytest.raises(RangeError):
            c.apply_local_change('d1', dict(CHS[0], requestType='change'))
        with pytest.raises(TypeError):
            c.apply_local_change('d1', {'requestType': 'change', 'ops': []})
        # transport-only requestType must not leak into shipped history
        shipped = c.get_missing_changes('d1', {})
        assert shipped and all('requestType' not in ch for ch in shipped)


def _local_request(actor, seq, ops, request_type='change', deps=None):
    return {'requestType': request_type, 'actor': actor, 'seq': seq,
            'deps': deps or {}, 'ops': ops}


class TestUndoRedo:
    """Sidecar undo/redo must match the scalar backend patch-for-patch
    for the same local-change request stream (backend/index.js:254-310)."""

    def _run_stream(self, requests):
        from automerge_tpu.sidecar.server import SidecarBackend
        side = SidecarBackend()
        st = Backend.init()
        for req in requests:
            st, want = Backend.apply_local_change(st, dict(req))
            got = side.apply_local_change('d', dict(req))
            assert got == want, '\nreq  %r\ngot  %r\nwant %r' % (
                req, got, want)
        return side, st

    def test_set_undo_redo_round_trip(self):
        self._run_stream([
            _local_request('a', 1, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': 'v1'}]),
            _local_request('a', 2, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': 'v2'}]),
            _local_request('a', 3, [], 'undo'),
            _local_request('a', 4, [], 'redo'),
            _local_request('a', 5, [], 'undo'),
            _local_request('a', 6, [], 'undo'),
        ])

    def test_undo_del_restores(self):
        self._run_stream([
            _local_request('a', 1, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'bird', 'value': 'magpie'}]),
            _local_request('a', 2, [{'action': 'del', 'obj': ROOT_ID,
                                     'key': 'bird'}]),
            _local_request('a', 3, [], 'undo'),   # bird back to magpie
        ])

    def test_new_change_clears_redo(self):
        side, st = self._run_stream([
            _local_request('a', 1, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': 1}]),
            _local_request('a', 2, [], 'undo'),
            _local_request('a', 3, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 'k', 'value': 2}]),
        ])
        with pytest.raises(RangeError):
            side.apply_local_change('d', _local_request('a', 4, [], 'redo'))

    def test_undo_empty_raises(self):
        from automerge_tpu.sidecar.server import SidecarBackend
        side = SidecarBackend()
        with pytest.raises(RangeError):
            side.apply_local_change('d', _local_request('a', 1, [], 'undo'))

    def test_timestamp_datatype_redo(self):
        self._run_stream([
            _local_request('a', 1, [{'action': 'set', 'obj': ROOT_ID,
                                     'key': 't', 'value': 123456,
                                     'datatype': 'timestamp'}]),
            _local_request('a', 2, [{'action': 'del', 'obj': ROOT_ID,
                                     'key': 't'}]),
            _local_request('a', 3, [], 'undo'),
            _local_request('a', 4, [], 'undo'),
            _local_request('a', 5, [], 'redo'),
        ])


def test_unix_socket():
    path = os.path.join(tempfile.mkdtemp(), 'amtpu.sock')
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'automerge_tpu.sidecar.server',
         '--socket', path], env=env, cwd=REPO)
    try:
        for _ in range(100):
            if os.path.exists(path):
                break
            time.sleep(0.1)
        with SidecarClient(sock_path=path) as c:
            assert c.call('ping') == {'ok': True}
            st, want = oracle_patches()
            got = c.apply_changes('doc1', [CHS[0]])
            assert got == want[0]
    finally:
        proc.terminate()
        proc.wait(timeout=10)
