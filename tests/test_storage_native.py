"""Native columnar fast path + arena op-state folding lanes (ISSUE 14,
docs/STORAGE.md): codec fuzz parity native vs Python (blob-for-blob AND
byte round-trip, including hand-mangled non-canonical bytes, dup-
(actor,seq) streams, and GC-truncated docs), arena-direct decode parity
vs the dict-replay oracle across both exec modes, the op-state folding
lane (flat arena under settled-overwrite churn with byte-identical
straggler backfill), chunk re-compaction, and the durable cold store
(kill-mid-save via the ``storage.save`` fault lane, manifest recovery,
checksum detection)."""

import os
import random

import msgpack
import pytest

from automerge_tpu import faults, storage, telemetry
from automerge_tpu.native import NativeDocPool
from automerge_tpu.native import columnar_decode_native, \
    columnar_encode_native
from automerge_tpu.storage.coldstore import ColdStore

ROOT = '00000000-0000-0000-0000-000000000000'


@pytest.fixture(autouse=True)
def _reset():
    telemetry.reset_all()
    faults.reset('')
    yield
    faults.reset('')
    telemetry.reset_all()


@pytest.fixture(params=['default', 'kernel'])
def exec_mode(request):
    """Both execution modes face the parity lanes (same pattern as
    tests/test_storage.py): arena-direct load always resolves host-
    side in C++, so its output must match the dict replay under the
    CPU default AND the forced kernel path."""
    if request.param == 'kernel':
        prior = {k: os.environ.get(k)
                 for k in ('AMTPU_HOST_FULL', 'AMTPU_HOST_REG')}
        os.environ['AMTPU_HOST_FULL'] = '0'
        os.environ['AMTPU_HOST_REG'] = '0'
        yield 'kernel'
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    else:
        yield 'default'


def _encode_arm(raws, native):
    """encode_columnar through one dispatch arm (the gate is checked
    per call, so flipping the env interleaves cleanly)."""
    prior = os.environ.get('AMTPU_STORAGE_NATIVE')
    os.environ['AMTPU_STORAGE_NATIVE'] = '1' if native else '0'
    try:
        return storage.encode_columnar(raws)
    finally:
        if prior is None:
            os.environ.pop('AMTPU_STORAGE_NATIVE', None)
        else:
            os.environ['AMTPU_STORAGE_NATIVE'] = prior


def _decode_arm(blob, native):
    prior = os.environ.get('AMTPU_STORAGE_NATIVE')
    os.environ['AMTPU_STORAGE_NATIVE'] = '1' if native else '0'
    try:
        return storage.decode_columnar(blob)
    finally:
        if prior is None:
            os.environ.pop('AMTPU_STORAGE_NATIVE', None)
        else:
            os.environ['AMTPU_STORAGE_NATIVE'] = prior


def _rand_change_dicts(rng, n=120, n_actors=5):
    """Well-formed random corpus: map sets, text runs, links, odd-but-
    canonical value types, catch-up deps, dup-(actor,seq) replays."""
    out = []
    seqs = {}
    elem = 0
    for i in range(n):
        actor = 'actor-%d' % rng.randrange(n_actors)
        seqs[actor] = seqs.get(actor, 0) + 1
        ops = []
        for _ in range(rng.randrange(1, 5)):
            roll = rng.random()
            if roll < 0.3:
                ops.append({'action': 'set', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(8),
                            'value': rng.choice([
                                rng.randrange(-10**9, 10**9), 'héllo 中',
                                3.140625, True, False, None, b'\x00\xff',
                                {'nested': [1, 'two', None]},
                                ['deep', {'er': 2.5}]])})
            elif roll < 0.5:
                elem += 1
                ops.append({'action': 'ins', 'obj': 'T',
                            'key': '_head' if elem == 1
                            else '%s:%d' % (actor, elem - 1),
                            'elem': elem})
            elif roll < 0.7:
                ops.append({'action': 'set', 'obj': 'T',
                            'key': '%s:%d' % (actor, max(1, elem)),
                            'value': chr(97 + i % 26)})
            elif roll < 0.8:
                ops.append({'action': 'makeMap', 'obj': 'm-%d' % i})
            else:
                ops.append({'action': 'del', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(8)})
        ch = {'actor': actor, 'seq': seqs[actor],
              'deps': {a: s for a, s in list(seqs.items())
                       [:rng.randrange(0, 3)]},
              'ops': ops}
        if rng.random() < 0.25:
            ch['message'] = 'round %d' % i
        out.append(ch)
        if rng.random() < 0.1:
            out.append(dict(ch))     # dup-(actor,seq) replay
    return out


def _mangled_raws():
    """Hand-mangled / non-canonical change bytes: every one must ride
    the residual column and still round-trip byte-exactly."""
    k = msgpack.packb
    return [
        # non-canonical int spelling (uint8 for a fixint value)
        b'\x82' + k('actor') + k('a') + k('seq') + b'\xcc\x05',
        # float32 value (canonical re-encode widens to float64)
        b'\x83' + k('actor') + k('a') + k('seq') + k(1) +
        k('x') + b'\xca\x3f\x80\x00\x00',
        # not a map at all
        k([1, 2, 3], use_bin_type=True),
        # bool seq (schema reject)
        k({'actor': 'a', 'seq': True}, use_bin_type=True),
        # negative seq (schema reject)
        k({'actor': 'a', 'seq': -3}, use_bin_type=True),
        # deps with a non-int value (schema reject)
        k({'actor': 'a', 'seq': 1, 'deps': {'b': 'x'}},
          use_bin_type=True),
        # duplicate map key (canonical re-encode collapses it)
        b'\x82' + k('actor') + k('a') + k('actor') + k('b'),
        # int obj in an op (schema reject: typed column desync)
        k({'actor': 'a', 'seq': 1,
           'ops': [{'action': 'set', 'obj': 7, 'key': 'k'}]},
          use_bin_type=True),
        # trailing bytes after the change map
        k({'actor': 'a', 'seq': 1}, use_bin_type=True) + b'\x01',
    ]


class TestCodecFuzzParity:
    """Native codec vs Python codec: blob-for-blob identical output and
    guaranteed byte round-trip on random corpora."""

    @pytest.mark.parametrize('seed', [7, 23, 101])
    def test_blob_and_roundtrip_parity(self, seed):
        rng = random.Random(seed)
        raws = [msgpack.packb(c, use_bin_type=True)
                for c in _rand_change_dicts(rng)]
        py_blob = _encode_arm(raws, native=False)
        nat_blob = _encode_arm(raws, native=True)
        assert py_blob == nat_blob          # bit-for-bit, zlib included
        # all four (encoder, decoder) pairs reproduce the input bytes
        assert _decode_arm(py_blob, native=False) == raws
        assert _decode_arm(py_blob, native=True) == raws
        assert _decode_arm(nat_blob, native=False) == raws
        assert _decode_arm(nat_blob, native=True) == raws
        flat = telemetry.metrics_snapshot()
        assert flat.get('storage.native_encodes', 0) >= 1
        assert flat.get('storage.python_encodes', 0) >= 1

    def test_mangled_bytes_ride_residual_and_roundtrip(self):
        rng = random.Random(5)
        good = [msgpack.packb(c, use_bin_type=True)
                for c in _rand_change_dicts(rng, n=20)]
        raws = []
        mangled = _mangled_raws()
        for i, raw in enumerate(good):
            raws.append(raw)
            if i < len(mangled):
                raws.append(mangled[i])
        py_blob = _encode_arm(raws, native=False)
        nat_blob = _encode_arm(raws, native=True)
        # round-trip is the hard guarantee for residual-laden streams
        assert _decode_arm(py_blob, native=True) == raws
        assert _decode_arm(nat_blob, native=False) == raws
        assert _decode_arm(nat_blob, native=True) == raws
        # both encoders sent the mangled changes residual (the exact
        # split is each encoder's own; the counter proves nonzero)
        assert telemetry.metrics_snapshot().get(
            'storage.columnar.residual_changes', 0) >= len(mangled)

    def test_native_decode_rejects_corrupt_blobs(self):
        blob = _encode_arm(
            [msgpack.packb({'actor': 'a', 'seq': 1, 'deps': {},
                            'ops': []}, use_bin_type=True)],
            native=True)
        for bad in (b'AMTX' + blob[4:],          # magic
                    blob[:4] + b'\x07' + blob[5:],   # version
                    blob[:6] + b'garbage',        # body
                    blob[:-3]):                   # truncated
            with pytest.raises(ValueError):
                columnar_decode_native(bad)

    def test_gc_truncated_doc_chunks_decode_identically(self):
        """GC-truncated docs: the snapshot chunks a compacted pool
        holds decode byte-identically through both codecs."""
        pool = NativeDocPool()
        for r in range(8):
            pool.apply_batch({'d': [
                {'actor': 'a1', 'seq': r + 1, 'deps': {},
                 'ops': [{'action': 'set', 'obj': ROOT,
                          'key': 'k%d' % (r % 2), 'value': r}]}]})
        assert pool.compact('d') > 0
        st = pool._storage[pool._doc_key('d')]
        assert st['chunks']
        for chunk in st['chunks']:
            assert _decode_arm(chunk, native=True) == \
                _decode_arm(chunk, native=False)

    def test_exotic_ext_bytes_ride_residual(self):
        """msgpack ext framing (outside the conservative canonical
        subset): the native encoder carries it verbatim in the residual
        column -- round-trip and cross-decode still hold."""
        ext = msgpack.packb(msgpack.ExtType(4, b'\x01\x02'))
        raws = [msgpack.packb({'actor': 'a', 'seq': 1},
                              use_bin_type=True), ext]
        blob = _encode_arm(raws, native=True)
        assert _decode_arm(blob, native=False) == raws
        assert _decode_arm(blob, native=True) == raws
        assert telemetry.metrics_snapshot().get(
            'storage.columnar.residual_changes', 0) >= 1


def _corpus_round(rng, state, n=3, n_actors=3, tag=''):
    """Causally-valid mixed changes for ONE doc round: map sets, a
    growing text run, object creations, deletes (the apply-side twin
    of the codec fuzz generator)."""
    out = []
    for _i in range(n):
        actor = 'b%d' % rng.randrange(n_actors)
        ops = []
        for _ in range(rng.randrange(1, 4)):
            roll = rng.random()
            if roll < 0.35:
                ops.append({'action': 'set', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(6),
                            'value': rng.choice([
                                rng.randrange(-999, 9999), 'v中', 2.5,
                                None, True, b'\x01\x02'])})
            elif roll < 0.7:
                state['elem'] += 1
                ops.append({'action': 'ins', 'obj': 'T',
                            'key': state['prev'],
                            'elem': state['elem']})
                key = '%s:%d' % (actor, state['elem'])
                ops.append({'action': 'set', 'obj': 'T', 'key': key,
                            'value': chr(97 + state['elem'] % 26)})
                state['prev'] = key
            elif roll < 0.85:
                state['mk'] += 1
                ops.append({'action': 'makeMap',
                            'obj': 'M-%s-%d' % (tag, state['mk'])})
            else:
                ops.append({'action': 'del', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(6)})
        out.append({'actor': actor, 'ops': ops})
    return out


def _stamp(rng, clock, chs):
    """Stamps a change list into a causally-ready per-doc stream
    (seq = next per actor, deps a subset of the applied clock)."""
    out = []
    for c in chs:
        a = c['actor']
        clock[a] = clock.get(a, 0) + 1
        c = dict(c)
        c['seq'] = clock[a]
        c['deps'] = {k: v for k, v in clock.items()
                     if k != a and rng.random() < 0.5}
        out.append(c)
    return out


def _build_corpus_pool(rng, n_docs=6, compact_some=True):
    """A builder pool with mixed doc shapes; some docs compacted so
    their checkpoints carry snapshot chunks."""
    pool = NativeDocPool()
    for d in range(n_docs):
        doc = 'doc-%d' % d
        clock = {}
        state = {'elem': 0, 'prev': '_head', 'mk': 0}
        init = [{'actor': 'b0', 'ops': [
            {'action': 'makeText', 'obj': 'T'},
            {'action': 'link', 'obj': ROOT, 'key': 'text',
             'value': 'T'}]}]
        pool.apply_batch({doc: _stamp(rng, clock, init)})
        for r in range(6):
            chs = _corpus_round(rng, state, tag='%s-%d' % (doc, r))
            pool.apply_batch({doc: _stamp(rng, clock, chs)})
        if compact_some and d % 2 == 0:
            pool.compact(doc)
    return pool


class TestDecodePathParity:
    """Arena-direct native load vs the dict-replay oracle: per-doc
    byte-identical state across both exec modes."""

    def test_load_batch_parity_both_arms(self, exec_mode, monkeypatch):
        rng = random.Random(11)
        pool = _build_corpus_pool(rng)
        docs = ['doc-%d' % d for d in range(6)]
        blobs = {d: pool.save(d) for d in docs}

        monkeypatch.setenv('AMTPU_STORAGE_NATIVE', '1')
        nat = NativeDocPool()
        nat.load_batch(blobs)
        assert telemetry.metrics_snapshot().get('storage.native_loads', 0) >= 1

        monkeypatch.setenv('AMTPU_STORAGE_NATIVE', '0')
        py = NativeDocPool()
        py.load_batch(blobs)

        monkeypatch.delenv('AMTPU_STORAGE_NATIVE', raising=False)
        for d in docs:
            assert nat.get_patch(d) == py.get_patch(d) == \
                pool.get_patch(d)
            assert nat.save(d) == py.save(d)
            assert nat.get_missing_changes(d, {}) == \
                py.get_missing_changes(d, {})

    def test_v1_checkpoints_load_native(self, monkeypatch):
        monkeypatch.setenv('AMTPU_STORAGE_FORMAT', 'json')
        rng = random.Random(3)
        pool = _build_corpus_pool(rng, n_docs=2, compact_some=False)
        blobs = {d: pool.save(d) for d in ('doc-0', 'doc-1')}
        assert all(b.startswith(storage.CKPT_V1_PREFIX)
                   for b in blobs.values())
        monkeypatch.setenv('AMTPU_STORAGE_NATIVE', '1')
        nat = NativeDocPool()
        nat.load_batch(blobs)
        for d in blobs:
            assert nat.get_patch(d) == pool.get_patch(d)


class TestOpStateFolding:
    """Settled-overwrite churn: history bytes AND op count stay FLAT
    (not merely sub-linear) with folding on, while a straggler behind
    the fold frontier still backfills byte-identically."""

    def _churn(self, fold_on, rounds=8, keys=6, monkeypatch=None):
        monkeypatch.setenv('AMTPU_STORAGE_FOLD', '1' if fold_on else '0')
        pool = NativeDocPool()
        track = []
        round_changes = []
        seq = 0
        for r in range(rounds):
            chs = []
            for k in range(keys):
                seq += 1
                chs.append({'actor': 'w', 'seq': seq, 'deps': {},
                            'ops': [{'action': 'set', 'obj': ROOT,
                                     'key': 'k%d' % k, 'value': r}]})
            round_changes.append(chs)
            pool.apply_batch({'churn': chs})
            pool.compact('churn')      # no subscribers: all settled
            track.append((pool.history_bytes('churn'),
                          pool.op_count('churn')))
        return pool, track, round_changes

    def test_arena_flat_under_churn_with_folding(self, monkeypatch):
        pool, track, _ = self._churn(True, monkeypatch=monkeypatch)
        bytes_per_round = [b for b, _n in track]
        ops_per_round = [n for _b, n in track]
        # FLAT: every post-compact round measures exactly the same
        assert len(set(bytes_per_round[1:])) == 1, bytes_per_round
        assert len(set(ops_per_round[1:])) == 1, ops_per_round
        assert telemetry.metrics_snapshot().get('storage.gc.ops_folded', 0) > 0

    def test_no_fold_arm_grows_and_patches_match(self, monkeypatch):
        pool_f, _track, _ = self._churn(True, monkeypatch=monkeypatch)
        patch_f = pool_f.get_patch('churn')
        telemetry.reset_all()
        pool_n, track_n, _ = self._churn(False, monkeypatch=monkeypatch)
        ops_n = [n for _b, n in track_n]
        assert ops_n[-1] > ops_n[1]          # no-fold arm grows
        assert telemetry.metrics_snapshot().get('storage.gc.ops_folded', 0) == 0
        assert pool_n.get_patch('churn') == patch_f

    def test_straggler_backfills_byte_identically(self, monkeypatch):
        """A replica that stopped at round 1 catches up from behind the
        fold frontier: the shipped bytes and final state must match the
        no-fold arm exactly."""
        results = {}
        for arm in (True, False):
            pool, _track, round_changes = self._churn(
                arm, monkeypatch=monkeypatch)
            straggler = NativeDocPool()
            straggler.apply_batch({'churn': round_changes[0]})
            have = straggler.get_clock('churn')['clock']
            missing = pool.get_missing_changes('churn', have)
            raw = pool.get_changes_for_actor_bytes('churn', 'w',
                                                   have.get('w', 0))
            straggler.apply_batch({'churn': missing})
            results[arm] = (missing, raw,
                            straggler.get_patch('churn'),
                            pool.get_patch('churn'))
        assert results[True] == results[False]
        fold_missing, _raw, straggler_patch, main_patch = results[True]
        assert straggler_patch == main_patch

    def test_duplicate_resend_of_folded_change_is_harmless(
            self, monkeypatch):
        pool, _track, round_changes = self._churn(
            True, monkeypatch=monkeypatch)
        before = pool.get_patch('churn')
        # folded entries freed their op records; a straggler re-sending
        # the settled change must dedup, not raise
        pool.apply_batch({'churn': round_changes[0]})
        assert pool.get_patch('churn') == before


class TestChunkRecompaction:
    def test_chunks_merge_past_cap(self, monkeypatch):
        monkeypatch.setenv('AMTPU_STORAGE_CHUNK_MAX', '3')
        pool = NativeDocPool()
        seq = 0
        for r in range(7):
            seq += 1
            pool.apply_batch({'d': [
                {'actor': 'a', 'seq': seq, 'deps': {},
                 'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                          'value': r}]}]})
            pool.compact('d')
        st = pool._storage[pool._doc_key('d')]
        assert len(st['chunks']) < 3
        assert telemetry.metrics_snapshot().get('storage.gc.rechunks', 0) >= 1
        # the merged snapshot still restores byte-identically
        twin = NativeDocPool()
        twin.load_batch({'d': pool.save('d')})
        assert twin.get_patch('d') == pool.get_patch('d')
        assert twin.save('d') == pool.save('d')

    def test_rechunk_disabled_by_zero(self, monkeypatch):
        monkeypatch.setenv('AMTPU_STORAGE_CHUNK_MAX', '0')
        pool = NativeDocPool()
        for r in range(5):
            pool.apply_batch({'d': [
                {'actor': 'a', 'seq': r + 1, 'deps': {},
                 'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                          'value': r}]}]})
            pool.compact('d')
        st = pool._storage[pool._doc_key('d')]
        assert len(st['chunks']) == 5
        assert telemetry.metrics_snapshot().get('storage.gc.rechunks', 0) == 0


class TestDurableColdStore:
    def _blob(self, tag):
        return (b'AMTC-fake-' + tag) * 40

    def test_manifest_recovery(self, tmp_path):
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=True)
        cs.put('doc-a', self._blob(b'a'))
        cs.put('doc-b', self._blob(b'b'))
        fresh = ColdStore(root=root, durable=True)
        assert sorted(fresh.doc_ids()) == ['doc-a', 'doc-b']
        assert fresh.get('doc-a') == self._blob(b'a')
        assert telemetry.metrics_snapshot().get(
            'storage.manifest_recovered', 0) == 2

    @pytest.mark.parametrize('durable', [True, False])
    def test_kill_mid_save_leaves_prior_intact(self, tmp_path, durable):
        """The storage.save fault lane: a save killed mid-write (a
        partial tempfile exists, the rename never ran) must leave the
        prior committed copy -- and in durable mode the manifest
        naming it -- untouched."""
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=durable)
        cs.put('doc-a', self._blob(b'v1'))
        spec = faults.arm('storage.save', 'permanent')
        with pytest.raises(faults.InjectedFault):
            cs.put('doc-a', self._blob(b'v2-new-bytes'))
        faults.disarm(spec)
        assert cs.get('doc-a') == self._blob(b'v1')
        # the crash evidence: a partial tempfile, strictly shorter
        tmps = [f for f in os.listdir(root) if f.endswith('.tmp')]
        assert tmps
        assert os.path.getsize(os.path.join(root, tmps[0])) \
            < len(self._blob(b'v2-new-bytes'))
        if durable:
            fresh = ColdStore(root=root, durable=True)
            assert fresh.get('doc-a') == self._blob(b'v1')

    def test_kill_between_rename_and_manifest_keeps_prior(
            self, tmp_path, monkeypatch):
        """The post-rename pre-manifest window: durable blob files are
        VERSIONED by content hash, so even after the new file landed,
        the manifest still names the intact prior copy."""
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=True)
        cs.put('doc-a', self._blob(b'v1'))

        def die(*_a, **_k):
            raise OSError('killed before the manifest write')

        monkeypatch.setattr(cs, '_write_manifest', die)
        with pytest.raises(OSError):
            cs.put('doc-a', self._blob(b'v2'))
        monkeypatch.undo()
        fresh = ColdStore(root=root, durable=True)
        assert fresh.get('doc-a') == self._blob(b'v1')

    def test_put_many_single_manifest_write(self, tmp_path):
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=True)
        cs.put_many({'doc-%d' % i: self._blob(b'%d' % i)
                     for i in range(10)})
        assert telemetry.metrics_snapshot().get(
            'storage.manifest_writes', 0) == 1
        fresh = ColdStore(root=root, durable=True)
        assert len(fresh.doc_ids()) == 10
        assert fresh.get('doc-3') == self._blob(b'3')

    def test_checksum_detects_bit_rot(self, tmp_path):
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=True)
        cs.put('doc-a', self._blob(b'a'))
        path = cs._index['doc-a'][0]
        data = bytearray(open(path, 'rb').read())
        data[5] ^= 0xff
        with open(path, 'wb') as f:
            f.write(data)
        with pytest.raises(ValueError, match='checksum'):
            cs.get('doc-a')
        assert telemetry.metrics_snapshot().get(
            'storage.checksum_failed', 0) == 1

    def test_non_durable_has_no_manifest(self, tmp_path):
        root = str(tmp_path / 'cold')
        cs = ColdStore(root=root, durable=False)
        cs.put('doc-a', self._blob(b'a'))
        assert not os.path.exists(os.path.join(root, 'manifest.amtm'))
        # a fresh non-durable store starts empty (extension of pool
        # memory, not durable storage)
        assert len(ColdStore(root=root, durable=False)) == 0


class TestEncodeSplit:
    """The CheckpointWAL satellite: save() (what WAL compaction
    records) routes through the native codec when available, and the
    native/python split is observable."""

    def _pool(self):
        pool = NativeDocPool()
        for r in range(3):
            pool.apply_batch({'d': [
                {'actor': 'a', 'seq': r + 1, 'deps': {},
                 'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                          'value': r}]}]})
        return pool

    def test_save_counts_native_encodes(self, monkeypatch):
        monkeypatch.setenv('AMTPU_STORAGE_NATIVE', '1')
        self._pool().save('d')
        flat = telemetry.metrics_snapshot()
        assert flat.get('storage.native_encodes', 0) >= 1
        assert flat.get('storage.python_encodes', 0) == 0

    def test_save_oracle_arm_counts_python_encodes(self, monkeypatch):
        monkeypatch.setenv('AMTPU_STORAGE_NATIVE', '0')
        self._pool().save('d')
        flat = telemetry.metrics_snapshot()
        assert flat.get('storage.python_encodes', 0) >= 1
        assert flat.get('storage.native_encodes', 0) == 0

    def test_direct_native_bindings_roundtrip(self):
        raws = [msgpack.packb({'actor': 'a', 'seq': i + 1, 'deps': {},
                               'ops': []}, use_bin_type=True)
                for i in range(10)]
        blob, n_changes, n_residual = columnar_encode_native(raws)
        assert (n_changes, n_residual) == (10, 0)
        assert columnar_decode_native(blob) == raws
