"""Fleet-router tests (ISSUE 18, docs/SERVING.md routing section):
consistent-hash ring determinism + minimal disruption, the live
RouterGateway over real unix sockets (forwarding, split-join,
proxied-subscribe byte parity), parked-op FIFO during a live
migration, WrongReplica redirects (router-transparent and
direct-client), the ColdStore concurrency regression, and a 3-replica
live end-to-end lane with migrations under concurrent writers.
"""

import json
import os
import socket
import threading
import time

import pytest

from automerge_tpu import telemetry
from automerge_tpu.errors import WrongReplicaError
from automerge_tpu.router import (HashRing, MigrationExecutor,
                                  Rebalancer, RouterGateway)
from automerge_tpu.scheduler import GatewayServer
from automerge_tpu.sidecar.client import SidecarClient
from automerge_tpu.sidecar.server import SidecarBackend
from automerge_tpu.storage.coldstore import ColdStore

ROOT_ID = '00000000-0000-0000-0000-000000000000'


@pytest.fixture(autouse=True)
def _hygiene():
    # reset_all, not metrics_reset: the live-gateway lanes bump the
    # registry histograms (BATCH_OCCUPANCY etc.) that later suites
    # assert exact counts on
    telemetry.reset_all()
    os.environ['AMTPU_FLUSH_DEADLINE_MS'] = '5'
    yield
    del os.environ['AMTPU_FLUSH_DEADLINE_MS']
    telemetry.reset_all()


def change(actor, seq, key='k', value=None):
    return {'actor': actor, 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': key,
                     'value': value if value is not None
                     else '%s-%d' % (actor, seq)}]}


def _flat():
    return telemetry.metrics_snapshot()


# ---------------------------------------------------------------------------
# ring lanes
# ---------------------------------------------------------------------------

def test_ring_deterministic_and_balanced():
    a = HashRing(['r0', 'r1', 'r2'], vnodes=64)
    b = HashRing(['r2', 'r0', 'r1'], vnodes=64)
    docs = ['doc-%d' % i for i in range(500)]
    pa = {d: a.owner(d) for d in docs}
    assert pa == {d: b.owner(d) for d in docs}, \
        'placement must not depend on membership insertion order'
    by_owner = {}
    for d, o in pa.items():
        by_owner[o] = by_owner.get(o, 0) + 1
    assert set(by_owner) == {'r0', 'r1', 'r2'}
    assert min(by_owner.values()) > 500 / 3 / 2.5, by_owner


def test_ring_minimal_disruption_on_membership_change():
    ring = HashRing(['r0', 'r1', 'r2'], vnodes=64)
    docs = ['doc-%d' % i for i in range(500)]
    before = {d: ring.owner(d) for d in docs}
    v0 = ring.version
    assert ring.add('r3') == v0 + 1
    after = {d: ring.owner(d) for d in docs}
    moved = [d for d in docs if before[d] != after[d]]
    # adding one replica of four remaps ~1/4 of the space, never more
    assert 0 < len(moved) < 500 * 0.45, len(moved)
    assert all(after[d] == 'r3' for d in moved), \
        'docs may only move TO the new member'
    # removing it restores the exact prior placement
    ring.remove('r3')
    assert {d: ring.owner(d) for d in docs} == before


def test_ring_overrides_and_version():
    ring = HashRing(['r0', 'r1'], vnodes=32)
    d = 'doc-x'
    home = ring.owner(d)
    other = 'r1' if home == 'r0' else 'r0'
    v = ring.version
    assert ring.set_overrides({d: other}) == v + 1
    assert ring.owner(d) == other
    assert ring.hash_owner(d) == home
    # overriding back to the hash home DROPS the override
    ring.set_overrides({d: home})
    assert ring.owner(d) == home and ring.overrides() == {}
    # int ids canonicalize: 5 and 'i:5' are the same doc
    assert ring.owner(5) == ring.owner('i:5')
    # removing the override target sends its docs back to hash owners
    ring.set_overrides({d: other})
    ring.remove(other)
    assert ring.overrides() == {}


# ---------------------------------------------------------------------------
# live router harness
# ---------------------------------------------------------------------------

class Fleet(object):
    """N in-process replica gateways + one router, torn down in one
    place.  (Real deployments run replicas as processes; in-process
    pools are isolated enough for these lanes and keep them fast.)"""

    def __init__(self, tmp, n=2):
        self.replicas = {}
        self.gateways = []
        for i in range(n):
            path = str(tmp / ('r%d.sock' % i))
            self.gateways.append(
                GatewayServer(path, backend=SidecarBackend()).start())
            self.replicas['r%d' % i] = path
        self.router_path = str(tmp / 'router.sock')
        self.router = RouterGateway(self.router_path,
                                    self.replicas).start()

    def stop(self):
        self.router.stop()
        for gw in self.gateways:
            gw.stop()


@pytest.fixture()
def fleet(tmp_path):
    f = Fleet(tmp_path, n=2)
    yield f
    f.stop()


def test_router_forwards_and_answers_pure(fleet):
    with SidecarClient(sock_path=fleet.router_path) as c:
        docs = ['doc-%d' % i for i in range(6)]
        for d in docs:
            r = c.apply_changes(d, [change('a', 1)])
            assert r['clock'] == {'a': 1}, r
        for d in docs:
            assert c.get_patch(d)['clock'] == {'a': 1}
        assert c.call('ping') == {'ok': True}
        hz = c.healthz()
        assert hz['routing']['role'] == 'router'
        assert hz['routing']['members'] == ['r0', 'r1']
    flat = _flat()
    assert flat.get('router.requests', 0) >= 12
    assert flat.get('router.local', 0) >= 2


def test_router_cross_owner_apply_batch_split_join(fleet):
    ring = fleet.router.ring
    docs = ['doc-%d' % i for i in range(12)]
    owners = {ring.owner(d) for d in docs}
    assert owners == {'r0', 'r1'}, 'need docs on both replicas'
    with SidecarClient(sock_path=fleet.router_path) as c:
        res = c.call('apply_batch',
                     docs={d: [change('a', 1)] for d in docs})
        assert set(res) == set(docs)
        for d in docs:
            assert res[d]['clock'] == {'a': 1}, (d, res[d])
    assert _flat().get('router.split_ops', 0) >= 1


def test_proxied_subscribe_byte_parity_vs_direct(fleet):
    """The router forwards upstream frames verbatim: a subscriber via
    the router reads BYTE-IDENTICAL fan-out frames to one connected
    directly to the owner replica."""
    doc = 'parity-doc'
    owner_path = fleet.replicas[fleet.router.ring.owner(doc)]

    def raw_subscribe(path):
        s = socket.socket(socket.AF_UNIX)
        s.connect(path)
        s.sendall((json.dumps(
            {'id': 1, 'cmd': 'subscribe', 'doc': doc,
             'peer': 'p-parity'}) + '\n').encode())
        f = s.makefile('rb')
        f.readline()                      # subscribe response
        return s, f

    with SidecarClient(sock_path=fleet.router_path) as w:
        w.apply_changes(doc, [change('a', 1)])
        s_direct, f_direct = raw_subscribe(owner_path)
        s_router, f_router = raw_subscribe(fleet.router_path)
        try:
            for seq in range(2, 6):
                w.apply_changes(doc, [change('a', seq)])
            for _ in range(4):
                direct = f_direct.readline()
                routed = f_router.readline()
                assert direct == routed, (direct, routed)
                assert json.loads(direct)['event'] == 'change'
        finally:
            s_direct.close()
            s_router.close()


def test_parked_ops_fifo_during_migration(fleet):
    """Frames touching a migrating doc park in arrival order and
    release in the same order at commit: pipelined seqs 2..6 (which
    MUST apply in order -- automerge rejects seq gaps) all land."""
    doc = 'parked-doc'
    with SidecarClient(sock_path=fleet.router_path) as c:
        c.apply_changes(doc, [change('a', 1)])
    router = fleet.router
    router.begin_migration([doc])
    s = socket.socket(socket.AF_UNIX)
    s.connect(fleet.router_path)
    f = s.makefile('rb')
    try:
        for seq in range(2, 7):
            s.sendall((json.dumps(
                {'id': seq, 'cmd': 'apply_changes', 'doc': doc,
                 'changes': [change('a', seq)]}) + '\n').encode())
        deadline = time.time() + 5
        while _flat().get('router.parked', 0) < 5:
            assert time.time() < deadline, _flat()
            time.sleep(0.01)
        s.settimeout(0.3)
        with pytest.raises(socket.timeout):
            s.recv(1)                     # parked: nothing answers
        s.settimeout(None)
        router.end_migration([doc])
        rids = [json.loads(f.readline())['id'] for _ in range(5)]
        assert rids == [2, 3, 4, 5, 6], rids
    finally:
        s.close()
    with SidecarClient(sock_path=fleet.router_path) as c:
        assert c.get_patch(doc)['clock'] == {'a': 6}


def test_live_migration_moves_doc_and_redirects(fleet, tmp_path):
    doc = 'mig-doc'
    router = fleet.router
    with SidecarClient(sock_path=fleet.router_path) as c:
        for seq in (1, 2):
            c.apply_changes(doc, [change('a', seq)])
        src = router.ring.owner(doc)
        dst = 'r1' if src == 'r0' else 'r0'
        ex = MigrationExecutor(router,
                               handoff_dir=str(tmp_path / 'handoff'))
        res = ex.migrate([doc], src, dst)
        assert res['docs'] == [doc] and not res['failed'], res
        assert router.ring.owner(doc) == dst
        # the doc keeps serving through the router, history intact
        r = c.apply_changes(doc, [change('a', 3)])
        assert r['clock'] == {'a': 3}
        assert c.get_patch(doc)['clock'] == {'a': 3}
    flat = _flat()
    assert flat.get('migrate.migrations', 0) == 1
    assert flat.get('migrate.out_docs', 0) == 1
    assert flat.get('migrate.in_docs', 0) == 1
    # replica-side booking (read the section directly: in-process the
    # healthz registry is shared, so the router's 'routing' section
    # shadows the replicas'; real replicas are separate processes)
    src_gw = fleet.gateways[int(src[1:])]
    rt = src_gw._routing_section()
    assert rt['migrations_out'] == 1 and rt['disowned_docs'] == 1


def test_router_transparent_redirect_on_stale_ring(fleet, tmp_path):
    """A doc migrated BEHIND the router's back (stale ring): the old
    owner answers WrongReplica, the router re-forwards the original
    frame to the named owner and learns the placement."""
    doc = 'stale-doc'
    router = fleet.router
    with SidecarClient(sock_path=fleet.router_path) as c:
        c.apply_changes(doc, [change('a', 1)])
        src = router.ring.owner(doc)
        dst = 'r1' if src == 'r0' else 'r0'
        store = str(tmp_path / 'stale-handoff')
        out = router.control_call(src, 'migrate_out', docs=[doc],
                                  store_dir=store, new_owner=dst,
                                  ring_version=99)
        assert out['migrated'] == [doc], out
        router.control_call(dst, 'migrate_in', docs=[doc],
                            store_dir=store, ring_version=99)
        # ring still says src; the redirect is invisible to the client
        assert router.ring.owner(doc) == src
        r = c.apply_changes(doc, [change('a', 2)])
        assert r['clock'] == {'a': 2}
        assert router.ring.owner(doc) == dst, \
            'the WrongReplica envelope must teach the ring'
    assert _flat().get('router.redirects', 0) >= 1


def test_direct_client_wrong_replica_typed_error(fleet, tmp_path):
    doc = 'direct-doc'
    router = fleet.router
    with SidecarClient(sock_path=fleet.router_path) as c:
        c.apply_changes(doc, [change('a', 1)])
    src = router.ring.owner(doc)
    dst = 'r1' if src == 'r0' else 'r0'
    ex = MigrationExecutor(router,
                           handoff_dir=str(tmp_path / 'handoff'))
    assert ex.migrate([doc], src, dst)['docs'] == [doc]
    cd = SidecarClient(sock_path=fleet.replicas[src])
    try:
        cd._max_redirects = 1
        with pytest.raises(WrongReplicaError) as ei:
            cd.get_patch(doc)
        assert ei.value.owner == dst
        assert isinstance(ei.value.ring_version, int)
    finally:
        cd.close()
    assert _flat().get('sidecar.client.redirects', 0) >= 1
    assert _flat().get('migrate.wrong_replica', 0) >= 1


def test_subscriber_resync_handoff_across_migration(fleet, tmp_path):
    doc = 'sub-doc'
    router = fleet.router
    with SidecarClient(sock_path=fleet.router_path) as w, \
            SidecarClient(sock_path=fleet.router_path) as sub:
        w.apply_changes(doc, [change('a', 1)])
        sub.subscribe(doc, peer='alice')
        src = router.ring.owner(doc)
        dst = 'r1' if src == 'r0' else 'r0'
        ex = MigrationExecutor(router,
                               handoff_dir=str(tmp_path / 'handoff'))
        assert ex.migrate([doc], src, dst)['docs'] == [doc]
        w.apply_changes(doc, [change('a', 2)])
        e = sub.next_event(timeout=30)
        while e is not None and not (e['event'] == 'change'
                                     and e['clock'] == {'a': 2}):
            e = sub.next_event(timeout=10)
        assert e is not None, \
            'subscription must survive migration via resync handoff'
    assert _flat().get('router.resyncs', 0) >= 1


# ---------------------------------------------------------------------------
# rebalancer planning (pure)
# ---------------------------------------------------------------------------

def _scrape(occ_bytes, top, pressure=0.0):
    return {'capacity': {
        'totals': {'arena_bytes': occ_bytes, 'ops': 0},
        'top': {'arena': top},
        'headroom': {'pressure': pressure}}}


def test_rebalancer_plan_picks_hot_to_cold(tmp_path):
    router = type('R', (), {'replicas': {'r0': '', 'r1': ''}})()
    rb = Rebalancer(router, executor=object(), interval_s=999,
                    topk=2, min_skew=0.5, pressure=0.8)
    hot = [{'doc': 'h%d' % i, 'arena_bytes': 1000 - i, 'ops': 0,
            'subscribers': 0} for i in range(6)]
    plan = rb.plan({'r0': _scrape(10000, hot),
                    'r1': _scrape(100, [])})
    assert plan is not None
    src, dst, victims = plan
    assert (src, dst) == ('r0', 'r1')
    assert victims == ['h0', 'h1']
    # balanced fleet: no plan
    assert rb.plan({'r0': _scrape(1000, hot),
                    'r1': _scrape(990, [])}) is None
    # pressure overrides skew
    assert rb.plan({'r0': _scrape(1000, hot, pressure=0.95),
                    'r1': _scrape(990, [])}) is not None


# ---------------------------------------------------------------------------
# ColdStore concurrency regression (the put_many/manifest race)
# ---------------------------------------------------------------------------

def test_coldstore_concurrent_put_many_manifest_safe(tmp_path):
    """Migration threads + WAL compaction race put_many/discard; the
    manifest must stay consistent with the blobs for a FRESH durable
    recovery."""
    path = str(tmp_path / 'cold')
    store = ColdStore(path, durable=True)
    errors = []

    def writer(w):
        try:
            for i in range(20):
                store.put_many({'w%d-doc%d' % (w, i):
                                b'blob-%d-%d' % (w, i)})
                if i % 5 == 4:
                    store.discard('w%d-doc%d' % (w, i - 2))
        except Exception as e:          # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    fresh = ColdStore(path, durable=True)
    assert set(fresh.doc_ids()) == set(store.doc_ids())
    for d in fresh.doc_ids():
        assert fresh.get(d) == store.get(d)


# ---------------------------------------------------------------------------
# 3-replica live end-to-end lane
# ---------------------------------------------------------------------------

def test_three_replica_e2e_with_live_migration(tmp_path):
    """Concurrent writers through the router while their docs migrate
    mid-stream: every op acks exactly once, per-doc history complete
    and in order afterwards."""
    f = Fleet(tmp_path, n=3)
    try:
        router = f.router
        docs = ['e2e-%d' % i for i in range(6)]
        n_seq = 12
        acks = {d: [] for d in docs}
        errors = []

        def writer(d):
            try:
                with SidecarClient(sock_path=f.router_path) as c:
                    for seq in range(1, n_seq + 1):
                        r = c.apply_changes(d, [change('w', seq)])
                        acks[d].append(r['clock']['w'])
            except Exception as e:      # noqa: BLE001
                errors.append((d, e))

        threads = [threading.Thread(target=writer, args=(d,))
                   for d in docs]
        for t in threads:
            t.start()
        # migrate each doc once, mid-stream, round-robin to the
        # next replica over
        ex = MigrationExecutor(router,
                               handoff_dir=str(tmp_path / 'handoff'),
                               timeout_s=30.0)
        time.sleep(0.05)
        for i, d in enumerate(docs):
            src = router.ring.owner(d)
            others = [r for r in sorted(f.replicas) if r != src]
            res = ex.migrate([d], src, others[i % len(others)])
            assert not res['failed'], res
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        # exactly-once, in-order acks; complete history on the owner
        with SidecarClient(sock_path=f.router_path) as c:
            for d in docs:
                assert acks[d] == list(range(1, n_seq + 1)), \
                    (d, acks[d])
                assert c.get_patch(d)['clock'] == {'w': n_seq}
        flat = _flat()
        assert flat.get('migrate.migrations', 0) == len(docs)
        assert flat.get('migrate.failed', 0) == 0
    finally:
        f.stop()
