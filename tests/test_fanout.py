"""Batched sync fan-out (ISSUE 9): byte-parity of the vectorized
(peer x doc) clock-matrix engine against a serial per-`Connection`
replay, encode-once reuse accounting, straggler/reconnect backfills,
quarantine envelopes, presence piggybacking, and the gateway wiring --
plus the satellite fixes (in-place per-doc clock_union, DocSet dirty
-doc draining).
"""

import json
import os
import tempfile
import threading
import time

import pytest

import automerge_tpu.backend as Backend
import automerge_tpu.frontend as Frontend
from automerge_tpu import telemetry
from automerge_tpu.native import NativeDocPool
from automerge_tpu.sync.connection import Connection, clock_union
from automerge_tpu.sync.doc_set import DocSet
from automerge_tpu.sync.fanout import (FanoutEngine, classify_scalar,
                                       classify_vector)

ROOT = '00000000-0000-0000-0000-000000000000'
DOC = 'fan-doc'


@pytest.fixture(autouse=True)
def _telemetry_hygiene():
    """The live-gateway lanes observe registry histograms
    (occupancy, fanout latency) that later suites assert fresh counts
    on -- leave the whole registry as a fresh process would."""
    yield
    telemetry.reset_all()


def ch(actor, seq, key, value, deps=None):
    return {'actor': actor, 'seq': seq, 'deps': dict(deps or {}),
            'ops': [{'action': 'set', 'obj': ROOT, 'key': key,
                     'value': value}]}


def canon(changes):
    return json.dumps(changes, sort_keys=True, default=str)


def history(n_actors=3, seqs=4):
    """Concurrent multi-actor history: every change causally ready."""
    out = []
    for s in range(1, seqs + 1):
        for a in range(n_actors):
            out.append(ch('a%d' % a, s, 'k%d' % a, s * 10 + a))
    return out


def peer_clocks(n):
    """n peers with empty / stale / divergent / exact clocks."""
    clocks = {}
    full = {'a0': 4, 'a1': 4, 'a2': 4}
    for i in range(n):
        kind = i % 4
        if kind == 0:
            clocks['p%03d' % i] = {}
        elif kind == 1:
            clocks['p%03d' % i] = {'a0': 1 + i % 3}
        elif kind == 2:
            clocks['p%03d' % i] = {'a0': 2, 'a1': 3, 'a2': 1 + i % 2}
        else:
            clocks['p%03d' % i] = dict(full)
    return clocks


class EngineHarness(object):
    """FanoutEngine over a real NativeDocPool with captured frames."""

    def __init__(self):
        self.pool = NativeDocPool()
        self.engine = FanoutEngine(
            self.pool, lambda obj: (json.dumps(obj) + '\n').encode())
        self.frames = {}

    def send_for(self, peer):
        def send(buf):
            self.frames.setdefault(peer, []).append(buf)
        return send

    def subscribe(self, peer, clock, doc=DOC, **kw):
        return self.engine.subscribe((1, peer), doc, clock,
                                     self.send_for(peer), **kw)

    def apply_and_flush(self, batch, doc=DOC, origins=None):
        res = self.pool.apply_changes(doc, batch)
        self.engine.on_flush({doc: res['clock']},
                             enq={doc: time.perf_counter()},
                             origins=origins)
        return res

    def received(self, peer, backfill=()):
        out = list(backfill)
        for buf in self.frames.get(peer, ()):
            frame = json.loads(buf)
            if frame.get('event') == 'change':
                out.extend(frame['changes'])
        return out


def serial_replay(hist, clocks, batches):
    """The reference shape: one `Connection` per peer over a DocSet,
    every mutation fanned through the per-peer scalar handler chain.
    Returns {peer: [change, ...]} in delivery order."""
    ds = DocSet()
    if hist:
        ds.apply_changes(DOC, hist)
    sent = {}
    for peer, clock in clocks.items():
        msgs = []
        sent[peer] = msgs
        conn = Connection(ds, msgs.append)
        conn.open()
        # the peer advertises its clock; the connection answers with
        # exactly the changes it is missing (connection.js:91-108)
        conn.receive_msg({'docId': DOC, 'clock': dict(clock)})
    for batch in batches:
        ds.apply_changes(DOC, batch)
    return {peer: [c for m in msgs if m.get('changes')
                   for c in m['changes']]
            for peer, msgs in sent.items()}


# ---------------------------------------------------------------------------
# byte-parity lane: batched fan-out vs serial per-Connection replay
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('vector', [True, False],
                         ids=['vectorized', 'scalar-oracle'])
def test_parity_batched_vs_serial_replay(vector, monkeypatch):
    """50+ peers with divergent/stale/empty/exact clocks across several
    flushes: every peer's received-change stream is byte-identical to
    the serial per-Connection replay of the same traffic."""
    monkeypatch.setenv('AMTPU_FANOUT_VECTOR', '1' if vector else '0')
    hist = history()
    clocks = peer_clocks(56)
    batches = [
        [ch('a0', 5, 'k0', 50), ch('a1', 5, 'k1', 51)],
        [ch('w', 1, 'w', 1)],
        [ch('a2', 5, 'k2', 52), ch('w', 2, 'w', 2)],
    ]
    h = EngineHarness()
    h.pool.apply_changes(DOC, hist)
    backfills = {p: h.subscribe(p, c)['changes']
                 for p, c in clocks.items()}
    for batch in batches:
        h.apply_and_flush(batch)
    expected = serial_replay(hist, clocks, batches)
    for peer in clocks:
        got = h.received(peer, backfills[peer])
        assert canon(got) == canon(expected[peer]), \
            'received-change divergence for %s (clock %r)' \
            % (peer, clocks[peer])
    snap = telemetry.metrics_snapshot()
    key = 'sync.fanout.%s_passes' % ('vector' if vector else 'scalar')
    assert snap.get(key, 0) >= len(batches)


def test_vector_scalar_classify_identical():
    """The two classification kernels agree bitwise on random clock
    matrices (the A/B arms compute the same thing)."""
    import numpy as np
    rng = np.random.RandomState(7)
    for _ in range(20):
        n, a = rng.randint(1, 40), rng.randint(1, 9)
        post = rng.randint(0, 5, size=(n, a)).astype(np.int64)
        pre = np.maximum(post - rng.randint(0, 3, size=(n, a)), 0)
        bel = np.maximum(post - rng.randint(0, 4, size=(n, a)), 0)
        bv, ev = classify_vector(bel, pre, post)
        bs, es = classify_scalar(bel, pre, post)
        assert (bv == bs).all() and (ev == es).all()


# ---------------------------------------------------------------------------
# encode-once coalescing
# ---------------------------------------------------------------------------

def test_encode_once_reuse_counts_and_shared_bytes():
    h = EngineHarness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1)])
    telemetry.metrics_reset()
    n = 60
    for i in range(n):
        h.subscribe('p%02d' % i, {'a': 1})
    h.apply_and_flush([ch('a', 2, 'k', 2)])
    bufs = {p: h.frames[p][-1] for p in h.frames}
    assert len(bufs) == n
    assert len(set(bufs.values())) == 1, \
        'coalesced subscribers received different bytes'
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.encode_reuse'] == n - 1
    assert snap['sync.fanout.coalesced_peers'] == n
    assert snap.get('sync.fanout.straggler_peers', 0) == 0
    # amplification: one encode, n sends
    assert snap['sync.fanout.bytes_on_wire'] == \
        n * snap['sync.fanout.bytes_encoded']


def test_straggler_gets_filtered_delta_not_coalesced_bytes():
    h = EngineHarness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1), ch('a', 2, 'k', 2)])
    h.subscribe('fresh', {'a': 2})
    # straggler registers at a stale clock with no backfill
    h.subscribe('stale', {'a': 1}, backfill=False)
    h.apply_and_flush([ch('b', 1, 'k2', 9)])
    fresh = json.loads(h.frames['fresh'][-1])
    stale = json.loads(h.frames['stale'][-1])
    assert [(c['actor'], c['seq']) for c in fresh['changes']] == \
        [('b', 1)]
    assert sorted((c['actor'], c['seq']) for c in stale['changes']) == \
        [('a', 2), ('b', 1)]
    # both now converged: the next flush coalesces them together
    telemetry.metrics_reset()
    h.apply_and_flush([ch('b', 2, 'k2', 10)])
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.coalesced_peers'] == 2
    assert snap['sync.fanout.encode_reuse'] == 1


def test_reconnect_mid_flush_full_backfill():
    """A peer that lost its connection re-subscribes (stale clock)
    between a mutation and the flush pass: its backfill is complete and
    the other subscribers still receive the flush's delta."""
    h = EngineHarness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1)])
    h.subscribe('steady', {'a': 1})
    h.subscribe('flaky', {'a': 1})
    h.engine.drop_conn(1)               # connection died entirely
    h.frames.clear()
    # the mutation lands, and BEFORE its on_flush the peers return
    res = h.pool.apply_changes(DOC, [ch('a', 2, 'k', 2)])
    back_flaky = h.subscribe('flaky', {'a': 1})
    back_steady = h.subscribe('steady', {'a': 1})
    # full backfill, not a coalesced delta that assumes pre-drop state
    assert [(c['actor'], c['seq']) for c in back_flaky['changes']] == \
        [('a', 2)]
    assert back_flaky['clock'] == {'a': 2} == back_steady['clock']
    h.engine.on_flush({DOC: res['clock']})
    # flush after the re-subscribe: nobody is behind, nothing resent
    assert not h.frames.get('flaky') and not h.frames.get('steady')
    # and the engine keeps serving subsequent flushes
    h.apply_and_flush([ch('a', 3, 'k', 3)])
    assert [(c['actor'], c['seq'])
            for c in json.loads(h.frames['flaky'][-1])['changes']] == \
        [('a', 3)]


def test_echo_suppression_via_origins():
    h = EngineHarness()
    h.subscribe('writer', {})                      # conn id 1
    h.engine.subscribe((2, 'reader'), DOC, {},     # a DIFFERENT conn
                       h.send_for('reader'))
    h.apply_and_flush([ch('w', 1, 'k', 1)],
                      origins={DOC: [(1, {'w': 1})]})
    # origins carries the writer's OWN connection id (1): no echo
    assert 'writer' not in h.frames
    assert [(c['actor'], c['seq'])
            for c in json.loads(h.frames['reader'][-1])['changes']] == \
        [('w', 1)]


def test_shared_transport_ships_copies_in_one_write():
    """Peers registered with the SAME send callable (one connection
    multiplexing many subscriptions -- the gateway passes each conn's
    stable `raw_send`) receive their k copies of a coalesced frame as
    ONE write of k concatenated frames."""
    h = EngineHarness()
    calls = []
    shared = calls.append
    for i in range(3):
        h.engine.subscribe((1, 'm%d' % i), DOC, {}, shared)
    h.engine.subscribe((2, 'solo'), DOC, {}, h.send_for('solo'))
    h.apply_and_flush([ch('a', 1, 'k', 1)])
    assert len(calls) == 1, 'expected ONE write for the shared conn'
    single = h.frames['solo'][-1]
    assert calls[0] == single * 3, 'shared write is not k frames'
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.coalesced_peers'] == 4
    assert snap['sync.fanout.encode_reuse'] == 3


def test_gateway_conn_transport_is_stable():
    """The _Conn transport the gateway hands the engine must be ONE
    stable object per connection, or the write-grouping above can
    never engage -- since ISSUE 13 that transport IS the bounded
    egress queue (identity-stable by construction; a bound-method
    access would mint a new object per call)."""
    from automerge_tpu.scheduler.gateway import _Conn

    class _Sock(object):
        def makefile(self, mode):
            import io
            return io.BytesIO()

    conn = _Conn(_Sock(), gateway=None, cid=1)
    assert conn.egress is conn.egress
    assert callable(conn.egress.stage)
    # and no writer thread was spawned for a connection that never
    # staged a frame (lazy start)
    assert conn.egress._thread is None


def test_exec_path_quarantine_still_fans_envelope():
    """A quarantine surfaced through a SINGLE-doc entry point (serial
    fallback replay, apply_local_change) is recognized from its raise
    contract and still fans the envelope -- not silence."""
    from automerge_tpu.native import _raise_if_quarantined
    from automerge_tpu.resilience import is_quarantine_error
    from automerge_tpu.errors import AutomergeError
    # the raise contract round-trips through the protocol error shape
    with pytest.raises(AutomergeError) as ei:
        _raise_if_quarantined('d', {'error': 'device poisoned',
                                    'errorType': 'AutomergeError'})
    resp = {'id': 1, 'error': str(ei.value),
            'errorType': 'AutomergeError'}
    assert is_quarantine_error(resp)
    assert not is_quarantine_error({'id': 1, 'error': 'bad seq',
                                    'errorType': 'RangeError'})
    assert not is_quarantine_error({'id': 1, 'error': 'plain failure',
                                    'errorType': 'AutomergeError'})

    # drive the gateway exec path with a backend that answers the
    # quarantine raise shape: subscribers get the quarantined frame
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.scheduler.queue import PendingOp

    class _QuarantineBackend(object):
        class pool(object):
            pass

        def handle(self, req):
            return {'id': req.get('id'), 'error': str(ei.value),
                    'errorType': 'AutomergeError'}

    class _FakeConn(object):
        cid = 7
        sent = None

        def send(self, resp):
            self.sent = resp

    gw = GatewayServer.__new__(GatewayServer)
    gw.backend = _QuarantineBackend()
    h = EngineHarness()
    gw.fanout = h.engine
    h.subscribe('watcher', {})
    fan = {'updates': {}, 'quarantined': {}, 'enq': {},
           'origins': {}}
    conn = _FakeConn()
    op = PendingOp(conn, 1, 'apply_changes',
                   {'id': 1, 'cmd': 'apply_changes', 'doc': DOC,
                    'changes': [ch('a', 1, 'k', 1)]},
                   (DOC,), 1, batchable=True)

    class _NoQueue(object):
        def note_complete(self, op):
            pass

    gw.queue = _NoQueue()
    gw._run_exec(op, count=False, fan=fan)
    assert conn.sent['errorType'] == 'AutomergeError'
    assert DOC in fan['quarantined'], \
        'exec-path quarantine not recorded for fan-out'
    h.engine.on_flush(fan['updates'], fan['quarantined'], fan['enq'])
    frame = json.loads(h.frames['watcher'][-1])
    assert frame['event'] == 'quarantined'


def test_matrix_growth_rows_and_columns():
    """Amortized-doubling growth of both matrix axes: many actors in
    one subscribe clock (column growth mid-call), many subscriptions
    (row growth), and growth-while-classifying flushes."""
    h = EngineHarness()
    big_clock = {'x%02d' % i: 1 for i in range(20)}
    h.subscribe('cold', big_clock)          # 20 actors into cap 8
    for i in range(40):                     # 41 rows into cap 8
        h.subscribe('p%02d' % i, {})
    for s in range(1, 4):                   # new actor per flush
        h.apply_and_flush([ch('y%02d' % s, 1, 'k', s)])
    stats = h.engine.healthz_section()
    assert stats['actors'] == 23
    assert stats['live_subscriptions'] == 41
    # every empty-clock subscriber saw every flush
    for i in range(40):
        got = [c['actor']
               for buf in h.frames['p%02d' % i]
               for c in json.loads(buf)['changes']]
        assert got == ['y01', 'y02', 'y03']


# ---------------------------------------------------------------------------
# quarantine + presence
# ---------------------------------------------------------------------------

def test_quarantined_doc_fans_envelope_not_silence():
    h = EngineHarness()
    h.subscribe('p1', {})
    h.subscribe('p2', {})
    env = {'error': 'poisoned device batch',
           'errorType': 'AutomergeError'}
    h.engine.on_flush({}, quarantined={DOC: env})
    for p in ('p1', 'p2'):
        frame = json.loads(h.frames[p][-1])
        assert frame['event'] == 'quarantined'
        assert frame['error'] == env['error']
        assert frame['errorType'] == env['errorType']


def test_presence_piggybacks_and_presence_only_frames():
    h = EngineHarness()
    h.subscribe('p1', {})
    h.subscribe('p2', {})
    h.engine.presence((1, 'p1'), DOC, {'cursor': 11})
    h.apply_and_flush([ch('a', 1, 'k', 1)])
    frame = json.loads(h.frames['p2'][-1])
    assert frame['event'] == 'change'
    assert frame['presence'] == {'1/p1': {'cursor': 11}}
    # presence-only flush: no mutation, ephemeral state still ships
    h.engine.presence((1, 'p2'), DOC, {'cursor': 3})
    h.engine.on_flush({})
    frame = json.loads(h.frames['p1'][-1])
    assert frame['event'] == 'presence'
    assert frame['presence'] == {'1/p2': {'cursor': 3}}
    # AMTPU_FANOUT_PRESENCE=0 sheds server-side
    os.environ['AMTPU_FANOUT_PRESENCE'] = '0'
    try:
        assert h.engine.presence((1, 'p1'), DOC, {'x': 1}).get('shed')
    finally:
        del os.environ['AMTPU_FANOUT_PRESENCE']


def test_unsubscribe_and_drop_conn_stop_frames():
    h = EngineHarness()
    h.subscribe('p1', {})
    other = h.engine.subscribe((2, 'p2'), DOC, {}, h.send_for('p2'))
    assert other['clock'] == {}
    h.engine.unsubscribe((1, 'p1'), DOC)
    h.engine.drop_conn(2)
    h.apply_and_flush([ch('a', 1, 'k', 1)])
    assert not h.frames
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.drops', 0) >= 1


# ---------------------------------------------------------------------------
# gateway wiring (live socket server)
# ---------------------------------------------------------------------------

def _next_change(client, timeout=30):
    while True:
        e = client.next_event(timeout=timeout)
        if e is None or e['event'] == 'change':
            return e


def test_gateway_fanout_end_to_end(tmp_path):
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.sidecar.server import SidecarBackend
    path = str(tmp_path / 'gw-fan.sock')
    os.environ['AMTPU_FLUSH_DEADLINE_MS'] = '5'
    gw = GatewayServer(path, backend=SidecarBackend()).start()
    try:
        sub = SidecarClient(sock_path=path)
        w = SidecarClient(sock_path=path)
        w.apply_changes('gdoc', [ch('w', 1, 'k', 1)])
        r = sub.subscribe('gdoc', peer='alice')
        assert r['clock'] == {'w': 1} and len(r['changes']) == 1
        w.subscribe('gdoc', peer='writer')
        w.apply_changes('gdoc', [ch('w', 2, 'k', 2)])
        e = _next_change(sub)
        assert e['doc'] == 'gdoc' and e['clock'] == {'w': 2}
        assert [(c['actor'], c['seq']) for c in e['changes']] == \
            [('w', 2)]
        # the writer's own connection is echo-suppressed
        assert _next_change(w, timeout=1.0) is None
        # presence roundtrip
        sub.presence('gdoc', {'cursor': 4}, peer='alice')
        pe = w.next_event(timeout=30)
        assert pe['event'] == 'presence' \
            and pe['presence']['1/alice'] == {'cursor': 4}
        # fanout healthz section is live
        h = w.healthz()
        assert h['fanout']['live_subscriptions'] == 2
        assert h['fanout'].get('frames', 0) >= 1
        assert h['fanout']['latency_ms'].get('count', 0) >= 1
        sub.close()
        w.close()
    finally:
        gw.stop()
        del os.environ['AMTPU_FLUSH_DEADLINE_MS']


def test_gateway_fanout_disabled_answers_typed_error(tmp_path):
    from automerge_tpu.errors import RangeError
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.sidecar.server import SidecarBackend
    path = str(tmp_path / 'gw-nofan.sock')
    os.environ['AMTPU_FANOUT'] = '0'
    try:
        gw = GatewayServer(path, backend=SidecarBackend()).start()
    finally:
        del os.environ['AMTPU_FANOUT']
    try:
        with SidecarClient(sock_path=path) as c:
            with pytest.raises(RangeError):
                c.subscribe('d', peer='x')
            # the mutation path is unaffected
            p = c.apply_changes('d', [ch('a', 1, 'k', 1)])
            assert p['clock'] == {'a': 1}
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# satellites: Connection clock-map + DocSet dirty set
# ---------------------------------------------------------------------------

def test_clock_union_updates_in_place_with_per_doc_isolation():
    cm = {}
    out = clock_union(cm, 'd1', {'a': 1})
    assert out is cm and cm == {'d1': {'a': 1}}
    before = cm['d1']
    clock_union(cm, 'd2', {'b': 2})
    assert cm['d1'] is before          # other docs untouched
    clock_union(cm, 'd1', {'a': 3, 'c': 1})
    assert cm['d1'] == {'a': 3, 'c': 1}
    assert before == {'a': 1}          # per-doc isolation: the old
    # entry object is not mutated (messages may still reference it)


def test_docset_dirty_drain_per_flush():
    ds = DocSet()
    assert ds.drain_dirty() == set()
    ds.apply_changes('d1', [ch('a', 1, 'k', 1)])
    ds.apply_changes('d2', [ch('b', 1, 'k', 1)])
    ds.apply_changes('d1', [ch('a', 2, 'k', 2)])
    assert ds.dirty_docs == {'d1', 'd2'}
    assert ds.drain_dirty() == {'d1', 'd2'}
    assert ds.drain_dirty() == set()   # drained
    ds.apply_changes('d2', [ch('b', 2, 'k', 2)])
    assert ds.drain_dirty() == {'d2'}


def test_connection_open_advertises_all_docs_single_state_fetch():
    ds = DocSet()
    ds.apply_changes('d1', [ch('a', 1, 'k', 1)])
    ds.apply_changes('d2', [ch('b', 1, 'k', 1)])
    fetches = []
    real = Frontend.get_backend_state

    def counting(doc):
        fetches.append(1)
        return real(doc)

    msgs = []
    conn = Connection(ds, msgs.append)
    orig = Frontend.get_backend_state
    Frontend.get_backend_state = counting
    try:
        conn.open()
    finally:
        Frontend.get_backend_state = orig
    assert len(msgs) == 2              # one advertisement per doc
    assert {m['docId'] for m in msgs} == {'d1', 'd2'}
    assert len(fetches) == 2, \
        'open() fetched backend state more than once per doc'
