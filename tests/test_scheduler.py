"""Serve-gateway tests (ISSUE 5, docs/SERVING.md): admission-queue
semantics, per-doc FIFO claims, coalesced-flush response routing (with
quarantine), and the live multi-connection gateway over a real unix
socket -- including a single SidecarClient shared across threads (the
client demultiplexes out-of-order responses by id).
"""

import os
import tempfile
import threading
import time

import pytest

from automerge_tpu import faults, telemetry
from automerge_tpu.errors import OverloadedError
from automerge_tpu.scheduler import AdmissionQueue, GatewayServer
from automerge_tpu.scheduler.queue import Overloaded, PendingOp
from automerge_tpu.sidecar.client import SidecarClient

ROOT_ID = '00000000-0000-0000-0000-000000000000'
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _hygiene():
    faults.disarm()
    telemetry.metrics_reset()
    yield
    faults.disarm()
    telemetry.metrics_reset()


def change(actor, seq, key='k', value=None, n_ops=1):
    return {'actor': actor, 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID,
                     'key': '%s%d' % (key, i),
                     'value': value if value is not None
                     else '%s-%d' % (actor, seq)}
                    for i in range(n_ops)]}


class FakeConn(object):
    """Captures responses the gateway would write to a socket."""

    def __init__(self):
        self.sent = []
        self.closed = False

    def send(self, resp):
        self.sent.append(resp)

    def by_id(self, rid):
        return next(r for r in self.sent if r.get('id') == rid)


def op(conn, rid, doc, changes, cmd='apply_changes'):
    req = {'id': rid, 'cmd': cmd, 'doc': doc, 'changes': changes}
    return PendingOp(conn, rid, cmd, req, (doc,), len(changes),
                     batchable=True)


class TestAdmissionQueue:
    def test_watermark_shedding_and_recovery(self):
        q = AdmissionQueue(max_ops=4, low_frac=0.5)
        conn = FakeConn()
        q.offer(op(conn, 1, 'a', [change('a', 1)]))
        q.offer(op(conn, 2, 'b', [change('b', 1), change('b', 2)]))
        # 3 queued ops; the next 2-op offer would cross max=4: shed
        with pytest.raises(Overloaded) as ei:
            q.offer(op(conn, 3, 'c', [change('c', 1), change('c', 2)]))
        assert ei.value.retry_after_ms >= 1
        assert q.shedding
        # shedding latches: even a 1-op offer is refused until drain
        with pytest.raises(Overloaded):
            q.offer(op(conn, 4, 'd', [change('d', 1)]))
        batch, execs = q.claim()
        assert [o.rid for o in batch] == [1, 2] and not execs
        # drained below low watermark (depth 0 <= 2): admission resumes
        q.offer(op(conn, 5, 'e', [change('e', 1)]))
        assert not q.shedding
        assert telemetry.metrics_snapshot()['scheduler.shed'] == 2

    def test_reads_admitted_while_shedding(self):
        q = AdmissionQueue(max_ops=1, low_frac=0.0)
        conn = FakeConn()
        q.offer(op(conn, 1, 'a', [change('a', 1)]))
        with pytest.raises(Overloaded):
            q.offer(op(conn, 2, 'b', [change('b', 1)]))
        read = PendingOp(conn, 3, 'get_patch',
                         {'id': 3, 'cmd': 'get_patch', 'doc': 'a'},
                         ('a',), 1, batchable=False)
        q.offer(read, admit_always=True)     # never shed
        batch, execs = q.claim()
        assert [o.rid for o in batch] == [1] and not execs
        # the read parks behind its doc's write, then claims as exec
        _, execs2 = q.claim()
        assert [o.rid for o in execs2] == [3]

    def test_per_doc_fifo_parks_followers(self):
        q = AdmissionQueue(max_ops=100)
        conn = FakeConn()
        q.offer(op(conn, 1, 'a', [change('a', 1)]))
        q.offer(op(conn, 2, 'a', [change('a', 2)]))   # same doc: parks
        q.offer(op(conn, 3, 'b', [change('b', 1)]))
        batch, execs = q.claim()
        assert [o.rid for o in batch] == [1, 3]
        assert q.doc_pending('a') and q.doc_pending('b')
        # rid 2 waits for the next flush, after its doc's batch
        batch2, _ = q.claim()
        assert [o.rid for o in batch2] == [2]
        assert telemetry.metrics_snapshot()['scheduler.parked'] == 1

    def test_parked_doc_blocks_later_multi_doc_op(self):
        """An apply_batch whose doc set overlaps a parked doc must park
        too, and its OTHER docs must then block later ops -- cross-doc
        reordering never reorders one doc's ops."""
        q = AdmissionQueue(max_ops=100)
        conn = FakeConn()
        q.offer(op(conn, 1, 'a', [change('a', 1)]))
        q.offer(op(conn, 2, 'a', [change('a', 2)]))
        multi = PendingOp(conn, 3, 'apply_batch',
                          {'id': 3, 'cmd': 'apply_batch',
                           'docs': {'a': [change('x', 1)],
                                    'b': [change('x', 1)]}},
                          ('a', 'b'), 2, batchable=True)
        q.offer(multi)
        q.offer(op(conn, 4, 'b', [change('b', 1)]))
        batch, _ = q.claim()
        assert [o.rid for o in batch] == [1]     # everyone else parked
        batch2, _ = q.claim()
        assert [o.rid for o in batch2] == [2]
        batch3, _ = q.claim()
        assert [o.rid for o in batch3] == [3]
        batch4, _ = q.claim()
        assert [o.rid for o in batch4] == [4]

    def test_doc_cap_closes_the_window(self):
        q = AdmissionQueue(max_ops=100)
        conn = FakeConn()
        for i in range(5):
            q.offer(op(conn, i, 'd%d' % i, [change('a', 1)]))
        batch, _ = q.claim(max_docs=3)
        assert len(batch) == 3
        batch2, _ = q.claim(max_docs=3)
        assert len(batch2) == 2

    def test_oversized_op_claims_alone(self):
        """Caps bound ADDITIONAL coalescing: an op bigger than the
        per-flush op cap must still claim into an empty flush (parking
        it forever would wedge its doc and hot-spin the dispatcher)."""
        q = AdmissionQueue(max_ops=1000)
        conn = FakeConn()
        big = [change('a', s) for s in range(1, 11)]     # 10 ops
        q.offer(op(conn, 1, 'big', big))
        q.offer(op(conn, 2, 'small', [change('b', 1)]))
        batch, _ = q.claim(max_ops=4)
        assert [o.rid for o in batch] == [1]     # alone, over the cap
        batch2, _ = q.claim(max_ops=4)
        assert [o.rid for o in batch2] == [2]

    def test_single_request_larger_than_queue_admitted_when_empty(self):
        """The watermark bounds backlog, not request size: a lone
        request bigger than the whole queue is admitted (the serial
        loop accepts it too) and served as its own flush."""
        q = AdmissionQueue(max_ops=4)
        conn = FakeConn()
        huge = [change('a', s) for s in range(1, 9)]     # 8 > max 4
        q.offer(op(conn, 1, 'huge', huge))               # empty: admit
        with pytest.raises(Overloaded):                  # backlog: shed
            q.offer(op(conn, 2, 'x', [change('b', 1)]))
        batch, _ = q.claim()
        assert [o.rid for o in batch] == [1]
        assert q.depth_ops == 0


class TestFlushRouting:
    """Deterministic dispatcher semantics: submit through the routing
    layer with the dispatcher thread NOT running, then claim + flush by
    hand."""

    def _gateway(self, **qkw):
        path = os.path.join(tempfile.mkdtemp(), 'gw.sock')
        return GatewayServer(path, queue=AdmissionQueue(**qkw))

    def test_coalesced_flush_routes_by_conn_and_id(self):
        gw = self._gateway()
        conns = [FakeConn() for _ in range(3)]
        for i, conn in enumerate(conns):
            gw.submit(conn, {'id': 10 + i, 'cmd': 'apply_changes',
                             'doc': 'doc-%d' % i,
                             'changes': [change('a%d' % i, 1)]})
        batch, execs = gw.queue.claim()
        assert len(batch) == 3 and not execs
        gw._flush(batch, execs)
        for i, conn in enumerate(conns):
            resp = conn.by_id(10 + i)
            assert resp['result']['clock'] == {'a%d' % i: 1}
        # the flush was ONE pool batch of 3 docs
        assert telemetry.BATCH_OCCUPANCY.summary()['count'] == 1
        snap = telemetry.metrics_snapshot()
        assert snap['scheduler.batched_docs'] == 3
        assert snap['scheduler.coalesced_ops'] == 3
        assert telemetry.QUEUE_WAIT.summary()['count'] == 3
        from automerge_tpu.native import live_batch_handles
        assert live_batch_handles() == 0

    def test_batched_patch_matches_serial_patch(self):
        from automerge_tpu.native import NativeDocPool
        gw = self._gateway()
        conn = FakeConn()
        streams = {'d%d' % i: [change('w%d' % i, 1, n_ops=3),
                               change('w%d' % i, 2, n_ops=2)]
                   for i in range(6)}
        rid = 0
        for r in range(2):
            for doc, chs in streams.items():
                rid += 1
                gw.submit(conn, {'id': rid, 'cmd': 'apply_changes',
                                 'doc': doc, 'changes': [chs[r]]})
            gw._flush(*gw.queue.claim())
        serial = NativeDocPool()
        want = {}
        for doc, chs in streams.items():
            for ch in chs:
                want[doc] = serial.apply_changes(doc, [ch])
        # the SECOND round's responses must equal serial application
        got = {r: conn.by_id(7 + i) for i, r in enumerate(streams)}
        for i, doc in enumerate(streams):
            assert conn.by_id(7 + i)['result'] == want[doc], doc
        assert got

    def test_read_bypass_vs_queued_read(self):
        gw = self._gateway()
        conn = FakeConn()
        gw.submit(conn, {'id': 1, 'cmd': 'apply_changes', 'doc': 'd',
                         'changes': [change('a', 1)]})
        # pipelined read on the SAME doc: must queue behind the write
        gw.submit(conn, {'id': 2, 'cmd': 'get_patch', 'doc': 'd'})
        # read on an idle doc: answered inline, ahead of the flush
        gw.submit(conn, {'id': 3, 'cmd': 'ping'})
        assert [r['id'] for r in conn.sent] == [3]
        gw._flush(*gw.queue.claim())
        assert [r['id'] for r in conn.sent] == [3, 1]
        # the read parked behind its doc's write; the next flush cycle
        # answers it
        gw._flush(*gw.queue.claim())
        assert [r['id'] for r in conn.sent] == [3, 1, 2]
        # the queued read observed the write (read-your-writes)
        assert conn.by_id(2)['result']['diffs']
        # doc released: the next read bypasses inline
        gw.submit(conn, {'id': 4, 'cmd': 'get_patch', 'doc': 'd'})
        assert conn.sent[-1]['id'] == 4
        assert telemetry.metrics_snapshot()['scheduler.bypass_reads'] \
            == 1
        assert conn.by_id(4)['result'] == conn.by_id(2)['result']

    def test_overload_envelope(self):
        gw = self._gateway(max_ops=2)
        conn = FakeConn()
        gw.submit(conn, {'id': 1, 'cmd': 'apply_changes', 'doc': 'a',
                         'changes': [change('a', 1), change('a', 2)]})
        gw.submit(conn, {'id': 2, 'cmd': 'apply_changes', 'doc': 'b',
                         'changes': [change('b', 1)]})
        resp = conn.by_id(2)
        assert resp['errorType'] == 'Overloaded'
        assert resp['retryAfterMs'] >= 1
        # the admitted request is untouched by the shed
        gw._flush(*gw.queue.claim())
        assert 'result' in conn.by_id(1)

    def test_malformed_apply_changes_never_poisons_a_flush(self):
        """A request whose changes payload the merge step could not
        assemble answers its own protocol error inline; coalesced
        siblings are untouched."""
        gw = self._gateway()
        good, bad = FakeConn(), FakeConn()
        gw.submit(bad, {'id': 1, 'cmd': 'apply_changes', 'doc': 'b'})
        resp = bad.by_id(1)
        assert resp['errorType'] in ('RangeError', 'TypeError'), resp
        gw.submit(bad, {'id': 2, 'cmd': 'apply_changes', 'doc': 'b',
                        'changes': 'not-a-list'})
        assert 'error' in bad.by_id(2)
        gw.submit(bad, {'id': 3, 'cmd': 'apply_batch',
                        'docs': {'b': 'not-a-list'}})
        assert 'error' in bad.by_id(3)
        # nothing queued; a healthy sibling flush is unaffected
        gw.submit(good, {'id': 4, 'cmd': 'apply_changes', 'doc': 'g',
                         'changes': [change('x', 1)]})
        batch, execs = gw.queue.claim()
        assert [o.rid for o in batch] == [4] and not execs
        gw._flush(batch, execs)
        assert good.by_id(4)['result']['clock'] == {'x': 1}

    def test_quarantined_doc_answers_only_its_request(self):
        """A doc-pinned permanent fault inside a coalesced flush: the
        poisoned doc's request gets the resilience error envelope, every
        other coalesced request commits (docs/RESILIENCE.md)."""
        gw = self._gateway()
        conns = {d: FakeConn() for d in ('ok-1', 'poison', 'ok-2')}
        for i, doc in enumerate(conns):
            gw.submit(conns[doc], {'id': i, 'cmd': 'apply_changes',
                                   'doc': doc,
                                   'changes': [change('w', 1)]})
        batch, execs = gw.queue.claim()
        assert len(batch) == 3
        faults.arm('native.begin', 'permanent', 1.0, match='poison')
        try:
            gw._flush(batch, execs)
        finally:
            faults.disarm()
        bad = conns['poison'].by_id(1)
        assert bad['errorType'] == 'PermanentFault'
        for doc, rid in (('ok-1', 0), ('ok-2', 2)):
            assert conns[doc].by_id(rid)['result']['clock'] == {'w': 1}
        snap = telemetry.metrics_snapshot()
        assert snap['scheduler.quarantined'] == 1
        assert snap['resilience.quarantined'] == 1

    def test_whole_batch_protocol_error_replays_serially(self):
        """A validation error (inconsistent seq reuse -- the pool's
        whole-batch protocol raise) fails only ITS request after the
        serial replay; sibling requests coalesced into the same flush
        still commit."""
        gw = self._gateway()
        good, bad = FakeConn(), FakeConn()
        gw.submit(bad, {'id': 1, 'cmd': 'apply_changes', 'doc': 'b',
                        'changes': [change('a', 1)]})
        gw._flush(*gw.queue.claim())
        assert 'result' in bad.by_id(1)
        # coalesce a healthy doc with a seq-1 REUSE carrying different
        # content (AutomergeError; protocol errors re-raise whole-batch
        # from the resilient path, post-rollback)
        gw.submit(good, {'id': 2, 'cmd': 'apply_changes', 'doc': 'g',
                         'changes': [change('x', 1)]})
        gw.submit(bad, {'id': 3, 'cmd': 'apply_changes', 'doc': 'b',
                        'changes': [change('a', 1, key='DIFFERENT')]})
        gw._flush(*gw.queue.claim())
        assert good.by_id(2)['result']['clock'] == {'x': 1}
        resp = bad.by_id(3)
        assert 'error' in resp, resp
        assert telemetry.metrics_snapshot()[
            'scheduler.serial_fallback'] == 1
        # the failing doc is intact: its next valid change applies
        gw.submit(bad, {'id': 4, 'cmd': 'apply_changes', 'doc': 'b',
                        'changes': [change('a', 2)]})
        gw._flush(*gw.queue.claim())
        assert bad.by_id(4)['result']['clock'] == {'a': 2}


class TestLiveGateway:
    """End-to-end over a real unix socket with the dispatcher running."""

    def _serve(self):
        path = os.path.join(tempfile.mkdtemp(), 'gw.sock')
        return GatewayServer(path).start(), path

    def test_concurrent_connections_coalesce_and_converge(self):
        gw, path = self._serve()
        try:
            results, errors = {}, []

            def client(i):
                try:
                    with SidecarClient(sock_path=path) as c:
                        doc = 'doc-%02d' % i
                        for s in range(1, 5):
                            p = c.apply_changes(doc, [change(
                                'a%02d' % i, s)])
                            assert p['clock'] == {'a%02d' % i: s}
                        results[i] = c.get_patch(doc)
                except Exception as e:          # surfaced after join
                    errors.append((i, e))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert len(results) == 10
            # serial parity for one stream
            from automerge_tpu.native import NativeDocPool
            ref = NativeDocPool()
            for s in range(1, 5):
                ref.apply_changes('doc-00', [change('a00', s)])
            assert results[0] == ref.get_patch('doc-00')
            # traffic actually coalesced and drained cleanly
            occ = telemetry.BATCH_OCCUPANCY.summary()
            assert occ['count'] >= 1
            snap = telemetry.metrics_snapshot()
            assert snap['scheduler.coalesced_ops'] == 40
            from automerge_tpu.native import live_batch_handles
            assert live_batch_handles() == 0
            health = telemetry.healthz()
            assert health['scheduler']['depth_ops'] == 0
            assert not health['scheduler']['shedding']
        finally:
            gw.stop()

    def test_one_client_shared_across_threads(self):
        """The thread-safety satellite: ONE SidecarClient, many caller
        threads, responses demultiplexed by id."""
        gw, path = self._serve()
        try:
            with SidecarClient(sock_path=path) as c:
                errors = []

                def worker(i):
                    try:
                        doc = 'shared-%d' % i
                        for s in range(1, 4):
                            p = c.apply_changes(doc,
                                                [change('t%d' % i, s)])
                            assert p['clock'] == {'t%d' % i: s}
                        patch = c.get_patch(doc)
                        assert patch['clock'] == {'t%d' % i: 3}
                    except Exception as e:
                        errors.append((i, e))

                threads = [threading.Thread(target=worker, args=(i,))
                           for i in range(8)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
                assert not errors, errors
                assert c.call('ping') == {'ok': True}
        finally:
            gw.stop()

    def test_overloaded_error_type_over_the_wire(self):
        path = os.path.join(tempfile.mkdtemp(), 'gw.sock')
        gw = GatewayServer(path, queue=AdmissionQueue(max_ops=1)).start()
        try:
            with SidecarClient(sock_path=path) as c:
                seen = []

                def push(i):
                    try:
                        c.apply_changes('ov-%d' % i,
                                        [change('a', 1),
                                         change('a', 2)])
                        seen.append('ok')
                    except OverloadedError as e:
                        assert e.retry_after_ms >= 1
                        seen.append('overloaded')

                threads = [threading.Thread(target=push, args=(i,))
                           for i in range(4)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
                assert 'overloaded' in seen
                # the server survives the burst and recovers
                deadline = time.time() + 30
                while time.time() < deadline:
                    try:
                        c.apply_changes('ov-after', [change('z', 1)])
                        break
                    except OverloadedError:
                        time.sleep(0.01)
                else:
                    pytest.fail('gateway never recovered from shed')
                assert c.healthz()['ok']
        finally:
            gw.stop()
