"""Read-path subsystem (ISSUE 20, docs/SERVING.md read path):
patch-mode fan-out byte parity against the serial backend/frontend
oracle across both exec modes, full-state healing for stragglers and
shed peers, quarantine envelopes in patch mode, the frontier-clock
snapshot cache, typed client events, and the live gateway + read
replica wiring.
"""

import base64
import json
import os
import random
import tempfile
import time

import pytest

import automerge_tpu.backend as Backend
import automerge_tpu.frontend as Frontend
from automerge_tpu import telemetry
from automerge_tpu.errors import RangeError
from automerge_tpu.frontend import apply_patch
from automerge_tpu.native import NativeDocPool
from automerge_tpu.readview.events import (ChangeEvent, PatchEvent,
                                           QuarantinedEvent, Snapshot,
                                           typed_event)
from automerge_tpu.readview.snapshot import SnapshotCache
from automerge_tpu.sync.fanout import FanoutEngine

ROOT = '00000000-0000-0000-0000-000000000000'
DOC = 'patch-doc'

#: the patch keys the gateway captures for fan-out (requester-specific
#: actor/seq stripped -- the shared frame must be peer-agnostic)
PATCH_KEYS = ('clock', 'deps', 'canUndo', 'canRedo', 'diffs')


@pytest.fixture(autouse=True)
def _telemetry_hygiene():
    yield
    telemetry.reset_all()


def ch(actor, seq, key, value, deps=None):
    return {'actor': actor, 'seq': seq, 'deps': dict(deps or {}),
            'ops': [{'action': 'set', 'obj': ROOT, 'key': key,
                     'value': value}]}


def canon(obj):
    return json.dumps(obj, sort_keys=True, default=str)


def norm_patch(patch):
    return {k: patch[k] for k in PATCH_KEYS if k in patch}


def fuzz_batches(seed, n_actors=3, n_batches=4):
    """Random causally-ready multi-actor batches (the fuzz surface the
    parity gate runs over)."""
    rng = random.Random(seed)
    seqs = {('a%d' % a): 0 for a in range(n_actors)}
    batches = []
    for _ in range(n_batches):
        batch = []
        for actor in sorted(seqs):
            for _ in range(rng.randint(1, 3)):
                seqs[actor] += 1
                batch.append(ch(actor, seqs[actor],
                                'k%d' % rng.randint(0, 4),
                                rng.randint(0, 99)))
        batches.append(batch)
    return batches


class PatchHarness(object):
    """FanoutEngine over a real pool, staging JSON-lines frames, with
    the gateway's patch capture emulated: each flush hands the pool's
    apply patch (normalized exactly like `GatewayServer._fan_note`)
    into `on_flush(patches=...)`."""

    def __init__(self):
        self.pool = NativeDocPool()
        self.engine = FanoutEngine(
            self.pool, lambda obj: (json.dumps(obj) + '\n').encode())
        self.frames = {}

    def send_for(self, peer):
        def send(buf):
            self.frames.setdefault(peer, []).append(buf)
        return send

    def subscribe(self, peer, clock=None, doc=DOC, **kw):
        return self.engine.subscribe((1, peer), doc, clock or {},
                                     self.send_for(peer), **kw)

    def flush(self, batch, doc=DOC, capture=True):
        res = self.pool.apply_changes(doc, batch)
        self.engine.on_flush(
            {doc: res['clock']}, enq={doc: time.perf_counter()},
            patches={doc: norm_patch(res)} if capture else None)
        return res

    def events(self, peer):
        return [json.loads(buf) for buf in self.frames.get(peer, ())]


def serial_oracle(batches):
    """The reference thin-client shape: a serial backend applies every
    batch; a frontend applies each returned patch.  Returns (per-batch
    normalized patches, final doc dict)."""
    state = Backend.init()
    doc = Frontend.init({'actorId': 'oracle'})
    patches = []
    for batch in batches:
        state, patch = Backend.apply_changes(state, batch)
        patches.append(norm_patch(patch))
        doc = apply_patch(doc, patch)
    return patches, dict(doc)


def thin_view(sub_result, frames):
    """What a patch-mode client materializes: the subscribe backfill
    (full state) then each patch frame in order (`full: true`
    REPLACES the view)."""
    doc = Frontend.init({'actorId': 'thin'})
    if sub_result.get('patch') is not None:
        doc = apply_patch(doc, sub_result['patch'])
    for f in frames:
        if f.get('event') != 'patch':
            continue
        if f.get('full'):
            doc = apply_patch(Frontend.init({'actorId': 'thin'}),
                              f['patch'])
        else:
            doc = apply_patch(doc, f['patch'])
    return dict(doc)


# ---------------------------------------------------------------------------
# patch parity: fanned frames vs the serial backend/frontend oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('vector', [True, False],
                         ids=['vectorized', 'scalar-oracle'])
@pytest.mark.parametrize('seed', [7, 23, 61])
def test_patch_fan_parity_vs_serial_oracle(vector, seed, monkeypatch):
    """N patch-mode peers across fuzzed multi-actor flushes: every
    fanned incremental patch is byte-identical to the serial backend's
    patch for the same batch, and every peer's materialized end state
    is byte-identical to the serial frontend oracle."""
    monkeypatch.setenv('AMTPU_FANOUT_VECTOR', '1' if vector else '0')
    batches = fuzz_batches(seed)
    h = PatchHarness()
    peers = ['p%02d' % i for i in range(8)]
    subs = {p: h.subscribe(p, mode='patch') for p in peers}
    for batch in batches:
        h.flush(batch)
    oracle_patches, oracle_doc = serial_oracle(batches)
    for p in peers:
        evs = h.events(p)
        got = [norm_patch(f['patch']) for f in evs
               if f['event'] == 'patch' and not f['full']]
        assert canon(got) == canon(oracle_patches), \
            'patch stream diverged from the serial oracle for %s' % p
        assert canon(thin_view(subs[p], evs)) == canon(oracle_doc)
    snap = telemetry.metrics_snapshot()
    # one patch frame per flush, fanned to all 8 peers, encoded once
    assert snap['sync.fanout.patch_frames'] == len(batches) * len(peers)
    assert snap['sync.fanout.encode_reuse'] >= \
        len(batches) * (len(peers) - 1)
    key = 'sync.fanout.%s_passes' % ('vector' if vector else 'scalar')
    assert snap.get(key, 0) >= len(batches)


def test_mixed_mode_fan_same_doc():
    """Change-mode and patch-mode subscribers of one doc each get
    their own frame kind from the same flush, both correct."""
    h = PatchHarness()
    fat = h.subscribe('fat')
    thin = h.subscribe('thin', mode='patch')
    assert 'changes' in fat and 'patch' in thin
    batches = [[ch('a', 1, 'k', 1)], [ch('a', 2, 'k', 2, {'a': 1})]]
    for b in batches:
        h.flush(b)
    fat_evs = h.events('fat')
    assert [e['event'] for e in fat_evs] == ['change', 'change']
    got_changes = [c for e in fat_evs for c in e['changes']]
    assert canon(got_changes) == canon([c for b in batches for c in b])
    oracle_patches, oracle_doc = serial_oracle(batches)
    thin_evs = h.events('thin')
    assert [norm_patch(e['patch']) for e in thin_evs] == oracle_patches
    assert canon(thin_view(thin, thin_evs)) == canon(oracle_doc)


def test_patch_subscribe_backfill_and_straggler_full_state():
    """A patch-mode subscriber arriving mid-history gets a full-state
    backfill; one subscribing with `backfill=False` is healed by the
    next flush with a `full: true` frame -- end state identical to the
    oracle either way."""
    batches = fuzz_batches(5, n_batches=2)
    h = PatchHarness()
    h.flush(batches[0])
    # late subscriber: full-state backfill covers batch 0
    late = h.subscribe('late', mode='patch')
    assert late['patch'] is not None
    # straggler: registered at a zero clock with no backfill -> the
    # next flush cannot ship it an incremental patch (it missed
    # nothing-to-batch-0); it must get full state
    h.subscribe('strag', mode='patch', backfill=False)
    h.flush(batches[1])
    _, oracle_doc = serial_oracle(batches)
    assert canon(thin_view(late, h.events('late'))) == \
        canon(oracle_doc)
    strag_evs = h.events('strag')
    assert [e['full'] for e in strag_evs
            if e['event'] == 'patch'] == [True]
    assert canon(thin_view({'patch': None}, strag_evs)) == \
        canon(oracle_doc)
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.patch_full_frames'] >= 1
    assert snap['sync.fanout.straggler_peers'] >= 1


def test_uncaptured_patch_falls_back_to_full_state():
    """A flush with NO captured patch (a `load`-style mutation) still
    serves patch-mode peers -- with a full-state frame, never
    silence."""
    h = PatchHarness()
    sub = h.subscribe('p', mode='patch')
    h.flush([ch('a', 1, 'k', 1)], capture=False)
    evs = h.events('p')
    assert [e.get('full') for e in evs
            if e['event'] == 'patch'] == [True]
    _, oracle_doc = serial_oracle([[ch('a', 1, 'k', 1)]])
    assert canon(thin_view(sub, evs)) == canon(oracle_doc)


def test_quarantine_envelope_in_patch_mode():
    h = PatchHarness()
    h.subscribe('thin', mode='patch')
    env = {'error': 'poisoned device batch',
           'errorType': 'AutomergeError'}
    h.engine.on_flush({}, quarantined={DOC: env})
    frame = h.events('thin')[-1]
    assert frame['event'] == 'quarantined'
    assert frame['error'] == env['error']
    assert frame['errorType'] == env['errorType']


def test_patch_mode_refused_when_disabled(monkeypatch):
    monkeypatch.setenv('AMTPU_READ_PATCH', '0')
    h = PatchHarness()
    with pytest.raises(RangeError):
        h.subscribe('p', mode='patch')
    # change mode unaffected
    assert 'changes' in h.subscribe('p')


def test_invalid_mode_rejected():
    h = PatchHarness()
    with pytest.raises(RangeError):
        h.subscribe('p', mode='delta')


# ---------------------------------------------------------------------------
# shed -> regress -> heal in patch mode (egress tier 1)
# ---------------------------------------------------------------------------

class FakeEgress(object):
    """Egress-shaped transport: frames deliver (on_write) or shed
    (on_drop) under test control, synchronously."""

    def __init__(self):
        self.delivered = []
        self.drop_next = 0

    def stage(self, buf, kind='event', on_write=None, on_drop=None):
        if kind == 'event' and self.drop_next > 0:
            self.drop_next -= 1
            if on_drop is not None:
                on_drop()
            return True
        self.delivered.append(buf)
        if on_write is not None:
            on_write()
        return True

    def events(self):
        return [json.loads(line) for buf in self.delivered
                for line in buf.decode().splitlines()]


def test_patch_shed_regress_heal_parity_vs_never_shed_twin():
    """A patch-mode peer whose frame is tier-1 shed regresses to its
    acked clock and is healed by the next flush with a `full: true`
    frame; its materialized end state is byte-identical to a twin that
    never shed (late, never wrong)."""
    def build():
        pool = NativeDocPool()
        engine = FanoutEngine(
            pool, lambda obj: (json.dumps(obj) + '\n').encode())
        t = FakeEgress()
        return pool, engine, t

    pool_s, eng_s, t_shed = build()
    pool_c, eng_c, t_clean = build()
    sub_s = eng_s.subscribe((1, 'p'), DOC, {}, t_shed, mode='patch')
    sub_c = eng_c.subscribe((1, 'p'), DOC, {}, t_clean, mode='patch')
    batches = [[ch('a', 1, 'k', 1)], [ch('a', 2, 'k', 2, {'a': 1})],
               [ch('b', 1, 'j', 3)]]
    for i, batch in enumerate(batches):
        if i == 1:
            t_shed.drop_next = 1          # tier-1 sheds this flush
        for pool, eng in ((pool_s, eng_s), (pool_c, eng_c)):
            res = pool.apply_changes(DOC, batch)
            eng.on_flush({DOC: res['clock']},
                         enq={DOC: time.perf_counter()},
                         patches={DOC: norm_patch(res)})
    _, oracle_doc = serial_oracle(batches)
    shed_view = thin_view(sub_s, t_shed.events())
    clean_view = thin_view(sub_c, t_clean.events())
    assert canon(shed_view) == canon(clean_view) == canon(oracle_doc)
    # the healing frame replaced state instead of replaying the gap
    fulls = [e for e in t_shed.events()
             if e.get('event') == 'patch' and e.get('full')]
    assert len(fulls) == 1
    snap = telemetry.metrics_snapshot()
    assert snap.get('sync.fanout.regressed_peers', 0) >= 1
    assert snap.get('sync.fanout.patch_full_frames', 0) >= 1


def test_full_patch_memo_reuses_and_invalidates():
    """Patch-mode stragglers and resubscribes at the same frontier
    share ONE get_patch materialization; any mutation invalidates by
    value."""
    h = PatchHarness()
    h.pool.apply_changes(DOC, [ch('a', 1, 'k', 1)])
    h.subscribe('p1', mode='patch')
    h.subscribe('p2', mode='patch')
    h.subscribe('p3', mode='patch')
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.patch_full_builds'] == 1
    assert snap['sync.fanout.patch_full_reuse'] == 2
    h.flush([ch('a', 2, 'k', 2, {'a': 1})])
    r = h.subscribe('p4', mode='patch')
    assert r['patch']['clock'] == {'a': 2}
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.patch_full_builds'] == 2


# ---------------------------------------------------------------------------
# snapshot cache + typed events
# ---------------------------------------------------------------------------

def test_snapshot_cache_hits_invalidation_and_lru():
    cache = SnapshotCache(max_entries=2)
    builds = []

    def build_for(doc, data):
        def build():
            builds.append(doc)
            return data
        return build

    assert cache.get('d1', {'a': 1}, build_for('d1', b'v1')) == b'v1'
    assert cache.get('d1', {'a': 1}, build_for('d1', b'XX')) == b'v1'
    assert builds == ['d1']
    # mutation invalidates by clock value
    assert cache.get('d1', {'a': 2}, build_for('d1', b'v2')) == b'v2'
    assert builds == ['d1', 'd1']
    # LRU: d1 evicted once d2+d3 land
    cache.get('d2', {}, build_for('d2', b'v'))
    cache.get('d3', {}, build_for('d3', b'v'))
    assert len(cache) == 2
    cache.get('d1', {'a': 2}, build_for('d1', b'v2'))
    assert builds.count('d1') == 3
    snap = telemetry.metrics_snapshot()
    assert snap['readview.snapshot_hits'] == 1
    assert snap['readview.snapshot_builds'] == 5


def test_typed_event_factory_and_dict_compat():
    pe = typed_event({'event': 'patch', 'doc': 'd', 'clock': {'a': 1},
                      'patch': {'diffs': []}, 'full': True})
    assert isinstance(pe, PatchEvent) and isinstance(pe, dict)
    assert pe.doc == 'd' and pe.full and pe['event'] == 'patch'
    ce = typed_event({'event': 'change', 'doc': 'd', 'changes': [1]})
    assert isinstance(ce, ChangeEvent) and ce.changes == [1]
    qe = typed_event({'event': 'quarantined', 'doc': 'd',
                      'error': 'x', 'errorType': 'AutomergeError'})
    assert isinstance(qe, QuarantinedEvent) and qe.error_type == \
        'AutomergeError'
    # unknown frames stay plain dicts (forward compatibility)
    plain = typed_event({'event': 'hologram', 'doc': 'd'})
    assert type(plain) is dict
    snap = Snapshot({'doc': 'd', 'clock': {'a': 1},
                     'snapshot_b64':
                     base64.b64encode(b'bytes').decode()})
    assert snap.data == b'bytes' and snap.clock == {'a': 1}


# ---------------------------------------------------------------------------
# live gateway: wire protocol, typed events, snapshot, read replica
# ---------------------------------------------------------------------------

def _live_gateway(tmp_path, monkeypatch):
    from automerge_tpu.scheduler import GatewayServer
    monkeypatch.setenv('AMTPU_FLUSH_DEADLINE_MS', '5')
    path = os.path.join(str(tmp_path), 'gw.sock')
    return GatewayServer(path).start(), path


def test_gateway_patch_mode_and_snapshot_over_the_wire(tmp_path,
                                                       monkeypatch):
    """subscribe(mode='patch') over the socket: typed PatchEvent
    frames, snapshot byte parity with pool.save, get_clock."""
    from automerge_tpu.sidecar.client import SidecarClient
    gw, path = _live_gateway(tmp_path, monkeypatch)
    try:
        w = SidecarClient(sock_path=path)
        r = SidecarClient(sock_path=path)
        w.apply_changes(DOC, [ch('a', 1, 'k', 1)])
        sub = r.subscribe(doc=DOC, peer='thin', mode='patch')
        assert sub['patch'] is not None and 'changes' not in sub
        w.apply_changes(DOC, [ch('a', 2, 'k', 2, {'a': 1})])
        ev = r.next_event(timeout=30)
        assert isinstance(ev, PatchEvent) and not ev.full
        oracle = w.call('get_patch', doc=DOC)
        assert canon(norm_patch(ev.patch)) != ''
        view = thin_view(sub, [dict(ev)])
        fe = apply_patch(Frontend.init({'actorId': 'o'}), oracle)
        assert canon(view) == canon(dict(fe))
        assert ev.clock == oracle['clock']
        # snapshot: byte parity with the pool checkpoint, cached
        snap = r.snapshot(DOC)
        assert isinstance(snap, Snapshot)
        with gw.pool_lock:
            assert snap.data == gw.backend.pool.save(DOC)
        assert w.snapshot(DOC).data == snap.data
        assert telemetry.metrics_snapshot()[
            'readview.snapshot_hits'] >= 1
        assert r.get_clock(DOC)['clock'] == oracle['clock']
        w.close()
        r.close()
    finally:
        gw.stop()


def test_client_auto_resubscribe_preserves_patch_mode(tmp_path,
                                                      monkeypatch):
    """A resync (egress tier 2) on a patch-mode subscription heals
    back into patch mode: the client re-subscribes with its recorded
    kwargs and surfaces the backfill as a synthetic full patch."""
    from automerge_tpu.sidecar.client import SidecarClient
    gw, path = _live_gateway(tmp_path, monkeypatch)
    try:
        w = SidecarClient(sock_path=path)
        r = SidecarClient(sock_path=path)
        w.apply_changes(DOC, [ch('a', 1, 'k', 1)])
        r.subscribe(doc=DOC, peer='thin', mode='patch')
        sub_key = (DOC, None, None, 'thin')
        assert r._subs[sub_key]['mode'] == 'patch'
        # server-side resync envelope (what egress tier 2 emits)
        w.apply_changes(DOC, [ch('a', 2, 'k', 2, {'a': 1})])
        ev = r.next_event(timeout=30)
        assert isinstance(ev, PatchEvent)
        r._auto_resub_worker({'docs': [DOC]})
        deadline = time.time() + 30
        got = None
        while time.time() < deadline:
            got = r.next_event(timeout=1.0)
            if got is not None:
                break
        assert isinstance(got, PatchEvent) and got.full \
            and got.is_resync_backfill
        # still in patch mode after the heal
        assert r._subs[sub_key]['mode'] == 'patch'
        w.close()
        r.close()
    finally:
        gw.stop()


def test_read_replica_materializes_serves_and_resyncs(tmp_path,
                                                      monkeypatch):
    """ReadReplica consumes the fan-out stream into its own pool,
    serves get_patch/snapshot read-only, refuses writes, and closes a
    forced gap via resync_doc."""
    from automerge_tpu.errors import AutomergeError
    from automerge_tpu.readview.replica import ReadReplica
    from automerge_tpu.sidecar.client import SidecarClient
    gw, up_path = _live_gateway(tmp_path, monkeypatch)
    rd_path = os.path.join(str(tmp_path), 'read.sock')
    rep = None
    try:
        w = SidecarClient(sock_path=up_path)
        w.apply_changes(DOC, [ch('a', 1, 'k', 1)])
        rep = ReadReplica(up_path, rd_path, docs=[DOC],
                          probe_s=30.0, slo_s=30.0).start()
        r = SidecarClient(sock_path=rd_path)
        assert r.get_patch(DOC)['clock'] == {'a': 1}
        w.apply_changes(DOC, [ch('a', 2, 'k', 2, {'a': 1})])
        deadline = time.time() + 30
        while time.time() < deadline:
            if r.get_patch(DOC)['clock'] == {'a': 2}:
                break
            time.sleep(0.02)
        assert r.get_patch(DOC)['clock'] == {'a': 2}
        assert canon(r.get_patch(DOC)) == canon(w.get_patch(DOC))
        # read-only: mutations answer the typed envelope
        with pytest.raises(AutomergeError):
            r.apply_changes(DOC, [ch('z', 1, 'k', 9)])
        # snapshot serves from the replica's own pool
        snap = r.snapshot(DOC)
        with gw.pool_lock:
            assert snap.data == gw.backend.pool.save(DOC)
        # forced gap: a doc the replica never subscribed to
        w.apply_changes('gap-doc', [ch('g', 1, 'k', 1)])
        n = rep.resync_doc('gap-doc')
        assert n == 1
        with rep.gw.pool_lock:
            got = rep.backend.pool.get_patch('gap-doc')
        assert canon(got) == canon(w.get_patch('gap-doc'))
        assert rep.healthz_section()['followed_docs'] >= 1
        r.close()
        w.close()
    finally:
        if rep is not None:
            rep.stop()
        gw.stop()
