"""Differential tests: the batched TPU engine must emit patches
byte-identical to the scalar oracle for the same change streams -- the
project's generalization of the reference's hand-built change/patch JSON
contract (`/root/reference/test/backend_test.js`).
"""

import random

import pytest

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu.parallel.engine import TPUDocPool

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def deliver_and_compare(change_batches, n_docs=1):
    """Feeds identical change batches to the oracle and the pool; asserts
    patch equality at every step and final getPatch equality."""
    oracle_states = {d: Backend.init() for d in range(n_docs)}
    pool = TPUDocPool()

    for batch in change_batches:
        # batch: {doc: [changes]}
        expected = {}
        for doc, changes in batch.items():
            oracle_states[doc], patch = Backend.apply_changes(
                oracle_states[doc], changes)
            expected[doc] = patch
        got = pool.apply_batch(batch)
        for doc in batch:
            assert got[doc] == expected[doc], (
                'patch mismatch for doc %r:\nexpected %r\ngot      %r'
                % (doc, expected[doc], got[doc]))

    for doc in range(n_docs):
        expect_patch = Backend.get_patch(oracle_states[doc])
        got_patch = pool.get_patch(doc)
        assert got_patch == expect_patch, (
            'getPatch mismatch:\nexpected %r\ngot      %r'
            % (expect_patch, got_patch))


class TestMapParity:
    def test_simple_sets(self):
        actor = 'actor-a'
        deliver_and_compare([
            {0: [{'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                 'value': 'magpie'}]}]},
            {0: [{'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
                 'value': 'jay'},
                {'action': 'del', 'obj': ROOT_ID, 'key': 'bird'}]}]},
        ])

    def test_concurrent_conflict(self):
        deliver_and_compare([
            {0: [{'actor': 'a1', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 1}]}]},
            {0: [{'actor': 'a2', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': 2}]}]},
            {0: [{'actor': 'a3', 'seq': 1, 'deps': {'a1': 1, 'a2': 1},
                  'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                           'value': 3}]}]},
        ])

    def test_nested_maps_and_links(self):
        actor = 'actor-a'
        deliver_and_compare([
            {0: [{'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeMap', 'obj': 'obj-1'},
                {'action': 'set', 'obj': 'obj-1', 'key': 'wrens', 'value': 3},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'birds',
                 'value': 'obj-1'}]}]},
            {0: [{'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 'obj-1', 'key': 'wrens'},
                {'action': 'set', 'obj': 'obj-1', 'key': 'sparrows',
                 'value': 15}]}]},
        ])

    def test_out_of_order_buffering(self):
        actor = 'actor-a'
        c1 = {'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'a', 'value': 1}]}
        c2 = {'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT_ID, 'key': 'b', 'value': 2}]}
        deliver_and_compare([{0: [c2]}, {0: [c1]}])

    def test_timestamps(self):
        deliver_and_compare([
            {0: [{'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT_ID, 'key': 'now',
                 'value': 1234567890123, 'datatype': 'timestamp'}]}]},
        ])


class TestListParity:
    def test_create_and_insert(self):
        actor = 'actor-a'
        deliver_and_compare([
            {0: [{'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeList', 'obj': 'list-1'},
                {'action': 'ins', 'obj': 'list-1', 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': 'list-1', 'key': '%s:1' % actor,
                 'value': 'chaffinch'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'birds',
                 'value': 'list-1'}]}]},
            {0: [{'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': 'list-1', 'key': '%s:1' % actor,
                 'value': 'greenfinch'}]}]},
            {0: [{'actor': actor, 'seq': 3, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 'list-1', 'key': '%s:1' % actor}]}]},
        ])

    def test_interleaved_inserts_deletes(self):
        actor = 'actor-a'
        deliver_and_compare([
            {0: [{'actor': actor, 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeText', 'obj': 'text-1'},
                {'action': 'ins', 'obj': 'text-1', 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'value': 'h'},
                {'action': 'ins', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'elem': 2},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:2' % actor,
                 'value': 'i'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
                 'value': 'text-1'}]}]},
            {0: [{'actor': actor, 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'del', 'obj': 'text-1', 'key': '%s:1' % actor},
                {'action': 'ins', 'obj': 'text-1', 'key': '%s:1' % actor,
                 'elem': 3},
                {'action': 'set', 'obj': 'text-1', 'key': '%s:3' % actor,
                 'value': 'H'}]}]},
        ])

    def test_concurrent_same_position_inserts(self):
        deliver_and_compare([
            {0: [{'actor': 'aa', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeList', 'obj': 'list-1'},
                {'action': 'ins', 'obj': 'list-1', 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': 'list-1', 'key': 'aa:1',
                 'value': 'base'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'l',
                 'value': 'list-1'}]}]},
            # two actors concurrently insert after 'aa:1'
            {0: [{'actor': 'aa', 'seq': 2, 'deps': {}, 'ops': [
                {'action': 'ins', 'obj': 'list-1', 'key': 'aa:1', 'elem': 2},
                {'action': 'set', 'obj': 'list-1', 'key': 'aa:2',
                 'value': 'from-aa'}]}]},
            {0: [{'actor': 'zz', 'seq': 1, 'deps': {'aa': 1}, 'ops': [
                {'action': 'ins', 'obj': 'list-1', 'key': 'aa:1', 'elem': 2},
                {'action': 'set', 'obj': 'list-1', 'key': 'zz:2',
                 'value': 'from-zz'}]}]},
        ])

    def test_concurrent_set_and_delete_resurrection(self):
        deliver_and_compare([
            {0: [{'actor': 'aa', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'makeList', 'obj': 'list-1'},
                {'action': 'ins', 'obj': 'list-1', 'key': '_head', 'elem': 1},
                {'action': 'set', 'obj': 'list-1', 'key': 'aa:1',
                 'value': 'x'},
                {'action': 'link', 'obj': ROOT_ID, 'key': 'l',
                 'value': 'list-1'}]}]},
            {0: [
                {'actor': 'aa', 'seq': 2, 'deps': {}, 'ops': [
                    {'action': 'del', 'obj': 'list-1', 'key': 'aa:1'}]},
                {'actor': 'bb', 'seq': 1, 'deps': {'aa': 1}, 'ops': [
                    {'action': 'set', 'obj': 'list-1', 'key': 'aa:1',
                     'value': 'resurrected'}]},
            ]},
        ])


class WorkloadGen:
    """Random valid multi-actor workload built through the real frontend,
    then replayed change-by-change into both backends."""

    def __init__(self, seed, n_actors=3, structure='mixed'):
        self.rng = random.Random(seed)
        self.structure = structure
        self.actors = sorted('actor-%02d' % i for i in range(n_actors))

    def generate(self, n_rounds):
        rng = self.rng
        docs = {a: am.init(a) for a in self.actors}
        seen = {a: am.init('obs-' + a) for a in self.actors}  # change trackers
        log = {a: [] for a in self.actors}

        def mutate(doc):
            def cb(d):
                choice = rng.random()
                if self.structure in ('mixed', 'map') and choice < 0.45:
                    key = 'k%d' % rng.randrange(4)
                    d[key] = rng.randrange(100)
                elif self.structure in ('mixed', 'list'):
                    if 'items' not in d:
                        d['items'] = []
                    items = d['items']
                    n = len(items)
                    action = rng.random()
                    if n == 0 or action < 0.6:
                        items.insert_at(rng.randrange(n + 1),
                                        'v%d' % rng.randrange(50))
                    elif action < 0.8 and n > 0:
                        items[rng.randrange(n)] = 'w%d' % rng.randrange(50)
                    elif n > 0:
                        items.delete_at(rng.randrange(n))
                else:
                    d['x'] = rng.randrange(10)
            return cb

        for _ in range(n_rounds):
            a = rng.choice(self.actors)
            docs[a] = am.change(docs[a], mutate(docs[a]))
            # occasionally sync actor pairs
            if rng.random() < 0.5:
                b = rng.choice([x for x in self.actors if x != a])
                docs[b] = am.merge(docs[b], docs[a])

        # full convergence at the end
        for a in self.actors:
            for b in self.actors:
                if a != b:
                    docs[b] = am.merge(docs[b], docs[a])

        # extract every actor's changes from one converged doc
        final = docs[self.actors[0]]
        return am.get_changes(am.init('empty-observer'), final)


class TestRandomWorkloads:
    @pytest.mark.parametrize('seed,structure', [
        (1, 'map'), (2, 'map'), (3, 'list'), (4, 'list'),
        (5, 'mixed'), (6, 'mixed'), (7, 'mixed'),
    ])
    def test_in_order_delivery(self, seed, structure):
        changes = WorkloadGen(seed, structure=structure).generate(20)
        deliver_and_compare([{0: [c]} for c in changes])

    @pytest.mark.parametrize('seed', [11, 12, 13])
    def test_shuffled_delivery(self, seed):
        rng = random.Random(seed)
        changes = WorkloadGen(seed, structure='mixed').generate(16)
        shuffled = list(changes)
        rng.shuffle(shuffled)
        deliver_and_compare([{0: shuffled}])

    @pytest.mark.parametrize('seed', [21, 22])
    def test_batched_delivery(self, seed):
        rng = random.Random(seed)
        changes = WorkloadGen(seed, structure='mixed').generate(18)
        batches = []
        i = 0
        while i < len(changes):
            k = rng.randint(1, 5)
            batches.append({0: changes[i:i + k]})
            i += k
        deliver_and_compare(batches)

    def test_multi_doc_batch(self):
        all_changes = [WorkloadGen(30 + i, structure='mixed').generate(10)
                       for i in range(4)]
        # deliver each doc's full stream in one multi-doc batch
        deliver_and_compare(
            [{d: all_changes[d] for d in range(4)}], n_docs=4)


def deliver_and_compare_all(change_batches, n_docs=1):
    """Three-way differential: oracle vs TPUDocPool vs NativeDocPool,
    patch-equal at every delivery and getPatch-equal at the end."""
    from automerge_tpu.native import NativeDocPool

    oracle_states = {d: Backend.init() for d in range(n_docs)}
    pools = [TPUDocPool(), NativeDocPool()]

    for batch in change_batches:
        expected = {}
        for doc, changes in batch.items():
            oracle_states[doc], patch = Backend.apply_changes(
                oracle_states[doc], [dict(c) for c in changes])
            expected[doc] = patch
        for pool in pools:
            got = pool.apply_batch(batch)
            for doc in batch:
                assert got[doc] == expected[doc], (
                    '%s patch mismatch for doc %r'
                    % (type(pool).__name__, doc))
    for doc in range(n_docs):
        want = Backend.get_patch(oracle_states[doc])
        for pool in pools:
            assert pool.get_patch(doc) == want, type(pool).__name__


class TestRotatingFuzz:
    """Seed-rotating nightly-style fuzz (VERDICT round-1 item 9): larger
    workloads than the fixed-seed suites, driving the NATIVE pool too.
    The seed rotates daily (or comes from AMTPU_FUZZ_SEED) and is printed
    on failure so any run is reproducible."""

    @staticmethod
    def base_seed():
        import datetime
        import os
        env = os.environ.get('AMTPU_FUZZ_SEED')
        if env:
            return int(env)
        return int(datetime.date.today().strftime('%Y%m%d'))

    @pytest.mark.parametrize('lane', range(3))
    def test_rotating_three_backend_fuzz(self, lane):
        seed = self.base_seed() * 10 + lane
        print('fuzz seed: %d (override with AMTPU_FUZZ_SEED)' % seed)
        rng = random.Random(seed)
        structure = ('map', 'list', 'mixed')[lane]
        changes = WorkloadGen(seed, n_actors=4,
                              structure=structure).generate(60)
        # random batching, sometimes shuffled within a batch
        batches = []
        i = 0
        while i < len(changes):
            k = rng.randint(1, 8)
            chunk = list(changes[i:i + k])
            if rng.random() < 0.3:
                rng.shuffle(chunk)
            batches.append({0: chunk})
            i += k
        deliver_and_compare_all(batches)

    def test_rotating_multi_doc_fuzz(self):
        seed = self.base_seed()
        print('fuzz seed: %d (override with AMTPU_FUZZ_SEED)' % seed)
        streams = [WorkloadGen(seed + 100 + d, structure='mixed')
                   .generate(25) for d in range(6)]
        deliver_and_compare_all(
            [{d: streams[d] for d in range(6)}], n_docs=6)
