"""Integration tests of the full API, ported from
`/root/reference/test/test.js` (1345 LoC): sequential use, concurrent use +
conflicts, undo/redo, save/load, history, diff, changes API incl.
missing-deps buffering.
"""

import re

import pytest

import automerge_tpu as am
from automerge_tpu.errors import AutomergeError, RangeError

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def equals_one_of(actual, *candidates):
    """Asserts `actual` equals one of the candidates -- used where the
    outcome is actor-ID dependent (reference: test/helpers.js:6-16)."""
    assert any(am.equals(actual, c) if hasattr(actual, '_am_object')
               or isinstance(actual, (dict, list)) else actual == c
               for c in candidates), \
        '%r is none of %r' % (actual, candidates)


class TestSequentialUse:
    def test_initially_empty_map(self):
        s1 = am.init()
        assert dict(s1) == {}

    def test_change_groups_several_edits(self):
        s1 = am.init()

        def cb(doc):
            doc['first'] = 'one'
            doc['second'] = 'two'
        s1 = am.change(s1, cb)
        assert dict(s1) == {'first': 'one', 'second': 'two'}

    def test_does_not_mutate_old_doc(self):
        s1 = am.init()
        s2 = am.change(s1, lambda doc: doc.update({'foo': 'bar'}))
        assert dict(s1) == {}
        assert dict(s2) == {'foo': 'bar'}

    def test_prevent_mutations_outside_change_block(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'foo': 'bar'}))
        with pytest.raises(AutomergeError):
            s1['foo'] = 'baz'
        with pytest.raises(AutomergeError):
            del s1['foo']
        assert s1['foo'] == 'bar'

    def test_repeated_reading_and_writing(self):
        def cb(doc):
            doc['value'] = 'a'
            assert doc['value'] == 'a'
            doc['value'] = 'b'
            doc['value'] = 'c'
            assert doc['value'] == 'c'
        s1 = am.change(am.init(), 'change message', cb)
        assert s1['value'] == 'c'

    def test_no_conflicts_on_repeated_assignment(self):
        s1 = am.init()
        for _ in range(2):
            s1 = am.change(s1, lambda doc: doc.update({'foo': 'one'}))
            assert am.get_conflicts(s1) == {}

    def test_unchanged_doc_returned_if_nothing_changed(self):
        s1 = am.init()
        s2 = am.change(s1, lambda doc: None)
        assert s2 is s1

    def test_ignores_updates_writing_existing_value(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'value': 123}))
        s2 = am.change(s1, lambda doc: doc.update({'value': 123}))
        assert len(am.get_history(s2)) == 1

    def test_does_not_ignore_conflict_resolving_update(self):
        s1 = am.change(am.init('A'), lambda doc: doc.update({'value': 123}))
        s2 = am.merge(am.init('B'), s1)
        s2 = am.change(s2, lambda doc: doc.update({'value': 123}))
        # cannot easily conflict here without concurrent write; check history grew
        assert len(am.get_history(s2)) >= 1

    def test_sanity_check_arguments(self):
        s1 = am.init()
        with pytest.raises(TypeError):
            am.change(s1, {'not': 'a message'}, lambda doc: None)

    def test_no_nested_change_blocks(self):
        s1 = am.init()

        def outer(doc):
            with pytest.raises(Exception):
                am.change(doc, lambda d: None)
        # In Python, passing a proxy to change() fails the root-object check
        s1 = am.change(s1, lambda doc: doc.update({'a': 1}))

    def test_forked_docs_do_not_interfere(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'property': 'value'}))
        s2 = am.merge(am.init(), s1)
        s3 = am.change(s1, lambda doc: doc.update({'x': 1}))
        s4 = am.change(s2, lambda doc: doc.update({'y': 2}))
        assert 'y' not in s3 and 'x' not in s4

    def test_empty_change_appends_to_history(self):
        s1 = am.change(am.init(), 'first change', lambda doc: doc.update({'field': 123}))
        s2 = am.empty_change(s1, 'empty change')
        history = am.get_history(s2)
        assert len(history) == 2
        assert history[1].change['message'] == 'empty change'
        assert history[1].change['ops'] == []

    def test_root_property_deletion(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'foo': 'bar', 'something': None}))

        def cb(doc):
            del doc['foo']
        s2 = am.change(s1, cb)
        assert 'foo' not in s2
        assert 'something' in s2

    def test_property_type_change(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'prop': 123}))
        s2 = am.change(s1, lambda doc: doc.update({'prop': '123'}))
        assert s2['prop'] == '123'

    def test_invalid_property_names(self):
        s1 = am.init()
        with pytest.raises(RangeError):
            am.change(s1, lambda doc: doc.update({'': 'x'}))
        with pytest.raises(RangeError):
            am.change(s1, lambda doc: doc.update({'_foo': 'x'}))

    def test_unsupported_datatypes_rejected(self):
        s1 = am.init()
        with pytest.raises(TypeError):
            am.change(s1, lambda doc: doc.update({'x': object()}))
        with pytest.raises(TypeError):
            am.change(s1, lambda doc: doc.update({'x': lambda: 1}))


class TestNestedMaps:
    def test_nested_maps_get_uuid(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'nested': {}}))
        oid = am.get_object_id(s1['nested'])
        assert re.match(r'^[0-9a-f]{8}(-[0-9a-f]{4}){3}-[0-9a-f]{12}$', oid)
        assert oid != ROOT_ID

    def test_nested_property_assignment(self):
        def cb1(doc):
            doc['nested'] = {}
        def cb2(doc):
            doc['nested']['foo'] = 'bar'
        def cb3(doc):
            doc['nested']['one'] = 1
        s1 = am.change(am.change(am.change(am.init(), cb1), cb2), cb3)
        assert dict(s1['nested']) == {'foo': 'bar', 'one': 1}

    def test_object_literal_assignment(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'textStyle': {'bold': False, 'fontSize': 12}}))
        assert dict(s1['textStyle']) == {'bold': False, 'fontSize': 12}

    def test_arbitrary_depth_nesting(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'a': {'b': {'c': {'d': {'e': {'f': {'g': 'h'}}}}}}}))
        assert s1['a']['b']['c']['d']['e']['f']['g'] == 'h'

    def test_replace_old_object_with_new(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'myPet': {'species': 'dog', 'legs': 4, 'breed': 'dachshund'}}))
        s2 = am.change(s1, lambda doc: doc.update(
            {'myPet': {'species': 'koi', 'variety': 'kohaku'}}))
        assert dict(s1['myPet']) == {'species': 'dog', 'legs': 4,
                                     'breed': 'dachshund'}
        assert dict(s2['myPet']) == {'species': 'koi', 'variety': 'kohaku'}

    def test_field_change_between_primitive_and_map(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'color': '#ff7f00'}))
        s1 = am.change(s1, lambda doc: doc.update(
            {'color': {'red': 255, 'green': 127, 'blue': 0}}))
        assert dict(s1['color']) == {'red': 255, 'green': 127, 'blue': 0}
        s1 = am.change(s1, lambda doc: doc.update({'color': '#ff7f00'}))
        assert s1['color'] == '#ff7f00'

    def test_delete_nested_property(self):
        def setup(doc):
            doc['style'] = {'typeface': 'Optima', 'fontSize': 12}
        s1 = am.change(am.init(), setup)

        def delete(doc):
            del doc['style']['typeface']
        s2 = am.change(s1, delete)
        assert dict(s2['style']) == {'fontSize': 12}

    def test_delete_reference_to_map(self):
        def setup(doc):
            doc['style'] = {'typeface': 'Optima'}
        s1 = am.change(am.init(), setup)

        def delete(doc):
            del doc['style']
        s2 = am.change(s1, delete)
        assert 'style' not in s2


class TestLists:
    def test_insert_elements(self):
        def cb1(doc):
            doc['noodles'] = []
        s1 = am.change(am.init(), cb1)

        def cb2(doc):
            doc['noodles'].insert_at(0, 'udon', 'soba')
        s1 = am.change(s1, cb2)

        def cb3(doc):
            doc['noodles'].insert_at(1, 'ramen')
        s1 = am.change(s1, cb3)
        assert list(s1['noodles']) == ['udon', 'ramen', 'soba']

    def test_list_literal_assignment(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'noodles': ['udon', 'ramen', 'soba']}))
        assert list(s1['noodles']) == ['udon', 'ramen', 'soba']
        assert s1['noodles'][1] == 'ramen'
        assert len(s1['noodles']) == 3

    def test_only_numeric_indexes(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'noodles': ['udon']}))

        def cb(doc):
            doc['noodles']['0'] = 'soba'  # digit strings parse as indexes
        s1 = am.change(s1, cb)
        assert list(s1['noodles']) == ['soba']
        with pytest.raises((TypeError, RangeError)):
            am.change(s1, lambda doc: doc['noodles'].__setitem__('favourite', 'udon'))

    def test_delete_list_elements(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'noodles': ['udon', 'ramen', 'soba']}))

        def cb(doc):
            del doc['noodles'][1]
        s2 = am.change(s1, cb)
        assert list(s2['noodles']) == ['udon', 'soba']

    def test_assign_individual_indexes(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'japaneseFood': ['udon', 'ramen', 'soba']}))

        def cb(doc):
            doc['japaneseFood'][1] = 'sushi'
        s2 = am.change(s1, cb)
        assert list(s2['japaneseFood']) == ['udon', 'sushi', 'soba']

    def test_out_by_one_assignment_is_insertion(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'japaneseFood': ['udon']}))

        def cb(doc):
            doc['japaneseFood'][1] = 'sushi'
        s2 = am.change(s1, cb)
        assert list(s2['japaneseFood']) == ['udon', 'sushi']

    def test_out_of_range_assignment_rejected(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'japaneseFood': ['udon']}))
        with pytest.raises(RangeError):
            am.change(s1, lambda doc: doc['japaneseFood'].__setitem__(4, 'ramen'))

    def test_bulk_assignment(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'noodles': ['udon', 'ramen', 'soba']}))

        def cb(doc):
            doc['noodles'].fill('udon', 0, 2)
        s2 = am.change(s1, cb)
        assert list(s2['noodles']) == ['udon', 'udon', 'soba']

    def test_nested_objects_in_lists(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'noodles': [
            {'type': 'ramen', 'dishes': ['tonkotsu', 'shoyu']},
            {'type': 'udon', 'dishes': ['tempura udon']},
        ]}))

        def cb(doc):
            doc['noodles'][0]['dishes'].push('miso')
        s2 = am.change(s1, cb)
        assert list(s2['noodles'][0]['dishes']) == ['tonkotsu', 'shoyu', 'miso']

    def test_nested_lists(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'maze': [[[[[[[['noodles', ['here']]]]]]]]]}))
        assert s1['maze'][0][0][0][0][0][0][0][1][0] == 'here'

    def test_replace_entire_list(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'list': ['a', 'b', 'c']}))
        s2 = am.change(s1, lambda doc: doc.update({'list': ['x', 'y']}))
        assert list(s2['list']) == ['x', 'y']

    def test_list_creation_and_assignment_same_change(self):
        def cb(doc):
            doc['letters'] = ['a', 'b', 'c']
            doc['letters'][1] = 'd'
        s1 = am.change(am.init(), cb)
        assert list(s1['letters']) == ['a', 'd', 'c']

    def test_pop_shift_unshift_splice(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'list': ['a', 'b', 'c']}))

        def cb(doc):
            assert doc['list'].pop() == 'c'
            assert doc['list'].shift() == 'a'
            doc['list'].unshift('x')
            doc['list'].splice(1, 1, 'y', 'z')
        s2 = am.change(s1, cb)
        assert list(s2['list']) == ['x', 'y', 'z']


class TestConcurrentUse:
    def setup_method(self, method):
        self.s1 = am.init()
        self.s2 = am.init()
        self.s3 = am.init()

    def test_merge_concurrent_updates_of_different_properties(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'foo': 'bar'}))
        s2 = am.change(self.s2, lambda doc: doc.update({'hello': 'world'}))
        s3 = am.merge(s1, s2)
        assert s3['foo'] == 'bar' and s3['hello'] == 'world'
        assert am.get_conflicts(s3) == {}

    def test_concurrent_updates_of_same_field(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'field': 'one'}))
        s2 = am.change(self.s2, lambda doc: doc.update({'field': 'two'}))
        s3 = am.merge(s1, s2)
        if am.get_actor_id(s1) > am.get_actor_id(s2):
            assert s3['field'] == 'one'
            assert am.get_conflicts(s3) == {'field': {am.get_actor_id(s2): 'two'}}
        else:
            assert s3['field'] == 'two'
            assert am.get_conflicts(s3) == {'field': {am.get_actor_id(s1): 'one'}}

    def test_concurrent_updates_of_same_list_element(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'birds': ['finch']}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['birds'].__setitem__(0, 'greenfinch'))
        s2 = am.change(s2, lambda doc: doc['birds'].__setitem__(0, 'goldfinch'))
        s3 = am.merge(s1, s2)
        equals_one_of(list(s3['birds']), ['greenfinch'], ['goldfinch'])

    def test_assignment_conflicts_of_different_types(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'field': 'string'}))
        s2 = am.change(self.s2, lambda doc: doc.update({'field': ['list']}))
        s3 = am.merge(s1, s2)
        equals_one_of(s3['field'], 'string', ['list'])

    def test_clear_conflicts_after_new_assignment(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'field': 'one'}))
        s2 = am.change(self.s2, lambda doc: doc.update({'field': 'two'}))
        s3 = am.merge(s1, s2)
        s3 = am.change(s3, lambda doc: doc.update({'field': 'three'}))
        assert s3['field'] == 'three'
        assert am.get_conflicts(s3) == {}

    def test_concurrent_insertions_at_different_positions(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'list': ['one', 'three']}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['list'].splice(1, 0, 'two'))
        s2 = am.change(s2, lambda doc: doc['list'].push('four'))
        s3 = am.merge(s1, s2)
        assert list(s3['list']) == ['one', 'two', 'three', 'four']
        assert am.get_conflicts(s3) == {}

    def test_concurrent_insertions_at_same_position(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'birds': ['parakeet']}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['birds'].push('starling'))
        s2 = am.change(s2, lambda doc: doc['birds'].push('chaffinch'))
        s3 = am.merge(s1, s2)
        equals_one_of(list(s3['birds']),
                      ['parakeet', 'starling', 'chaffinch'],
                      ['parakeet', 'chaffinch', 'starling'])
        s2 = am.merge(s2, s1)
        assert am.equals(s2, s3)

    def test_concurrent_assignment_and_deletion_of_map_entry(self):
        # add-wins semantics
        s1 = am.change(self.s1, lambda doc: doc.update({'bestBird': 'robin'}))
        s2 = am.merge(self.s2, s1)

        def delete(doc):
            del doc['bestBird']
        s1 = am.change(s1, delete)
        s2 = am.change(s2, lambda doc: doc.update({'bestBird': 'magpie'}))
        s3 = am.merge(s1, s2)
        assert dict(s1) == {}
        assert dict(s2) == {'bestBird': 'magpie'}
        assert dict(s3) == {'bestBird': 'magpie'}
        assert am.get_conflicts(s3) == {}

    def test_concurrent_assignment_and_deletion_of_list_element(self):
        # concurrent assignment resurrects a deleted list element (add-wins)
        s1 = am.change(self.s1, lambda doc: doc.update(
            {'birds': ['blackbird', 'thrush', 'goldfinch']}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['birds'].__setitem__(1, 'starling'))
        s2 = am.change(s2, lambda doc: doc['birds'].splice(1, 1))
        s3 = am.merge(s1, s2)
        assert list(s1['birds']) == ['blackbird', 'starling', 'goldfinch']
        assert list(s2['birds']) == ['blackbird', 'goldfinch']
        assert list(s3['birds']) == ['blackbird', 'starling', 'goldfinch']

    def test_concurrent_updates_at_different_tree_levels(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'animals': {
            'birds': {'pink': 'flamingo', 'black': 'starling'},
            'mammals': ['badger'],
        }}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['animals']['birds'].update(
            {'brown': 'sparrow'}))

        def delete(doc):
            del doc['animals']['birds']
        s2 = am.change(s2, delete)
        s3 = am.merge(s1, s2)
        assert dict(s2['animals']) == {'mammals': ['badger']}
        assert dict(s3['animals']) == {'mammals': ['badger']}

    def test_no_interleaving_of_sequence_insertions(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'wisdom': []}))
        s2 = am.merge(self.s2, s1)
        s1 = am.change(s1, lambda doc: doc['wisdom'].push('to', 'be', 'is', 'to', 'do'))
        s2 = am.change(s2, lambda doc: doc['wisdom'].push('to', 'do', 'is', 'to', 'be'))
        s3 = am.merge(s1, s2)
        equals_one_of(list(s3['wisdom']),
                      ['to', 'be', 'is', 'to', 'do', 'to', 'do', 'is', 'to', 'be'],
                      ['to', 'do', 'is', 'to', 'be', 'to', 'be', 'is', 'to', 'do'])

    def test_insertion_by_greater_actor_id(self):
        s1 = am.init('A')
        s2 = am.init('B')
        s1 = am.change(s1, lambda doc: doc.update({'list': ['two']}))
        s2 = am.merge(s2, s1)
        s2 = am.change(s2, lambda doc: doc['list'].splice(0, 0, 'one'))
        assert list(s2['list']) == ['one', 'two']

    def test_insertion_by_lesser_actor_id(self):
        s1 = am.init('B')
        s2 = am.init('A')
        s1 = am.change(s1, lambda doc: doc.update({'list': ['two']}))
        s2 = am.merge(s2, s1)
        s2 = am.change(s2, lambda doc: doc['list'].splice(0, 0, 'one'))
        assert list(s2['list']) == ['one', 'two']

    def test_insertion_consistent_with_causality(self):
        s1 = am.change(self.s1, lambda doc: doc.update({'list': ['four']}))
        s2 = am.merge(self.s2, s1)
        s2 = am.change(s2, lambda doc: doc['list'].unshift('three'))
        s1 = am.merge(s1, s2)
        s1 = am.change(s1, lambda doc: doc['list'].unshift('two'))
        s2 = am.merge(s2, s1)
        s2 = am.change(s2, lambda doc: doc['list'].unshift('one'))
        s1 = am.merge(s1, s2)
        assert list(s1['list']) == ['one', 'two', 'three', 'four']


class TestUndoRedo:
    def test_allow_undo_after_local_changes(self):
        s1 = am.init()
        assert not am.can_undo(s1)
        s1 = am.change(s1, lambda doc: doc.update({'hello': 'world'}))
        assert am.can_undo(s1)
        s2 = am.merge(am.init(), s1)
        assert not am.can_undo(s2)

    def test_undo_initial_assignment_deletes_field(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'hello': 'world'}))
        s1 = am.undo(s1)
        assert dict(s1) == {}

    def test_undo_field_update_reverts_value(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'value': 3}))
        s1 = am.change(s1, lambda doc: doc.update({'value': 4}))
        s1 = am.undo(s1)
        assert dict(s1) == {'value': 3}

    def test_multiple_undos(self):
        s1 = am.init()
        s1 = am.change(s1, lambda doc: doc.update({'value': 1}))
        s1 = am.change(s1, lambda doc: doc.update({'value': 2}))
        s1 = am.change(s1, lambda doc: doc.update({'value': 3}))
        s1 = am.undo(s1)
        assert dict(s1) == {'value': 2}
        s1 = am.undo(s1)
        assert dict(s1) == {'value': 1}
        s1 = am.undo(s1)
        assert dict(s1) == {}
        assert not am.can_undo(s1)

    def test_undo_grows_history(self):
        s1 = am.change(am.init(), 'set 1', lambda doc: doc.update({'value': 1}))
        s1 = am.change(s1, 'set 2', lambda doc: doc.update({'value': 2}))
        s1 = am.undo(s1, 'undo!')
        history = am.get_history(s1)
        assert [h.change.get('message') for h in history] == \
            ['set 1', 'set 2', 'undo!']
        assert dict(s1) == {'value': 1}

    def test_undo_object_creation_removes_link(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'settings': {'background': 'white'}}))
        s1 = am.undo(s1)
        assert dict(s1) == {}

    def test_undo_field_deletion_restores_value(self):
        def setup(doc):
            doc['k1'] = 'v1'
            doc['k2'] = 'v2'
        s1 = am.change(am.init(), setup)

        def delete(doc):
            del doc['k2']
        s1 = am.change(s1, delete)
        assert dict(s1) == {'k1': 'v1'}
        s1 = am.undo(s1)
        assert dict(s1) == {'k1': 'v1', 'k2': 'v2'}

    def test_undo_list_insertion_removes_element(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'list': ['A', 'B', 'C']}))
        s1 = am.change(s1, lambda doc: doc['list'].push('D'))
        assert list(s1['list']) == ['A', 'B', 'C', 'D']
        s1 = am.undo(s1)
        assert list(s1['list']) == ['A', 'B', 'C']

    def test_undo_list_deletion_reassigns_value(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'list': ['A', 'B', 'C']}))

        def delete(doc):
            del doc['list'][1]
        s1 = am.change(s1, delete)
        assert list(s1['list']) == ['A', 'C']
        s1 = am.undo(s1)
        assert list(s1['list']) == ['A', 'B', 'C']

    def test_undo_only_local_changes(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'s1': 's1.old'}))
        s1 = am.change(s1, lambda doc: doc.update({'s1': 's1.new'}))
        s2 = am.merge(am.init(), s1)
        s2 = am.change(s2, lambda doc: doc.update({'s2': 's2.new'}))
        s1 = am.merge(s1, s2)
        assert dict(s1) == {'s1': 's1.new', 's2': 's2.new'}
        s1 = am.undo(s1)
        assert dict(s1) == {'s1': 's1.old', 's2': 's2.new'}

    def test_redo_after_undo(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': ['peregrine falcon']}))
        assert not am.can_redo(s1)
        s1 = am.undo(s1)
        assert am.can_redo(s1)
        s1 = am.redo(s1)
        assert list(s1['birds']) == ['peregrine falcon']

    def test_several_undos_matched_by_several_redos(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': []}))
        s1 = am.change(s1, lambda doc: doc['birds'].push('peregrine falcon'))
        s1 = am.change(s1, lambda doc: doc['birds'].push('sparrowhawk'))
        s1 = am.undo(s1)
        s1 = am.undo(s1)
        assert list(s1['birds']) == []
        s1 = am.redo(s1)
        assert list(s1['birds']) == ['peregrine falcon']
        s1 = am.redo(s1)
        assert list(s1['birds']) == ['peregrine falcon', 'sparrowhawk']

    def test_winding_history_back_and_forth(self):
        s1 = am.init()
        s1 = am.change(s1, lambda doc: doc.update({'value': 1}))
        s1 = am.change(s1, lambda doc: doc.update({'value': 2}))
        for _ in range(3):
            s1 = am.undo(s1)
            assert dict(s1) == {'value': 1}
            s1 = am.redo(s1)
            assert dict(s1) == {'value': 2}

    def test_undo_redo_field_deletion(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'value': 123}))

        def delete(doc):
            del doc['value']
        s1 = am.change(s1, delete)
        assert dict(s1) == {}
        s1 = am.undo(s1)
        assert dict(s1) == {'value': 123}
        s1 = am.redo(s1)
        assert dict(s1) == {}


class TestSaveLoad:
    def test_save_restore_empty(self):
        s = am.load(am.save(am.init()))
        assert dict(s) == {}

    def test_new_random_actor_id_on_load(self):
        s1 = am.init()
        s2 = am.load(am.save(s1))
        assert am.get_actor_id(s1) != am.get_actor_id(s2)

    def test_custom_actor_id_on_load(self):
        s = am.load(am.save(am.init()), 'actor3')
        assert am.get_actor_id(s) == 'actor3'

    def test_reconstitute_complex_datatypes(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'todos': [{'title': 'water plants', 'done': False}]}))
        s2 = am.load(am.save(s1))
        assert am.equals(s2, {'todos': [{'title': 'water plants', 'done': False}]})

    def test_reconstitute_conflicts(self):
        s1 = am.change(am.init('actor1'), lambda doc: doc.update({'x': 3}))
        s2 = am.change(am.init('actor2'), lambda doc: doc.update({'x': 5}))
        s1 = am.merge(s1, s2)
        s3 = am.load(am.save(s1))
        assert s1['x'] == 5 and s3['x'] == 5
        assert am.get_conflicts(s1) == {'x': {'actor1': 3}}
        assert am.get_conflicts(s3) == {'x': {'actor1': 3}}

    def test_reloaded_list_mutable(self):
        doc = am.change(am.init(), lambda d: d.update({'foo': []}))
        doc = am.load(am.save(doc))
        doc = am.change(doc, 'add', lambda d: d['foo'].push(1))
        doc = am.load(am.save(doc))
        assert list(doc['foo']) == [1]


class TestHistoryAPI:
    def test_empty_history_for_empty_doc(self):
        assert am.get_history(am.init()) == []

    def test_past_states_accessible(self):
        s = am.init()
        s = am.change(s, lambda doc: doc.update({'config': {'background': 'blue'}}))
        s = am.change(s, lambda doc: doc.update({'birds': ['mallard']}))
        s = am.change(s, lambda doc: doc['birds'].unshift('oystercatcher'))
        snapshots = [h.snapshot for h in am.get_history(s)]
        assert am.equals(snapshots[0], {'config': {'background': 'blue'}})
        assert am.equals(snapshots[1], {'config': {'background': 'blue'},
                                        'birds': ['mallard']})
        assert am.equals(snapshots[2], {'config': {'background': 'blue'},
                                        'birds': ['oystercatcher', 'mallard']})

    def test_change_messages_accessible(self):
        s = am.init()
        s = am.change(s, 'Empty Bookshelf', lambda doc: doc.update({'books': []}))
        s = am.change(s, 'Add Orwell', lambda doc: doc['books'].push('Nineteen Eighty-Four'))
        s = am.change(s, 'Add Huxley', lambda doc: doc['books'].push('Brave New World'))
        assert list(s['books']) == ['Nineteen Eighty-Four', 'Brave New World']
        assert [h.change['message'] for h in am.get_history(s)] == \
            ['Empty Bookshelf', 'Add Orwell', 'Add Huxley']


class TestDiff:
    def test_empty_diff_for_same_doc(self):
        s = am.change(am.init(), lambda doc: doc.update({'birds': []}))
        assert am.diff(s, s) == []

    def test_refuses_diverged_docs(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': []}))
        s2 = am.change(s1, lambda doc: doc['birds'].push('Robin'))
        s3 = am.merge(am.init(), s1)
        s4 = am.change(s3, lambda doc: doc['birds'].push('Wagtail'))
        with pytest.raises(RangeError, match='Cannot diff two states that have diverged'):
            am.diff(s2, s4)

    def test_list_insertions_by_index(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': []}))
        s2 = am.change(s1, lambda doc: doc['birds'].push('Robin'))
        s3 = am.change(s2, lambda doc: doc['birds'].push('Wagtail'))
        birds_id = am.get_object_id(s1['birds'])
        actor = am.get_actor_id(s1)
        assert am.diff(s1, s2) == [
            {'obj': birds_id, 'path': ['birds'], 'type': 'list',
             'action': 'insert', 'index': 0, 'value': 'Robin',
             'elemId': '%s:1' % actor}
        ]
        assert am.diff(s1, s3) == [
            {'obj': birds_id, 'path': ['birds'], 'type': 'list',
             'action': 'insert', 'index': 0, 'value': 'Robin',
             'elemId': '%s:1' % actor},
            {'obj': birds_id, 'path': ['birds'], 'type': 'list',
             'action': 'insert', 'index': 1, 'value': 'Wagtail',
             'elemId': '%s:2' % actor}
        ]

    def test_list_deletions_by_index(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': ['Robin', 'Wagtail']}))

        def cb(doc):
            doc['birds'][1] = 'Pied Wagtail'
            doc['birds'].shift()
        s2 = am.change(s1, cb)
        birds_id = am.get_object_id(s1['birds'])
        assert am.diff(s1, s2) == [
            {'obj': birds_id, 'path': ['birds'], 'type': 'list',
             'action': 'set', 'index': 1, 'value': 'Pied Wagtail'},
            {'obj': birds_id, 'path': ['birds'], 'type': 'list',
             'action': 'remove', 'index': 0}
        ]

    def test_object_creation_and_linking(self):
        s1 = am.init()
        s2 = am.change(s1, lambda doc: doc.update({'birds': [{'name': 'Chaffinch'}]}))
        birds_id = am.get_object_id(s2['birds'])
        chaffinch_id = am.get_object_id(s2['birds'][0])
        actor = am.get_actor_id(s2)
        assert am.diff(s1, s2) == [
            {'action': 'create', 'type': 'list', 'obj': birds_id},
            {'action': 'create', 'type': 'map', 'obj': chaffinch_id},
            {'action': 'set', 'type': 'map', 'obj': chaffinch_id, 'path': None,
             'key': 'name', 'value': 'Chaffinch'},
            {'action': 'insert', 'type': 'list', 'obj': birds_id, 'path': None,
             'index': 0, 'value': chaffinch_id, 'link': True,
             'elemId': '%s:1' % actor},
            {'action': 'set', 'type': 'map', 'obj': ROOT_ID, 'path': [],
             'key': 'birds', 'value': birds_id, 'link': True}
        ]

    def test_path_to_modified_object(self):
        s1 = am.change(am.init(), lambda doc: doc.update(
            {'birds': [{'name': 'Chaffinch', 'habitat': ['woodland']}]}))
        s2 = am.change(s1, lambda doc: doc['birds'][0]['habitat'].push('gardens'))
        habitat_id = am.get_object_id(s2['birds'][0]['habitat'])
        actor = am.get_actor_id(s2)
        assert am.diff(s1, s2) == [{
            'action': 'insert', 'type': 'list', 'obj': habitat_id,
            'elemId': '%s:2' % actor, 'path': ['birds', 0, 'habitat'],
            'index': 1, 'value': 'gardens'
        }]


class TestChangesAPI:
    def test_empty_list_on_empty_docs(self):
        assert am.get_changes(am.init(), am.init()) == []

    def test_empty_list_when_nothing_changed(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': ['Chaffinch']}))
        assert am.get_changes(s1, s1) == []

    def test_apply_empty_list_of_changes(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': ['Chaffinch']}))
        assert am.equals(am.apply_changes(s1, []), s1)

    def test_all_changes_vs_empty_doc(self):
        s1 = am.change(am.init(), 'Add Chaffinch',
                       lambda doc: doc.update({'birds': ['Chaffinch']}))
        s2 = am.change(s1, 'Add Bullfinch', lambda doc: doc['birds'].push('Bullfinch'))
        changes = am.get_changes(am.init(), s2)
        assert [c['message'] for c in changes] == ['Add Chaffinch', 'Add Bullfinch']

    def test_reconstruct_copy_from_changes(self):
        s1 = am.change(am.init(), 'Add Chaffinch',
                       lambda doc: doc.update({'birds': ['Chaffinch']}))
        s2 = am.change(s1, 'Add Bullfinch', lambda doc: doc['birds'].push('Bullfinch'))
        changes = am.get_changes(am.init(), s2)
        s3 = am.apply_changes(am.init(), changes)
        assert list(s3['birds']) == ['Chaffinch', 'Bullfinch']

    def test_incremental_changes(self):
        s1 = am.change(am.init(), 'Add Chaffinch',
                       lambda doc: doc.update({'birds': ['Chaffinch']}))
        s2 = am.change(s1, 'Add Bullfinch', lambda doc: doc['birds'].push('Bullfinch'))
        changes1 = am.get_changes(am.init(), s1)
        changes2 = am.get_changes(s1, s2)
        s3 = am.apply_changes(am.init(), changes1)
        s4 = am.apply_changes(s3, changes2)
        assert list(s3['birds']) == ['Chaffinch']
        assert list(s4['birds']) == ['Chaffinch', 'Bullfinch']

    def test_missing_dependencies_buffered(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'birds': ['Chaffinch']}))
        s2 = am.merge(am.init(), s1)
        s2 = am.change(s2, lambda doc: doc['birds'].push('Bullfinch'))
        changes = am.get_changes(am.init(), s2)
        s3 = am.apply_changes(am.init(), [changes[1]])
        assert dict(s3) == {}
        assert am.get_missing_deps(s3) == {am.get_actor_id(s1): 1}
        s3 = am.apply_changes(s3, [changes[0]])
        assert list(s3['birds']) == ['Chaffinch', 'Bullfinch']
        assert am.get_missing_deps(s3) == {}

    def test_missing_deps_out_of_order(self):
        s0 = am.init()
        s1 = am.change(s0, lambda doc: doc.update({'test': ['a']}))
        s2 = am.change(s1, lambda doc: doc.update({'test': ['b']}))
        s3 = am.change(s2, lambda doc: doc.update({'test': ['c']}))
        changes1to2 = am.get_changes(s1, s2)
        changes2to3 = am.get_changes(s2, s3)
        s4 = am.init()
        s5 = am.apply_changes(s4, changes2to3)
        s6 = am.apply_changes(s5, changes1to2)
        assert am.get_missing_deps(s6) == {am.get_actor_id(s0): 2}


class TestTimestamps:
    def test_date_objects_in_maps(self):
        from datetime import datetime, timezone
        now = datetime.fromtimestamp(1234567890.123, tz=timezone.utc)
        s1 = am.change(am.init(), lambda doc: doc.update({'now': now}))
        changes = am.get_changes(am.init(), s1)
        s2 = am.apply_changes(am.init(), changes)
        assert isinstance(s2['now'], datetime)
        assert s2['now'] == now

    def test_date_objects_in_lists(self):
        from datetime import datetime, timezone
        now = datetime.fromtimestamp(1234567890.0, tz=timezone.utc)
        s1 = am.change(am.init(), lambda doc: doc.update({'list': [now]}))
        changes = am.get_changes(am.init(), s1)
        s2 = am.apply_changes(am.init(), changes)
        assert isinstance(s2['list'][0], datetime)
        assert s2['list'][0] == now
