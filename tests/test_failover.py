"""Fleet-failover tests (ISSUE 19, docs/RESILIENCE.md fleet
degradation tiers): the per-member health state machine, deterministic
chaos lanes driven through the `router.heartbeat` fault site
(permanent -> up/suspect/dead/failover with history intact; transient
-> suspect/recover, no failover), write-through restore onto ring
survivors, parked-frame park/replay/fail semantics with the typed
ReplicaUnavailable / ReplicaFailed envelopes, park expiry, and the
placement-journal router restart (post-failover placement survives
byte-identically).
"""

import json
import os
import socket
import threading
import time

import pytest

from automerge_tpu import faults, telemetry
from automerge_tpu.errors import (ReplicaFailedError,
                                  ReplicaUnavailableError)
from automerge_tpu.router import (FailoverExecutor, HealthMonitor,
                                  RouterGateway)
from automerge_tpu.scheduler import GatewayServer
from automerge_tpu.sidecar.client import SidecarClient
from automerge_tpu.sidecar.server import SidecarBackend
from automerge_tpu.storage.coldstore import ColdStore

ROOT_ID = '00000000-0000-0000-0000-000000000000'


@pytest.fixture(autouse=True)
def _hygiene():
    telemetry.reset_all()
    faults.disarm()
    os.environ['AMTPU_FLUSH_DEADLINE_MS'] = '5'
    yield
    del os.environ['AMTPU_FLUSH_DEADLINE_MS']
    faults.disarm()
    telemetry.reset_all()


def change(actor, seq, key='k', value=None):
    return {'actor': actor, 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': key,
                     'value': value if value is not None
                     else '%s-%d' % (actor, seq)}]}


def _flat():
    return telemetry.metrics_snapshot()


def _poll(cond, deadline_s=10.0, what='condition'):
    deadline = time.time() + deadline_s
    while not cond():
        assert time.time() < deadline, 'timed out on %s' % what
        time.sleep(0.02)


class Fleet(object):
    """N in-process replica gateways (each with its own write-through
    sync store, as a supervised subprocess fleet would get from
    AMTPU_STORAGE_SYNC) + one router."""

    def __init__(self, tmp, n=2, journal=False):
        self.replicas = {}
        self.gateways = {}
        self.stores = {}
        for i in range(n):
            rid = 'r%d' % i
            path = str(tmp / (rid + '.sock'))
            store = str(tmp / ('store-' + rid))
            self.stores[rid] = store
            self.gateways[rid] = GatewayServer(
                path, backend=SidecarBackend(),
                sync_dir=store).start()
            self.replicas[rid] = path
        self.router_path = str(tmp / 'router.sock')
        self.journal_path = str(tmp / 'placement.json') \
            if journal else None
        self.router = RouterGateway(
            self.router_path, self.replicas,
            journal_path=self.journal_path).start()

    def stop(self):
        self.router.stop()
        for gw in self.gateways.values():
            gw.stop()


@pytest.fixture()
def fleet(tmp_path):
    f = Fleet(tmp_path, n=2)
    yield f
    f.stop()


# ---------------------------------------------------------------------------
# health state machine (no threads)
# ---------------------------------------------------------------------------

class _StubRouter(object):
    replicas = {}
    use_msgpack = False

    def __init__(self):
        self.released = []

    def attach_health(self, m):
        pass

    def release_member_parks(self, member):
        self.released.append(member)


def test_health_state_machine_hysteresis():
    r = _StubRouter()
    hm = HealthMonitor(r, heartbeat_s=9, deadline_s=9, miss_max=3)
    assert hm.state('r0') == 'up'
    hm.note_miss('r0')
    assert hm.state('r0') == 'suspect' and hm.is_parking('r0')
    hm.note_miss('r0')
    assert hm.state('r0') == 'suspect', 'two misses < miss_max'
    # a probe answering again fully recovers (and replays the parks)
    hm.note_ok('r0')
    assert hm.state('r0') == 'up' and not hm.is_parking('r0')
    assert r.released == ['r0']
    # the miss counter reset: three FRESH consecutive misses kill
    for _ in range(3):
        hm.note_miss('r0')
    assert hm.state('r0') == 'dead'
    hm.note_ok('r0')
    assert hm.state('r0') == 'dead', 'dead is terminal for the id'
    flat = _flat()
    assert flat.get('router.health.suspects') == 2
    assert flat.get('router.health.deaths') == 1
    assert flat.get('router.health.recoveries') == 1
    assert flat.get('router.health.misses') == 5


def test_health_mark_dead_and_transport_signals():
    hm = HealthMonitor(_StubRouter(), heartbeat_s=9, deadline_s=9,
                       miss_max=3)
    hm.note_transport_death('r1')
    assert hm.state('r1') == 'suspect'
    hm.mark_dead('r0', cause='exit rc=-9')
    assert hm.state('r0') == 'dead'
    snap = hm.members()
    assert snap['r0']['state'] == 'dead'
    assert snap['r1']['misses'] == 1


# ---------------------------------------------------------------------------
# chaos lanes: the router.heartbeat fault site drives the ladder
# ---------------------------------------------------------------------------

def test_permanent_heartbeat_fault_drives_failover(fleet):
    """A permanently unreachable member walks up -> suspect -> dead
    deterministically, the failover executor restores its docs onto
    the survivor from the write-through store, and every doc keeps
    serving with history intact."""
    router = fleet.router
    docs = ['doc-%d' % i for i in range(16)]
    with SidecarClient(sock_path=fleet.router_path) as c:
        for d in docs:
            for seq in (1, 2):
                assert c.apply_changes(
                    d, [change('a', seq)])['clock'] == {'a': seq}
        victim = 'r0'
        victim_docs = [d for d in docs
                       if router.ring.owner(d) == victim]
        assert victim_docs, 'need docs on the victim'
        ex = FailoverExecutor(router, store_dirs=fleet.stores)
        hm = HealthMonitor(router, heartbeat_s=0.05, deadline_s=0.2,
                           miss_max=2, on_dead=ex.fail_over).start()
        try:
            faults.arm('router.heartbeat', kind='permanent',
                       match=victim)
            _poll(lambda: victim not in router.replicas,
                  what='failover to remove the victim')
            assert hm.state(victim) == 'dead'
            assert router.ring.members() == ['r1']
            # every doc is answerable with its full history, and new
            # writes keep applying in sequence (nothing duplicated:
            # seq 3 on top of a restored seq<=2 history)
            for d in docs:
                assert c.get_patch(d)['clock'] == {'a': 2}, d
                assert c.apply_changes(
                    d, [change('a', 3)])['clock'] == {'a': 3}
        finally:
            faults.disarm()
            hm.stop()
    flat = _flat()
    assert flat.get('router.health.deaths') == 1
    assert flat.get('failover.failovers') == 1
    assert flat.get('failover.docs_recovered') >= len(victim_docs)
    assert not flat.get('failover.docs_lost')
    assert not flat.get('fallback.oracle'), \
        'chaos must never push the pool onto the oracle path'


def test_transient_heartbeat_fault_clears_without_failover(fleet):
    """One injected probe miss only SUSPECTS the member; the next
    probe answers and the member recovers -- no failover, no
    membership change."""
    router = fleet.router
    ex = FailoverExecutor(router, store_dirs=fleet.stores)
    hm = HealthMonitor(router, heartbeat_s=0.05, deadline_s=0.2,
                       miss_max=5, on_dead=ex.fail_over).start()
    try:
        faults.arm('router.heartbeat', kind='transient', count=1)
        _poll(lambda: _flat().get('router.health.suspects', 0) >= 1,
              what='the injected miss to suspect a member')
        _poll(lambda: _flat().get('router.health.recoveries', 0) >= 1,
              what='the next probe to recover it')
        assert sorted(router.replicas) == ['r0', 'r1']
        assert all(st['state'] == 'up'
                   for st in hm.members().values())
    finally:
        faults.disarm()
        hm.stop()
    flat = _flat()
    assert not flat.get('failover.failovers')
    assert not flat.get('router.health.deaths')
    assert not flat.get('fallback.oracle')


# ---------------------------------------------------------------------------
# park / replay / fail semantics
# ---------------------------------------------------------------------------

def _raw_conn(path):
    s = socket.socket(socket.AF_UNIX)
    s.connect(path)
    return s, s.makefile('rb')


def test_suspect_member_parks_mutations_and_replays_on_failover(
        fleet, tmp_path):
    """Mutating frames for a suspect member's docs park in the per-doc
    FIFOs; when the member is declared dead and failed over they
    replay IN ARRIVAL ORDER against the restored doc on the new owner
    -- pipelined seqs must land gapless."""
    router = fleet.router
    doc = 'park-doc'
    with SidecarClient(sock_path=fleet.router_path) as c:
        c.apply_changes(doc, [change('a', 1)])
    victim = router.ring.owner(doc)
    ex = FailoverExecutor(router, store_dirs=fleet.stores)
    # attached but UNSTARTED monitor: the lane drives the machine by
    # hand so the park window is deterministic, not a thread race
    hm = HealthMonitor(router, miss_max=2)
    router.attach_health(hm)
    hm.note_miss(victim)
    s, f = _raw_conn(fleet.router_path)
    try:
        for seq in range(2, 7):
            s.sendall((json.dumps(
                {'id': seq, 'cmd': 'apply_changes', 'doc': doc,
                 'changes': [change('a', seq)]}) + '\n').encode())
        # frame 1 opens the fleet park; frames 2..5 land in the same
        # per-doc FIFO through the ordinary park check
        _poll(lambda: _flat().get('router.health.parked', 0) >= 1
              and _flat().get('router.parked', 0) >= 4,
              what='frames to park for the suspect member')
        assert router.parked_docs_for(victim) == [doc]
        s.settimeout(0.3)
        with pytest.raises(socket.timeout):
            s.recv(1)
        s.settimeout(None)
        hm.note_miss(victim)            # 2nd miss: dead
        assert hm.state(victim) == 'dead'
        res = ex.fail_over(victim)
        assert doc in res['recovered'] and not res['lost'], res
        rids = [json.loads(f.readline())['id'] for _ in range(5)]
        assert rids == [2, 3, 4, 5, 6], rids
    finally:
        s.close()
        router.attach_health(None)
    with SidecarClient(sock_path=fleet.router_path) as c:
        assert c.get_patch(doc)['clock'] == {'a': 6}
    assert _flat().get('failover.replayed') == 5
    assert router.park_stats() == {'parked_docs': 0,
                                   'parked_bytes': 0}


def test_unrecoverable_docs_answer_replica_failed(fleet):
    """With nothing durable registered for the dead member, parked
    mutating frames answer the terminal typed ReplicaFailed envelope
    (and the client maps it)."""
    router = fleet.router
    doc = 'lost-doc'
    with SidecarClient(sock_path=fleet.router_path) as c:
        c.apply_changes(doc, [change('a', 1)])
    victim = router.ring.owner(doc)
    ex = FailoverExecutor(router)       # no store_dirs registered
    hm = HealthMonitor(router, miss_max=1)
    router.attach_health(hm)
    hm.note_miss(victim)                # miss_max=1: straight to dead
    errs = []
    with SidecarClient(sock_path=fleet.router_path) as c:
        t = threading.Thread(target=lambda: errs.append(
            pytest.raises(ReplicaFailedError, c.apply_changes, doc,
                          [change('a', 2)])))
        t.start()
        _poll(lambda: _flat().get('router.health.parked', 0) >= 1,
              what='the mutation to park')
        res = ex.fail_over(victim)
        t.join(timeout=10)
        assert not t.is_alive()
    assert doc in res['lost']
    assert errs and errs[0].value.doc == doc
    assert _flat().get('failover.docs_lost') >= 1
    router.attach_health(None)


def test_park_budget_and_expiry_answer_replica_unavailable(
        tmp_path, monkeypatch):
    """The park window is bounded: past AMTPU_FLEET_PARK_S the sweep
    flushes parked frames with the retryable ReplicaUnavailable
    envelope (mapped by the client), and a zero byte budget refuses
    the park outright."""
    monkeypatch.setenv('AMTPU_FLEET_PARK_S', '0.2')
    f = Fleet(tmp_path, n=2)
    try:
        router = f.router
        doc = 'expire-doc'
        with SidecarClient(sock_path=f.router_path) as c:
            c.apply_changes(doc, [change('a', 1)])
        victim = router.ring.owner(doc)
        hm = HealthMonitor(router, miss_max=2)
        router.attach_health(hm)
        hm.note_miss(victim)
        with SidecarClient(sock_path=f.router_path) as c:
            errs = []
            t = threading.Thread(target=lambda: errs.append(
                pytest.raises(ReplicaUnavailableError,
                              c.apply_changes, doc,
                              [change('a', 2)])))
            t.start()
            _poll(lambda: _flat().get('router.health.parked', 0) >= 1,
                  what='the mutation to park')
            time.sleep(0.25)            # > AMTPU_FLEET_PARK_S
            router.sweep_parked()
            t.join(timeout=10)
            assert not t.is_alive()
            assert errs and errs[0].value.retry_after_ms >= 100
        assert _flat().get('router.health.park_expired') == 1
        # zero budget: the park is refused, the envelope is immediate
        router.park_bytes_max = 0
        with SidecarClient(sock_path=f.router_path) as c:
            with pytest.raises(ReplicaUnavailableError):
                c.apply_changes(doc, [change('a', 2)])
        assert _flat().get('router.health.park_overflow') == 1
        router.attach_health(None)
    finally:
        f.stop()


# ---------------------------------------------------------------------------
# placement journal: a restarted router serves post-failover placement
# ---------------------------------------------------------------------------

def test_journal_restores_post_failover_placement(tmp_path):
    f = Fleet(tmp_path, n=3, journal=True)
    docs = ['doc-%d' % i for i in range(24)]
    try:
        router = f.router
        with SidecarClient(sock_path=f.router_path) as c:
            for d in docs:
                c.apply_changes(d, [change('a', 1)])
        ex = FailoverExecutor(router, store_dirs=f.stores)
        res = ex.fail_over('r0')
        assert not res['lost']
        placement = {d: router.ring.owner(d) for d in docs}
        overrides = router.ring.overrides()
        epoch = router.ring.version
        members = dict(router.replicas)
        assert 'r0' not in members
    finally:
        f.stop()
    # restart a router from the ORIGINAL seed (r0 included): the
    # journal must win -- the dead placement stays dead, byte for byte
    r2 = RouterGateway(str(tmp_path / 'router2.sock'), f.replicas,
                       journal_path=f.journal_path).start()
    try:
        assert r2.replicas == members
        assert {d: r2.ring.owner(d) for d in docs} == placement
        assert r2.ring.overrides() == overrides
        assert r2.ring.version >= epoch
    finally:
        r2.stop()


def test_journal_ignores_corruption(tmp_path):
    journal = tmp_path / 'placement.json'
    journal.write_text('{not json')
    sock = str(tmp_path / 'r.sock')
    gw = GatewayServer(sock, backend=SidecarBackend()).start()
    router = RouterGateway(str(tmp_path / 'router.sock'),
                           {'r0': sock},
                           journal_path=str(journal)).start()
    try:
        assert sorted(router.replicas) == ['r0'], \
            'corrupt journal falls back to the seed membership'
        router.add_member('r0b', sock)
        data = json.loads(journal.read_text())
        assert sorted(data['members']) == ['r0', 'r0b']
    finally:
        router.stop()
        gw.stop()


# ---------------------------------------------------------------------------
# rejoin pinning: a new member must not implicitly claim existing docs
# ---------------------------------------------------------------------------

def test_rejoin_pins_existing_docs_to_survivors(tmp_path):
    """After a failover, a respawned generation joins as a NEW ring
    member.  Without pins the hash remap would route ~1/N of existing
    docs to the empty joiner (forking them on first write); with
    `join_pins` every known doc stays with the member that holds its
    state, and only genuinely new docs may hash to the joiner."""
    f = Fleet(tmp_path, n=3)
    docs = ['doc-%d' % i for i in range(30)]
    try:
        router = f.router
        with SidecarClient(sock_path=f.router_path) as c:
            for d in docs:
                c.apply_changes(d, [change('a', 1)])
        ex = FailoverExecutor(router, store_dirs=dict(f.stores))
        assert not ex.fail_over('r0')['lost']
        before = {d: router.ring.owner(d) for d in docs}
        assert set(before.values()) <= {'r1', 'r2'}
        # the rejoiner gets a fresh empty store, registered AFTER the
        # pins are computed (supervisor ordering)
        pins = ex.join_pins()
        ex.register_store('r0-g1', str(tmp_path / 'store-r0-g1'))
        router.add_member('r0-g1', f.replicas['r1'], pins=pins)
        after = {d: router.ring.owner(d) for d in docs}
        assert after == before, \
            'join remapped docs away from their state: %r' % {
                d: (before[d], after[d]) for d in docs
                if before[d] != after[d]}
        # writes keep landing with history intact through the pins
        with SidecarClient(sock_path=f.router_path) as c:
            for d in docs:
                assert c.apply_changes(
                    d, [change('a', 2)])['clock'] == {'a': 2}
    finally:
        f.stop()


# ---------------------------------------------------------------------------
# supervisor policy (process-free: spawn is stubbed)
# ---------------------------------------------------------------------------

def test_supervisor_generation_naming():
    from automerge_tpu.router.supervisor import ReplicaSupervisor as S
    assert S._member_name('r0', 0) == 'r0'
    assert S._member_name('r0', 2) == 'r0-g2'
    assert S._parse('r0') == ('r0', 0)
    assert S._parse('r0-g2') == ('r0', 2)
    assert S._parse('odd-gName') == ('odd-gName', 0)


def test_supervisor_respawns_then_quarantines(tmp_path, monkeypatch):
    from automerge_tpu.router.supervisor import ReplicaSupervisor

    class _R(object):
        replicas = {}
    sup = ReplicaSupervisor(_R(), str(tmp_path), flap_max=2)
    spawned = []
    monkeypatch.setattr(
        sup, 'spawn', lambda base, gen=0: spawned.append((base, gen)))
    for _ in range(2):                  # deaths 1..2: respawn
        sup._on_exit('r0' if not spawned
                     else 'r0-g%d' % spawned[-1][1], -9)
    assert spawned == [('r0', 1), ('r0', 2)]
    sup._on_exit('r0-g2', -9)           # death 3 > flap_max: barred
    assert spawned == [('r0', 1), ('r0', 2)]
    flat = _flat()
    assert flat.get('failover.respawns') == 2
    assert flat.get('failover.quarantined') == 1


# ---------------------------------------------------------------------------
# write-through checkpointing (the durability the restore rests on)
# ---------------------------------------------------------------------------

def test_write_through_store_holds_every_acked_change(tmp_path):
    sync = str(tmp_path / 'sync')
    gw = GatewayServer(str(tmp_path / 'r.sock'),
                       backend=SidecarBackend(),
                       sync_dir=sync).start()
    try:
        with SidecarClient(sock_path=str(tmp_path / 'r.sock')) as c:
            for seq in (1, 2, 3):
                c.apply_changes('wt-doc', [change('a', seq)])
        store = ColdStore(sync, durable=True)
        assert 'wt-doc' in store.doc_ids()
        # the checkpoint is the FULL doc as of the last ack
        from automerge_tpu.sidecar.server import SidecarBackend as SB
        probe = SB()
        probe.pool.load('wt-doc', store.get('wt-doc'))
        patch = probe.handle({'id': 1, 'cmd': 'get_patch',
                              'doc': 'wt-doc'})['result']
        assert patch['clock'] == {'a': 3}
    finally:
        gw.stop()
    assert _flat().get('storage.sync_saves') == 3
    assert not _flat().get('storage.sync_failed')
