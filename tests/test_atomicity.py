"""Failed applies must leave the pool untouched.

The reference backend is immutable: a change that throws mid-apply leaves the
caller holding the old state, so the change is neither recorded nor shipped
(`/root/reference/backend/index.js:144-155` -- the caller's binding keeps the
pre-call value on throw).  The long-lived pools must match: validation runs
read-only BEFORE clock/states/arenas commit, and the causal queue is rolled
back on error.
"""

import pytest

from automerge_tpu.errors import AutomergeError
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.parallel.engine import TPUDocPool

ROOT = '00000000-0000-0000-0000-000000000000'

POOLS = [NativeDocPool, TPUDocPool, lambda: ShardedNativePool(n_shards=2)]


def good(seq, key='k', value=1):
    return {'actor': 'A', 'seq': seq, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT, 'key': key,
                     'value': value}]}


@pytest.mark.parametrize('make_pool', POOLS)
def test_failed_batch_fully_rolls_back(make_pool):
    pool = make_pool()
    bad = {'actor': 'A', 'seq': 2, 'deps': {},
           'ops': [{'action': 'set', 'obj': 'nonexistent', 'key': 'x',
                    'value': 1}]}
    with pytest.raises(AutomergeError, match='unknown object'):
        pool.apply_changes('d', [good(1), bad])
    # NOTHING from the failed batch committed: the valid first change must
    # re-apply (it would be dropped as a duplicate if the clock advanced)
    assert pool.get_patch('d')['clock'] == {}
    assert pool.get_missing_changes('d', {}) == []
    patch = pool.apply_changes('d', [good(1)])
    assert [d['key'] for d in patch['diffs']] == ['k']
    assert pool.get_patch('d')['clock'] == {'A': 1}


@pytest.mark.parametrize('make_pool', POOLS)
def test_failed_batch_restores_causal_queue(make_pool):
    pool = make_pool()
    # queue a change with an unmet dependency, then fail a later batch
    future = good(2, key='later')
    pool.apply_changes('d', [future])
    assert pool.get_missing_deps('d') == {'A': 1}
    bad = {'actor': 'B', 'seq': 1, 'deps': {},
           'ops': [{'action': 'set', 'obj': 'nonexistent', 'key': 'x',
                    'value': 1}]}
    with pytest.raises(AutomergeError, match='unknown object'):
        pool.apply_changes('d', [bad])
    # the queued change survived the failed batch
    assert pool.get_missing_deps('d') == {'A': 1}
    patch = pool.apply_changes('d', [good(1)])
    assert pool.get_patch('d')['clock'] == {'A': 2}
    assert {d['key'] for d in patch['diffs']} == {'k', 'later'}


@pytest.mark.parametrize('make_pool', POOLS)
def test_missing_list_element_fails_before_commit(make_pool):
    pool = make_pool()
    pool.apply_changes('d', [
        {'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeText', 'obj': 'T'},
                 {'action': 'link', 'obj': ROOT, 'key': 't',
                  'value': 'T'}]}])
    bad = {'actor': 'A', 'seq': 2, 'deps': {},
           'ops': [{'action': 'set', 'obj': 'T', 'key': 'A:99',
                    'value': 'x'}]}
    with pytest.raises(AutomergeError, match='Missing index entry'):
        pool.apply_changes('d', [bad])
    assert pool.get_patch('d')['clock'] == {'A': 1}
    # a del on a missing element is silently dropped, not an error
    patch = pool.apply_changes('d', [
        {'actor': 'A', 'seq': 2, 'deps': {},
         'ops': [{'action': 'del', 'obj': 'T', 'key': 'A:99'}]}])
    assert patch['diffs'] == []
    assert pool.get_patch('d')['clock'] == {'A': 2}


@pytest.mark.parametrize('make_pool', POOLS)
def test_inconsistent_seq_reuse_rejected_without_commit(make_pool):
    pool = make_pool()
    pool.apply_changes('d', [good(1)])
    with pytest.raises(AutomergeError, match='Inconsistent reuse'):
        pool.apply_changes('d', [good(1, value=999)])
    # exact duplicate still tolerated afterwards
    assert pool.apply_changes('d', [good(1)])['diffs'] == []


@pytest.mark.parametrize('make_pool', POOLS)
def test_multi_error_batches_surface_first_error_in_op_order(make_pool):
    """When a batch contains several invalid ops, the FIRST one in
    application order wins -- the oracle applies ops strictly in order, so
    every backend must report the same error for the same input."""
    pool = make_pool()
    pool.apply_changes('d', [
        {'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeText', 'obj': 'T'},
                 {'action': 'link', 'obj': ROOT, 'key': 't',
                  'value': 'T'}]}])
    bad = {'actor': 'A', 'seq': 2, 'deps': {},
           'ops': [{'action': 'set', 'obj': 'T', 'key': 'A:99',
                    'value': 'x'},          # error 1: absent list element
                   {'action': 'makeText', 'obj': 'T'}]}  # error 2: dup
    with pytest.raises(AutomergeError, match='Missing index entry'):
        pool.apply_changes('d', [bad])
    assert pool.get_patch('d')['clock'] == {'A': 1}


@pytest.mark.parametrize('make_pool', POOLS)
def test_assign_before_insert_in_same_change_rejected(make_pool):
    """An assign referencing an element inserted LATER in the same change
    must error: the oracle applies ops in order, so the element does not
    exist yet when the assign runs."""
    pool = make_pool()
    bad = {'actor': 'A', 'seq': 1, 'deps': {},
           'ops': [{'action': 'makeText', 'obj': 'T'},
                   {'action': 'set', 'obj': 'T', 'key': 'A:1',
                    'value': 'x'},
                   {'action': 'ins', 'obj': 'T', 'key': '_head',
                    'elem': 1}]}
    with pytest.raises(AutomergeError, match='Missing index entry'):
        pool.apply_changes('d', [bad])
    assert pool.get_patch('d')['clock'] == {}


def test_queries_do_not_materialize_phantom_docs():
    pool = NativeDocPool()
    assert pool.get_patch('never-created')['diffs'] == []
    assert pool.get_missing_deps('never-created') == {}
    assert pool.get_missing_changes('never-created', {}) == []
    assert pool.get_changes_for_actor('never-created', 'A') == []
    assert pool.get_register('never-created', ROOT, 'k') == []
    # the doc must still be creatable with full semantics afterwards
    patch = pool.apply_changes('never-created', [good(1)])
    assert [d['key'] for d in patch['diffs']] == ['k']


@pytest.mark.parametrize('make_pool', [NativeDocPool,
                                       lambda: ShardedNativePool(n_shards=2)])
def test_out_of_range_elem_counter_rejected(make_pool):
    """Arena columns are i32 (the kernel layout): inserts with counters
    outside that range are rejected atomically, never silently truncated."""
    pool = make_pool()
    pool.apply_changes('d', [
        {'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeText', 'obj': 'T'}]}])
    for elem in (-1, 2 ** 31, 2 ** 40):
        with pytest.raises(AutomergeError, match='out of range'):
            pool.apply_changes('d', [
                {'actor': 'A', 'seq': 2, 'deps': {},
                 'ops': [{'action': 'ins', 'obj': 'T', 'key': '_head',
                          'elem': elem}]}])
    assert pool.get_patch('d')['clock'] == {'A': 1}
    # a huge counter inside an elemId string is malformed, not a wrap
    with pytest.raises(AutomergeError, match='Missing index entry'):
        pool.apply_changes('d', [
            {'actor': 'A', 'seq': 2, 'deps': {},
             'ops': [{'action': 'ins', 'obj': 'T',
                      'key': 'A:99999999999999999999', 'elem': 1}]}])
