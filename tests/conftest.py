"""Test configuration: force JAX onto a virtual 8-device CPU mesh so the
multi-chip sharding paths (dp/sp over a Mesh) are exercised without TPU
hardware, per the build contract."""

import os
import re
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'  # override (env may preset a TPU backend)
# force 8 virtual devices even if the env presets a different count
flags = re.sub(r'--xla_force_host_platform_device_count=\d+', '',
               os.environ.get('XLA_FLAGS', ''))
os.environ['XLA_FLAGS'] = (
    flags + ' --xla_force_host_platform_device_count=8').strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# sitecustomize may have registered an accelerator platform and prepended it
# to jax_platforms before this file runs; pin the config back to cpu (backend
# init is lazy, so this takes effect as long as no test imported jax first)
from automerge_tpu.utils.jaxenv import pin_cpu  # noqa: E402
pin_cpu(force=True)
