"""Chaos suite (ISSUE 4): fault injection x poison isolation x healing.

Three layers under test, matching docs/RESILIENCE.md:

  * the fault MATRIX: every injection site x transient/permanent x both
    execution modes, asserting byte-parity of surviving docs against the
    no-fault run, quarantine accounting, and the retry counters;
  * poison-batch isolation on the sharded pool (a failure stays inside
    its shard, then inside its doc);
  * the self-healing sidecar: crash (SIGKILL and the in-band
    `sidecar.frame` fault) -> respawn -> checkpoint-WAL replay ->
    byte-identical state, plus the serve-loop InternalError catch-all
    and the unix-socket SIGTERM cleanup satellites.
"""

import msgpack
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import pytest

from automerge_tpu import faults, resilience, telemetry
from automerge_tpu.native import NativeDocPool, ShardedNativePool

ROOT_ID = '00000000-0000-0000-0000-000000000000'
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the poison doc the matrix pins permanent faults to
POISON = 'd3'


@pytest.fixture(autouse=True)
def _fault_hygiene():
    """No lane may leak armed specs or counters into the next."""
    faults.disarm()
    telemetry.metrics_reset()
    yield
    faults.disarm()
    telemetry.metrics_reset()


@pytest.fixture(params=['default', 'kernel'])
def exec_mode(request):
    """Both execution modes face every fault lane: the CPU default
    (full host path; device sites are unreachable by construction) and
    the forced kernel path (AMTPU_HOST_REG=0 keeps the hot-key batch on
    the escalation ladder instead of the CPU hostreg shortcut)."""
    if request.param == 'kernel':
        prior = {k: os.environ.get(k)
                 for k in ('AMTPU_HOST_FULL', 'AMTPU_HOST_REG')}
        os.environ['AMTPU_HOST_FULL'] = '0'
        os.environ['AMTPU_HOST_REG'] = '0'
        yield 'kernel'
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    else:
        yield 'default'


def build_docs():
    """Six plain map docs plus one 20-concurrent-writer hot doc, so the
    kernel path exercises dispatch, collect, AND the escalation ladder
    in one batch."""
    docs = {('d%d' % i): [
        {'actor': 'a%d' % i, 'seq': s + 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'k%d' % s,
                  'value': s}]}
        for s in range(3)] for i in range(6)}
    docs['hot'] = [
        {'actor': 'w%03d' % a, 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT_ID, 'key': 'k',
                  'value': 'w%03d' % a}]}
        for a in range(20)]
    return docs


def reference_patches():
    """The no-fault run the matrix compares against (per-call fresh
    pool; faults are guaranteed disarmed by the hygiene fixture)."""
    return NativeDocPool().apply_batch(build_docs())


def assert_byte_parity(got, want, skip=()):
    """Per-doc byte parity: every surviving doc's patch must be
    msgpack-byte-identical to the fault-free run."""
    assert set(got) == set(want)
    for doc in want:
        if doc in skip:
            continue
        assert msgpack.packb(got[doc], use_bin_type=True) == \
            msgpack.packb(want[doc], use_bin_type=True), doc


class TestFaultMatrix:
    """Each site x {transient, permanent} x both exec modes."""

    # (site, fires_in): device-path sites cannot fire on the full host
    # path -- those lanes assert the armed-but-unreachable contract
    SITES = [('native.begin', ('default', 'kernel')),
             ('native.mid', ('default', 'kernel')),
             ('device.dispatch', ('kernel',)),
             ('device.collect', ('kernel',)),
             ('escalation.tier', ('kernel',))]

    @pytest.mark.parametrize('site,fires_in',
                             SITES, ids=[s for s, _ in SITES])
    def test_transient_retries_to_parity(self, site, fires_in, exec_mode):
        """Two forced transient faults: the batch must complete with
        results byte-identical to the fault-free run and
        resilience.retry.success >= 1 (the ISSUE-4 acceptance lane)."""
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm(site, 'transient', 1.0, count=2)
        got = NativeDocPool().apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want)
        if exec_mode in fires_in:
            assert snap.get('resilience.fault_injected', 0) == 2, snap
            assert snap.get('resilience.retry.success', 0) >= 1, snap
            assert snap.get('resilience.rollback', 0) >= 2, snap
        else:
            # armed but unreachable in this mode: zero fires, zero cost
            assert snap.get('resilience.fault_injected', 0) == 0, snap
        assert not snap.get('resilience.quarantined'), snap

    @pytest.mark.parametrize('site,fires_in',
                             SITES, ids=[s for s, _ in SITES])
    def test_permanent_quarantines_poison_doc(self, site, fires_in,
                                              exec_mode):
        """A permanent fault pinned to one doc: that doc alone is
        quarantined (per-doc error envelope) and every other doc's
        patch is byte-identical to the fault-free run."""
        if site == 'escalation.tier':
            # no doc scope at the tier dispatch: the hot doc is the only
            # one whose resolution escalates, so an unpinned permanent
            # fault converges on exactly it
            poison, arm_kwargs = 'hot', {}
        else:
            poison, arm_kwargs = POISON, {'match': POISON}
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm(site, 'permanent', 1.0, **arm_kwargs)
        pool = NativeDocPool()
        got = pool.apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        if exec_mode not in fires_in:
            assert_byte_parity(got, want)
            assert snap.get('resilience.fault_injected', 0) == 0, snap
            return
        assert_byte_parity(got, want, skip=(poison,))
        assert resilience.is_quarantined(got[poison]), got[poison]
        assert got[poison]['errorType'] == 'PermanentFault'
        assert snap.get('resilience.quarantined') == 1, snap
        assert snap.get('resilience.bisect.rounds', 0) >= 1, snap
        # nothing of the poison doc committed (rollback accounting)
        faults.disarm()
        assert pool.get_patch(poison)['clock'] == {}
        # ...and the doc heals on a later, fault-free delivery
        healed = pool.apply_changes(poison, build_docs()[poison])
        assert msgpack.packb(healed, use_bin_type=True) == \
            msgpack.packb(want[poison], use_bin_type=True)

    def test_transient_budget_exhaustion_quarantines(self, exec_mode):
        """An unbounded transient fault pinned to one doc exhausts the
        retry budget and degrades into quarantine -- bounded retries,
        not an infinite stall."""
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm('native.mid', 'transient', 1.0, match=POISON)
        got = NativeDocPool().apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want, skip=(POISON,))
        assert resilience.is_quarantined(got[POISON])
        assert snap.get('resilience.retry.exhausted', 0) >= 1, snap
        assert snap.get('resilience.quarantined') == 1, snap

    def test_degraded_path_heals_device_poison(self, exec_mode):
        """AMTPU_DEGRADE=1: a doc whose device path is permanently
        poisoned commits via the full-host path instead of quarantine;
        counted as resilience.degraded, NOT fallback.oracle."""
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm('device.dispatch', 'permanent', 1.0, match=POISON)
        os.environ['AMTPU_DEGRADE'] = '1'
        try:
            got = NativeDocPool().apply_batch(build_docs())
        finally:
            os.environ.pop('AMTPU_DEGRADE', None)
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want)
        if exec_mode == 'kernel':
            assert snap.get('resilience.degraded') == 1, snap
            assert not snap.get('resilience.quarantined'), snap
        assert not snap.get('fallback.oracle'), snap

    def test_checkpoint_load_fault_surfaces_and_clears(self, exec_mode):
        """checkpoint.load faults surface to the caller (the WAL replay
        driver owns the retry policy there); a retry after the fault
        clears restores byte-identical state."""
        src = NativeDocPool()
        want = src.apply_batch(build_docs())
        blobs = {d: src.save(d) for d in build_docs()}
        faults.arm('checkpoint.load', 'transient', 1.0, count=1)
        dst = NativeDocPool()
        with pytest.raises(faults.TransientFault):
            dst.load_batch(blobs)
        assert dst.doc_count() == 0      # nothing half-restored
        dst.load_batch(blobs)            # fault budget spent: clean run
        for d in want:
            assert dst.get_patch(d) == src.get_patch(d)

    def test_env_armed_spec(self, exec_mode):
        """AMTPU_FAULT env syntax arms exactly like the programmatic
        API (the sidecar server subprocess path)."""
        want = reference_patches()
        telemetry.metrics_reset()
        faults.reset('native.begin:transient:1.0:2')
        got = NativeDocPool().apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want)
        assert snap.get('resilience.fault_injected', 0) == 2, snap
        assert snap.get('resilience.retry.success', 0) >= 1, snap

    def test_bad_env_spec_raises(self):
        with pytest.raises(ValueError):
            faults.load_env('nonsense')
        with pytest.raises(ValueError):
            faults.load_env('no.such.site:transient:1.0')
        with pytest.raises(ValueError):
            faults.load_env('native.mid:sometimes:1.0')


class TestShardedIsolation:
    @pytest.mark.parametrize('mode', ['pipeline', 'threads'])
    def test_poison_doc_stays_inside_its_shard(self, mode, exec_mode):
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm('native.mid', 'permanent', 1.0, match=POISON)
        sp = ShardedNativePool(n_shards=4, mode=mode)
        got = sp.apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want, skip=(POISON,))
        assert resilience.is_quarantined(got[POISON])
        assert snap.get('resilience.quarantined') == 1, snap

    @pytest.mark.parametrize('mode', ['pipeline', 'threads'])
    def test_transient_shard_failure_retries_to_parity(self, mode,
                                                       exec_mode):
        want = reference_patches()
        telemetry.metrics_reset()
        faults.arm('native.begin', 'transient', 1.0, count=1)
        sp = ShardedNativePool(n_shards=4, mode=mode)
        got = sp.apply_batch(build_docs())
        snap = telemetry.metrics_snapshot()
        assert_byte_parity(got, want)
        assert snap.get('resilience.retry.success', 0) >= 1, snap

    def test_validation_error_preempts_isolation_atomically(self,
                                                            exec_mode):
        """A begin-level validation error fires before any injected
        fault, so isolation never starts: the whole batch raises AND
        (via rollback) commits nothing -- after dropping the bad doc,
        the still-armed infra fault isolates normally."""
        docs = build_docs()
        docs['bad'] = [{'actor': 'X', 'seq': 1, 'deps': {},
                        'ops': [{'action': 'set', 'obj': 'nonexistent',
                                 'key': 'k', 'value': 1}]}]
        want = reference_patches()
        faults.arm('native.mid', 'permanent', 1.0, match=POISON)
        pool = NativeDocPool()
        from automerge_tpu.errors import AutomergeError
        with pytest.raises(AutomergeError, match='unknown object'):
            pool.apply_batch(docs)
        assert pool.get_patch('d0')['clock'] == {}   # nothing committed
        del docs['bad']
        telemetry.metrics_reset()
        got = pool.apply_batch(docs)
        assert_byte_parity(got, want, skip=(POISON,))
        assert resilience.is_quarantined(got[POISON])
        assert telemetry.metrics_snapshot().get(
            'resilience.quarantined') == 1

    def test_protocol_errors_still_raise(self, exec_mode):
        """Validation errors are NOT infrastructure faults: the
        whole-batch raise contract survives the resilience layer."""
        from automerge_tpu.errors import AutomergeError
        pool = NativeDocPool()
        pool.apply_changes('d', [{'actor': 'A', 'seq': 1, 'deps': {},
                                  'ops': [{'action': 'set', 'obj': ROOT_ID,
                                           'key': 'k', 'value': 1}]}])
        with pytest.raises(AutomergeError):
            pool.apply_changes('d', [{'actor': 'A', 'seq': 1, 'deps': {},
                                      'ops': [{'action': 'set',
                                               'obj': ROOT_ID,
                                               'key': 'k',
                                               'value': 'other'}]}])


# ---------------------------------------------------------------------------
# sidecar chaos
# ---------------------------------------------------------------------------

CHS = [
    {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'set', 'obj': ROOT_ID, 'key': 'bird',
         'value': 'magpie'}]},
    {'actor': 'b', 'seq': 1, 'deps': {'a': 1}, 'ops': [
        {'action': 'makeText', 'obj': 't1'},
        {'action': 'ins', 'obj': 't1', 'key': '_head', 'elem': 1},
        {'action': 'set', 'obj': 't1', 'key': 'b:1', 'value': 'x'},
        {'action': 'link', 'obj': ROOT_ID, 'key': 'text',
         'value': 't1'}]},
]


def _client(**kw):
    from automerge_tpu.sidecar.client import SidecarClient
    return SidecarClient(**kw)


def _uninterrupted_patch():
    with _client() as ref:
        for ch in CHS:
            ref.apply_changes('doc1', [ch])
        return ref.get_patch('doc1')


class TestSidecarSelfHealing:
    def test_sigkill_respawn_replays_wal(self):
        """The ISSUE-4 acceptance lane: SIGKILL mid-session, then a
        subsequent get_patch returns the same patch as an uninterrupted
        session, and healthz exposes the restart count."""
        want = _uninterrupted_patch()
        c = _client()
        try:
            for ch in CHS:
                c.apply_changes('doc1', [ch])
            os.kill(c._proc.pid, signal.SIGKILL)
            time.sleep(0.2)
            assert c.get_patch('doc1') == want
            hz = c.healthz()
            assert hz['restarts'] == 1
            assert c.restarts == 1
            # the healed session keeps working (and keeps its WAL)
            assert c.get_missing_deps('doc1') == {}
        finally:
            c.close()
        # process tree clean: the respawned server is reaped
        assert c._proc is None or c._proc.returncode is not None

    def test_frame_fault_crashes_server_and_client_heals(self):
        """`sidecar.frame` armed in the SERVER via the environment: the
        first request kills the serve loop (simulated crash); the
        client respawns (clean env) and the retried request succeeds."""
        want = _uninterrupted_patch()
        os.environ['AMTPU_FAULT'] = 'sidecar.frame:transient:1.0:1'
        try:
            c = _client()
        finally:
            # respawned servers must NOT re-arm, or the heal loop spins
            os.environ.pop('AMTPU_FAULT', None)
        try:
            for ch in CHS:
                c.apply_changes('doc1', [ch])
            assert c.restarts == 1
            assert c.get_patch('doc1') == want
        finally:
            c.close()

    def test_wal_compaction_round_trip(self):
        """State replays correctly through a compacted WAL (snapshots +
        residual log), not just a raw log."""
        from automerge_tpu.sidecar.client import CheckpointWAL
        want = _uninterrupted_patch()
        c = _client(wal=CheckpointWAL(compact_every=1))
        try:
            for ch in CHS:
                c.apply_changes('doc1', [ch])
            assert c._wal.snapshots         # compaction actually ran
            os.kill(c._proc.pid, signal.SIGKILL)
            time.sleep(0.2)
            assert c.get_patch('doc1') == want
        finally:
            c.close()

    def test_heal_requires_owned_server(self):
        """Satellite: heal means respawning from OUR spawn recipe --
        adopted-process and socket clients must refuse it loudly
        instead of recording a WAL that can never replay."""
        proc = subprocess.Popen(
            [sys.executable, '-m', 'automerge_tpu.sidecar.server'],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=dict(os.environ, PYTHONPATH=REPO), cwd=REPO)
        try:
            with pytest.raises(ValueError, match='self-spawned'):
                _client(proc=proc, heal=True)
        finally:
            proc.kill()
            proc.wait(timeout=10)

    def test_dead_client_refuses_reuse(self):
        """Satellite: after an unhealed ConnectionError the client is
        dead -- reuse raises a clear error instead of desyncing ids."""
        c = _client(heal=False)
        try:
            c.apply_changes('d', [CHS[0]])
            os.kill(c._proc.pid, signal.SIGKILL)
            time.sleep(0.2)
            with pytest.raises(ConnectionError):
                c.get_patch('d')
            with pytest.raises(ConnectionError, match='dead'):
                c.get_patch('d')
        finally:
            c.close()

    def test_internal_error_envelope_keeps_loop_alive(self):
        """Satellite: an unexpected exception out of the pool answers
        the InternalError envelope and bumps sidecar.internal_errors;
        the serve loop (and the pool) survives."""
        from automerge_tpu.sidecar.server import SidecarBackend

        class WoundedPool:
            def __init__(self):
                self.real = NativeDocPool()

            def apply_batch(self, docs):
                raise RuntimeError('XLA ate the batch')

            def __getattr__(self, name):
                return getattr(self.real, name)

        telemetry.metrics_reset()
        backend = SidecarBackend(pool=WoundedPool())
        resp = backend.handle({'id': 7, 'cmd': 'apply_batch',
                               'docs': {'d': [CHS[0]]}})
        assert resp['errorType'] == 'InternalError'
        assert 'XLA ate the batch' in resp['error']
        assert telemetry.metrics_snapshot().get(
            'sidecar.internal_errors') == 1
        # the loop survives: the next request answers normally
        assert backend.handle({'id': 8, 'cmd': 'ping'})['result'] == \
            {'ok': True}

    def test_quarantine_envelope_crosses_the_protocol(self):
        """A permanently poisoned doc surfaces as the per-doc error
        envelope in the apply_batch RESPONSE, and healthz reports the
        degraded/quarantine state."""
        os.environ['AMTPU_FAULT'] = 'native.mid:permanent:1.0'
        try:
            c = _client()
        finally:
            os.environ.pop('AMTPU_FAULT', None)
        try:
            got = c.apply_batch({'d1': [CHS[0]]})
            assert resilience.is_quarantined(got['d1']), got
            hz = c.healthz()
            assert hz['degraded'] is True
            assert hz['resilience']['quarantined'] >= 1
        finally:
            c.close()

    def test_unix_socket_sigterm_unlinks_socket(self):
        """Satellite: SIGTERM closes the listener and unlinks the
        socket path, so a supervised restart never hits 'address
        already in use'."""
        path = os.path.join(tempfile.mkdtemp(), 'amtpu-chaos.sock')
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.Popen(
            [sys.executable, '-m', 'automerge_tpu.sidecar.server',
             '--socket', path], env=env, cwd=REPO)
        try:
            for _ in range(200):
                if os.path.exists(path):
                    break
                time.sleep(0.1)
            assert os.path.exists(path)
            proc.terminate()                  # SIGTERM, not SIGKILL
            assert proc.wait(timeout=20) == 128 + signal.SIGTERM
            assert not os.path.exists(path)
            # the next incarnation binds immediately (no stale socket)
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                srv.bind(path)
            finally:
                srv.close()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
