"""Device-resident incremental state (SURVEY hard part 5, VERDICT r2 #2).

The contract under test: once a big list arena is resident, a subsequent
batch uploads O(batch) rows -- not O(arena) -- and patches stay
byte-identical to the oracle through deletes, undo, and overflow-free
editing.  The C++ env knobs latch per process, so scenarios run in a
subprocess with AMTPU_RESIDENT=1 and a small AMTPU_RESIDENT_MIN.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCENARIO = r"""
import os, sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
from automerge_tpu import trace, backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True

def counts(report, name):
    for line in report.splitlines():
        if name in line:
            return int(line.rsplit('x', 1)[1])
    return 0

pool = NativeDocPool()
st = Backend.init()

# batch 1: build a 600-element text
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(600):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': chr(97 + e % 26)})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs)
st, _ = Backend.apply_changes(st, chs)
rep = trace.report()
assert counts(rep, 'resident.dispatch') == 1, rep
assert counts(rep, 'resident.full_upload_rows') == 600, rep

# batches 2..4: small edits (inserts + deletes) -> delta uploads only
seq = 2
for b in range(3):
    seq += 1
    ops = []
    for i in range(8):
        e += 1
        ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
        ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                    'value': 'X'})
        prev = 'a0:%d' % e
    ops.append({'action': 'del', 'obj': 't', 'key': 'a0:%d' % (b + 3)})
    batch = [{'actor': 'a0', 'seq': seq, 'deps': {}, 'ops': ops}]
    trace.reset()
    pool.apply_changes('doc', batch)
    st, _ = Backend.apply_changes(st, batch)
    rep = trace.report()
    assert counts(rep, 'resident.dispatch') == 1, rep
    # O(batch): exactly the 8 appended rows, NOT the 600-element arena
    assert counts(rep, 'resident.delta_upload_rows') == 8, rep
    assert counts(rep, 'resident.full_upload_rows') == 0, rep

assert pool.get_patch('doc') == Backend.get_patch(st)

# a second writer whose actor id sorts in the middle invalidates ranks
# (correctness over cache retention), then editing resumes resident
mid = [{'actor': 'a00', 'seq': 1,
        'deps': {'a0': seq},
        'ops': [{'action': 'ins', 'obj': 't', 'key': prev,
                 'elem': e + 1},
                {'action': 'set', 'obj': 't', 'key': 'a00:%d' % (e + 1),
                 'value': 'Z'}]}]
trace.reset()
pool.apply_changes('doc', mid)
st, _ = Backend.apply_changes(st, mid)
assert pool.get_patch('doc') == Backend.get_patch(st)

# save/load round trip of the resident doc
blob = pool.save('doc')
pool2 = NativeDocPool()
pool2.load('doc', blob)
assert pool2.get_patch('doc') == pool.get_patch('doc')
print('RESIDENT-OK')
""".replace('REPO_PATH', repr(REPO))


def test_resident_delta_uploads_and_parity():
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16')
    out = subprocess.run([sys.executable, '-c', SCENARIO], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'RESIDENT-OK' in out.stdout


def test_resident_disabled_on_cpu_by_default():
    script = r"""
import sys
sys.path.insert(0, %r)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import trace
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True
pool = NativeDocPool()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
ops = []
prev = '_head'
for i in range(1, 101):
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': i})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%%d' %% i,
                'value': 'x'})
    prev = 'a0:%%d' %% i
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs)
assert 'resident.dispatch' not in trace.report()
print('CPU-DEFAULT-OK')
""" % (REPO,)
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT_MIN='16')
    env.pop('AMTPU_RESIDENT', None)
    out = subprocess.run([sys.executable, '-c', script], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'CPU-DEFAULT-OK' in out.stdout


CROSS_PATH = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
pool = NativeDocPool(); st = Backend.init()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(100):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': 'x'})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
pool.apply_changes('doc', chs); st, _ = Backend.apply_changes(st, chs)
# batch 2 touches the text AND a second list -> NON-resident path
# deletes a char; the cached device ev must be invalidated
b2 = [{'actor': 'a0', 'seq': 3, 'deps': {}, 'ops': [
    {'action': 'makeList', 'obj': 'l2'},
    {'action': 'link', 'obj': ROOT, 'key': 'other', 'value': 'l2'},
    {'action': 'ins', 'obj': 'l2', 'key': '_head', 'elem': 1},
    {'action': 'set', 'obj': 'l2', 'key': 'a0:1', 'value': 9},
    {'action': 'del', 'obj': 't', 'key': 'a0:5'}]}]
pool.apply_changes('doc', b2); st, _ = Backend.apply_changes(st, b2)
# batch 3 is text-only again (resident; stale ev would misindex)
b3 = [{'actor': 'a0', 'seq': 4, 'deps': {}, 'ops': [
    {'action': 'ins', 'obj': 't', 'key': 'a0:10', 'elem': e + 1},
    {'action': 'set', 'obj': 't', 'key': 'a0:%d' % (e + 1),
     'value': 'Z'}]}]
pool.apply_changes('doc', b3); st, _ = Backend.apply_changes(st, b3)
assert pool.get_patch('doc') == Backend.get_patch(st)
print('CROSS-PATH-OK')
""".replace('REPO_PATH', repr(REPO))


def test_non_resident_batch_invalidates_cached_visibility():
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16')
    out = subprocess.run([sys.executable, '-c', CROSS_PATH], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'CROSS-PATH-OK' in out.stdout


SHARDED = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
assert len(jax.devices()) >= 8, jax.devices()
from automerge_tpu import trace, backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True
pool = NativeDocPool(); st = Backend.init()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(300):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': 'x'})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs); st, _ = Backend.apply_changes(st, chs)
rep = trace.report()
assert 'resident.sharded_dispatch' in rep, rep
b2 = [{'actor': 'a0', 'seq': 3, 'deps': {}, 'ops': [
    {'action': 'del', 'obj': 't', 'key': 'a0:7'},
    {'action': 'ins', 'obj': 't', 'key': prev, 'elem': e + 1},
    {'action': 'set', 'obj': 't', 'key': 'a0:%d' % (e + 1),
     'value': 'Z'}]}]
pool.apply_changes('doc', b2); st, _ = Backend.apply_changes(st, b2)
assert pool.get_patch('doc') == Backend.get_patch(st)
print('SHARDED-RESIDENT-OK')
""".replace('REPO_PATH', repr(REPO))


def test_sharded_resident_on_virtual_mesh():
    """The promoted sp path: the pool's default entry point shards the
    element axis over every local device (8 virtual CPU devices here)
    with oracle-identical patches (VERDICT r2 #4).  AMTPU_MESH_SP_MIN
    routes this tiny arena past the sp fence (ISSUE 7): the lane pins
    the sharded kernel's PARITY, while the fence's routing policy has
    its own lanes in test_meshpool.py."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16', AMTPU_MESH_SP_MIN='16',
               XLA_FLAGS='--xla_force_host_platform_device_count=8')
    out = subprocess.run([sys.executable, '-c', SHARDED], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'SHARDED-RESIDENT-OK' in out.stdout


# ---------------------------------------------------------------------------
# Pool-level resident batch state (ISSUE 6): the register/clock path's
# cross-batch cache.  Same subprocess pattern as the arena lanes: the
# AMTPU_RESIDENT* knobs latch per process.
# ---------------------------------------------------------------------------

# Multi-doc, multi-actor table workload: concurrent writes to shared
# root keys (kernel groups), single-actor private keys (the trivial
# route), a same-change duplicate assign (escalation food), deletes.
# Emitted as one builder so every lane below sees the same shape.
BATCH_WORKLOAD = r"""
ROOT = '00000000-0000-0000-0000-000000000000'

def build_round(r, docs=24, actors=4):
    payload = {}
    for d in range(docs):
        chs = []
        for a in range(actors):
            ops = [{'action': 'set', 'obj': ROOT,
                    'key': 'shared%d' % (r % 3),
                    'value': 'a%d r%d' % (a, r)},
                   {'action': 'set', 'obj': ROOT,
                    'key': 'p%d_%d' % (a, r), 'value': d * r + a}]
            if a == 0 and d % 5 == 0:
                # same-change duplicate assign: both survive as conflicts
                ops.append({'action': 'set', 'obj': ROOT,
                            'key': 'dup', 'value': 'x%d' % r})
                ops.append({'action': 'set', 'obj': ROOT,
                            'key': 'dup', 'value': 'y%d' % r})
            if a == 1 and r > 1:
                ops.append({'action': 'del', 'obj': ROOT,
                            'key': 'p0_%d' % (r - 1)})
            # deps empty: actors are mutually concurrent every round
            chs.append({'actor': 'w%d' % a, 'seq': r, 'deps': {},
                        'ops': ops})
        payload['doc%d' % d] = chs
    return payload
"""

BATCH_RESIDENT = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import backend as Backend
from automerge_tpu import faults, trace
from automerge_tpu.native import NativeDocPool
trace.ENABLED = True
WORKLOAD

pool = NativeDocPool()
states = {}

def apply_round(r, docs=24):
    payload = build_round(r, docs=docs)
    pool.apply_batch(payload)
    for d, chs in payload.items():
        st = states.get(d) or Backend.init()
        states[d], _ = Backend.apply_changes(st, chs)

def assert_parity(tag):
    for d, st in states.items():
        got, want = pool.get_patch(d), Backend.get_patch(st)
        assert got == want, '%s: %s diverged' % (tag, d)

# round 1 seeds the table; round 2 is SMALLER than round 1's pow2
# capacity slack, so it must be served by persisted rows (C++ hits)
# with a delta upload of only its own appends
apply_round(1)
apply_round(2, docs=6)
m = trace.metrics_snapshot()
assert m.get('resident.batch_full_uploads', 0) >= 1, m
assert m.get('resident.batch_hits', 0) >= 1, m
assert m.get('resident.batch_hit_rows', 0) >= 1, m
assert_parity('steady')

# cross-path invalidation: a failed batch ROLLS BACK -> the rows it
# appended are stale, the generation bumps, the next batch re-uploads
spec = faults.arm('native.mid', 'permanent')
try:
    pool.apply_batch(build_round(3, docs=6))
    raise SystemExit('armed fault did not fire')
except faults.InjectedFault:
    pass
finally:
    faults.disarm(spec)
apply_round(3, docs=6)   # the SAME round re-applies after rollback
m = trace.metrics_snapshot()
assert m.get('resident.batch_gen_invalidation', 0) >= 1, m
assert_parity('post-rollback')
print('BATCH-RESIDENT-OK')
""".replace('WORKLOAD', BATCH_WORKLOAD).replace('REPO_PATH', repr(REPO))


def test_batch_resident_steady_state_and_rollback_invalidation():
    """Pool-level clock cache: steady-state batches hit persisted rows
    (delta uploads only), rollback invalidates via the generation
    counter, and every patch stays byte-identical to the oracle."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_HOST_FULL='0',
               AMTPU_RESILIENCE='0')
    out = subprocess.run([sys.executable, '-c', BATCH_RESIDENT], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'BATCH-RESIDENT-OK' in out.stdout


WAVE_ERROR_IDENTITY = r"""
import sys
sys.path.insert(0, REPO_PATH)
import os
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import trace
from automerge_tpu.errors import AutomergeError
from automerge_tpu.native import NativeDocPool
trace.ENABLED = True
ROOT = '00000000-0000-0000-0000-000000000000'

def build_payload():
    '''70 docs; payload-order doc 0 and doc 60 each carry a validation
    error on a DIFFERENT unknown object.  The serial contract: the
    FIRST error in application order surfaces (missing-early).'''
    payload = {}
    for d in range(70):
        obj = ROOT
        if d == 0:
            obj = 'missing-early'
        elif d == 60:
            obj = 'missing-late'
        payload['doc%03d' % d] = [
            {'actor': 'w0', 'seq': 1, 'deps': {},
             'ops': [{'action': 'set', 'obj': obj, 'key': 'k',
                      'value': d}]}]
    return payload

os.environ['AMTPU_PIPELINE_MIN_DOCS'] = '8'
errs = {}
for depth in ('1', '4'):
    os.environ['AMTPU_PIPELINE_DEPTH'] = depth
    pool = NativeDocPool()
    try:
        pool.apply_batch(build_payload())
        raise SystemExit('multi-error payload did not raise')
    except AutomergeError as e:
        errs[depth] = str(e)
assert 'missing-early' in errs['1'], errs['1']
assert errs['4'] == errs['1'], (
    'wave path surfaced a different error than serial:\n%r\n%r'
    % (errs['4'], errs['1']))
m = trace.metrics_snapshot()
assert m.get('pipeline.serial_replay', 0) >= 1, m
print('WAVE-ERROR-IDENTITY-OK')
""".replace('REPO_PATH', repr(REPO))


def test_wave_pipeline_error_identity_matches_serial():
    """A multi-error payload must surface the SAME error on the wave
    path as on the serial path (first in application order): pre-emit
    wave failures roll back atomically and replay unpipelined."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_HOST_FULL='0',
               AMTPU_RESILIENCE='0')
    out = subprocess.run([sys.executable, '-c', WAVE_ERROR_IDENTITY],
                         env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'WAVE-ERROR-IDENTITY-OK' in out.stdout
    assert 'RuntimeWarning' not in out.stderr, out.stderr


ACTOR_CAP_DROP = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import backend as Backend
from automerge_tpu import trace
from automerge_tpu.native import NativeDocPool
trace.ENABLED = True
WORKLOAD

pool = NativeDocPool()
states = {}

def apply(payload):
    pool.apply_batch(payload)
    for d, chs in payload.items():
        st = states.get(d) or Backend.init()
        states[d], _ = Backend.apply_changes(st, chs)

# 4 actors <= AMTPU_RESCLK_MAX_ACTORS=5: the pool table seeds on device
apply(build_round(1))
m = trace.metrics_snapshot()
assert m.get('resident.batch_full_uploads', 0) >= 1, m
assert pool._resclk.tab is not None

# two NEW actors push the pool past the cap: C++ permanently disables
# the cache, and the driver must release the device table (the buffer
# is pool-lifetime large and will never be read again)
over = {}
for d in range(6):
    over['doc%d' % d] = [
        {'actor': 'z%d' % a, 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'shared0',
                  'value': 'z%d' % a}]}
        for a in (0, 1)]
apply(over)
m = trace.metrics_snapshot()
assert m.get('resident.batch_cache_dropped', 0) >= 1, m
assert pool._resclk.tab is None

# the pool keeps serving (non-resident) batches with oracle parity
apply(build_round(2))
m = trace.metrics_snapshot()
assert m.get('resident.batch_cache_dropped', 0) == 1, m
for d, st in states.items():
    assert pool.get_patch(d) == Backend.get_patch(st), d
print('CAP-DROP-OK')
""".replace('WORKLOAD', BATCH_WORKLOAD).replace('REPO_PATH', repr(REPO))


def test_batch_resident_actor_cap_releases_device_table():
    """Crossing AMTPU_RESCLK_MAX_ACTORS permanently disables the C++
    cache; the driver must drop its device copy of the clock table (it
    can be hundreds of MB and is never read again) and keep serving
    batches non-resident with oracle parity."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_HOST_FULL='0',
               AMTPU_RESILIENCE='0', AMTPU_RESCLK_MAX_ACTORS='5')
    out = subprocess.run([sys.executable, '-c', ACTOR_CAP_DROP], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'CAP-DROP-OK' in out.stdout


AB_PATCHES = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu.native import NativeDocPool
WORKLOAD

pool = NativeDocPool()
for r in (1, 2, 3):
    pool.apply_batch(build_round(r))
for d in sorted('doc%d' % i for i in range(24)):
    sys.stdout.write('%s %r\n' % (d, pool.get_patch(d)))
""".replace('WORKLOAD', BATCH_WORKLOAD).replace('REPO_PATH', repr(REPO))


def _ab_run(**env_over):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS='cpu', **env_over)
    out = subprocess.run([sys.executable, '-c', AB_PATCHES], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


def test_batch_resident_ab_parity_both_exec_modes():
    """Byte parity of every patch across the resident-clock latch and
    both execution modes: the resident table must be unobservable."""
    ref = _ab_run(AMTPU_HOST_FULL='0', AMTPU_RESIDENT_CLK='1')
    assert ref == _ab_run(AMTPU_HOST_FULL='0', AMTPU_RESIDENT_CLK='0')
    assert ref == _ab_run(AMTPU_HOST_FULL='1')


def test_wave_pipeline_parity_and_staging_alias():
    """Cross-batch double-buffering (ISSUE 6 tentpole c): the wave path
    must be byte-identical to the unpipelined path, and the resident
    delta-upload staging must tolerate host-side mutation as soon as
    the scatter dispatch returns (jax zero-copying a still-in-flight
    numpy buffer is the PR-4 regression class this lane pins)."""
    script = r"""
import sys
sys.path.insert(0, REPO_PATH)
import os
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu.native import NativeDocPool
import automerge_tpu.native.batch_resident as br
WORKLOAD

def run_rounds():
    pool = NativeDocPool()
    for r in (1, 2, 3):
        pool.apply_batch(build_round(r))
    return [pool.get_patch('doc%d' % i) for i in range(24)]

# uncorrupted reference FIRST (unpipelined, no hostile wrapper): the
# hostile arms below must match it, not merely each other -- identical
# corruption in both arms would otherwise pass
os.environ['AMTPU_PIPELINE_DEPTH'] = '1'
os.environ['AMTPU_PIPELINE_MIN_DOCS'] = '4'
ref = run_rounds()

# scribble over the delta-upload staging arrays the moment the scatter
# dispatch returns: if jax zero-copied them, the async execution reads
# garbage and parity below breaks
_orig = br._jit_row_scatter
def _hostile(donate):
    fn = _orig(donate)
    def run(tab, idx, rows):
        out = fn(tab, idx, rows)
        idx.fill(127)
        rows.fill(127)
        return out
    return run
br._jit_row_scatter = _hostile

results = {}
for depth in ('1', '4'):
    os.environ['AMTPU_PIPELINE_DEPTH'] = depth
    results[depth] = run_rounds()
assert results['1'] == ref, 'hostile staging mutation corrupted results'
assert results['4'] == ref, 'wave path diverged from clean reference'
print('WAVE-PARITY-OK')
""".replace('WORKLOAD', BATCH_WORKLOAD).replace('REPO_PATH', repr(REPO))
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_HOST_FULL='0')
    out = subprocess.run([sys.executable, '-c', script], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'WAVE-PARITY-OK' in out.stdout
