"""Device-resident incremental state (SURVEY hard part 5, VERDICT r2 #2).

The contract under test: once a big list arena is resident, a subsequent
batch uploads O(batch) rows -- not O(arena) -- and patches stay
byte-identical to the oracle through deletes, undo, and overflow-free
editing.  The C++ env knobs latch per process, so scenarios run in a
subprocess with AMTPU_RESIDENT=1 and a small AMTPU_RESIDENT_MIN.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCENARIO = r"""
import os, sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
from automerge_tpu import trace, backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True

def counts(report, name):
    for line in report.splitlines():
        if name in line:
            return int(line.rsplit('x', 1)[1])
    return 0

pool = NativeDocPool()
st = Backend.init()

# batch 1: build a 600-element text
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(600):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': chr(97 + e % 26)})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs)
st, _ = Backend.apply_changes(st, chs)
rep = trace.report()
assert counts(rep, 'resident.dispatch') == 1, rep
assert counts(rep, 'resident.full_upload_rows') == 600, rep

# batches 2..4: small edits (inserts + deletes) -> delta uploads only
seq = 2
for b in range(3):
    seq += 1
    ops = []
    for i in range(8):
        e += 1
        ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
        ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                    'value': 'X'})
        prev = 'a0:%d' % e
    ops.append({'action': 'del', 'obj': 't', 'key': 'a0:%d' % (b + 3)})
    batch = [{'actor': 'a0', 'seq': seq, 'deps': {}, 'ops': ops}]
    trace.reset()
    pool.apply_changes('doc', batch)
    st, _ = Backend.apply_changes(st, batch)
    rep = trace.report()
    assert counts(rep, 'resident.dispatch') == 1, rep
    # O(batch): exactly the 8 appended rows, NOT the 600-element arena
    assert counts(rep, 'resident.delta_upload_rows') == 8, rep
    assert counts(rep, 'resident.full_upload_rows') == 0, rep

assert pool.get_patch('doc') == Backend.get_patch(st)

# a second writer whose actor id sorts in the middle invalidates ranks
# (correctness over cache retention), then editing resumes resident
mid = [{'actor': 'a00', 'seq': 1,
        'deps': {'a0': seq},
        'ops': [{'action': 'ins', 'obj': 't', 'key': prev,
                 'elem': e + 1},
                {'action': 'set', 'obj': 't', 'key': 'a00:%d' % (e + 1),
                 'value': 'Z'}]}]
trace.reset()
pool.apply_changes('doc', mid)
st, _ = Backend.apply_changes(st, mid)
assert pool.get_patch('doc') == Backend.get_patch(st)

# save/load round trip of the resident doc
blob = pool.save('doc')
pool2 = NativeDocPool()
pool2.load('doc', blob)
assert pool2.get_patch('doc') == pool.get_patch('doc')
print('RESIDENT-OK')
""".replace('REPO_PATH', repr(REPO))


def test_resident_delta_uploads_and_parity():
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16')
    out = subprocess.run([sys.executable, '-c', SCENARIO], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'RESIDENT-OK' in out.stdout


def test_resident_disabled_on_cpu_by_default():
    script = r"""
import sys
sys.path.insert(0, %r)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import trace
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True
pool = NativeDocPool()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
ops = []
prev = '_head'
for i in range(1, 101):
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': i})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%%d' %% i,
                'value': 'x'})
    prev = 'a0:%%d' %% i
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs)
assert 'resident.dispatch' not in trace.report()
print('CPU-DEFAULT-OK')
""" % (REPO,)
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT_MIN='16')
    env.pop('AMTPU_RESIDENT', None)
    out = subprocess.run([sys.executable, '-c', script], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'CPU-DEFAULT-OK' in out.stdout


CROSS_PATH = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
pool = NativeDocPool(); st = Backend.init()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(100):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': 'x'})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
pool.apply_changes('doc', chs); st, _ = Backend.apply_changes(st, chs)
# batch 2 touches the text AND a second list -> NON-resident path
# deletes a char; the cached device ev must be invalidated
b2 = [{'actor': 'a0', 'seq': 3, 'deps': {}, 'ops': [
    {'action': 'makeList', 'obj': 'l2'},
    {'action': 'link', 'obj': ROOT, 'key': 'other', 'value': 'l2'},
    {'action': 'ins', 'obj': 'l2', 'key': '_head', 'elem': 1},
    {'action': 'set', 'obj': 'l2', 'key': 'a0:1', 'value': 9},
    {'action': 'del', 'obj': 't', 'key': 'a0:5'}]}]
pool.apply_changes('doc', b2); st, _ = Backend.apply_changes(st, b2)
# batch 3 is text-only again (resident; stale ev would misindex)
b3 = [{'actor': 'a0', 'seq': 4, 'deps': {}, 'ops': [
    {'action': 'ins', 'obj': 't', 'key': 'a0:10', 'elem': e + 1},
    {'action': 'set', 'obj': 't', 'key': 'a0:%d' % (e + 1),
     'value': 'Z'}]}]
pool.apply_changes('doc', b3); st, _ = Backend.apply_changes(st, b3)
assert pool.get_patch('doc') == Backend.get_patch(st)
print('CROSS-PATH-OK')
""".replace('REPO_PATH', repr(REPO))


def test_non_resident_batch_invalidates_cached_visibility():
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16')
    out = subprocess.run([sys.executable, '-c', CROSS_PATH], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'CROSS-PATH-OK' in out.stdout


SHARDED = r"""
import sys
sys.path.insert(0, REPO_PATH)
import jax; jax.config.update('jax_platforms', 'cpu')
assert len(jax.devices()) >= 8, jax.devices()
from automerge_tpu import trace, backend as Backend
from automerge_tpu.native import NativeDocPool
ROOT = '00000000-0000-0000-0000-000000000000'
trace.ENABLED = True
pool = NativeDocPool(); st = Backend.init()
chs = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
    {'action': 'makeText', 'obj': 't'},
    {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 't'}]}]
prev, e = '_head', 0
ops = []
for i in range(300):
    e += 1
    ops.append({'action': 'ins', 'obj': 't', 'key': prev, 'elem': e})
    ops.append({'action': 'set', 'obj': 't', 'key': 'a0:%d' % e,
                'value': 'x'})
    prev = 'a0:%d' % e
chs.append({'actor': 'a0', 'seq': 2, 'deps': {}, 'ops': ops})
trace.reset()
pool.apply_changes('doc', chs); st, _ = Backend.apply_changes(st, chs)
rep = trace.report()
assert 'resident.sharded_dispatch' in rep, rep
b2 = [{'actor': 'a0', 'seq': 3, 'deps': {}, 'ops': [
    {'action': 'del', 'obj': 't', 'key': 'a0:7'},
    {'action': 'ins', 'obj': 't', 'key': prev, 'elem': e + 1},
    {'action': 'set', 'obj': 't', 'key': 'a0:%d' % (e + 1),
     'value': 'Z'}]}]
pool.apply_changes('doc', b2); st, _ = Backend.apply_changes(st, b2)
assert pool.get_patch('doc') == Backend.get_patch(st)
print('SHARDED-RESIDENT-OK')
""".replace('REPO_PATH', repr(REPO))


def test_sharded_resident_on_virtual_mesh():
    """The promoted sp path: the pool's default entry point shards the
    element axis over every local device (8 virtual CPU devices here)
    with oracle-identical patches (VERDICT r2 #4)."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', AMTPU_RESIDENT='1',
               AMTPU_RESIDENT_MIN='16',
               XLA_FLAGS='--xla_force_host_platform_device_count=8')
    out = subprocess.run([sys.executable, '-c', SHARDED], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'SHARDED-RESIDENT-OK' in out.stdout
