"""Sharded-vs-unsharded differential tests for the mesh execution path.

Runs on the virtual 8-device CPU mesh (see conftest.py): the shard_map step
with dp=4, sp=2 must produce exactly the outputs of the single-device
pipeline -- collectives (pmax over dp, psum over sp) included.
"""

import jax
import numpy as np
import pytest

from automerge_tpu.ops import list_rank
from automerge_tpu.parallel import mesh as M
from automerge_tpu.parallel import replica


@pytest.fixture(scope='module')
def mesh():
    assert len(jax.devices()) >= 8
    return M.make_mesh(8)


def test_mesh_axes(mesh):
    assert mesh.shape['dp'] * mesh.shape['sp'] == 8
    assert mesh.shape['sp'] == 2


def test_sharded_step_matches_single(mesh):
    sp = mesh.shape['sp']
    batch = M.demo_batch(n_docs=2 * mesh.shape['dp'], n_changes=4,
                         n_actors=4, n_regs=8, n_elems=8 * sp,
                         n_list_ops=12)
    n_iters = list_rank.ceil_log2(batch['eo'].shape[1]) + 1

    step = M.build_sharded_step(mesh, n_linearize_iters=n_iters, chunk=4)
    out = step(M.shard_batch(mesh, batch))
    ref = M.single_step(batch, n_linearize_iters=n_iters)

    for key in ref:
        np.testing.assert_array_equal(
            np.asarray(out[key]), np.asarray(ref[key]), err_msg=key)


def test_graft_entry_single_chip():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert 'indexes' in out and 'frontier' in out


def test_graft_dryrun_multichip(monkeypatch):
    # the driver runs the dryrun at full scale (2048-doc scaling table);
    # in the suite the same code paths run with a reduced doc count --
    # the 16640-element resident arena stays full-size because the
    # unconditional sharded-dispatch assert needs it past the latched
    # AMTPU_RESIDENT_MIN default
    monkeypatch.setenv('AMTPU_DRYRUN_DOCS', '128')
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_replica_deficits():
    clocks = np.array([[3, 0, 1],
                       [1, 2, 1],
                       [0, 0, 4]], np.int32)
    frontier, deficit = replica.replica_deficits(clocks)
    np.testing.assert_array_equal(frontier, [3, 2, 4])
    np.testing.assert_array_equal(deficit, [[0, 2, 3], [2, 0, 3], [3, 2, 0]])


def test_want_matrix():
    clocks = np.array([[1, 0], [0, 2]], np.int32)
    have = np.array([1, 2], np.int32)
    need, from_seq, to_seq = replica.want_matrix(clocks, have)
    np.testing.assert_array_equal(need, [[False, True], [True, False]])
    np.testing.assert_array_equal(from_seq, clocks)
    np.testing.assert_array_equal(to_seq, [[1, 2], [1, 2]])
