"""Fleet aggregation tests (ISSUE 16): mergeable SLO window slots
(element-wise sums recomputed through the SAME pure function each
replica's healthz uses -- never averaged percentiles), headroom/skew
aggregation, scrape-error degradation, and the amtpu_top restart
detection + fleet rendering satellites."""

import io
import json
import os
import sys

import pytest

from automerge_tpu import telemetry
from automerge_tpu.telemetry import QUEUE_WAIT_BUCKETS, attribution, fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, 'tools'))

import amtpu_fleet  # noqa: E402
import amtpu_top  # noqa: E402

NB = len(QUEUE_WAIT_BUCKETS) + 1     # bucket counts incl. +Inf


@pytest.fixture(autouse=True)
def _isolate():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


def _slot(hits, breaches=0):
    """One raw slot entry ``[bucket_counts, total, breaches]`` from
    {bucket_index: n}."""
    counts = [0] * NB
    for b, n in hits.items():
        counts[b] += n
    return [counts, sum(hits.values()), breaches]


# ---------------------------------------------------------------------------
# merge_slots
# ---------------------------------------------------------------------------

def test_merge_slots_sums_elementwise_and_normalizes_keys():
    # replica A's slot keys are ints (in-process snapshot), replica B's
    # are strings (JSON wire) -- both must land in the same merged slot
    a = {'mutate': {100: _slot({3: 5}, breaches=1)}}
    b = {'mutate': {'100': _slot({3: 2, 7: 1})},
         'read': {'101': _slot({0: 4})}}
    merged = fleet.merge_slots([a, b])
    m = merged['mutate'][100]
    assert m[0][3] == 7 and m[0][7] == 1
    assert m[1] == 8 and m[2] == 1
    assert merged['read'][101][1] == 4


def test_merge_slots_pads_short_count_lists():
    a = {'read': {5: [[1, 2], 3, 0]}}
    b = {'read': {5: [[1, 1, 1], 3, 1]}}
    m = fleet.merge_slots([a, b])['read'][5]
    assert m[0] == [2, 3, 1] and m[1] == 6 and m[2] == 1


def test_merged_section_bit_identical_to_single_replica():
    """The load-bearing property: splitting one replica's traffic
    across two replicas and merging their slots reproduces the single
    replica's SLO section EXACTLY (same percentiles, same burn), not
    approximately."""
    now_slot = 101
    whole = {'mutate': {99: _slot({4: 10, 9: 2}, breaches=1),
                        100: _slot({4: 6}),
                        101: _slot({2: 3, 12: 1}, breaches=1)},
             'read': {100: _slot({1: 20})},
             'control': {}}
    half_a = {'mutate': {99: _slot({4: 10}, breaches=1),
                         101: _slot({2: 3})},
              'read': {100: _slot({1: 8})},
              'control': {}}
    half_b = {'mutate': {99: _slot({9: 2}),
                         100: _slot({4: 6}),
                         101: _slot({12: 1}, breaches=1)},
              'read': {'100': _slot({1: 12})},
              'control': {}}
    merged = fleet.merge_slots([half_a, half_b])
    got = attribution.section_from_slots(merged, now_slot=now_slot)
    want = attribution.section_from_slots(whole, now_slot=now_slot)
    assert got == want
    # sanity: the section actually carries signal
    assert want['classes']['mutate']['3600s']['count'] == 22
    assert want['classes']['mutate']['3600s']['p99_ms'] > 0


def test_window_counts_additive_across_replicas():
    now_slot = 50
    a = {'mutate': {49: _slot({3: 4})}, 'read': {}, 'control': {}}
    b = {'mutate': {49: _slot({3: 6}), 50: _slot({5: 1})},
         'read': {}, 'control': {}}
    sec_a = attribution.section_from_slots(a, now_slot=now_slot)
    sec_b = attribution.section_from_slots(b, now_slot=now_slot)
    sec_m = attribution.section_from_slots(fleet.merge_slots([a, b]),
                                           now_slot=now_slot)
    for w in ('60s', '300s', '3600s'):
        assert sec_m['classes']['mutate'][w]['count'] == \
            sec_a['classes']['mutate'][w]['count'] + \
            sec_b['classes']['mutate'][w]['count']


# ---------------------------------------------------------------------------
# fleet_section / headroom / degradation
# ---------------------------------------------------------------------------

def _good_scrape(rid, used, budget, slots=None):
    return {'url': 'http://%s:9464' % rid,
            'replica_id': rid,
            'uptime_s': 12.5,
            'healthz': {'capacity': {
                'headroom': {'used_bytes': used, 'budget_bytes': budget,
                             'pressure': used / budget if budget else 0.0,
                             'exhaustion_s': None},
                'totals': {'arena_bytes': used, 'egress_bytes': 0}}},
            'slots': slots or {'mutate': {10: _slot({3: 2})},
                               'read': {}, 'control': {}}}


def test_fleet_section_degrades_on_scrape_error():
    good = _good_scrape('r1', 100, 1000)
    bad = {'url': 'http://dead:9464', 'error': 'URLError: refused'}
    section = fleet.fleet_section([good, bad], now_slot=11)
    assert [r['replica_id'] for r in section['replicas']] == ['r1']
    assert section['errors'] == [{'url': 'http://dead:9464',
                                  'error': 'URLError: refused'}]
    # the merged SLO section is the SURVIVOR's section, not poisoned
    assert section['slo']['classes']['mutate']['3600s']['count'] == 2


def test_fleet_headroom_aggregates_and_skews():
    hr = fleet.fleet_headroom([_good_scrape('r1', 100, 1000),
                               _good_scrape('r2', 900, 1000)])
    assert hr['used_bytes'] == 1000 and hr['budget_bytes'] == 2000
    assert hr['pressure'] == 0.5
    assert hr['pressure_skew'] == 0.8          # 0.9 - 0.1
    assert [r['replica_id'] for r in hr['replicas']] == ['r1', 'r2']


def test_scrape_unreachable_returns_error_row():
    row = fleet.scrape('http://127.0.0.1:9', timeout=0.5)
    assert row['url'] == 'http://127.0.0.1:9'
    assert 'error' in row
    assert telemetry.metrics_snapshot().get('fleet.scrape_errors') == 1.0


def test_amtpu_fleet_render_smoke():
    good = _good_scrape('r1', 100, 1000)
    bad = {'url': 'http://dead:9464', 'error': 'URLError: refused'}
    section = fleet.fleet_section([good, bad], now_slot=11)
    out = io.StringIO()
    amtpu_fleet.render([good, bad], section, out=out)
    text = out.getvalue()
    assert '1 replicas up, 1 unreachable' in text
    assert 'r1' in text and 'DOWN' in text
    assert 'slo (merged windows' in text and 'headroom:' in text


# ---------------------------------------------------------------------------
# amtpu_top restart detection (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def test_counters_reset_detects_backwards_runtime():
    assert amtpu_top.counters_reset(
        {}, {}, {'slo.requests': 2.0}, {'slo.requests': 10.0})
    assert not amtpu_top.counters_reset(
        {}, {}, {'slo.requests': 11.0}, {'slo.requests': 10.0})


def test_counters_reset_detects_backwards_stage_histogram():
    prev = {'total': {'sum': 50.0, 'count': 9.0}}
    assert amtpu_top.counters_reset(
        {'total': {'sum': 1.0, 'count': 1.0}}, prev, {}, {})
    assert not amtpu_top.counters_reset(
        {'total': {'sum': 50.0, 'count': 9.0}}, prev, {}, {})
    # first poll: no baseline, never "restarted"
    assert not amtpu_top.counters_reset({}, None, {}, None)


def test_render_restarted_clamps_rate_and_marks_frame():
    health = {'uptime_s': 1.2, 'scheduler': {}, 'slo': {},
              'recorder': {}, 'resilience': {}}
    stages = {'total': {'sum': 4.0, 'count': 2.0}}
    runtime = {'slo.requests': 2.0}
    # a naive delta against the dead process's counters would be
    # negative; the frame clamps at 0 and carries the marker
    frame = amtpu_top.render(health, stages, None, runtime,
                             {'slo.requests': 50.0}, 2.0,
                             restarted=True)
    assert 'RESTARTED' in frame
    assert 'req/s 0.0' in frame
    normal = amtpu_top.render(health, stages, None, runtime, None, 2.0)
    assert 'RESTARTED' not in normal


def test_amtpu_top_requires_fleet_for_multiple_urls():
    with pytest.raises(SystemExit):
        amtpu_top.main(['--url', 'http://a', '--url', 'http://b',
                        '--once'])


def test_amtpu_fleet_once_json_rc(monkeypatch, capsys):
    """--once --json against stubbed scrapes: JSON on stdout, rc 1
    when any replica was unreachable, 0 when all answered."""
    good = _good_scrape('r1', 100, 1000)
    bad = {'url': 'http://dead:9464', 'error': 'URLError: refused'}

    def fake_scrape_fleet(urls, timeout=2.0):
        rows = [bad if 'dead' in u else good for u in urls]
        return rows, fleet.fleet_section(rows, now_slot=11)

    monkeypatch.setattr(fleet, 'scrape_fleet', fake_scrape_fleet)
    rc = amtpu_fleet.main(['--url', 'http://a', '--url', 'http://dead',
                           '--once', '--json'])
    assert rc == 1
    section = json.loads(capsys.readouterr().out.strip())
    assert [r['replica_id'] for r in section['replicas']] == ['r1']
    rc = amtpu_fleet.main(['--url', 'http://a', '--once', '--json'])
    assert rc == 0
