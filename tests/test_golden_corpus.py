"""Replays the golden corpus mechanically derived from the reference's own
backend test fixtures (`/root/reference/test/backend_test.js`, extracted
by tools/extract_golden_corpus.py) against every backend: the scalar
oracle, both pools, and the sidecar protocol surface.

The expected patches are the reference suite's own assertions -- this is
the differential-testing seam SURVEY.md section 4 calls for: hand-built
change JSON in, byte-identical patch JSON out.
"""

import json
import os
import re

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
from automerge_tpu.parallel.engine import TPUDocPool
from automerge_tpu.sidecar.server import SidecarBackend

CORPUS = os.path.join(os.path.dirname(__file__), 'golden',
                      'backend_corpus.json')
with open(CORPUS) as f:
    _corpus = json.load(f)
CASES = _corpus['cases']


def case_ids():
    return [c['name'].replace(' ', '-') for c in CASES]


def run_against_oracle(case):
    state = Backend.init()
    for step in case['steps']:
        if step['op'] == 'apply_changes':
            state, patch = Backend.apply_changes(state, step['changes'])
        elif step['op'] == 'apply_local_change':
            state, patch = Backend.apply_local_change(state,
                                                      dict(step['request']))
        elif step['op'] == 'apply_local_change_error':
            with pytest.raises(Exception, match=step['error_match']):
                Backend.apply_local_change(state, dict(step['request']))
            continue
        elif step['op'] == 'get_patch':
            patch = Backend.get_patch(state)
        if 'expected' in step:
            assert patch == step['expected'], step['op']


def run_against_pool(case, pool, doc='d'):
    for step in case['steps']:
        if step['op'] == 'apply_changes':
            patch = pool.apply_changes(doc, step['changes'])
        elif step['op'] == 'apply_local_change':
            patch = pool.apply_local_change(doc, dict(step['request']))
        elif step['op'] == 'apply_local_change_error':
            with pytest.raises(Exception, match=step['error_match']):
                pool.apply_local_change(doc, dict(step['request']))
            continue
        elif step['op'] == 'get_patch':
            patch = pool.get_patch(doc)
        if 'expected' in step:
            assert patch == step['expected'], step['op']


def run_against_sidecar(case, backend, doc='d'):
    rid = [0]

    def call(cmd, **kw):
        rid[0] += 1
        return backend.handle(dict(kw, id=rid[0], cmd=cmd, doc=doc))

    for step in case['steps']:
        if step['op'] == 'apply_changes':
            resp = call('apply_changes', changes=step['changes'])
        elif step['op'] == 'apply_local_change':
            resp = call('apply_local_change', request=dict(step['request']))
        elif step['op'] == 'apply_local_change_error':
            resp = call('apply_local_change', request=dict(step['request']))
            assert 'error' in resp
            assert re.search(step['error_match'], resp['error'])
            continue
        elif step['op'] == 'get_patch':
            resp = call('get_patch')
        assert 'error' not in resp, resp
        if 'expected' in step:
            assert resp['result'] == step['expected'], step['op']


@pytest.mark.parametrize('case', CASES, ids=case_ids())
def test_oracle_matches_reference_fixtures(case):
    run_against_oracle(case)


@pytest.mark.parametrize('case', CASES, ids=case_ids())
def test_native_pool_matches_reference_fixtures(case):
    run_against_pool(case, NativeDocPool())


@pytest.mark.parametrize('case', CASES, ids=case_ids())
def test_tpu_pool_matches_reference_fixtures(case):
    run_against_pool(case, TPUDocPool())


@pytest.mark.parametrize('case', CASES, ids=case_ids())
def test_sidecar_matches_reference_fixtures(case):
    run_against_sidecar(case, SidecarBackend())


def test_corpus_covers_the_reference_suite():
    """The corpus must track the reference file: every it-block is either
    extracted or explicitly skipped with a reason."""
    ref = '/root/reference/test/backend_test.js'
    if not os.path.exists(ref):
        pytest.skip('reference suite %s not present on this host; the '
                    'committed corpus is still replayed by the fixture '
                    'tests above' % ref)
    src = open(ref).read()
    its = re.findall(r"\bit\('([^']+)'", src)
    covered = {c['name'] for c in CASES} | \
        {s['name'] for s in _corpus['skipped']}
    assert set(its) == covered
    assert len(CASES) >= 18
