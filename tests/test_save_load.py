"""Checkpoint/resume: save() dumps the application-order change history,
load() restores it as ONE batched replay (the reference's save/load is the
same log-replay model, `/root/reference/src/automerge.js:10-17,45-52`, but
scalar; round-tripping must reproduce the document byte-identically).
"""

import random

import pytest

from automerge_tpu.errors import RangeError
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.parallel.engine import TPUDocPool

ROOT = '00000000-0000-0000-0000-000000000000'

POOLS = [NativeDocPool, TPUDocPool, lambda: ShardedNativePool(n_shards=2)]


def build_history(pool, doc='d', seed=3):
    rng = random.Random(seed)
    pool.apply_changes(doc, [
        {'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeText', 'obj': 'T'},
                 {'action': 'ins', 'obj': 'T', 'key': '_head', 'elem': 1},
                 {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'x'},
                 {'action': 'link', 'obj': ROOT, 'key': 'text',
                  'value': 'T'}]}])
    # interleaved concurrent edits from two actors, applied in a specific
    # order (replay must preserve it for byte-identical materialization)
    for seq in range(1, 6):
        for actor in ('B', 'C'):
            elem = 10 * seq + (1 if actor == 'B' else 2)
            pool.apply_changes(doc, [
                {'actor': actor, 'seq': seq, 'deps': {'A': 1},
                 'ops': [{'action': 'ins', 'obj': 'T', 'key': 'A:1',
                          'elem': elem},
                         {'action': 'set', 'obj': 'T',
                          'key': '%s:%d' % (actor, elem),
                          'value': chr(97 + seq)},
                         {'action': 'set', 'obj': ROOT,
                          'key': 'k%d' % rng.randrange(3),
                          'value': seq}]}])


@pytest.mark.parametrize('make_pool', POOLS)
def test_save_load_round_trip(make_pool):
    pool = make_pool()
    build_history(pool)
    want = pool.get_patch('d')
    blob = pool.save('d')
    assert isinstance(blob, bytes)

    fresh = make_pool()
    patch = fresh.load('d2', blob)
    assert patch == want
    assert fresh.get_patch('d2') == want
    # the restored doc keeps full semantics: history ships, edits apply
    assert fresh.get_missing_changes('d2', {}) \
        == pool.get_missing_changes('d', {})
    fresh.apply_changes('d2', [
        {'actor': 'B', 'seq': 6, 'deps': {'B': 5},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'post',
                  'value': 1}]}])
    assert fresh.get_clock('d2')['clock']['B'] == 6


def test_checkpoints_are_cross_pool_compatible():
    """An engine-pool checkpoint restores into the native pool and vice
    versa (one wire format)."""
    tpool = TPUDocPool()
    build_history(tpool)
    blob = tpool.save('d')
    npool = NativeDocPool()
    assert npool.load('x', blob) == tpool.get_patch('d')

    blob2 = npool.save('x')
    tpool2 = TPUDocPool()
    assert tpool2.load('y', blob2) == npool.get_patch('x')


@pytest.mark.parametrize('make_pool', [NativeDocPool, TPUDocPool])
def test_load_rejects_garbage(make_pool):
    pool = make_pool()
    with pytest.raises(RangeError, match='checkpoint'):
        pool.load('d', b'\x81\xa1x\x01')


def test_empty_doc_round_trips():
    pool = NativeDocPool()
    blob = pool.save('never-touched')
    fresh = NativeDocPool()
    patch = fresh.load('d', blob)
    assert patch['diffs'] == [] and patch['clock'] == {}


def test_load_batch_restores_many_docs_in_one_pass():
    pool = NativeDocPool()
    for d in ('a', 'b', 'c'):
        build_history(pool, doc=d, seed=ord(d))
    blobs = {d: pool.save(d) for d in ('a', 'b', 'c')}
    fresh = ShardedNativePool(n_shards=2)
    fresh.load_batch(blobs)
    for d in ('a', 'b', 'c'):
        assert fresh.get_patch(d) == pool.get_patch(d)
    with pytest.raises(RangeError, match='checkpoint'):
        fresh.load_batch({'x': b'garbage'})


@pytest.mark.parametrize('make_pool', [NativeDocPool, TPUDocPool])
@pytest.mark.parametrize('garbage', [b'\x90', b'garbage', b'\xc0'])
def test_load_rejects_all_malformed_shapes(make_pool, garbage):
    with pytest.raises(RangeError, match='checkpoint'):
        make_pool().load('d', garbage)


def test_sidecar_save_load_survives_json_framing():
    """Checkpoints are binary; the sidecar base64-wraps them so the
    default JSON-lines framing can carry them round trip."""
    import io
    import json as _json
    from automerge_tpu.sidecar.server import SidecarBackend, serve_stream
    reqs = [
        {'id': 1, 'cmd': 'apply_changes', 'doc': 'd', 'changes': [
            {'actor': 'A', 'seq': 1, 'deps': {},
             'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                      'value': 7}]}]},
        {'id': 2, 'cmd': 'save', 'doc': 'd'},
    ]
    rfile = io.BytesIO(('\n'.join(_json.dumps(r) for r in reqs))
                       .encode() + b'\n')
    wfile = io.BytesIO()
    backend = SidecarBackend()
    serve_stream(rfile, wfile, use_msgpack=False, backend=backend)
    lines = [_json.loads(x) for x in wfile.getvalue().splitlines()]
    assert all('error' not in r for r in lines), lines
    blob = lines[1]['result']['checkpoint_b64']
    # restore through the same JSON framing into a fresh doc
    req3 = {'id': 3, 'cmd': 'load', 'doc': 'd2', 'data': blob}
    rfile = io.BytesIO((_json.dumps(req3) + '\n').encode())
    wfile = io.BytesIO()
    serve_stream(rfile, wfile, use_msgpack=False, backend=backend)
    out = _json.loads(wfile.getvalue().splitlines()[0])
    assert 'error' not in out, out
    assert out['result'] == backend.pool.get_patch('d')
    # malformed base64 maps to a protocol error, not a crashed loop
    req4 = {'id': 4, 'cmd': 'load', 'doc': 'd3', 'data': '!!not-base64!!'}
    rfile = io.BytesIO((_json.dumps(req4) + '\n').encode())
    wfile = io.BytesIO()
    serve_stream(rfile, wfile, use_msgpack=False, backend=backend)
    out = _json.loads(wfile.getvalue().splitlines()[0])
    assert out.get('errorType') == 'RangeError'
