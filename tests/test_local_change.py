"""Differential tests for apply_local_change / undo / redo across all three
backends (scalar oracle, TPUDocPool, NativeDocPool) plus the sidecar.

The reference semantics under test (`/root/reference/backend/index.js:175-310`,
`backend/op_set.js:193-200, 233-250, 296-308`):
  * undoable changes capture inverse ops ONLY for top-level assignments --
    assigns into objects created by the same change are skipped (the
    newObjects gate); round 1 shipped this wrong in the sidecar.
  * undo builds redo ops from the CURRENT field state before applying.
  * patches report real canUndo/canRedo.
"""

import random

import pytest

from automerge_tpu.backend import (apply_local_change, get_missing_changes,
                                   get_patch, init)
from automerge_tpu.errors import RangeError
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.parallel.engine import TPUDocPool
from automerge_tpu.sidecar.server import SidecarBackend

ROOT = '00000000-0000-0000-0000-000000000000'


def drive_oracle(reqs):
    state = init()
    patches = []
    for r in reqs:
        state, p = apply_local_change(state, dict(r))
        patches.append(p)
    return state, patches


def drive_pool(pool, reqs, doc='d'):
    return [pool.apply_local_change(doc, dict(r)) for r in reqs]


def assert_three_way(reqs):
    state, oracle = drive_oracle(reqs)
    npool, tpool = NativeDocPool(), TPUDocPool()
    nat = drive_pool(npool, reqs)
    tpu = drive_pool(tpool, reqs)
    for i, (o, n, t) in enumerate(zip(oracle, nat, tpu)):
        assert o == n, 'native patch mismatch at request %d' % i
        assert o == t, 'tpu-pool patch mismatch at request %d' % i
    assert get_patch(state) == npool.get_patch('d') == tpool.get_patch('d')
    hist = get_missing_changes(state, {})
    assert hist == npool.get_missing_changes('d', {})
    assert hist == tpool.get_missing_changes('d', {})


def test_undo_skips_same_change_object_creation():
    """The round-1 sidecar bug: a change that creates an object and assigns
    into it must capture inverse ops only for the top-level link, so undo
    emits no diff for the nested assign (op_set.js topLevel gate)."""
    reqs = [
        {'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeList', 'obj': 'L1'},
                 {'action': 'ins', 'obj': 'L1', 'key': '_head', 'elem': 1},
                 {'action': 'set', 'obj': 'L1', 'key': 'A:1', 'value': 'x'},
                 {'action': 'link', 'obj': ROOT, 'key': 'list',
                  'value': 'L1'}]},
        {'requestType': 'undo', 'actor': 'A', 'seq': 2, 'deps': {}},
    ]
    state, oracle = drive_oracle(reqs)
    # the undo patch must only remove the top-level link
    undo_diffs = oracle[1]['diffs']
    assert all(d.get('obj') != 'L1' for d in undo_diffs)
    assert_three_way(reqs)


def test_undo_redo_round_trips():
    reqs = [
        {'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 1}]},
        {'requestType': 'change', 'actor': 'A', 'seq': 2, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 2}]},
        {'requestType': 'undo', 'actor': 'A', 'seq': 3, 'deps': {}},
        {'requestType': 'undo', 'actor': 'A', 'seq': 4, 'deps': {}},
        {'requestType': 'redo', 'actor': 'A', 'seq': 5, 'deps': {}},
        {'requestType': 'redo', 'actor': 'A', 'seq': 6, 'deps': {}},
        {'requestType': 'undo', 'actor': 'A', 'seq': 7, 'deps': {}},
        {'requestType': 'change', 'actor': 'A', 'seq': 8, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'j', 'value': 9}]},
    ]
    assert_three_way(reqs)
    # the change at seq 8 must clear the redo stack
    _, oracle = drive_oracle(reqs)
    assert oracle[-1]['canRedo'] is False
    assert oracle[-1]['canUndo'] is True


def test_datatype_survives_redo_not_undo():
    """Undo capture drops datatype (projection to action/obj/key/value);
    redo capture keeps it (field op minus actor/seq only)."""
    reqs = [
        {'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 't', 'value': 123,
                  'datatype': 'timestamp'}]},
        {'requestType': 'undo', 'actor': 'A', 'seq': 2, 'deps': {}},
        {'requestType': 'redo', 'actor': 'A', 'seq': 3, 'deps': {}},
    ]
    state, oracle = drive_oracle(reqs)
    assert oracle[2]['diffs'][0]['datatype'] == 'timestamp'
    assert_three_way(reqs)


def test_random_local_change_sweep():
    rng = random.Random(11)
    reqs, made = [], []
    seq = 0
    can_undo = can_redo = 0
    for _ in range(50):
        seq += 1
        r = rng.random()
        if r < 0.2 and can_undo:
            reqs.append({'requestType': 'undo', 'actor': 'A', 'seq': seq,
                         'deps': {}})
            can_undo -= 1
            can_redo += 1
            continue
        if r < 0.3 and can_redo:
            reqs.append({'requestType': 'redo', 'actor': 'A', 'seq': seq,
                         'deps': {}})
            can_redo -= 1
            can_undo += 1
            continue
        ops = []
        kind = rng.random()
        if kind < 0.3 or not made:
            obj = 'obj%d' % seq
            mk = rng.choice(['makeMap', 'makeList', 'makeText'])
            ops.append({'action': mk, 'obj': obj})
            if mk == 'makeMap':
                ops.append({'action': 'set', 'obj': obj, 'key': 'x',
                            'value': seq})
            else:
                ops.append({'action': 'ins', 'obj': obj, 'key': '_head',
                            'elem': 1})
                ops.append({'action': 'set', 'obj': obj, 'key': 'A:1',
                            'value': 'c'})
            ops.append({'action': 'link', 'obj': ROOT, 'key': 'k%d' % seq,
                        'value': obj})
            made.append((obj, mk))
        elif kind < 0.6:
            obj, mk = rng.choice(made)
            if mk in ('makeList', 'makeText'):
                ops.append({'action': 'ins', 'obj': obj, 'key': 'A:1',
                            'elem': seq + 100})
                ops.append({'action': 'set', 'obj': obj,
                            'key': 'A:%d' % (seq + 100),
                            'value': 'v%d' % seq})
            else:
                ops.append({'action': 'set', 'obj': obj,
                            'key': 'f%d' % (seq % 3), 'value': seq})
        elif kind < 0.8:
            ops.append({'action': 'set', 'obj': ROOT,
                        'key': 'top%d' % (seq % 4), 'value': seq})
        else:
            ops.append({'action': 'del', 'obj': ROOT,
                        'key': 'top%d' % (seq % 4)})
        reqs.append({'requestType': 'change', 'actor': 'A', 'seq': seq,
                     'deps': {}, 'ops': ops})
        can_undo += 1
        can_redo = 0
    assert_three_way(reqs)


def test_local_then_remote_patch_flags():
    """apply_changes patches report current canUndo/canRedo (reference
    makePatch reads the live stacks for every patch)."""
    pool = NativeDocPool()
    pool.apply_local_change('d', {
        'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
        'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 1}]})
    patch = pool.apply_changes('d', [
        {'actor': 'B', 'seq': 1, 'deps': {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'j', 'value': 2}]}])
    assert patch['canUndo'] is True
    assert patch['canRedo'] is False
    assert pool.get_patch('d')['canUndo'] is True


@pytest.mark.parametrize('make_pool', [
    NativeDocPool, TPUDocPool, lambda: ShardedNativePool(n_shards=2)])
def test_error_parity(make_pool):
    pool = make_pool()
    with pytest.raises(TypeError):
        pool.apply_local_change('d', {'requestType': 'change', 'seq': 1,
                                      'deps': {}, 'ops': []})
    with pytest.raises(RangeError, match='Cannot undo'):
        pool.apply_local_change('d', {'requestType': 'undo', 'actor': 'A',
                                      'seq': 1, 'deps': {}})
    with pytest.raises(RangeError, match='Cannot redo'):
        pool.apply_local_change('d', {'requestType': 'redo', 'actor': 'A',
                                      'seq': 1, 'deps': {}})
    with pytest.raises(RangeError, match='Unknown requestType: None'):
        pool.apply_local_change('d', {'actor': 'A', 'seq': 1, 'deps': {},
                                      'ops': []})
    pool.apply_local_change('d', {
        'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
        'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 1}]})
    with pytest.raises(RangeError, match='already been applied'):
        pool.apply_local_change('d', {
            'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
            'ops': []})


def test_sidecar_undo_parity():
    """The sidecar path produces the oracle's exact patches (round-1 VERDICT
    weak item #2: the old Python-shim capture emitted extra removes)."""
    reqs = [
        {'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
         'ops': [{'action': 'makeList', 'obj': 'L1'},
                 {'action': 'ins', 'obj': 'L1', 'key': '_head', 'elem': 1},
                 {'action': 'set', 'obj': 'L1', 'key': 'A:1', 'value': 'x'},
                 {'action': 'link', 'obj': ROOT, 'key': 'list',
                  'value': 'L1'}]},
        {'requestType': 'undo', 'actor': 'A', 'seq': 2, 'deps': {}},
        {'requestType': 'redo', 'actor': 'A', 'seq': 3, 'deps': {}},
    ]
    _, oracle = drive_oracle(reqs)
    backend = SidecarBackend()
    got = [backend.handle({'id': i, 'cmd': 'apply_local_change', 'doc': 'd',
                           'request': dict(r)})
           for i, r in enumerate(reqs)]
    for i, resp in enumerate(got):
        assert 'error' not in resp, resp
        assert resp['result'] == oracle[i], 'sidecar mismatch at %d' % i


def test_sharded_pool_routes_local_changes():
    pool = ShardedNativePool(n_shards=4)
    for d in ('a', 'b', 'c', 'd', 'e'):
        p = pool.apply_local_change(d, {
            'requestType': 'change', 'actor': 'A', 'seq': 1, 'deps': {},
            'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                     'value': d}]})
        assert p['canUndo'] is True
        u = pool.apply_local_change(d, {
            'requestType': 'undo', 'actor': 'A', 'seq': 2, 'deps': {}})
        assert u['canUndo'] is False and u['canRedo'] is True
        assert pool.get_patch(d)['diffs'] == []
