"""Columnar storage tier lanes (ISSUE 10, docs/STORAGE.md): codec fuzz
round-trip in both formats, settled-history GC with straggler backfill
parity, save -> evict -> reload -> mutate byte parity vs a never-
evicted twin (both exec modes), the gateway's LRU eviction +
reload-on-touch, the WAL byte bound, and per-connection fan-out frame
batching."""

import json
import os
import random
import time

import msgpack
import pytest

from automerge_tpu import storage, telemetry
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.parallel.engine import TPUDocPool
from automerge_tpu.storage.coldstore import ColdStore, DocEvictor
from automerge_tpu.storage.columnar import (decode_columnar,
                                            decode_columnar_meta,
                                            encode_columnar)

ROOT = '00000000-0000-0000-0000-000000000000'


@pytest.fixture(autouse=True)
def _reset_metrics():
    # reset_all, not metrics_reset: the gateway e2e lane observes
    # registry histograms (BATCH_OCCUPANCY) that would otherwise leak
    # into test_scheduler's exact-count assertions (same pattern as
    # tests/test_fanout.py)
    telemetry.reset_all()
    yield
    telemetry.reset_all()


@pytest.fixture(params=['default', 'kernel'])
def exec_mode(request):
    """Both execution modes face the parity lanes: the CPU default
    (full host path) and the forced kernel path (same pattern as
    tests/test_chaos.py)."""
    if request.param == 'kernel':
        prior = {k: os.environ.get(k)
                 for k in ('AMTPU_HOST_FULL', 'AMTPU_HOST_REG')}
        os.environ['AMTPU_HOST_FULL'] = '0'
        os.environ['AMTPU_HOST_REG'] = '0'
        yield 'kernel'
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    else:
        yield 'default'


def _rand_changes(rng, n_actors=4, n_rounds=6, with_weird=True):
    """A random mixed corpus: maps, text inserts, deletes, odd value
    types -- the fuzz lane's input."""
    changes = [{'actor': 'a0', 'seq': 1, 'deps': {}, 'ops': [
        {'action': 'makeText', 'obj': 'T'},
        {'action': 'link', 'obj': ROOT, 'key': 'text', 'value': 'T'}]}]
    seqs = {'a0': 1}
    prev, elem = '_head', 0
    for r in range(n_rounds):
        actor = 'a%d' % rng.randrange(n_actors)
        seqs.setdefault(actor, 0)
        seqs[actor] += 1
        ops = []
        for _ in range(rng.randrange(1, 6)):
            roll = rng.random()
            if roll < 0.4:
                elem += 1
                ops.append({'action': 'ins', 'obj': 'T', 'key': prev,
                            'elem': elem})
                ops.append({'action': 'set', 'obj': 'T',
                            'key': '%s:%d' % (actor, elem),
                            'value': chr(97 + elem % 26)})
                prev = '%s:%d' % (actor, elem)
            elif roll < 0.6:
                ops.append({'action': 'del', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(8)})
            else:
                vals = [rng.randrange(-1000, 1000), 'v%d' % r, True,
                        False, None]
                if with_weird:
                    vals += [rng.random(), {'nest': [1, 'x']},
                             [1, 2, 3]]
                ops.append({'action': 'set', 'obj': ROOT,
                            'key': 'k%d' % rng.randrange(8),
                            'value': rng.choice(vals)})
        deps = {a: s for a, s in seqs.items() if a != actor and s
                and rng.random() < 0.7}
        changes.append({'actor': actor, 'seq': seqs[actor],
                        'deps': deps, 'ops': ops})
    return changes


# ---------------------------------------------------------------------------
# codec round-trip lanes
# ---------------------------------------------------------------------------

class TestColumnarCodec(object):
    def test_fuzz_round_trip_byte_identical(self):
        rng = random.Random(11)
        for trial in range(20):
            changes = _rand_changes(rng, n_rounds=rng.randrange(1, 30))
            raws = [msgpack.packb(c, use_bin_type=True)
                    for c in changes]
            blob = encode_columnar(raws)
            assert decode_columnar(blob) == raws, 'trial %d' % trial
            # decode -> re-encode is stable (the fuzz lane's
            # byte-equality after decode->re-encode)
            assert decode_columnar(encode_columnar(
                decode_columnar(blob))) == raws

    def test_non_canonical_bytes_ride_the_residual_column(self):
        c = {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT, 'key': 'k', 'value': 5}]}
        raw = msgpack.packb(c, use_bin_type=True)
        # value 5 re-spelled as uint16: same object, different bytes --
        # a canonical re-encode would corrupt it, the residual column
        # must carry it verbatim
        bad = raw.replace(b'\x05', b'\xcd\x00\x05')
        assert msgpack.unpackb(bad, raw=False) == c
        raws = [raw, bad, raw]
        telemetry.metrics_reset()
        blob = encode_columnar(raws)
        assert decode_columnar(blob) == raws
        snap = telemetry.metrics_snapshot()
        assert snap['storage.columnar.residual_changes'] == 1
        # meta decode recovers actor/seq for residuals too
        assert [(a, s) for _r, a, s in decode_columnar_meta(blob)] == \
            [('a', 1)] * 3

    def test_compression_beats_json_on_structured_corpora(self):
        rng = random.Random(3)
        changes = _rand_changes(rng, n_rounds=200, with_weird=False)
        raws = [msgpack.packb(c, use_bin_type=True) for c in changes]
        blob = encode_columnar(raws)
        jbytes = len(json.dumps(changes, separators=(',', ':')))
        assert len(blob) * 5 <= jbytes, \
            'columnar %d vs json %d' % (len(blob), jbytes)

    def test_unicode_digit_keys_stay_encodable(self):
        # '\u00b2'.isdigit() is True but int('\u00b2') raises: the key
        # splitter must not crash on such a legal string key
        c = {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'set', 'obj': ROOT, 'key': 'x:\u00b2',
             'value': 1},
            {'action': 'set', 'obj': ROOT, 'key': 'y:\u0663',
             'value': 2},
            {'action': 'set', 'obj': ROOT, 'key': 'z:007',
             'value': 3}]}
        raws = [msgpack.packb(c, use_bin_type=True)]
        assert decode_columnar(encode_columnar(raws)) == raws

    def test_corrupt_container_raises_the_typed_error(self):
        """A blob with a valid v2 prefix but garbage body keeps the
        RangeError contract on pool.load (never a raw zlib/IndexError)."""
        from automerge_tpu.errors import RangeError
        bad = storage.CKPT_V2_PREFIX + b'\xc4\x08garbage!'
        with pytest.raises((ValueError, RangeError)):
            storage.unpack_checkpoint(bad)
        pool = NativeDocPool()
        with pytest.raises(RangeError, match='checkpoint'):
            pool.load('d', bad)
        # corrupt columnar body inside a well-formed container
        blob = storage.pack_checkpoint(
            {'a': 1}, [b'AMTC\x01\x01not-zlib'],
            [msgpack.packb({'actor': 'a', 'seq': 1, 'deps': {},
                            'ops': []}, use_bin_type=True)])
        with pytest.raises(RangeError, match='checkpoint'):
            pool.load('d', blob)
        t = TPUDocPool()
        from automerge_tpu.errors import RangeError as RE
        with pytest.raises(RE, match='checkpoint'):
            t.load('d', blob)

    def test_null_deps_or_ops_ride_the_residual_column(self):
        # explicit nulls are legal msgpack but not columnarizable: they
        # must fall to the residual column, not crash the encoder
        raws = [msgpack.packb({'actor': 'a', 'seq': 1, 'deps': None,
                               'ops': [{'action': 'set', 'obj': ROOT,
                                        'key': 'k', 'value': 1}]},
                              use_bin_type=True),
                msgpack.packb({'actor': 'a', 'seq': 2, 'deps': {},
                               'ops': None}, use_bin_type=True)]
        telemetry.metrics_reset()
        blob = encode_columnar(raws)
        assert decode_columnar(blob) == raws
        assert telemetry.metrics_snapshot()[
            'storage.columnar.residual_changes'] == 2

    def test_checkpoint_container_round_trip(self):
        rng = random.Random(5)
        raws = [msgpack.packb(c, use_bin_type=True)
                for c in _rand_changes(rng)]
        blob = storage.pack_checkpoint({'a0': 1}, [
            encode_columnar(raws[:2])], raws[2:])
        assert storage.is_checkpoint(blob)
        frontier, chunks, tail = storage.unpack_checkpoint(blob)
        assert frontier == {'a0': 1} and len(chunks) == 1
        assert tail == raws[2:]
        assert storage.checkpoint_raw_changes(blob) == raws
        v1 = storage.pack_checkpoint_v1(raws)
        assert storage.checkpoint_raw_changes(v1) == raws


# ---------------------------------------------------------------------------
# both-formats apply parity (the AMTPU_STORAGE_FORMAT oracle)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('fmt', ['columnar', 'json'])
def test_save_format_oracle_parity(fmt, monkeypatch, exec_mode):
    """Both container formats restore byte-identical state, and the
    decoded changes applied to a fresh pool equal the original apply
    (decode->apply parity oracle, both exec modes)."""
    monkeypatch.setenv('AMTPU_STORAGE_FORMAT', fmt)
    rng = random.Random(21)
    changes = _rand_changes(rng, n_rounds=12, with_weird=False)
    pool = NativeDocPool()
    for c in changes:
        pool.apply_changes('d', [c])
    blob = pool.save('d')
    if fmt == 'json':
        assert blob.startswith(storage.CKPT_V1_PREFIX)
    else:
        assert blob.startswith(storage.CKPT_V2_PREFIX)
    fresh = NativeDocPool()
    assert fresh.load('d2', blob) == pool.get_patch('d')
    assert fresh.get_missing_changes('d2', {}) == \
        pool.get_missing_changes('d', {})


# ---------------------------------------------------------------------------
# settled-history GC: frontier + straggler backfill
# ---------------------------------------------------------------------------

def _interleaved_history(pool, doc='d'):
    pool.apply_changes(doc, [
        {'actor': 'A', 'seq': 1, 'deps': {}, 'ops': [
            {'action': 'makeText', 'obj': 'T'},
            {'action': 'ins', 'obj': 'T', 'key': '_head', 'elem': 1},
            {'action': 'set', 'obj': 'T', 'key': 'A:1', 'value': 'x'},
            {'action': 'link', 'obj': ROOT, 'key': 'text',
             'value': 'T'}]}])
    for seq in range(1, 6):
        for actor in ('B', 'C'):
            elem = 10 * seq + (1 if actor == 'B' else 2)
            pool.apply_changes(doc, [
                {'actor': actor, 'seq': seq, 'deps': {'A': 1}, 'ops': [
                    {'action': 'ins', 'obj': 'T', 'key': 'A:1',
                     'elem': elem},
                    {'action': 'set', 'obj': 'T',
                     'key': '%s:%d' % (actor, elem),
                     'value': chr(97 + seq)},
                    {'action': 'set', 'obj': ROOT,
                     'key': 'k%d' % (seq % 3), 'value': seq}]}])


class TestSettledHistoryGC(object):
    def test_gc_shrinks_arena_and_straggler_backfills(self, exec_mode):
        """A straggler subscriber whose clock sits BEHIND the settled
        frontier still backfills byte-identically via
        get_missing_changes (the GC-frontier lane)."""
        pool = NativeDocPool()
        twin = NativeDocPool()
        _interleaved_history(pool)
        _interleaved_history(twin)
        before = pool.history_bytes('d')
        folded = pool.compact('d', frontier={'A': 1, 'B': 3, 'C': 3})
        assert folded > 0
        assert pool.history_bytes('d') < before
        assert pool.get_patch('d') == twin.get_patch('d')
        for have in ({}, {'A': 1}, {'A': 1, 'B': 2}, {'B': 1},
                     {'A': 1, 'B': 5, 'C': 5},
                     {'A': 1, 'B': 3, 'C': 3}):
            assert pool.get_missing_changes('d', have) == \
                twin.get_missing_changes('d', have), have
        for actor in ('A', 'B', 'C'):
            for after in (0, 1, 2):
                assert pool.get_changes_for_actor('d', actor, after) \
                    == twin.get_changes_for_actor('d', actor, after)
        snap = telemetry.metrics_snapshot()
        assert snap.get('storage.snapshot_backfills', 0) > 0
        assert snap.get('storage.gc.compactions', 0) == 1

    def test_gc_folds_only_the_settled_prefix(self):
        """Folding must preserve application order: a frontier that
        settles a LATER change before an earlier concurrent one only
        folds up to the first unsettled change."""
        pool = NativeDocPool()
        # B1 applied before A1; both concurrent
        pool.apply_changes('d', [
            {'actor': 'B', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT, 'key': 'x',
                 'value': 1}]}])
        pool.apply_changes('d', [
            {'actor': 'A', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT, 'key': 'y',
                 'value': 2}]}])
        # frontier settles A1 but NOT B1 -> nothing may fold (A1 sits
        # after the unsettled B1 in application order)
        assert pool.compact('d', frontier={'A': 1}) == 0
        # settling B1 folds exactly the B1 prefix
        assert pool.compact('d', frontier={'B': 1}) == 1

    def test_loading_old_checkpoint_into_live_doc_loses_nothing(self):
        """Loading an OLDER v2 checkpoint into a live doc must not
        overwrite the doc's newer snapshot state: later-compacted
        changes would then live in neither arena nor snapshot."""
        pool = NativeDocPool()
        twin = NativeDocPool()
        for seq in range(1, 4):
            ch = [{'actor': 'a', 'seq': seq,
                   'deps': {'a': seq - 1} if seq > 1 else {},
                   'ops': [{'action': 'set', 'obj': ROOT,
                            'key': 'k%d' % seq, 'value': seq}]}]
            pool.apply_changes('d', ch)
            twin.apply_changes('d', ch)
            if seq == 1:
                pool.compact('d')
                old_blob = pool.save('d')
        pool.compact('d')                   # frontier now {a: 3}
        pool.load('d', old_blob)            # replays as seq-dedup no-ops
        assert pool.get_clock('d')['clock'] == {'a': 3}
        assert pool.get_missing_changes('d', {}) == \
            twin.get_missing_changes('d', {})
        fresh = NativeDocPool()
        fresh.load('d2', pool.save('d'))
        assert fresh.get_patch('d2') == twin.get_patch('d')

    def test_repeated_compactions_append_chunks(self):
        pool = NativeDocPool()
        twin = NativeDocPool()
        seqs = []
        for seq in range(1, 9):
            ch = [{'actor': 'W', 'seq': seq,
                   'deps': {'W': seq - 1} if seq > 1 else {},
                   'ops': [{'action': 'set', 'obj': ROOT,
                            'key': 'k%d' % (seq % 2), 'value': seq}]}]
            pool.apply_changes('d', ch)
            twin.apply_changes('d', ch)
            seqs.append(seq)
            if seq % 3 == 0:
                assert pool.compact('d') > 0
        assert pool.get_missing_changes('d', {}) == \
            twin.get_missing_changes('d', {})
        blob = pool.save('d')
        fresh = NativeDocPool()
        assert fresh.load('d2', blob) == twin.get_patch('d')


# ---------------------------------------------------------------------------
# save -> evict -> reload -> mutate byte parity (both exec modes)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('make_pool', [
    NativeDocPool, lambda: ShardedNativePool(n_shards=2)],
    ids=['native', 'sharded'])
def test_evict_reload_mutate_parity(make_pool, exec_mode):
    pool = make_pool()
    twin = make_pool()
    _interleaved_history(pool)
    _interleaved_history(twin)
    pool.compact('d')
    blob = pool.save('d')
    assert pool.drop_doc('d')
    assert not pool.drop_doc('d')          # idempotent
    assert pool.history_bytes('d') == 0
    pool.load('d', blob)
    mut = [{'actor': 'B', 'seq': 6, 'deps': {'B': 5, 'C': 5},
            'ops': [{'action': 'set', 'obj': ROOT, 'key': 'post',
                     'value': 7},
                    {'action': 'ins', 'obj': 'T', 'key': 'A:1',
                     'elem': 99},
                    {'action': 'set', 'obj': 'T', 'key': 'B:99',
                     'value': 'z'}]}]
    got = pool.apply_changes('d', mut)
    want = twin.apply_changes('d', mut)
    assert got == want
    assert pool.get_patch('d') == twin.get_patch('d')
    assert pool.get_missing_changes('d', {}) == \
        twin.get_missing_changes('d', {})
    # a reloaded doc keeps its compacted economics
    assert pool.history_bytes('d') < twin.history_bytes('d')


# ---------------------------------------------------------------------------
# the cold store + evictor (unit level)
# ---------------------------------------------------------------------------

class TestDocEvictor(object):
    def test_lru_eviction_and_reload(self, tmp_path):
        pool = NativeDocPool()
        ev = DocEvictor(pool, max_resident=2,
                        store=ColdStore(str(tmp_path)), gc_every=0)
        patches = {}
        for i in range(4):
            doc = 'doc%d' % i
            pool.apply_changes(doc, [
                {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                    {'action': 'set', 'obj': ROOT, 'key': 'k',
                     'value': i}]}])
            patches[doc] = pool.get_patch(doc)
            ev.note_touch([doc])
            ev.maybe_evict(protect=[doc])
        assert len(ev.store) == 2           # doc0, doc1 went cold
        assert 'doc0' in ev.store and 'doc1' in ev.store
        assert pool.doc_count() == 2
        # reload-on-touch restores byte-identical state
        ev.ensure_resident(['doc0'])
        assert 'doc0' not in ev.store
        assert pool.get_patch('doc0') == patches['doc0']
        snap = telemetry.metrics_snapshot()
        assert snap['storage.evictions'] == 2
        assert snap['storage.reloads'] == 1

    def test_failed_reload_keeps_the_cold_blob(self, tmp_path):
        """A reload that raises must NOT destroy the only copy of the
        doc: the blob stays in the store, the failure is reported per
        doc, and a later touch succeeds."""
        pool = NativeDocPool()
        ev = DocEvictor(pool, max_resident=0,
                        store=ColdStore(str(tmp_path)), gc_every=0)
        want = {}
        for doc in ('d', 'healthy'):
            pool.apply_changes(doc, [
                {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                    {'action': 'set', 'obj': ROOT, 'key': 'k',
                     'value': 1}]}])
            want[doc] = pool.get_patch(doc)
            ev.store.put(doc, pool.save(doc))
            pool.drop_doc(doc)

        real_load = pool.load_batch
        poison = {'on': True}

        def flaky_load(blobs):
            if poison['on'] and 'd' in blobs:
                raise RuntimeError('transient replay failure')
            return real_load(blobs)
        pool.load_batch = flaky_load
        failed = ev.ensure_resident(['d', 'healthy'])
        # the poison doc is isolated: its blob survives, the healthy
        # doc reloaded anyway
        assert list(failed) == ['d']
        assert 'd' in ev.store and 'healthy' not in ev.store
        assert pool.get_patch('healthy') == want['healthy']
        snap = telemetry.metrics_snapshot()
        assert snap['storage.reload_failed'] == 1
        poison['on'] = False
        assert ev.ensure_resident(['d']) == {}
        assert 'd' not in ev.store
        assert pool.get_patch('d') == want['d']

    def test_protected_docs_never_evict(self, tmp_path):
        pool = NativeDocPool()
        ev = DocEvictor(pool, max_resident=1,
                        store=ColdStore(str(tmp_path)), gc_every=0)
        for doc in ('a', 'b'):
            pool.apply_changes(doc, [
                {'actor': 'x', 'seq': 1, 'deps': {}, 'ops': [
                    {'action': 'set', 'obj': ROOT, 'key': 'k',
                     'value': 1}]}])
            ev.note_touch([doc])
        ev.maybe_evict(protect=['a', 'b'])
        assert len(ev.store) == 0           # both protected: no evict
        ev.maybe_evict(protect=['b'])
        assert 'a' in ev.store


# ---------------------------------------------------------------------------
# gateway e2e: eviction + reload-on-touch through the flush cycle
# ---------------------------------------------------------------------------

def test_gateway_evicts_and_reloads_on_touch(tmp_path, monkeypatch):
    from automerge_tpu.scheduler import GatewayServer
    from automerge_tpu.sidecar.client import SidecarClient
    from automerge_tpu.sidecar.server import SidecarBackend
    monkeypatch.setenv('AMTPU_FLUSH_DEADLINE_MS', '2')
    monkeypatch.setenv('AMTPU_RESIDENT_DOCS_MAX', '2')
    monkeypatch.setenv('AMTPU_STORAGE_DIR', str(tmp_path / 'cold'))
    path = str(tmp_path / 'gw-storage.sock')
    gw = GatewayServer(path, backend=SidecarBackend()).start()
    try:
        with SidecarClient(sock_path=path) as c:
            want = {}
            for i in range(5):
                doc = 'cold%d' % i
                c.apply_changes(doc, [
                    {'actor': 'a', 'seq': 1, 'deps': {}, 'ops': [
                        {'action': 'set', 'obj': ROOT, 'key': 'k',
                         'value': i}]}])
                want[doc] = c.get_patch(doc)
            # wait until the storage tier reports evictions
            deadline = time.time() + 30
            while time.time() < deadline:
                h = c.healthz()
                if h['storage']['cold_docs'] >= 1:
                    break
                time.sleep(0.05)
            assert h['storage']['cold_docs'] >= 1, h['storage']
            assert h['storage']['resident_docs'] <= 2
            # touching every doc again (reads AND writes) reloads cold
            # ones transparently with byte-identical state
            for i in range(5):
                doc = 'cold%d' % i
                assert c.get_patch(doc) == want[doc], doc
            c.apply_changes('cold0', [
                {'actor': 'a', 'seq': 2, 'deps': {'a': 1}, 'ops': [
                    {'action': 'set', 'obj': ROOT, 'key': 'k2',
                     'value': 'post-reload'}]}])
            p = c.get_patch('cold0')
            assert p['clock'] == {'a': 2}
            snap = c.healthz()['storage']
            assert snap['max_resident'] == 2
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# WAL byte bound
# ---------------------------------------------------------------------------

class TestWALByteBound(object):
    def _wal_server(self, wal):
        """A fake call_raw that answers save/load like the sidecar."""
        state = {'saves': 0}

        def call_raw(cmd, kwargs):
            if cmd == 'save':
                state['saves'] += 1
                return {'checkpoint_b64': 'QQ=='}
            return {}
        return call_raw, state

    def test_byte_bound_trips_before_entry_count(self):
        from automerge_tpu.sidecar.client import CheckpointWAL
        wal = CheckpointWAL(compact_every=1000, max_bytes=100)
        call_raw, state = self._wal_server(wal)
        big = {'doc': 'd', 'changes': [{'actor': 'a', 'seq': 1,
                                        'ops': [], 'pad': 'x' * 120}]}
        wal.record('apply_changes', big)
        assert wal.log_bytes > 100
        wal.maybe_compact(call_raw)        # 1 entry but > 100 bytes
        assert state['saves'] == 1
        assert wal.log == [] and wal.log_bytes == 0
        snap = telemetry.metrics_snapshot()
        assert snap['sidecar.client.wal_compactions'] == 1
        # the gauge tracks the current footprint (snapshots only now)
        assert snap['sidecar.client.wal_bytes'] == wal.snap_bytes

    def test_compaction_failure_keeps_retrying_under_byte_bound(self):
        from automerge_tpu.sidecar.client import CheckpointWAL
        wal = CheckpointWAL(compact_every=1000, max_bytes=64)

        def broken(cmd, kwargs):
            raise ConnectionError('server died')
        entry = {'doc': 'd', 'changes': [{'actor': 'a', 'seq': 1,
                                          'pad': 'y' * 80}]}
        wal.record('apply_changes', entry)
        wal.maybe_compact(broken)
        wal.record('apply_changes', entry)
        wal.maybe_compact(broken)
        snap = telemetry.metrics_snapshot()
        assert snap['sidecar.client.wal_compact_failed'] == 2
        assert len(wal.log) == 2           # log retained for replay
        # a healthy server finally compacts
        call_raw, state = self._wal_server(wal)
        wal.maybe_compact(call_raw)
        assert state['saves'] == 1 and wal.log == []

    def test_disabled_byte_bound_keeps_entry_trigger_only(self):
        from automerge_tpu.sidecar.client import CheckpointWAL
        wal = CheckpointWAL(compact_every=3, max_bytes=0)
        call_raw, state = self._wal_server(wal)
        huge = {'doc': 'd', 'pad': 'z' * 10000}
        wal.record('apply_changes', huge)
        wal.maybe_compact(call_raw)
        assert state['saves'] == 0          # bytes never trip
        wal.record('apply_changes', huge)
        wal.record('apply_changes', huge)
        wal.maybe_compact(call_raw)
        assert state['saves'] == 1          # entry count does


# ---------------------------------------------------------------------------
# fan-out: one write per connection per flush
# ---------------------------------------------------------------------------

def test_fanout_one_write_per_conn_across_docs():
    """A connection multiplexing peers on TWO dirty docs receives both
    frames in ONE write per flush (`sync.fanout.writes_coalesced`)."""
    from automerge_tpu.sync.fanout import FanoutEngine
    pool = NativeDocPool()
    engine = FanoutEngine(
        pool, lambda obj: (json.dumps(obj) + '\n').encode())
    writes = []
    shared = writes.append
    solo_writes = []
    for doc in ('dA', 'dB'):
        pool.apply_changes(doc, [
            {'actor': 'w', 'seq': 1, 'deps': {}, 'ops': [
                {'action': 'set', 'obj': ROOT, 'key': 'k',
                 'value': 0}]}])
        engine.subscribe((1, 'multi'), doc, {'w': 1}, shared)
    engine.subscribe((2, 'solo'), 'dA', {'w': 1}, solo_writes.append)
    telemetry.metrics_reset()
    updates = {}
    for doc in ('dA', 'dB'):
        updates[doc] = pool.apply_changes(doc, [
            {'actor': 'w', 'seq': 2, 'deps': {'w': 1}, 'ops': [
                {'action': 'set', 'obj': ROOT, 'key': 'k',
                 'value': 1}]}])['clock']
    engine.on_flush(updates)
    assert len(writes) == 1, 'expected ONE write for the shared conn'
    frames = [json.loads(line)
              for line in writes[0].decode().strip().split('\n')]
    assert sorted(f['doc'] for f in frames) == ['dA', 'dB']
    assert len(solo_writes) == 1
    snap = telemetry.metrics_snapshot()
    assert snap['sync.fanout.writes_coalesced'] == 1
    assert snap['sync.fanout.frames'] == 3
    # both subscriptions advanced: the next flush has nothing to send
    writes.clear()
    engine.on_flush(updates)
    assert not writes


def test_fanout_acked_clock_is_pointwise_min():
    from automerge_tpu.sync.fanout import FanoutEngine
    pool = NativeDocPool()
    engine = FanoutEngine(pool, lambda obj: b'')
    assert engine.acked_clock('nope') is None
    engine.subscribe((1, 'p1'), 'd', {'a': 3, 'b': 1}, lambda b: None,
                     backfill=False)
    engine.subscribe((2, 'p2'), 'd', {'a': 2, 'b': 5}, lambda b: None,
                     backfill=False)
    assert engine.acked_clock('d') == {'a': 2, 'b': 1}


def test_gc_frontier_from_fanout_keeps_straggler_serveable():
    """End-to-end GC sanity: compaction bounded by the fan-out acked
    clock never folds past what a live straggler still needs from the
    C++ tail, and the straggler's catch-up stays byte-identical."""
    from automerge_tpu.sync.fanout import FanoutEngine
    pool = NativeDocPool()
    twin = NativeDocPool()
    engine = FanoutEngine(pool, lambda obj: b'')
    _interleaved_history(pool)
    _interleaved_history(twin)
    engine.subscribe((1, 'slow'), 'd', {'A': 1, 'B': 2}, lambda b: None,
                     backfill=False)
    acked = engine.acked_clock('d')
    assert acked == {'A': 1, 'B': 2}
    folded = pool.compact('d', frontier=acked)
    assert folded > 0
    # the straggler's own catch-up comes straight off the C++ tail
    telemetry.metrics_reset()
    assert pool.get_missing_changes('d', {'A': 1, 'B': 2}) == \
        twin.get_missing_changes('d', {'A': 1, 'B': 2})
    assert telemetry.metrics_snapshot().get(
        'storage.snapshot_backfills', 0) == 0
    # an EVEN OLDER reconnector merges from the snapshot
    assert pool.get_missing_changes('d', {}) == \
        twin.get_missing_changes('d', {})
    assert telemetry.metrics_snapshot().get(
        'storage.snapshot_backfills', 0) == 1


def test_engine_pool_checkpoints_stay_cross_compatible():
    t = TPUDocPool()
    _interleaved_history(t)
    n = NativeDocPool()
    assert n.load('x', t.save('d')) == t.get_patch('d')
    n.compact('x')
    t2 = TPUDocPool()
    assert t2.load('y', n.save('x')) == t.get_patch('d')
