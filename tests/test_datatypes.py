"""Datatype behavior tests: Text, Table, proxies, uuid factory override.

Ported from `/root/reference/test/text_test.js`, `table_test.js`,
`proxies_test.js` (core behaviors), `test_uuid.js`.
"""

import pytest

import automerge_tpu as am
from automerge_tpu.errors import AutomergeError, RangeError
from automerge_tpu.utils import uuid as _uuid_pkg
from automerge_tpu.utils.uuid import reset as uuid_reset, set_factory, uuid as make_uuid


class TestText:
    def make_text(self):
        s1 = am.change(am.init(), lambda doc: doc.update({'text': am.Text()}))
        return s1

    def test_support_insertion_and_deletion(self):
        s1 = self.make_text()
        s1 = am.change(s1, lambda doc: doc['text'].insert_at(0, 'a'))
        s1 = am.change(s1, lambda doc: doc['text'].insert_at(1, 'b', 'c'))
        assert str(s1['text']) == 'abc'
        s1 = am.change(s1, lambda doc: doc['text'].delete_at(1))
        assert str(s1['text']) == 'ac'
        assert len(s1['text']) == 2
        assert s1['text'].get(0) == 'a'

    def test_concurrent_text_insert(self):
        """(reference: text_test.js:26)"""
        s1 = am.change(am.init('A'), lambda doc: doc.update({'text': am.Text()}))
        s2 = am.merge(am.init('B'), s1)
        s1 = am.change(s1, lambda doc: doc['text'].insert_at(0, 'a', 'b'))
        s2 = am.change(s2, lambda doc: doc['text'].insert_at(0, 'x', 'y'))
        s3 = am.merge(s1, s2)
        text = str(s3['text'])
        assert text in ('abxy', 'xyab')
        # both replicas converge to the same interleaving
        s4 = am.merge(s2, s1)
        assert str(s4['text']) == text

    def test_elem_ids(self):
        s1 = self.make_text()
        s1 = am.change(s1, lambda doc: doc['text'].insert_at(0, 'h', 'i'))
        actor = am.get_actor_id(s1)
        assert s1['text'].get_elem_id(0) == '%s:1' % actor
        assert s1['text'].get_elem_id(1) == '%s:2' % actor

    def test_text_in_saved_doc(self):
        s1 = self.make_text()
        s1 = am.change(s1, lambda doc: doc['text'].insert_at(0, *'persist'))
        s2 = am.load(am.save(s1))
        assert str(s2['text']) == 'persist'


class TestTable:
    def make_table(self):
        return am.change(am.init(), lambda doc: doc.update(
            {'books': am.Table(['authors', 'title', 'isbn'])}))

    def test_empty_table(self):
        s1 = self.make_table()
        assert s1['books'].count == 0
        assert list(s1['books'].columns) == ['authors', 'title', 'isbn']

    def test_add_row_as_dict(self):
        s1 = self.make_table()
        row_id = {}

        def cb(doc):
            row_id['id'] = doc['books'].add({
                'authors': ['Kleppmann, Martin'],
                'title': 'Designing Data-Intensive Applications',
                'isbn': '1449373321'})
        s1 = am.change(s1, cb)
        row = s1['books'].by_id(row_id['id'])
        assert row['title'] == 'Designing Data-Intensive Applications'
        assert am.get_object_id(row) == row_id['id']

    def test_add_row_as_list(self):
        s1 = self.make_table()

        def cb(doc):
            doc['books'].add([['Kleppmann, Martin'], 'DDIA', '1449373321'])
        s1 = am.change(s1, cb)
        assert s1['books'].count == 1
        assert s1['books'].rows[0]['title'] == 'DDIA'

    def test_remove_row(self):
        s1 = self.make_table()
        row_id = {}

        def add(doc):
            row_id['id'] = doc['books'].add({'title': 'a', 'authors': [],
                                             'isbn': ''})
        s1 = am.change(s1, add)

        def remove(doc):
            doc['books'].remove(row_id['id'])
        s2 = am.change(s1, remove)
        assert s2['books'].count == 0
        with pytest.raises(RangeError):
            am.change(s2, remove)

    def test_concurrent_row_insertion(self):
        """(reference: table_test.js:159)"""
        s1 = self.make_table()
        s2 = am.merge(am.init(), s1)
        s1 = am.change(s1, lambda doc: doc['books'].add(
            {'title': 'one', 'authors': [], 'isbn': '1'}))
        s2 = am.change(s2, lambda doc: doc['books'].add(
            {'title': 'two', 'authors': [], 'isbn': '2'}))
        s3 = am.merge(s1, s2)
        assert s3['books'].count == 2
        assert sorted(r['title'] for r in s3['books'].rows) == ['one', 'two']

    def test_sort_and_filter(self):
        s1 = self.make_table()

        def cb(doc):
            doc['books'].add({'title': 'c', 'authors': [], 'isbn': '3'})
            doc['books'].add({'title': 'a', 'authors': [], 'isbn': '1'})
            doc['books'].add({'title': 'b', 'authors': [], 'isbn': '2'})
        s1 = am.change(s1, cb)
        assert [r['title'] for r in s1['books'].sort('title')] == ['a', 'b', 'c']
        assert sorted(r['title'] for r in s1['books'].filter(
            lambda r: r['isbn'] > '1')) == ['b', 'c']
        found = s1['books'].find(lambda r: r['isbn'] == '2')
        assert found['title'] == 'b'

    def test_rows_frozen_outside_change(self):
        s1 = self.make_table()
        with pytest.raises(AutomergeError):
            s1['books'].set('x', 'y')


class TestProxies:
    def test_map_proxy_behaves_like_dict(self):
        def cb(doc):
            doc['key1'] = 'value1'
            doc['key2'] = 'value2'
            assert 'key1' in doc
            assert 'absent' not in doc
            assert sorted(doc.keys()) == ['key1', 'key2']
            assert doc.get('key1') == 'value1'
            assert doc.get('absent', 'fallback') == 'fallback'
            assert len(doc) == 2
        am.change(am.init(), cb)

    def test_list_proxy_behaves_like_list(self):
        def setup(doc):
            doc['list'] = [1, 2, 3]
        s1 = am.change(am.init(), setup)

        def cb(doc):
            lst = doc['list']
            assert len(lst) == 3
            assert list(lst) == [1, 2, 3]
            assert lst[0] == 1
            assert lst.index_of(2) == 1
            assert lst.includes(3)
            assert not lst.includes(99)
            assert lst.slice(1) == [2, 3]
            assert lst.map(lambda x: x * 2) == [2, 4, 6]
            assert lst.filter(lambda x: x > 1) == [2, 3]
            assert 2 in lst
        am.change(s1, cb)

    def test_proxy_object_id(self):
        def cb(doc):
            doc['nested'] = {}
            assert doc._objectId == '00000000-0000-0000-0000-000000000000'
            assert doc['nested']._objectId is not None
            assert doc._type == 'map'
            assert doc['nested']._type == 'map'
        am.change(am.init(), cb)

    def test_list_proxy_type_and_negative_index(self):
        def setup(doc):
            doc['list'] = ['a']
        s1 = am.change(am.init(), setup)

        def cb(doc):
            assert doc['list']._type == 'list'
            with pytest.raises(RangeError):
                doc['list'][-1] = 'x'
        am.change(s1, cb)


class TestUuidFactory:
    def test_factory_override(self):
        """(reference: test_uuid.js:24)"""
        try:
            counter = [0]

            def factory():
                counter[0] += 1
                return 'custom-uuid-%04d' % counter[0]
            set_factory(factory)
            assert make_uuid() == 'custom-uuid-0001'
            assert make_uuid() == 'custom-uuid-0002'
            doc = am.init()
            assert am.get_actor_id(doc) == 'custom-uuid-0003'
        finally:
            uuid_reset()

    def test_default_uuid_format(self):
        import re
        assert re.match(r'^[0-9a-f]{8}(-[0-9a-f]{4}){3}-[0-9a-f]{12}$',
                        make_uuid())
