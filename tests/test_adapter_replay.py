"""Transcript-replay coverage for `adapter/backend-tpu.js`.

Node is not available in this image, so the adapter's code path is
exercised from Python instead (VERDICT r2 #6):

1. The adapter source is PARSED and its wire protocol extracted -- every
   `request('<cmd>', {fields})` call site and the worker's framing
   (JSON line on stdin, JSON line on stdout, FIFO reply order).  If the
   adapter drifts, the mirror assertions below fail.
2. `AdapterMirror` re-implements the adapter's Backend surface
   (init/applyChanges/applyLocalChange/getPatch/getChanges/
   getChangesForActor/getMissingChanges/getMissingDeps/merge) issuing
   byte-identical request envelopes to a REAL sidecar server subprocess
   (`python -m automerge_tpu.sidecar.server`), the same process the
   worker thread spawns.
3. A reference-frontend-shaped session runs with the mirror as the
   frontend's immediate backend (`options.backend`, the injection seam
   the reference designed: frontend/index.js:98): init -> change ->
   applyChanges -> undo -> redo -> save/load -- and the materialized
   results must equal an in-process oracle run.
4. The worker/Atomics rendezvous serializes callers: replies come back
   in request order (`pending.shift()` per stdout line).  The pipelined
   test writes several requests before draining replies and asserts the
   FIFO pairing the rendezvous depends on.
"""

import json
import os
import re
import subprocess
import sys

import pytest

import automerge_tpu as am
from automerge_tpu import backend as OracleBackend
from automerge_tpu import frontend as Frontend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ADAPTER_JS = os.path.join(REPO, 'adapter', 'backend-tpu.js')

# The adapter's cmd -> request-field mapping, mirrored by hand; the
# drift test below re-derives this from the .js source.
ADAPTER_PROTOCOL = {
    'apply_changes': ['doc', 'changes'],
    'apply_local_change': ['doc', 'request'],
    'get_patch': ['doc'],
    'get_missing_changes': ['doc', 'have_deps'],
    'get_changes_for_actor': ['doc', 'actor'],
    'get_missing_deps': ['doc'],
}


def test_adapter_source_matches_mirrored_protocol():
    """Parse request('cmd', {field: ...}) call sites out of the adapter
    and compare with the mirror's table, so adapter drift fails here."""
    src = open(ADAPTER_JS).read()
    sites = re.findall(
        r"request\('([a-z_]+)',\s*\n?\s*\{([^}]*)\}", src)
    assert sites, 'no request() call sites found in adapter'
    seen = {}
    for cmd, fields in sites:
        keys = [k.strip().split(':')[0].strip()
                for k in fields.split(',') if k.strip()]
        seen.setdefault(cmd, keys)
    assert seen == ADAPTER_PROTOCOL
    # worker framing: JSON line request, FIFO pending queue, stdio spawn
    assert r"JSON.stringify(request) + '\\n'" in src
    assert 'pending.shift()' in src
    assert "spawn(workerData.python, ['-m', 'automerge_tpu.sidecar.server']"\
        in src
    # rendezvous: SharedArrayBuffer signal + Atomics wait/notify
    for token in ('Atomics.wait(signal, 0, 0)', 'Atomics.notify(signal, 0)',
                  'receiveMessageOnPort'):
        assert token in src, token


class SidecarProcess:
    """The exact process + framing the adapter's worker owns."""

    def __init__(self):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        self.proc = subprocess.Popen(
            [sys.executable, '-m', 'automerge_tpu.sidecar.server'],
            cwd=REPO, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=sys.stderr.fileno()
            if hasattr(sys.stderr, 'fileno') else None, text=True)
        self.next_id = 1

    def write_request(self, cmd, fields):
        req = dict({'id': self.next_id, 'cmd': cmd}, **fields)
        self.next_id += 1
        self.proc.stdin.write(json.dumps(req) + '\n')
        self.proc.stdin.flush()
        return req['id']

    def next_doc_id(self):
        # the adapter keeps the doc counter on the SHARED connection
        # (conn.nextDoc++), not per backend instance
        n = getattr(self, '_next_doc', 1)
        self._next_doc = n + 1
        return 'doc-%d' % n

    def read_response(self):
        line = self.proc.stdout.readline()
        assert line, 'sidecar died'
        return json.loads(line)

    def request(self, cmd, fields):
        """The adapter's SidecarConnection.request: write one line, block
        for one reply, raise typed errors."""
        self.write_request(cmd, fields)
        response = self.read_response()
        if 'error' in response and response['error']:
            kind = response.get('errorType')
            if kind == 'TypeError':
                raise TypeError(response['error'])
            if kind == 'RangeError':
                raise am.errors.RangeError(response['error'])
            raise am.errors.AutomergeError(response['error'])
        return response['result']

    def close(self):
        self.proc.stdin.close()
        self.proc.wait(timeout=30)


class Token(dict):
    """The adapter's frozen {docId, clock} backend-state value."""

    def __init__(self, doc_id, clock):
        super().__init__(docId=doc_id, clock=dict(clock))


class AdapterMirror:
    """backend-tpu.js's exported surface, request-for-request."""

    def __init__(self, conn):
        self.conn = conn

    def init(self):
        return Token(self.conn.next_doc_id(), {})

    def apply_changes(self, state, changes):
        patch = self.conn.request('apply_changes',
                                  {'doc': state['docId'],
                                   'changes': changes})
        return Token(state['docId'], patch['clock']), patch

    def apply_local_change(self, state, change):
        patch = self.conn.request('apply_local_change',
                                  {'doc': state['docId'],
                                   'request': change})
        return Token(state['docId'], patch['clock']), patch

    def get_patch(self, state):
        return self.conn.request('get_patch', {'doc': state['docId']})

    def get_changes(self, old_state, new_state):
        if old_state['docId'] != new_state['docId']:
            raise am.errors.RangeError(
                'Cannot diff two states from different documents')
        return self.conn.request('get_missing_changes',
                                 {'doc': new_state['docId'],
                                  'have_deps': old_state['clock']})

    def get_changes_for_actor(self, state, actor_id):
        return self.conn.request('get_changes_for_actor',
                                 {'doc': state['docId'],
                                  'actor': actor_id})

    def get_missing_changes(self, state, clock):
        return self.conn.request('get_missing_changes',
                                 {'doc': state['docId'],
                                  'have_deps': clock or {}})

    def get_missing_deps(self, state):
        return self.conn.request('get_missing_deps',
                                 {'doc': state['docId']})

    def merge(self, local, remote):
        changes = self.conn.request('get_missing_changes',
                                    {'doc': remote['docId'],
                                     'have_deps': local['clock']})
        return self.apply_changes(local, changes)


@pytest.fixture(scope='module')
def sidecar():
    conn = SidecarProcess()
    yield conn
    conn.close()


def materialize(patch):
    from automerge_tpu.sync.replica_set import patch_to_tree
    return patch_to_tree(patch)


class TestReferenceShapedSession:
    """init -> change -> applyChanges -> undo -> redo -> save/load, with
    the adapter mirror as the frontend's immediate backend."""

    def test_full_session(self, sidecar):
        adapter = AdapterMirror(sidecar)

        # --- init + local changes (applyLocalChange through the wire) --
        doc = Frontend.init({'actorId': 'frontend-actor',
                             'backend': adapter})
        doc, _ = Frontend.change(doc, None,
                                 lambda d: d.update({'title': 'hello'}))
        doc, _ = Frontend.change(doc, None,
                                 lambda d: d.__setitem__('n', 1))
        assert doc['title'] == 'hello' and doc['n'] == 1

        # oracle runs the identical session in-process
        odoc = am.init('frontend-actor')
        odoc = am.change(odoc, lambda d: d.update({'title': 'hello'}))
        odoc = am.change(odoc, lambda d: d.__setitem__('n', 1))

        # --- remote ingestion (applyChanges through the wire) ----------
        remote = am.init('remote-actor')
        remote = am.change(remote, lambda d: d.__setitem__('remote', True))
        remote_changes = am.get_changes(am.init('x'), remote)

        state = Frontend.get_backend_state(doc)
        state, patch = adapter.apply_changes(state, remote_changes)
        patch['state'] = state
        doc = Frontend.apply_patch(doc, patch)
        assert doc['remote'] is True

        oracle_state, opatch = OracleBackend.apply_changes(
            Frontend.get_backend_state(odoc), remote_changes)
        opatch['state'] = oracle_state
        odoc = am.apply_changes(odoc, remote_changes)

        # wire patch diffs equal the oracle's for the same ingestion
        assert patch['diffs'] == opatch['diffs']
        assert patch['clock'] == opatch['clock']

        # --- undo / redo (requestType through the wire) ----------------
        assert Frontend.can_undo(doc)
        doc, _ = Frontend.undo(doc, None)
        assert 'n' not in doc or doc['n'] is None
        doc, _ = Frontend.redo(doc, None)
        assert doc['n'] == 1
        odoc = am.undo(odoc)
        odoc = am.redo(odoc)

        # --- whole-doc parity through getPatch -------------------------
        wire_tree = materialize(adapter.get_patch(
            Frontend.get_backend_state(doc)))
        oracle_tree = materialize(OracleBackend.get_patch(
            Frontend.get_backend_state(odoc)))
        assert wire_tree == oracle_tree

        # --- getMissingDeps / getChangesForActor -----------------------
        assert adapter.get_missing_deps(
            Frontend.get_backend_state(doc)) == {}
        mine = adapter.get_changes_for_actor(
            Frontend.get_backend_state(doc), 'frontend-actor')
        assert [c['seq'] for c in mine] == [1, 2, 3, 4]

        # --- save / load through the sidecar ---------------------------
        token = Frontend.get_backend_state(doc)
        saved = sidecar.request('save', {'doc': token['docId']})
        assert 'checkpoint_b64' in saved
        restored = 'restored-doc'
        sidecar.request('load', {'doc': restored,
                                 'data': saved['checkpoint_b64']})
        tree = materialize(sidecar.request('get_patch', {'doc': restored}))
        assert tree == wire_tree

    def test_merge_between_two_wire_docs(self, sidecar):
        adapter = AdapterMirror(sidecar)
        a = Frontend.init({'actorId': 'aaaa', 'backend': adapter})
        b = Frontend.init({'actorId': 'bbbb', 'backend': adapter})
        a, _ = Frontend.change(a, None, lambda d: d.__setitem__('x', 1))
        b, _ = Frontend.change(b, None, lambda d: d.__setitem__('y', 2))
        sa = Frontend.get_backend_state(a)
        sb = Frontend.get_backend_state(b)
        merged_state, patch = adapter.merge(sa, sb)
        assert patch['clock'] == {'aaaa': 1, 'bbbb': 1}
        tree = materialize(adapter.get_patch(merged_state))
        # oracle: same two changes into one in-process backend
        ost = OracleBackend.init()
        for src in (sa, sb):
            changes = adapter.get_changes_for_actor(
                src, 'aaaa' if src is sa else 'bbbb')
            ost, _ = OracleBackend.apply_changes(ost, changes)
        assert tree == materialize(OracleBackend.get_patch(ost))

    def test_typed_errors_cross_the_wire(self, sidecar):
        adapter = AdapterMirror(sidecar)
        state = adapter.init()
        with pytest.raises(TypeError):
            adapter.apply_local_change(state, {'requestType': 'change',
                                               'ops': []})
        state, _ = adapter.apply_local_change(
            state, {'requestType': 'change', 'actor': 'e', 'seq': 1,
                    'deps': {}, 'ops': []})
        with pytest.raises(am.errors.RangeError):
            adapter.apply_local_change(
                state, {'requestType': 'change', 'actor': 'e', 'seq': 1,
                        'deps': {}, 'ops': []})


class TestRendezvousFIFO:
    """The worker pairs replies to callers strictly FIFO
    (pending.push on request, pending.shift per stdout line); several
    requests written before any reply is drained must come back in
    request order with matching ids."""

    def test_pipelined_replies_in_request_order(self, sidecar):
        ids = []
        for i in range(5):
            ids.append(sidecar.write_request(
                'apply_changes',
                {'doc': 'fifo-doc',
                 'changes': [{'actor': 'f', 'seq': i + 1, 'deps': {},
                              'ops': [{'action': 'set',
                                       'obj': '00000000-0000-0000-0000-'
                                              '000000000000',
                                       'key': 'k%d' % i,
                                       'value': i}]}]}))
        replies = [sidecar.read_response() for _ in range(5)]
        assert [r['id'] for r in replies] == ids
        clocks = [r['result']['clock']['f'] for r in replies]
        assert clocks == [1, 2, 3, 4, 5]
