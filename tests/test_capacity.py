"""Per-doc resource accounting + capacity observability (ISSUE 15).

Lanes:
  * reconciliation -- `doc_stats` column totals equal the pool-wide
    `history_bytes()` / `op_count()` BIT-EXACTLY across mutate / GC /
    fold / evict / reload cycles, in both exec modes;
  * space-saver sketch -- zipfian correctness vs exact counts +
    overestimation bounds;
  * headroom estimator -- budget / pressure / burn / exhaustion unit
    lanes with injected used_fn + clock;
  * tracker surfaces -- cost vectors, hot-doc tables, healthz section;
  * DocEvictor -- per-eviction freed-bytes accounting + the
    per-doc `storage.evict` recorder event; pressure mode ignores the
    doc-count cap;
  * drop/re-add resident-clock attribution (subprocess lane, forced
    kernel path): `amtpu_drop_doc` must leave NO stale resclk row
    attribution -- the doc-pointer-keyed cache is the known reuse
    hazard.
"""

import os
import random
import subprocess
import sys

import pytest

from automerge_tpu import telemetry
from automerge_tpu.native import NativeDocPool, ShardedNativePool
from automerge_tpu.storage.coldstore import ColdStore, DocEvictor
from automerge_tpu.telemetry import capacity, recorder
from automerge_tpu.telemetry.capacity import (HeadroomEstimator,
                                              SpaceSaver)

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def _changes(actor, seq0, n, keyspace=8, seed=0):
    rng = random.Random(seed * 1000 + seq0)
    out = []
    for i in range(n):
        out.append({'actor': actor, 'seq': seq0 + i + 1,
                    'deps': {actor: seq0 + i} if seq0 + i else {},
                    'ops': [{'action': 'set', 'obj': ROOT_ID,
                             'key': 'k%d' % rng.randrange(keyspace),
                             'value': 'v%d' % rng.randrange(1 << 16)}]})
    return out


def _reconciled(pool):
    ids, stats = pool.doc_stats()
    hist = int(stats[:, 0].sum()) if len(ids) else 0
    ops = int(stats[:, 1].sum()) if len(ids) else 0
    assert hist == pool.history_bytes()
    assert ops == pool.op_count()
    return ids, stats


@pytest.mark.parametrize('host_full', ['0', '1'])
def test_doc_stats_reconcile_churn_gc_evict_reload(host_full,
                                                   monkeypatch):
    monkeypatch.setenv('AMTPU_HOST_FULL', host_full)
    pool = NativeDocPool()
    evictor = DocEvictor(pool, max_resident=3, store=ColdStore(),
                         gc_every=4)
    seqs = {}
    for rnd in range(3):
        for d in range(6):
            doc = 'doc%d' % d
            n = 3 + (d % 2)
            pool.apply_changes(doc, _changes('a%d' % (d % 2),
                                             seqs.get(doc, 0), n,
                                             seed=d))
            seqs[doc] = seqs.get(doc, 0) + n
            evictor.note_mutations(doc, n)   # folds past the cadence
            evictor.note_touch([doc])
        _reconciled(pool)
        evictor.maybe_evict()
        _reconciled(pool)
    failed = evictor.ensure_resident(list(seqs))
    assert not failed
    ids, stats = _reconciled(pool)
    assert len(ids) == 6
    # per-doc rows agree with the per-doc pool queries too
    for i, key in enumerate(ids):
        assert int(stats[i, 0]) == pool.history_bytes(key)
        assert int(stats[i, 1]) == pool.op_count(key)


def test_doc_stats_sharded_concat():
    pool = ShardedNativePool(3)
    for d in range(9):
        pool.apply_changes('s%d' % d, _changes('w', 0, 2, seed=d))
    ids, stats = pool.doc_stats()
    assert sorted(ids) == sorted('s%d' % d for d in range(9))
    assert int(stats[:, 0].sum()) == pool.history_bytes()
    assert int(stats[:, 1].sum()) == pool.op_count()


def test_doc_stats_folded_and_queued_columns():
    pool = NativeDocPool()
    pool.apply_changes('f', _changes('w', 0, 12, seed=1))
    assert pool.compact('f') > 0          # folds the settled prefix
    ids, stats = _reconciled(pool)
    i = ids.index(NativeDocPool._doc_key('f'))
    assert int(stats[i, 2]) > 0           # folded_ops recorded
    # a causally-parked change lands in the queued column
    pool.apply_changes('f', [{'actor': 'q', 'seq': 2,
                              'deps': {'q': 1},
                              'ops': [{'action': 'set', 'obj': ROOT_ID,
                                       'key': 'z', 'value': 1}]}])
    ids, stats = _reconciled(pool)
    i = ids.index(NativeDocPool._doc_key('f'))
    assert int(stats[i, 4]) == 1
    # delivering the missing dep drains the queue; still reconciled
    pool.apply_changes('f', [{'actor': 'q', 'seq': 1, 'deps': {},
                              'ops': [{'action': 'set', 'obj': ROOT_ID,
                                       'key': 'z', 'value': 0}]}])
    ids, stats = _reconciled(pool)
    i = ids.index(NativeDocPool._doc_key('f'))
    assert int(stats[i, 4]) == 0


def test_doc_stats_rollback_and_local_change_paths():
    """Accounting survives the non-batch mutation paths: a FAILED
    batch's journal rollback restores the exact pre-batch rows, and
    the local-change / undo / redo pipeline stays reconciled."""
    pool = NativeDocPool()
    pool.apply_local_change('lc', {'requestType': 'change',
                                   'actor': 'me', 'seq': 1, 'deps': {},
                                   'ops': [{'action': 'set',
                                            'obj': ROOT_ID, 'key': 'a',
                                            'value': 1}]})
    _reconciled(pool)
    pre = pool.doc_stats()[1].copy()
    with pytest.raises(Exception):
        # inconsistent seq reuse: validation fails post-schedule and
        # the begin journal rolls everything back
        pool.apply_batch({'lc': [{'actor': 'me', 'seq': 1, 'deps': {},
                                  'ops': [{'action': 'set',
                                           'obj': ROOT_ID, 'key': 'a',
                                           'value': 999}]}]})
    _ids, stats = _reconciled(pool)
    assert (stats == pre).all()
    pool.apply_local_change('lc', {'requestType': 'change',
                                   'actor': 'me', 'seq': 2, 'deps': {},
                                   'ops': [{'action': 'set',
                                            'obj': ROOT_ID, 'key': 'b',
                                            'value': 2}]})
    pool.apply_local_change('lc', {'requestType': 'undo', 'actor': 'me',
                                   'seq': 3, 'deps': {}})
    pool.apply_local_change('lc', {'requestType': 'redo', 'actor': 'me',
                                   'seq': 4, 'deps': {}})
    _reconciled(pool)


def test_space_saver_zipfian_vs_exact():
    rng = random.Random(7)
    sketch = SpaceSaver(48)
    exact = {}
    for _ in range(20000):
        k = 'd%d' % min(int(rng.paretovariate(1.15)) - 1, 499)
        w = rng.randrange(1, 512)
        sketch.offer(k, w)
        exact[k] = exact.get(k, 0) + w
    top_exact = [k for k, _ in sorted(exact.items(),
                                      key=lambda kv: -kv[1])]
    top_sketch = [k for k, _v, _e in sketch.top()]
    assert top_sketch[:3] == top_exact[:3]
    assert sketch.total == sum(exact.values())
    for k, est, err in sketch.top():
        assert est - err <= exact.get(k, 0) <= est
    # the guarantee: any key heavier than total/k is present
    thresh = sketch.total / sketch.k
    for k, v in exact.items():
        if v > thresh:
            assert k in sketch.counts


def test_space_saver_bounded_memory():
    sketch = SpaceSaver(16)
    for i in range(5000):
        sketch.offer('k%d' % i, 1 + i % 7)
    assert len(sketch.counts) <= 16
    assert len(sketch.errs) <= 16
    assert len(sketch._heap) <= 8 * 16


def test_headroom_estimator_lanes():
    used = {'v': 100}
    t = {'v': 0.0}
    est = HeadroomEstimator(budget_bytes=1000,
                            used_fn=lambda: used['v'],
                            clock=lambda: t['v'])
    out = est.sample({})
    assert out['pressure'] == 0.1
    assert out['burn_bytes_s'] is None and out['exhaustion_s'] is None
    used['v'], t['v'] = 400, 1.0         # +300 B/s
    out = est.sample({})
    assert out['pressure'] == 0.4
    assert out['burn_bytes_s'] == 300.0
    assert out['exhaustion_s'] == 2.0    # (1000-400)/300
    # pressure eviction trips at the configured fraction
    os.environ['AMTPU_MEM_PRESSURE_EVICT'] = '0.5'
    try:
        assert not est.evict_due(0.4)
        assert est.evict_due(0.6)
    finally:
        del os.environ['AMTPU_MEM_PRESSURE_EVICT']
    # no budget -> no pressure, never evict-due
    est2 = HeadroomEstimator(budget_bytes=0, used_fn=lambda: 10**9)
    out = est2.sample({})
    assert out['pressure'] == 0.0
    assert not est2.evict_due(99.0)


def test_pressure_pass_cooldown(monkeypatch):
    """A stuck-high pressure signal gets ONE bounded eviction pass per
    cooldown window, never one per flush (evict/reload thrash guard)."""
    monkeypatch.setenv('AMTPU_MEM_PRESSURE_EVICT', '0.5')
    monkeypatch.setenv('AMTPU_CAPACITY_REFRESH_S', '0')
    tr = capacity.CapacityTracker()
    tr.estimator = HeadroomEstimator(budget_bytes=100,
                                     used_fn=lambda: 90)  # 0.9 > 0.5
    monkeypatch.setenv('AMTPU_PRESSURE_EVICT_COOLDOWN_S', '3600')
    assert tr.evict_due()
    tr.note_pressure_pass()
    assert not tr.evict_due()             # inside the window
    monkeypatch.setenv('AMTPU_PRESSURE_EVICT_COOLDOWN_S', '0')
    assert tr.evict_due()                 # 0 disables the cooldown


def test_headroom_component_sum_fallback():
    est = HeadroomEstimator(budget_bytes=0)
    out = est.sample({'rss': 0, 'arena': 30, 'wal': 10,
                      'cold_disk': 999})
    # cold disk is not memory: excluded from the component-sum fallback
    assert out['used_bytes'] == 40


def test_tracker_cost_vectors_and_section():
    pool = NativeDocPool()
    pool.apply_changes('big', _changes('w', 0, 20, seed=2))
    pool.apply_changes('small', _changes('w', 0, 2, seed=3))
    evictor = DocEvictor(pool, max_resident=0, store=ColdStore(),
                         gc_every=0)
    blob = pool.save('small')
    evictor.store.put('small', blob)
    tr = capacity.CapacityTracker()
    tr.attach(pool=pool, storage_tier=evictor)
    tr.note_fanout('big', 100, 700, 7)
    tr.note_egress('big', 256)
    vecs = tr.cost_vectors()
    key = NativeDocPool._doc_key('big')
    assert vecs[key]['arena_bytes'] == pool.history_bytes('big')
    assert vecs[key]['fanned_bytes'] == 700
    assert vecs[key]['egress_bytes'] == 256
    assert vecs[key]['subscribers'] == 7
    assert vecs['small']['disk_bytes'] == len(blob)
    section = tr.capacity_section()
    assert section['top']['arena'][0]['doc'] == key
    assert section['totals']['disk_bytes'] == len(blob)
    assert 'headroom' in section
    fan_row = section['top']['fanned'][0]
    assert fan_row['doc'] == 'big'
    assert fan_row['encoded_bytes'] == 100
    assert fan_row['amplification'] == 7.0      # 700 fanned / 100 enc
    # a flush that finds the doc subscriber-less zeroes its count
    tr.note_fanout('big', 0, 0, 0)
    snap = tr.refresh(force=True)
    assert snap['top']['fanned'][0]['subscribers'] == 0
    dbg = tr.debug_docs()
    assert any(r['doc'] == key for r in dbg['hot_docs'])
    assert dbg['cost_fields'] == list(capacity.COST_FIELDS)


def test_evictor_records_freed_bytes_and_event():
    telemetry.metrics_reset()
    pool = NativeDocPool()
    for d in range(4):
        pool.apply_changes('e%d' % d, _changes('w', 0, 4, seed=d))
    per_doc = {d: pool.history_bytes('e%d' % d) for d in range(4)}
    evictor = DocEvictor(pool, max_resident=2, store=ColdStore(),
                         gc_every=0)
    evictor.note_touch(['e0', 'e1', 'e2', 'e3'])
    assert evictor.maybe_evict() == 2     # e0, e1 LRU out
    flat = telemetry.metrics_snapshot()
    assert flat['storage.evictions'] == 2
    assert flat['storage.evicted_bytes'] == per_doc[0] + per_doc[1]
    evs = [e for e in recorder.events_json()
           if e['event'] == 'storage.evict' and e['doc'] == 'e0']
    assert evs and evs[-1]['n'] == per_doc[0]
    # healthz carries the running totals
    hz = evictor.healthz_section()
    assert hz['evicted_bytes'] == per_doc[0] + per_doc[1]
    assert hz['pressure_evictions'] == 0


def test_evictor_pressure_mode_ignores_doc_cap():
    telemetry.metrics_reset()
    pool = NativeDocPool()
    for d in range(4):
        pool.apply_changes('pe%d' % d, _changes('w', 0, 2, seed=d))
    evictor = DocEvictor(pool, max_resident=0, store=ColdStore(),
                         gc_every=0)
    evictor.note_touch(['pe%d' % d for d in range(4)])
    assert evictor.maybe_evict() == 0     # cap disabled: LRU mode idle
    n = evictor.maybe_evict(protect=['pe3'], pressure=True,
                            max_evict=2)
    assert n == 2
    flat = telemetry.metrics_snapshot()
    assert flat['storage.pressure_evictions'] == 2
    assert flat['storage.evicted_bytes'] > 0
    assert 'pe3' not in evictor.store    # protected doc stayed hot


def test_bench_block_capacity_preseed():
    block = telemetry.bench_block()
    assert set(telemetry.KNOWN_CAPACITY_KEYS) <= set(block['capacity'])


_DROP_READD_SCRIPT = r'''
import os, ctypes
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['AMTPU_RESIDENT'] = '1'      # force the kernel path: the
                                        # resident clock table engages
from automerge_tpu.native import NativeDocPool, lib

ROOT = '00000000-0000-0000-0000-000000000000'

def concurrent_batch(pool, doc, seq=1):
    # two (pool-known) actors writing the SAME key concurrently: a
    # non-trivial register group, so clock rows actually densify into
    # the pool table (fixed actor names -- a first-seen actor would
    # invalidate every cached row, which is correct but not this lane)
    pool.apply_batch({doc: [
        {'actor': 'A', 'seq': seq, 'deps': {'A': seq - 1} if seq > 1
         else {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                  'value': 1}]},
        {'actor': 'B', 'seq': seq, 'deps': {'B': seq - 1} if seq > 1
         else {},
         'ops': [{'action': 'set', 'obj': ROOT, 'key': 'k',
                  'value': 2}]},
    ]})

def resclk_rows(pool):
    info = (ctypes.c_int64 * 4)()
    lib().amtpu_resclk_info(pool._pool, info)
    return int(info[0])

pool = NativeDocPool()
concurrent_batch(pool, 'd1')
concurrent_batch(pool, 'd2')
concurrent_batch(pool, 'd1', seq=2)     # actors are pool-known now:
concurrent_batch(pool, 'd2', seq=2)     # these rows PERSIST
ids, stats = pool.doc_stats()
total = int(stats[:, 5].sum())
assert total == resclk_rows(pool) > 0, (total, resclk_rows(pool))
assert all(int(stats[i, 5]) > 0 for i in range(len(ids))), stats[:, 5]

# drop d1: the pool table invalidates (rows key on the DocState
# POINTER; a reused address must never inherit them)
pool.drop_doc('d1')
ids, stats = pool.doc_stats()
assert int(stats[:, 5].sum()) == resclk_rows(pool) == 0

# re-add a doc with the SAME id (the address-reuse hazard) and batch
# again: attribution must cover exactly the live rows, on live docs
concurrent_batch(pool, 'd1')
ids, stats = pool.doc_stats()
assert int(stats[:, 5].sum()) == resclk_rows(pool) > 0
assert set(ids) == {'d1', 'd2'}
i1 = ids.index('d1')
assert int(stats[i1, 5]) > 0            # the NEW rows, on the new doc
assert int(stats[:, 0].sum()) == pool.history_bytes()
print('OK')
'''


def test_drop_readd_resclk_attribution_subprocess():
    """ISSUE 15 satellite: amtpu_doc_stats rows for docs dropped via
    amtpu_drop_doc must leave no stale resident-clock attribution
    (subprocess: AMTPU_RESIDENT latches at the first batch)."""
    env = dict(os.environ)
    env.pop('XLA_FLAGS', None)
    out = subprocess.run([sys.executable, '-c', _DROP_READD_SCRIPT],
                         env=env, capture_output=True, text=True,
                         timeout=240,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert 'OK' in out.stdout
