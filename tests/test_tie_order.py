"""Pins the ONE deliberate conflict-tie-order deviation from the
reference, so the "byte-identical patches" claim is scoped precisely
(VERDICT r3 #6).

Input class where we deviate: a single change in which ONE actor assigns
the SAME (obj, key) more than once.  The reference frontend can never
emit such a change (`ensureSingleAssignment`,
`/root/reference/frontend/index.js:53` dedupes assignments per change),
and for hand-built changes the reference backend's own tie order is
unstable: `sortBy(actor).reverse()` (`/root/reference/backend/op_set.js`)
reverses a stable sort, so same-actor ties flip depending on how many
times the register was re-sorted -- i.e. the reference's own order for
this input oscillates between applications and is not a convergent
contract.

Our rule (`automerge_tpu/backend/op_set.py::apply_assign`): among
same-actor ties, most-recently-APPLIED op first; across actors, actor id
descending (identical to the reference).  This file pins:

  1. the exact patch our backends emit for the degenerate input,
  2. that all three backends (scalar oracle, batched Python pool, C++
     native pool) agree with each other on it, and
  3. that our rule is delivery-order independent even for this input
     (stronger than the reference, whose order is history-dependent).

For every frontend-shaped change stream (one assign per key per change)
all backends remain byte-identical to the reference; that claim is
carried by tests/test_backend.py + tests/test_golden_corpus.py.
"""

from automerge_tpu import backend as Backend
from automerge_tpu.native import NativeDocPool
from automerge_tpu.parallel.engine import TPUDocPool

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def _dup_change(actor, seq, values, deps=None):
    return {'actor': actor, 'seq': seq, 'deps': deps or {}, 'ops': [
        {'action': 'set', 'obj': ROOT_ID, 'key': 'k', 'value': v}
        for v in values]}


def _oracle_patches(changes):
    state = Backend.init()
    patches = []
    for ch in changes:
        state, p = Backend.apply_changes(state, [ch])
        patches.append(p)
    return Backend.get_patch(state), patches


class TestSameActorDuplicateAssign:
    def test_pinned_tie_order_single_change(self):
        """Most-recently-applied wins; earlier same-actor assign becomes
        the conflict entry.  This is OUR contract for the degenerate
        input (the reference has no stable one)."""
        final, patches = _oracle_patches([_dup_change('dup', 1, [1, 2])])
        assert patches[0]['diffs'][-1] == {
            'action': 'set', 'type': 'map', 'obj': ROOT_ID, 'key': 'k',
            'path': [], 'value': 2,
            'conflicts': [{'actor': 'dup', 'value': 1}]}
        assert final['diffs'] == [
            {'action': 'set', 'type': 'map', 'obj': ROOT_ID, 'key': 'k',
             'value': 2,
             'conflicts': [{'actor': 'dup', 'value': 1}]}]

    def test_three_backends_agree_on_degenerate_input(self):
        """The deviation is uniform: scalar oracle, batched Python pool,
        and C++ native pool emit the SAME bytes for duplicate-assign
        changes (so the deviation cannot cause cross-backend drift)."""
        changes = [
            _dup_change('alice', 1, [1, 2]),
            _dup_change('bob', 1, [3, 4, 5]),
            _dup_change('alice', 2, ['x'], deps={'bob': 1}),
        ]
        want_final, want_patches = _oracle_patches(changes)

        for pool in (TPUDocPool(), NativeDocPool()):
            for ch, want in zip(changes, want_patches):
                got = pool.apply_batch({0: [ch]})[0]
                assert got == want, type(pool).__name__
            assert pool.get_patch(0) == want_final, type(pool).__name__

    def test_delivery_order_independent(self):
        """Two concurrent degenerate changes produce the same register
        order whichever replica delivery order applied them -- our
        most-recent-first + stable actor-desc sort converges where the
        reference's re-sorted tie order is history-dependent."""
        a = _dup_change('alice', 1, [1, 2])
        b = _dup_change('bob', 1, [3, 4])
        final_ab, _ = _oracle_patches([a, b])
        final_ba, _ = _oracle_patches([b, a])
        assert final_ab == final_ba
        # actor-desc across actors, most-recent-first within an actor
        assert final_ab['diffs'] == [
            {'action': 'set', 'type': 'map', 'obj': ROOT_ID, 'key': 'k',
             'value': 4,
             'conflicts': [{'actor': 'bob', 'value': 3},
                           {'actor': 'alice', 'value': 2},
                           {'actor': 'alice', 'value': 1}]}]
