"""Top-level API facade: binds Frontend + Backend into the one-process
convenience API (reference: `/root/reference/src/automerge.js`, 134 LoC).

Exports: init, change, empty_change, undo, redo, load, save, merge, diff,
get_changes, apply_changes, get_missing_deps, equals, inspect, get_history,
uuid, Frontend, Backend, DocSet, WatchableDoc, Connection, Text, Table,
can_undo, can_redo, get_actor_id, set_actor_id, get_conflicts, get_object_id.
"""

from . import backend as Backend
from . import frontend as Frontend
from . import telemetry
from .errors import RangeError
from .models.table import Table
from .models.text import Text
from .serialization import deserialize_changes, serialize_changes
from .sync.connection import Connection
from .sync.doc_set import DocSet
from .sync.watchable_doc import WatchableDoc
from .utils.common import is_object
from .utils.uuid import uuid


def doc_from_changes(actor_id, changes):
    """Constructs a fresh frontend document reflecting `changes`
    (reference: automerge.js:10-17)."""
    if not actor_id:
        raise RangeError('actor_id is required in doc_from_changes')
    doc = Frontend.init({'actorId': actor_id, 'backend': Backend})
    state, _ = Backend.apply_changes(Backend.init(), changes)
    patch = Backend.get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


def init(actor_id=None):
    """Creates a document with the immediate (synchronous) backend
    (reference: automerge.js:21-23).  Accepts an actor-ID string or an
    options dict; `backend` defaults to the oracle backend module."""
    if isinstance(actor_id, dict):
        options = dict(actor_id)
    elif isinstance(actor_id, str):
        options = {'actorId': actor_id}
    else:
        options = {}
    options.setdefault('backend', Backend)
    return Frontend.init(options)


def change(doc, message=None, callback=None):
    """(reference: automerge.js:25-28)"""
    # root span: mints the trace id every nested backend/sidecar span
    # (and cross-process request) inherits
    with telemetry.span('frontend.change'):
        new_doc, _ = Frontend.change(doc, message, callback)
    return new_doc


def empty_change(doc, message=None):
    """(reference: automerge.js:30-33)"""
    new_doc, _ = Frontend.empty_change(doc, message)
    return new_doc


def undo(doc, message=None):
    """(reference: automerge.js:35-38)"""
    new_doc, _ = Frontend.undo(doc, message)
    return new_doc


def redo(doc, message=None):
    """(reference: automerge.js:40-43)"""
    new_doc, _ = Frontend.redo(doc, message)
    return new_doc


def load(string, actor_id=None):
    """Rebuilds a document from a saved change history
    (reference: automerge.js:45-47)."""
    return doc_from_changes(actor_id or uuid(), deserialize_changes(string))


def save(doc):
    """Serializes the full change history (reference: automerge.js:49-52)."""
    state = Frontend.get_backend_state(doc)
    return serialize_changes(list(state['opSet']['history']))


def merge(local_doc, remote_doc):
    """(reference: automerge.js:54-64)"""
    if Frontend.get_actor_id(local_doc) == Frontend.get_actor_id(remote_doc):
        raise RangeError('Cannot merge an actor with itself')
    with telemetry.span('frontend.merge'):
        local_state = Frontend.get_backend_state(local_doc)
        remote_state = Frontend.get_backend_state(remote_doc)
        state, patch = Backend.merge(local_state, remote_state)
        if not patch['diffs']:
            return local_doc
        patch['state'] = state
        return Frontend.apply_patch(local_doc, patch)


def diff(old_doc, new_doc):
    """(reference: automerge.js:66-72)"""
    old_state = Frontend.get_backend_state(old_doc)
    new_state = Frontend.get_backend_state(new_doc)
    changes = Backend.get_changes(old_state, new_state)
    _, patch = Backend.apply_changes(old_state, changes)
    return patch['diffs']


def get_changes(old_doc, new_doc):
    """(reference: automerge.js:74-78)"""
    old_state = Frontend.get_backend_state(old_doc)
    new_state = Frontend.get_backend_state(new_doc)
    return Backend.get_changes(old_state, new_state)


def apply_changes(doc, changes):
    """(reference: automerge.js:80-85)"""
    with telemetry.span('frontend.apply_changes', changes=len(changes)):
        old_state = Frontend.get_backend_state(doc)
        new_state, patch = Backend.apply_changes(old_state, changes)
        patch['state'] = new_state
        return Frontend.apply_patch(doc, patch)


def get_missing_deps(doc):
    """(reference: automerge.js:87-89)"""
    return Backend.get_missing_deps(Frontend.get_backend_state(doc))


def equals(val1, val2):
    """Deep structural equality ignoring metadata
    (reference: automerge.js:91-100)."""
    if not is_object(val1) or not is_object(val2):
        return val1 == val2
    if isinstance(val1, Table) or isinstance(val2, Table):
        if not (isinstance(val1, Table) and isinstance(val2, Table)):
            return False
        if not equals(list(val1.columns or []), list(val2.columns or [])):
            return False
        ids1, ids2 = sorted(val1.ids), sorted(val2.ids)
        if ids1 != ids2:
            return False
        return all(equals(val1.by_id(i), val2.by_id(i)) for i in ids1)
    if isinstance(val1, (list, Text)) != isinstance(val2, (list, Text)):
        return False
    if isinstance(val1, (list, Text)):
        items1, items2 = list(val1), list(val2)
        if len(items1) != len(items2):
            return False
        return all(equals(a, b) for a, b in zip(items1, items2))
    keys1 = sorted(k for k in val1.keys())
    keys2 = sorted(k for k in val2.keys())
    if keys1 != keys2:
        return False
    return all(equals(val1[k], val2[k]) for k in keys1)


def inspect(doc):
    """Plain-data snapshot (reference: automerge.js:102-104)."""
    from .frontend.inspect_util import to_plain
    return to_plain(doc)


class HistoryEntry:
    """One state in the document history: the change that created it and a
    lazily-materialized snapshot (reference: automerge.js:106-120)."""

    def __init__(self, actor, history, index):
        self._actor = actor
        self._history = history
        self._index = index

    @property
    def change(self):
        return self._history[self._index]

    @property
    def snapshot(self):
        return doc_from_changes(self._actor, self._history[:self._index + 1])


def get_history(doc):
    """(reference: automerge.js:106-120)"""
    state = Frontend.get_backend_state(doc)
    actor = Frontend.get_actor_id(doc)
    history = list(state['opSet']['history'])
    return [HistoryEntry(actor, history, i) for i in range(len(history))]


# Frontend re-exports (reference: automerge.js:132-134)
can_undo = Frontend.can_undo
can_redo = Frontend.can_redo
get_actor_id = Frontend.get_actor_id
set_actor_id = Frontend.set_actor_id
get_conflicts = Frontend.get_conflicts
get_object_id = Frontend.get_object_id
get_element_ids = Frontend.get_element_ids

# camelCase aliases: full reference API surface (automerge.js:122-134)
emptyChange = empty_change
getChanges = get_changes
applyChanges = apply_changes
getMissingDeps = get_missing_deps
getHistory = get_history
canUndo = can_undo
canRedo = can_redo
getActorId = get_actor_id
setActorId = set_actor_id
getConflicts = get_conflicts
getObjectId = get_object_id
docFromChanges = doc_from_changes
