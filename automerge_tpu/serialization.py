"""Serialization: save/load of documents.

The reference serializes the full change history with transit-JSON and
replays it through a fresh backend on load
(`/root/reference/src/automerge.js:45-52`).  Here the format is plain JSON:
`{"version": 1, "changes": [...]}` -- the change schema is already
JSON-native, so the checkpoint format doubles as the wire format of the
sidecar protocol.  Load replays through one batched `apply_changes` call
(O(history), like the reference), and the TPU engine can replay the same
columnar-encoded history in one device pass.
"""

import json

FORMAT_VERSION = 1


def serialize_changes(changes):
    return json.dumps({'version': FORMAT_VERSION, 'changes': changes},
                      separators=(',', ':'), sort_keys=True)


def deserialize_changes(string):
    data = json.loads(string)
    if isinstance(data, list):  # bare change-list form is also accepted
        return data
    if data.get('version') != FORMAT_VERSION:
        raise ValueError('Unsupported save format version: %r'
                         % (data.get('version'),))
    return data['changes']
